"""featmat: the feature-composition matrix auditor (ISSUE 16).

The third static-analysis tier.  simlint (tools/simlint/) reads the
SOURCE; hloaudit (tools/hloaudit/) reads the COMPILED artifacts; featmat
reads the repo's *composition gates* — every ``tp_reject_reason`` /
``hier_reject_reason`` / ``_check_fleet_spec`` / ``WorldSpec.validate``
/ CLI guard-rail clause — and audits the feature × runner matrix they
collectively imply:

* **Extraction** (:mod:`.extract`): the gates' bracketed clause IDs
  (``[TP-CHAOS]``, ``[FLEET-HIER]``, ``[SPEC-CHAOS-ENERGY]``,
  ``[CLI-SWEEP-TP]``) are pulled out of the AST with file:line, split
  into *definitions* (the site in the ID family's owning module) and
  *citations* (a CLI one-liner re-keying on an engine gate's ID).
* **The matrix** (:mod:`.matrix`): a declarative feature × runner table
  plus a composition-pair table.  Every REJECTED cell names the clause
  ID that enforces it; every ACCEPTED cell names its evidence — a
  dedicated hloaudit variant (compiled + audited by
  ``python -m tools.hloaudit --check``) or a pinned test literal.
* **Consistency gates**: an extracted ID the matrix does not map, a
  mapped ID whose gate site vanished (a deleted rejection clause!), two
  definitions drifting for one cell, a rejected cell no test asserts,
  or an accepted cell with no audit evidence — each IS a finding, and
  ``python -m tools.featmat --check`` (tools/ci_check.sh) fails on any.

``--write`` regenerates the two checked-in artifacts: the machine-
readable ``tools/featmat/matrix.json`` and the human ``FEATURES.md``
at the repo root; ``--check`` also fails when either is stale.
"""
from .extract import Site, extract_sites  # noqa: F401
from .matrix import (  # noqa: F401
    build_matrix,
    consistency_findings,
    render_markdown,
)
