"""AST extraction of composition-gate clause IDs (the featmat front-end).

A *gate site* is any string constant in one of the ``GATE_FILES`` whose
text carries a bracketed clause ID — ``[TP-CHAOS]``, ``[SPEC-STATIC-MAC]``,
``[CLI-SWEEP-TP]`` — the stable machine-parseable keys the rejection
prose leads with (core/engine.tp_reject_reason's docstring states the
contract).  Docstrings are excluded: prose ABOUT an ID is not a gate.

Two site roles:

* **definition** — the site lives in the module that OWNS the ID's
  family (``OWNER_OF``: ``TP-*`` → the engine, ``FLEET-*`` → the fleet
  runner, ``SPEC-*`` → spec.py, ``CLI-*`` → the CLI).  Exactly one
  definition per ID is the no-drift invariant matrix.py enforces.
* **citation** — the same ID in any other gate file: a CLI one-liner
  keying on an engine gate's cell (``[TP-SERIES]`` in __main__.py)
  instead of re-wording it.  Citations are the anti-drift mechanism,
  not drift.

The one parameterized clause — ``hier/federation.hier_reject_reason``'s
``f"[{runner.upper()}-HIER] ..."`` template, the shared message source
for the TP and fleet hierarchy gates — cannot be read off a plain
constant, so extraction synthesizes the concrete ``[TP-HIER]`` /
``[FLEET-HIER]`` definitions at the CALL sites that pass the literal
runner name.  Parsing reuses simlint's :class:`~tools.simlint.core.
ModuleInfo` (AST + parent links + line texts): one parser family across
all three analysis tiers.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Set

from tools.simlint.core import ModuleInfo, dotted

#: The composition-gate surfaces (repo-relative).  A new gate module
#: must be added here or its clauses are invisible to the matrix — and
#: the matrix's unmapped-ID gate fires the moment one of ITS IDs shows
#: up anywhere else, so the list cannot rot silently.
GATE_FILES = (
    "fognetsimpp_tpu/spec.py",
    "fognetsimpp_tpu/core/engine.py",
    "fognetsimpp_tpu/hier/federation.py",
    "fognetsimpp_tpu/parallel/fleet.py",
    "fognetsimpp_tpu/twin/gates.py",
    "fognetsimpp_tpu/__main__.py",
)

#: ID-family prefix -> the ONE module allowed to define its clauses.
OWNER_OF = {
    "TP": "fognetsimpp_tpu/core/engine.py",
    "FLEET": "fognetsimpp_tpu/parallel/fleet.py",
    "SPEC": "fognetsimpp_tpu/spec.py",
    "TWIN": "fognetsimpp_tpu/twin/gates.py",
    "CLI": "fognetsimpp_tpu/__main__.py",
}

_ID_RE = re.compile(r"\[((?:TP|FLEET|SPEC|TWIN|CLI)-[A-Z0-9-]+)\]")


@dataclasses.dataclass(frozen=True)
class Site:
    """One gate site: clause ID + where it lives + its role."""

    id: str
    relpath: str
    line: int
    role: str  # "definition" | "citation"
    text: str  # the source line (trimmed), for rendering

    def render(self) -> str:
        return f"{self.relpath}:{self.line} [{self.id}] ({self.role})"


def _docstring_constants(tree: ast.AST) -> Set[int]:
    """``id()`` of every docstring Constant node (excluded from
    extraction: prose about an ID is not a gate)."""
    out: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(
            node,
            (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef,
             ast.ClassDef),
        ):
            body = getattr(node, "body", [])
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                out.add(id(body[0].value))
    return out


def _role(clause_id: str, relpath: str) -> str:
    prefix = clause_id.split("-", 1)[0]
    owner = OWNER_OF.get(prefix)
    return "definition" if owner == relpath else "citation"


def extract_module(mod: ModuleInfo) -> List[Site]:
    """All gate sites of one parsed gate file."""
    sites: List[Site] = []
    seen: Set[tuple] = set()
    docstrings = _docstring_constants(mod.tree)

    def add(clause_id: str, lineno: int, role: str) -> None:
        key = (clause_id, lineno)
        if key in seen:
            return
        seen.add(key)
        sites.append(Site(
            id=clause_id,
            relpath=mod.relpath,
            line=lineno,
            role=role,
            text=mod.line_text(lineno),
        ))

    for node in ast.walk(mod.tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and id(node) not in docstrings
        ):
            for m in _ID_RE.finditer(node.value):
                add(m.group(1), node.lineno, _role(m.group(1), mod.relpath))
        elif isinstance(node, ast.Call):
            # the hier template: hier_reject_reason(spec, "<runner>")
            # defines [<RUNNER>-HIER] at the call site
            name = dotted(node.func) or ""
            if name.split(".")[-1] != "hier_reject_reason":
                continue
            if len(node.args) >= 2 and isinstance(
                node.args[1], ast.Constant
            ) and isinstance(node.args[1].value, str):
                clause_id = f"{node.args[1].value.upper()}-HIER"
                add(clause_id, node.lineno, _role(clause_id, mod.relpath))
    return sites


def extract_sites(root: str) -> List[Site]:
    """Every gate site under repo root ``root`` (sorted by file, line)."""
    sites: List[Site] = []
    for rel in GATE_FILES:
        full = os.path.join(root, rel)
        with open(full, encoding="utf-8") as fh:
            src = fh.read()
        sites += extract_module(ModuleInfo(full, rel, src))
    return sorted(sites, key=lambda s: (s.relpath, s.line, s.id))


def sites_by_id(sites: List[Site]) -> Dict[str, List[Site]]:
    out: Dict[str, List[Site]] = {}
    for s in sites:
        out.setdefault(s.id, []).append(s)
    return out
