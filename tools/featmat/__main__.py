"""CLI for the feature-composition matrix auditor.

  python -m tools.featmat             # print findings + cell summary
  python -m tools.featmat --check     # exit 1 on findings/stale artifacts
  python -m tools.featmat --write     # regenerate matrix.json + FEATURES.md
  python -m tools.featmat --markdown  # FEATURES.md body on stdout

Pure static analysis — no jax import, no compiles: extraction walks the
gate files' ASTs, the consistency gates cross-reference the checked-in
hloaudit manifests and the tests/ corpus as text.  The compile-side
audit of every accepted cell is hloaudit's job (CI runs both).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))
MATRIX_JSON = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "matrix.json"
)
FEATURES_MD = os.path.join(REPO_ROOT, "FEATURES.md")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.featmat",
        description="feature-composition matrix auditor "
        "(tools/featmat/__init__.py docstring)",
    )
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any finding or stale artifact (CI)")
    ap.add_argument("--write", action="store_true",
                    help="regenerate tools/featmat/matrix.json and "
                    "FEATURES.md")
    ap.add_argument("--markdown", action="store_true",
                    help="emit the FEATURES.md body on stdout")
    ap.add_argument("--root", default=REPO_ROOT, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    from .extract import extract_sites
    from .matrix import (
        build_matrix, consistency_findings, matrix_json, render_markdown,
    )

    sites = extract_sites(args.root)
    matrix = build_matrix(sites)
    findings = consistency_findings(sites, args.root)

    if args.write:
        with open(MATRIX_JSON, "w") as f:
            f.write(matrix_json(matrix))
        with open(FEATURES_MD, "w") as f:
            f.write(render_markdown(matrix))
        print(f"wrote {MATRIX_JSON}", file=sys.stderr)
        print(f"wrote {FEATURES_MD}", file=sys.stderr)
    elif args.markdown:
        print(render_markdown(matrix))
    else:
        counts = {"accepted": 0, "rejected": 0, "untracked": 0}
        for c in matrix["cells"]:
            counts[c["verdict"]] += 1
        print(json.dumps({
            "gate_sites": len(sites),
            "clause_ids": len({s.id for s in sites}),
            "cells": counts,
            "compositions": len(matrix["compositions"]),
        }))

    # stale-artifact detection (also under --check after --write runs
    # in the same CI job order: write is never run by CI)
    if not args.write:
        def stale(path: str, want: str) -> bool:
            if not os.path.exists(path):
                return True
            with open(path) as f:
                return f.read() != want
        if stale(MATRIX_JSON, matrix_json(matrix)):
            findings.append(
                "stale artifact: tools/featmat/matrix.json does not "
                "match the extracted matrix — regenerate with "
                "`python -m tools.featmat --write` and commit"
            )
        if stale(FEATURES_MD, render_markdown(matrix)):
            findings.append(
                "stale artifact: FEATURES.md does not match the "
                "extracted matrix — regenerate with `python -m "
                "tools.featmat --write` and commit"
            )

    for f_ in findings:
        print(f"featmat: {f_}", file=sys.stderr)
    print(
        f"featmat: {len({s.id for s in sites})} clause ID(s), "
        + ("clean" if not findings else f"{len(findings)} finding(s)"),
        file=sys.stderr,
    )
    return 1 if (args.check and findings) else 0


if __name__ == "__main__":
    sys.path.insert(0, REPO_ROOT)
    sys.exit(main())
