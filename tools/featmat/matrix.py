"""The declarative feature-composition matrix + consistency gates.

Two tables:

* :data:`CELLS` — the feature × runner matrix over the three engine
  runners (``run`` = single-device run/run_jit/run_chunked, ``tp`` =
  the shard_map'd TP tick, ``fleet`` = the replica-sharded fleet vmap).
  Verdicts: ``accepted`` (must carry evidence — a dedicated hloaudit
  variant or a pinned test literal), ``rejected`` (must carry the
  clause ID whose gate enforces it), or ``untracked`` (no gate and no
  pinned evidence yet: honest open coverage debt, rendered ``·`` and
  listed in FEATURES.md, never silently dropped).
* :data:`COMPOSITIONS` — the feature × feature / CLI-mode rejection
  pairs (``[SPEC-*]`` spec-validation clauses and ``[CLI-*]`` guard
  rails) that do not fit a runner column.

The consistency gates (:func:`consistency_findings`) tie the tables to
the extracted gate sites and to the other two analysis tiers:

1. every extracted clause ID must be mapped (a cell clause or a
   composition entry) — an unmapped ID is a gate the matrix has never
   reviewed;
2. every mapped ID must keep exactly ONE definition site in its owning
   module — zero means the gate was DELETED while the matrix/CLI/tests
   still claim it (the deleted-gate CI failure), two means drift;
3. every rejected clause must be asserted by tests (the literal
   ``[ID]`` under ``tests/``) — unasserted rejections rot into prose;
4. every accepted cell's evidence must exist: ``variant:<name>`` needs
   the checked-in hloaudit manifest (the variant is then compiled and
   A1–A7-audited by ``python -m tools.hloaudit --check`` in CI), and
   ``test:<literal>`` must appear under ``tests/``.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Tuple

from .extract import OWNER_OF, Site, sites_by_id

RUNNERS = ("run", "tp", "fleet")

#: feature key -> one-line description (FEATURES.md row legend).
FEATURES = {
    "baseline": "dense-broker FIFO static two-stage world (the op-budget family)",
    "telemetry": "carry-resident telemetry accumulators (+ latency histogram)",
    "series": "per-tick series recording (record_tick_series)",
    "window": "bounded K-window arrival regime (arrival_window)",
    "dyntopo": "dynamic topology / liveness (assume_static off)",
    "energy": "energy & lifecycle model (battery drain, shutdown/restart)",
    "wired": "DropTail wired-queue backpressure",
    "learn": "bandit learner broker policies (UCB/DUCB/EXP3)",
    "pool": "POOL phase-sequential fog servers",
    "sparse_policy": "non-dense broker policy family (sequential-pool scoring)",
    "legacy_arrivals": "single-stage arrival front-end (two_stage_arrivals off)",
    "no_fogs": "fog-free worlds (local-only execution)",
    "chaos": "chaos fault-injection schedules",
    "hier": "federated multi-broker hierarchy (n_brokers > 1)",
    "journeys": "causal task-journey event rings",
    "dynspec": "DynSpec-promoted numeric knobs (zero-recompile reconfig)",
    "ingest": "live arrival ingestion (queue-fed chunk-boundary injection)",
    "whatif": "state-forked what-if grids (run_whatif from a live carry)",
    "front": "multi-tenant serve front door (twin/front.FrontDoor)",
}


@dataclasses.dataclass(frozen=True)
class Cell:
    feature: str
    runner: str
    verdict: str  # "accepted" | "rejected" | "untracked"
    clause: Optional[str] = None  # rejected: the enforcing clause ID
    evidence: Tuple[str, ...] = ()  # accepted: variant:<v> / test:<lit>


def _a(f, r, *evidence) -> Cell:
    return Cell(f, r, "accepted", evidence=tuple(evidence))


def _r(f, r, clause) -> Cell:
    return Cell(f, r, "rejected", clause=clause)


def _u(f, r) -> Cell:
    return Cell(f, r, "untracked")


CELLS: Tuple[Cell, ...] = (
    _a("baseline", "run", "variant:tick_fused"),
    _a("baseline", "tp", "variant:tp_tick"),
    _a("baseline", "fleet", "variant:fleet_step"),
    _a("telemetry", "run", "variant:tick_telemetry", "variant:tick_hist"),
    _a("telemetry", "tp", "variant:tp_tick_telemetry"),
    _a("telemetry", "fleet",
       "test:test_fleet_carries_telemetry_identically_to_vmap"),
    _a("series", "run", "variant:tick_series"),
    _r("series", "tp", "TP-SERIES"),
    _a("series", "fleet",
       "test:test_fleet_series_chunked_matches_straight_recording"),
    _a("window", "run", "variant:tick_window"),
    _a("window", "tp", "variant:tp_tick_window",
       "test:test_tp_window_bitexact_vs_reference"),
    _u("window", "fleet"),
    _a("dyntopo", "run", "test:assume_static=False"),
    _r("dyntopo", "tp", "TP-DYNTOPO"),
    _u("dyntopo", "fleet"),
    _a("energy", "run", "variant:tick_energy"),
    _r("energy", "tp", "TP-ENERGY"),
    _u("energy", "fleet"),
    _a("wired", "run", "variant:tick_wired"),
    _r("wired", "tp", "TP-WIRED"),
    _u("wired", "fleet"),
    _a("learn", "run", "variant:tick_learn"),
    _r("learn", "tp", "TP-LEARN"),
    _u("learn", "fleet"),
    _a("pool", "run", "variant:tick_pool"),
    _r("pool", "tp", "TP-POOL"),
    _u("pool", "fleet"),
    _a("sparse_policy", "run", "test:test_policies_end_to_end"),
    _r("sparse_policy", "tp", "TP-POLICY"),
    _u("sparse_policy", "fleet"),
    _a("legacy_arrivals", "run", "test:two_stage_arrivals=False"),
    _r("legacy_arrivals", "tp", "TP-ARRIVALS"),
    _u("legacy_arrivals", "fleet"),
    _u("no_fogs", "run"),
    _r("no_fogs", "tp", "TP-NOFOGS"),
    _u("no_fogs", "fleet"),
    _a("chaos", "run", "variant:tick_chaos"),
    _r("chaos", "tp", "TP-CHAOS"),
    _a("chaos", "fleet",
       "test:test_fleet_chaos_per_replica_schedules_match_vmap"),
    _a("hier", "run", "variant:tick_hier"),
    _r("hier", "tp", "TP-HIER"),
    _r("hier", "fleet", "FLEET-HIER"),
    _a("journeys", "run", "variant:tick_journeys"),
    _a("journeys", "tp", "variant:tp_tick_journeys",
       "test:test_tp_journey_chains_bit_match_single_device"),
    _a("journeys", "fleet", "test:test_fleet_vmap_carries_journey_rings"),
    _a("dynspec", "run", "variant:tick_dyn"),
    _a("dynspec", "tp", "variant:tp_tick_dyn",
       "test:test_tp_promoted_bitexact_vs_static"),
    _a("dynspec", "fleet", "variant:fleet_step_dyn",
       "test:test_fleet_promoted_bitexact_vs_static"),
    _a("ingest", "run", "variant:tick_ingest",
       "test:test_replay_from_arrival_log"),
    _r("ingest", "tp", "TWIN-INGEST-TP"),
    _r("ingest", "fleet", "TWIN-INGEST-FLEET"),
    _a("whatif", "run", "test:test_whatif_fork_matches_cold_runs"),
    _a("whatif", "tp", "test:test_tp_whatif_fork_matches_cold_runs"),
    _r("whatif", "fleet", "TWIN-WHATIF-FLEET"),
    _a("front", "run", "test:test_front_door_shared_program"),
    _r("front", "tp", "TWIN-FRONT-TP"),
    _r("front", "fleet", "TWIN-FRONT-FLEET"),
)


@dataclasses.dataclass(frozen=True)
class Composition:
    """A rejected feature × feature / CLI-mode pair that no runner
    column captures; ``clause`` is the enforcing ID."""

    clause: str
    a: str
    b: str
    note: str


COMPOSITIONS: Tuple[Composition, ...] = (
    Composition("SPEC-STATIC-MAC", "dyntopo-hoist", "mac80211",
                "the CSMA/CA MAC resolves per-tick contention; the "
                "static-association hoist would freeze it"),
    Composition("SPEC-JOURNEYS-TELEM", "journeys", "telemetry-off",
                "journey rings ride TelemetryState; journeys>0 needs "
                "telemetry_every>0"),
    Composition("SPEC-CHAOS-STATIC", "chaos", "dyntopo-hoist",
                "chaos mutates fog liveness; assume_static would freeze "
                "the association cache"),
    Composition("SPEC-CHAOS-ENERGY", "chaos", "energy",
                "both subsystems own fog liveness; composing their "
                "writes is a follow-up"),
    Composition("SPEC-HIER-POLICY", "hier", "sparse_policy",
                "only the dense-broker policy family federates "
                "(per-domain decide masks)"),
    Composition("CLI-CHECKIFY-SOLO", "checkify", "fan-out/series",
                "the checkify debug slow path is single-world only"),
    Composition("CLI-TP-FLEET", "tp", "fleet",
                "one parallel axis per run: TP shards one world, the "
                "fleet fans out many"),
    Composition("CLI-TPWINDOW", "tp-window-knob", "tp-off",
                "--tp-window refines --tp; meaningless without it"),
    Composition("CLI-SWEEP-TP", "sweep", "tp",
                "sweeps own their replica fan-out"),
    Composition("CLI-SWEEP-HIER", "sweep", "hier",
                "sweeps own their replica fan-out; no hierarchy"),
    Composition("CLI-SWEEP-CHAOS", "sweep", "chaos",
                "chaos perturbs one world; sweeps grid many"),
    Composition("CLI-SWEEP-SERIES", "sweep", "series",
                "sweeps return counter grids, not series"),
    Composition("CLI-SWEEP-TELEM", "sweep", "telemetry",
                "sweeps return counter grids, not a final world"),
    Composition("CLI-SWEEP-SERVE", "sweep", "serve",
                "sweeps return counter grids, not a live world"),
    Composition("CLI-SWEEP-FLEET", "sweep", "fleet",
                "sweeps own their replica fan-out (reps=)"),
    Composition("CLI-SWEEP-POLICY", "sweep", "policy-flag",
                "the sweep grid owns the policy axis"),
    Composition("CLI-CHAOS-KNOBS", "chaos-knobs", "chaos-off",
                "chaos knobs refine a --chaos profile"),
    Composition("CLI-HIERPOLICY", "hier-policy-knob", "hier-off",
                "--hier-policy refines --brokers"),
    Composition("CLI-SERVE-SERIES", "serve", "series",
                "serving owns the chunking; no per-tick series flags"),
    Composition("CLI-SERVE-FLEET", "serve", "fleet",
                "serving is a single-world loop"),
    Composition("CLI-FLEET-PROGRESS", "fleet", "progress",
                "the fleet scan is one jitted program; no host ticks"),
    Composition("CLI-FLEET-TRAILS", "fleet", "trails",
                "per-replica trails would fetch the whole batch"),
    Composition("CLI-PROGRESS-SERIES", "progress", "series",
                "progress chunking and straight series recording "
                "conflict"),
    Composition("TWIN-INGEST-SERVE", "ingest", "serve-off",
                "live ingestion drains at the serving loop's chunk "
                "boundaries; it needs --serve"),
    Composition("TWIN-INGEST-OFF", "ingest-feed", "ingest-gate-off",
                "injection is compiled out when spec.ingest is False "
                "(the bit-exactness contract)"),
    Composition("TWIN-WHATIF-STATIC", "whatif", "static-spec",
                "what-if grids ride the promoted DynSpec operand; the "
                "FNS_SPEC_PROMOTE=0 path would compile per cell"),
    Composition("TWIN-PAYLOAD", "ingest-http", "malformed-payload",
                "malformed ingest traffic gets a one-line 400, never "
                "kills the live session"),
    Composition("TWIN-WHATIF-PAYLOAD", "whatif-http", "malformed-payload",
                "malformed what-if requests get a one-line 400 from "
                "the door"),
    Composition("TWIN-FRONT-SERVE", "front", "serve-off",
                "--tenants multiplexes live sessions behind one HTTP "
                "endpoint; it needs --serve"),
    Composition("TWIN-CAP", "front", "over-admission",
                "tenant admission past the capacity bound is a "
                "one-line rejection, not a queue"),
    Composition("CLI-SWEEP-TWIN", "sweep", "twin",
                "sweeps build every cell's world from the grid; no "
                "live twin surface"),
    Composition("CLI-TENANTS-WHATIF", "tenants", "whatif-flag",
                "per-tenant what-ifs ride POST /t/<label>/whatif, not "
                "the one-shot flag"),
    Composition("CLI-TENANTS-REPLAY", "tenants", "replay",
                "arrival logs are per session; replay one tenant solo"),
    Composition("CLI-TENANTCAP", "tenant-cap-knob", "tenants-off",
                "--tenant-cap bounds front-door admission; it refines "
                "--tenants"),
)


# ----------------------------------------------------------------------
# matrix build + consistency gates
# ----------------------------------------------------------------------

def build_matrix(sites: List[Site]) -> dict:
    """The canonical machine-readable matrix: cells + compositions,
    each rejected entry annotated with its extracted gate sites."""
    by_id = sites_by_id(sites)

    def site_rows(clause: Optional[str]) -> List[dict]:
        return [
            {"file": s.relpath, "line": s.line, "role": s.role}
            for s in by_id.get(clause, [])
        ]

    return {
        "_comment": (
            "generated by `python -m tools.featmat --write` — do not "
            "edit; the feature x runner composition matrix extracted "
            "from the repo's gate clauses (see tools/featmat/)"
        ),
        "runners": list(RUNNERS),
        "features": dict(FEATURES),
        "cells": [
            {
                "feature": c.feature,
                "runner": c.runner,
                "verdict": c.verdict,
                **({"clause": c.clause} if c.clause else {}),
                **({"evidence": list(c.evidence)} if c.evidence else {}),
                **(
                    {"sites": site_rows(c.clause)}
                    if c.verdict == "rejected" else {}
                ),
            }
            for c in CELLS
        ],
        "compositions": [
            {
                "clause": p.clause, "a": p.a, "b": p.b, "note": p.note,
                "sites": site_rows(p.clause),
            }
            for p in COMPOSITIONS
        ],
    }


def _tests_corpus(root: str) -> str:
    """Concatenated source of every tests/*.py (rejection-coverage and
    test-evidence lookups)."""
    parts = []
    tdir = os.path.join(root, "tests")
    for name in sorted(os.listdir(tdir)):
        if name.endswith(".py"):
            with open(os.path.join(tdir, name), encoding="utf-8") as fh:
                parts.append(fh.read())
    return "\n".join(parts)


def _manifest_exists(root: str, variant: str) -> bool:
    return os.path.exists(os.path.join(
        root, "tools", "hloaudit", "manifests", f"{variant}.json"
    ))


def consistency_findings(sites: List[Site], root: str) -> List[str]:
    """The featmat CI gate: every inconsistency between the declarative
    matrix, the extracted gate sites, the hloaudit variant registry and
    the test suite, as rendered finding strings (empty = clean)."""
    findings: List[str] = []
    by_id = sites_by_id(sites)
    mapped: Dict[str, str] = {}
    for c in CELLS:
        if c.clause:
            mapped[c.clause] = f"cell {c.feature}x{c.runner}"
    for p in COMPOSITIONS:
        mapped.setdefault(p.clause, f"composition {p.a}x{p.b}")

    # 1. every extracted ID is mapped
    for clause_id in sorted(by_id):
        if clause_id not in mapped:
            s = by_id[clause_id][0]
            findings.append(
                f"unmapped gate: [{clause_id}] at {s.relpath}:{s.line} "
                "is enforced in code but absent from the featmat matrix "
                "— add the cell/composition entry (tools/featmat/"
                "matrix.py) and regenerate with --write"
            )

    # 2. every mapped ID keeps exactly one definition in its owner file
    for clause_id, where in sorted(mapped.items()):
        defs = [
            s for s in by_id.get(clause_id, []) if s.role == "definition"
        ]
        if not defs:
            owner = OWNER_OF.get(clause_id.split("-", 1)[0], "?")
            cites = by_id.get(clause_id, [])
            extra = (
                "; still cited at "
                + ", ".join(f"{s.relpath}:{s.line}" for s in cites)
                if cites else ""
            )
            findings.append(
                f"deleted gate: [{clause_id}] ({where}) has no "
                f"definition site left in {owner}{extra} — the matrix "
                "claims a rejection no code enforces; restore the gate "
                "or re-verdict the cell WITH audit coverage"
            )
        elif len(defs) > 1:
            locs = ", ".join(f"{s.relpath}:{s.line}" for s in defs)
            findings.append(
                f"drifting gate: [{clause_id}] ({where}) is defined "
                f"{len(defs)} times ({locs}) — one cell, one defining "
                "clause; make the extra sites citations of the one "
                "message source"
            )

    # 3. every rejected clause is asserted by tests
    corpus = _tests_corpus(root)
    for clause_id, where in sorted(mapped.items()):
        if f"[{clause_id}]" not in corpus:
            findings.append(
                f"untested rejection: [{clause_id}] ({where}) is never "
                "asserted under tests/ — add a test that drives the "
                "gate and asserts the literal ID"
            )

    # 4. accepted-cell evidence exists
    for c in CELLS:
        if c.verdict != "accepted":
            continue
        if not c.evidence:
            findings.append(
                f"unevidenced acceptance: cell {c.feature}x{c.runner} "
                "is accepted with no evidence — name an hloaudit "
                "variant or a test literal"
            )
        for ev in c.evidence:
            kind, _, val = ev.partition(":")
            if kind == "variant" and not _manifest_exists(root, val):
                findings.append(
                    f"unaudited acceptance: cell {c.feature}x{c.runner} "
                    f"claims hloaudit variant '{val}' but tools/"
                    f"hloaudit/manifests/{val}.json does not exist — "
                    "register the variant and `python -m tools.hloaudit "
                    "--write`"
                )
            elif kind == "test" and val not in corpus:
                findings.append(
                    f"unevidenced acceptance: cell {c.feature}x"
                    f"{c.runner} pins test literal '{val}' which "
                    "appears nowhere under tests/"
                )
            elif kind not in ("variant", "test"):
                findings.append(
                    f"bad evidence kind '{kind}' on cell "
                    f"{c.feature}x{c.runner} (want variant:/test:)"
                )
    return findings


# ----------------------------------------------------------------------
# rendering
# ----------------------------------------------------------------------

_MARK = {"accepted": "yes", "rejected": "no", "untracked": "·"}


def render_markdown(matrix: dict) -> str:
    """FEATURES.md body: the feature × runner table, the composition
    table, and the untracked-cell debt list."""
    cells = {
        (c["feature"], c["runner"]): c for c in matrix["cells"]
    }
    lines = [
        "# Feature-composition matrix",
        "",
        "Generated by `python -m tools.featmat --write` from the gate",
        "clauses themselves (`tools/featmat/`); `--check` fails CI when",
        "this file, the gates, the hloaudit variants or the tests drift",
        "apart.  **yes** cells name their audit evidence (an hloaudit",
        "variant compiled + A1–A7-checked in CI, or a pinned test);",
        "**no** cells name the machine-parseable clause ID the rejection",
        "leads with (assert THESE in tests, never the prose); `·` cells",
        "are open coverage debt — no gate rejects them, no evidence",
        "pins them.",
        "",
        "| feature | " + " | ".join(matrix["runners"]) + " |",
        "|---|" + "---|" * len(matrix["runners"]),
    ]
    for feat in matrix["features"]:
        row = [f"| {feat} "]
        for runner in matrix["runners"]:
            c = cells[(feat, runner)]
            if c["verdict"] == "accepted":
                ev = ", ".join(
                    e.split(":", 1)[1] for e in c.get("evidence", ())
                )
                row.append(f"| yes ({ev}) ")
            elif c["verdict"] == "rejected":
                row.append(f"| no `[{c['clause']}]` ")
            else:
                row.append("| · ")
        lines.append("".join(row) + "|")
    lines += [
        "",
        "Feature legend:",
        "",
    ]
    for feat, desc in matrix["features"].items():
        lines.append(f"- **{feat}** — {desc}")
    lines += [
        "",
        "## Rejected compositions (spec-validation + CLI guard rails)",
        "",
        "| clause | pair | why |",
        "|---|---|---|",
    ]
    for p in matrix["compositions"]:
        lines.append(
            f"| `[{p['clause']}]` | {p['a']} × {p['b']} | {p['note']} |"
        )
    untracked = sorted(
        (c["feature"], c["runner"]) for c in matrix["cells"]
        if c["verdict"] == "untracked"
    )
    lines += [
        "",
        "## Open coverage debt (untracked cells)",
        "",
    ]
    for feat, runner in untracked:
        lines.append(f"- {feat} × {runner}")
    lines += [
        "",
        "Machine-readable form: `tools/featmat/matrix.json` (same",
        "`--write`).  Gate-site file:line detail lives there.",
        "",
    ]
    return "\n".join(lines)


def matrix_json(matrix: dict) -> str:
    return json.dumps(matrix, indent=1) + "\n"
