"""CPU-DES vs TPU-engine crossover measurement.

Runs the SAME synthetic publish workloads through the native sequential
event-driven core (``native/desim.cpp``, one CPU core) and the batched
TPU engine, and prints one JSON line per (world, backend) with
events/s (DES) and decisions/s (both).  The honest "when does the TPU
win" answer demanded by the r2 verdict lands in BENCHMARKS.md.

Usage: python tools/crossover.py [des|tpu|both]
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

WORLDS = {
    # name: (n_users, n_fogs, send_interval, horizon)
    "example-ish:1u": (1, 5, 0.05, 3.35),
    "smoke:2u": (2, 2, 0.05, 3.35),
    "grid:96u": (96, 4, 0.01, 1.0),
    "mid:1000u": (1000, 24, 0.01, 0.25),
    "headline:10ku": (10_000, 32, 0.0025, 0.1),
}


def schedule(n_users, interval, horizon, seed=0):
    """Synthetic client workload: staggered periodic publishes."""
    rng = np.random.default_rng(seed)
    start = rng.uniform(0.0, min(0.05, horizon / 4), n_users)
    per_user = [
        np.arange(start[u], horizon, interval) for u in range(n_users)
    ]
    user = np.concatenate(
        [np.full(len(t), u, np.int32) for u, t in enumerate(per_user)]
    )
    t_create = np.concatenate(per_user)
    order = np.argsort(t_create, kind="stable")
    user, t_create = user[order], t_create[order]
    mips = rng.integers(200, 901, len(user)).astype(np.float64)
    return user, t_create, mips


def run_des(name, n_users, n_fogs, interval, horizon):
    from fognetsimpp_tpu.native.bridge import run_gen

    user, t_create, mips = schedule(n_users, interval, horizon)
    d_ub = np.full(n_users, 2.0424e-4)  # wired_star 1e-4 + ser(128B)
    d_bf = np.full(n_fogs, 2.0424e-4)
    fog_mips = np.asarray(
        [(1000.0, 2000.0, 3000.0, 4000.0)[i % 4] for i in range(n_fogs)]
    )
    kw = dict(
        task_user=user, task_t_create=t_create, task_mips_req=mips,
        d_ub=d_ub, d_bf=d_bf, fog_mips=fog_mips,
        register_t=d_bf.copy(), adv0_t=3 * d_bf, horizon=horizon,
        queue_capacity=128,
    )
    run_gen(**kw)  # warm (JIT-free, but page in)
    t0 = time.perf_counter()
    out = run_gen(**kw)
    wall = time.perf_counter() - t0
    n_events = int(out["n_events"])
    print(json.dumps({
        "config": name, "backend": "des-1-cpu-core",
        "tasks": len(user), "events": n_events,
        "wall_s": round(wall, 4),
        "events_per_sec": round(n_events / wall, 1),
        "decisions_per_sec": round(len(user) / wall, 1),
    }), flush=True)


def run_tpu(name, n_users, n_fogs, interval, horizon):
    import jax

    from fognetsimpp_tpu.compile_cache import enable_compile_cache
    from fognetsimpp_tpu.core.engine import run
    from fognetsimpp_tpu.scenarios import smoke

    enable_compile_cache()
    spec, state, net, bounds = smoke.build(
        n_users=n_users, n_fogs=n_fogs,
        fog_mips=(1000.0, 2000.0, 3000.0, 4000.0),
        send_interval=interval, horizon=horizon, dt=1e-3,
        max_sends_per_user=int(horizon / interval) + 4,
        arrival_window=min(
            4096, max(64, int(1.1 * n_users * 1e-3 / interval))
        ),
        queue_capacity=128,
        start_time_max=min(0.05, horizon / 4),
    )

    @jax.jit
    def go(s):
        return run(spec, s, net, bounds)[0].metrics

    def fetch(m):
        return int(np.sum(np.asarray(m.n_scheduled)))

    fetch(go(state))  # compile + sync
    n_pipe = 3
    args = [state.replace(key=jax.random.PRNGKey(i + 1)) for i in range(n_pipe)]
    t0 = time.perf_counter()
    ms = [go(a) for a in args]
    dec = sum(fetch(m) for m in ms)
    wall = time.perf_counter() - t0
    print(json.dumps({
        "config": name, "backend": "tpu-batched-engine",
        "decisions": dec, "wall_s": round(wall, 4),
        "decisions_per_sec": round(dec / wall, 1),
    }), flush=True)


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    for name, (u, f, iv, hz) in WORLDS.items():
        if which in ("des", "both"):
            run_des(name, u, f, iv, hz)
        if which in ("tpu", "both"):
            run_tpu(name, u, f, iv, hz)


if __name__ == "__main__":
    main()
