"""Leave-one-out phase profiler for the batched tick engine.

Every per-tick phase has data-INdependent cost (fixed shapes, masked
updates), so the marginal device time of a phase can be measured by
patching it to identity and re-timing the whole scan — no xplane parsing
needed, and fusion interactions are captured for free.

Methodology (r4): the tunneled runtime charges a flat ~80-110 ms per
jitted CALL (dispatch + fetch round trip), independent of enqueued work —
single-call wall times are dominated by it.  Each configuration is
therefore timed at TWO scan lengths and the per-tick device cost is the
difference quotient  (wall(N_hi) - wall(N_lo)) / (N_hi - N_lo),  with
metrics-only outputs so no multi-MB state fetch pollutes the numbers.

Usage (on the TPU):  python tools/profile_tick.py [n_users]
Prints per-phase marginal ms/tick plus the full-step baseline.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

from fognetsimpp_tpu.compile_cache import enable_compile_cache
import fognetsimpp_tpu.core.engine as E
from fognetsimpp_tpu.scenarios import smoke

N_LO, N_HI = 100, 500


def build(n_users: int, dt: float = 1e-3):
    horizon, interval = 0.1, 0.0025
    mspt = max(1, -(-int(round(dt * 1e6)) // int(round(interval * 1e6))))
    return smoke.build(
        n_users=n_users,
        n_fogs=32,
        fog_mips=tuple(float(m) for m in (1000, 2000, 3000, 4000)),
        send_interval=interval,
        horizon=horizon,
        dt=dt,
        max_sends_per_user=int(horizon / interval) + 4,
        max_sends_per_tick=mspt,
        arrival_window=max(1024, int(1.15 * n_users * dt / interval)),
        queue_capacity=128,
        start_time_max=min(0.025, horizon / 4),
        derive_acks=True,  # the bench configuration (r5)
    )


def _wall(fn, state, reps=4):
    np.asarray(fn(state).n_scheduled)  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(fn(state).n_scheduled)
        best = min(best, time.perf_counter() - t0)
    return best


def time_scan(spec, state, net, bounds):
    """(device ms/tick, compile_s) via the two-length difference quotient."""

    t0 = time.perf_counter()

    @jax.jit
    def go_lo(s):
        return E.run(spec, s, net, bounds, n_ticks=N_LO)[0].metrics

    @jax.jit
    def go_hi(s):
        return E.run(spec, s, net, bounds, n_ticks=N_HI)[0].metrics

    w_lo = _wall(go_lo, state)
    compile_s = time.perf_counter() - t0 - w_lo * 3
    w_hi = _wall(go_hi, state)
    return (w_hi - w_lo) / (N_HI - N_LO) * 1e3, compile_s


def roofline(spec, state, net, bounds, device_ms_per_tick):
    """Measured bytes/FLOPs per tick vs chip peaks (VERDICT r3 item 8).

    XLA's own cost analysis of the compiled 1-tick program gives the HBM
    traffic and FLOP count; dividing by the measured device time yields
    achieved bandwidth/compute and their fraction of peak — so
    "bandwidth-bound at X%" is a computed claim, not a guess.  Peaks are
    the v5e datasheet: 819 GB/s HBM, 197 TFLOP/s bf16 (394 int8-OPS/s
    not relevant here; f32 matmul ~49 TFLOP/s).
    """
    step = E.make_step(spec)
    c = (
        jax.jit(lambda s: step(s, net, bounds))
        .lower(state)
        .compile()
        .cost_analysis()
    )
    if isinstance(c, (list, tuple)):
        c = c[0]
    if not c:
        print("roofline: cost_analysis unavailable on this backend")
        return
    flops = float(c.get("flops", 0.0))
    bts = float(c.get("bytes accessed", 0.0))
    t = device_ms_per_tick * 1e-3
    bw = bts / t
    fl = flops / t
    hbm_peak, f32_peak = 819e9, 49e12
    print(
        f"roofline: {bts / 1e6:.1f} MB + {flops / 1e6:.1f} MFLOP per tick -> "
        f"{bw / 1e9:.0f} GB/s ({bw / hbm_peak * 100:.1f}% of HBM peak), "
        f"{fl / 1e9:.1f} GFLOP/s ({fl / f32_peak * 100:.2f}% of f32 peak)"
    )
    print(
        "  -> "
        + (
            "bandwidth-bound"
            if bw / hbm_peak > fl / f32_peak
            else "compute-bound"
        )
        + f" at {max(bw / hbm_peak, fl / f32_peak) * 100:.1f}% of the "
        "limiting peak; the rest of the tick is kernel-launch/fusion "
        "overhead, not data"
    )


def main():
    enable_compile_cache()
    n_users = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
    dt = float(sys.argv[2]) if len(sys.argv) > 2 else 1e-3
    spec, state, net, bounds = build(n_users, dt)
    print(f"backend={jax.default_backend()} users={n_users} dt={dt} "
          f"T={spec.task_capacity} K={spec.window} ticks={spec.n_ticks}")

    base_ms, base_c = time_scan(spec, state, net, bounds)
    print(f"full step:            {base_ms:8.3f} ms/tick   (compile {base_c:.1f}s)")
    roofline(spec, state, net, bounds, base_ms)

    ident2 = lambda spec, state, net, cache, buf, *a, **k: (state, buf)
    # _phase_broker additionally returns the v2 release reschedule
    ident3 = lambda spec, state, net, cache, buf, *a, **k: (state, buf, None)

    def patched(name, attr, repl):
        orig = getattr(E, attr)
        setattr(E, attr, repl)
        try:
            ms, c = time_scan(spec, state, net, bounds)
        finally:
            setattr(E, attr, orig)
        print(f"- {name:20s} {ms:8.3f} ms/tick   marginal {base_ms - ms:+.3f}")

    patched("connect", "_phase_connect", ident2)
    patched("adverts", "_phase_adverts", lambda state, t1: state)
    # coarse dt (mspt > 1) dispatches the multi-send spawn instead
    spawn_attr = (
        "_phase_spawn_multi"
        if spec.max_sends_per_tick > 1
        else "_phase_spawn"
    )
    patched("spawn", spawn_attr, ident2)
    patched("broker", "_phase_broker", ident3)
    patched("broker_dense", "_phase_broker_dense", ident2)
    patched("completions", "_phase_completions", ident2)
    patched("fog_arrivals", "_phase_fog_arrivals", ident2)

    # mobility + association: patch both to constants
    cache0 = E.associate(net, state.nodes.pos, state.nodes.alive,
                         broker=spec.broker_index)
    patched("associate", "associate",
            lambda net_, pos, alive, broker=None, **kw: cache0)
    patched("mobility", "step_mobility",
            lambda nodes, bounds_, t1, dt: (nodes.pos, nodes.vel))

    # _compact: replace with a cheap (wrong but shape-correct) version to
    # bound its total share across phases
    import jax.numpy as jnp

    def fake_compact(mask, K, T, rot=None):
        idx = jnp.arange(K, dtype=jnp.int32)
        return idx, idx, mask[:K]

    patched("compact(all)", "_compact", fake_compact)

    # floor: all protocol phases stubbed — measures scan + mobility +
    # associate + state-carry overhead alone
    saved = {}
    for attr, repl in [
        ("_phase_connect", ident2), (spawn_attr, ident2),
        ("_phase_broker", ident3), ("_phase_broker_dense", ident2),
        ("_phase_completions", ident2),
        ("_phase_fog_arrivals", ident2),
        ("_phase_adverts", lambda state, t1: state),
    ]:
        saved[attr] = getattr(E, attr)
        setattr(E, attr, repl)
    try:
        ms, c = time_scan(spec, state, net, bounds)
    finally:
        for attr, orig in saved.items():
            setattr(E, attr, orig)
    print(f"- {'NULL (all stubbed)':20s} {ms:8.3f} ms/tick")


if __name__ == "__main__":
    main()
