"""Leave-one-out phase profiler for the batched tick engine.

Every per-tick phase has data-INdependent cost (fixed shapes, masked
updates), so the marginal device time of a phase can be measured by
patching it to identity and re-timing the whole scan — no xplane parsing
needed, and fusion interactions are captured for free.

Usage (on the TPU):  python tools/profile_tick.py [n_users]
Prints per-phase marginal ms/tick plus the full-step baseline.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from fognetsimpp_tpu.compile_cache import enable_compile_cache
import fognetsimpp_tpu.core.engine as E
from fognetsimpp_tpu.scenarios import smoke


def build(n_users: int):
    horizon, interval = 0.1, 0.0025
    return smoke.build(
        n_users=n_users,
        n_fogs=32,
        fog_mips=tuple(float(m) for m in (1000, 2000, 3000, 4000)),
        send_interval=interval,
        horizon=horizon,
        dt=1e-3,
        max_sends_per_user=int(horizon / interval) + 4,
        arrival_window=min(4096, max(1024, int(1.1 * n_users * 1e-3 / interval))),
        queue_capacity=128,
        start_time_max=min(0.05, horizon / 4),
    )


def time_scan(spec, state, net, bounds, n_ticks=100, reps=3):
    @jax.jit
    def go(s):
        final, _ = E.run(spec, s, net, bounds, n_ticks=n_ticks)
        return final

    t0 = time.perf_counter()
    jax.block_until_ready(go(state))
    compile_s = time.perf_counter() - t0
    best = float("inf")
    for r in range(reps):
        s = state.replace(key=jax.random.PRNGKey(r + 1))
        t0 = time.perf_counter()
        jax.block_until_ready(go(s))
        best = min(best, time.perf_counter() - t0)
    return best / n_ticks * 1e3, compile_s  # ms/tick


def main():
    enable_compile_cache()
    n_users = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
    spec, state, net, bounds = build(n_users)
    print(f"backend={jax.default_backend()} users={n_users} "
          f"T={spec.task_capacity} K={spec.window} ticks={spec.n_ticks}")

    base_ms, base_c = time_scan(spec, state, net, bounds)
    print(f"full step:            {base_ms:8.3f} ms/tick   (compile {base_c:.1f}s)")

    ident2 = lambda spec, state, net, cache, buf, *a, **k: (state, buf)
    # _phase_broker additionally returns the v2 release reschedule
    ident3 = lambda spec, state, net, cache, buf, *a, **k: (state, buf, None)

    def patched(name, attr, repl):
        orig = getattr(E, attr)
        setattr(E, attr, repl)
        try:
            ms, c = time_scan(spec, state, net, bounds)
        finally:
            setattr(E, attr, orig)
        print(f"- {name:20s} {ms:8.3f} ms/tick   marginal {base_ms - ms:+.3f}   (compile {c:.1f}s)")

    patched("connect", "_phase_connect", ident2)
    patched("adverts", "_phase_adverts", lambda state, t1: state)
    patched("spawn", "_phase_spawn", ident2)
    patched("broker", "_phase_broker", ident3)
    patched("completions", "_phase_completions", ident2)
    patched("fog_arrivals", "_phase_fog_arrivals", ident2)

    # mobility + association: patch both to constants
    cache0 = E.associate(net, state.nodes.pos, state.nodes.alive,
                         broker=spec.broker_index)
    patched("associate", "associate",
            lambda net_, pos, alive, broker: cache0)
    patched("mobility", "step_mobility",
            lambda nodes, bounds_, t1, dt: (nodes.pos, nodes.vel))

    # _compact: replace with a cheap (wrong but shape-correct) version to
    # bound its total share across phases
    K_ = spec.window

    def fake_compact(mask, K, T):
        idx = jnp.arange(K, dtype=jnp.int32)
        return idx, idx, mask[:K]

    patched("compact(all)", "_compact", fake_compact)

    # floor: all protocol phases stubbed — measures scan + mobility +
    # associate + state-carry overhead alone
    saved = {}
    for attr, repl in [
        ("_phase_connect", ident2), ("_phase_spawn", ident2),
        ("_phase_broker", ident3), ("_phase_completions", ident2),
        ("_phase_fog_arrivals", ident2),
        ("_phase_adverts", lambda state, t1: state),
    ]:
        saved[attr] = getattr(E, attr)
        setattr(E, attr, repl)
    try:
        ms, c = time_scan(spec, state, net, bounds)
    finally:
        for attr, orig in saved.items():
            setattr(E, attr, orig)
    print(f"- {'NULL (all stubbed)':20s} {ms:8.3f} ms/tick   (compile {c:.1f}s)")


if __name__ == "__main__":
    main()
