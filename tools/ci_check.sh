#!/usr/bin/env bash
# CI/developer gate: the simlint static pass, then the quick test tier.
#
#   tools/ci_check.sh            # lint + quick tests (the <60 s loop)
#   tools/ci_check.sh --full     # lint + the whole suite
#
# simlint runs first and fails fast: an unsuppressed JAX/TPU hazard
# (tools/simlint/RULES.md) never reaches the test run.  The suppression
# baseline lives at tools/simlint/baseline.json; grandfather a finding
# with `python -m tools.simlint --update-baseline fognetsimpp_tpu` and
# commit the (reviewable) diff.
#
# The quick tier includes the fleet equivalence gate (tests/test_fleet.py):
# conftest.py forces an 8-virtual-device CPU mesh, so the replica-sharded
# fleet runner's per-replica state-hash A/B vs the vmap path runs here
# and in tier-1 without TPU hardware.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== simlint =="
python -m tools.simlint fognetsimpp_tpu

echo "== op budget (fused-tick kernel-count gate) =="
JAX_PLATFORMS=cpu python tools/op_budget.py --check > /dev/null

echo "== hloaudit (compiled-artifact audit of every tick variant) =="
# host transfers, f64 promotion chains, undeclared/degenerate
# collectives, the f32 2^24 bound, golden audit manifests, donation
# aliasing (A6) and peak-buffer budgets (A7) — over fused/unfused x
# telemetry/hist x fleet x TP-dryrun x accepted-cell compiles (the
# 8-virtual-device CPU mesh is forced by the CLI itself)
python -m tools.hloaudit --check > /dev/null

echo "== featmat (feature-composition matrix consistency) =="
# the gates' clause IDs vs the declared feature x runner matrix vs the
# hloaudit variant registry vs the tests: a deleted/drifting rejection
# clause, an untested rejection, an unevidenced acceptance, or a stale
# FEATURES.md/matrix.json fails here (regen: python -m tools.featmat
# --write)
python -m tools.featmat --check > /dev/null

echo "== bench trend (>10% regression gate over BENCH_r*/MULTICHIP_r*) =="
python tools/bench_trend.py --check

echo "== telemetry smoke (trace export + OpenMetrics lint, hist on) =="
TELEM_OUT="$(mktemp -d)"
JAX_PLATFORMS=cpu python -m fognetsimpp_tpu --scenario smoke \
    --set spec.horizon=0.5 --telemetry --hist --slo 100 \
    --trace-out "${TELEM_OUT}/trace.json" --out "${TELEM_OUT}" > /dev/null
python -c "import json, sys; json.load(open(sys.argv[1]))" "${TELEM_OUT}/trace.json"
python tools/check_openmetrics.py "${TELEM_OUT}"/General-0.om.txt
rm -rf "${TELEM_OUT}"

MARKER="quick"
if [[ "${1:-}" == "--full" ]]; then
    MARKER="not slow or slow"
fi

echo "== pytest (-m '${MARKER}') =="
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m "${MARKER}" \
    -p no:cacheprovider
