"""CLI for the simlint static pass: nonzero exit on unsuppressed findings."""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .core import lint, write_baseline
from .rules import default_rules

_DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.simlint",
        description="JAX/TPU-hazard static analysis for this repo "
        "(rules: tools/simlint/RULES.md)",
    )
    ap.add_argument("paths", nargs="*", help="packages/files to lint")
    ap.add_argument(
        "--baseline", default=_DEFAULT_BASELINE,
        help="suppression baseline JSON (default: tools/simlint/"
        "baseline.json)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="ignore the baseline (report everything)",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="write the current unsuppressed findings into the baseline "
        "and exit 0 (grandfathering workflow: lint, fix what you can, "
        "baseline the rest with a reviewable diff)",
    )
    ap.add_argument("--json", action="store_true", help="machine output")
    ap.add_argument(
        "--show-baselined", action="store_true",
        help="also print findings matched by the baseline",
    )
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in default_rules():
            print(f"{r.id}: {r.title}")
        return 0
    if not args.paths:
        ap.error("no paths given (try: python -m tools.simlint "
                 "fognetsimpp_tpu)")

    baseline = None if args.no_baseline else args.baseline
    result = lint(args.paths, baseline_path=baseline)

    if args.update_baseline:
        write_baseline(args.baseline, result.findings + result.baselined)
        print(
            f"simlint: baselined {len(result.findings)} new finding(s) "
            f"({len(result.baselined)} kept) -> {args.baseline}"
        )
        return 0

    if args.json:
        print(json.dumps({
            "files": result.n_files,
            "findings": [f.__dict__ for f in result.findings],
            "baselined": [f.__dict__ for f in result.baselined],
            "inline_suppressed": result.inline_suppressed,
        }, indent=2))
    else:
        for f in result.findings:
            print(f.render())
        if args.show_baselined:
            for f in result.baselined:
                print(f"[baselined] {f.render()}")
        status = "clean" if result.ok else f"{len(result.findings)} finding(s)"
        print(
            f"simlint: {result.n_files} file(s), {status} "
            f"({len(result.baselined)} baselined, "
            f"{result.inline_suppressed} inline-suppressed)",
            file=sys.stderr,
        )
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
