"""simlint core: AST framework, device-context classifier, suppressions.

The OMNeT++ reference gets schema/state discipline from nedtool codegen
and the C++ type system; this JAX port gets it from here.  simlint is a
*codebase-specific* static pass: it knows which modules are device code
(traced into `lax.scan` bodies and jit programs), which parameter types
are static under `jax.jit` (``WorldSpec``, plain ints) versus traced
(``WorldState``, ``NetParams``, ``jax.Array``), and it checks the hazard
classes that repeatedly cost us TPU performance or correctness — hidden
host syncs, recompile triggers, dtype promotion, nondeterminism, missing
buffer donation, per-trace constant churn, and uncontracted engine
phases.  See ``tools/simlint/RULES.md`` for the rule catalogue.

Architecture:

* :class:`ModuleInfo` — one parsed file: AST + parent links + the set of
  *device functions* (see below) + per-function scope tables.
* :class:`Rule` — ``check_module`` runs per file; ``check_project`` runs
  once over the whole corpus (used by R8 contract coverage).
* Device classification — a function is device code when it (a) lives in
  a blanket device module (``DEVICE_MODULE_GLOBS``: the engine, ops,
  kernels, state), (b) is jit/pallas-decorated or passed to a tracing
  combinator (``lax.scan``, ``jax.vmap``, ...), (c) is named like an
  engine phase (``_phase_*``), (d) is nested in or called from a device
  function (module-local call-graph fixpoint).
* Suppressions — inline ``# simlint: disable=R6 -- reason`` on the
  finding line or in the comment block directly above it, plus a JSON
  baseline file for grandfathered findings (``--update-baseline``
  refreshes it; new findings stay fatal).
"""
from __future__ import annotations

import ast
import dataclasses
import fnmatch
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# ----------------------------------------------------------------------
# repo-specific configuration
# ----------------------------------------------------------------------

# Modules whose every function is device code (hot path / traced).
DEVICE_MODULE_GLOBS: Tuple[str, ...] = (
    "core/engine.py",
    "core/contracts.py",
    "ops/*.py",
    "net/energy.py",
    "net/mobility.py",
    "learn/bandits.py",
    "learn/rewards.py",
    "parallel/tp.py",
    "state.py",
)
# telemetry/metrics.py and telemetry/health.py are deliberately NOT
# blanket device modules: each mixes one carry-resident accumulation
# function (device, reached through core/engine.py which IS covered)
# with host-side post-run readers over fetched numpy arrays — a
# blanket classification would flag the legitimate host half.

# Annotation tokens that mean "static under jit" (hashable, not traced).
STATIC_TYPE_TOKENS: Set[str] = {
    "int", "float", "bool", "str", "bytes", "None", "Optional",
    "WorldSpec", "Policy", "Stage", "FogModel", "Mobility", "NodeKind",
    "Callable", "Sequence", "Dict", "List", "Mesh", "str",
    # plain-dict params are host containers whose STRUCTURE drives
    # trace-time control flow (the fused views pack: `views:
    # Optional[dict]`); their leaves re-enter tracedness as soon as
    # they feed a jnp op
    "dict",
}

# Unannotated parameter names assumed static (the spec convention).
# NOTE: the fused front-end's `views` packs are annotated
# `Optional[dict]`, which the "dict" token above already classifies —
# no bare-name exemption, so an unannotated traced `views` array in a
# future module keeps full R1/R2 coverage.
STATIC_PARAM_NAMES: Set[str] = {"spec", "self", "cls", "sp"}

# Attribute accesses that yield static metadata even on traced arrays.
STATIC_ATTRS: Set[str] = {"shape", "ndim", "dtype", "size", "sharding"}

# Calls whose RESULT is host data even when their arguments are traced:
# fetching/materializing calls.  The call site itself may still be an R1
# finding (R1 inspects the arguments); what these entries fix is the
# DOWNSTREAM false-positive — `if jax.device_get(x) > 0` is a host
# branch, not a traced one, and a name assigned from such a call must
# not propagate tracedness through the dataflow layer.
HOST_RESULT_CALLS: Set[str] = {
    "jax.device_get", "np.asarray", "np.array", "numpy.asarray",
    "numpy.array", "float", "int", "bool", "len",
    # host-only introspection: never returns device data
    "isinstance", "issubclass", "hasattr", "callable", "type",
    "jax.default_backend", "jax.eval_shape", "jax.devices",
    "jax.local_devices", "jax.device_count",
}

# Method calls on traced objects whose RESULT is host data.
HOST_RESULT_METHODS: Set[str] = {
    "item", "tolist", "tobytes", "unsafe_buffer_pointer",
}

# Calls whose function-name arguments become traced (device) code.
TRACING_COMBINATORS: Set[str] = {
    "jax.lax.scan", "lax.scan", "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop", "jax.lax.cond", "lax.cond",
    "jax.lax.switch", "lax.switch", "jax.lax.map", "lax.map",
    "jax.jit", "jax.vmap", "jax.pmap", "jax.grad", "jax.value_and_grad",
    "jax.checkpoint", "jax.remat", "jax.eval_shape", "jax.linearize",
    "jax.custom_jvp", "jax.custom_vjp",
    # the sharded runners' explicit-collective combinator (ISSUE 20):
    # a shard_map body is device code like any scanned/jitted fn, so
    # R13 sees promoted-knob reads inside parallel/ shard_map bodies
    "shard_map", "jax.shard_map", "jax.experimental.shard_map.shard_map",
}

_SUPPRESS_RE = re.compile(r"#\s*simlint:\s*disable=([A-Za-z0-9_,\s]+)")


# ----------------------------------------------------------------------
# findings
# ----------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    relpath: str
    line: int
    message: str
    text: str  # stripped source line: the line-number-stable baseline key

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.relpath, self.text)

    def render(self) -> str:
        return f"{self.relpath}:{self.line}: {self.rule}: {self.message}"


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]          # unsuppressed: these fail the build
    baselined: List[Finding]         # matched the suppression baseline
    inline_suppressed: int
    n_files: int

    @property
    def ok(self) -> bool:
        return not self.findings


# ----------------------------------------------------------------------
# AST helpers
# ----------------------------------------------------------------------

def dotted(node: ast.AST) -> Optional[str]:
    """``jax.lax.scan`` for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _ann_is_static(ann: Optional[ast.AST]) -> Optional[bool]:
    """True/False from an annotation, None when there is no annotation."""
    if ann is None:
        return None
    text = ast.unparse(ann)
    idents = set(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", text))
    return bool(idents) and idents <= STATIC_TYPE_TOKENS


def param_is_static(arg: ast.arg) -> bool:
    by_ann = _ann_is_static(arg.annotation)
    if by_ann is not None:
        return by_ann
    return arg.arg in STATIC_PARAM_NAMES or arg.arg.isupper()


def func_params(fn: ast.FunctionDef) -> List[ast.arg]:
    a = fn.args
    return [*a.posonlyargs, *a.args, *a.kwonlyargs]


def is_jit_decorator(dec: ast.AST) -> bool:
    """``@jax.jit`` / ``@functools.partial(jax.jit, ...)`` / pallas."""
    name = dotted(dec)
    if name in ("jax.jit", "jit") or (name or "").endswith("pallas_call"):
        return True
    if isinstance(dec, ast.Call):
        fname = dotted(dec.func)
        if fname in ("jax.jit", "jit") or (fname or "").endswith(
            "pallas_call"
        ):
            return True
        if fname in ("functools.partial", "partial") and dec.args:
            inner = dotted(dec.args[0])
            return inner in ("jax.jit", "jit")
    return False


def jit_call_kwargs(dec: ast.AST) -> Optional[Dict[str, ast.AST]]:
    """Keyword args of a jit decorator/call, else None."""
    if isinstance(dec, ast.Call):
        return {kw.arg: kw.value for kw in dec.keywords if kw.arg}
    return {} if dotted(dec) in ("jax.jit", "jit") else None


def const_int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """Literal int / tuple-of-ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (
                isinstance(e, ast.Constant) and isinstance(e.value, int)
            ):
                return None
            out.append(e.value)
        return tuple(out)
    return None


def is_const_expr(node: ast.AST) -> bool:
    """Compile-time constant-ish: literals, enum members, int()/float()
    of those, and arithmetic over them — the R7 "rebuilt every trace"
    class."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp):
        return is_const_expr(node.operand)
    if isinstance(node, ast.BinOp):
        return is_const_expr(node.left) and is_const_expr(node.right)
    if isinstance(node, ast.Attribute):
        name = dotted(node)
        # Stage.LOST / Policy.MAX_MIPS: CamelCase root = enum class
        return bool(name) and name.split(".")[0][:1].isupper()
    if isinstance(node, ast.Name):
        return node.id.isupper()  # module-level constant convention
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in ("int", "float", "bool") and len(node.args) == 1:
            return is_const_expr(node.args[0])
    return False


# ----------------------------------------------------------------------
# module model
# ----------------------------------------------------------------------

_FuncNode = ast.FunctionDef  # (async defs are treated identically)


class ModuleInfo:
    """Parsed file + device classification + scope tables."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.functions: List[_FuncNode] = [
            n
            for n in ast.walk(self.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        # suffix-anchored against BOTH the scan-relative path and the
        # absolute path, so classification is independent of the scan
        # root: `fognetsimpp_tpu`, `.`, `fognetsimpp_tpu/core`, or the
        # file itself all classify core/engine.py as a device module
        abspath = os.path.abspath(path).replace(os.sep, "/")
        self.blanket_device = any(
            fnmatch.fnmatch(cand, g) or fnmatch.fnmatch(cand, "*/" + g)
            for g in DEVICE_MODULE_GLOBS
            for cand in (self.relpath, abspath)
        )
        self._locals: Dict[_FuncNode, Set[str]] = {
            f: self._collect_locals(f) for f in self.functions
        }
        self._traced_env: Dict[_FuncNode, Set[str]] = {}
        self.device_funcs: Set[_FuncNode] = self._classify_device()

    # -- scopes --------------------------------------------------------

    def _collect_locals(self, fn: _FuncNode) -> Set[str]:
        names = {a.arg for a in func_params(fn)}
        if fn.args.vararg:
            names.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            names.add(fn.args.kwarg.arg)
        for node in ast.walk(fn):
            if node is fn:
                continue
            if self.enclosing_function(node) is not fn:
                continue  # belongs to a nested scope
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                names.add(node.id)
            elif isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                names.add(node.name)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    names.add((alias.asname or alias.name).split(".")[0])
        return names

    def enclosing_function(self, node: ast.AST) -> Optional[_FuncNode]:
        """Nearest FunctionDef strictly above ``node`` (None: module)."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def function_chain(self, fn: _FuncNode) -> List[_FuncNode]:
        """``fn`` and every function lexically enclosing it, inner-first."""
        chain = [fn]
        cur = self.enclosing_function(fn)
        while cur is not None:
            chain.append(cur)
            cur = self.enclosing_function(cur)
        return chain

    def local_names(self, fn: _FuncNode) -> Set[str]:
        return self._locals[fn]

    # -- device classification ----------------------------------------

    def _classify_device(self) -> Set[_FuncNode]:
        by_name: Dict[str, List[_FuncNode]] = {}
        for f in self.functions:
            by_name.setdefault(f.name, []).append(f)

        device: Set[_FuncNode] = set()
        for f in self.functions:
            if self.blanket_device or f.name.startswith("_phase_"):
                device.add(f)
            elif any(is_jit_decorator(d) for d in f.decorator_list):
                device.add(f)

        # functions passed (by name) to tracing combinators
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            if dotted(call.func) not in TRACING_COMBINATORS:
                continue
            for arg in ast.walk(call):
                if isinstance(arg, ast.Name) and arg.id in by_name:
                    device.update(by_name[arg.id])

        # fixpoint: nested-in-device + called-from-device (module-local)
        changed = True
        while changed:
            changed = False
            for f in self.functions:
                if f in device:
                    continue
                enc = self.enclosing_function(f)
                if enc is not None and enc in device:
                    device.add(f)
                    changed = True
            for df in list(device):
                for node in ast.walk(df):
                    if (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in by_name
                    ):
                        for target in by_name[node.func.id]:
                            if target not in device:
                                device.add(target)
                                changed = True
        return device

    # -- tracedness ----------------------------------------------------

    def traced_roots(self, fn: _FuncNode) -> Set[str]:
        """Names that are traced arrays inside ``fn``: its own non-static
        params plus those of enclosing device functions."""
        roots: Set[str] = set()
        for f in self.function_chain(fn):
            for a in func_params(f):
                if not param_is_static(a):
                    roots.add(a.arg)
        return roots

    def traced_env(self, fn: _FuncNode) -> Set[str]:
        """The v2 dataflow layer: traced names including ASSIGNED ones.

        :meth:`traced_roots` sees only parameters; this adds a fixpoint
        over the assignments of ``fn`` and its enclosing functions, so
        ``y = x * 2; if y > 0`` fires R2 just like ``if x * 2 > 0``
        would.  Propagation is deliberately narrower than
        :meth:`expr_is_traced`: a value flows tracedness only through
        arithmetic/indexing/jnp-calls/method-calls — the result of a
        call to a *local helper function* is unknown and does NOT
        propagate (that is where v1-style guessing would manufacture
        false positives on container-returning helpers), and
        ``HOST_RESULT_CALLS`` results explicitly stop the flow.
        """
        if fn in self._traced_env:
            return self._traced_env[fn]
        traced = set(self.traced_roots(fn))
        def target_names(t: ast.AST) -> List[str]:
            # only true REBINDS of a name — a Subscript/Attribute store
            # (`views["k"] = ...`) mutates a container and must not
            # re-type the container's name
            if isinstance(t, ast.Name):
                return [t.id]
            if isinstance(t, (ast.Tuple, ast.List)):
                return [n for e in t.elts for n in target_names(e)]
            if isinstance(t, ast.Starred):
                return target_names(t.value)
            return []

        assigns: List[Tuple[str, ast.AST, bool]] = []  # (name, value, aug)
        for f in self.function_chain(fn):
            for node in ast.walk(f):
                if self.enclosing_function(node) is not f:
                    continue
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        for name in target_names(t):
                            assigns.append((name, node.value, False))
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    if isinstance(node.target, ast.Name):
                        assigns.append(
                            (node.target.id, node.value, False)
                        )
                elif isinstance(node, ast.AugAssign):
                    if isinstance(node.target, ast.Name):
                        assigns.append((node.target.id, node.value, True))
        changed = True
        while changed:
            changed = False
            for name, value, aug in assigns:
                if name in traced and not aug:
                    continue
                if name in traced or self._value_propagates(value, traced):
                    if name not in traced:
                        traced.add(name)
                        changed = True
        self._traced_env[fn] = traced
        return traced

    def _value_propagates(self, value: ast.AST, traced: Set[str]) -> bool:
        """Whether an assigned VALUE carries tracedness onto its target
        (the narrowed propagation rule of :meth:`traced_env`)."""
        if isinstance(value, ast.Call):
            name = dotted(value.func) or ""
            if name in HOST_RESULT_CALLS:
                return False
            if name.startswith(("jnp.", "jax.", "lax.")):
                return True
            if isinstance(value.func, ast.Attribute):
                # method call on a traced object: x.astype(...), x.sum()
                return (
                    value.func.attr not in HOST_RESULT_METHODS
                    and self.expr_is_traced(value.func.value, traced)
                )
            return False  # local-helper call: unknown result, no flow
        # containers/conditionals recurse through THIS narrowed rule, so
        # `fv = pack(...) if fused else None` does not leak the generic
        # call's any-arg-traced guess into the assignment layer
        if isinstance(value, ast.IfExp):
            return self._value_propagates(
                value.body, traced
            ) or self._value_propagates(value.orelse, traced)
        if isinstance(value, ast.BoolOp):
            return any(
                self._value_propagates(v, traced) for v in value.values
            )
        if isinstance(value, (ast.Tuple, ast.List)):
            return any(
                self._value_propagates(e, traced) for e in value.elts
            )
        return self.expr_is_traced(value, traced)

    def expr_is_traced(self, node: ast.AST, roots: Set[str]) -> bool:
        """Conservative syntactic test: does ``node`` produce (or contain)
        a traced value?  Attribute chains through ``.shape``-style static
        metadata and ``is None`` checks are static."""
        if isinstance(node, ast.Name):
            return node.id in roots
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self.expr_is_traced(node.value, roots)
        if isinstance(node, ast.Subscript):
            return self.expr_is_traced(node.value, roots) or (
                self.expr_is_traced(node.slice, roots)
            )
        if isinstance(node, ast.Call):
            name = dotted(node.func) or ""
            if name in HOST_RESULT_CALLS:
                return False  # materializes on host; result is not traced
            if name.startswith(("jnp.", "jax.", "lax.")):
                return True
            if isinstance(node.func, ast.Attribute) and self.expr_is_traced(
                node.func.value, roots
            ):
                if node.func.attr in HOST_RESULT_METHODS:
                    return False  # fetches to host (R1's job to flag)
                return True  # method call on a traced object (x.sum(), ...)
            return any(
                self.expr_is_traced(a, roots) for a in node.args
            ) or any(
                self.expr_is_traced(k.value, roots) for k in node.keywords
            )
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False  # `x is None` host checks on optionals
            return self.expr_is_traced(node.left, roots) or any(
                self.expr_is_traced(c, roots) for c in node.comparators
            )
        if isinstance(node, (ast.BoolOp,)):
            return any(self.expr_is_traced(v, roots) for v in node.values)
        if isinstance(node, ast.BinOp):
            return self.expr_is_traced(
                node.left, roots
            ) or self.expr_is_traced(node.right, roots)
        if isinstance(node, ast.UnaryOp):
            return self.expr_is_traced(node.operand, roots)
        if isinstance(node, ast.IfExp):
            return any(
                self.expr_is_traced(n, roots)
                for n in (node.test, node.body, node.orelse)
            )
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.expr_is_traced(e, roots) for e in node.elts)
        return False

    # -- iteration helpers --------------------------------------------

    def device_nodes(self) -> Iterable[Tuple[_FuncNode, ast.AST]]:
        """(device_function, node) for every node inside device code."""
        for f in self.device_funcs:
            for node in ast.walk(f):
                enc = self.enclosing_function(node)
                if enc is f:
                    yield f, node

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule, self.relpath, line, message, self.line_text(line))


# ----------------------------------------------------------------------
# rules + runner
# ----------------------------------------------------------------------

class Rule:
    id: str = "R0"
    title: str = ""

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        return ()

    def check_project(
        self, mods: Sequence[ModuleInfo]
    ) -> Iterable[Finding]:
        return ()


def _inline_suppressed(mod: ModuleInfo, f: Finding) -> bool:
    """``# simlint: disable=Rx`` on the finding line, or anywhere in the
    contiguous comment block directly above it."""

    def match(text: str) -> bool:
        m = _SUPPRESS_RE.search(text)
        if not m:
            return False
        rules = {r.strip().split()[0] for r in m.group(1).split(",")}
        return f.rule in rules or "all" in rules

    if match(mod.line_text(f.line)):
        return True
    i = f.line - 1
    while i >= 1:
        text = mod.line_text(i)
        if not text.startswith("#"):
            break
        if match(text):
            return True
        i -= 1
    return False


def collect_files(paths: Sequence[str]) -> List[Tuple[str, str]]:
    """(abspath, relpath) for every .py under ``paths``; relpath is
    relative to the scanned top-level dir (device-glob keys)."""
    out: List[Tuple[str, str]] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            # full path (not basename) so suffix-anchored device-module
            # globs still classify directly-linted files correctly
            out.append((p, p))
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(
                d for d in dirs if d not in ("__pycache__", ".git")
            )
            for name in sorted(files):
                if name.endswith(".py"):
                    full = os.path.join(root, name)
                    out.append((full, os.path.relpath(full, p)))
    return out


def load_baseline(
    path: Optional[str],
) -> Dict[Tuple[str, str, str], int]:
    """key -> grandfathered occurrence count.  Counted (not a set) so a
    future textually-identical violation in the same file is NOT covered
    by an older grandfathered one — new findings stay fatal."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    counts: Dict[Tuple[str, str, str], int] = {}
    for e in data.get("suppress", []):
        key = (e["rule"], e["path"], e["text"])
        counts[key] = counts.get(key, 0) + int(e.get("count", 1))
    return counts


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    data = {
        "_comment": (
            "simlint suppression baseline: grandfathered findings keyed "
            "by (rule, path, source-line text, occurrence count) so line "
            "drift does not invalidate them.  Regenerate with "
            "--update-baseline; new findings (including new copies of a "
            "baselined line) stay fatal until fixed or re-baselined."
        ),
        "suppress": [
            {
                "rule": r, "path": p, "text": t,
                **({"count": c} if c > 1 else {}),
            }
            for (r, p, t), c in sorted(counts.items())
        ],
    }
    with open(path, "w") as fh:
        json.dump(data, fh, indent=2, sort_keys=False)
        fh.write("\n")


def lint(
    paths: Sequence[str],
    rules: Optional[Sequence[Rule]] = None,
    baseline_path: Optional[str] = None,
) -> LintResult:
    if rules is None:
        from .rules import default_rules

        rules = default_rules()
    baseline = load_baseline(baseline_path)

    mods: List[ModuleInfo] = []
    for full, rel in collect_files(paths):
        with open(full, encoding="utf-8") as fh:
            src = fh.read()
        mods.append(ModuleInfo(full, rel, src))

    raw: List[Tuple[ModuleInfo, Finding]] = []
    by_rel = {m.relpath: m for m in mods}
    for mod in mods:
        for rule in rules:
            for f in rule.check_module(mod):
                raw.append((mod, f))
    for rule in rules:
        for f in rule.check_project(mods):
            raw.append((by_rel.get(f.relpath, mods[0]), f))

    findings: List[Finding] = []
    baselined: List[Finding] = []
    used: Dict[Tuple[str, str, str], int] = {}
    n_inline = 0
    for mod, f in sorted(
        raw, key=lambda mf: (mf[1].relpath, mf[1].line, mf[1].rule)
    ):
        if _inline_suppressed(mod, f):
            n_inline += 1
        elif used.get(f.key(), 0) < baseline.get(f.key(), 0):
            used[f.key()] = used.get(f.key(), 0) + 1
            baselined.append(f)
        else:
            findings.append(f)
    return LintResult(findings, baselined, n_inline, len(mods))
