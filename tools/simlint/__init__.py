"""simlint: the repo's JAX/TPU-hazard static-analysis pass.

Usage::

    python -m tools.simlint fognetsimpp_tpu        # lint the package
    python -m tools.simlint --list-rules
    python -m tools.simlint --update-baseline fognetsimpp_tpu

Programmatic: :func:`tools.simlint.core.lint`.
"""
from .core import Finding, LintResult, lint  # noqa: F401
from .rules import default_rules  # noqa: F401
