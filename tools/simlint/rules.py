"""The simlint rule catalogue (R1-R14).  See RULES.md for the narrative
version with offending/sanctioned snippets; docstrings here are the
machine-adjacent summary."""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import (
    Finding,
    ModuleInfo,
    Rule,
    const_int_tuple,
    dotted,
    func_params,
    is_const_expr,
    is_jit_decorator,
    jit_call_kwargs,
    param_is_static,
)

_NP_SYNC_CALLS = {
    "np.asarray", "np.array", "np.ascontiguousarray",
    "numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
}

_64BIT_DTYPES = {
    "jnp.float64", "jnp.int64", "jnp.uint64", "jnp.complex128",
    "np.float64", "np.int64", "numpy.float64", "numpy.int64",
}
_64BIT_STRINGS = {"float64", "int64", "uint64", "complex128"}

_SMALL_DTYPES = {
    "jnp.int8", "jnp.int16", "jnp.int32", "jnp.uint8", "jnp.uint16",
    "jnp.uint32", "jnp.float32", "jnp.float16", "jnp.bfloat16",
}


class HostSyncRule(Rule):
    """R1: host-sync in device code — ``.item()``, ``float()/int()/
    bool()`` on traced values, ``np.asarray``/``np.array`` on device
    arrays.  Each forces a device->host transfer that serializes the
    step stream (and is simply invalid under `lax.scan` tracing)."""

    id = "R1"
    title = "host sync in device code"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn, node in mod.device_nodes():
            if not isinstance(node, ast.Call):
                continue
            roots = mod.traced_env(fn)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and not node.args
            ):
                yield mod.finding(
                    self.id, node,
                    "`.item()` forces a blocking device->host sync inside "
                    "device code; keep the value on device (0-d array) or "
                    "move the readback outside the jit/scan boundary",
                )
                continue
            name = dotted(node.func)
            if name in _NP_SYNC_CALLS and any(
                mod.expr_is_traced(a, roots) for a in node.args
            ):
                yield mod.finding(
                    self.id, node,
                    f"`{name}(...)` on a traced value materializes it on "
                    "host; use `jnp` ops (or hoist the conversion out of "
                    "the device path)",
                )
                continue
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int", "bool")
                and len(node.args) == 1
                and mod.expr_is_traced(node.args[0], roots)
            ):
                yield mod.finding(
                    self.id, node,
                    f"`{node.func.id}(...)` of a traced value is a hidden "
                    "host sync (concretization error under jit); use "
                    "`.astype(...)` / `jnp.*` casts instead",
                )


class TracedBranchRule(Rule):
    """R2: Python ``if``/``while`` branching on traced comparisons —
    a concretization error under jit, and a per-value recompile when it
    accidentally works via early concrete values."""

    id = "R2"
    title = "Python branch on traced value"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn, node in mod.device_nodes():
            if not isinstance(node, (ast.If, ast.While)):
                continue
            roots = mod.traced_env(fn)
            if mod.expr_is_traced(node.test, roots):
                kind = "if" if isinstance(node, ast.If) else "while"
                yield mod.finding(
                    self.id, node,
                    f"Python `{kind}` on a traced comparison; use "
                    "`jnp.where` / `lax.cond` / `lax.while_loop` (static "
                    "spec fields are fine — annotate them)",
                )


def _jit_sites(
    mod: ModuleInfo,
) -> Iterable[Tuple[ast.AST, Optional[ast.FunctionDef], Dict[str, ast.AST]]]:
    """(site_node, wrapped_function_def_or_None, jit_kwargs)."""
    by_name = {f.name: f for f in mod.functions}
    for f in mod.functions:
        for dec in f.decorator_list:
            if is_jit_decorator(dec):
                yield dec, f, (jit_call_kwargs(dec) or {})
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or dotted(node.func) not in (
            "jax.jit", "jit",
        ):
            continue
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        wrapped: Optional[ast.FunctionDef] = None
        if node.args:
            a0 = node.args[0]
            if isinstance(a0, ast.Name):
                wrapped = by_name.get(a0.id)
            elif isinstance(a0, ast.Call):  # jax.jit(jax.vmap(f))
                for inner in ast.walk(a0):
                    if isinstance(inner, ast.Name) and inner.id in by_name:
                        wrapped = by_name[inner.id]
                        break
        yield node, wrapped, kwargs


def _module_globals(mod: ModuleInfo) -> Set[str]:
    names: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                names.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else
                [node.target]
            )
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
        elif isinstance(node, (ast.If, ast.Try)):
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Import, ast.ImportFrom)):
                    for alias in sub.names:
                        names.add((alias.asname or alias.name).split(".")[0])
    return names


class RecompileHazardRule(Rule):
    """R3: recompile triggers at jit boundaries — (a) array-annotated
    params marked static (retrace per value, unhashable TypeError), and
    (b) traced values captured by closure into a jit entry point (baked
    in as constants; silently retraced/re-embedded per call)."""

    id = "R3"
    title = "recompile hazard at jit boundary"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        import builtins

        mod_globals = _module_globals(mod)
        for site, wrapped, kwargs in _jit_sites(mod):
            # (a) static argnums pointing at array-annotated params
            if wrapped is not None and "static_argnums" in kwargs:
                idxs = const_int_tuple(kwargs["static_argnums"]) or ()
                params = func_params(wrapped)
                for i in idxs:
                    if i < len(params):
                        p = params[i]
                        if p.annotation is not None and not param_is_static(p):
                            yield mod.finding(
                                self.id, site,
                                f"static_argnums marks `{p.arg}: "
                                f"{ast.unparse(p.annotation)}` static: "
                                "arrays are unhashable (TypeError) or "
                                "retrace per value; pass it traced or "
                                "donate it",
                            )
            if "static_argnames" in kwargs and wrapped is not None:
                names = {
                    n.value
                    for n in ast.walk(kwargs["static_argnames"])
                    if isinstance(n, ast.Constant) and isinstance(n.value, str)
                }
                for p in func_params(wrapped):
                    if p.arg in names and p.annotation is not None and not (
                        param_is_static(p)
                    ):
                        yield mod.finding(
                            self.id, site,
                            f"static_argnames marks array-annotated "
                            f"`{p.arg}` static (recompile per value)",
                        )
            # (b) closure capture of traced values from outside the boundary
            if wrapped is None:
                continue
            outer_chain = mod.function_chain(wrapped)[1:]  # strictly outside
            if not outer_chain:
                continue
            inside = {wrapped, *(
                f for f in mod.functions
                if wrapped in mod.function_chain(f)
            )}
            inside_locals: Set[str] = set()
            for f in inside:
                inside_locals |= mod.local_names(f)
            reported: Set[str] = set()
            for node in ast.walk(wrapped):
                if not (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                ):
                    continue
                name = node.id
                if (
                    name in reported
                    or name in inside_locals
                    or name in mod_globals
                    or hasattr(builtins, name)
                ):
                    continue
                for outer in outer_chain:
                    if name not in mod.local_names(outer):
                        continue
                    params = {a.arg: a for a in func_params(outer)}
                    traced = False
                    if name in params:
                        traced = not param_is_static(params[name])
                    else:
                        roots = mod.traced_roots(outer)
                        for stmt in ast.walk(outer):
                            if isinstance(stmt, ast.Assign) and any(
                                isinstance(t, ast.Name) and t.id == name
                                for t in stmt.targets
                            ):
                                if mod.expr_is_traced(stmt.value, roots):
                                    traced = True
                    if traced:
                        reported.add(name)
                        yield mod.finding(
                            self.id, node,
                            f"jit entry `{wrapped.name}` closes over traced "
                            f"`{name}` from `{outer.name}`: the array is "
                            "baked into the trace as a constant (re-traced "
                            "and re-embedded per call); pass it as an "
                            "argument",
                        )
                    break


class DtypePromotionRule(Rule):
    """R4: dtype discipline — 64-bit dtypes in device paths (silent f32
    truncation with x64 off, 2x memory + carry mismatch with it on) and
    `jax_enable_x64` flips anywhere.  Host-side `np.float64` (scave
    exporters, Bianchi tables in net/topology.py) stays legal."""

    id = "R4"
    title = "64-bit dtype in device path"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn, node in mod.device_nodes():
            if isinstance(node, ast.Attribute):
                name = dotted(node)
                if name in _64BIT_DTYPES:
                    parent = mod.parents.get(node)
                    if isinstance(parent, ast.Attribute):
                        continue  # report the outermost chain only
                    yield mod.finding(
                        self.id, node,
                        f"`{name}` in device code: with x64 disabled this "
                        "silently becomes 32-bit; with it enabled it "
                        "doubles memory and breaks carry contracts — use "
                        "an explicit 32-bit dtype",
                    )
            elif isinstance(node, ast.Call):
                cname = dotted(node.func) or ""
                if not cname.startswith(("jnp.", "jax.")):
                    continue
                for kw in node.keywords:
                    if (
                        kw.arg == "dtype"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value in _64BIT_STRINGS
                    ):
                        yield mod.finding(
                            self.id, node,
                            f'dtype="{kw.value.value}" in device code '
                            "(see R4: 64-bit dtypes are banned on the "
                            "device path)",
                        )
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Call)
                and dotted(node.func) == "jax.config.update"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "jax_enable_x64"
            ):
                yield mod.finding(
                    self.id, node,
                    "`jax_enable_x64` flip: the engine's carries and "
                    "parity gates are f32/int8-disciplined; enabling x64 "
                    "process-wide changes every weak-typed promotion",
                )


class NondeterminismRule(Rule):
    """R5: host RNG in device paths — `random`/`np.random` draws are
    invisible to the jax PRNG key threading, so same-seed determinism
    (and the DES parity gates) silently break; the engine is
    `jax.random`-only."""

    id = "R5"
    title = "host RNG in device path"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        imports_random = any(
            isinstance(node, ast.Import)
            and any(a.name == "random" for a in node.names)
            for node in ast.walk(mod.tree)
        )
        for fn, node in mod.device_nodes():
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            if name.startswith(("np.random.", "numpy.random.")):
                yield mod.finding(
                    self.id, node,
                    f"`{name}` in device code: numpy RNG state is host-"
                    "global and unkeyed — use `jax.random` with a "
                    "threaded key (same-seed determinism gate)",
                )
            elif name.startswith("random.") and imports_random:
                yield mod.finding(
                    self.id, node,
                    f"stdlib `{name}` in device code: wall-clock-seeded "
                    "host RNG (the reference's rand() bug class); use "
                    "`jax.random`",
                )


class DonationRule(Rule):
    """R6: jit entry points taking the WorldState carry must donate it —
    the carry dominates the bytes/tick footprint, and without
    `donate_argnums` XLA keeps input and output copies live."""

    id = "R6"
    title = "missing donate_argnums on large-carry jit entry"

    # unannotated params with these names count as carries too, so
    # dropping the WorldState annotation cannot evade the rule
    CARRY_NAMES = {"state", "batch", "carry", "world"}

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for site, wrapped, kwargs in _jit_sites(mod):
            if wrapped is None:
                continue
            if "donate_argnums" in kwargs or "donate_argnames" in kwargs:
                continue
            carry = [
                p.arg
                for p in func_params(wrapped)
                if (
                    p.annotation is not None
                    and "WorldState" in ast.unparse(p.annotation)
                )
                or (p.annotation is None and p.arg in self.CARRY_NAMES)
            ]
            if carry:
                yield mod.finding(
                    self.id, site,
                    f"jit entry `{wrapped.name}` takes WorldState carry "
                    f"`{carry[0]}` without donate_argnums: input + output "
                    "copies of the dominant state footprint stay live; "
                    "donate the carry (or suppress with a reason if "
                    "callers must reuse the input)",
                )


class ConstantChurnRule(Rule):
    """R7: the same scalar constant (`jnp.int8(int(Stage.X))`-style)
    constructed repeatedly inside device functions of one module — each
    occurrence re-enters tracing and op-by-op dispatch; hoist one
    module-level constant."""

    id = "R7"
    title = "repeated per-call scalar constant construction"
    threshold = 3

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        seen: Dict[str, List[ast.Call]] = {}
        for fn, node in mod.device_nodes():
            if not isinstance(node, ast.Call):
                continue
            if dotted(node.func) in _SMALL_DTYPES and node.args and all(
                is_const_expr(a) for a in node.args
            ):
                seen.setdefault(ast.unparse(node), []).append(node)
        for text, nodes in seen.items():
            if len(nodes) >= self.threshold:
                first = min(nodes, key=lambda n: n.lineno)
                yield mod.finding(
                    self.id, first,
                    f"`{text}` constructed {len(nodes)}x in this module's "
                    "device functions; hoist it to one module-level "
                    "constant (numpy scalars keep the dtype with zero "
                    "per-trace churn)",
                )


class ContractCoverageRule(Rule):
    """R8: every engine phase (`_phase_*`) must be registered in the
    trace-time contract registry (PHASE_CONTRACTS /
    core/contracts.py) so tier-1 eval_shape checks catch carry
    promotion before it recompiles on TPU."""

    id = "R8"
    title = "engine phase missing a trace-time contract"

    def check_project(
        self, mods: Sequence[ModuleInfo]
    ) -> Iterable[Finding]:
        phases: List[Tuple[ModuleInfo, ast.FunctionDef]] = []
        covered: Set[str] = set()
        for mod in mods:
            for f in mod.functions:
                if f.name.startswith("_phase_"):
                    phases.append((mod, f))
            for node in ast.walk(mod.tree):
                is_registry_assign = (
                    isinstance(node, (ast.Assign, ast.AnnAssign))
                    and any(
                        isinstance(t, ast.Name)
                        and t.id == "PHASE_CONTRACTS"
                        for t in (
                            node.targets
                            if isinstance(node, ast.Assign)
                            else [node.target]
                        )
                    )
                )
                if is_registry_assign and node.value is not None:
                    for c in ast.walk(node.value):
                        if isinstance(c, ast.Constant) and isinstance(
                            c.value, str
                        ):
                            covered.add(c.value)
                if (
                    isinstance(node, ast.Call)
                    and (dotted(node.func) or "").endswith("PhaseContract")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    covered.add(node.args[0].value)
        for mod, f in phases:
            if f.name not in covered:
                yield mod.finding(
                    self.id, f,
                    f"engine phase `{f.name}` has no entry in "
                    "PHASE_CONTRACTS (core/contracts.py): its carry "
                    "shape/dtype contract is unchecked in tier-1 — "
                    "register it (and let tests/test_contracts.py trace "
                    "it)",
                )


# ----------------------------------------------------------------------
# v2 rules (ISSUE 7): sharding axes, f32 integer sums, callbacks, donation
# ----------------------------------------------------------------------

_COLLECTIVE_CALLS = {
    "jax.lax.all_gather", "lax.all_gather", "jax.lax.psum", "lax.psum",
    "jax.lax.pmean", "lax.pmean", "jax.lax.pmax", "lax.pmax",
    "jax.lax.pmin", "lax.pmin", "jax.lax.all_to_all", "lax.all_to_all",
    "jax.lax.ppermute", "lax.ppermute", "jax.lax.axis_index",
    "lax.axis_index", "jax.lax.psum_scatter", "lax.psum_scatter",
}


def _module_str_consts(mod: ModuleInfo) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` bindings (axis-name constants)."""
    out: Dict[str, str] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Constant
        ) and isinstance(node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = node.value.value
    return out


def _axis_token(node: ast.AST, consts: Dict[str, str]) -> Optional[str]:
    """Resolve an axis-name argument to a string when statically known."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


class ShardAxisRule(Rule):
    """R9: `shard_map`/`PartitionSpec` axis names must be bound by the
    enclosing mesh, and collectives must name a live axis.  Conservative:
    only fires when both the axis name AND the mesh's axis tuple are
    statically resolvable (literals or module string constants) — a mesh
    that arrives as a parameter is unverifiable and stays silent."""

    id = "R9"
    title = "unbound mesh axis in shard_map/PartitionSpec/collective"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        consts = _module_str_consts(mod)
        # every Mesh(...) constructed with a literal axis tuple, module-wide
        mesh_axes: Dict[str, Set[str]] = {}  # bound name -> axes
        all_mesh_axes: Set[str] = set()
        saw_mesh = False
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and (dotted(node.func) or "").endswith("Mesh")):
                continue
            axes: Set[str] = set()
            args = list(node.args) + [
                kw.value for kw in node.keywords
                if kw.arg in ("axis_names", None)
            ]
            for a in args[1:] if node.args else args:
                for sub in ast.walk(a):
                    tok = _axis_token(sub, consts)
                    if tok:
                        axes.add(tok)
            if not axes:
                continue
            saw_mesh = True
            all_mesh_axes |= axes
            parent = mod.parents.get(node)
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    if isinstance(t, ast.Name):
                        mesh_axes[t.id] = axes
        if not saw_mesh:
            return  # no statically-known mesh in this module: unverifiable

        def universe_for(call: ast.Call) -> Set[str]:
            for kw in call.keywords:
                if kw.arg == "mesh" and isinstance(kw.value, ast.Name):
                    if kw.value.id in mesh_axes:
                        return mesh_axes[kw.value.id]
            return all_mesh_axes

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            if name.endswith("shard_map") or (
                name in ("functools.partial", "partial")
                and node.args
                and (dotted(node.args[0]) or "").endswith("shard_map")
            ):
                axes = universe_for(node)
                for kw in node.keywords:
                    if kw.arg not in ("in_specs", "out_specs"):
                        continue
                    for sub in ast.walk(kw.value):
                        if isinstance(sub, ast.Call) and (
                            dotted(sub.func) or ""
                        ).split(".")[-1] in ("P", "PartitionSpec"):
                            for a in sub.args:
                                tok = _axis_token(a, consts)
                                if tok is not None and tok not in axes:
                                    yield mod.finding(
                                        self.id, sub,
                                        f"PartitionSpec names axis "
                                        f"{tok!r} but the enclosing mesh "
                                        f"binds {sorted(axes)} — the "
                                        "spec silently replicates (or "
                                        "errors) instead of sharding",
                                    )
            elif name in _COLLECTIVE_CALLS:
                # axis_name's positional slot: args[0] for axis_index
                # (its ONLY argument), args[1] for x-first collectives
                pos = (
                    node.args[0:1] if name.endswith("axis_index")
                    else node.args[1:2]
                )
                cand = [
                    kw.value for kw in node.keywords
                    if kw.arg == "axis_name"
                ] + pos
                for a in cand:
                    tok = _axis_token(a, consts)
                    if tok is not None and tok not in all_mesh_axes:
                        yield mod.finding(
                            self.id, node,
                            f"collective `{name}` names axis {tok!r}, "
                            f"not bound by any mesh in scope "
                            f"({sorted(all_mesh_axes)}): unbound-axis "
                            "NameError at trace time, or a collective "
                            "over the wrong axis after a rename",
                        )


_F32_TOKENS = {"jnp.float32", "np.float32", "numpy.float32"}


def _f32_aliases(mod: ModuleInfo, fn: ast.FunctionDef) -> Set[str]:
    """Names bound to ``jnp.float32`` in ``fn`` or at module level (the
    ``f32 = jnp.float32`` convention)."""
    out: Set[str] = set()
    scopes: List[ast.AST] = [mod.tree, *mod.function_chain(fn)]
    for scope in scopes:
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign) and dotted(
                node.value
            ) in _F32_TOKENS:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
    return out


def _has_float_arith(node: ast.AST) -> bool:
    """Whether an expression visibly involves a non-integral float
    constant or a true division — i.e. its value is fractional, not an
    integer-valued count, whatever dtype the accumulator is pinned to."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(
            sub.value, float
        ) and not float(sub.value).is_integer():
            return True
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Div):
            return True
    return False


class IntF32SumRule(Rule):
    """R10: integer-valued f32 accumulations need an adjacent static
    2^24 overflow guard.  ``jnp.sum(mask, dtype=f32)`` and
    ``jnp.sum(cond.astype(f32))`` produce *integer-valued floats*; they
    are exact (and backend/reduction-order independent) only below
    2^24.  The sanctioned pattern is the engine's ``_fused_mips_exact``:
    a trace-time bound comparison against ``2 ** 24`` in the same module
    (the rule recognizes the literal bound or a call to a
    ``*exact*``/``*fused_ok*``-named guard)."""

    id = "R10"
    title = "unguarded integer-valued f32 accumulation"

    def _module_has_guard(self, mod: ModuleInfo) -> bool:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.BinOp) and isinstance(
                node.op, ast.Pow
            ):
                if (
                    isinstance(node.left, ast.Constant)
                    and node.left.value == 2
                    and isinstance(node.right, ast.Constant)
                    and node.right.value == 24
                ):
                    return True
            if isinstance(node, ast.Constant) and node.value == 16777216:
                return True
            if isinstance(node, ast.Call):
                name = (dotted(node.func) or "").split(".")[-1]
                if "exact" in name or "fused_ok" in name:
                    return True
        return False

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        if self._module_has_guard(mod):
            return
        for fn, node in mod.device_nodes():
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            if name.split(".")[-1] not in ("sum", "cumsum"):
                continue
            if not (name.startswith(("jnp.", "jax.numpy."))
                    or isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("sum", "cumsum")
                    and mod.expr_is_traced(
                        node.func.value, mod.traced_env(fn))):
                continue
            f32 = _f32_aliases(mod, fn) | {"float32"}
            args = node.args or (
                [node.func.value]
                if isinstance(node.func, ast.Attribute) else []
            )
            integer_f32 = False
            for kw in node.keywords:
                if kw.arg != "dtype":
                    continue
                tok = dotted(kw.value) or (
                    kw.value.value
                    if isinstance(kw.value, ast.Constant) else None
                )
                if (tok in _F32_TOKENS or tok in f32) and not (
                    args and _has_float_arith(args[0])
                ):
                    # forcing dtype=f32 on a sum is the count-sum idiom:
                    # the input is bool/int, the output integer-valued —
                    # unless the summand visibly does FLOAT arithmetic
                    # (`w * 0.5`), where dtype=f32 just pins the
                    # accumulator of genuinely fractional data
                    integer_f32 = True
            if not integer_f32 and args:
                a0 = args[0]
                if (
                    isinstance(a0, ast.Call)
                    and isinstance(a0.func, ast.Attribute)
                    and a0.func.attr == "astype"
                    and a0.args
                    and (
                        dotted(a0.args[0]) in _F32_TOKENS
                        or (isinstance(a0.args[0], ast.Name)
                            and a0.args[0].id in f32)
                    )
                    and isinstance(
                        a0.func.value, (ast.Compare, ast.BoolOp)
                    )
                ):
                    integer_f32 = True
            if integer_f32:
                yield mod.finding(
                    self.id, node,
                    "integer-valued f32 sum with no static overflow "
                    "guard in this module: exact (and reduction-order-"
                    "independent) only below 2^24 — add a trace-time "
                    "bound check (the `_fused_mips_exact` pattern) or "
                    "accumulate in int32",
                )


_CALLBACK_CALLS = {
    "jax.experimental.io_callback", "io_callback",
    "jax.pure_callback", "pure_callback",
    "jax.debug.print", "jax.debug.callback", "jax.debug.breakpoint",
    "jax.experimental.host_callback.call", "host_callback.call",
    "hcb.call",
}


class ScanCallbackRule(Rule):
    """R11: host callbacks inside device code must either declare
    ordering (``ordered=True``) or sit behind a telemetry/debug gate.
    An unordered callback in a scan body may be reordered, batched or
    elided by XLA — fine for idempotent telemetry taps, silently wrong
    for anything stateful — and every callback is a host round-trip the
    compiled-artifact audit (tools/hloaudit A1) will flag in the
    audited variants."""

    id = "R11"
    title = "undeclared host callback in device code"

    def _gated(self, mod: ModuleInfo, node: ast.AST) -> bool:
        cur = mod.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.If):
                test = ast.unparse(cur.test)
                if "telemetry" in test or "debug" in test.lower():
                    return True
            cur = mod.parents.get(cur)
        return False

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn, node in mod.device_nodes():
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            if name not in _CALLBACK_CALLS:
                continue
            ordered = any(
                kw.arg == "ordered"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords
            )
            if ordered or self._gated(mod, node):
                continue
            yield mod.finding(
                self.id, node,
                f"`{name}` in device code declares no ordering and is "
                "not telemetry/debug-gated: XLA may reorder, batch or "
                "elide it inside the scan — pass ordered=True (ordering "
                "matters) or gate it behind the telemetry/debug flag "
                "(it is a tap)",
            )


#: Package entry points that donate their state/batch argument (position
#: of the donated parameter).  `run_chunked` only donates on the
#: callback-free path, but its contract says "do not reuse after
#: calling" either way, so the rule covers it unconditionally.
_KNOWN_DONATING: Dict[str, int] = {
    "run_jit": 1,
    "run_chunked": 1,
    "run_fleet": 1,
    "run_fleet_series": 1,
}


class DonatedReuseRule(Rule):
    """R12: a buffer passed to a donating call is DEAD afterwards —
    XLA aliases it into the outputs, and reading it again returns
    garbage or raises.  This is the escape class
    ``engine._dealias_for_donation`` exists for (aliased *inputs*); the
    rule catches the caller-side variant: reusing the donated name
    after the call instead of rebinding it."""

    id = "R12"
    title = "use of a donated buffer after its donating call"

    def _donating_map(self, mod: ModuleInfo) -> Dict[str, Tuple[int, ...]]:
        out = {k: (v,) for k, v in _KNOWN_DONATING.items()}
        for site, wrapped, kwargs in _jit_sites(mod):
            if wrapped is None:
                continue
            idxs = ()
            if "donate_argnums" in kwargs:
                idxs = const_int_tuple(kwargs["donate_argnums"]) or ()
            if idxs:
                out[wrapped.name] = idxs
        return out

    @staticmethod
    def _stmt_path(mod: ModuleInfo, node: ast.AST, fn: ast.FunctionDef):
        """((block id, index), ...) statement coordinates of ``node``
        inside ``fn`` — used for happens-after ordering that does not
        confuse sibling branches with sequential statements."""
        path = []
        cur = node
        while cur is not None and cur is not fn:
            parent = mod.parents.get(cur)
            if parent is None:
                break
            for field in ("body", "orelse", "finalbody", "handlers"):
                block = getattr(parent, field, None)
                if isinstance(block, list) and cur in block:
                    path.append((id(block), block.index(cur)))
            cur = parent
        return tuple(reversed(path))

    @staticmethod
    def _happens_after(path_a, path_b) -> bool:
        """True when statement coordinates ``path_a`` execute strictly
        after ``path_b`` (same block, later index, at some shared
        level)."""
        for (blk_a, i_a), (blk_b, i_b) in zip(path_a, path_b):
            if blk_a != blk_b:
                return False  # sibling branches: no ordering
            if i_a != i_b:
                return i_a > i_b
        return False

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        donating = self._donating_map(mod)
        for fn in mod.functions:
            own = [
                n for n in ast.walk(fn)
                if mod.enclosing_function(n) is fn
            ]
            calls = []
            for node in own:
                if not isinstance(node, ast.Call):
                    continue
                name = (dotted(node.func) or "").split(".")[-1]
                if name not in donating:
                    continue
                for idx in donating[name]:
                    if idx >= len(node.args):
                        continue
                    arg = node.args[idx]
                    # unwrap the _dealias_for_donation(state) wrapper:
                    # dealiasing copies duplicate leaves only; the name's
                    # buffers are still donated
                    if isinstance(arg, ast.Call) and len(arg.args) == 1:
                        arg = arg.args[0]
                    if isinstance(arg, ast.Name):
                        calls.append((node, arg.id))
            for call, donated in calls:
                call_path = self._stmt_path(mod, call, fn)
                # an Assign that rebinds the name at the call statement
                # (`state = go(state)`) makes later uses the NEW value
                stmt = call
                while mod.parents.get(stmt) is not None and not isinstance(
                    stmt, ast.stmt
                ):
                    stmt = mod.parents[stmt]
                if isinstance(stmt, ast.Assign) and any(
                    isinstance(n, ast.Name) and n.id == donated
                    for t in stmt.targets
                    for n in ast.walk(t)  # tuple targets: `b, s = f(b)`
                ):
                    continue
                rebinds = []
                uses = []
                for node in own:
                    if not isinstance(node, ast.Name) or node.id != donated:
                        continue
                    p = self._stmt_path(mod, node, fn)
                    if not self._happens_after(p, call_path):
                        continue
                    if isinstance(node.ctx, (ast.Store, ast.Del)):
                        rebinds.append(p)
                    else:
                        uses.append((node, p))
                for node, p in uses:
                    # a use at the SAME coordinates as a rebind is the
                    # rebind's own RHS: it executes BEFORE the store,
                    # so only a strictly-earlier rebind covers it
                    if any(self._happens_after(p, r) for r in rebinds):
                        continue
                    fname = (dotted(call.func) or "?").split(".")[-1]
                    yield mod.finding(
                        self.id, node,
                        f"`{donated}` is read after `{fname}(...)` "
                        "donated its buffers: donated inputs are dead "
                        "(aliased into the outputs) — rebind the result "
                        "to the same name, copy before donating, or "
                        "call a non-donating entry",
                    )
                    break  # one finding per donated name per call


#: WorldSpec fields promoted to DynSpec operands (ISSUE 13).  A literal
#: copy of ``fognetsimpp_tpu.dynspec.DYN_FIELDS`` — simlint stays
#: AST-only (never imports the package it lints); tests/test_dynspec.py
#: pins the two lists equal so they cannot drift.
DYN_PROMOTED_FIELDS = frozenset({
    "uplink_loss_prob", "send_stop_time", "link_up_s", "link_drain_s",
    "link_drain2_s", "link_rate_bps", "chaos_mtbf_s", "chaos_mttr_s",
    "chaos_rtt_amp", "chaos_rtt_period_s", "chaos_rtt_burst_prob",
    "chaos_rtt_burst_mult", "chaos_max_retries", "learn_discount",
    "learn_reward_scale", "hier_threshold", "hier_max_hops",
    "hier_rtt_s", "hier_rtt_matrix",
    "idle_power_w", "tx_energy_j", "rx_energy_j",
    "compute_power_w", "harvest_power_w", "harvest_period_s",
    "harvest_duty", "shutdown_frac", "start_frac",
})


class DynOperandRule(Rule):
    """R13: a promoted spec knob read inside device code that bypasses
    the DynSpec operand.  ``spec.<knob>`` folded into a trace as a
    constant silently re-specializes the program on that knob's VALUE —
    the exact recompile wall ISSUE 13 removed, re-opened by closure
    re-capture.  Device code must read promoted knobs through the
    ``dv`` / ``dyn`` DynSpec view; Python-level GATE reads (``if
    spec.uplink_loss_prob > 0:``) stay legitimate trace structure and
    are exempt, as are asserts/raises."""

    id = "R13"
    title = "promoted spec knob bypasses the DynSpec operand"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn, node in mod.device_nodes():
            if not isinstance(node, ast.Attribute):
                continue
            if not (
                isinstance(node.value, ast.Name)
                and node.value.id in ("spec", "sp")
            ):
                continue
            if node.attr not in DYN_PROMOTED_FIELDS:
                continue
            if self._is_static_gate(mod, node):
                continue
            yield mod.finding(
                self.id, node,
                f"`spec.{node.attr}` is a promoted dynamic-operand knob "
                "(dynspec.DYN_FIELDS): folding it into the trace as a "
                "constant re-specializes the compiled program per value "
                "— read it through the DynSpec view (`dv."
                f"{node.attr}`) so warm re-configuration stays "
                "compile-free; Python gate reads belong in an `if` test",
            )

    @staticmethod
    def _is_static_gate(mod: ModuleInfo, node: ast.AST) -> bool:
        """True when the read is trace STRUCTURE, not trace data: the
        test of an ``if``/``while``/ternary, or an assert/raise."""
        cur = node
        parent = mod.parents.get(cur)
        while parent is not None:
            if isinstance(
                parent, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return False
            if isinstance(parent, (ast.Assert, ast.Raise)):
                return True
            if (
                isinstance(parent, (ast.If, ast.IfExp, ast.While))
                and cur is parent.test
            ):
                return True
            cur, parent = parent, mod.parents.get(parent)
        return False


#: Directory components whose modules are derived-stream territory for
#: R14 (a module anywhere can also opt in by defining a ``*_FOLD``
#: module constant — the named-lineage discipline's own marker).
_DERIVED_STREAM_DIRS = {"chaos", "hier", "telemetry"}

_FOLD_CONST_RE = re.compile(r"\A_?[A-Z][A-Z0-9_]*_FOLD\Z")

_SPLIT_LEAVES = {"split"}
_RANDOM_MODULES = {"jax.random", "jrandom", "jr", "random"}


class KeyLineageRule(Rule):
    """R14: PRNG key lineage in derived-stream modules — folded, never
    split.  The chaos/hier/telemetry streams derive every substream as
    ``fold_in(parent_key, <named constant or index>)``: a PURE function
    of the parent, so host replay (``outage_timeline``), per-replica
    re-keying and the journeys sampler all reconstruct identical draws
    without threading consumed keys.  ``jax.random.split`` breaks that
    contract — the Nth substream depends on every earlier consumer, so
    inserting one draw silently re-seeds everything after it.  A bare
    int literal in ``fold_in(key, 42)`` is the same bug one step
    earlier: two anonymous literals collide and the streams correlate;
    name the lane (``_X_FOLD = 0x...``) so collisions are greppable.
    Scope: modules under chaos/, hier/, telemetry/, or any module that
    defines a ``*_FOLD`` constant (the discipline's own marker); the
    engine's ROOT key split (one-time fan-out at world build) is out of
    scope by construction."""

    id = "R14"
    title = "derived-stream key split / anonymous fold literal"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        if not self._in_scope(mod):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            head, _, leaf = name.rpartition(".")
            if leaf in _SPLIT_LEAVES and head in _RANDOM_MODULES:
                yield mod.finding(
                    self.id, node,
                    f"`{name}(...)` in a derived-stream module: split "
                    "lineage makes substream N depend on every earlier "
                    "consumer, so one inserted draw re-seeds all later "
                    "ones; derive substreams as `fold_in(parent, "
                    "_LANE_FOLD)` / `fold_in(parent, index)` instead",
                )
            elif leaf == "fold_in" and len(node.args) >= 2:
                arg = node.args[1]
                if (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, int)
                    and not isinstance(arg.value, bool)
                ):
                    yield mod.finding(
                        self.id, node,
                        f"anonymous fold literal `fold_in(..., "
                        f"{arg.value})`: two magic numbers collide "
                        "silently and the streams correlate — name the "
                        "lane with a module-level `_X_FOLD` constant",
                    )

    @staticmethod
    def _in_scope(mod: ModuleInfo) -> bool:
        dirs = set(mod.relpath.split("/")[:-1])
        if dirs & _DERIVED_STREAM_DIRS:
            return True
        for stmt in mod.tree.body:
            targets: List[ast.expr] = []
            if isinstance(stmt, ast.Assign):
                targets = list(stmt.targets)
            elif isinstance(stmt, ast.AnnAssign):
                targets = [stmt.target]
            for t in targets:
                if isinstance(t, ast.Name) and _FOLD_CONST_RE.match(t.id):
                    return True
        return False


def default_rules() -> Tuple[Rule, ...]:
    return (
        HostSyncRule(),
        TracedBranchRule(),
        RecompileHazardRule(),
        DtypePromotionRule(),
        NondeterminismRule(),
        DonationRule(),
        ConstantChurnRule(),
        ContractCoverageRule(),
        ShardAxisRule(),
        IntF32SumRule(),
        ScanCallbackRule(),
        DonatedReuseRule(),
        DynOperandRule(),
        KeyLineageRule(),
    )
