"""The simlint rule catalogue (R1-R8).  See RULES.md for the narrative
version with offending/sanctioned snippets; docstrings here are the
machine-adjacent summary."""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import (
    Finding,
    ModuleInfo,
    Rule,
    const_int_tuple,
    dotted,
    func_params,
    is_const_expr,
    is_jit_decorator,
    jit_call_kwargs,
    param_is_static,
)

_NP_SYNC_CALLS = {
    "np.asarray", "np.array", "np.ascontiguousarray",
    "numpy.asarray", "numpy.array", "numpy.ascontiguousarray",
}

_64BIT_DTYPES = {
    "jnp.float64", "jnp.int64", "jnp.uint64", "jnp.complex128",
    "np.float64", "np.int64", "numpy.float64", "numpy.int64",
}
_64BIT_STRINGS = {"float64", "int64", "uint64", "complex128"}

_SMALL_DTYPES = {
    "jnp.int8", "jnp.int16", "jnp.int32", "jnp.uint8", "jnp.uint16",
    "jnp.uint32", "jnp.float32", "jnp.float16", "jnp.bfloat16",
}


class HostSyncRule(Rule):
    """R1: host-sync in device code — ``.item()``, ``float()/int()/
    bool()`` on traced values, ``np.asarray``/``np.array`` on device
    arrays.  Each forces a device->host transfer that serializes the
    step stream (and is simply invalid under `lax.scan` tracing)."""

    id = "R1"
    title = "host sync in device code"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn, node in mod.device_nodes():
            if not isinstance(node, ast.Call):
                continue
            roots = mod.traced_roots(fn)
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and not node.args
            ):
                yield mod.finding(
                    self.id, node,
                    "`.item()` forces a blocking device->host sync inside "
                    "device code; keep the value on device (0-d array) or "
                    "move the readback outside the jit/scan boundary",
                )
                continue
            name = dotted(node.func)
            if name in _NP_SYNC_CALLS and any(
                mod.expr_is_traced(a, roots) for a in node.args
            ):
                yield mod.finding(
                    self.id, node,
                    f"`{name}(...)` on a traced value materializes it on "
                    "host; use `jnp` ops (or hoist the conversion out of "
                    "the device path)",
                )
                continue
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int", "bool")
                and len(node.args) == 1
                and mod.expr_is_traced(node.args[0], roots)
            ):
                yield mod.finding(
                    self.id, node,
                    f"`{node.func.id}(...)` of a traced value is a hidden "
                    "host sync (concretization error under jit); use "
                    "`.astype(...)` / `jnp.*` casts instead",
                )


class TracedBranchRule(Rule):
    """R2: Python ``if``/``while`` branching on traced comparisons —
    a concretization error under jit, and a per-value recompile when it
    accidentally works via early concrete values."""

    id = "R2"
    title = "Python branch on traced value"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn, node in mod.device_nodes():
            if not isinstance(node, (ast.If, ast.While)):
                continue
            roots = mod.traced_roots(fn)
            if mod.expr_is_traced(node.test, roots):
                kind = "if" if isinstance(node, ast.If) else "while"
                yield mod.finding(
                    self.id, node,
                    f"Python `{kind}` on a traced comparison; use "
                    "`jnp.where` / `lax.cond` / `lax.while_loop` (static "
                    "spec fields are fine — annotate them)",
                )


def _jit_sites(
    mod: ModuleInfo,
) -> Iterable[Tuple[ast.AST, Optional[ast.FunctionDef], Dict[str, ast.AST]]]:
    """(site_node, wrapped_function_def_or_None, jit_kwargs)."""
    by_name = {f.name: f for f in mod.functions}
    for f in mod.functions:
        for dec in f.decorator_list:
            if is_jit_decorator(dec):
                yield dec, f, (jit_call_kwargs(dec) or {})
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or dotted(node.func) not in (
            "jax.jit", "jit",
        ):
            continue
        kwargs = {kw.arg: kw.value for kw in node.keywords if kw.arg}
        wrapped: Optional[ast.FunctionDef] = None
        if node.args:
            a0 = node.args[0]
            if isinstance(a0, ast.Name):
                wrapped = by_name.get(a0.id)
            elif isinstance(a0, ast.Call):  # jax.jit(jax.vmap(f))
                for inner in ast.walk(a0):
                    if isinstance(inner, ast.Name) and inner.id in by_name:
                        wrapped = by_name[inner.id]
                        break
        yield node, wrapped, kwargs


def _module_globals(mod: ModuleInfo) -> Set[str]:
    names: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                names.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else
                [node.target]
            )
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        names.add(n.id)
        elif isinstance(node, (ast.If, ast.Try)):
            for sub in ast.walk(node):
                if isinstance(sub, (ast.Import, ast.ImportFrom)):
                    for alias in sub.names:
                        names.add((alias.asname or alias.name).split(".")[0])
    return names


class RecompileHazardRule(Rule):
    """R3: recompile triggers at jit boundaries — (a) array-annotated
    params marked static (retrace per value, unhashable TypeError), and
    (b) traced values captured by closure into a jit entry point (baked
    in as constants; silently retraced/re-embedded per call)."""

    id = "R3"
    title = "recompile hazard at jit boundary"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        import builtins

        mod_globals = _module_globals(mod)
        for site, wrapped, kwargs in _jit_sites(mod):
            # (a) static argnums pointing at array-annotated params
            if wrapped is not None and "static_argnums" in kwargs:
                idxs = const_int_tuple(kwargs["static_argnums"]) or ()
                params = func_params(wrapped)
                for i in idxs:
                    if i < len(params):
                        p = params[i]
                        if p.annotation is not None and not param_is_static(p):
                            yield mod.finding(
                                self.id, site,
                                f"static_argnums marks `{p.arg}: "
                                f"{ast.unparse(p.annotation)}` static: "
                                "arrays are unhashable (TypeError) or "
                                "retrace per value; pass it traced or "
                                "donate it",
                            )
            if "static_argnames" in kwargs and wrapped is not None:
                names = {
                    n.value
                    for n in ast.walk(kwargs["static_argnames"])
                    if isinstance(n, ast.Constant) and isinstance(n.value, str)
                }
                for p in func_params(wrapped):
                    if p.arg in names and p.annotation is not None and not (
                        param_is_static(p)
                    ):
                        yield mod.finding(
                            self.id, site,
                            f"static_argnames marks array-annotated "
                            f"`{p.arg}` static (recompile per value)",
                        )
            # (b) closure capture of traced values from outside the boundary
            if wrapped is None:
                continue
            outer_chain = mod.function_chain(wrapped)[1:]  # strictly outside
            if not outer_chain:
                continue
            inside = {wrapped, *(
                f for f in mod.functions
                if wrapped in mod.function_chain(f)
            )}
            inside_locals: Set[str] = set()
            for f in inside:
                inside_locals |= mod.local_names(f)
            reported: Set[str] = set()
            for node in ast.walk(wrapped):
                if not (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                ):
                    continue
                name = node.id
                if (
                    name in reported
                    or name in inside_locals
                    or name in mod_globals
                    or hasattr(builtins, name)
                ):
                    continue
                for outer in outer_chain:
                    if name not in mod.local_names(outer):
                        continue
                    params = {a.arg: a for a in func_params(outer)}
                    traced = False
                    if name in params:
                        traced = not param_is_static(params[name])
                    else:
                        roots = mod.traced_roots(outer)
                        for stmt in ast.walk(outer):
                            if isinstance(stmt, ast.Assign) and any(
                                isinstance(t, ast.Name) and t.id == name
                                for t in stmt.targets
                            ):
                                if mod.expr_is_traced(stmt.value, roots):
                                    traced = True
                    if traced:
                        reported.add(name)
                        yield mod.finding(
                            self.id, node,
                            f"jit entry `{wrapped.name}` closes over traced "
                            f"`{name}` from `{outer.name}`: the array is "
                            "baked into the trace as a constant (re-traced "
                            "and re-embedded per call); pass it as an "
                            "argument",
                        )
                    break


class DtypePromotionRule(Rule):
    """R4: dtype discipline — 64-bit dtypes in device paths (silent f32
    truncation with x64 off, 2x memory + carry mismatch with it on) and
    `jax_enable_x64` flips anywhere.  Host-side `np.float64` (scave
    exporters, Bianchi tables in net/topology.py) stays legal."""

    id = "R4"
    title = "64-bit dtype in device path"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for fn, node in mod.device_nodes():
            if isinstance(node, ast.Attribute):
                name = dotted(node)
                if name in _64BIT_DTYPES:
                    parent = mod.parents.get(node)
                    if isinstance(parent, ast.Attribute):
                        continue  # report the outermost chain only
                    yield mod.finding(
                        self.id, node,
                        f"`{name}` in device code: with x64 disabled this "
                        "silently becomes 32-bit; with it enabled it "
                        "doubles memory and breaks carry contracts — use "
                        "an explicit 32-bit dtype",
                    )
            elif isinstance(node, ast.Call):
                cname = dotted(node.func) or ""
                if not cname.startswith(("jnp.", "jax.")):
                    continue
                for kw in node.keywords:
                    if (
                        kw.arg == "dtype"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value in _64BIT_STRINGS
                    ):
                        yield mod.finding(
                            self.id, node,
                            f'dtype="{kw.value.value}" in device code '
                            "(see R4: 64-bit dtypes are banned on the "
                            "device path)",
                        )
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Call)
                and dotted(node.func) == "jax.config.update"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "jax_enable_x64"
            ):
                yield mod.finding(
                    self.id, node,
                    "`jax_enable_x64` flip: the engine's carries and "
                    "parity gates are f32/int8-disciplined; enabling x64 "
                    "process-wide changes every weak-typed promotion",
                )


class NondeterminismRule(Rule):
    """R5: host RNG in device paths — `random`/`np.random` draws are
    invisible to the jax PRNG key threading, so same-seed determinism
    (and the DES parity gates) silently break; the engine is
    `jax.random`-only."""

    id = "R5"
    title = "host RNG in device path"

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        imports_random = any(
            isinstance(node, ast.Import)
            and any(a.name == "random" for a in node.names)
            for node in ast.walk(mod.tree)
        )
        for fn, node in mod.device_nodes():
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func) or ""
            if name.startswith(("np.random.", "numpy.random.")):
                yield mod.finding(
                    self.id, node,
                    f"`{name}` in device code: numpy RNG state is host-"
                    "global and unkeyed — use `jax.random` with a "
                    "threaded key (same-seed determinism gate)",
                )
            elif name.startswith("random.") and imports_random:
                yield mod.finding(
                    self.id, node,
                    f"stdlib `{name}` in device code: wall-clock-seeded "
                    "host RNG (the reference's rand() bug class); use "
                    "`jax.random`",
                )


class DonationRule(Rule):
    """R6: jit entry points taking the WorldState carry must donate it —
    the carry dominates the bytes/tick footprint, and without
    `donate_argnums` XLA keeps input and output copies live."""

    id = "R6"
    title = "missing donate_argnums on large-carry jit entry"

    # unannotated params with these names count as carries too, so
    # dropping the WorldState annotation cannot evade the rule
    CARRY_NAMES = {"state", "batch", "carry", "world"}

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        for site, wrapped, kwargs in _jit_sites(mod):
            if wrapped is None:
                continue
            if "donate_argnums" in kwargs or "donate_argnames" in kwargs:
                continue
            carry = [
                p.arg
                for p in func_params(wrapped)
                if (
                    p.annotation is not None
                    and "WorldState" in ast.unparse(p.annotation)
                )
                or (p.annotation is None and p.arg in self.CARRY_NAMES)
            ]
            if carry:
                yield mod.finding(
                    self.id, site,
                    f"jit entry `{wrapped.name}` takes WorldState carry "
                    f"`{carry[0]}` without donate_argnums: input + output "
                    "copies of the dominant state footprint stay live; "
                    "donate the carry (or suppress with a reason if "
                    "callers must reuse the input)",
                )


class ConstantChurnRule(Rule):
    """R7: the same scalar constant (`jnp.int8(int(Stage.X))`-style)
    constructed repeatedly inside device functions of one module — each
    occurrence re-enters tracing and op-by-op dispatch; hoist one
    module-level constant."""

    id = "R7"
    title = "repeated per-call scalar constant construction"
    threshold = 3

    def check_module(self, mod: ModuleInfo) -> Iterable[Finding]:
        seen: Dict[str, List[ast.Call]] = {}
        for fn, node in mod.device_nodes():
            if not isinstance(node, ast.Call):
                continue
            if dotted(node.func) in _SMALL_DTYPES and node.args and all(
                is_const_expr(a) for a in node.args
            ):
                seen.setdefault(ast.unparse(node), []).append(node)
        for text, nodes in seen.items():
            if len(nodes) >= self.threshold:
                first = min(nodes, key=lambda n: n.lineno)
                yield mod.finding(
                    self.id, first,
                    f"`{text}` constructed {len(nodes)}x in this module's "
                    "device functions; hoist it to one module-level "
                    "constant (numpy scalars keep the dtype with zero "
                    "per-trace churn)",
                )


class ContractCoverageRule(Rule):
    """R8: every engine phase (`_phase_*`) must be registered in the
    trace-time contract registry (PHASE_CONTRACTS /
    core/contracts.py) so tier-1 eval_shape checks catch carry
    promotion before it recompiles on TPU."""

    id = "R8"
    title = "engine phase missing a trace-time contract"

    def check_project(
        self, mods: Sequence[ModuleInfo]
    ) -> Iterable[Finding]:
        phases: List[Tuple[ModuleInfo, ast.FunctionDef]] = []
        covered: Set[str] = set()
        for mod in mods:
            for f in mod.functions:
                if f.name.startswith("_phase_"):
                    phases.append((mod, f))
            for node in ast.walk(mod.tree):
                is_registry_assign = (
                    isinstance(node, (ast.Assign, ast.AnnAssign))
                    and any(
                        isinstance(t, ast.Name)
                        and t.id == "PHASE_CONTRACTS"
                        for t in (
                            node.targets
                            if isinstance(node, ast.Assign)
                            else [node.target]
                        )
                    )
                )
                if is_registry_assign and node.value is not None:
                    for c in ast.walk(node.value):
                        if isinstance(c, ast.Constant) and isinstance(
                            c.value, str
                        ):
                            covered.add(c.value)
                if (
                    isinstance(node, ast.Call)
                    and (dotted(node.func) or "").endswith("PhaseContract")
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    covered.add(node.args[0].value)
        for mod, f in phases:
            if f.name not in covered:
                yield mod.finding(
                    self.id, f,
                    f"engine phase `{f.name}` has no entry in "
                    "PHASE_CONTRACTS (core/contracts.py): its carry "
                    "shape/dtype contract is unchecked in tier-1 — "
                    "register it (and let tests/test_contracts.py trace "
                    "it)",
                )


def default_rules() -> Tuple[Rule, ...]:
    return (
        HostSyncRule(),
        TracedBranchRule(),
        RecompileHazardRule(),
        DtypePromotionRule(),
        NondeterminismRule(),
        DonationRule(),
        ConstantChurnRule(),
        ContractCoverageRule(),
    )
