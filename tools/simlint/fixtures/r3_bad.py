"""R3 fixture: recompile hazards — array-valued static args and array
closure capture at a jit boundary."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnums=1)
def scale(x, factor: jax.Array):        # R3a: array marked static
    return x * factor


def make_runner(table: jax.Array):
    @jax.jit
    def inner(x):
        return x + table                # R3b: traced closure capture
    return inner


def sweep(batch, weights: jax.Array):
    lut = jnp.cumsum(weights)

    @jax.jit
    def apply(x):
        return x * lut                  # R3b: derived-array capture
    return apply(batch)
