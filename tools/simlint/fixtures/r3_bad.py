"""R3 fixture: recompile hazards — array-valued static args and array
closure capture at a jit boundary."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnums=1)
def scale(x, factor: jax.Array):        # R3a: array marked static
    return x * factor


def make_runner(table: jax.Array):
    @jax.jit
    def inner(x):
        return x + table                # R3b: traced closure capture
    return inner


def sweep(batch, weights: jax.Array):
    lut = jnp.cumsum(weights)

    @jax.jit
    def apply(x):
        return x * lut                  # R3b: derived-array capture
    return apply(batch)


def make_accumulator(net: jax.Array, bounds: jax.Array):
    # the telemetry metrics-accumulation shape: a per-tick helper that
    # closes over the world's net/bounds arrays instead of taking them
    # as arguments — baked into the jaxpr, retraced per world
    @jax.jit
    def accumulate(telem, q_len):
        return telem + q_len * net[0] + bounds[0]   # R3b: net capture
    return accumulate
