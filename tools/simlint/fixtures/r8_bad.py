"""R8 fixture: an engine phase with no trace-time contract entry."""
import jax.numpy as jnp

PHASE_CONTRACTS = ()  # the registry forgot this phase


def _phase_orphan(spec, state, net, cache, buf, t0, t1):   # R8
    return state, buf


def helper(x):
    return jnp.asarray(x)
