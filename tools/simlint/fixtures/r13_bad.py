"""R13 fixture: promoted spec knobs folded into the trace as constants,
bypassing the DynSpec operand (the closure-re-capture rot ISSUE 13's
simlint rule guards against)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def degrade_links(spec, d2b, t0):
    # R13: value read of a promoted knob flows into the trace as a
    # constant — re-specializes the program per amplitude
    fac = 1.0 + np.float32(spec.chaos_rtt_amp) * jnp.sin(t0)
    # R13: same rot through an intermediate assignment
    scale = spec.learn_reward_scale
    return d2b * fac * scale


def sharded_tick(spec, mesh, parts):
    from jax import shard_map

    def body(rows):
        # R13: the same rot inside a shard_map body — the sharded
        # runners' promoted knobs must ride the replicated operand
        return rows * spec.uplink_loss_prob

    return shard_map(body, mesh=mesh)(parts)
