"""R11 fixture: host callbacks inside device code that neither declare
ordering nor sit behind a telemetry/debug gate — XLA may reorder, batch
or elide them inside the scan."""
import functools

import jax
from jax.experimental import io_callback


def _tap(x):
    return None


@functools.partial(jax.jit, donate_argnums=0)
def step(carry):
    io_callback(_tap, None, carry)        # R11: ordering undeclared
    jax.debug.print("q={q}", q=carry)     # R11: ungated debug tap
    return carry + 1
