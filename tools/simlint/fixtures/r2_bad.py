"""R2 fixture: Python control flow branching on traced comparisons."""
import jax


@jax.jit
def clamp(x, lo):
    if x > lo:                  # R2: Python `if` on a traced compare
        return lo
    while x < lo:               # R2: Python `while` on a traced compare
        x = x + 1.0
    return x
