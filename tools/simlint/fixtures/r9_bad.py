"""R9 fixture: axis names unbound by the enclosing mesh — a
PartitionSpec over a misspelled axis silently replicates instead of
sharding, and a collective over an unbound axis is a trace-time error
(or, after a rename, a collective over the WRONG axis)."""
import jax
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

FOG_AXIS = "fog"

mesh = Mesh(np.asarray(jax.devices()), (FOG_AXIS,))


def sharded_apply(fn, x):
    f = shard_map(
        fn,
        mesh=mesh,
        in_specs=(P("fogs"),),          # R9: "fogs" is not bound ("fog" is)
        out_specs=P(FOG_AXIS),
    )
    return f(x)


def combine(x):
    return jax.lax.psum(x, axis_name="replica")   # R9: no mesh binds "replica"
