"""R10 fixture: the two sanctioned shapes — a trace-time 2^24 bound
check adjacent to the f32 count sum (the ``engine._fused_mips_exact``
pattern), or accumulation in int32."""
import jax
import jax.numpy as jnp


def _counts_exact(n: int) -> None:
    """Static guard: n rows of 0/1 summed in f32 stay exact below 2^24."""
    if n >= 2 ** 24:
        raise ValueError("f32 count sum loses integer exactness")


@jax.jit
def count_busy(mask):
    _counts_exact(mask.shape[0])
    return jnp.sum(mask, dtype=jnp.float32)       # guarded: exact by bound


@jax.jit
def count_over(x, lo: float):
    return jnp.sum((x > lo).astype(jnp.int32))    # integer accumulator
