"""R8 fixture: the phase is registered in the contract registry, so the
tier-1 eval_shape check covers it."""
import jax.numpy as jnp

PHASE_CONTRACTS = (
    ("_phase_orphan", "checked by tests/test_contracts.py"),
)


def _phase_orphan(spec, state, net, cache, buf, t0, t1):
    return state, buf


def helper(x):
    return jnp.asarray(x)
