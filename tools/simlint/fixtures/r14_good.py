"""R14 good fixture: every substream is a named fold of its parent —
pure lineage, host-replayable, no splits, no magic literals."""
import jax

_STREAM_FOLD = 0x5EED
_PHASE_FOLD = 0x0B17


def derive_streams(key, fog):
    base = jax.random.fold_in(key, _STREAM_FOLD)
    phase = jax.random.fold_in(base, _PHASE_FOLD)
    # per-entity lanes fold the INDEX — a name, not an anonymous literal
    per_fog = jax.random.fold_in(base, fog)
    return phase, per_fog
