"""R5 fixture: keyed jax.random draws — deterministic under one seed."""
import jax
import jax.numpy as jnp


@jax.jit
def jitter(x, key):
    k1, k2 = jax.random.split(key)
    x = x + jax.random.uniform(k1)
    return x * jax.random.uniform(k2), jnp.asarray(0)
