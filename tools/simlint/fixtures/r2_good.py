"""R2 fixture: masked selects / lax loops; static-spec branches are fine."""
import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def clamp(x, lo):
    x = jnp.where(x > lo, lo, x)                   # select, not branch
    return lax.while_loop(lambda v: jnp.all(v < lo), lambda v: v + 1.0, x)


def build(spec: int, x, threshold: float = 0.5):
    if spec > 2:              # static (annotated int): host branch is fine
        return clamp(x, jnp.float32(threshold))
    if x is None:             # `is None` optional-arg checks are host-side
        return None
    return clamp(x, jnp.float32(0.0))
