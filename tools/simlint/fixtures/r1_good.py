"""R1 fixture: the sanctioned alternatives — everything stays on device."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(arr):
    total = jnp.sum(arr)                # stays a 0-d device array
    n = jnp.mean(arr)
    flag = jnp.any(arr > 0)
    return arr + total + n + flag.astype(arr.dtype)


def summarize(final_state) -> float:
    # host conversion OUTSIDE the jit boundary is fine (and `float()` of
    # a plain Python constant never fires)
    scale = float("1e3")
    return float(np.asarray(final_state).mean()) * scale
