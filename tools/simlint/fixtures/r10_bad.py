"""R10 fixture: integer-valued f32 accumulations with no static
overflow guard anywhere in the module — exact (and reduction-order
independent) only below 2^24, and nothing pins that bound."""
import jax
import jax.numpy as jnp


@jax.jit
def count_busy(mask):
    # forcing dtype=f32 on a bool sum is the count-sum idiom: the
    # output is an integer-valued float
    return jnp.sum(mask, dtype=jnp.float32)       # R10: unguarded count


@jax.jit
def count_over(x, lo: float):
    return jnp.sum((x > lo).astype(jnp.float32))  # R10: bool->f32 sum
