"""R9 fixture: every axis name a spec or collective uses is bound by
the enclosing mesh — via the module axis constant, never a re-typed
string literal."""
import jax
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

FOG_AXIS = "fog"

mesh = Mesh(np.asarray(jax.devices()), (FOG_AXIS,))


def sharded_apply(fn, x):
    f = shard_map(
        fn,
        mesh=mesh,
        in_specs=(P(FOG_AXIS),),        # bound by the mesh above
        out_specs=P(FOG_AXIS),
    )
    return f(x)


def combine(x):
    return jax.lax.psum(x, axis_name=FOG_AXIS)    # bound axis constant
