"""R7 fixture: hoisted module-level constant (numpy scalar keeps the
dtype through every jnp op with zero per-trace churn)."""
import enum

import jax
import jax.numpy as jnp
import numpy as np


class Stage(enum.IntEnum):
    LOST = 10


_ST_LOST = np.int8(int(Stage.LOST))


@jax.jit
def mark(stage, lost):
    a = jnp.where(lost, _ST_LOST, stage)
    b = stage == _ST_LOST
    c = jnp.full((4,), _ST_LOST)
    return a, b, c
