"""R6 fixture: the carry is donated — XLA aliases input into output."""
import functools

import jax


class WorldState:
    pass


@functools.partial(jax.jit, static_argnums=0, donate_argnums=1)
def advance(spec, state: WorldState, net):
    return state
