"""R7 fixture: the same scalar constant rebuilt at every use site."""
import enum

import jax
import jax.numpy as jnp


class Stage(enum.IntEnum):
    LOST = 10


@jax.jit
def mark(stage, lost):
    a = jnp.where(lost, jnp.int8(int(Stage.LOST)), stage)   # R7 (x3)
    b = stage == jnp.int8(int(Stage.LOST))
    c = jnp.full((4,), jnp.int8(int(Stage.LOST)))
    return a, b, c
