"""R1 fixture: host syncs inside device code (every marked line fires)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(state):
    total = float(state.sum())          # R1: float() on a traced value
    n = jnp.mean(state).item()          # R1: .item() host sync
    host = np.asarray(state * 2.0)      # R1: np.asarray on a device array
    flag = bool(jnp.any(state > 0))     # R1: bool() concretization
    return state + total + n + host.shape[0] + flag
