"""R12 fixture: reading a donated buffer after its donating call —
the buffers were aliased into the call's outputs, so the name is dead
(garbage results, or a deleted-buffer error)."""
import functools

import jax


@functools.partial(jax.jit, donate_argnums=0)
def advance(state):
    return state + 1


def drive(state):
    out = advance(state)
    return out + state          # R12: `state` was donated to advance()


def compare(spec, state, net, bounds):
    final = run_jit(spec, state, net, bounds)   # noqa: F821 (fixture)
    return final, state.tasks   # R12: `state` donated to the run entry
