"""R11 fixture: the two sanctioned shapes — ``ordered=True`` when the
callback's sequencing matters, or a telemetry/debug gate when it is an
idempotent tap."""
import functools

import jax
from jax.experimental import io_callback


def _tap(x):
    return None


@functools.partial(jax.jit, donate_argnums=0)
def step(carry, spec):
    io_callback(_tap, None, carry, ordered=True)  # ordering declared
    if spec.debug:
        jax.debug.print("q={q}", q=carry)         # debug-gated tap
    return carry + 1
