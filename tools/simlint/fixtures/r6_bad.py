"""R6 fixture: a whole-run jit entry that keeps two copies of the carry."""
import functools

import jax


class WorldState:  # stand-in for the real carry pytree
    pass


@functools.partial(jax.jit, static_argnums=0)
def advance(spec, state: WorldState, net):   # R6: carry not donated
    return state


@jax.jit
def advance_unannotated(state, net):   # R6: dropping the annotation is
    return state                       # not an escape hatch
