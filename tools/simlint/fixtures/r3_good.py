"""R3 fixture: static args are hashable scalars; arrays ride as
arguments through the jit boundary."""
import functools

import jax


@functools.partial(jax.jit, static_argnums=1)
def scale(x, factor: int):              # hashable static: fine
    return x * factor


def make_runner(table: jax.Array):
    @jax.jit
    def inner(x, tab):
        return x + tab                  # array passed as an argument
    return functools.partial(inner, tab=table)
