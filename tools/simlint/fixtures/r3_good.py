"""R3 fixture: static args are hashable scalars; arrays ride as
arguments through the jit boundary."""
import functools

import jax


@functools.partial(jax.jit, static_argnums=1)
def scale(x, factor: int):              # hashable static: fine
    return x * factor


def make_runner(table: jax.Array):
    @jax.jit
    def inner(x, tab):
        return x + tab                  # array passed as an argument
    return functools.partial(inner, tab=table)


@jax.jit
def accumulate(telem, q_len, net: jax.Array, bounds: jax.Array):
    # the telemetry metrics-accumulation discipline: net/bounds ride
    # through the jit boundary as arguments, never by closure
    return telem + q_len * net[0] + bounds[0]
