"""R14 bad fixture: a derived-stream module (the ``_STREAM_FOLD``
constant opts it into scope) that splits its key and folds in an
anonymous literal — two findings."""
import jax

_STREAM_FOLD = 0x5EED


def derive_streams(key):
    # BAD: split lineage — substream order depends on consumer order
    burst_key, phase_key = jax.random.split(key)
    # BAD: anonymous fold literal — collides silently with any other 77
    aux = jax.random.fold_in(burst_key, 77)
    return phase_key, aux
