"""R13 negative fixture: gate reads in `if` tests are trace structure
(legitimate), and value reads go through the DynSpec view `dv`."""
import jax
import jax.numpy as jnp


@jax.jit
def degrade_links(spec, dv, d2b, t0):
    fac = jnp.ones_like(d2b)
    if spec.chaos_rtt_amp > 0:  # gate read: selects the trace, ok
        # value read through the operand view: compile-free reconfig
        fac = 1.0 + dv.chaos_rtt_amp * jnp.sin(t0)
    if spec.queue_capacity > 4:  # non-promoted field: out of scope
        fac = fac * 2.0
    return d2b * fac


def sharded_tick(spec, mesh, parts, dyn):
    from jax import shard_map

    def body(rows, dv):
        # value read through the replicated operand view: the sharded
        # runners' compile-free reconfig path (ISSUE 20)
        scale = dv.uplink_loss_prob
        if spec.uplink_loss_prob > 0:  # gate read: trace structure, ok
            rows = rows * scale
        return rows

    return shard_map(body, mesh=mesh)(parts, dyn)
