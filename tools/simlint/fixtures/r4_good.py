"""R4 fixture: 32-bit device dtypes; host-side np.float64 stays legal
(the scave exporter / Bianchi-table pattern)."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def accumulate(x):
    acc = jnp.zeros((4,), jnp.float32)
    big = jnp.arange(8, dtype="int32")
    return acc + x + big.sum()


def export_stats(values) -> float:
    # host-side double-precision accumulation for result files is exactly
    # what runtime/scave.py does — legal outside device code
    return float(np.asarray(values, np.float64).sum())
