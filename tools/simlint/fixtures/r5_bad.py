"""R5 fixture: host RNG inside device code."""
import random

import jax
import numpy as np


@jax.jit
def jitter(x):
    x = x + np.random.uniform()     # R5: numpy global RNG in device code
    return x * random.random()      # R5: stdlib wall-clock-seeded RNG
