"""R12 fixture: the sanctioned shapes — rebind the result to the
donated name (later reads see the NEW value), or copy before donating
when the original must survive."""
import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, donate_argnums=0)
def advance(state):
    return state + 1


def drive(state):
    state = advance(state)      # rebind at the donating call
    return state + 1


def drive_keep(state):
    scratch = jax.tree.map(jnp.copy, state)
    final = advance(scratch)    # the copy is donated, not the original
    return final, state
