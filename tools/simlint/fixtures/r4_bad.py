"""R4 fixture: 64-bit dtypes on the device path + process-wide x64 flip."""
import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)   # R4: global promotion flip


@jax.jit
def accumulate(x):
    acc = jnp.zeros((4,), jnp.float64)      # R4: 64-bit device dtype
    big = jnp.arange(8, dtype="int64")      # R4: 64-bit dtype string
    return acc + x + big.sum()
