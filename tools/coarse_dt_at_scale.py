"""Measure the dt-staleness envelope AT THE BENCH SHAPE (VERDICT r4 item 6).

tests/test_coarse_dt.py pins the envelope on a 16-user toy; this script
runs the 10k-user/32-fog bench world at dt=1 ms (exact ordering) and
dt=5 ms (headline staleness) with the SAME seed and reports the per-fog
assignment histogram L1 shift plus the dt-sensitive timing observables
(wait-to-service, completions, drops) — turning the headline's fidelity
claim into a measurement.  (Ack event times are exact at ANY dt by
construction; what staleness can move is WHICH fog a task goes to and
hence queue waits — measured here.  The 0.3 s horizon lets ~3 service
generations complete on the saturated fogs.)

Usage (TPU): python tools/coarse_dt_at_scale.py
Prints one JSON line; recorded in BENCHMARKS.md.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from fognetsimpp_tpu.compile_cache import enable_compile_cache
from fognetsimpp_tpu.core.engine import run
from fognetsimpp_tpu.scenarios import smoke


def build(dt, fog_mips=(1000, 2000, 3000, 4000), queue_capacity=128,
          horizon=0.3):
    n_users, interval = 10_000, 0.0025
    mspt = max(1, -(-int(round(dt * 1e6)) // int(round(interval * 1e6))))
    return smoke.build(
        n_users=n_users, n_fogs=32,
        fog_mips=tuple(float(m) for m in fog_mips),
        send_interval=interval, horizon=horizon, dt=dt,
        max_sends_per_user=int(horizon / interval) + 4,
        max_sends_per_tick=mspt,
        arrival_window=4096, queue_capacity=queue_capacity,
        start_time_max=min(0.05, horizon / 4),
    )


def stats(dt, **kw):
    spec, state, net, bounds = build(dt, **kw)

    @jax.jit
    def go(s):
        final, _ = run(spec, s, net, bounds)
        t = final.tasks
        lat = t.t_service_start - t.t_at_fog  # queue wait at the fog
        ok = jnp.isfinite(lat) & (t.t_service_start <= final.t)
        per_fog = jnp.sum(
            (t.fog[None, :] == jnp.arange(spec.n_fogs)[:, None])
            & (t.fog >= 0)[None, :],
            axis=1,
        )
        latv = jnp.where(ok, lat, 0.0)
        return (
            per_fog,
            jnp.sum(latv) / jnp.maximum(jnp.sum(ok), 1),
            jnp.sum(ok),
            final.metrics.n_scheduled,
            final.metrics.n_deferred_max,
            final.metrics.n_dropped,
            jnp.sort(jnp.where(ok, lat, jnp.inf)),
        )

    per_fog, lat_mean, n_ok, n_sched, n_def, n_drop, lat_sorted = go(state)
    per_fog = np.asarray(per_fog, np.float64)
    n_ok = int(n_ok)
    ls = np.asarray(lat_sorted)[:n_ok]
    return {
        "per_fog": per_fog,
        "lat_mean": float(lat_mean),
        "lat_p50": float(ls[n_ok // 2]) if n_ok else float("nan"),
        "lat_p95": float(ls[int(n_ok * 0.95)]) if n_ok else float("nan"),
        "n_ok": n_ok,
        "n_sched": int(n_sched),
        "n_deferred_max": int(n_def),
        "n_dropped": int(n_drop),
    }


def report(name, a, b):
    tot = a["per_fog"].sum()
    l1 = float(np.abs(a["per_fog"] / tot - b["per_fog"] / b["per_fog"].sum()).sum())
    print(json.dumps({
        "regime": name,
        "shape": "10k users / 32 fogs / 0.3 s",
        "decisions_dt1": a["n_sched"], "decisions_dt5": b["n_sched"],
        "assign_l1_shift": round(l1, 5),
        "wait_mean_dt1_s": round(a["lat_mean"], 6),
        "wait_mean_dt5_s": round(b["lat_mean"], 6),
        "wait_mean_delta_pct": round(
            100 * (b["lat_mean"] - a["lat_mean"])
            / max(a["lat_mean"], 1e-12), 3),
        "wait_p95_dt1_s": round(a["lat_p95"], 6),
        "wait_p95_dt5_s": round(b["lat_p95"], 6),
        "served_dt1": a["n_ok"], "served_dt5": b["n_ok"],
        "dropped_dt1": a["n_dropped"], "dropped_dt5": b["n_dropped"],
        "n_deferred_max": max(a["n_deferred_max"], b["n_deferred_max"]),
    }))


def main():
    enable_compile_cache()
    # The north-star world is inherently saturated (10k users publishing
    # every 2.5 ms vs 32 fogs serving ~0.2-0.9 s tasks): the envelope
    # observables are WHICH fog tasks go to, how many drop, and the
    # queue waits of the genuinely-served population.  0.3 s captures
    # the split/drop picture; 1.0 s lets each fog cycle a few services
    # so the wait distribution is populated.  (A "served regime" at this
    # shape does not exist: service capacity is ~1e3x under the offered
    # load by construction — that IS the benchmark.)
    report("saturated-0.3s", stats(1e-3), stats(5e-3))
    report(
        "saturated-1.0s-waits",
        stats(1e-3, horizon=1.0),
        stats(5e-3, horizon=1.0),
    )


if __name__ == "__main__":
    main()
