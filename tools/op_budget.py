"""HLO op-budget gate for the exact-ordering tick (r6).

The r5 roofline work proved the dt=1 ms tick is per-op-bound, not
bytes-bound (~35 us per serialized op slot, tools/kernel_overhead.py),
so the kernel COUNT of the compiled tick is the throughput-critical
quantity — and, unlike wall time, it is deterministic and checkable in
CI.  This tool compiles the single-tick step at one pinned CPU shape,
counts the optimized HLO module's ENTRY-computation instructions
(everything but parameter/constant/tuple plumbing) and its fusions, and
gates them against the checked-in budget (``tools/op_budget.json``) the
same way simlint failures gate tier-1:

  python tools/op_budget.py            # print fused/unfused counts + ratio
  python tools/op_budget.py --check    # exit 1 on budget violation (CI)
  python tools/op_budget.py --write    # regenerate tools/op_budget.json

The budget carries three gates:
  * ``max_ops`` / ``max_fusions`` — the fused tick's counts with slack
    (RATIO_SLACK) for toolchain drift: an engine change that grows the
    kernel count fails here before it lands;
  * ``max_fused_ratio`` — fused/unfused, measured live at check time
    (version-independent): the fused front-end must keep its >= 30%
    kernel-count reduction (ISSUE 5 acceptance).

Pinned shape: the bench world's decision path at the exact-ordering
tick (dt = 1 ms, dense MIN_BUSY broker, two-stage arrivals,
derive_acks, ``arrival_window=None`` so the fused no-window mode
engages), shrunk to 256 users so the CPU compile stays in tier-1 time.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUDGET_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "op_budget.json")


#: Slack over the recorded fused counts before --check fails (absolute
#: counts drift a little across XLA versions; the ratio gate does not).
COUNT_SLACK = 1.10

#: The acceptance bar: fused must compile to <= this fraction of the
#: unfused tick's kernels at the pinned shape.
MAX_FUSED_RATIO = 0.70

PINNED = dict(
    n_users=256,
    n_fogs=8,
    fog_mips=(1000.0, 2000.0, 3000.0, 4000.0),
    send_interval=2.5e-3,
    dt=1e-3,
    horizon=0.02,
    max_sends_per_user=12,
    queue_capacity=32,
    arrival_window=None,
    derive_acks=True,
)


def _build():
    from fognetsimpp_tpu.scenarios import smoke

    return smoke.build(**PINNED)


def entry_op_counts(hlo_text: str) -> dict:
    """Count the optimized module's ENTRY-computation instructions.

    Returns {"ops": nontrivial instruction count, "fusions": fusion
    count} — "ops" approximates the serialized kernel slots the r5
    calibration priced at ~35 us each.  Counting is delegated to the
    ONE shared HLO parser (``tools/hloaudit/hlo.py``, ISSUE 7): the
    op-budget gate and the compiled-artifact audit read the same parse
    of the same text, so their numbers can never drift apart.
    """
    from tools.hloaudit.hlo import entry_op_counts as _shared

    return _shared(hlo_text)


def compile_tick_counts(fused: bool) -> dict:
    """Compile one tick of the pinned world and count its HLO ops."""
    import jax

    from fognetsimpp_tpu.core.engine import make_step
    from fognetsimpp_tpu.net.topology import associate

    spec, state, net, bounds = _build()
    spec = dataclasses.replace(spec, fused_slots=fused).validate()
    step = make_step(spec)
    cache = associate(
        net, state.nodes.pos, state.nodes.alive, broker=spec.broker_index
    )
    compiled = jax.jit(
        lambda s: step(s, net, bounds, cache)
    ).lower(state).compile()
    return entry_op_counts(compiled.as_text())


def compile_chaos_counts() -> dict:
    """Compile the chaos-on tick (the hloaudit ``tick_chaos`` shape)
    and count its HLO ops — the fault path's own kernel-count pin
    (ISSUE 12): chaos adds a lifecycle phase, an in-flight sweep and
    the RTT perturbation to every tick, so a regression here is a
    hostile-world throughput loss CI should catch like any other."""
    from tools.hloaudit.variants import variants

    v = next(x for x in variants() if x.name == "tick_chaos")
    text = v.compile_fn().text
    return entry_op_counts(text)


def compile_hier_counts() -> dict:
    """Compile the federated-hierarchy tick (the hloaudit ``tick_hier``
    shape: 2 broker domains, THRESHOLD migration) and count its HLO
    ops — the federation path's own kernel-count pin (ISSUE 14): the
    domain-masked winners and the migrate phase ride every federated
    tick, so a regression here is a multi-broker throughput loss CI
    should catch like any other."""
    from tools.hloaudit.variants import variants

    v = next(x for x in variants() if x.name == "tick_hier")
    text = v.compile_fn().text
    return entry_op_counts(text)


def compile_journeys_counts() -> dict:
    """Compile the journey-tap tick (the hloaudit ``tick_journeys``
    shape: the chaos+hier world with telemetry + the task-journey
    event rings live) and count its HLO ops — the observability
    plane's own kernel-count pin (ISSUE 15): the per-tick snapshot
    diff and ring drop-scatter ride every journey-on tick, so a
    regression here is a traced-world throughput loss CI should catch
    like any other."""
    from tools.hloaudit.variants import variants

    v = next(x for x in variants() if x.name == "tick_journeys")
    text = v.compile_fn().text
    return entry_op_counts(text)


def compile_dyn_counts() -> dict:
    """Compile the promoted-operand tick (the hloaudit ``tick_dyn``
    shape: the tick_chaos world with every promoted knob a DynSpec
    operand, ISSUE 13) and count its HLO ops.  The pin is what keeps
    "one program, many worlds" from quietly costing kernels: an operand
    that blocks a constant-fold XLA used to exploit shows up here as op
    growth vs ``tick_chaos``."""
    from tools.hloaudit.variants import variants

    v = next(x for x in variants() if x.name == "tick_dyn")
    text = v.compile_fn().text
    return entry_op_counts(text)


_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
                "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "f32": 4,
                "s64": 8, "u64": 8, "f64": 8}
_SHAPE_RE = re.compile(r"(pred|bf16|[suf]\d+)\[([\d,]*)\]")


def _result_bytes(result: str) -> int:
    """Bytes of an HLO result type's (first) array shape — for an async
    start's tuple result the first element is the payload buffer."""
    m = _SHAPE_RE.search(result)
    if not m:
        return 0
    dtype, dims = m.groups()
    n = 1
    for d in dims.split(","):
        if d.strip():
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def compile_tp_counts(
    telemetry: bool = False, window: bool = False,
    journeys: bool = False, promote: bool = False,
) -> dict:
    """Compile the shard_map'd TP sharded tick and count its HLO ops +
    collectives (ISSUE 9).

    The program is a 2-tick ``lax.scan``, which lowers to a while loop
    whose body is counted ONCE — so the collective tally is the
    PER-TICK collective count, pinned EXACTLY by ``--check`` (a new
    collective in the sharded tick must arrive together with its
    ``DECLARED_COLLECTIVES`` entry and a reviewed budget regeneration;
    hloaudit A3 checks the kinds, this pins the count).

    ``telemetry=True`` compiles the ISSUE 11 telemetry-on variant
    (exchange gauges + hist): its EXTRA psums — the phase-work/
    histogram i32 fold and the exchange/latency f32 fold — get their
    own exactly-pinned count, while the telemetry-OFF tick must keep
    the PR 8 count unchanged.

    ``window=True`` compiles the ISSUE 18 WINDOWED tick
    (``arrival_window=4``): the hop-pruned top-K merge ring.  Its
    ``ppermute_payload_bytes`` pin is the O(K) proof — every
    collective-permute hop must carry exactly the packed (K, 5) i32
    window (K*5*4 bytes), never the full candidate gather.

    ``journeys=True`` compiles the ISSUE 19 windowed journey-on tick:
    the shard-local ring tap must add ZERO collectives (its only
    cross-shard scalar rides the established end-of-tick psum), so the
    pinned collective count equals the windowed telemetry tick's.

    ``promote=True`` compiles the ISSUE 20 promoted-operand TP tick
    (the DynSpec operand replicated across the node mesh): promotion
    must be communication-free, so its collective counts AND per-hop
    ppermute payload are pinned byte-identical to the constant-folded
    ``tp_tick``.
    """
    from tools.hloaudit.hlo import (
        COLLECTIVE_OPS,
        base_collective,
        parse_hlo,
    )
    from tools.hloaudit.variants import _compile_tp_tick

    if journeys:
        text = _compile_tp_tick(
            telemetry=True, telemetry_journeys=8,
            telemetry_journey_ring=16, arrival_window=4,
            derive_acks=False,
        ).text
    elif window:
        text = _compile_tp_tick(arrival_window=4).text
    elif telemetry:
        text = _compile_tp_tick(
            telemetry=True, telemetry_hist=True, derive_acks=False
        ).text
    elif promote:
        text = _compile_tp_tick(promote=True).text
    else:
        text = _compile_tp_tick().text
    mod = parse_hlo(text)
    counts = mod.entry_op_counts()
    colls: dict = {}
    payloads: set = set()
    for i in mod.all_instructions():
        op = base_collective(i.opcode)
        if op in COLLECTIVE_OPS and not i.opcode.endswith("-done"):
            colls[op] = colls.get(op, 0) + 1
            if op == "collective-permute":
                payloads.add(_result_bytes(i.result))
    return {
        "ops": counts["ops"],
        "fusions": counts["fusions"],
        "collectives": dict(sorted(colls.items())),
        "collective_count": sum(colls.values()),
        # distinct per-hop collective-permute payload sizes (bytes);
        # pinned EXACTLY by --check
        "ppermute_payload_bytes": sorted(payloads),
    }


def measure(
    tp: bool = True, hier: bool = True, journeys: bool = True
) -> dict:
    """Compile and count the gated programs.

    ``tp=False`` skips the TP sharded-tick compile (tier-1's
    test_op_budget fixture: test_tp.py already compiles TP programs,
    and the TP budget gate still runs in CI via
    ``python tools/op_budget.py --check``).  ``hier=False`` likewise
    skips the federated-tick compile in the tier-1 fixture
    (test_hier.py compiles hier programs in-tier; the tick_hier budget
    gate still runs in CI via ``--check``), and ``journeys=False`` the
    journey-tap tick (test_journeys.py compiles journey programs
    in-tier; the tick_journeys budget gate still runs via ``--check``).
    """
    fused = compile_tick_counts(fused=True)
    unfused = compile_tick_counts(fused=False)
    chaos = compile_chaos_counts()
    dyn = compile_dyn_counts()
    hier_counts = compile_hier_counts() if hier else None
    journey_counts = compile_journeys_counts() if journeys else None
    out_tp = {}
    if tp:
        for key, kw in (("tp_tick", {}),
                        ("tp_tick_dyn", dict(promote=True)),
                        ("tp_tick_telemetry", dict(telemetry=True)),
                        ("tp_tick_window", dict(window=True)),
                        ("tp_tick_journeys", dict(journeys=True))):
            t = compile_tp_counts(**kw)
            out_tp[key] = {
                **t,
                "max_ops": int(t["ops"] * COUNT_SLACK),
                "max_fusions": int(t["fusions"] * COUNT_SLACK),
            }
    return {
        "shape": {k: (list(v) if isinstance(v, tuple) else v)
                  for k, v in PINNED.items()},
        "fused": fused,
        "unfused": unfused,
        "ratio": {
            k: round(fused[k] / max(unfused[k], 1), 4)
            for k in ("ops", "fusions")
        },
        "max_ops": int(fused["ops"] * COUNT_SLACK),
        "max_fusions": int(fused["fusions"] * COUNT_SLACK),
        "max_fused_ratio": MAX_FUSED_RATIO,
        "tick_chaos": {
            **chaos,
            "max_ops": int(chaos["ops"] * COUNT_SLACK),
            "max_fusions": int(chaos["fusions"] * COUNT_SLACK),
        },
        "tick_dyn": {
            **dyn,
            "max_ops": int(dyn["ops"] * COUNT_SLACK),
            "max_fusions": int(dyn["fusions"] * COUNT_SLACK),
        },
        **(
            {
                "tick_hier": {
                    **hier_counts,
                    "max_ops": int(hier_counts["ops"] * COUNT_SLACK),
                    "max_fusions": int(
                        hier_counts["fusions"] * COUNT_SLACK
                    ),
                }
            }
            if hier_counts is not None
            else {}
        ),
        **(
            {
                "tick_journeys": {
                    **journey_counts,
                    "max_ops": int(journey_counts["ops"] * COUNT_SLACK),
                    "max_fusions": int(
                        journey_counts["fusions"] * COUNT_SLACK
                    ),
                }
            }
            if journey_counts is not None
            else {}
        ),
        **out_tp,
    }


def check(measured: dict, budget: dict) -> list:
    """Gate ``measured`` against ``budget``; returns failure strings."""
    errs = []
    for k, cap_key in (("ops", "max_ops"), ("fusions", "max_fusions")):
        got = measured["fused"][k]
        cap = budget[cap_key]
        if got > cap:
            errs.append(
                f"fused tick {k} regressed: {got} > budget {cap} "
                f"(regenerate with --write ONLY if the growth is "
                f"justified and reviewed)"
            )
    cap = budget.get("max_fused_ratio", MAX_FUSED_RATIO)
    # the ratio gate runs on "ops" — the serialized-kernel-slot count the
    # r5 ~35 us/op calibration prices; "fusions" is recorded (and capped
    # absolutely above) but not ratio-gated, since fusion granularity is
    # an XLA partitioning choice, not a kernel-slot count
    ratio = measured["fused"]["ops"] / max(measured["unfused"]["ops"], 1)
    if ratio > cap:
        errs.append(
            f"fused/unfused ops ratio {ratio:.3f} > {cap} — the "
            f"fused front-end lost its kernel-count reduction"
        )
    # --- the chaos (ISSUE 12), promoted-operand (ISSUE 13),
    # federated-hierarchy (ISSUE 14) and journey-tap (ISSUE 15) ticks --
    for vname in ("tick_chaos", "tick_dyn", "tick_hier",
                  "tick_journeys"):
        tc, btc = measured.get(vname), budget.get(vname)
        if tc is None:
            continue
        if btc is None:
            errs.append(
                f"budget file predates the {vname} variant — "
                "regenerate with --write"
            )
            continue
        for k, cap_key in (("ops", "max_ops"),
                           ("fusions", "max_fusions")):
            if tc[k] > btc[cap_key]:
                errs.append(
                    f"{vname} {k} regressed: {tc[k]} > "
                    f"budget {btc[cap_key]}"
                )
    # --- the TP sharded ticks (ISSUE 9; telemetry-on since ISSUE 11;
    # windowed hop-pruned exchange since ISSUE 18; journey rings since
    # ISSUE 19; promoted DynSpec operand since ISSUE 20) ---
    for key in ("tp_tick", "tp_tick_dyn", "tp_tick_telemetry",
                "tp_tick_window", "tp_tick_journeys"):
        tp = measured.get(key)
        btp = budget.get(key)
        if tp is None:
            continue
        if btp is None:
            errs.append(
                f"budget file predates the {key} variant — regenerate "
                "with --write"
            )
            continue
        for k, cap_key in (("ops", "max_ops"),
                           ("fusions", "max_fusions")):
            if tp[k] > btp[cap_key]:
                errs.append(
                    f"{key} {k} regressed: {tp[k]} > "
                    f"budget {btp[cap_key]}"
                )
        if tp["collectives"] != btp["collectives"]:
            errs.append(
                f"{key} per-tick collectives drifted: "
                f"{tp['collectives']} != pinned {btp['collectives']} "
                "— a collective change must land with its "
                "DECLARED_COLLECTIVES entry and a reviewed --write"
            )
        # exact payload pin: for tp_tick_window this is the O(K)
        # proof — each ppermute hop carries the packed (K, 5) i32
        # window, never the full candidate gather
        bpay = btp.get("ppermute_payload_bytes")
        if (bpay is not None
                and tp.get("ppermute_payload_bytes") != bpay):
            errs.append(
                f"{key} per-hop ppermute payload drifted: "
                f"{tp.get('ppermute_payload_bytes')} != pinned {bpay} "
                "bytes — the exchange ring stopped carrying its "
                "pinned per-hop payload"
            )
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--write", action="store_true",
                    help="regenerate the checked-in budget file")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless counts are within the budget")
    ap.add_argument("--budget", default=BUDGET_PATH,
                    help="budget file path (default: tools/op_budget.json)")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the TP sharded tick compiles on the 8-virtual-device mesh: the
    # topology flag must land before the first backend init
    from tools.hloaudit.variants import ensure_devices

    ensure_devices()
    measured = measure()
    print(json.dumps(measured, indent=1))
    if args.write:
        # read-modify-write: hloaudit --write owns the "peak_bytes"
        # table inside this same file (A7 budgets) — preserve it
        out = dict(measured)
        if os.path.exists(args.budget):
            with open(args.budget) as f:
                prev = json.load(f)
            if "peak_bytes" in prev:
                out["peak_bytes"] = prev["peak_bytes"]
        with open(args.budget, "w") as f:
            json.dump(out, f, indent=1)
            f.write("\n")
        print(f"wrote {args.budget}", file=sys.stderr)
        return 0
    if args.check:
        if not os.path.exists(args.budget):
            print(f"missing budget file {args.budget} (run --write)",
                  file=sys.stderr)
            return 1
        with open(args.budget) as f:
            budget = json.load(f)
        errs = check(measured, budget)
        for e in errs:
            print(f"op_budget: {e}", file=sys.stderr)
        return 1 if errs else 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
