"""Measure the per-kernel overhead of a scan body on this TPU (r5).

The r5 finding: the dt=1 ms tick floor (~0.79 ms) is flat in table size,
i.e. per-op overhead, not bytes.  This microbench calibrates that
constant: a lax.scan whose body is a chain of N deliberately unfusable
ops (each a scatter touching a distinct buffer region — XLA cannot merge
them) timed by the two-length difference quotient.  ms/tick divided by N
estimates the per-kernel cost the engine's ~100-op tick pays.
"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from fognetsimpp_tpu.compile_cache import enable_compile_cache

N_LO, N_HI = 200, 1000


def chain(n_ops, size=440_000, k=4096):
    """Scan body = n_ops sequential K-index scatters into a (size,) buf."""
    idx0 = jnp.arange(k, dtype=jnp.int32) * (size // k)

    def body(carry, t):
        buf = carry
        for j in range(n_ops):
            buf = buf.at[(idx0 + j) % size].add(1.0)
        return buf, ()

    def run(n_ticks):
        @jax.jit
        def go(b):
            out, _ = jax.lax.scan(body, b, jnp.arange(n_ticks))
            return jnp.sum(out)
        return go

    b0 = jnp.zeros((size,), jnp.float32)
    lo, hi = run(N_LO), run(N_HI)

    def wall(fn):
        np.asarray(fn(b0))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(fn(b0))
            best = min(best, time.perf_counter() - t0)
        return best

    w_lo, w_hi = wall(lo), wall(hi)
    ms = (w_hi - w_lo) / (N_HI - N_LO) * 1e3
    return ms


def main():
    enable_compile_cache()
    for n_ops in (1, 8, 32, 64):
        ms = chain(n_ops)
        print(f"n_ops={n_ops:3d}: {ms:7.4f} ms/tick  "
              f"({ms / n_ops * 1e3:6.1f} us/op)")


if __name__ == "__main__":
    main()
