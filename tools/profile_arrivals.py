"""Decompose _phase_fog_arrivals cost on the TPU (r5).

Same difference-quotient methodology as profile_tick.py, but patching
the arrival phase's INTERNALS: candidate reduction, plan_arrivals
(rank), batched_enqueue, and the T-column scatter-writes.
"""
import os, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp, numpy as np
from fognetsimpp_tpu.compile_cache import enable_compile_cache
import fognetsimpp_tpu.core.engine as E
import fognetsimpp_tpu.ops.queues as Q
from tools.profile_tick import build, time_scan

def main():
    enable_compile_cache()
    n_users = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
    win = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
    spec, state, net, bounds = build(n_users, 1e-3)
    import dataclasses
    # this tool bisects the r5 REFERENCE arrival path: pin the fused
    # front-end off so the monkeypatched internals actually trace
    spec = dataclasses.replace(spec, arrival_window=win,
                               fused_slots=False)
    print(f"users={n_users} K={spec.window} T={spec.task_capacity} "
          f"R={spec.arrival_cands}")
    base, c = time_scan(spec, state, net, bounds)
    print(f"full step:            {base:8.3f} ms/tick (compile {c:.0f}s)")

    def patched(name, mod, attr, repl):
        orig = getattr(mod, attr)
        setattr(mod, attr, repl)
        try:
            ms, _ = time_scan(spec, state, net, bounds)
        finally:
            setattr(mod, attr, orig)
        print(f"- {name:22s} {ms:8.3f} ms/tick   marginal {base - ms:+.3f}")

    # 1. rank/plan: constant plan (wrong but shape-correct)
    def fake_plan(mask, fog, t, F, idle, per_fog=None, **_kw):
        K = mask.shape[0]
        return Q.ArrivalPlan(
            assign_task=jnp.full((F,), Q.NO_TASK, jnp.int32),
            rank=jnp.where(mask, 0, -1).astype(jnp.int32),
            counts=jnp.zeros((F,), jnp.int32),
        )
    patched("plan_arrivals", E, "plan_arrivals", fake_plan)

    # 2. enqueue: no-op
    def fake_enq(queue, qh, ql, mask, fog, rank, ids=None):
        return queue, ql, jnp.zeros_like(mask), jnp.zeros_like(ql)
    patched("batched_enqueue", E, "batched_enqueue", fake_enq)

    # 3. whole tail
    def fake_tail(spec_, state_, cache, buf, tasks, fogs, *a, **_kw):
        return state_.replace(tasks=tasks, fogs=fogs), buf
    patched("tail(all)", E, "_fog_arrivals_tail", fake_tail)

    # 4. whole phase
    ident2 = lambda spec_, s, net_, cache, buf, *a, **k: (s, buf)
    patched("phase(all)", E, "_phase_fog_arrivals", ident2)

    # 5. compact
    def fake_compact(mask, K, T, rot=None):
        idx = jnp.arange(K, dtype=jnp.int32)
        return idx, idx, mask[:K]
    patched("compact", E, "_compact", fake_compact)

if __name__ == "__main__":
    main()
