"""OpenMetrics text-format lint (~20 lines): `python tools/check_openmetrics.py FILE`.

Checks the subset the telemetry exposition emits: every line is either a
`# TYPE <name> <kind>` / `# EOF` comment or a `<name>[{labels}] <value>`
sample with a finite decimal value, and the file ends with `# EOF`.
"""
import math
import re
import sys

SAMPLE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"\\\n]*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"\\\n]*")*\})? -?[0-9][0-9.eE+-]*$'
)
TYPE = re.compile(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* [a-z]+$")


def check(path: str) -> int:
    lines = open(path).read().splitlines()
    for i, ln in enumerate(lines, 1):
        if ln == "# EOF" or TYPE.match(ln):
            continue
        m = SAMPLE.match(ln)
        if not m or not math.isfinite(float(ln.rsplit(" ", 1)[1])):
            print(f"{path}:{i}: bad OpenMetrics line: {ln!r}")
            return 1
    if not lines or lines[-1] != "# EOF":
        print(f"{path}: missing trailing '# EOF'")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(max(check(p) for p in sys.argv[1:]) if sys.argv[1:] else 2)
