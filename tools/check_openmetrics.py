"""OpenMetrics text-format lint: `python tools/check_openmetrics.py FILE...`.

Checks the subset the telemetry exposition emits, extended for the live
health plane (r6):

* every line is a ``# HELP`` / ``# TYPE`` / ``# EOF`` comment or a
  ``<name>[{labels}] <value>`` sample with a finite decimal value, and
  the file ends with ``# EOF``;
* every sample's family carries BOTH a ``# TYPE`` and a ``# HELP``
  line (scrape UIs surface the help text; a bare family reads as an
  exposition bug) — histogram samples (``_bucket``/``_sum``/``_count``
  suffixes) resolve to their base family;
* no two samples share the same (name, label-set): a scraper would
  silently last-write-win on duplicates;
* histogram families obey the bucket contract: every ``_bucket``
  sample has an ``le`` label, each label-group's ``le`` values ascend
  strictly and terminate at ``+Inf``, bucket counts are cumulative
  (non-decreasing), the group's ``_count`` equals its ``+Inf`` bucket
  and a ``_sum`` sample is present;
* the TP exchange-plane families (ISSUE 11, ``fns_tp_exchange_*``)
  carry the ``shard`` label dimension on every sample, with
  non-negative decimal-integer values and no gaps (shards 0..N-1 all
  present per family) — a missing shard in the scrape is a silent
  observability hole, and duplicate (family, shard, fog) series are
  already rejected by the generic duplicate-series rule;
* the per-broker federation families (``fns_hier_migrations_out/in``,
  ``fns_hier_fogs``, ``fns_hier_users``, ``fns_hier_load_mean``) carry
  the ``broker`` label dimension on every sample, integer-valued and
  gap-free ``0..B-1`` cross-checked against the published
  ``fns_hier_brokers`` count — exactly the ISSUE 11 shard-label rule;
  previously a missing trailing broker series passed the lint.
* the twin front-door families (ISSUE 17, ``fns_twin_tenant_*``)
  carry the ``tenant`` label dimension on every sample, integer-valued
  and gap-free ``0..N-1`` cross-checked against the published
  ``fns_twin_tenants`` count — the shard/broker label rule replayed
  for the multi-tenant aggregate exposition;
* the twin ingestion family (``fns_twin_ingest_*``) is all-or-nothing:
  once any of its gauges appears, the full set (depth, capacity,
  accepted/dropped/injected/rejected totals, latency) must be present
  — a partial ingest exposition means a dashboard silently loses the
  drop or depth signal it alarms on;
* the journey census (``fns_journey_tasks``) carries the ``stage``
  label dimension on every sample, drawn from the KNOWN census stages
  (the terminal journey event names plus ``in_flight``/``unspawned``)
  with no stage emitted twice — an unknown or duplicated stage is a
  census key drifting away from the dashboards that match on it (the
  broker/shard label-rule pattern).
"""
import math
import re
import sys

LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="[^"\\\n]*"'
SAMPLE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
    rf'(\{{{LABEL}(,{LABEL})*\}})? -?[0-9][0-9.eE+-]*$'
)
TYPE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) ([a-z]+)$")
HELP = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) \S.*$")
LABEL_ONE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"\\\n]*)"')

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")

#: The per-broker federation families (hier/): every sample of these
#: must carry a ``broker`` label.  Scalar fns_hier_* roll-ups
#: (``fns_hier_migrated``, ``fns_hier_hop_exhausted``,
#: ``fns_hier_brokers``) are legitimately label-free and stay outside
#: this set.
_HIER_BROKER_FAMILIES = frozenset(
    (
        "fns_hier_migrations_out",
        "fns_hier_migrations_in",
        "fns_hier_fogs",
        "fns_hier_users",
        "fns_hier_load_mean",
    )
)


#: The complete ingestion gauge family (twin/): the live exposition
#: emits all of these or none — alarms ride depth vs capacity and the
#: dropped counter, so a partial render is a silent hole.
_TWIN_INGEST_FAMILIES = frozenset(
    (
        "fns_twin_ingest_depth",
        "fns_twin_ingest_capacity",
        "fns_twin_ingest_accepted_total",
        "fns_twin_ingest_dropped_total",
        "fns_twin_ingest_injected_total",
        "fns_twin_ingest_rejected_total",
        "fns_twin_ingest_latency_seconds",
    )
)


#: The journey census stages (telemetry/openmetrics._render_journeys):
#: the TERMINAL journey event names plus the two non-terminal census
#: buckets.  Hardcoded so the linter stays stdlib-only (importing
#: journeys pulls in jax) — extend together with JourneyEvent's
#: terminal set.  Non-terminal events (spawn, decide, defer, ...) are
#: NEVER census stages: a ring whose last event is one of those counts
#: as in_flight.
_JOURNEY_STAGES = frozenset(
    (
        "done",
        "no_resource",
        "rejected",
        "dropped",
        "lost",
        "crash_lost",
        "retry_exhaust",
        "hop_exhausted",
        "in_flight",
        "unspawned",
    )
)


def _parse_labels(text):
    """'{a="1",b="2"}' -> dict; '' -> {}."""
    return dict(LABEL_ONE.findall(text or ""))


def _family(name, types):
    """Resolve a sample name to its metadata family: histogram samples
    drop their `_bucket`/`_sum`/`_count` suffix when the base family is
    TYPE histogram."""
    for suf in _HIST_SUFFIXES:
        if name.endswith(suf):
            base = name[: -len(suf)]
            if types.get(base) == "histogram":
                return base
    return name


def check_lines(lines, where: str) -> int:
    types, helps = {}, {}
    samples = []  # (lineno, name, labels_text, value)
    seen = set()
    for i, ln in enumerate(lines, 1):
        if ln == "# EOF":
            continue
        mt = TYPE.match(ln)
        if mt:
            if mt.group(1) in types:
                print(f"{where}:{i}: duplicate # TYPE for {mt.group(1)}")
                return 1
            types[mt.group(1)] = mt.group(2)
            continue
        mh = HELP.match(ln)
        if mh:
            helps[mh.group(1)] = True
            continue
        if ln.startswith("#"):
            print(f"{where}:{i}: bad comment line: {ln!r}")
            return 1
        m = SAMPLE.match(ln)
        if not m:
            print(f"{where}:{i}: bad OpenMetrics line: {ln!r}")
            return 1
        v = float(ln.rsplit(" ", 1)[1])
        if not math.isfinite(v):
            print(f"{where}:{i}: non-finite sample value: {ln!r}")
            return 1
        series = (m.group(1), m.group(2) or "")
        if series in seen:
            print(f"{where}:{i}: duplicate series {m.group(1)}{series[1]}")
            return 1
        seen.add(series)
        samples.append((i, m.group(1), m.group(2) or "", v))
    if not lines or lines[-1] != "# EOF":
        print(f"{where}: missing trailing '# EOF'")
        return 1
    # metadata coverage: every sample family needs # TYPE and # HELP
    for i, name, _labels, _v in samples:
        fam = _family(name, types)
        if fam not in types:
            print(f"{where}:{i}: sample {name} has no # TYPE line")
            return 1
        if fam not in helps:
            print(f"{where}:{i}: sample {name} has no # HELP line")
            return 1
    # TP exchange-plane shard-label contract (ISSUE 11)
    shard_vals = {}  # family -> set of shard ints
    n_shards = None  # the exposition's own fns_tp_shards sample
    for i, name, labels_text, v in samples:
        if name == "fns_tp_shards":
            n_shards = int(v)
        fam = _family(name, types)
        if not fam.startswith("fns_tp_exchange"):
            continue
        labels = _parse_labels(labels_text)
        if "shard" not in labels:
            print(f"{where}:{i}: {name} sample without a 'shard' label")
            return 1
        sv = labels["shard"]
        if not sv.isdigit():
            print(
                f"{where}:{i}: {name} has non-integer shard={sv!r}"
            )
            return 1
        shard_vals.setdefault(fam, set()).add(int(sv))
    for fam, vals in shard_vals.items():
        # cross-check against the published shard count when present:
        # MISSING TRAILING shards (a truncated render loop) are the
        # silent observability hole the gap rule exists for, and only
        # fns_tp_shards knows the true N
        want = set(range(n_shards if n_shards else max(vals) + 1))
        if vals != want:
            print(
                f"{where}: family {fam} has shard gaps: saw "
                f"{sorted(vals)}, expected 0..{max(want)}"
            )
            return 1
    # federation broker-label contract: the PR 9 shard rule replayed
    # for the per-broker fns_hier_* families
    broker_vals = {}  # family -> set of broker ints
    n_brokers = None  # the exposition's own fns_hier_brokers sample
    for i, name, labels_text, v in samples:
        if name == "fns_hier_brokers":
            n_brokers = int(v)
        fam = _family(name, types)
        if fam not in _HIER_BROKER_FAMILIES:
            continue
        labels = _parse_labels(labels_text)
        if "broker" not in labels:
            print(f"{where}:{i}: {name} sample without a 'broker' label")
            return 1
        bv = labels["broker"]
        if not bv.isdigit():
            print(f"{where}:{i}: {name} has non-integer broker={bv!r}")
            return 1
        broker_vals.setdefault(fam, set()).add(int(bv))
    for fam, vals in broker_vals.items():
        # cross-check against the published broker count when present:
        # a MISSING TRAILING broker series (a truncated render loop)
        # previously passed — only fns_hier_brokers knows the true B
        want = set(range(n_brokers if n_brokers else max(vals) + 1))
        if vals != want:
            print(
                f"{where}: family {fam} has broker gaps: saw "
                f"{sorted(vals)}, expected 0..{max(want)}"
            )
            return 1
    # twin front-door tenant-label contract (ISSUE 17): the
    # shard/broker rule replayed for the per-tenant aggregate families
    tenant_vals = {}  # family -> set of tenant ints
    n_tenants = None  # the exposition's own fns_twin_tenants sample
    for i, name, labels_text, v in samples:
        if name == "fns_twin_tenants":
            n_tenants = int(v)
        fam = _family(name, types)
        if not fam.startswith("fns_twin_tenant_"):
            continue
        labels = _parse_labels(labels_text)
        if "tenant" not in labels:
            print(f"{where}:{i}: {name} sample without a 'tenant' label")
            return 1
        tv = labels["tenant"]
        if not tv.isdigit():
            print(f"{where}:{i}: {name} has non-integer tenant={tv!r}")
            return 1
        tenant_vals.setdefault(fam, set()).add(int(tv))
    for fam, vals in tenant_vals.items():
        # cross-check against the published tenant count when present:
        # a missing trailing tenant series (a truncated render loop)
        # would otherwise pass — only fns_twin_tenants knows the true N
        want = set(range(n_tenants if n_tenants else max(vals) + 1))
        if vals != want:
            print(
                f"{where}: family {fam} has tenant gaps: saw "
                f"{sorted(vals)}, expected 0..{max(want)}"
            )
            return 1
    # journey census stage-label contract (ISSUE 19): every
    # fns_journey_tasks sample names a KNOWN stage exactly once —
    # series uniqueness alone would let a drifted/extra-labeled stage
    # double-count the census
    stage_seen = set()
    for i, name, labels_text, v in samples:
        if _family(name, types) != "fns_journey_tasks":
            continue
        labels = _parse_labels(labels_text)
        if "stage" not in labels:
            print(f"{where}:{i}: {name} sample without a 'stage' label")
            return 1
        sv = labels["stage"]
        if sv not in _JOURNEY_STAGES:
            print(
                f"{where}:{i}: {name} has unknown stage={sv!r} "
                f"(known: {', '.join(sorted(_JOURNEY_STAGES))})"
            )
            return 1
        if sv in stage_seen:
            print(f"{where}:{i}: {name} repeats stage={sv!r}")
            return 1
        stage_seen.add(sv)
    # twin ingestion-family completeness (ISSUE 17): all-or-nothing
    ingest_present = {
        _family(name, types)
        for _i, name, _l, _v in samples
        if _family(name, types) in _TWIN_INGEST_FAMILIES
    }
    if ingest_present and ingest_present != _TWIN_INGEST_FAMILIES:
        missing = sorted(_TWIN_INGEST_FAMILIES - ingest_present)
        print(
            f"{where}: partial fns_twin_ingest_* exposition: missing "
            f"{', '.join(missing)}"
        )
        return 1
    # histogram bucket contract
    hist_fams = {n for n, k in types.items() if k == "histogram"}
    for fam in hist_fams:
        groups = {}  # non-le label signature -> [(le, count, lineno)]
        counts, sums = {}, set()
        for i, name, labels_text, v in samples:
            labels = _parse_labels(labels_text)
            if name == fam + "_bucket":
                if "le" not in labels:
                    print(
                        f"{where}:{i}: {name} sample without an "
                        "'le' label"
                    )
                    return 1
                le = labels.pop("le")
                key = tuple(sorted(labels.items()))
                try:
                    le_v = (
                        float("inf") if le == "+Inf" else float(le)
                    )
                except ValueError:
                    print(
                        f"{where}:{i}: {name} has non-numeric "
                        f"le={le!r}"
                    )
                    return 1
                groups.setdefault(key, []).append((le_v, v, i))
            elif name == fam + "_count":
                key = tuple(sorted(labels.items()))
                counts[key] = (v, i)
            elif name == fam + "_sum":
                sums.add(tuple(sorted(labels.items())))
        if not groups:
            print(f"{where}: histogram {fam} has no _bucket samples")
            return 1
        for key, rows in groups.items():
            les = [le for le, _, _ in rows]
            if les != sorted(les) or len(set(les)) != len(les):
                print(
                    f"{where}: histogram {fam}{dict(key)}: 'le' values "
                    "not strictly ascending"
                )
                return 1
            if not math.isinf(les[-1]):
                print(
                    f"{where}: histogram {fam}{dict(key)}: missing "
                    "terminal '+Inf' bucket"
                )
                return 1
            vals = [c for _, c, _ in rows]
            if any(b < a for a, b in zip(vals, vals[1:])):
                print(
                    f"{where}: histogram {fam}{dict(key)}: bucket "
                    "counts not cumulative"
                )
                return 1
            if key not in counts:
                print(
                    f"{where}: histogram {fam}{dict(key)}: missing "
                    "_count sample"
                )
                return 1
            if counts[key][0] != vals[-1]:
                print(
                    f"{where}: histogram {fam}{dict(key)}: _count "
                    f"{counts[key][0]} != +Inf bucket {vals[-1]}"
                )
                return 1
            if key not in sums:
                print(
                    f"{where}: histogram {fam}{dict(key)}: missing "
                    "_sum sample"
                )
                return 1
    return 0


def check_text(text: str, where: str = "<text>") -> int:
    """Lint an in-memory exposition (the live endpoint smoke test)."""
    return check_lines(text.splitlines(), where)


def check(path: str) -> int:
    return check_lines(open(path).read().splitlines(), path)


if __name__ == "__main__":
    sys.exit(max(check(p) for p in sys.argv[1:]) if sys.argv[1:] else 2)
