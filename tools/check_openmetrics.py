"""OpenMetrics text-format lint: `python tools/check_openmetrics.py FILE...`.

Checks the subset the telemetry exposition emits: every line is either a
`# TYPE <name> <kind>` / `# EOF` comment or a `<name>[{labels}] <value>`
sample with a finite decimal value, the file ends with `# EOF`, and —
since the fleet exposition grew per-replica labels (r6) — no two samples
share the same (name, label-set): duplicate series are an exposition bug
a scraper would silently last-write-win on.
"""
import math
import re
import sys

LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="[^"\\\n]*"'
SAMPLE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
    rf'(\{{{LABEL}(,{LABEL})*\}})? -?[0-9][0-9.eE+-]*$'
)
TYPE = re.compile(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* [a-z]+$")


def check(path: str) -> int:
    lines = open(path).read().splitlines()
    seen = set()
    for i, ln in enumerate(lines, 1):
        if ln == "# EOF" or TYPE.match(ln):
            continue
        m = SAMPLE.match(ln)
        if not m or not math.isfinite(float(ln.rsplit(" ", 1)[1])):
            print(f"{path}:{i}: bad OpenMetrics line: {ln!r}")
            return 1
        series = (m.group(1), m.group(2) or "")
        if series in seen:
            print(f"{path}:{i}: duplicate series {m.group(1)}{series[1]}")
            return 1
        seen.add(series)
    if not lines or lines[-1] != "# EOF":
        print(f"{path}: missing trailing '# EOF'")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(max(check(p) for p in sys.argv[1:]) if sys.argv[1:] else 2)
