"""The audited tick-variant registry: every compiled shape CI must prove
clean.

One variant = one ``jax.jit(...).lower(...).compile()`` of a production
step program at a pinned CPU shape small enough for tier-1 time:

* ``tick_fused`` / ``tick_unfused`` — the exact-ordering dt=1 ms tick at
  the op-budget pinned shape (``tools/op_budget.PINNED`` — ONE shape
  definition shared with the kernel-count gate), fused front-end on/off;
* ``tick_telemetry`` / ``tick_hist`` — the same tick with the
  device-resident telemetry plane / streaming latency histogram riding
  the carry (the variants whose extra accumulators must still compile
  host-transfer-free);
* ``fleet_step`` — the replica-sharded fleet scan
  (``parallel/fleet._fleet_run``) on the 8-virtual-device CPU mesh:
  its "zero steady-state collectives" claim becomes a static check;
* ``tp_dryrun`` — the TP fog-sharded argmin
  (``parallel/tp.sharded_min_busy``): must compile with EXACTLY its
  declared collectives (``parallel/tp.DECLARED_COLLECTIVES``) — the
  correctness rail the ROADMAP's task-table-sharding promotion runs on.

Multi-device variants need >= 8 devices: call :func:`ensure_devices`
BEFORE importing jax (the CLI does; under pytest, conftest.py's forced
8-virtual-device topology already covers it).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List, Optional, Set

_N_DEVICES = 8

#: Shrunk fleet/TP shapes: compile cost only, semantics don't depend on
#: size (the equivalence tests own the semantics).
_FLEET = dict(n_users=64, n_fogs=8, horizon=0.02, send_interval=2.5e-3,
              dt=1e-3, max_sends_per_user=8)
_FLEET_TICKS = 4
_TP_FOGS = 16
_TP_TASKS = 32
#: Shrunk TP sharded-tick shape (divisible over the 8-device mesh).
_TP_TICK = dict(n_users=16, n_fogs=4, horizon=0.02, send_interval=2.5e-3,
                dt=1e-3, max_sends_per_user=8, start_time_max=0.01,
                queue_capacity=8)
_TP_TICK_TICKS = 2


def ensure_devices() -> None:
    """Force the 8-virtual-device CPU topology (no-op once jax is up)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={_N_DEVICES}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


@dataclasses.dataclass(frozen=True)
class Variant:
    name: str
    description: str
    compile_fn: Callable[[], "tuple"]  # () -> (hlo_text, spec_or_None)
    sharded: bool = False
    declared_collectives: Optional[Dict[str, Set[str]]] = None


#: The chaos-on tick overrides (ISSUE 12), shared by ``tick_chaos`` and
#: the promoted ``tick_dyn`` variant so the two audit the SAME world —
#: one as trace constants, one as DynSpec operands.
CHAOS_OVERRIDES = dict(
    chaos=True,
    chaos_mode=1,  # ChaosMode.REOFFLOAD
    chaos_mtbf_s=0.05,
    chaos_mttr_s=0.02,
    chaos_max_retries=3,
    chaos_script=((0, 0.005, 0.01),),
    chaos_rtt_amp=0.5,
    chaos_rtt_burst_prob=0.02,
    # chaos mutates fog liveness: no static hoist, and the ack columns
    # must stay eager (derive_acks needs assume_static)
    assume_static=False,
    derive_acks=False,
)


#: The federated-hierarchy tick overrides (ISSUE 14): 2 broker domains
#: over the op-budget pinned 8-fog world, THRESHOLD migration live —
#: the domain-masked dense decide + the migrate phase both trace.
HIER_OVERRIDES = dict(
    n_brokers=2,
    hier_policy=1,  # HierPolicy.THRESHOLD
    hier_threshold=0.5,
    hier_max_hops=2,
)


#: The journey-tap tick overrides (ISSUE 15): the chaos+hier world with
#: the telemetry plane AND the task-journey event rings live — the
#: RICHEST tap surface (re-offload retry deltas, migration hop deltas
#: and every terminal all trace), so the audit covers the full edge
#: synthesis, not just the happy-path subset.
JOURNEY_OVERRIDES = dict(
    **CHAOS_OVERRIDES,
    **HIER_OVERRIDES,
    telemetry=True,
    telemetry_journeys=8,
    telemetry_journey_ring=16,
)


def _compile_tick(**build_overrides):
    """Compile ONE tick of the op-budget pinned world; returns
    (hlo_text, spec).  The same lower/compile path op_budget gates, so
    the two tools can never audit different programs."""
    import jax

    from fognetsimpp_tpu.net.topology import associate
    from fognetsimpp_tpu.core.engine import make_step
    from fognetsimpp_tpu.scenarios import smoke
    from tools.op_budget import PINNED

    spec, state, net, bounds = smoke.build(**{**PINNED, **build_overrides})
    step = make_step(spec)
    cache = associate(
        net, state.nodes.pos, state.nodes.alive, broker=spec.broker_index
    )
    compiled = jax.jit(
        lambda s: step(s, net, bounds, cache)
    ).lower(state).compile()
    return compiled.as_text(), spec


def _compile_tick_dyn():
    """Compile the PROMOTED tick (ISSUE 13): shape key static, every
    promoted knob a DynSpec operand — the program the warm-reconfig /
    shape-bucket reuse path executes.  Audited on the chaos-on world so
    the chaos/learn/link operand leaves are actually CONSUMED (a
    knob-free world would audit dead operands).  Must stay
    host-transfer-free and f64-free exactly like the constant-folded
    twin (``tick_chaos``)."""
    import jax

    from fognetsimpp_tpu.net.topology import associate
    from fognetsimpp_tpu.core.engine import make_step
    from fognetsimpp_tpu.dynspec import split_spec
    from fognetsimpp_tpu.scenarios import smoke
    from tools.op_budget import PINNED

    spec, state, net, bounds = smoke.build(
        **{**PINNED, **CHAOS_OVERRIDES}
    )
    key_spec, dyn = split_spec(spec)
    step = make_step(key_spec)
    cache = associate(
        net, state.nodes.pos, state.nodes.alive, broker=spec.broker_index
    )
    compiled = jax.jit(
        lambda s, d: step(s, net, bounds, cache, dyn=d)
    ).lower(state, dyn).compile()
    return compiled.as_text(), key_spec


def _compile_fleet():
    """Compile the replica-sharded fleet scan on the 8-device mesh."""
    import jax

    from fognetsimpp_tpu.parallel.fleet import _fleet_run
    from fognetsimpp_tpu.parallel.mesh import make_mesh, shard_world
    from fognetsimpp_tpu.parallel.replicas import replicate_state
    from fognetsimpp_tpu.scenarios import smoke

    spec, state, net, bounds = smoke.build(**_FLEET)
    mesh = make_mesh(_N_DEVICES)
    batch = replicate_state(spec, state, _N_DEVICES)
    batch, net, bounds, _ = shard_world(batch, net, bounds, mesh)
    compiled = _fleet_run.lower(
        spec, _FLEET_TICKS, batch, net, bounds
    ).compile()
    return compiled.as_text(), spec


def _compile_tp():
    """Compile the fog-sharded two-stage argmin (the TP dryrun step)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from fognetsimpp_tpu.parallel.tp import FOG_AXIS, sharded_min_busy

    mesh = Mesh(np.asarray(jax.devices()[:_N_DEVICES]), (FOG_AXIS,))
    K, F = _TP_TASKS, _TP_FOGS
    compiled = jax.jit(
        lambda m, q, b, v, r: sharded_min_busy(mesh, m, q, b, v, r)
    ).lower(
        jnp.ones((K,), bool),
        jnp.ones((K,), jnp.float32),
        jnp.zeros((F,), jnp.float32),
        jnp.full((F,), 1000.0, jnp.float32),
        jnp.ones((F,), bool),
    ).compile()
    return compiled.as_text(), None


def _compile_tp_tick(**build_overrides):
    """Compile the shard_map'd TP sharded tick (the ISSUE 9 production
    path) through taskshard's OWN program builder — the audited
    artifact is the program ``run_tp_sharded`` executes, never a twin.

    ``build_overrides`` select the variant: ``telemetry=True`` compiles
    the ISSUE 11 telemetry-on tick (exchange-plane gauges + the
    phase-work/histogram fold psums riding the shard_map body)."""
    from fognetsimpp_tpu.parallel.mesh import make_mesh
    from fognetsimpp_tpu.parallel.taskshard import NODE_AXIS, _tp_setup
    from fognetsimpp_tpu.scenarios import smoke

    spec, state, net, bounds = smoke.build(
        **{**_TP_TICK, **build_overrides}
    )
    mesh = make_mesh(_N_DEVICES, axis_name=NODE_AXIS)
    go, parts, net_r, cache_r, spec = _tp_setup(
        spec, state, net, mesh, _TP_TICK_TICKS, NODE_AXIS,
        None, False, False,
    )
    compiled = go.lower(*parts, net_r, cache_r).compile()
    return compiled.as_text(), spec


def _fleet_declared() -> Dict[str, Set[str]]:
    from fognetsimpp_tpu.parallel.fleet import DECLARED_COLLECTIVES

    return DECLARED_COLLECTIVES


def _tp_declared() -> Dict[str, Set[str]]:
    from fognetsimpp_tpu.parallel.tp import DECLARED_COLLECTIVES

    return DECLARED_COLLECTIVES


def variants() -> List[Variant]:
    return [
        Variant(
            "tick_fused",
            "exact-ordering dt=1ms tick, fused front-end (op-budget shape)",
            lambda: _compile_tick(),
        ),
        Variant(
            "tick_unfused",
            "the same tick on the unfused reference path",
            lambda: _compile_tick(fused_slots=False),
        ),
        Variant(
            "tick_telemetry",
            "fused tick with the device-resident telemetry plane on",
            lambda: _compile_tick(telemetry=True),
        ),
        Variant(
            "tick_hist",
            "fused tick with telemetry + the streaming latency histogram "
            "(eager acks: the hist phase reads t_ack6 inside the tick)",
            lambda: _compile_tick(
                telemetry=True, telemetry_hist=True, derive_acks=False
            ),
        ),
        Variant(
            "tick_chaos",
            "the op-budget tick with the chaos fault-injection "
            "subsystem live (REOFFLOAD churn: random MTBF/MTTR + a "
            "scripted outage + periodic/burst RTT degradation) — the "
            "fault path must stay host-transfer-free, f64-free and "
            "collective-free like every single-device tick",
            lambda: _compile_tick(**CHAOS_OVERRIDES),
        ),
        Variant(
            "tick_hier",
            "the op-budget tick with the federated multi-broker "
            "hierarchy live (2 domains, THRESHOLD migration: "
            "domain-masked per-broker winners + the broker_migrate "
            "phase + aged peer views) — the federation path must stay "
            "host-transfer-free, f64-free and collective-free like "
            "every single-device tick",
            lambda: _compile_tick(**HIER_OVERRIDES),
        ),
        Variant(
            "tick_journeys",
            "the chaos+hier tick with the telemetry plane and the "
            "task-journey event rings live (ISSUE 15: per-sampled-task "
            "snapshot diff + ring drop-scatter every tick) — the "
            "journey tap must stay host-transfer-free, f64-free and "
            "collective-free like every single-device tick",
            lambda: _compile_tick(**JOURNEY_OVERRIDES),
        ),
        Variant(
            "tick_dyn",
            "the same chaos-on tick with the promoted DynSpec operand "
            "(ISSUE 13): shape key static, every promoted knob run-time "
            "data — the warm-reconfig/shape-bucket program; must stay "
            "host-transfer-free with its op budget pinned",
            _compile_tick_dyn,
        ),
        Variant(
            "fleet_step",
            "replica-sharded fleet scan on the 8-virtual-device mesh "
            "(declared collectives: none — the zero-steady-state claim)",
            _compile_fleet,
            sharded=True,
            declared_collectives=None,  # resolved lazily from fleet.py
        ),
        Variant(
            "tp_dryrun",
            "TP fog-sharded argmin (parallel/tp.sharded_min_busy)",
            _compile_tp,
            sharded=True,
            declared_collectives=None,  # resolved lazily from tp.py
        ),
        Variant(
            "tp_tick",
            "shard_map'd TP sharded tick on the 8-device node mesh "
            "(parallel/taskshard.run_tp_sharded: psum combines + ring "
            "arrival exchange)",
            lambda: _compile_tp_tick(),
            sharded=True,
            declared_collectives=None,  # resolved lazily from taskshard.py
        ),
        Variant(
            "tp_tick_telemetry",
            "the same TP sharded tick with the telemetry plane on "
            "(ISSUE 11: per-shard exchange gauges + the phase-work/"
            "latency-hist fold psums; collective kinds must stay "
            "within taskshard.DECLARED_COLLECTIVES)",
            lambda: _compile_tp_tick(
                telemetry=True, telemetry_hist=True, derive_acks=False
            ),
            sharded=True,
            declared_collectives=None,  # resolved lazily from taskshard.py
        ),
    ]


def declared_for(v: Variant) -> Optional[Dict[str, Set[str]]]:
    """Resolve a sharded variant's declaration table from its module
    (kept next to the sharded code, not in this registry)."""
    if v.declared_collectives is not None:
        return v.declared_collectives
    if v.name == "fleet_step":
        return _fleet_declared()
    if v.name == "tp_dryrun":
        return _tp_declared()
    if v.name in ("tp_tick", "tp_tick_telemetry"):
        from fognetsimpp_tpu.parallel.taskshard import (
            DECLARED_COLLECTIVES as tp_tick_declared,
        )

        return tp_tick_declared
    return None
