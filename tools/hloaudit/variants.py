"""The audited tick-variant registry: every compiled shape CI must prove
clean.

One variant = one ``jax.jit(...).lower(...).compile()`` of a production
step program at a pinned CPU shape small enough for tier-1 time:

* ``tick_fused`` / ``tick_unfused`` — the exact-ordering dt=1 ms tick at
  the op-budget pinned shape (``tools/op_budget.PINNED`` — ONE shape
  definition shared with the kernel-count gate), fused front-end on/off;
* ``tick_telemetry`` / ``tick_hist`` — the same tick with the
  device-resident telemetry plane / streaming latency histogram riding
  the carry (the variants whose extra accumulators must still compile
  host-transfer-free);
* ``fleet_step`` — the replica-sharded fleet scan
  (``parallel/fleet._fleet_run``) on the 8-virtual-device CPU mesh:
  its "zero steady-state collectives" claim becomes a static check;
* ``tp_dryrun`` — the TP fog-sharded argmin
  (``parallel/tp.sharded_min_busy``): must compile with EXACTLY its
  declared collectives (``parallel/tp.DECLARED_COLLECTIVES``) — the
  correctness rail the ROADMAP's task-table-sharding promotion runs on.

Multi-device variants need >= 8 devices: call :func:`ensure_devices`
BEFORE importing jax (the CLI does; under pytest, conftest.py's forced
8-virtual-device topology already covers it).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List, Optional, Set

_N_DEVICES = 8

#: Shrunk fleet/TP shapes: compile cost only, semantics don't depend on
#: size (the equivalence tests own the semantics).
_FLEET = dict(n_users=64, n_fogs=8, horizon=0.02, send_interval=2.5e-3,
              dt=1e-3, max_sends_per_user=8)
_FLEET_TICKS = 4
_TP_FOGS = 16
_TP_TASKS = 32
#: Shrunk TP sharded-tick shape (divisible over the 8-device mesh).
_TP_TICK = dict(n_users=16, n_fogs=4, horizon=0.02, send_interval=2.5e-3,
                dt=1e-3, max_sends_per_user=8, start_time_max=0.01,
                queue_capacity=8)
_TP_TICK_TICKS = 2
#: Small whole-run shape for the donating ``engine._run_jit`` variant
#: (a handful of ticks: the donation layout, not the horizon, is what
#: the A6 alias pin guards).
_RUN_JIT = dict(n_users=16, n_fogs=4, horizon=0.01, send_interval=2.5e-3,
                dt=1e-3, max_sends_per_user=8)


def ensure_devices() -> None:
    """Force the 8-virtual-device CPU topology (no-op once jax is up)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={_N_DEVICES}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


@dataclasses.dataclass(frozen=True)
class CompiledArtifact:
    """What one variant compile yields: the optimized-HLO text, the spec
    it was built from (None for spec-free programs) and the compiled
    executable's memory roll-up (None when the backend's
    ``memory_analysis()`` is unavailable)."""

    text: str
    spec: object = None
    mem: Optional[dict] = None


def _artifact(compiled, spec=None) -> CompiledArtifact:
    """Roll a ``.lower(...).compile()`` result into a CompiledArtifact.

    ``peak_bytes`` is the A7 budget quantity: argument + output + temp
    buffer bytes minus the aliased (donated-and-honoured) bytes that are
    double-counted between arguments and outputs — the live-buffer
    high-water mark the pinned budgets in ``tools/op_budget.json`` gate.
    """
    mem = None
    try:
        ma = compiled.memory_analysis()
        mem = {
            "arg_bytes": int(ma.argument_size_in_bytes),
            "out_bytes": int(ma.output_size_in_bytes),
            "temp_bytes": int(ma.temp_size_in_bytes),
            "alias_bytes": int(ma.alias_size_in_bytes),
        }
        mem["peak_bytes"] = (
            mem["arg_bytes"] + mem["out_bytes"] + mem["temp_bytes"]
            - mem["alias_bytes"]
        )
    except Exception:
        mem = None  # backend without memory stats: A7 skips, A1-A6 run
    return CompiledArtifact(compiled.as_text(), spec, mem)


@dataclasses.dataclass(frozen=True)
class Variant:
    name: str
    description: str
    compile_fn: Callable[[], CompiledArtifact]
    sharded: bool = False
    declared_collectives: Optional[Dict[str, Set[str]]] = None
    #: jit argument positions declared ``donate_argnums`` by the compiled
    #: entry point (pytree args, not flat buffers).  Non-empty means rule
    #: A6 requires the compiled module to carry ``input_output_alias``
    #: entries — a donation that silently stopped aliasing is a memory
    #: regression nothing else sees.
    donated: tuple = ()


#: The chaos-on tick overrides (ISSUE 12), shared by ``tick_chaos`` and
#: the promoted ``tick_dyn`` variant so the two audit the SAME world —
#: one as trace constants, one as DynSpec operands.
CHAOS_OVERRIDES = dict(
    chaos=True,
    chaos_mode=1,  # ChaosMode.REOFFLOAD
    chaos_mtbf_s=0.05,
    chaos_mttr_s=0.02,
    chaos_max_retries=3,
    chaos_script=((0, 0.005, 0.01),),
    chaos_rtt_amp=0.5,
    chaos_rtt_burst_prob=0.02,
    # chaos mutates fog liveness: no static hoist, and the ack columns
    # must stay eager (derive_acks needs assume_static)
    assume_static=False,
    derive_acks=False,
)


#: The federated-hierarchy tick overrides (ISSUE 14): 2 broker domains
#: over the op-budget pinned 8-fog world, THRESHOLD migration live —
#: the domain-masked dense decide + the migrate phase both trace.
HIER_OVERRIDES = dict(
    n_brokers=2,
    hier_policy=1,  # HierPolicy.THRESHOLD
    hier_threshold=0.5,
    hier_max_hops=2,
)


#: The journey-tap tick overrides (ISSUE 15): the chaos+hier world with
#: the telemetry plane AND the task-journey event rings live — the
#: RICHEST tap surface (re-offload retry deltas, migration hop deltas
#: and every terminal all trace), so the audit covers the full edge
#: synthesis, not just the happy-path subset.
JOURNEY_OVERRIDES = dict(
    **CHAOS_OVERRIDES,
    **HIER_OVERRIDES,
    telemetry=True,
    telemetry_journeys=8,
    telemetry_journey_ring=16,
)


#: The live-twin tick overrides (ISSUE 17): the ingestion gate ON over
#: the telemetry+histogram serving shape.  Injection happens at HOST
#: chunk boundaries (twin/ingest drains into engine.inject_arrivals),
#: so the compiled tick itself must be bit-identical in structure to
#: the ingest-off tick — auditing it proves the gate adds NO ops, no
#: host transfers and no budget growth to the inner loop.
INGEST_OVERRIDES = dict(
    ingest=True,
    telemetry=True,
    telemetry_hist=True,
    derive_acks=False,
)


def _compile_tick(**build_overrides):
    """Compile ONE tick of the op-budget pinned world; returns a
    :class:`CompiledArtifact`.  The same lower/compile path op_budget
    gates, so the two tools can never audit different programs."""
    import jax

    from fognetsimpp_tpu.net.topology import associate
    from fognetsimpp_tpu.core.engine import make_step
    from fognetsimpp_tpu.scenarios import smoke
    from tools.op_budget import PINNED

    spec, state, net, bounds = smoke.build(**{**PINNED, **build_overrides})
    step = make_step(spec)
    cache = associate(
        net, state.nodes.pos, state.nodes.alive, broker=spec.broker_index
    )
    compiled = jax.jit(
        lambda s: step(s, net, bounds, cache)
    ).lower(state).compile()
    return _artifact(compiled, spec)


def _compile_tick_dyn():
    """Compile the PROMOTED tick (ISSUE 13): shape key static, every
    promoted knob a DynSpec operand — the program the warm-reconfig /
    shape-bucket reuse path executes.  Audited on the chaos-on world so
    the chaos/learn/link operand leaves are actually CONSUMED (a
    knob-free world would audit dead operands).  Must stay
    host-transfer-free and f64-free exactly like the constant-folded
    twin (``tick_chaos``)."""
    import jax

    from fognetsimpp_tpu.net.topology import associate
    from fognetsimpp_tpu.core.engine import make_step
    from fognetsimpp_tpu.dynspec import split_spec
    from fognetsimpp_tpu.scenarios import smoke
    from tools.op_budget import PINNED

    spec, state, net, bounds = smoke.build(
        **{**PINNED, **CHAOS_OVERRIDES}
    )
    key_spec, dyn = split_spec(spec)
    step = make_step(key_spec)
    cache = associate(
        net, state.nodes.pos, state.nodes.alive, broker=spec.broker_index
    )
    compiled = jax.jit(
        lambda s, d: step(s, net, bounds, cache, dyn=d)
    ).lower(state, dyn).compile()
    return _artifact(compiled, key_spec)


def _compile_fleet(promote=False):
    """Compile the replica-sharded fleet scan on the 8-device mesh.

    ``promote=True`` compiles the ISSUE 20 promoted variant: the spec
    split on its shape key and every promoted knob fed as a per-replica
    ``dyn_rows`` operand (the ``sweep_dyn(mesh=)`` one-compile program).
    The default stays the constant-folded sibling, byte-stable.
    """
    import jax

    from fognetsimpp_tpu.parallel.fleet import _fleet_dyn_rows, _fleet_run
    from fognetsimpp_tpu.parallel.mesh import make_mesh, shard_world
    from fognetsimpp_tpu.parallel.replicas import replicate_state
    from fognetsimpp_tpu.scenarios import smoke

    spec, state, net, bounds = smoke.build(**_FLEET)
    mesh = make_mesh(_N_DEVICES)
    batch = replicate_state(spec, state, _N_DEVICES)
    batch, net, bounds, _ = shard_world(batch, net, bounds, mesh)
    if promote:
        run_spec, dyn_rows = _fleet_dyn_rows(
            spec, _N_DEVICES, mesh, None, True
        )
        compiled = _fleet_run.lower(
            run_spec, _FLEET_TICKS, batch, net, bounds, dyn_rows
        ).compile()
    else:
        compiled = _fleet_run.lower(
            spec, _FLEET_TICKS, batch, net, bounds
        ).compile()
    return _artifact(compiled, spec)


def _compile_tp():
    """Compile the fog-sharded two-stage argmin (the TP dryrun step)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from fognetsimpp_tpu.parallel.tp import FOG_AXIS, sharded_min_busy

    mesh = Mesh(np.asarray(jax.devices()[:_N_DEVICES]), (FOG_AXIS,))
    K, F = _TP_TASKS, _TP_FOGS
    compiled = jax.jit(
        lambda m, q, b, v, r: sharded_min_busy(mesh, m, q, b, v, r)
    ).lower(
        jnp.ones((K,), bool),
        jnp.ones((K,), jnp.float32),
        jnp.zeros((F,), jnp.float32),
        jnp.full((F,), 1000.0, jnp.float32),
        jnp.ones((F,), bool),
    ).compile()
    return _artifact(compiled, None)


def _compile_tp_tick(promote=False, **build_overrides):
    """Compile the shard_map'd TP sharded tick (the ISSUE 9 production
    path) through taskshard's OWN program builder — the audited
    artifact is the program ``run_tp_sharded`` executes, never a twin.

    ``build_overrides`` select the variant: ``telemetry=True`` compiles
    the ISSUE 11 telemetry-on tick (exchange-plane gauges + the
    phase-work/histogram fold psums riding the shard_map body).

    ``promote=True`` compiles the ISSUE 20 promoted tick — the DynSpec
    operand rides the shard_map body replicated, and the audited
    artifact is the zero-recompile program warm retunes re-execute.
    The constant-folded (``promote=False``) siblings stay byte-stable:
    promotion is a separate ``_tp_program`` cache entry, not a rewrite
    of the static path."""
    from fognetsimpp_tpu.parallel.mesh import make_mesh
    from fognetsimpp_tpu.parallel.taskshard import NODE_AXIS, _tp_setup
    from fognetsimpp_tpu.scenarios import smoke

    spec, state, net, bounds = smoke.build(
        **{**_TP_TICK, **build_overrides}
    )
    mesh = make_mesh(_N_DEVICES, axis_name=NODE_AXIS)
    go, parts, net_r, cache_r, spec, dyn = _tp_setup(
        spec, state, net, mesh, _TP_TICK_TICKS, NODE_AXIS,
        None, False, False, promote=promote,
    )
    if dyn is not None:
        compiled = go.lower(*parts, net_r, cache_r, dyn).compile()
    else:
        compiled = go.lower(*parts, net_r, cache_r).compile()
    return _artifact(compiled, spec)


def _compile_run_jit():
    """Compile the DONATING whole-run program (``engine._run_jit``:
    ``jit(static_argnums=0, donate_argnums=1)``) at a small smoke shape.

    This is the A6 exemplar for the engine's whole-run donation family
    (``_run_jit``/``_run_jit_dyn``/``run_chunked``'s chunk program all
    share the donate-the-state layout): the compiled module must carry
    ``input_output_alias`` entries for the donated WorldState buffers,
    and the alias count is pinned in the manifest so a refactor that
    silently breaks donation (a dtype change, an output that stops
    being shape-compatible) fails A6 instead of doubling peak memory.
    """
    from fognetsimpp_tpu.core.engine import _run_jit
    from fognetsimpp_tpu.scenarios import smoke

    spec, state, net, bounds = smoke.build(**_RUN_JIT)
    compiled = _run_jit.lower(spec, state, net, bounds).compile()
    return _artifact(compiled, spec)


def _compile_tick_pool():
    from fognetsimpp_tpu.spec import FogModel

    return _compile_tick(
        fog_model=int(FogModel.POOL), derive_acks=False
    )


def _compile_tick_learn():
    from fognetsimpp_tpu.spec import Policy

    return _compile_tick(policy=int(Policy.UCB), derive_acks=False)


def _fleet_declared() -> Dict[str, Set[str]]:
    from fognetsimpp_tpu.parallel.fleet import DECLARED_COLLECTIVES

    return DECLARED_COLLECTIVES


def _tp_declared() -> Dict[str, Set[str]]:
    from fognetsimpp_tpu.parallel.tp import DECLARED_COLLECTIVES

    return DECLARED_COLLECTIVES


def variants() -> List[Variant]:
    return [
        Variant(
            "tick_fused",
            "exact-ordering dt=1ms tick, fused front-end (op-budget shape)",
            lambda: _compile_tick(),
        ),
        Variant(
            "tick_unfused",
            "the same tick on the unfused reference path",
            lambda: _compile_tick(fused_slots=False),
        ),
        Variant(
            "tick_telemetry",
            "fused tick with the device-resident telemetry plane on",
            lambda: _compile_tick(telemetry=True),
        ),
        Variant(
            "tick_hist",
            "fused tick with telemetry + the streaming latency histogram "
            "(eager acks: the hist phase reads t_ack6 inside the tick)",
            lambda: _compile_tick(
                telemetry=True, telemetry_hist=True, derive_acks=False
            ),
        ),
        Variant(
            "tick_chaos",
            "the op-budget tick with the chaos fault-injection "
            "subsystem live (REOFFLOAD churn: random MTBF/MTTR + a "
            "scripted outage + periodic/burst RTT degradation) — the "
            "fault path must stay host-transfer-free, f64-free and "
            "collective-free like every single-device tick",
            lambda: _compile_tick(**CHAOS_OVERRIDES),
        ),
        Variant(
            "tick_hier",
            "the op-budget tick with the federated multi-broker "
            "hierarchy live (2 domains, THRESHOLD migration: "
            "domain-masked per-broker winners + the broker_migrate "
            "phase + aged peer views) — the federation path must stay "
            "host-transfer-free, f64-free and collective-free like "
            "every single-device tick",
            lambda: _compile_tick(**HIER_OVERRIDES),
        ),
        Variant(
            "tick_journeys",
            "the chaos+hier tick with the telemetry plane and the "
            "task-journey event rings live (ISSUE 15: per-sampled-task "
            "snapshot diff + ring drop-scatter every tick) — the "
            "journey tap must stay host-transfer-free, f64-free and "
            "collective-free like every single-device tick",
            lambda: _compile_tick(**JOURNEY_OVERRIDES),
        ),
        # ---- featmat cell variants (ISSUE 16) ------------------------
        # every ACCEPTED cell of the feature-composition matrix
        # (tools/featmat) maps to a dedicated variant; these cover the
        # single-device cells no earlier variant compiled.  Deleting a
        # rejection clause flips its cell to ACCEPTED, and featmat
        # --check fails until the cell's variant lands here.
        Variant(
            "tick_energy",
            "the op-budget tick with the energy/lifecycle model live "
            "(per-message radio costs, battery drain, lifecycle "
            "shutdown/restart mutating liveness — no static hoist)",
            lambda: _compile_tick(
                energy_enabled=True, derive_acks=False
            ),
        ),
        Variant(
            "tick_wired",
            "the op-budget tick with DropTail wired-queue backpressure "
            "live (per-link queues; derive_acks stays eager)",
            lambda: _compile_tick(
                wired_queue_enabled=True, derive_acks=False
            ),
        ),
        Variant(
            "tick_learn",
            "the op-budget tick with a learned (UCB bandit) broker "
            "policy live — learner state rides the carry, rewards "
            "credit at ack time (eager acks)",
            _compile_tick_learn,
        ),
        Variant(
            "tick_pool",
            "the op-budget tick on POOL (phase-sequential) fog servers "
            "instead of FIFO — the sequential-pool service path",
            _compile_tick_pool,
        ),
        Variant(
            "tick_series",
            "the op-budget tick with per-tick series recording on "
            "(record_tick_series: the demo-scale vectors path)",
            lambda: _compile_tick(record_tick_series=True),
        ),
        Variant(
            "tick_window",
            "the op-budget tick in the WINDOWED arrival regime "
            "(arrival_window=16: the bounded candidate tail instead of "
            "the fused no-window mode)",
            lambda: _compile_tick(arrival_window=16),
        ),
        Variant(
            "tick_ingest",
            "the telemetry+histogram serving tick with the live-"
            "ingestion gate on (ISSUE 17: spec.ingest=True) — arrival "
            "injection is a host-side chunk-boundary phase, so the "
            "compiled tick must stay host-transfer-free and carry "
            "ZERO extra ops versus the ingest-off serving tick",
            lambda: _compile_tick(**INGEST_OVERRIDES),
        ),
        Variant(
            "run_jit_donated",
            "the donating whole-run program (engine._run_jit, "
            "donate_argnums=1) at a small smoke shape — the A6 "
            "donation-alias exemplar for the engine's donate-the-state "
            "entry family",
            _compile_run_jit,
            donated=(1,),
        ),
        Variant(
            "tick_dyn",
            "the same chaos-on tick with the promoted DynSpec operand "
            "(ISSUE 13): shape key static, every promoted knob run-time "
            "data — the warm-reconfig/shape-bucket program; must stay "
            "host-transfer-free with its op budget pinned",
            _compile_tick_dyn,
        ),
        Variant(
            "fleet_step",
            "replica-sharded fleet scan on the 8-virtual-device mesh "
            "(declared collectives: none — the zero-steady-state claim; "
            "donates the batch state: A6 pins the alias count)",
            _compile_fleet,
            sharded=True,
            declared_collectives=None,  # resolved lazily from fleet.py
            donated=(2,),  # _fleet_run's donate_argnums
        ),
        Variant(
            "fleet_step_dyn",
            "the replica-sharded fleet scan with the promoted DynSpec "
            "operand (ISSUE 20): shape key static, per-replica knob "
            "rows run-time data — the sweep_dyn(mesh=) one-compile "
            "program; declared collectives and the donated-batch alias "
            "contract must match the constant-folded fleet_step",
            lambda: _compile_fleet(promote=True),
            sharded=True,
            declared_collectives=None,  # resolved lazily from fleet.py
            donated=(2,),  # _fleet_run's donate_argnums
        ),
        Variant(
            "tp_dryrun",
            "TP fog-sharded argmin (parallel/tp.sharded_min_busy)",
            _compile_tp,
            sharded=True,
            declared_collectives=None,  # resolved lazily from tp.py
        ),
        Variant(
            "tp_tick",
            "shard_map'd TP sharded tick on the 8-device node mesh "
            "(parallel/taskshard.run_tp_sharded: psum combines + ring "
            "arrival exchange)",
            lambda: _compile_tp_tick(),
            sharded=True,
            declared_collectives=None,  # resolved lazily from taskshard.py
        ),
        Variant(
            "tp_tick_dyn",
            "the shard_map'd TP sharded tick with the promoted DynSpec "
            "operand (ISSUE 20): shape key static, every promoted knob "
            "read from a replicated operand inside the sharded phases "
            "— the warm-reconfig TP program; collective kinds/counts "
            "and the ppermute payload must stay byte-identical to the "
            "constant-folded tp_tick",
            lambda: _compile_tp_tick(promote=True),
            sharded=True,
            declared_collectives=None,  # resolved lazily from taskshard.py
        ),
        Variant(
            "tp_tick_telemetry",
            "the same TP sharded tick with the telemetry plane on "
            "(ISSUE 11: per-shard exchange gauges + the phase-work/"
            "latency-hist fold psums; collective kinds must stay "
            "within taskshard.DECLARED_COLLECTIVES)",
            lambda: _compile_tp_tick(
                telemetry=True, telemetry_hist=True, derive_acks=False
            ),
            sharded=True,
            declared_collectives=None,  # resolved lazily from taskshard.py
        ),
        Variant(
            "tp_tick_window",
            "the TP sharded tick at a WINDOWED spec (ISSUE 18: "
            "distributed K-window selection — per-shard top-K then the "
            "hop-pruned lax.ppermute merge ring carries an O(K) packed "
            "payload instead of the full candidate gather)",
            lambda: _compile_tp_tick(arrival_window=4),
            sharded=True,
            declared_collectives=None,  # resolved lazily from taskshard.py
        ),
        Variant(
            "tp_tick_journeys",
            "the WINDOWED TP sharded tick with the journey rings live "
            "(ISSUE 19: shard-local snapshot diff over the owned "
            "sampled slots + the drop-oldest census riding the "
            "end-of-tick psum) — the journey tap must add NO "
            "collective beyond taskshard.DECLARED_COLLECTIVES",
            lambda: _compile_tp_tick(
                telemetry=True, telemetry_journeys=8,
                telemetry_journey_ring=16, arrival_window=4,
                derive_acks=False,
            ),
            sharded=True,
            declared_collectives=None,  # resolved lazily from taskshard.py
        ),
    ]


def declared_for(v: Variant) -> Optional[Dict[str, Set[str]]]:
    """Resolve a sharded variant's declaration table from its module
    (kept next to the sharded code, not in this registry)."""
    if v.declared_collectives is not None:
        return v.declared_collectives
    if v.name in ("fleet_step", "fleet_step_dyn"):
        return _fleet_declared()
    if v.name == "tp_dryrun":
        return _tp_declared()
    if v.name in (
        "tp_tick", "tp_tick_dyn", "tp_tick_telemetry", "tp_tick_window",
        "tp_tick_journeys",
    ):
        from fognetsimpp_tpu.parallel.taskshard import (
            DECLARED_COLLECTIVES as tp_tick_declared,
        )

        return tp_tick_declared
    return None
