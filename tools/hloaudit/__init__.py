"""hloaudit: static analysis of the COMPILED artifact (ISSUE 7).

simlint gates the source tier; this package compiles every production
tick variant (``variants.py``), parses the optimized HLO with the one
shared parser (``hlo.py`` — ``tools/op_budget.py`` counts through the
same one), attributes ops to engine phases via the ``jax.named_scope``
metadata, and checks the rule set in ``audit.py``: no host round-trips,
no f64 promotion chains, collectives only where declared (and never
degenerate), the f32 exact-integer 2^24 bound, and golden per-variant
audit manifests.  ``python -m tools.hloaudit --check`` gates CI.
"""
from .audit import AuditFinding, audit_module  # noqa: F401
from .hlo import HloModule, entry_op_counts, parse_hlo  # noqa: F401
