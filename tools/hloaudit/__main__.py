"""CLI for the compiled-artifact auditor.

  python -m tools.hloaudit                 # audit + print per-variant summary
  python -m tools.hloaudit --check         # exit 1 on any finding (CI)
  python -m tools.hloaudit --write         # regenerate the golden manifests
  python -m tools.hloaudit --only tick_fused --check
  python -m tools.hloaudit --markdown      # the BENCHMARKS.md phase table

Findings are fatal in CI exactly like simlint: `tools/ci_check.sh` runs
``--check`` over every variant, so a hidden host transfer, a surviving
f64 promotion, an undeclared collective, a phase-attribution drift, a
silently-declined donation (A6) or a peak-memory blowup (A7) in ANY
compiled tick variant fails the build before it reaches hardware.

``--write`` regenerates TWO artifacts: the per-variant manifests under
``manifests/`` (op/fusion caps, phase set, alias floors) and the
``"peak_bytes"`` table inside ``tools/op_budget.json`` (A7's budgets —
read-modify-written so op_budget's own keys survive, and vice versa).
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import List, Optional

from .variants import ensure_devices

MANIFEST_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "manifests"
)
#: A7's budgets live INSIDE the op-budget file (top-level "peak_bytes"
#: table) — one pinned-numbers artifact for compiled-cost regressions.
OP_BUDGET_JSON = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "op_budget.json",
)


def manifest_path(variant: str) -> str:
    return os.path.join(MANIFEST_DIR, f"{variant}.json")


def load_peak_budgets() -> dict:
    """The ``"peak_bytes"`` table of tools/op_budget.json ({} when the
    file or table is absent)."""
    if not os.path.exists(OP_BUDGET_JSON):
        return {}
    with open(OP_BUDGET_JSON) as f:
        return json.load(f).get("peak_bytes", {})


def write_peak_budgets(budgets: dict) -> None:
    """Read-modify-write the budget file so ``tools/op_budget.py
    --write``'s own keys survive regeneration (and vice versa)."""
    data = {}
    if os.path.exists(OP_BUDGET_JSON):
        with open(OP_BUDGET_JSON) as f:
            data = json.load(f)
    data["peak_bytes"] = dict(sorted(budgets.items()))
    with open(OP_BUDGET_JSON, "w") as f:
        json.dump(data, f, indent=1)
        f.write("\n")


def load_manifest(variant: str) -> Optional[dict]:
    p = manifest_path(variant)
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


def measure_variant(v) -> dict:
    """Compile one variant and roll up everything the manifest records."""
    from .audit import COUNT_SLACK
    from .hlo import COLLECTIVE_OPS, base_collective, parse_hlo
    from .variants import declared_for

    art = v.compile_fn()
    mod = parse_hlo(art.text)
    counts = mod.entry_op_counts()
    collectives = sorted({
        base_collective(i.opcode) for i in mod.all_instructions()
        if base_collective(i.opcode) in COLLECTIVE_OPS
    })
    n_aliases = len(mod.input_output_aliases)
    return {
        "variant": v.name,
        "description": v.description,
        "sharded": v.sharded,
        "entry": counts,
        # ceil, not floor: tiny variants (the 9-op TP combine) must keep
        # at least one op of slack or every toolchain wiggle pages
        "max_ops": math.ceil(counts["ops"] * COUNT_SLACK),
        "max_fusions": math.ceil(counts["fusions"] * COUNT_SLACK),
        "phases": mod.phase_op_counts(),
        "collectives": collectives,
        # A6: compiled donation contract — alias count with a FLOOR
        # (aliases must not silently vanish; growing is fine)
        "donated": sorted(v.donated),
        "aliases": n_aliases,
        "min_aliases": math.floor(n_aliases / COUNT_SLACK),
        "_module": mod,  # stripped before serialization
        "_spec": art.spec,
        "_mem": art.mem,
        "_declared": declared_for(v),
    }


def audit_variant(
    measured: dict,
    manifest: Optional[dict],
    peak_budget: Optional[int] = None,
) -> List:
    from .audit import audit_module

    return audit_module(
        measured["_module"],
        measured["variant"],
        spec=measured["_spec"],
        sharded=measured["sharded"],
        declared_collectives=measured["_declared"],
        manifest=manifest,
        donated=measured["donated"],
        mem=measured["_mem"],
        peak_budget=peak_budget,
    )


def _serializable(measured: dict) -> dict:
    return {k: v for k, v in measured.items() if not k.startswith("_")}


def phase_table_markdown(rows: List[dict]) -> str:
    """The BENCHMARKS.md per-phase op-count attribution table."""
    phases = sorted({p for r in rows for p in r["phases"]})
    head = "| phase | " + " | ".join(r["variant"] for r in rows) + " |"
    sep = "|" + "---|" * (len(rows) + 1)
    lines = [head, sep]
    for p in phases:
        cells = [str(r["phases"].get(p, "—")) for r in rows]
        lines.append(f"| {p} | " + " | ".join(cells) + " |")
    totals = [str(r["entry"]["ops"]) for r in rows]
    lines.append("| **ENTRY total** | " + " | ".join(totals) + " |")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.hloaudit",
        description="compiled-HLO static audit of every tick variant "
        "(rules: tools/hloaudit/audit.py docstring)",
    )
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any audit finding (CI gate)")
    ap.add_argument("--write", action="store_true",
                    help="regenerate the golden audit manifests")
    ap.add_argument("--only", action="append", default=None,
                    metavar="VARIANT", help="restrict to named variant(s)")
    ap.add_argument("--markdown", action="store_true",
                    help="emit the per-phase op-count markdown table")
    ap.add_argument("--list", action="store_true",
                    help="list variant names and exit")
    args = ap.parse_args(argv)

    ensure_devices()
    from .variants import variants

    vs = variants()
    if args.list:
        for v in vs:
            print(f"{v.name}: {v.description}")
        return 0
    if args.only:
        known = {v.name for v in vs}
        bad = sorted(set(args.only) - known)
        if bad:
            print(f"unknown variant(s) {bad} (have {sorted(known)})",
                  file=sys.stderr)
            return 2
        vs = [v for v in vs if v.name in args.only]

    findings = []
    rows = []
    peaks = load_peak_budgets()
    from .audit import COUNT_SLACK
    for v in vs:
        measured = measure_variant(v)
        rows.append(measured)
        if args.write:
            os.makedirs(MANIFEST_DIR, exist_ok=True)
            with open(manifest_path(v.name), "w") as f:
                json.dump(_serializable(measured), f, indent=1)
                f.write("\n")
            print(f"wrote {manifest_path(v.name)}", file=sys.stderr)
            if measured["_mem"] is not None:
                peaks[v.name] = math.ceil(
                    measured["_mem"]["peak_bytes"] * COUNT_SLACK
                )
            continue
        findings += audit_variant(
            measured, load_manifest(v.name), peaks.get(v.name)
        )

    if args.write:
        write_peak_budgets(peaks)
        print(
            f"wrote peak_bytes budgets for {len(peaks)} variant(s) into "
            f"{OP_BUDGET_JSON}", file=sys.stderr,
        )
        return 0
    if args.markdown:
        # table on stdout (for embedding); findings still fall through
        # to stderr below, and --check still fails on them
        print(phase_table_markdown(rows))
    else:
        for r in rows:
            e = r["entry"]
            print(json.dumps({
                "variant": r["variant"], "ops": e["ops"],
                "fusions": e["fusions"], "collectives": r["collectives"],
                "phases": len([p for p in r["phases"]
                               if p != "(unattributed)"]),
            }))
    for f in findings:
        print(f"hloaudit: {f.render()}", file=sys.stderr)
    n = len(findings)
    print(
        f"hloaudit: {len(rows)} variant(s), "
        + ("clean" if not n else f"{n} finding(s)"),
        file=sys.stderr,
    )
    return 1 if (args.check and findings) else 0


if __name__ == "__main__":
    sys.path.insert(
        0,
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
    )
    sys.exit(main())
