"""hloaudit rule set: defect classes only visible in the compiled artifact.

simlint (tools/simlint/) guards the SOURCE tier; these rules guard what
XLA actually compiled.  The classes — each has cost this repo, or is the
failure mode the ROADMAP's TP-sharding promotion is most likely to ship:

* **A1 host round-trips** — ``infeed``/``outfeed``/``send``/``recv`` or
  a host-callback ``custom-call`` inside the step program serializes the
  whole tick stream on a device->host hop the source never shows
  (a `pure_callback` that survived into a scan body, a debug print left
  in a phase).
* **A2 64-bit floats** — an ``f64``/``c128`` op or a ``convert``
  promotion that survived tracing doubles bandwidth on the carry and
  breaks the f32 parity discipline (simlint R4's compiled-tier twin:
  R4 sees written dtypes, A2 sees *promotion chains* XLA materialized).
* **A3 collectives** — single-device programs must compile to ZERO
  collectives (an accidental ``all-gather`` means a sharding annotation
  leaked); sharded programs may contain only the collectives their
  module DECLARES (``DECLARED_COLLECTIVES``), and none may be
  degenerate (single-participant groups: a collective over a 1-wide
  axis is a silent copy that still pays collective latency).
* **A4 f32 exact-integer bound** — the fused tick's merged reductions
  are bit-stable across backends only while the summed integers stay
  below 2^24 (engine._fused_mips_exact); the audit re-derives that
  bound from the spec so a spec drift that silently voids it fails CI
  here, not in a TPU-vs-CPU parity hunt.
* **A5 manifest drift** — per-variant golden "audit manifests"
  (checked-in JSON) gate ENTRY op/fusion counts with slack and pin the
  attributed PHASE SET exactly: a phase whose ``named_scope`` vanishes
  from the compiled artifact is a silent observability regression even
  when counts stay flat.
* **A6 donation-alias** — every variant that declares donated operands
  (``Variant.donated``, mirroring its ``donate_argnums``) must compile
  to a module whose header actually carries ``input_output_alias``
  entries, and at least the manifest's floor-slack count of them: a
  donation XLA silently declined (a dtype/layout mismatch, a consumed
  operand) doubles the steady-state carry footprint with ZERO source
  diff and no warning.  The inverse drifts too: aliases on a variant
  that declares no donation mean the registry lost track of a
  ``donate_argnums`` site.
* **A7 peak-memory budgets** — per-variant peak device-buffer budgets
  (``compiled.memory_analysis()``: argument + output + temp − aliased
  bytes) pinned in ``tools/op_budget.json``'s ``"peak_bytes"`` table
  with the same ceil-slack/``--write`` discipline as op counts: a
  fusion-boundary change that blows up temp buffers is invisible in op
  counts and source alike, and on real accelerators it is an OOM.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set

from .hlo import COLLECTIVE_OPS, HloModule, Instruction, base_collective

#: Slack over recorded counts before A5 fails (matches the op-budget
#: convention: absolute counts drift a little across XLA versions).
COUNT_SLACK = 1.10

#: f32 integer-exactness bound: sums of integer-valued f32 above this
#: stop being associativity-independent (engine._fused_mips_exact).
EXACT_I32_IN_F32 = 2 ** 24

_HOST_OPS = frozenset({"infeed", "outfeed", "send", "recv",
                       "send-done", "recv-done"})
#: custom-call targets that are host round-trips (python callbacks,
#: host-memory placement) rather than backend compute kernels.
_HOST_TARGET_RE = re.compile(
    r"callback|MoveToHost|MoveToDevice|annotate_device_placement",
    re.I,
)


@dataclasses.dataclass(frozen=True)
class AuditFinding:
    rule: str
    variant: str
    message: str

    def render(self) -> str:
        return f"{self.variant}: {self.rule}: {self.message}"


def _fmt(i: Instruction) -> str:
    where = i.phase and f"phase_{i.phase}" or (i.op_name or i.computation)
    return f"%{i.name} ({i.opcode}) in {where}"


def check_host_transfers(
    mod: HloModule, variant: str
) -> List[AuditFinding]:
    """A1: no host round-trips anywhere in the compiled step program."""
    out = []
    for i in mod.all_instructions():
        if i.opcode in _HOST_OPS:
            out.append(AuditFinding(
                "A1", variant,
                f"host transfer op {_fmt(i)}: the tick stream serializes "
                "on a device->host hop; keep the step device-pure and "
                "read results outside the jit boundary",
            ))
        elif i.opcode == "custom-call":
            tgt = i.custom_call_target or ""
            if _HOST_TARGET_RE.search(tgt) or i.has_side_effect:
                out.append(AuditFinding(
                    "A1", variant,
                    f"host-callback custom-call {_fmt(i)} "
                    f"(target={tgt!r}): a python callback survived into "
                    "the compiled step — remove it or gate it out of the "
                    "audited variants",
                ))
    return out


def check_f64(mod: HloModule, variant: str) -> List[AuditFinding]:
    """A2: no 64-bit floats in the compiled artifact (promotion chains
    included: a ``convert`` to f64 shows up here even when no source
    line ever wrote ``float64``)."""
    out = []
    for i in mod.all_instructions():
        for dt in ("f64", "c128"):
            if i.mentions_dtype(dt):
                kind = (
                    "promotion chain (convert)" if i.opcode == "convert"
                    else "op"
                )
                out.append(AuditFinding(
                    "A2", variant,
                    f"{dt} {kind} {_fmt(i)}: 64-bit floats are banned on "
                    "the device path (2x carry bandwidth, f32 parity "
                    "discipline) — find the promoting input and cast it",
                ))
                break
    return out


def check_collectives(
    mod: HloModule,
    variant: str,
    sharded: bool,
    declared: Optional[Dict[str, Set[str]]] = None,
) -> List[AuditFinding]:
    """A3: collectives only where declared, and never degenerate.

    ``declared`` maps an op_name substring (a scope: ``"shmap_body"``,
    ``"phase_broker"``) to the collective opcodes that scope is allowed
    to emit — the module-level ``DECLARED_COLLECTIVES`` tables next to
    the sharded code are the source of truth.
    """
    declared = declared or {}
    out = []
    for i in mod.all_instructions():
        op = base_collective(i.opcode)
        if op not in COLLECTIVE_OPS:
            continue
        if i.opcode.endswith("-done"):
            continue  # the matching -start op carries the checks
        if not sharded:
            out.append(AuditFinding(
                "A3", variant,
                f"collective {_fmt(i)} in a SINGLE-DEVICE compile: a "
                "sharding annotation leaked into the unsharded step",
            ))
            continue
        ok = any(
            scope in i.op_name and op in ops
            for scope, ops in declared.items()
        )
        if not ok:
            out.append(AuditFinding(
                "A3", variant,
                f"undeclared collective {_fmt(i)}: sharded variants may "
                "only emit the collectives their module declares "
                f"(declared: { {k: sorted(v) for k, v in declared.items()} })",
            ))
        sizes = i.replica_group_sizes()
        if sizes and max(sizes) <= 1:
            out.append(AuditFinding(
                "A3", variant,
                f"degenerate collective {_fmt(i)} (single-participant "
                "replica groups): a collective over a 1-wide axis is a "
                "copy that still pays collective latency",
            ))
    return out


def check_exact_integer_bound(spec, variant: str) -> List[AuditFinding]:
    """A4: the fused tick's merged integer-valued f32 reductions must be
    covered by the static 2^24 bound, re-derived here from spec fields
    (independent of the engine's own gate, so the two can't drift apart
    silently — a mismatch IS the finding)."""
    from fognetsimpp_tpu.core import engine as E

    out = []
    fused = E._fused_ok(spec)
    mips_max = (
        spec.fixed_mips_required
        if spec.fixed_mips_required is not None
        else spec.mips_required_max
    )
    R = min(spec.arrival_cands, spec.max_sends_per_user)
    width = min(spec.window, spec.n_users * R)
    bound = width * max(int(mips_max), 1)
    if fused and bound >= EXACT_I32_IN_F32:
        out.append(AuditFinding(
            "A4", variant,
            f"fused tick engaged but busy-MIPS bound {bound} >= 2^24: "
            "the merged f32 reduction is no longer exact-integer — "
            "engine._fused_mips_exact and the audit's derivation have "
            "drifted apart",
        ))
    if spec.learn_active and spec.task_capacity >= EXACT_I32_IN_F32:
        out.append(AuditFinding(
            "A4", variant,
            f"learn-active spec with task_capacity {spec.task_capacity} "
            ">= 2^24: the bandit f32 credit counters "
            "(learn/rewards.credit_batch) lose integer exactness",
        ))
    return out


def check_manifest(
    mod: HloModule, variant: str, manifest: Optional[dict]
) -> List[AuditFinding]:
    """A5: counts within the golden manifest's slack caps; attributed
    phase set pinned exactly."""
    if manifest is None:
        return [AuditFinding(
            "A5", variant,
            "no checked-in audit manifest — regenerate with "
            "`python -m tools.hloaudit --write` and commit it",
        )]
    out = []
    counts = mod.entry_op_counts()
    for key, cap_key in (("ops", "max_ops"), ("fusions", "max_fusions")):
        if counts[key] > manifest[cap_key]:
            out.append(AuditFinding(
                "A5", variant,
                f"ENTRY {key} regressed: {counts[key]} > manifest cap "
                f"{manifest[cap_key]} (regenerate with --write ONLY if "
                "the growth is justified and reviewed)",
            ))
    got_phases = set(mod.phase_op_counts()) - {"(unattributed)"}
    want_phases = set(manifest.get("phases", {})) - {"(unattributed)"}
    if got_phases != want_phases:
        gone = sorted(want_phases - got_phases)
        new = sorted(got_phases - want_phases)
        out.append(AuditFinding(
            "A5", variant,
            f"attributed phase set drifted (gone: {gone}, new: {new}): "
            "a phase's named_scope vanished from (or appeared in) the "
            "compiled artifact — profiling/telemetry attribution follows "
            "these scopes",
        ))
    return out


def check_donation_alias(
    mod: HloModule,
    variant: str,
    donated: Sequence[int] = (),
    manifest: Optional[dict] = None,
) -> List[AuditFinding]:
    """A6: declared donations must compile to live ``input_output_alias``
    entries (and at least the manifest's floor-slack count of them);
    aliases on a non-donating variant mean the registry lost a
    ``donate_argnums`` site."""
    n = len(mod.input_output_aliases)
    out = []
    if donated and n == 0:
        out.append(AuditFinding(
            "A6", variant,
            f"donate_argnums={tuple(donated)} declared but the compiled "
            "module carries NO input_output_alias entries: XLA silently "
            "declined every donation (dtype/layout mismatch or a "
            "consumed operand) — the steady-state carry is paying double "
            "its footprint",
        ))
    if not donated and n > 0:
        out.append(AuditFinding(
            "A6", variant,
            f"{n} input_output_alias entr{'y' if n == 1 else 'ies'} in a "
            "variant that declares no donation: record the compile's "
            "donate_argnums on the Variant (donated=...) so A6 guards it",
        ))
    if donated and manifest is not None:
        floor = manifest.get("min_aliases")
        if floor is not None and n < floor:
            out.append(AuditFinding(
                "A6", variant,
                f"donated-buffer alias count regressed: {n} < manifest "
                f"floor {floor} (recorded {manifest.get('aliases')}): "
                "some carry leaves stopped aliasing — find the de-aliased "
                "leaf before regenerating with --write",
            ))
    return out


def check_peak_memory(
    mem: Optional[dict], variant: str, budget: Optional[int]
) -> List[AuditFinding]:
    """A7: compiled peak device-buffer bytes within the pinned budget
    (``tools/op_budget.json``'s ``"peak_bytes"`` table).  ``mem`` is the
    ``CompiledArtifact.mem`` dict (None when the backend exposes no
    ``memory_analysis()`` — then the rule skips)."""
    if mem is None:
        return []
    if budget is None:
        return [AuditFinding(
            "A7", variant,
            "no pinned peak-memory budget in tools/op_budget.json "
            "(\"peak_bytes\" table) — regenerate with "
            "`python -m tools.hloaudit --write` and commit it",
        )]
    peak = int(mem["peak_bytes"])
    if peak > budget:
        return [AuditFinding(
            "A7", variant,
            f"peak device-buffer bytes regressed: {peak} > budget "
            f"{budget} (arg={mem.get('arg_bytes')} "
            f"out={mem.get('out_bytes')} temp={mem.get('temp_bytes')} "
            f"alias={mem.get('alias_bytes')}): a fusion-boundary or "
            "carry-layout change grew live memory — on real accelerators "
            "this is an OOM, not a slowdown; regenerate with --write "
            "ONLY if the growth is justified and reviewed",
        )]
    return []


def audit_module(
    mod: HloModule,
    variant: str,
    spec=None,
    sharded: bool = False,
    declared_collectives: Optional[Dict[str, Set[str]]] = None,
    manifest: Optional[dict] = None,
    check_manifest_counts: bool = True,
    donated: Sequence[int] = (),
    mem: Optional[dict] = None,
    peak_budget: Optional[int] = None,
) -> List[AuditFinding]:
    """Run the full rule set over one compiled variant."""
    out: List[AuditFinding] = []
    out += check_host_transfers(mod, variant)
    out += check_f64(mod, variant)
    out += check_collectives(mod, variant, sharded, declared_collectives)
    if spec is not None:
        out += check_exact_integer_bound(spec, variant)
    if check_manifest_counts:
        out += check_manifest(mod, variant, manifest)
    out += check_donation_alias(mod, variant, donated, manifest)
    if check_manifest_counts:
        out += check_peak_memory(mem, variant, peak_budget)
    return out


def render_findings(findings: Sequence[AuditFinding]) -> str:
    return "\n".join(f.render() for f in findings)
