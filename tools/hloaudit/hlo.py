"""The ONE compiled-HLO text parser (ISSUE 7).

Both static gates over compiled artifacts — the op-budget kernel-count
gate (``tools/op_budget.py``) and the hloaudit rule set
(``tools/hloaudit/audit.py``) — read the optimized module text that
``jax.jit(...).lower(...).compile().as_text()`` returns.  They used to
each regex it independently; this module is the single parser both now
share, so a drift in XLA's text format breaks ONE place and every
count/check stays mutually consistent.

The grammar we rely on (stable across the XLA versions this repo has
seen) is::

    HloModule <name>, <attrs>

where ``<attrs>`` may carry the module's donation contract::

    input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {1}, ...) }

mapping an OUTPUT index tuple to a (parameter number, parameter index
tuple, kind) triple — the compiled form of ``jit(..., donate_argnums)``
and the only place a donation that silently stopped aliasing is visible
(hloaudit rule A6).

    %<computation> (<params>) -> <type> {
      [ROOT ]%<instr> = <type> <opcode>(<operands>), <attrs>,
          metadata={op_name="jit(f)/.../phase_spawn/mul" ...}
    }

    ENTRY %main.<n> (<params>) -> <type> { ... }

Phase attribution rides the ``op_name`` metadata: the engine brackets
every phase call in ``jax.named_scope("phase_<name>")``
(core/engine.py's ``_ph`` harness), and XLA threads that scope into each
derived instruction's ``op_name`` — so compiled ops map back to engine
phases with zero engine changes.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, List, Optional

#: ENTRY instructions that are plumbing, not kernels (the op-budget
#: convention: "ops" approximates serialized kernel slots).
TRIVIAL_OPS = ("parameter", "constant", "get-tuple-element", "tuple",
               "bitcast", "copy")

#: Collective opcodes GSPMD/shard_map can emit (async "-start"/"-done"
#: halves normalize onto the base opcode via :func:`base_collective`).
COLLECTIVE_OPS = frozenset({
    "all-reduce", "all-gather", "all-to-all", "reduce-scatter",
    "collective-permute", "collective-broadcast",
})


def base_collective(opcode: str) -> str:
    """Normalize an async collective half (``all-gather-start`` /
    ``all-gather-done``) onto its base opcode."""
    for suffix in ("-start", "-done"):
        if opcode.endswith(suffix):
            return opcode[: -len(suffix)]
    return opcode

# computation headers sit at column 0 (instructions are indented);
# parameter lists may nest parens (tuple-typed params), so only the
# leading name and the trailing brace anchor the match
_COMP_RE = re.compile(r"^(ENTRY\s+)?%([\w.\-]+)\s*\(.*\{\s*$")
# the result type is non-greedy `.+?`, NOT `\S+`: tuple-typed results
# contain spaces (`(f32[8]{0}, u32[], token[]) recv(...)`) and every
# async collective start and send/recv op has one — a `\S+` type would
# silently drop exactly the ops A1/A3 exist to catch
_INSTR_RE = re.compile(
    r"^\s*(ROOT\s+)?%([\w.\-]+)\s*=\s*(.+?)\s+([a-z0-9\-]+)\("
)
_OPNAME_RE = re.compile(r'metadata=\{[^}]*op_name="([^"]*)"')
_TARGET_RE = re.compile(r'custom_call_target="([^"]*)"')
_PHASE_RE = re.compile(r"phase_([A-Za-z0-9_]+)")
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")
# one alias entry inside the module-header input_output_alias={...}
# attribute: `{<out idx>}: (<param>, {<param idx>}, <kind>)`
_ALIAS_ENTRY_RE = re.compile(
    r"\{([\d,\s]*)\}:\s*\((\d+),\s*\{([\d,\s]*)\},\s*"
    r"(may-alias|must-alias)\)"
)


@dataclasses.dataclass(frozen=True)
class Instruction:
    name: str
    result: str            # result type text, e.g. ``f32[8]{0}`` / ``(f32[])``
    opcode: str            # ``fusion``, ``all-gather``, ``custom-call``, ...
    code: str              # the line up to (not including) ``metadata={``
    op_name: str           # metadata op_name ("" when absent)
    computation: str       # owning computation's name
    is_entry: bool         # owning computation is the ENTRY
    lineno: int

    @property
    def phase(self) -> Optional[str]:
        """Engine phase this op attributes to (``phase_<x>`` scope in its
        op_name metadata), else None."""
        m = _PHASE_RE.search(self.op_name)
        return m.group(1) if m else None

    @property
    def custom_call_target(self) -> Optional[str]:
        m = _TARGET_RE.search(self.code)
        return m.group(1) if m else None

    @property
    def has_side_effect(self) -> bool:
        return "custom_call_has_side_effect=true" in self.code

    def replica_group_sizes(self) -> List[int]:
        """Sizes of a collective's replica groups ([] when unannotated)."""
        m = _GROUPS_RE.search(self.code)
        if not m:
            return []
        return [
            len([t for t in g.split(",") if t.strip() != ""])
            for g in re.findall(r"\{([^}]*)\}", m.group(1))
        ]

    def mentions_dtype(self, dtype: str) -> bool:
        """Whether ``dtype`` (e.g. ``f64``) appears in the instruction's
        CODE — result or operand types — ignoring metadata strings."""
        return bool(re.search(rf"\b{re.escape(dtype)}\[", self.code))


@dataclasses.dataclass
class Computation:
    name: str
    is_entry: bool
    instructions: List[Instruction]


@dataclasses.dataclass(frozen=True)
class AliasEntry:
    """One compiled donation: ENTRY output ``output_index`` reuses the
    buffer of parameter ``param_number`` at ``param_index``."""

    output_index: tuple
    param_number: int
    param_index: tuple
    kind: str  # "may-alias" | "must-alias"


@dataclasses.dataclass
class HloModule:
    name: str
    computations: List[Computation]
    input_output_aliases: List[AliasEntry] = dataclasses.field(
        default_factory=list
    )

    def aliased_params(self) -> List[int]:
        """Sorted distinct parameter numbers with at least one aliased
        (donated-and-honoured) buffer."""
        return sorted({e.param_number for e in self.input_output_aliases})

    @property
    def entry(self) -> Computation:
        for c in self.computations:
            if c.is_entry:
                return c
        raise ValueError("no ENTRY computation in HLO text")

    def all_instructions(self) -> Iterable[Instruction]:
        for c in self.computations:
            yield from c.instructions

    # -- the op-budget counting convention ----------------------------

    def entry_op_counts(self) -> Dict[str, int]:
        """{"ops": nontrivial ENTRY instruction count, "fusions": fusion
        count} — the pre-refactor ``tools/op_budget.entry_op_counts``
        convention, except that tuple-typed results (multi-output
        fusions, async collective starts, send/recv) now count: the old
        regex silently dropped them, and the checked-in budgets were
        regenerated under the fixed parser."""
        ops = [
            i for i in self.entry.instructions
            if i.opcode not in TRIVIAL_OPS
        ]
        return {
            "ops": len(ops),
            "fusions": sum(1 for i in ops if i.opcode == "fusion"),
        }

    def phase_op_counts(self, entry_only: bool = False) -> Dict[str, int]:
        """Nontrivial op count per attributed engine phase.

        Ops whose metadata carries no ``phase_*`` scope (glue between
        phases, scan plumbing, XLA-invented ops that lost metadata) land
        under ``"(unattributed)"`` so the rows always sum to the total.
        """
        out: Dict[str, int] = {}
        instrs = (
            self.entry.instructions if entry_only
            else list(self.all_instructions())
        )
        for i in instrs:
            if i.opcode in TRIVIAL_OPS:
                continue
            key = i.phase or "(unattributed)"
            out[key] = out.get(key, 0) + 1
        return dict(sorted(out.items()))


def _parse_aliases(text: str) -> List[AliasEntry]:
    """Extract the module header's ``input_output_alias={...}`` entries
    ([] when the module declares no donation)."""
    header = next(
        (ln for ln in text.splitlines() if ln.startswith("HloModule")), ""
    )
    at = header.find("input_output_alias={")
    if at < 0:
        return []
    # the attribute's value nests braces one level ({out idx} keys):
    # scan to the matching close instead of trusting a regex span
    depth = 0
    start = header.index("{", at)
    end = start
    for end in range(start, len(header)):
        if header[end] == "{":
            depth += 1
        elif header[end] == "}":
            depth -= 1
            if depth == 0:
                break
    body = header[start:end + 1]
    return [
        AliasEntry(
            output_index=tuple(
                int(t) for t in g[0].split(",") if t.strip()
            ),
            param_number=int(g[1]),
            param_index=tuple(
                int(t) for t in g[2].split(",") if t.strip()
            ),
            kind=g[3],
        )
        for g in _ALIAS_ENTRY_RE.findall(body)
    ]


def parse_hlo(text: str) -> HloModule:
    """Parse one optimized-HLO module's ``as_text()`` dump."""
    m = re.search(r"^HloModule\s+([\w.\-]+)", text, re.M)
    mod = HloModule(m.group(1) if m else "?", [],
                    input_output_aliases=_parse_aliases(text))
    cur: Optional[Computation] = None
    for lineno, line in enumerate(text.splitlines(), 1):
        h = _COMP_RE.match(line)
        if h:
            cur = Computation(h.group(2), bool(h.group(1)), [])
            mod.computations.append(cur)
            continue
        if cur is None:
            continue
        if line.startswith("}"):
            cur = None
            continue
        g = _INSTR_RE.match(line)
        if not g:
            continue
        meta_at = line.find("metadata={")
        code = line if meta_at < 0 else line[:meta_at]
        om = _OPNAME_RE.search(line)
        cur.instructions.append(Instruction(
            name=g.group(2),
            result=g.group(3),
            opcode=g.group(4),
            code=code,
            op_name=om.group(1) if om else "",
            computation=cur.name,
            is_entry=cur.is_entry,
            lineno=lineno,
        ))
    if not mod.computations:
        raise ValueError("no computations parsed from HLO text")
    return mod


def entry_op_counts(hlo_text: str) -> Dict[str, int]:
    """Module-level convenience: parse + ENTRY op/fusion counts."""
    return parse_hlo(hlo_text).entry_op_counts()
