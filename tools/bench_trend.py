"""Bench-trend observability: trajectory table + CI regression gate.

    python tools/bench_trend.py             # print the trajectory table
    python tools/bench_trend.py --check     # CI gate (>10% regression fails)
    python tools/bench_trend.py --markdown  # the table BENCHMARKS.md embeds

Parses the committed ``BENCH_r*.json`` / ``MULTICHIP_r*.json`` round
captures into one trajectory per *shape* (metric, backend, users, fogs,
dt, window, policy — rounds that changed the measured configuration are
different trajectories, so a dt=1ms round is never compared against a
windowed dt=5ms round).  ``--check`` fails when the LATEST round at a
shape regressed more than :data:`TOLERANCE` vs the best prior round at
the same shape — the perf story's ratchet, wired into
``tools/ci_check.sh`` so a throughput loss is a red build, not a line
in a markdown file nobody re-reads.  Compile seconds ride along
(``compile_s``): the streaming serving mode's blocker is tracked in the
same table.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

#: Latest round may lose at most this fraction vs the best prior round
#: at the same shape.
TOLERANCE = 0.10

#: Telemetry-on wall overhead bar (ISSUE 11): any capture recording a
#: ``telemetry_overhead`` ratio (telemetry-on wall / off wall,
#: interleaved A/B — bench.py BENCH_TP_TELEMETRY) above this fails
#: --check.  The same <= 10% bar every observability plane has shipped
#: under since PR 4.
OVERHEAD_BAR = 1.10

#: Warm-reconfig bar (ISSUE 13): a capture recording both ``compile_s``
#: and ``reconfig_s`` (bench.py --reconfig) must show the warm knob
#: tweak >= this many times faster than the cold compile, or the
#: dynamic-operand promotion has rotted back into a recompile.
RECONFIG_SPEEDUP_BAR = 10.0

#: And the warm reconfig itself may regress at most TOLERANCE vs the
#: best (lowest) prior ``reconfig_s`` at the same shape — the
#: lower-is-better twin of the throughput ratchet.

_ROUND_RE = re.compile(r"_r(\d+)\.json$")

#: Fields that define a comparable measurement shape.  Missing fields
#: (older capture formats) stay None and form their own shape — an old
#: round that did not record dt is never silently compared to a new one.
SHAPE_FIELDS = (
    "metric", "backend", "n_users", "n_fogs", "dt", "arrival_window",
    "policy", "n_devices", "n_replicas", "tp_shards", "chaos",
    "n_brokers", "tp_window",
)

#: Shape values a capture that predates the field is known to have run
#: with.  bench.py only started recording ``policy`` in r6, but every
#: committed BENCH_r*/MULTICHIP_r* round ran the BENCH_POLICY default
#: (min_busy) — without this backfill the first policy-recording
#: capture would form a fresh one-entry trajectory and the regression
#: gate would silently stop comparing against all prior history.
SHAPE_DEFAULTS = {
    "policy": "min_busy",
    # TP task-table sharding arrived in r6 (ISSUE 9): every prior
    # capture ran unsharded single worlds or replica fleets — backfill
    # None so the r6 TP captures form their own trajectory and the
    # replica-fleet/single-chip histories keep comparing like-for-like.
    "tp_shards": None,
    # chaos fault injection arrived with ISSUE 12: every prior capture
    # ran the happy path — backfill None so hostile-world rows
    # (bench.py --chaos records a "chaos" string) form their own
    # trajectory instead of regressing the happy-path ratchet.
    "chaos": None,
    # the federated multi-broker hierarchy arrived with ISSUE 14: every
    # prior capture ran the single base broker — backfill None so
    # federation rows (bench.py --hier records n_brokers) ratchet as
    # their own trajectories.
    "n_brokers": None,
    # windowed TP (ISSUE 18: distributed K-window selection) — every
    # prior TP capture ran the no-window exchange ring; backfill None
    # so windowed rows (bench.py BENCH_TP_ARRIVAL_WINDOW records
    # tp_window) ratchet as their own trajectories.
    "tp_window": None,
}


def _round_of(path: str) -> Optional[int]:
    m = _ROUND_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def load_rounds(root: str = ".") -> List[Dict]:
    """All parseable round captures, sorted by round number.

    A capture without a ``parsed`` metric dict (e.g. the dryrun-only
    MULTICHIP rounds before ISSUE 3, or a failed capture) is skipped —
    absence of a number is not a regression.
    """
    rows = []
    for pattern in ("BENCH_r*.json", "MULTICHIP_r*.json"):
        for path in glob.glob(os.path.join(root, pattern)):
            rnd = _round_of(path)
            if rnd is None:
                continue
            try:
                with open(path) as f:
                    d = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            # a wrapper normally carries ONE parsed capture; since
            # ISSUE 20 a round may ride along extra captures taken the
            # same session (``extra_parsed``: the --reconfig --tp and
            # --reconfig --fleet rows of MULTICHIP_r09) — each becomes
            # its own trajectory row at its own shape
            blocks = [
                p
                for p in [d.get("parsed"), *(d.get("extra_parsed") or [])]
                if isinstance(p, dict) and "value" in p
            ]
            for parsed in blocks:
                rows.append(
                    _row_of(rnd, path, parsed)
                )
    rows.sort(key=lambda r: (r["file"].split("_r")[0], r["round"]))
    return rows


def _row_of(rnd: int, path: str, parsed: Dict) -> Dict:
    return (
                {
                    "round": rnd,
                    "file": os.path.basename(path),
                    "shape": tuple(
                        (k, parsed.get(k, SHAPE_DEFAULTS.get(k)))
                        for k in SHAPE_FIELDS
                    ),
                    "value": float(parsed["value"]),
                    "unit": parsed.get("unit", ""),
                    "compile_s": parsed.get("compile_s"),
                    "reconfig_s": parsed.get("reconfig_s"),
                    # sharded warm-reconfig columns (ISSUE 20,
                    # bench.py --reconfig --tp / --reconfig --fleet):
                    # the promoted TP tick / fleet scan retune walls,
                    # gated like-for-like with the ISSUE 13 row
                    "tp_reconfig_s": parsed.get("tp_reconfig_s"),
                    "fleet_reconfig_s": parsed.get("fleet_reconfig_s"),
                    "reconfig_compile_events": parsed.get(
                        "reconfig_compile_events"
                    ),
                    "program_cache_misses_delta": parsed.get(
                        "program_cache_misses_delta"
                    ),
                    "telemetry_overhead": parsed.get("telemetry_overhead"),
                    # journey-ring overhead (ISSUE 15): interleaved
                    # off/on A/B recorded by bench.py BENCH_JOURNEYS=1
                    "journey_overhead": parsed.get("journey_overhead"),
                    # TP journey-ring overhead (ISSUE 19): the same A/B
                    # under bench.py --tp with BENCH_TP_JOURNEYS=1
                    "tp_journey_overhead": parsed.get(
                        "tp_journey_overhead"
                    ),
                    # digital-twin doors (ISSUE 17, bench.py --twin):
                    # pre-twin captures backfill None via .get
                    # per-hop TP exchange-ring payload (ISSUE 18):
                    # pre-windowed captures backfill None via .get
                    "exchange_payload_bytes": parsed.get(
                        "exchange_payload_bytes"
                    ),
                    "ingest_rate": parsed.get("ingest_rate"),
                    "whatif_latency_s": parsed.get("whatif_latency_s"),
                    "whatif_compile_events": parsed.get(
                        "whatif_compile_events"
                    ),
                    "parsed": parsed,
                }
    )


def _shape_str(shape: Tuple) -> str:
    d = dict(shape)
    bits = [str(d.get("metric") or "?"), str(d.get("backend") or "?")]
    for k in ("n_users", "n_fogs", "dt", "arrival_window", "n_devices",
              "tp_shards", "chaos", "n_brokers", "tp_window"):
        if d.get(k) is not None:
            bits.append(f"{k}={d[k]}")
    return " ".join(bits)


def trajectories(rows: List[Dict]) -> Dict[Tuple, List[Dict]]:
    by_shape: Dict[Tuple, List[Dict]] = {}
    for r in rows:
        by_shape.setdefault(r["shape"], []).append(r)
    for v in by_shape.values():
        v.sort(key=lambda r: r["round"])
    return by_shape


def check(rows: List[Dict], tolerance: float = TOLERANCE) -> List[str]:
    """Regression findings (empty = green)."""
    problems = []
    # telemetry/journey-overhead bars: gate every capture that measured
    # one (the same <= 10% bar every observability plane ships under)
    for r in rows:
        for field, what in (
            ("telemetry_overhead", "telemetry-on"),
            ("journey_overhead", "journey-rings-on"),
            ("tp_journey_overhead", "TP-journey-rings-on"),
        ):
            oh = r.get(field)
            if oh is not None and float(oh) > OVERHEAD_BAR:
                problems.append(
                    f"{r['file']}: {what} overhead ratio {oh:.3f} "
                    f"exceeds the {OVERHEAD_BAR:.2f} bar (interleaved "
                    "off/on A/B; the observability planes ship under "
                    "<=10%)"
                )
        # warm-reconfig bars (ISSUE 13): every capture that measured a
        # reconfig_s must (a) have compiled NOTHING during the warm
        # runs and (b) beat the cold compile by RECONFIG_SPEEDUP_BAR
        # the sharded rows (ISSUE 20) mirror reconfig_s into their own
        # tp_reconfig_s / fleet_reconfig_s column — gate whichever
        # columns the capture recorded, once each (a sharded capture
        # carries both the generic and the named column at one value)
        rc_cols = [
            ("reconfig_s", "re-configure"),
            ("tp_reconfig_s", "TP re-configure"),
            ("fleet_reconfig_s", "fleet re-configure"),
        ]
        gated_vals = set()
        for field, what in rc_cols:
            rc = r.get(field)
            if rc is None or float(rc) in gated_vals:
                continue
            gated_vals.add(float(rc))
            ev = r.get("reconfig_compile_events")
            if ev:
                problems.append(
                    f"{r['file']}: {ev:.0f} compile event(s) during the "
                    f"warm {what} runs — the dynamic-operand "
                    "promotion is recompiling (compile_stats delta "
                    "must be 0)"
                )
            comp = r.get("compile_s")
            if comp is not None and float(rc) > 0 and (
                float(comp) / float(rc) < RECONFIG_SPEEDUP_BAR
            ):
                problems.append(
                    f"{r['file']}: warm {what} {float(rc):.3f}s is "
                    f"only {float(comp) / float(rc):.1f}x faster than "
                    f"the {float(comp):.1f}s cold compile (bar: "
                    f">= {RECONFIG_SPEEDUP_BAR:.0f}x)"
                )
        # sharded program-cache misses (ISSUE 20): a warm retune that
        # missed the TP/fleet program cache recompiled even if the
        # compile-event listener missed it — delta must be 0
        pcm = r.get("program_cache_misses_delta")
        if pcm:
            problems.append(
                f"{r['file']}: {float(pcm):.0f} program-cache miss(es) "
                "during the warm sharded re-configure runs — the "
                "promoted runner re-keyed its program (delta must "
                "be 0)"
            )
        # warm what-if bar (ISSUE 17): every capture that measured a
        # whatif_latency_s must have compiled NOTHING during the warm
        # asks — the grid rides the live session's fork program
        if r.get("whatif_latency_s") is not None:
            wev = r.get("whatif_compile_events")
            if wev:
                problems.append(
                    f"{r['file']}: {float(wev):.0f} compile event(s) "
                    "during the warm what-if asks — the fork grid is "
                    "recompiling instead of reusing the live session's "
                    "program (compile_stats delta must be 0)"
                )
    # per-hop exchange-payload ratchet (ISSUE 18): at a fixed shape the
    # ring payload is a program property, not a measurement — the
    # latest capture may never carry MORE bytes per hop than the best
    # (lowest) prior round at the same shape (no tolerance)
    for shape, traj in trajectories(rows).items():
        seq = [
            r for r in traj
            if r.get("exchange_payload_bytes") is not None
        ]
        if len(seq) < 2:
            continue
        latest = seq[-1]
        best_prior = min(
            seq[:-1], key=lambda r: float(r["exchange_payload_bytes"])
        )
        if (float(latest["exchange_payload_bytes"])
                > float(best_prior["exchange_payload_bytes"])):
            problems.append(
                f"{latest['file']}: per-hop exchange payload "
                f"{float(latest['exchange_payload_bytes']):.0f} B grew "
                f"vs best prior "
                f"{float(best_prior['exchange_payload_bytes']):.0f} B "
                f"({best_prior['file']}) at shape [{_shape_str(shape)}] "
                "— the exchange ring widened at an unchanged shape"
            )
    # lower-is-better ratchet on reconfig_s per shape
    for shape, traj in trajectories(rows).items():
        seq = [r for r in traj if r.get("reconfig_s") is not None]
        if len(seq) < 2:
            continue
        latest = seq[-1]
        best_prior = min(seq[:-1], key=lambda r: float(r["reconfig_s"]))
        ceil_ = float(best_prior["reconfig_s"]) * (1.0 + tolerance)
        if float(latest["reconfig_s"]) > ceil_:
            problems.append(
                f"{latest['file']}: reconfig_s "
                f"{float(latest['reconfig_s']):.3f} regressed vs best "
                f"prior {float(best_prior['reconfig_s']):.3f} "
                f"({best_prior['file']}) at shape [{_shape_str(shape)}] "
                f"(tolerance {tolerance * 100:.0f}%)"
            )
    for shape, traj in trajectories(rows).items():
        if len(traj) < 2:
            continue
        latest = traj[-1]
        best_prior = max(traj[:-1], key=lambda r: r["value"])
        floor = best_prior["value"] * (1.0 - tolerance)
        if latest["value"] < floor:
            problems.append(
                f"{latest['file']}: {latest['value']:.1f} is "
                f"{(1 - latest['value'] / best_prior['value']) * 100:.1f}% "
                f"below best prior {best_prior['value']:.1f} "
                f"({best_prior['file']}) at shape [{_shape_str(shape)}] "
                f"(tolerance {tolerance * 100:.0f}%)"
            )
    return problems


def table(rows: List[Dict], markdown: bool = False) -> str:
    """The trajectory table (``--markdown`` = the BENCHMARKS.md embed)."""
    out = []
    if markdown:
        out.append(
            "| round | file | value | vs prior | compile_s | "
            "reconfig_s |"
        )
        out.append("|---|---|---|---|---|---|")
    for shape, traj in sorted(
        trajectories(rows).items(), key=lambda kv: _shape_str(kv[0])
    ):
        if not markdown:
            out.append(f"# shape: {_shape_str(shape)}")
        prev = None
        for r in traj:
            ratio = (
                f"{r['value'] / prev:.2f}x" if prev else "—"
            )
            comp = (
                f"{r['compile_s']:.1f}" if r["compile_s"] is not None
                else "—"
            )
            rc = (
                f"{r['reconfig_s']:.3f}"
                if r.get("reconfig_s") is not None
                else "—"
            )
            if markdown:
                out.append(
                    f"| r{r['round']} | {r['file']} | "
                    f"{r['value']:,.0f} | {ratio} | {comp} | {rc} |"
                )
            else:
                oh = (
                    f", telemetry x{r['telemetry_overhead']:.3f}"
                    if r.get("telemetry_overhead") is not None
                    else ""
                )
                oh += (
                    f", journeys x{r['journey_overhead']:.3f}"
                    if r.get("journey_overhead") is not None
                    else ""
                )
                oh += (
                    f", tp-journeys x{r['tp_journey_overhead']:.3f}"
                    if r.get("tp_journey_overhead") is not None
                    else ""
                )
                # sharded rows label their column (ISSUE 20); the
                # generic label covers the single-device ISSUE 13 row
                if r.get("tp_reconfig_s") is not None:
                    rcs = f", tp-reconfig {rc}s"
                elif r.get("fleet_reconfig_s") is not None:
                    rcs = f", fleet-reconfig {rc}s"
                elif r.get("reconfig_s") is not None:
                    rcs = f", reconfig {rc}s"
                else:
                    rcs = ""
                rcs += (
                    f", whatif {r['whatif_latency_s']:.3f}s"
                    if r.get("whatif_latency_s") is not None
                    else ""
                )
                rcs += (
                    f", payload {int(r['exchange_payload_bytes']):,}B/hop"
                    if r.get("exchange_payload_bytes") is not None
                    else ""
                )
                out.append(
                    f"  r{r['round']:<2} {r['value']:>14,.1f} {r['unit']}"
                    f"  ({ratio}, compile {comp}s{oh}{rcs})  {r['file']}"
                )
            prev = r["value"]
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/bench_trend.py",
        description="bench trajectory table + >10%% regression CI gate",
    )
    ap.add_argument("--root", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".."
    ))
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on a >tolerance regression vs the best "
                    "prior round at the same shape")
    ap.add_argument("--markdown", action="store_true",
                    help="emit the markdown table BENCHMARKS.md embeds")
    ap.add_argument("--tolerance", type=float, default=TOLERANCE)
    args = ap.parse_args(argv)
    rows = load_rounds(args.root)
    if not rows:
        print("bench_trend: no parseable BENCH_r*/MULTICHIP_r* captures",
              file=sys.stderr)
        return 0 if args.check else 2
    if args.check:
        problems = check(rows, args.tolerance)
        for p in problems:
            print(f"REGRESSION: {p}", file=sys.stderr)
        if problems:
            return 1
        shapes = len(trajectories(rows))
        print(
            f"bench_trend ok: {len(rows)} captures, {shapes} shape(s), "
            f"no regression > {args.tolerance * 100:.0f}%"
        )
        return 0
    print(table(rows, markdown=args.markdown))
    return 0


if __name__ == "__main__":
    sys.exit(main())
