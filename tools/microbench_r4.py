"""r4 scratch microbench: scan floor + _compact variants on the TPU.

Tunnel-aware methodology (see bench.py): a single blocking fetch costs
~95 ms flat on the axon runtime, so every measurement enqueues a pipeline
of runs and syncs once; reported time = (wall - one fetch) / work-items.
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from fognetsimpp_tpu.compile_cache import enable_compile_cache
import fognetsimpp_tpu.core.engine as E
from fognetsimpp_tpu.scenarios import smoke

PIPE = 5


def timed_pipeline(fn, args_list, n_items):
    """Enqueue len(args_list) calls, fetch once; returns s/item."""
    np.asarray(jax.tree_util.tree_leaves(fn(args_list[0]))[0])  # warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        outs = [fn(a) for a in args_list]
        for o in outs:
            np.asarray(jax.tree_util.tree_leaves(o)[0])
        best = min(best, time.perf_counter() - t0)
    return best / n_items


def main():
    enable_compile_cache()
    print("backend", jax.default_backend())
    spec, state, net, bounds = smoke.build(
        n_users=10_000, n_fogs=32,
        fog_mips=(1000.0, 2000.0, 3000.0, 4000.0),
        send_interval=0.0025, horizon=0.1, dt=1e-3,
        max_sends_per_user=44, arrival_window=4096,
        queue_capacity=128, start_time_max=0.025,
    )
    N_TICKS = 100
    keys = [jax.random.PRNGKey(i) for i in range(PIPE)]
    states = [state.replace(key=k) for k in keys]

    # (a) identity-body scan floor, metrics-only output
    @jax.jit
    def floor_scan(s):
        def body(c, _):
            return c.replace(tick=c.tick + 1), None
        f, _ = jax.lax.scan(body, s, None, length=N_TICKS)
        return f.metrics

    ms = timed_pipeline(floor_scan, states, PIPE * N_TICKS) * 1e3
    print(f"identity-body scan:   {ms:8.4f} ms/tick")

    # (a2) full step, metrics-only output (bench pattern)
    @jax.jit
    def full_scan(s):
        f, _ = E.run(spec, s, net, bounds, n_ticks=N_TICKS)
        return f.metrics

    ms = timed_pipeline(full_scan, states, PIPE * N_TICKS) * 1e3
    print(f"full step (metrics):  {ms:8.4f} ms/tick")

    # (b) compaction variants: R rolled invocations inside one jit, so the
    # per-call work is real and the fetch is amortized over R x PIPE
    T = spec.task_capacity
    R = 50

    def make_loop(comp, K):
        @jax.jit
        def go(m0):
            def body(i, acc):
                m = jnp.roll(m0, i * 97)
                idx, idxc, valid = comp(m, K)
                return acc + idx[0] + jnp.sum(valid.astype(jnp.int32))
            return jax.lax.fori_loop(0, R, body, jnp.zeros((), jnp.int32))
        return go

    def comp_current(m, K):
        return E._compact(m, K, T)

    def comp_topk(m, K):
        idxs = jnp.arange(T, dtype=jnp.int32)
        keyv = jnp.where(m, T - idxs, 0)
        vals, _ = jax.lax.top_k(keyv, K)
        valid = vals > 0
        idx = jnp.where(valid, T - vals, T)
        return idx, jnp.minimum(idx, T - 1), valid

    def comp_cumsum_scatter(m, K):
        pos = jnp.cumsum(m.astype(jnp.int32)) - 1
        tgt = jnp.where(m & (pos < K), pos, K)
        idx = jnp.full((K,), T, jnp.int32).at[tgt].set(
            jnp.arange(T, dtype=jnp.int32), mode="drop"
        )
        valid = idx < T
        return idx, jnp.minimum(idx, T - 1), valid

    key = jax.random.PRNGKey(0)
    for K, dens in ((4096, 4000), (40960, 40000)):
        mask = jax.random.uniform(key, (T,)) < (dens / T)
        masks = [jnp.roll(mask, i) for i in range(PIPE)]
        # correctness vs current
        i1, _, v1 = comp_current(mask, K)
        for name, comp in [("2-level", comp_current), ("top_k", comp_topk),
                           ("cum+scat", comp_cumsum_scatter)]:
            i2, _, v2 = comp(mask, K)
            ok = bool(jnp.all(i1 == i2) & jnp.all(v1 == v2))
            ms = timed_pipeline(make_loop(comp, K), masks, PIPE * R) * 1e3
            print(f"compact K={K:6d} {name:9s} {ms:8.4f} ms  match={ok}")


if __name__ == "__main__":
    main()
