"""Bisect _fog_arrivals_tail cost on the TPU (r5)."""
import os, sys, dataclasses
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax, jax.numpy as jnp
from fognetsimpp_tpu.compile_cache import enable_compile_cache
import fognetsimpp_tpu.core.engine as E
from fognetsimpp_tpu.ops.queues import NO_TASK, batched_enqueue, plan_arrivals
from fognetsimpp_tpu.spec import Stage
from tools.profile_tick import build, time_scan

def make_tail(do_assign, do_queue, do_busy, do_bufm):
    def tail(spec, state, cache, buf, tasks, fogs, idx, idxc, valid,
             fog_g, t_af_g, mips_g, user_g, n_fast, n_fast_f, **_kw):
        T, F, K = spec.task_capacity, spec.n_fogs, spec.window
        U = spec.n_users
        i32 = jnp.int32
        fog_alive = state.nodes.alive[U:U+F]
        fog_gc = jnp.clip(fog_g, 0, F - 1)
        dead_dst = valid & ~fog_alive[fog_gc]
        arr = valid & ~dead_dst
        svc_g = E._svc_time(spec, mips_g, fogs.mips[fog_gc])
        per_fog_arr = E._per_fog(arr, fog_g, F)
        if do_busy:
            add_busy = jnp.sum(jnp.where(per_fog_arr, svc_g[None,:], 0.0), axis=1)
            fogs = fogs.replace(busy_time=fogs.busy_time + add_busy)
        idle = fogs.current_task == NO_TASK
        plan = plan_arrivals(arr, fog_g, t_af_g, F, idle, per_fog=per_fog_arr)
        a_pos = plan.assign_task
        assigned = a_pos != NO_TASK
        a_posc = jnp.clip(a_pos, 0, K - 1)
        a_task = jnp.where(assigned, idx[a_posc], NO_TASK)
        a_taskc = jnp.clip(a_task, 0, T - 1)
        if do_assign:
            t_start = jnp.maximum(tasks.t_at_fog[a_taskc], fogs.free_since)
            svc_a = E._svc_time(spec, tasks.mips_req[a_taskc], fogs.mips)
            d_fb = cache.d2b[U:U+F]
            d_bu_a = cache.d2b[a_taskc // spec.max_sends_per_user]
            t_ack5 = t_start + d_fb + d_bu_a
            scat_a = jnp.where(assigned, a_task, T)
            tasks = tasks.replace(
                stage=tasks.stage.at[scat_a].set(jnp.int8(int(Stage.RUNNING)), mode="drop"),
                t_service_start=tasks.t_service_start.at[scat_a].set(
                    jnp.where(assigned, t_start, 0), mode="drop"),
                t_ack5=tasks.t_ack5.at[scat_a].set(jnp.where(assigned, t_ack5, 0), mode="drop"),
            )
            fogs = fogs.replace(
                current_task=jnp.where(assigned, a_task, fogs.current_task),
                busy_until=jnp.where(assigned, t_start + svc_a, fogs.busy_until),
            )
        if do_queue:
            d_fb = cache.d2b[U:U+F]
            got_head = assigned[fog_gc] & idle[fog_gc]
            eff_rank = jnp.where(arr, plan.rank - got_head.astype(i32), -1)
            to_queue = arr & (eff_rank >= 0) & (idx != a_task[fog_gc])
            queue, q_len, enq_ok, dropped = batched_enqueue(
                fogs.queue, fogs.q_head, fogs.q_len, to_queue, fog_g, eff_rank, idx)
            d_bu_q = cache.d2b[user_g]
            d_fb_q = d_fb[fog_gc]
            assigned_row = arr & (idx == a_task[fog_gc])
            stage_k = jnp.where(enq_ok, jnp.int8(int(Stage.QUEUED)),
                jnp.where((to_queue & ~enq_ok) | dead_dst, jnp.int8(int(Stage.DROPPED)),
                jnp.where(assigned_row, jnp.int8(int(Stage.RUNNING)),
                          jnp.int8(int(Stage.TASK_INFLIGHT)))))
            tasks = tasks.replace(
                stage=tasks.stage.at[idx].set(stage_k, mode="drop"),
                t_q_enter=tasks.t_q_enter.at[idx].set(
                    jnp.where(enq_ok, t_af_g, jnp.inf), mode="drop"),
                t_ack4_queued=tasks.t_ack4_queued.at[idx].set(
                    jnp.where(enq_ok, t_af_g + d_fb_q + d_bu_q, jnp.inf), mode="drop"),
            )
            fogs = fogs.replace(queue=queue, q_len=q_len, q_drops=fogs.q_drops + dropped)
        if do_bufm:
            acked = (assigned[fog_gc] & (idx == a_task[fog_gc])) & arr
            sums = jnp.sum(jnp.stack([dead_dst, dead_dst, acked]).astype(i32), axis=1)
            metrics = state.metrics.replace(
                n_dropped=state.metrics.n_dropped + sums[0] + n_fast)
            arr_per_fog = jnp.sum(per_fog_arr, axis=1, dtype=i32) + n_fast_f
            buf = buf._replace(
                tx_f=buf.tx_f + arr_per_fog, rx_f=buf.rx_f + arr_per_fog,
                tx_b=buf.tx_b + sums[2], rx_b=buf.rx_b + sums[2],
                rx_u=buf.rx_u.at[user_g].add(acked.astype(i32), mode="drop"),
            )
            state = state.replace(metrics=metrics)
        return state.replace(tasks=tasks, fogs=fogs), buf
    return tail

def main():
    enable_compile_cache()
    n_users = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000
    spec, state, net, bounds = build(n_users, 1e-3)
    # the bisection targets the r5 reference tail: pin the fused
    # front-end off so the monkeypatched tails actually run
    spec = dataclasses.replace(spec, arrival_window=4096,
                               fused_slots=False)
    base, c = time_scan(spec, state, net, bounds)
    print(f"full: {base:7.3f} ms/tick (compile {c:.0f}s)")
    orig = E._fog_arrivals_tail
    for name, args in [
        ("assign+queue+busy+buf", (1,1,1,1)),
        ("no buf/metrics", (1,1,1,0)),
        ("no queue-branch", (1,0,1,1)),
        ("no assign-branch", (0,1,1,1)),
        ("no busy-add", (1,1,0,1)),
        ("busy only", (0,0,1,0)),
    ]:
        E._fog_arrivals_tail = make_tail(*args)
        try:
            ms, _ = time_scan(spec, state, net, bounds)
        finally:
            E._fog_arrivals_tail = orig
        print(f"- {name:22s} {ms:7.3f} ms/tick  marginal {base-ms:+.3f}")

if __name__ == "__main__":
    main()
