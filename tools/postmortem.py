"""Flight-recorder post-mortem inspector.

    python tools/postmortem.py DUMP.json            # summarize one dump
    python tools/postmortem.py --diff A.json B.json # field-level diff

A dump is the manifest :class:`fognetsimpp_tpu.telemetry.live
.FlightRecorder` writes on NaN / SLO breach / watchdog anomaly / crash:
the bounded ring of recent reservoir rows + per-chunk state hashes, the
watchdog state, compile-cache stats, the spec and (when the world was
at hand) a Perfetto trace twin.  The inspector answers the two
first-response questions without opening a notebook: *what tripped*
(reason, anomalies, nonfinite leaves) and *when the runs diverged*
(``--diff`` walks the two rings and reports the first chunk whose state
hashes disagree).
"""
from __future__ import annotations

import argparse
import json
import struct
import sys
from typing import Dict, List, Optional

#: Journey event-code names (ISSUE 15) — resolved from the package
#: when importable (so a renumbered/extended JourneyEvent can never
#: drift from this table), with a literal fallback so the inspector
#: stays a stdlib-only tool (a post-mortem box may not have jax at
#: hand; importing journeys pulls in jax).
try:
    from fognetsimpp_tpu.telemetry.journeys import (
        EVENT_NAMES as _JOURNEY_NAMES,
    )
except Exception:
    _JOURNEY_NAMES = {
        1: "spawn", 2: "reoffload", 3: "migrate", 4: "decide",
        5: "local_run", 6: "enqueue", 7: "svc_start", 8: "done",
        9: "no_resource", 10: "rejected", 11: "dropped", 12: "lost",
        13: "crash_lost", 14: "retry_exhaust", 15: "hop_exhausted",
        16: "defer",
    }


def _bits_to_time(bits: int) -> float:
    """i32 bit pattern -> the exact f32 event time it encodes."""
    return struct.unpack("<f", struct.pack("<i", int(bits)))[0]


def _decode_journey(snap: Dict, task_id: Optional[int] = None) -> List[Dict]:
    """Decode a manifest's raw ring snapshot (``journeys.rings``) into
    per-task event chains — drop-oldest wrap resolved, stdlib only.
    ``task_id`` filters to one task (the ``--task`` flag)."""
    out = []
    tasks = snap.get("task") or []
    cursor = snap.get("cursor") or []
    ring = snap.get("ring") or []
    # owning-shard column: written by TP bundles since ISSUE 19;
    # pre-TP bundles simply lack the key (the .get-safe contract)
    shard = snap.get("shard") or []
    for j, task in enumerate(tasks):
        if task_id is not None and int(task) != int(task_id):
            continue
        n = int(cursor[j]) if j < len(cursor) else 0
        rows = ring[j] if j < len(ring) else []
        R = len(rows)
        order = range(n) if n <= R else [(n + k) % R for k in range(R)]
        out.append(
            {
                "task": int(task),
                "shard": int(shard[j]) if j < len(shard) else None,
                "events_total": n,
                "dropped": max(0, n - R) if R else n,
                "events": [
                    {
                        "t": _bits_to_time(rows[k][0]),
                        "name": _JOURNEY_NAMES.get(
                            int(rows[k][1]), f"code{rows[k][1]}"
                        ),
                        "a": int(rows[k][2]),
                        "b": int(rows[k][3]),
                    }
                    for k in order
                ],
            }
        )
    return out


def load(path: str) -> Dict:
    """Load a dump with every post-PR-6 manifest field OPTIONAL.

    Older bundles predate fields newer builds always write
    (``compile_cache`` arrived with the compile-latency observability,
    ``shard_hashes`` with the sharded health plane) — an inspector that
    crashes on its own older output is useless exactly when a
    post-mortem matters, so missing fields default instead of raising
    (tests/test_tp_telemetry.py pins an old-style bundle).
    """
    with open(path) as f:
        d = json.load(f)
    d.setdefault("reason", "unknown")
    d.setdefault("ticks_done", 0)
    d.setdefault("detail", {})
    d.setdefault("ring", [])
    d.setdefault("compile_cache", {})
    d.setdefault("watchdog", {})
    for entry in d["ring"]:
        if isinstance(entry, dict):
            entry.setdefault("ticks_done", 0)
    return d


def _fmt_z(z) -> str:
    return f"{z:.2f}" if isinstance(z, (int, float)) else "?"


def summarize(d: Dict) -> List[str]:
    out = [
        f"reason:      {d.get('reason')}",
        f"recorded_at: {d.get('recorded_at')}",
        f"ticks_done:  {d.get('ticks_done')}",
    ]
    detail = d.get("detail") or {}
    for k, v in detail.items():
        out.append(f"detail.{k}: {json.dumps(v)[:200]}")
    wd = d.get("watchdog") or {}
    anomalies = wd.get("anomalies") or []
    out.append(f"anomalies:   {len(anomalies)}")
    for a in anomalies[-5:]:
        kind = f" [{a['kind']}]" if a.get("kind") else ""
        out.append(
            f"  - {a.get('signal')} z={_fmt_z(a.get('z'))}{kind} "
            f"value={a.get('value')} at tick {a.get('ticks_done')}"
        )
    if wd.get("last_signals"):
        out.append(f"signals:     {json.dumps(wd['last_signals'])}")
    hist = d.get("hist") or {}
    if hist:
        out.append(
            f"latency:     n={hist.get('count')} "
            f"quantiles_ms={json.dumps(hist.get('quantiles_ms'))}"
        )
    chaos = d.get("chaos") or {}
    if chaos:
        # chaos manifests (ISSUE 12): .get-safe like every other
        # optional field — pre-chaos bundles simply skip the line
        out.append(
            "chaos:       "
            f"mode={chaos.get('mode')} crashes={chaos.get('crashes')} "
            f"lost_crash={chaos.get('lost_crash')} "
            f"reoffloaded={chaos.get('reoffloaded')} "
            f"retry_exhausted={chaos.get('retry_exhausted')}"
        )
    journeys = d.get("journeys") or {}
    if journeys:
        # journey rings (ISSUE 15): .get-safe like every other optional
        # field — pre-journey bundles simply skip the section
        out.append(
            "journeys:    "
            f"{journeys.get('sampled')} sampled task(s), "
            f"dropped={journeys.get('dropped_total')}"
        )
        for chain in _decode_journey(journeys.get("rings") or {})[:3]:
            tail = chain["events"][-3:]
            out.append(
                f"  - task {chain['task']}: {chain['events_total']} "
                "event(s), last "
                + " -> ".join(
                    f"{e['name']}@{e['t']:.4f}" for e in tail
                )
            )
    ing = d.get("ingest_summary") or {}
    if ing:
        # twin ingestion roll-up (ISSUE 17): .get-safe like every other
        # optional field — pre-twin bundles simply skip the line
        out.append(
            "ingest:      "
            f"depth={ing.get('depth')}/{ing.get('capacity')} "
            f"accepted={ing.get('accepted')} "
            f"dropped={ing.get('dropped')} "
            f"injected={ing.get('injected')} "
            f"rejected={ing.get('rejected')}"
        )
    cc = d.get("compile_cache") or {}
    if cc:
        out.append(
            "compile:     "
            f"hits={cc.get('cache_hits')} misses={cc.get('cache_misses')} "
            f"compiles={cc.get('compiles')} "
            f"compile_s_total={cc.get('compile_s_total')}"
        )
    ring = d.get("ring") or []
    out.append(f"ring:        {len(ring)} chunk(s)")
    if ring:
        first, last = ring[0], ring[-1]
        shards = last.get("shard_hashes") or []
        out.append(
            f"  ticks {first.get('ticks_done')} .. "
            f"{last.get('ticks_done')}, "
            f"hashes {'present' if last.get('state_hash') else 'absent'}"
            + (f", {len(shards)} shard hash(es)" if shards else "")
        )
    if d.get("trace"):
        out.append(f"trace:       {d['trace']}")
    return out


def diff(a: Dict, b: Dict) -> List[str]:
    """Field-level diff of two dumps; pinpoints first hash divergence.

    When both dumps carry per-shard hashes (sharded health plane), the
    first divergence is attributed to the SHARD(s) whose blocks first
    disagree — the bisection that turns "a TP run diverged" into
    "shard 3 diverged first at tick 4000".
    """
    out = []
    for key in ("reason", "ticks_done"):
        if a.get(key) != b.get(key):
            out.append(f"{key}: {a.get(key)} != {b.get(key)}")
    ra = {e.get("ticks_done"): e for e in a.get("ring") or []}
    rb = {e.get("ticks_done"): e for e in b.get("ring") or []}
    shared = sorted(k for k in set(ra) & set(rb) if k is not None)
    if not shared:
        out.append("rings share no chunk boundaries")
        return out
    first_div = None
    for t in shared:
        ha, hb = ra[t].get("state_hash"), rb[t].get("state_hash")
        if ha and hb and ha != hb:
            first_div = t
            break
    if first_div is None:
        out.append(
            f"state hashes agree on all {len(shared)} shared chunk(s)"
        )
    else:
        out.append(f"first state-hash divergence at tick {first_div}")
        sa = ra[first_div].get("shard_hashes") or []
        sb = rb[first_div].get("shard_hashes") or []
        if sa and sb and len(sa) == len(sb):
            bad = [s for s, (x, y) in enumerate(zip(sa, sb)) if x != y]
            if bad:
                out.append(
                    f"  diverging shard(s) at tick {first_div}: "
                    + ", ".join(str(s) for s in bad)
                )
            else:
                out.append(
                    f"  all {len(sa)} shard blocks agree at tick "
                    f"{first_div}: the divergence is in the replicated "
                    "fog/broker state"
                )
    for t in shared:
        # twin ingestion (ISSUE 17): diverging injected counts mean the
        # two sessions were FED differently — the input stream, not the
        # engine, explains a downstream hash divergence
        ia = (ra[t].get("ingest") or {}).get("injected")
        ib = (rb[t].get("ingest") or {}).get("injected")
        if ia is not None and ib is not None and ia != ib:
            out.append(
                f"tick {t}: injected arrivals differ ({ia} != {ib}) — "
                "the sessions were fed different input streams"
            )
    for t in shared:
        for field, va in (ra[t].get("rows") or {}).items():
            vb = (rb[t].get("rows") or {}).get(field)
            if vb is not None and va != vb:
                out.append(
                    f"tick {t}: reservoir field {field!r} differs "
                    f"(first {next((i for i, (x, y) in enumerate(zip(va, vb)) if x != y), '?')})"
                )
    wa = (a.get("watchdog") or {}).get("anomalies") or []
    wb = (b.get("watchdog") or {}).get("anomalies") or []
    if len(wa) != len(wb):
        out.append(f"anomaly count: {len(wa)} != {len(wb)}")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/postmortem.py",
        description="inspect / diff flight-recorder post-mortem dumps",
    )
    ap.add_argument("paths", nargs="+", metavar="DUMP.json")
    ap.add_argument(
        "--diff", action="store_true",
        help="diff exactly two dumps instead of summarizing each",
    )
    ap.add_argument(
        "--task", type=int, metavar="ID", default=None,
        help="print one sampled task's decoded journey event chain "
        "from the dump's ring snapshot (needs a journey-on bundle)",
    )
    args = ap.parse_args(argv)
    if args.task is not None:
        rc = 0
        for p in args.paths:
            d = load(p)
            snap = (d.get("journeys") or {}).get("rings") or {}
            chains = _decode_journey(snap, task_id=args.task)
            if not chains:
                sampled = snap.get("task") or []
                print(
                    f"{p}: task {args.task} is not in the journey "
                    f"sample ({len(sampled)} sampled"
                    + (
                        f": {sampled[:16]}..." if len(sampled) > 16
                        else f": {sampled}"
                    )
                    + ")"
                )
                rc = 1
                continue
            chain = chains[0]
            own = (
                f", owned by shard {chain['shard']}"
                if chain.get("shard") is not None else ""
            )
            print(
                f"== {p}: task {chain['task']} "
                f"({chain['events_total']} event(s), "
                f"{chain['dropped']} dropped{own}) =="
            )
            for e in chain["events"]:
                print(
                    f"  {e['t']:.6f}s  {e['name']:<14s} "
                    f"a={e['a']} b={e['b']}"
                )
        return rc
    if args.diff:
        if len(args.paths) != 2:
            ap.error("--diff needs exactly two dump paths")
        lines = diff(load(args.paths[0]), load(args.paths[1]))
        print("\n".join(lines) if lines else "dumps are equivalent")
        return 0
    for p in args.paths:
        print(f"== {p} ==")
        print("\n".join(summarize(load(p))))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `| head` closed stdout; not an error
        sys.exit(0)
