"""Flight-recorder post-mortem inspector.

    python tools/postmortem.py DUMP.json            # summarize one dump
    python tools/postmortem.py --diff A.json B.json # field-level diff

A dump is the manifest :class:`fognetsimpp_tpu.telemetry.live
.FlightRecorder` writes on NaN / SLO breach / watchdog anomaly / crash:
the bounded ring of recent reservoir rows + per-chunk state hashes, the
watchdog state, compile-cache stats, the spec and (when the world was
at hand) a Perfetto trace twin.  The inspector answers the two
first-response questions without opening a notebook: *what tripped*
(reason, anomalies, nonfinite leaves) and *when the runs diverged*
(``--diff`` walks the two rings and reports the first chunk whose state
hashes disagree).
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List


def load(path: str) -> Dict:
    with open(path) as f:
        return json.load(f)


def summarize(d: Dict) -> List[str]:
    out = [
        f"reason:      {d.get('reason')}",
        f"recorded_at: {d.get('recorded_at')}",
        f"ticks_done:  {d.get('ticks_done')}",
    ]
    detail = d.get("detail") or {}
    for k, v in detail.items():
        out.append(f"detail.{k}: {json.dumps(v)[:200]}")
    wd = d.get("watchdog") or {}
    anomalies = wd.get("anomalies") or []
    out.append(f"anomalies:   {len(anomalies)}")
    for a in anomalies[-5:]:
        out.append(
            f"  - {a.get('signal')} z={a.get('z'):.2f} "
            f"value={a.get('value')} at tick {a.get('ticks_done')}"
        )
    if wd.get("last_signals"):
        out.append(f"signals:     {json.dumps(wd['last_signals'])}")
    hist = d.get("hist") or {}
    if hist:
        out.append(
            f"latency:     n={hist.get('count')} "
            f"quantiles_ms={json.dumps(hist.get('quantiles_ms'))}"
        )
    cc = d.get("compile_cache") or {}
    if cc:
        out.append(
            "compile:     "
            f"hits={cc.get('cache_hits')} misses={cc.get('cache_misses')} "
            f"compiles={cc.get('compiles')} "
            f"compile_s_total={cc.get('compile_s_total')}"
        )
    ring = d.get("ring") or []
    out.append(f"ring:        {len(ring)} chunk(s)")
    if ring:
        first, last = ring[0], ring[-1]
        out.append(
            f"  ticks {first['ticks_done']} .. {last['ticks_done']}, "
            f"hashes {'present' if last.get('state_hash') else 'absent'}"
        )
    if d.get("trace"):
        out.append(f"trace:       {d['trace']}")
    return out


def diff(a: Dict, b: Dict) -> List[str]:
    """Field-level diff of two dumps; pinpoints first hash divergence."""
    out = []
    for key in ("reason", "ticks_done"):
        if a.get(key) != b.get(key):
            out.append(f"{key}: {a.get(key)} != {b.get(key)}")
    ra = {e["ticks_done"]: e for e in a.get("ring") or []}
    rb = {e["ticks_done"]: e for e in b.get("ring") or []}
    shared = sorted(set(ra) & set(rb))
    if not shared:
        out.append("rings share no chunk boundaries")
        return out
    first_div = None
    for t in shared:
        ha, hb = ra[t].get("state_hash"), rb[t].get("state_hash")
        if ha and hb and ha != hb:
            first_div = t
            break
    if first_div is None:
        out.append(
            f"state hashes agree on all {len(shared)} shared chunk(s)"
        )
    else:
        out.append(f"first state-hash divergence at tick {first_div}")
    for t in shared:
        for field, va in (ra[t].get("rows") or {}).items():
            vb = (rb[t].get("rows") or {}).get(field)
            if vb is not None and va != vb:
                out.append(
                    f"tick {t}: reservoir field {field!r} differs "
                    f"(first {next((i for i, (x, y) in enumerate(zip(va, vb)) if x != y), '?')})"
                )
    wa = (a.get("watchdog") or {}).get("anomalies") or []
    wb = (b.get("watchdog") or {}).get("anomalies") or []
    if len(wa) != len(wb):
        out.append(f"anomaly count: {len(wa)} != {len(wb)}")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python tools/postmortem.py",
        description="inspect / diff flight-recorder post-mortem dumps",
    )
    ap.add_argument("paths", nargs="+", metavar="DUMP.json")
    ap.add_argument(
        "--diff", action="store_true",
        help="diff exactly two dumps instead of summarizing each",
    )
    args = ap.parse_args(argv)
    if args.diff:
        if len(args.paths) != 2:
            ap.error("--diff needs exactly two dump paths")
        lines = diff(load(args.paths[0]), load(args.paths[1]))
        print("\n".join(lines) if lines else "dumps are equivalent")
        return 0
    for p in args.paths:
        print(f"== {p} ==")
        print("\n".join(summarize(load(p))))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:  # `| head` closed stdout; not an error
        sys.exit(0)
