"""Headline benchmark: task-offload decisions/sec on one chip.

Measures the north-star metric of BASELINE.json — broker scheduling
decisions per wall-clock second at 10k-node scale (the reference's hot loop
``src/mqttapp/BrokerBaseApp3.cc:267-281``, which the batched engine turns
into per-tick compacted argmin kernels under one ``lax.scan``).

World: 10,000 users publishing every 2.5 ms to 32 heterogeneous fog nodes
(4M offload decisions per simulated second), full v3 semantics: MQTT
connect gating, advertisement staleness, FIFO queues, exact event-time ack
chain.  The whole horizon runs as one jitted device-resident scan; the
timed measurement enqueues BENCH_PIPELINE back-to-back runs (fresh PRNG
key each, same executable) and syncs once — sustained throughput, since
the tunneled runtime charges a flat ~95 ms per blocking fetch regardless
of queued work.  Measured 2026-07 (round 3) on the tunneled v5e chip:
2.8-3.45M decisions/s/chip across sessions (quiet-host median ~3.1M;
concurrent host load costs ~10%); device time 0.79 ms/tick.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
``vs_baseline`` is value / 1e6 (the ≥1M decisions/sec/chip target; the
reference itself publishes no throughput numbers — BASELINE.md).

Env knobs: BENCH_USERS, BENCH_FOGS, BENCH_HORIZON, BENCH_INTERVAL,
BENCH_REPLICAS (vmap fan-out), BENCH_CPU_SCALE (shrink factor auto-applied
on cpu backends).
"""
from __future__ import annotations

import json
import os
import time


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, default))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fognetsimpp_tpu.compile_cache import enable_compile_cache

    enable_compile_cache()

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)

    n_users = _env_int("BENCH_USERS", 10_000 if on_accel else 1_000)
    n_fogs = _env_int("BENCH_FOGS", 32)
    horizon = _env_float("BENCH_HORIZON", 0.1 if on_accel else 0.05)
    interval = _env_float("BENCH_INTERVAL", 0.0025 if on_accel else 0.005)
    n_replicas = _env_int("BENCH_REPLICAS", 1)

    from fognetsimpp_tpu.core.engine import run
    from fognetsimpp_tpu.parallel import replicate_state
    from fognetsimpp_tpu.scenarios import smoke

    spec, state, net, bounds = smoke.build(
        n_users=n_users,
        n_fogs=n_fogs,
        fog_mips=tuple(float(m) for m in (1000, 2000, 3000, 4000)),
        send_interval=interval,
        horizon=horizon,
        dt=1e-3,
        max_sends_per_user=int(horizon / interval) + 4,
        # steady-state arrivals/tick = n_users * dt / interval; cap at the
        # O(K^2)-rank limit — overflow degrades to next-tick processing
        arrival_window=min(
            4096, max(1024, int(1.1 * n_users * 1e-3 / interval))
        ),
        queue_capacity=128,
        start_time_max=min(0.05, horizon / 4),
    )

    # The benched function returns ONLY the metrics counters: returning the
    # full ~60-buffer world pytree costs ~50 ms of host-side output-buffer
    # handling per call (profiled r3) that has nothing to do with simulation
    # throughput.  The simulation work is identical either way.
    if n_replicas > 1:
        batch = replicate_state(spec, state, n_replicas, seed=0)

        @jax.jit
        def go(b):
            return jax.vmap(lambda s: run(spec, s, net, bounds)[0].metrics)(b)

        arg0 = batch
        rekey = lambda b, k: b.replace(
            key=jax.random.split(k, n_replicas)
        )
    else:

        @jax.jit
        def go(s):
            return run(spec, s, net, bounds)[0].metrics

        arg0 = state
        rekey = lambda s, k: s.replace(key=k)

    def fetch(m):
        # force a real device->host sync: on the tunneled (axon) runtime
        # jax.block_until_ready resolves before device completion; only a
        # value fetch round-trips (measured: a fetch costs ~95 ms flat
        # regardless of queued work — pure tunnel latency, not chip time)
        return int(np.sum(np.asarray(m.n_scheduled)))

    # compile + warm
    t_c0 = time.perf_counter()
    fetch(go(arg0))
    compile_s = time.perf_counter() - t_c0

    # timed: enqueue a pipeline of runs (fresh key each, same executable)
    # and sync once at the end — sustained throughput, amortizing the
    # harness's fixed ~95 ms sync latency the way any real sweep would.
    # BENCH_REPS outer repetitions; the median repetition is reported.
    n_pipeline = _env_int("BENCH_PIPELINE", 5)
    n_reps = _env_int("BENCH_REPS", 3)
    walls, decs = [], []
    for rep in range(n_reps):
        args = [
            rekey(arg0, jax.random.PRNGKey(1 + rep * n_pipeline + i))
            for i in range(n_pipeline)
        ]
        t0 = time.perf_counter()
        ms = [go(a) for a in args]
        d = sum(fetch(m) for m in ms)
        walls.append(time.perf_counter() - t0)
        decs.append(d)
    # median by index (an even rep count would make np.median interpolate
    # a value not present in walls)
    mid = int(np.argsort(walls)[len(walls) // 2])
    wall = walls[mid]
    decisions = decs[mid]
    n_ticks = spec.n_ticks * n_replicas * n_pipeline
    value = decisions / wall

    print(
        json.dumps(
            {
                "metric": "task_offload_decisions_per_sec_per_chip",
                "value": round(value, 1),
                "unit": "decisions/s",
                "vs_baseline": round(value / 1e6, 4),
                "backend": backend,
                "n_users": n_users,
                "n_fogs": n_fogs,
                "n_replicas": n_replicas,
                "horizon_s": horizon,
                "decisions": decisions,
                "wall_s": round(wall, 4),
                "wall_reps_s": [round(w, 4) for w in walls],
                "ticks_per_sec": round(n_ticks / wall, 1),
                "compile_s": round(compile_s, 1),
            }
        )
    )


if __name__ == "__main__":
    main()
