"""Headline benchmark: task-offload decisions/sec on one chip.

Measures the north-star metric of BASELINE.json — broker scheduling
decisions per wall-clock second at 10k-node scale (the reference's hot loop
``src/mqttapp/BrokerBaseApp3.cc:267-281``, which the batched engine turns
into per-tick argmin kernels under one ``lax.scan``).

World: 10,000 users publishing every 2.5 ms to 32 heterogeneous fog nodes
(4M offload decisions per simulated second), full v3 semantics: MQTT
connect gating, advertisement staleness, FIFO queues, exact event-time ack
chain.

Tick size: the default window is ``dt = 5 ms`` — two publish intervals,
half the v1/v2 advertisement period, the staleness scale the reference
broker itself operates under (its view is only as fresh as the last
advertisement that ARRIVED).  Event times stay exact at any dt; the
decision count is identical and the decision/latency deviation vs a
``dt = 1 ms`` run is bounded by tests/test_coarse_dt.py (count-exact,
per-fog split L1 < 0.10 at saturation, latency < 1% at moderate load).
Set BENCH_DT=0.001 for the exact-ordering configuration (numbers for the
full dt ladder are tabulated in BENCHMARKS.md).

Methodology (r4): the tunneled runtime charges a flat ~80-110 ms per
jitted call (dispatch + fetch round trip) regardless of enqueued work, so
the timed section runs BENCH_PIPELINE complete simulations inside ONE
jitted call (a ``lax.scan`` over fresh PRNG keys, same compiled body) and
fetches one scalar.  BENCH_REPS outer repetitions; the median is reported.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
``vs_baseline`` is value / 1e6 (the ≥1M decisions/sec/chip target; the
reference itself publishes no throughput numbers — BASELINE.md).

Env knobs: BENCH_USERS, BENCH_FOGS, BENCH_HORIZON, BENCH_INTERVAL,
BENCH_DT, BENCH_PIPELINE, BENCH_REPS, BENCH_REPLICAS (vmap fan-out),
auto-shrunk world on cpu backends.  BENCH_POLICY=<name|id> (e.g. ``ucb``,
``ducb``, ``exp3``) swaps the scheduler — the learned-policy rows track
the overhead of the in-loop bandit updates (decision bookkeeping +
delayed-reward credit phase) against the min_busy default; learned
policies disable the derive_acks fast path (they credit at ack time
inside the tick).

Telemetry (ISSUE 4): ``BENCH_TELEMETRY=1`` runs the same world with the
device-resident TelemetryState riding the carry (spec.telemetry) — the
value/off-value ratio is the telemetry-on overhead BENCHMARKS.md
quotes.  ``BENCH_JOURNEYS=1`` (ISSUE 15) additionally runs an
interleaved journeys-off/on A/B over telemetry-on twins of the bench
world (``BENCH_JOURNEYS_N`` sampled tasks, default 16) and records the
``journey_overhead`` ratio tools/bench_trend.py gates at the
established <= 1.10 bar.  ``BENCH_TP_JOURNEYS=1`` (ISSUE 19) runs the
same interleaved off/on A/B under ``--tp`` (``BENCH_TP_JOURNEYS_N``
sampled tasks, default 16) and records ``tp_journey_overhead``, gated
at the same bar.  ``python bench.py --profile`` (or ``BENCH_PROFILE=<dir>``)
wraps the timed section in ``jax.profiler.trace`` (engine phases appear
as named scopes) and appends a per-call dispatch-latency histogram plus
the cold-compile time to the JSON line.

Digital twin (ISSUE 17): ``python bench.py --twin`` (or
``BENCH_TWIN=1``) measures the live-serving input/question doors —
``ingest_rate`` (arrivals/s through feed → chunk-boundary injection)
and ``whatif_latency_s`` (warm ``run_whatif`` grid wall; the warm asks
must compile NOTHING, gated by tools/bench_trend.py --check).
"""
from __future__ import annotations

import json
import os
import time


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, default))


def _build_bench_world(
    on_accel: bool, cpu_users: int = 1_000, **spec_overrides
):
    """The shared bench world + its knob dict (single-chip and fleet).

    ``spec_overrides`` refine the env-derived build kwargs — the
    journey-overhead A/B (``BENCH_JOURNEYS=1``) builds its off/on twin
    worlds through here so both arms share every other knob.
    """
    from fognetsimpp_tpu.scenarios import smoke
    from fognetsimpp_tpu.spec import LEARNED_POLICIES, policy_from_name

    n_users = _env_int("BENCH_USERS", 10_000 if on_accel else cpu_users)
    n_fogs = _env_int("BENCH_FOGS", 32)
    horizon = _env_float("BENCH_HORIZON", 0.1 if on_accel else 0.05)
    interval = _env_float("BENCH_INTERVAL", 0.0025 if on_accel else 0.005)
    dt = _env_float("BENCH_DT", 5e-3)
    policy = policy_from_name(os.environ.get("BENCH_POLICY", "min_busy"))

    telemetry = os.environ.get("BENCH_TELEMETRY", "") not in ("", "0")
    # BENCH_HIST=1 additionally carries the streaming latency histogram
    # (spec.telemetry_hist; implies telemetry) — the ISSUE 6 overhead
    # A/B knob: interleave BENCH_TELEMETRY=1 and BENCH_HIST=1 runs for
    # the histogram-on-top-of-telemetry cost BENCHMARKS.md quotes
    hist = os.environ.get("BENCH_HIST", "") not in ("", "0")
    telemetry = telemetry or hist
    # BENCH_FUSED=0 forces the unfused per-phase reference engine — the
    # A/B knob for the r6 fused slot-window front-end (interleave 0/1
    # runs for the off/on comparison, the BENCH_TELEMETRY methodology)
    fused = os.environ.get("BENCH_FUSED", "1") not in ("0",)
    mspt = max(1, -(-int(round(dt * 1e6)) // int(round(interval * 1e6))))
    build_kw = dict(
        telemetry=telemetry,
        telemetry_hist=hist,
        fused_slots=fused,
        n_users=n_users,
        n_fogs=n_fogs,
        fog_mips=tuple(float(m) for m in (1000, 2000, 3000, 4000)),
        send_interval=interval,
        horizon=horizon,
        dt=dt,
        policy=int(policy),
        max_sends_per_user=int(horizon / interval) + 4,
        max_sends_per_tick=mspt,
        queue_capacity=128,
        start_time_max=min(0.05, horizon / 4),
        # ack columns reconstructed once post-run (bit-exact; r5): the
        # per-tick scatters they cost are ~25 us each on the v5e —
        # except for the learned policies, which must observe the
        # status-6 ack inside the tick to credit their rewards, and the
        # streaming histogram, which bins them at ack time (ISSUE 6)
        derive_acks=policy not in LEARNED_POLICIES and not hist,
    )
    build_kw.update(spec_overrides)
    # default window: the K=4096 O(K^2)-rank sweet spot — warm-up
    # overflow defers to later windows (counted in n_deferred) and
    # saturation tail-drops take the dense fast path.  BENCH_WINDOW=auto
    # sizes K from the spec's own arrival rate instead (never defers;
    # see WorldSpec.auto_arrival_window), BENCH_WINDOW=<int> pins it.
    win_env = os.environ.get("BENCH_WINDOW", "")
    if win_env == "auto":
        spec0, *_ = smoke.build(arrival_window=None, **build_kw)
        window = spec0.auto_arrival_window
    elif win_env:
        window = int(win_env)
    else:
        window = min(
            4096, max(1024, int(1.1 * n_users * min(dt, 1e-3) / interval))
        )
    spec, state, net, bounds = smoke.build(arrival_window=window, **build_kw)
    knobs = dict(
        n_users=n_users, n_fogs=n_fogs, horizon=horizon,
        interval=interval, dt=dt, policy=policy, telemetry=telemetry,
        hist=hist, fused=fused,
    )
    return spec, state, net, bounds, knobs


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fognetsimpp_tpu.compile_cache import enable_compile_cache

    enable_compile_cache()

    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)

    n_replicas = _env_int("BENCH_REPLICAS", 1)
    n_pipeline = _env_int("BENCH_PIPELINE", 30 if on_accel else 3)
    n_reps = _env_int("BENCH_REPS", 3)

    from fognetsimpp_tpu.core.engine import run
    from fognetsimpp_tpu.parallel import replicate_state

    spec, state, net, bounds, knobs = _build_bench_world(on_accel)
    n_users, n_fogs = knobs["n_users"], knobs["n_fogs"]
    horizon, interval = knobs["horizon"], knobs["interval"]
    dt, policy = knobs["dt"], knobs["policy"]

    # one jitted call runs the whole pipeline of independent simulations
    # (fresh key each, same compiled body) and returns one scalar — the
    # only device->host fetch in the timed section
    if n_replicas > 1:
        batch = replicate_state(spec, state, n_replicas, seed=0)

        @jax.jit
        def go(keys):
            def body(_, k):
                b = batch.replace(key=jax.random.split(k, n_replicas))
                m = jax.vmap(
                    lambda s: run(spec, s, net, bounds)[0].metrics
                )(b)
                return 0, (jnp.sum(m.n_scheduled),
                           jnp.max(m.n_deferred_max))

            _, (d, dm) = jax.lax.scan(body, 0, keys)
            return jnp.sum(d), jnp.max(dm)

    else:

        @jax.jit
        def go(keys):
            def body(_, k):
                m = run(spec, state.replace(key=k), net, bounds)[0].metrics
                return 0, (m.n_scheduled, m.n_deferred_max)

            _, (d, dm) = jax.lax.scan(body, 0, keys)
            return jnp.sum(d), jnp.max(dm)

    def fetch(x):
        d, dm = x
        return int(np.asarray(d)), int(np.asarray(dm))

    # tiny jitted round trip for the --profile dispatch-latency probe
    _dispatch_probe = jax.jit(lambda x: x + 1)

    import sys

    from fognetsimpp_tpu.telemetry.profile import (
        measure_dispatch,
        profile_trace,
    )

    prof_dir = os.environ.get("BENCH_PROFILE") or (
        "/tmp/fns_profile" if "--profile" in sys.argv else None
    )

    # compile + warm
    from fognetsimpp_tpu.compile_cache import compile_stats, note_compile

    keys0 = jax.random.split(jax.random.PRNGKey(0), n_pipeline)
    t_c0 = time.perf_counter()
    fetch(go(keys0))
    compile_s = time.perf_counter() - t_c0
    note_compile(compile_s)  # compile-latency observability (ISSUE 6)

    walls, decs, defs = [], [], []
    with profile_trace(prof_dir) as prof:
        for rep in range(n_reps):
            keys = jax.random.split(
                jax.random.PRNGKey(1 + rep), n_pipeline
            )
            t0 = time.perf_counter()
            d, dm = fetch(go(keys))
            walls.append(time.perf_counter() - t0)
            decs.append(d)
            defs.append(dm)
    # median by index (an even rep count would make np.median interpolate
    # a value not present in walls)
    mid = int(np.argsort(walls)[len(walls) // 2])
    wall = walls[mid]
    decisions = decs[mid]
    n_ticks = spec.n_ticks * n_replicas * n_pipeline
    value = decisions / wall

    # interleaved journey-overhead A/B (ISSUE 15, BENCH_JOURNEYS=1):
    # telemetry-on worlds with the journey rings off vs on, everything
    # else identical — the measured journeys-on overhead BENCHMARKS.md
    # quotes, gated <= OVERHEAD_BAR by tools/bench_trend.py (the
    # BENCH_TELEMETRY methodology)
    journey_fields = {}
    if os.environ.get("BENCH_JOURNEYS", "") not in ("", "0"):
        J = _env_int("BENCH_JOURNEYS_N", 16)
        arms = {}
        for label, j in (("off", 0), ("on", J)):
            sp, st, nt, bd, _k = _build_bench_world(
                on_accel, telemetry=True, telemetry_journeys=j
            )
            f = jax.jit(
                lambda s, sp=sp, nt=nt, bd=bd: run(sp, s, nt, bd)[
                    0
                ].metrics.n_scheduled
            )
            f(st).block_until_ready()  # untimed compile+warm
            arms[label] = (f, st)
        n_ab = max(3, n_reps)
        ab_walls = {"off": [], "on": []}
        for rep in range(n_ab):
            for label in ("off", "on"):
                f, st = arms[label]
                s2 = st.replace(key=jax.random.PRNGKey(100 + rep))
                t0 = time.perf_counter()
                int(np.asarray(f(s2)))
                ab_walls[label].append(time.perf_counter() - t0)
        off_med = float(np.median(ab_walls["off"]))
        on_med = float(np.median(ab_walls["on"]))
        journey_fields = {
            "journey_overhead": round(on_med / max(off_med, 1e-9), 4),
            "journey_off_wall_s": round(off_med, 4),
            "journey_on_wall_s": round(on_med, 4),
            "journey_sampled": J,
            "journey_ab_reps": n_ab,
        }

    print(
        json.dumps(
            {
                "metric": "task_offload_decisions_per_sec_per_chip",
                "value": round(value, 1),
                **journey_fields,
                "unit": "decisions/s",
                "vs_baseline": round(value / 1e6, 4),
                "policy": policy.name.lower(),
                "backend": backend,
                "n_users": n_users,
                "n_fogs": n_fogs,
                "n_replicas": n_replicas,
                "horizon_s": horizon,
                "dt": dt,
                "arrival_window": spec.window,
                "n_pipeline": n_pipeline,
                "decisions": decisions,
                "wall_s": round(wall, 4),
                "wall_reps_s": [round(w, 4) for w in walls],
                "ticks_per_sec": round(n_ticks / wall, 1),
                "ms_per_window": round(wall / n_ticks * 1e3, 4),
                # peak matured-but-unseated backlog across all runs: the
                # warm-up transient before the saturated queues fill; 0 =
                # every window was fully current (Metrics.n_deferred_max)
                "n_deferred_max": max(defs),
                "compile_s": round(compile_s, 1),
                # compile-latency observability (ISSUE 6): persistent-
                # cache hit/miss + backend compile seconds — the
                # streaming serving mode's blocker, tracked per capture
                # (tools/bench_trend.py tabulates compile_s per round)
                "compile_cache": {
                    k: (round(v, 3) if isinstance(v, float) else v)
                    for k, v in compile_stats().items()
                },
                "telemetry": knobs["telemetry"],
                "telemetry_hist": knobs["hist"],
                "fidelity": "count-exact vs dt=1e-3; tests/test_coarse_dt.py",
                # --profile extras: where the XLA trace landed plus the
                # flat per-call dispatch+fetch cost the pipeline
                # methodology amortises, measured as a histogram over a
                # trivial jitted round trip
                **(
                    {
                        "profile_dir": prof["dir"] if prof["active"] else None,
                        **(
                            {"profile_error": prof["error"]}
                            if prof["error"] else {}
                        ),
                        "dispatch_latency": measure_dispatch(
                            lambda: int(
                                np.asarray(_dispatch_probe(jnp.int32(0)))
                            ),
                            n=10,
                        ),
                    }
                    if prof_dir
                    else {}
                ),
            }
        )
    )


def ensure_mesh_devices(n: int, flip_unset: bool = False) -> None:
    """Guarantee an ``n``-device jax platform before backend init.

    One shared copy of the virtual-device provisioning dance (the
    reviewer-flagged duplicate between ``fleet_main`` and
    ``__graft_entry__.dryrun_multichip``): append the
    host-platform-device-count XLA flag when absent, flip a tunneled
    single-chip session (axon sitecustomize) to the virtual CPU
    platform — with ``flip_unset=True`` (the dryrun's historical
    behavior) an unset platform is flipped too; the fleet benchmark
    leaves it alone so a real multi-chip host measures its own hardware
    — then validate the device count.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()

    import jax

    platforms = jax.config.jax_platforms or ""
    if "axon" in platforms or (flip_unset and platforms in ("", None)):
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    if len(jax.devices()) < n:
        raise RuntimeError(
            f"need {n} devices, found {len(jax.devices())}; for a "
            "virtual CPU mesh run with JAX_PLATFORMS=cpu "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n}"
        )


def fleet_measurement(n_devices=None) -> dict:
    """Measured replica-sharded multi-chip throughput (ISSUE 3).

    Replaces the compile-only ``dryrun_multichip ok`` flag with real
    metric fields: the SAME bench world runs (a) one replica on a
    1-device mesh and (b) ``n_devices x BENCH_RPD`` replicas sharded
    over the full mesh — both through
    :func:`fognetsimpp_tpu.parallel.fleet.fleet_decisions` (one jitted
    call per measurement, a pipeline of complete fleets, one scalar
    pair fetched), so the aggregate number and the weak-scaling
    efficiency ``aggregate / (n_devices x single-device)`` share one
    methodology.  Correctness of the path itself is gated separately:
    per-replica state hashes equal the vmap path
    (``tests/test_fleet.py``).

    Assumes the devices already exist (callers own the
    ``xla_force_host_platform_device_count`` dance —
    ``__graft_entry__.dryrun_multichip`` or the ``--fleet`` entry).
    """
    import jax
    import numpy as np

    from fognetsimpp_tpu.compile_cache import enable_compile_cache
    from fognetsimpp_tpu.parallel import make_mesh, replicate_state
    from fognetsimpp_tpu.parallel.fleet import fleet_decisions

    enable_compile_cache()
    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)
    D = int(n_devices or len(jax.devices()))
    rpd = _env_int("BENCH_RPD", 1)  # replicas per device (weak scaling)
    n_pipeline = _env_int("BENCH_PIPELINE", 10 if on_accel else 2)
    n_reps = _env_int("BENCH_REPS", 3 if on_accel else 2)

    # smaller CPU default than the single-chip bench: the fleet runs the
    # world D x rpd times per pipeline step
    spec, state, net, bounds, knobs = _build_bench_world(
        on_accel, cpu_users=512
    )

    def measure(n_dev: int, n_replicas: int):
        mesh = make_mesh(n_dev)
        batch = replicate_state(spec, state, n_replicas, seed=0)
        keys0 = jax.random.split(jax.random.PRNGKey(0), n_pipeline)
        t0 = time.perf_counter()
        d, dm = fleet_decisions(spec, batch, net, bounds, keys0, mesh)
        d, dm = int(np.asarray(d)), int(np.asarray(dm))
        compile_s = time.perf_counter() - t0
        walls, decs, defs = [], [], []
        for rep in range(n_reps):
            keys = jax.random.split(jax.random.PRNGKey(1 + rep), n_pipeline)
            t0 = time.perf_counter()
            d, dm = fleet_decisions(spec, batch, net, bounds, keys, mesh)
            d, dm = int(np.asarray(d)), int(np.asarray(dm))
            walls.append(time.perf_counter() - t0)
            decs.append(d)
            defs.append(dm)
        # median by index; LOWER middle for even rep counts (the CPU
        # default is 2 reps — upper-middle would systematically record
        # the worse run)
        mid = int(np.argsort(walls)[(len(walls) - 1) // 2])
        return decs[mid], walls[mid], max(defs), compile_s

    d1, w1, _, _ = measure(1, rpd)
    dF, wF, dmF, cF = measure(D, D * rpd)
    ds1 = d1 / w1
    dsF = dF / wF
    # forced virtual CPU devices share the host's cores, so the efficiency
    # field tracks host parallelism (roughly cores/D, modulo how much of
    # the host the 1-device baseline already used) rather than device
    # count; a real mesh gives every device its own silicon.  Record the
    # core count so captures stay interpretable.
    host = {}
    if not on_accel:
        host = {"cpu_cores": os.cpu_count() or 1}
    return {
        "metric": "fleet_task_offload_decisions_per_sec",
        "value": round(dsF, 1),
        "unit": "decisions/s",
        "backend": backend,
        "n_devices": D,
        "n_replicas": D * rpd,
        "policy": knobs["policy"].name.lower(),
        "n_users": knobs["n_users"],
        "n_fogs": knobs["n_fogs"],
        "horizon_s": knobs["horizon"],
        "dt": knobs["dt"],
        "n_pipeline": n_pipeline,
        "decisions": dF,
        "wall_s": round(wF, 4),
        "per_device_decisions_per_sec": round(dsF / D, 1),
        "singlechip_decisions_per_sec": round(ds1, 1),
        "speedup_vs_singlechip": round(dsF / ds1, 3),
        "weak_scaling_efficiency": round(dsF / (D * ds1), 4),
        "n_deferred_max": dmF,
        "compile_s": round(cF, 1),
        **host,
        "equivalence": "per-replica state-hash == vmap path; "
        "tests/test_fleet.py",
    }


def tp_measurement(n_devices=None) -> dict:
    """Measured TP (task-table-sharded) single-world throughput (ISSUE 9).

    ONE world whose user/task axis spans the mesh — the capacity path —
    through :func:`fognetsimpp_tpu.parallel.taskshard.run_tp_sharded`
    (shard_map megaphases, explicit broker↔fog collectives, ring
    arrival exchange), replacing the compile-only TP dryrun with real
    decisions/s.  Default population: 2^20 users (the ≥1M-user single
    world of the ROADMAP's first open item).  Env knobs:
    BENCH_TP_USERS / BENCH_TP_FOGS / BENCH_TP_INTERVAL / BENCH_TP_DT /
    BENCH_TP_HORIZON / BENCH_TP_REPS / BENCH_TP_WINDOW (per-shard
    exchange window; 0 = never-defer full window) /
    BENCH_TP_ARRIVAL_WINDOW (GLOBAL spec-level arrival window K > 0:
    the ISSUE 18 windowed regime — distributed K-window selection over
    the hop-pruned top-K exchange ring, per-hop payload K*5*4 bytes;
    mutually exclusive with BENCH_TP_WINDOW).

    Assumes the devices already exist (callers own the
    ``xla_force_host_platform_device_count`` dance).
    """
    import jax
    import numpy as np

    from fognetsimpp_tpu.compile_cache import (
        compile_stats,
        enable_compile_cache,
        note_compile,
    )
    from fognetsimpp_tpu.parallel import make_mesh, run_tp_sharded
    from fognetsimpp_tpu.scenarios import smoke

    enable_compile_cache()
    backend = jax.default_backend()
    D = int(n_devices or len(jax.devices()))
    n_users = _env_int("BENCH_TP_USERS", 1_048_576)
    n_fogs = _env_int("BENCH_TP_FOGS", 64)
    interval = _env_float("BENCH_TP_INTERVAL", 0.05)
    dt = _env_float("BENCH_TP_DT", 5e-3)
    horizon = _env_float("BENCH_TP_HORIZON", 0.25)
    n_reps = _env_int("BENCH_TP_REPS", 1)
    mspt = max(1, -(-int(round(dt * 1e6)) // int(round(interval * 1e6))))

    tp_telem_ab = os.environ.get("BENCH_TP_TELEMETRY", "") not in ("", "0")
    # ISSUE 18 windowed regime: a GLOBAL spec-level arrival window K
    # switches the exchange to the hop-pruned top-K merge ring
    arrival_k = _env_int("BENCH_TP_ARRIVAL_WINDOW", 0)

    def build(telemetry=False, journeys=0):
        return smoke.build(
            n_users=n_users,
            n_fogs=n_fogs,
            fog_mips=tuple(float(m) for m in (1000, 2000, 3000, 4000)),
            send_interval=interval,
            horizon=horizon,
            dt=dt,
            max_sends_per_user=int(horizon / interval) + 4,
            max_sends_per_tick=mspt,
            queue_capacity=128,
            start_time_max=min(0.05, horizon / 4),
            derive_acks=True,
            telemetry=telemetry,
            **({"telemetry_journeys": journeys} if journeys > 0 else {}),
            **({"arrival_window": arrival_k} if arrival_k > 0 else {}),
        )

    spec, state, net, bounds = build()
    mesh = make_mesh(D, axis_name="node")
    # per-shard exchange window: auto-size from the spec's own arrival
    # rate (the WorldSpec.auto_arrival_window discipline, per shard)
    win_env = _env_int("BENCH_TP_WINDOW", -1)
    if arrival_k > 0:
        if win_env > 0:
            raise SystemExit(
                "BENCH_TP_ARRIVAL_WINDOW (windowed spec) and "
                "BENCH_TP_WINDOW (no-window exchange tuning) are "
                "mutually exclusive"
            )
        window = None  # the spec's own K-window bounds the exchange
    elif win_env == 0:
        window = None  # full candidate list: never defers
    elif win_env > 0:
        window = win_env
    else:
        u_loc = n_users // D
        window = max(256, int(1.3 * u_loc * dt / max(interval, 1e-12)) + 64)
    # per-hop exchange-ring payload (bytes): the windowed merge ring
    # carries a packed (K, 5) i32 block; the no-window all-gather ring
    # a packed (K_ex, 4) i32 block (K_ex defaults to shard capacity)
    if arrival_k > 0:
        payload_bytes = arrival_k * 5 * 4
    else:
        k_ex = window if window is not None else (n_users // D) * mspt
        payload_bytes = k_ex * 4 * 4

    t0 = time.perf_counter()
    _, final = run_tp_sharded(
        spec, state, net, bounds, mesh, exchange_window=window, donate=True
    )
    decisions = int(np.asarray(final.metrics.n_scheduled))
    compile_s = time.perf_counter() - t0
    note_compile(compile_s)

    walls, decs, defs = [], [], []
    for _rep in range(n_reps):
        spec, state, net, bounds = build()
        t0 = time.perf_counter()
        _, final = run_tp_sharded(
            spec, state, net, bounds, mesh, exchange_window=window,
            donate=True,
        )
        d = int(np.asarray(final.metrics.n_scheduled))
        walls.append(time.perf_counter() - t0)
        decs.append(d)
        defs.append(int(np.asarray(final.metrics.n_deferred_max)))
    mid = int(np.argsort(walls)[(len(walls) - 1) // 2])
    wall, decisions = walls[mid], decs[mid]

    telem_fields = {}
    if tp_telem_ab:
        # interleaved telemetry off/on A/B (ISSUE 11): the measured
        # TP telemetry-on overhead — per-shard exchange gauges + the
        # phase-work fold psums — quoted by BENCHMARKS.md and gated
        # by tools/bench_trend.py (<= OVERHEAD_BAR).  One untimed
        # telemetry-on run first eats the extra compile.
        sp, st, nt, bd = build(telemetry=True)
        run_tp_sharded(
            sp, st, nt, bd, mesh, exchange_window=window, donate=True
        )
        n_ab = max(3, n_reps)
        w_off, w_on = [], []
        for _rep in range(n_ab):
            for telem, sink in ((False, w_off), (True, w_on)):
                sp, st, nt, bd = build(telemetry=telem)
                t0 = time.perf_counter()
                _, f = run_tp_sharded(
                    sp, st, nt, bd, mesh, exchange_window=window,
                    donate=True,
                )
                jax.block_until_ready(f.metrics.n_scheduled)
                sink.append(time.perf_counter() - t0)
        off_med = float(np.median(w_off))
        on_med = float(np.median(w_on))
        telem_fields = {
            "telemetry_overhead": round(on_med / max(off_med, 1e-9), 4),
            "telemetry_off_wall_s": round(off_med, 4),
            "telemetry_on_wall_s": round(on_med, 4),
            "telemetry_ab_reps": n_ab,
        }

    jour_fields = {}
    if os.environ.get("BENCH_TP_JOURNEYS", "") not in ("", "0"):
        # interleaved journeys off/on A/B over telemetry-on twins
        # (ISSUE 19): the measured TP journey-ring overhead — the
        # shard-local snapshot diff + ring scatter inside the sharded
        # tick — quoted by BENCHMARKS.md and gated by
        # tools/bench_trend.py (<= OVERHEAD_BAR, the BENCH_JOURNEYS
        # methodology).  One untimed journeys-on run eats the compile.
        J = _env_int("BENCH_TP_JOURNEYS_N", 16)
        sp, st, nt, bd = build(telemetry=True, journeys=J)
        run_tp_sharded(
            sp, st, nt, bd, mesh, exchange_window=window, donate=True
        )
        n_ab = max(3, n_reps)
        w_off, w_on = [], []
        for _rep in range(n_ab):
            for j, sink in ((0, w_off), (J, w_on)):
                sp, st, nt, bd = build(telemetry=True, journeys=j)
                t0 = time.perf_counter()
                _, f = run_tp_sharded(
                    sp, st, nt, bd, mesh, exchange_window=window,
                    donate=True,
                )
                jax.block_until_ready(f.metrics.n_scheduled)
                sink.append(time.perf_counter() - t0)
        off_med = float(np.median(w_off))
        on_med = float(np.median(w_on))
        jour_fields = {
            "tp_journey_overhead": round(on_med / max(off_med, 1e-9), 4),
            "tp_journey_off_wall_s": round(off_med, 4),
            "tp_journey_on_wall_s": round(on_med, 4),
            "tp_journey_sampled": J,
            "tp_journey_ab_reps": n_ab,
        }

    return {
        "metric": "tp_task_offload_decisions_per_sec",
        "value": round(decisions / wall, 1),
        **telem_fields,
        **jour_fields,
        "unit": "decisions/s",
        "backend": backend,
        "n_devices": D,
        "tp_shards": D,
        "n_users": spec.n_users,
        "n_fogs": n_fogs,
        "horizon_s": horizon,
        "dt": dt,
        "interval": interval,
        "exchange_window": window,
        "tp_window": arrival_k if arrival_k > 0 else None,
        "exchange_payload_bytes": payload_bytes,
        "decisions": decisions,
        "wall_s": round(wall, 4),
        "per_device_decisions_per_sec": round(decisions / wall / D, 1),
        "n_deferred_max": max(defs),
        "compile_s": round(compile_s, 1),
        "compile_cache": {
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in compile_stats().items()
        },
        "collectives_per_tick": "pinned in tools/op_budget.json "
        + ("tp_tick_window" if arrival_k > 0 else "tp_tick"),
        "equivalence": "state-hash == single-device engine; "
        "tests/test_tp.py",
    }


def chaos_measurement() -> dict:
    """Hostile-world benchmark (ISSUE 12): the bench world under churn.

    ``python bench.py --chaos`` runs the single-chip bench world with
    the chaos fault-injection subsystem live — random fog crash/recover
    (MTBF/MTTR), RE-OFFLOAD in-flight handling and bursty broker→fog
    RTT degradation — once per policy in ``BENCH_CHAOS_POLICIES``
    (default: two static + two learned schedulers), and reports
    throughput plus the policy-family latency/robustness table
    BENCHMARKS.md quotes: under churn the bandits should win on mean
    latency by learning to avoid flaky arms, which the happy-path table
    cannot show.

    Env knobs: BENCH_CHAOS_USERS / BENCH_CHAOS_FOGS /
    BENCH_CHAOS_HORIZON / BENCH_CHAOS_INTERVAL / BENCH_CHAOS_MTBF /
    BENCH_CHAOS_MTTR / BENCH_CHAOS_POLICIES / BENCH_CHAOS_SEED.
    Headline value = min_busy decisions/s (comparable across rounds at
    the same shape); ``chaos`` rides the JSON so tools/bench_trend.py
    forms a separate trajectory from the happy-path rows.
    """
    import jax
    import numpy as np

    from fognetsimpp_tpu.compile_cache import (
        compile_stats,
        enable_compile_cache,
        note_compile,
    )
    from fognetsimpp_tpu.core.engine import run_jit
    from fognetsimpp_tpu.runtime.signals import extract_signals
    from fognetsimpp_tpu.scenarios import smoke
    from fognetsimpp_tpu.spec import ChaosMode, policy_from_name

    enable_compile_cache()
    backend = jax.default_backend()
    on_accel = backend not in ("cpu",)

    # Unlike the saturated happy-path bench (a pure throughput probe),
    # the hostile world is the test_chaos.py churn shape scaled up:
    # fog 0 is SLOW and scripted-flaky (a square-wave outage; after
    # every reboot it advertises busy=0, so stale-view scheduling keeps
    # feeding it), the rest are fast and stable, and the offered load
    # stays within single-fog capacity so the window-level-argmax
    # policies (MIN_BUSY's quirk family, UCB/DUCB) compete on
    # ADAPTIVITY, not queueing noise.  BENCH_CHAOS_MTBF>0 adds global
    # random churn on top of the scripted wave.
    n_users = _env_int("BENCH_CHAOS_USERS", 12)
    n_fogs = _env_int("BENCH_CHAOS_FOGS", 8)
    horizon = _env_float("BENCH_CHAOS_HORIZON", 4.0)
    interval = _env_float("BENCH_CHAOS_INTERVAL", 0.1)
    dt = _env_float("BENCH_CHAOS_DT", 1e-3)
    mtbf = _env_float("BENCH_CHAOS_MTBF", 0.0)
    mttr = _env_float("BENCH_CHAOS_MTTR", 0.1)
    seed = _env_int("BENCH_CHAOS_SEED", 0)
    names = os.environ.get(
        "BENCH_CHAOS_POLICIES", "min_busy,round_robin,random,ducb,exp3"
    ).split(",")
    policies = [policy_from_name(p) for p in names if p.strip()]
    # fog 0's square-wave outage: down 0.15 s of every 0.3 s
    script = tuple(
        (0, round(0.3 * k + 0.15, 3), round(0.3 * k + 0.30, 3))
        for k in range(int(horizon / 0.3))
    )

    def build(policy):
        return smoke.build(
            n_users=n_users,
            n_fogs=n_fogs,
            # fog 0 slow AND flaky; the rest fast and stable
            fog_mips=(6000.0,) + tuple(
                float(m)
                for _, m in zip(
                    range(n_fogs - 1), (60000, 80000, 100000) * n_fogs
                )
            ),
            send_interval=interval,
            horizon=horizon,
            dt=dt,
            policy=int(policy),
            max_sends_per_user=int(horizon / interval) + 4,
            queue_capacity=128,
            start_time_max=min(0.05, horizon / 4),
            seed=seed,
            learn_explore=0.1,
            learn_discount=0.999,
            chaos=True,
            chaos_mode=int(ChaosMode.REOFFLOAD),
            chaos_seed=seed,
            chaos_script=script,
            chaos_mtbf_s=mtbf,
            chaos_mttr_s=mttr,
            chaos_max_retries=8,
            chaos_rtt_amp=0.5,
            chaos_rtt_period_s=0.5,
            chaos_rtt_burst_prob=0.02,
            chaos_rtt_burst_mult=4.0,
        )

    per_policy = {}
    headline = None
    headline_name = None
    compile_s_total = 0.0
    for pol in policies:
        # compile pass (untimed), then one timed run on a fresh world
        spec, state, net, bounds = build(pol)
        t0 = time.perf_counter()
        jax.block_until_ready(run_jit(spec, state, net, bounds))
        compile_s = time.perf_counter() - t0
        note_compile(compile_s)
        compile_s_total += compile_s
        spec, state, net, bounds = build(pol)
        t0 = time.perf_counter()
        final = run_jit(spec, state, net, bounds)
        jax.block_until_ready(final.metrics.n_scheduled)
        wall = time.perf_counter() - t0
        lat = extract_signals(final)["task_time"]
        ch = final.chaos
        decisions = int(np.asarray(final.metrics.n_scheduled))
        row = {
            "decisions": decisions,
            "decisions_per_sec": round(decisions / wall, 1),
            "wall_s": round(wall, 4),
            "completed": int(np.asarray(final.metrics.n_completed)),
            "mean_latency_ms": (
                round(float(lat.mean()), 3) if lat.size else None
            ),
            "p95_latency_ms": (
                round(float(np.percentile(lat, 95)), 3)
                if lat.size else None
            ),
            "reoffloaded": int(np.asarray(ch.n_reoffloaded)),
            "retry_exhausted": int(np.asarray(ch.n_retry_exhausted)),
            "lost_crash": int(np.asarray(ch.n_lost_crash)),
            "crashes": int(np.asarray(ch.n_crashes)),
        }
        per_policy[pol.name.lower()] = row
        # the headline (trend-ratcheted) row is min_busy when present;
        # otherwise the first policy run — the recorded "policy" field
        # must name whichever actually produced the number, or
        # bench_trend would compare unlike shapes (its policy SHAPE_FIELD)
        if headline is None or pol.name.lower() == "min_busy":
            headline = row
            headline_name = pol.name.lower()

    return {
        "metric": "chaos_task_offload_decisions_per_sec",
        "value": headline["decisions_per_sec"],
        "unit": "decisions/s",
        "backend": backend,
        "chaos": "reoffload-churn",
        "n_users": n_users,
        "n_fogs": n_fogs,
        "horizon_s": horizon,
        "dt": dt,
        "interval": interval,
        "chaos_mtbf_s": mtbf,
        "chaos_mttr_s": mttr,
        "policy": headline_name,
        "decisions": headline["decisions"],
        "wall_s": headline["wall_s"],
        "per_policy": per_policy,
        "compile_s": round(compile_s_total, 1),
        "compile_cache": {
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in compile_stats().items()
        },
        "conservation": "spawned = completed + dropped + lost + "
        "in-flight; tests/test_chaos.py",
    }


def hier_measurement() -> dict:
    """Federation benchmark (ISSUE 14): the imbalanced multi-broker
    world, one row per migration policy.

    ``python bench.py --hier`` runs TWO acceptance worlds:

    * **imbalance** — every user publishes to broker 0, whose small
      slow domain saturates, while broker 1 owns the fast idle fogs one
      federation RTT away: THRESHOLD / LEAST_LOADED migration must beat
      NEVER on mean AND p95 task latency;
    * **domain-down** — scripted chaos kills the whole of domain 0
      mid-run (RE-OFFLOAD in-flight handling): migration must recover
      tasks that NEVER terminally loses (NO_RESOURCE / LOST /
      hop-exhausted).

    Env knobs: BENCH_HIER_USERS / BENCH_HIER_FOGS / BENCH_HIER_BROKERS
    / BENCH_HIER_HORIZON / BENCH_HIER_INTERVAL / BENCH_HIER_RTT /
    BENCH_HIER_SEED.  Headline value = THRESHOLD decisions/s on the
    imbalance world; ``n_brokers`` rides the JSON so
    tools/bench_trend.py ratchets federation rows as their own
    trajectories.
    """
    import jax
    import numpy as np

    from fognetsimpp_tpu.compile_cache import (
        compile_stats,
        enable_compile_cache,
        note_compile,
    )
    from fognetsimpp_tpu.core.engine import run_jit
    from fognetsimpp_tpu.hier import stamp_ownership
    from fognetsimpp_tpu.runtime.signals import extract_signals
    from fognetsimpp_tpu.scenarios import smoke
    from fognetsimpp_tpu.spec import ChaosMode, HierPolicy, Stage

    enable_compile_cache()
    backend = jax.default_backend()

    n_users = _env_int("BENCH_HIER_USERS", 16)
    n_fogs = _env_int("BENCH_HIER_FOGS", 8)
    n_brokers = _env_int("BENCH_HIER_BROKERS", 2)
    horizon = _env_float("BENCH_HIER_HORIZON", 4.0)
    interval = _env_float("BENCH_HIER_INTERVAL", 0.05)
    dt = _env_float("BENCH_HIER_DT", 1e-3)
    rtt = _env_float("BENCH_HIER_RTT", 0.005)
    seed = _env_int("BENCH_HIER_SEED", 0)
    # domain 0: the first n_fogs//4 fogs, slow; domain 1..B-1 split the
    # fast remainder.  Every user publishes into domain 0 (the hot cell)
    n_slow = max(1, n_fogs // 4)
    fog_owner = [0] * n_slow + [
        1 + (i * (n_brokers - 1)) // (n_fogs - n_slow)
        for i in range(n_fogs - n_slow)
    ]
    user_owner = [0] * n_users
    mips = tuple([3000.0] * n_slow + [80000.0] * (n_fogs - n_slow))

    def build(hier_policy, chaos_script=None):
        kw = dict(
            n_users=n_users,
            n_fogs=n_fogs,
            fog_mips=mips,
            send_interval=interval,
            horizon=horizon,
            dt=dt,
            max_sends_per_user=int(horizon / interval) + 4,
            queue_capacity=128,
            start_time_max=min(0.05, horizon / 4),
            seed=seed,
            assume_static=chaos_script is None,
            n_brokers=n_brokers,
            hier_policy=int(hier_policy),
            hier_threshold=0.5,
            hier_max_hops=2,
            hier_rtt_s=rtt,
        )
        if chaos_script is not None:
            kw.update(
                chaos=True,
                chaos_mode=int(ChaosMode.REOFFLOAD),
                chaos_seed=seed,
                chaos_script=chaos_script,
                chaos_max_retries=8,
                assume_static=False,
            )
        spec, state, net, bounds = smoke.build(**kw)
        state = stamp_ownership(
            spec, state, user_broker=user_owner, fog_broker=fog_owner
        )
        return spec, state, net, bounds

    # domain-down script: every domain-0 fog out for the middle ~80%
    down = tuple(
        (f, round(horizon * 0.1, 3), round(horizon * 0.9, 3))
        for f in range(n_slow)
    )

    def measure(hier_policy, chaos_script=None):
        spec, state, net, bounds = build(hier_policy, chaos_script)
        t0 = time.perf_counter()
        jax.block_until_ready(run_jit(spec, state, net, bounds))
        compile_s = time.perf_counter() - t0
        note_compile(compile_s)
        spec, state, net, bounds = build(hier_policy, chaos_script)
        t0 = time.perf_counter()
        final = run_jit(spec, state, net, bounds)
        jax.block_until_ready(final.metrics.n_scheduled)
        wall = time.perf_counter() - t0
        lat = extract_signals(final)["task_time"]
        stage = np.asarray(final.tasks.stage)
        lost = int(
            (stage == int(Stage.NO_RESOURCE)).sum()
            + (stage == int(Stage.LOST)).sum()
            + (stage == int(Stage.HOP_EXHAUSTED)).sum()
        )
        decisions = int(np.asarray(final.metrics.n_scheduled))
        return {
            "decisions": decisions,
            "decisions_per_sec": round(decisions / wall, 1),
            "wall_s": round(wall, 4),
            "completed": int(np.asarray(final.metrics.n_completed)),
            "mean_latency_ms": (
                round(float(lat.mean()), 3) if lat.size else None
            ),
            "p95_latency_ms": (
                round(float(np.percentile(lat, 95)), 3)
                if lat.size else None
            ),
            "migrated": int(np.asarray(final.hier.n_migrated)),
            "hop_exhausted": int(np.asarray(final.hier.n_hop_exhausted)),
            "lost_terminal": lost,
        }, compile_s

    pols = (HierPolicy.NEVER, HierPolicy.THRESHOLD,
            HierPolicy.LEAST_LOADED)
    imbalance, domain_down = {}, {}
    compile_s_total = 0.0
    for pol in pols:
        row, cs = measure(pol)
        imbalance[pol.name.lower()] = row
        compile_s_total += cs
        row, cs = measure(pol, chaos_script=down)
        domain_down[pol.name.lower()] = row
        compile_s_total += cs

    headline = imbalance["threshold"]
    return {
        "metric": "hier_task_offload_decisions_per_sec",
        "value": headline["decisions_per_sec"],
        "unit": "decisions/s",
        "backend": backend,
        "n_brokers": n_brokers,
        "n_users": n_users,
        "n_fogs": n_fogs,
        "horizon_s": horizon,
        "dt": dt,
        "interval": interval,
        "hier_rtt_s": rtt,
        "policy": "min_busy",
        "decisions": headline["decisions"],
        "wall_s": headline["wall_s"],
        "imbalance": imbalance,
        "domain_down": domain_down,
        "compile_s": round(compile_s_total, 1),
        "compile_cache": {
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in compile_stats().items()
        },
        "conservation": "spawned = completed + dropped + lost + "
        "in-flight + hop-exhausted; tests/test_hier.py",
    }


def reconfig_measurement() -> dict:
    """Warm re-configuration benchmark (ISSUE 13): cold compile vs warm
    knob tweak on the promoted (shape-key + DynSpec operand) path.

    ``python bench.py --reconfig`` builds a chaos-on world at a pinned
    CPU-friendly shape, pays the cold compile ONCE via the promoted
    ``run_jit``, then re-configures promoted knobs (RTT burst
    amplitude, MTBF, reward scale) and re-runs.  The warm run must
    trigger ZERO compile events (``compile_stats()`` snapshot/delta —
    the satellite accounting this round added) and land >= 10x faster
    than the cold compile; both numbers ride the JSON (``reconfig_s``
    next to ``compile_s``) so ``tools/bench_trend.py --check`` gates
    warm-reconfig regressions like any throughput loss.

    Headline value = compile_s / reconfig_s (the warm-reconfig speedup,
    higher is better — ratchet-compatible with bench_trend's
    best-prior comparison).

    Env knobs: BENCH_RECONFIG_USERS / BENCH_RECONFIG_FOGS /
    BENCH_RECONFIG_HORIZON / BENCH_RECONFIG_INTERVAL.
    """
    import jax
    import numpy as np

    from fognetsimpp_tpu import compile_cache
    from fognetsimpp_tpu.compile_cache import (
        compile_stats,
        enable_compile_cache,
        note_compile,
    )
    from fognetsimpp_tpu.core.engine import run_jit
    from fognetsimpp_tpu.dynspec import registry_stats
    from fognetsimpp_tpu.scenarios import smoke

    enable_compile_cache()
    backend = jax.default_backend()

    # Pinned CPU shape: the warm wall INCLUDES the re-configured run
    # itself (the honest number an operator waits for), so the horizon
    # is sized to the serve loop's chunk scale (150 ticks ~ one scrape
    # interval) rather than a long batch run — compile cost is
    # scan-length-invariant, run wall is not.
    n_users = _env_int("BENCH_RECONFIG_USERS", 256)
    n_fogs = _env_int("BENCH_RECONFIG_FOGS", 8)
    horizon = _env_float("BENCH_RECONFIG_HORIZON", 0.15)
    interval = _env_float("BENCH_RECONFIG_INTERVAL", 0.005)

    def build(**overrides):
        kw = dict(
            n_users=n_users,
            n_fogs=n_fogs,
            horizon=horizon,
            send_interval=interval,
            max_sends_per_user=int(horizon / interval) + 4,
            chaos=True,
            chaos_mtbf_s=0.1,
            chaos_mttr_s=0.05,
            chaos_rtt_amp=0.5,
            chaos_rtt_period_s=0.5,
            chaos_rtt_burst_prob=0.02,
            uplink_loss_prob=0.01,
        )
        kw.update(overrides)
        return smoke.build(**kw)

    # --- cold: first world in the shape bucket pays the compile -------
    spec, state, net, bounds = build()
    t0 = time.perf_counter()
    jax.block_until_ready(run_jit(spec, state, net, bounds, promote=True))
    compile_s = time.perf_counter() - t0
    note_compile(compile_s)

    # --- warm: re-configured knobs re-use the compiled program --------
    knob_tweaks = {
        "chaos_rtt_amp": 1.75,
        "chaos_rtt_burst_prob": 0.08,
        "chaos_mtbf_s": 0.05,
        "uplink_loss_prob": 0.04,
    }
    walls = []
    decisions = 0
    compiles_delta = 0.0
    for rep in range(3):
        spec2, state2, net2, bounds2 = build(**knob_tweaks)
        snap = compile_cache.snapshot()
        t0 = time.perf_counter()
        final = run_jit(spec2, state2, net2, bounds2, promote=True)
        jax.block_until_ready(final.metrics.n_scheduled)
        walls.append(time.perf_counter() - t0)
        compiles_delta += compile_cache.delta_since(snap)["compiles"]
        decisions = int(np.asarray(final.metrics.n_scheduled))
    reconfig_s = sorted(walls)[len(walls) // 2]

    return {
        "metric": "warm_reconfig_speedup",
        "value": round(compile_s / reconfig_s, 1),
        "unit": "x (cold compile / warm reconfig)",
        "backend": backend,
        "n_users": n_users,
        "n_fogs": n_fogs,
        "horizon_s": horizon,
        "dt": 1e-3,
        "policy": "min_busy",
        "compile_s": round(compile_s, 2),
        "reconfig_s": round(reconfig_s, 4),
        "reconfig_walls_s": [round(w, 4) for w in walls],
        "reconfig_compile_events": compiles_delta,
        "knob_tweaks": knob_tweaks,
        "decisions": decisions,
        "program_registry": registry_stats(),
        "compile_cache": {
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in compile_stats().items()
            if not isinstance(v, dict)
        },
        "promoted": "dynspec.split_spec: shape key static + DynSpec "
        "operand; bit-exact vs the static path (tests/test_dynspec.py)",
    }


def sharded_reconfig_measurement(mode: str, n_devices=None) -> dict:
    """Warm re-configuration on the SHARDED runners (ISSUE 20): cold
    compile vs warm knob tweak on the promoted TP tick / fleet scan.

    ``python bench.py --reconfig --tp`` (``mode="tp"``) pays the cold
    shard_map TP compile ONCE through the promoted
    :func:`~fognetsimpp_tpu.parallel.taskshard.run_tp_sharded` (shape
    key static, mesh-replicated DynSpec operand), then re-configures
    promoted knobs (uplink loss, send-stop time) and re-runs the SAME
    cached program — ``--reconfig --fleet`` (``mode="fleet"``) does the
    identical dance through :func:`~fognetsimpp_tpu.parallel.fleet
    .run_fleet` on a replica-sharded batch.  The warm runs must trigger
    ZERO compile events AND zero program-cache misses (both deltas ride
    the JSON) and beat the cold compile by the same >= 10x bar the
    single-device ``--reconfig`` row ships under —
    ``tools/bench_trend.py --check`` gates the sharded rows via the
    ``tp_reconfig_s`` / ``fleet_reconfig_s`` columns, like-for-like
    with the ISSUE 13 gate.

    Env knobs: BENCH_RECONFIG_TP_USERS / BENCH_RECONFIG_FLEET_USERS /
    BENCH_RECONFIG_FOGS / BENCH_RECONFIG_HORIZON /
    BENCH_RECONFIG_INTERVAL (shared with the single-device row).
    """
    import jax
    import numpy as np

    from fognetsimpp_tpu import compile_cache
    from fognetsimpp_tpu.compile_cache import (
        compile_stats,
        enable_compile_cache,
        note_compile,
    )
    from fognetsimpp_tpu.dynspec import registry_stats
    from fognetsimpp_tpu.parallel import (
        make_mesh,
        replicate_state,
        run_fleet,
        run_tp_sharded,
    )
    from fognetsimpp_tpu.parallel import fleet as _fleet_mod
    from fognetsimpp_tpu.parallel import taskshard as _ts_mod
    from fognetsimpp_tpu.scenarios import smoke

    assert mode in ("tp", "fleet"), mode
    enable_compile_cache()
    backend = jax.default_backend()
    D = int(n_devices or len(jax.devices()))

    # CPU-friendly sharded shapes: the TP world's user axis spans the
    # mesh (users divisible by D); the fleet runs D replicas of a small
    # world.  The warm wall includes the re-configured run itself (the
    # number an operator waits for at a serve chunk boundary).
    if mode == "tp":
        n_users = _env_int("BENCH_RECONFIG_TP_USERS", 1024)
    else:
        n_users = _env_int("BENCH_RECONFIG_FLEET_USERS", 256)
    n_fogs = _env_int("BENCH_RECONFIG_FOGS", 8)
    # shorter horizons than the single-device row: the warm wall
    # includes the re-configured run itself, and a chunk-boundary
    # retune advances ONE serve chunk (tens of ticks), not a batch
    # horizon — sized so the speedup quotes retune cost, not run cost
    if mode == "tp":
        horizon = _env_float("BENCH_RECONFIG_HORIZON", 0.03)
        interval = _env_float("BENCH_RECONFIG_INTERVAL", 0.0015)
    else:
        horizon = _env_float("BENCH_RECONFIG_HORIZON", 0.05)
        interval = _env_float("BENCH_RECONFIG_INTERVAL", 0.0025)

    def build(**overrides):
        # both knobs start in their promoted gate class (positive
        # loss, finite send-stop) so every retune stays in ONE shape
        # bucket — the gate flips are the recompiles, by design
        kw = dict(
            n_users=n_users,
            n_fogs=n_fogs,
            horizon=horizon,
            send_interval=interval,
            max_sends_per_user=int(horizon / interval) + 4,
            uplink_loss_prob=0.01,
            send_stop_time=horizon * 0.8,
        )
        kw.update(overrides)
        return smoke.build(**kw)

    if mode == "tp":
        mesh = make_mesh(D, axis_name="node")

        def run_once(sp, st, nt, bd):
            _, final = run_tp_sharded(
                sp, st, nt, bd, mesh, donate=True, promote=True
            )
            jax.block_until_ready(final.metrics.n_scheduled)
            return int(np.asarray(final.metrics.n_scheduled))

        def cache_misses():
            return _ts_mod._tp_program.cache_info().misses
    else:
        mesh = make_mesh(D)

        def run_once(sp, st, nt, bd):
            batch = replicate_state(sp, st, D, seed=0)
            final = run_fleet(
                sp, batch, nt, bd, mesh=mesh, donate=True, promote=True
            )
            jax.block_until_ready(final.metrics.n_scheduled)
            return int(np.asarray(final.metrics.n_scheduled).sum())

        def cache_misses():
            return _fleet_mod._fleet_run._cache_size()

    # --- cold: the first promoted sharded program pays the compile ----
    spec, state, net, bounds = build()
    t0 = time.perf_counter()
    decisions = run_once(spec, state, net, bounds)
    compile_s = time.perf_counter() - t0
    note_compile(compile_s)

    # --- warm: re-configured knobs re-use the compiled program --------
    knob_tweaks = {
        "uplink_loss_prob": 0.04,
        "send_stop_time": round(horizon * 0.3, 4),
    }
    walls = []
    compiles_delta = 0.0
    misses0 = cache_misses()
    for _rep in range(3):
        sp2, st2, nt2, bd2 = build(**knob_tweaks)
        snap = compile_cache.snapshot()
        t0 = time.perf_counter()
        decisions = run_once(sp2, st2, nt2, bd2)
        walls.append(time.perf_counter() - t0)
        compiles_delta += compile_cache.delta_since(snap)["compiles"]
    reconfig_s = sorted(walls)[len(walls) // 2]
    miss_delta = cache_misses() - misses0

    shape_extra = (
        {"tp_shards": D} if mode == "tp" else {"n_replicas": D}
    )
    return {
        "metric": f"{mode}_warm_reconfig_speedup",
        "value": round(compile_s / reconfig_s, 1),
        "unit": "x (cold compile / warm reconfig)",
        "backend": backend,
        "n_devices": D,
        **shape_extra,
        "n_users": n_users,
        "n_fogs": n_fogs,
        "horizon_s": horizon,
        "dt": 1e-3,
        "policy": "min_busy",
        "compile_s": round(compile_s, 2),
        "reconfig_s": round(reconfig_s, 4),
        f"{mode}_reconfig_s": round(reconfig_s, 4),
        "reconfig_walls_s": [round(w, 4) for w in walls],
        "reconfig_compile_events": compiles_delta,
        "program_cache_misses_delta": int(miss_delta),
        "knob_tweaks": knob_tweaks,
        "decisions": decisions,
        "program_registry": registry_stats(),
        "compile_cache": {
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in compile_stats().items()
            if not isinstance(v, dict)
        },
        "promoted": "sharded DynSpec operand (ISSUE 20): shape key "
        "static, promoted knobs mesh-replicated; bit-exact vs "
        "FNS_SPEC_PROMOTE=0 (tests/test_sharded_dynspec.py)",
    }


def twin_measurement() -> dict:
    """``bench.py --twin`` (ISSUE 17): the live-twin door latencies.

    Two numbers off one live carry:

    * ``ingest_rate`` — arrivals/s through the full input door (host
      ``IngestQueue.feed`` → chunk-boundary drain → the compiled
      draw-free injector), the rate bound on external traffic a live
      session can absorb between chunks;
    * ``whatif_latency_s`` — median warm wall of a
      ``BENCH_TWIN_CELLS``-cell ``run_whatif`` grid
      ``BENCH_TWIN_TICKS`` ticks ahead: the time-to-answer for "p95
      under these K retunings, from current state".  The warm asks ride
      the session's compiled fork program — ``whatif_compile_events``
      must stay 0 (tools/bench_trend.py --check gates it).

    Env knobs: BENCH_TWIN_USERS / BENCH_TWIN_FOGS / BENCH_TWIN_HORIZON /
    BENCH_TWIN_INTERVAL / BENCH_TWIN_BATCH / BENCH_TWIN_ROUNDS /
    BENCH_TWIN_CELLS / BENCH_TWIN_TICKS.
    """
    import jax
    import numpy as np

    from fognetsimpp_tpu import compile_cache
    from fognetsimpp_tpu.compile_cache import (
        compile_stats,
        enable_compile_cache,
    )
    from fognetsimpp_tpu.core.engine import run
    from fognetsimpp_tpu.scenarios import smoke
    from fognetsimpp_tpu.twin.ingest import IngestQueue, make_inject
    from fognetsimpp_tpu.twin.whatif import run_whatif

    enable_compile_cache()
    backend = jax.default_backend()
    n_users = _env_int("BENCH_TWIN_USERS", 256)
    n_fogs = _env_int("BENCH_TWIN_FOGS", 8)
    horizon = _env_float("BENCH_TWIN_HORIZON", 0.6)
    interval = _env_float("BENCH_TWIN_INTERVAL", 0.005)
    batch = _env_int("BENCH_TWIN_BATCH", 16)
    rounds = _env_int("BENCH_TWIN_ROUNDS", 20)
    cells = _env_int("BENCH_TWIN_CELLS", 8)
    ticks = _env_int("BENCH_TWIN_TICKS", 200)

    spec, state, net, bounds = smoke.build(
        n_users=n_users,
        n_fogs=n_fogs,
        horizon=horizon,
        send_interval=interval,
        max_sends_per_user=int(horizon / interval) + 4,
        telemetry=True,
        telemetry_hist=True,
        derive_acks=False,
        ingest=True,
        ingest_batch=batch,
        # positive loss: the what-if grid stays on the carry's side of
        # the 0-vs-positive trace gate (one shape bucket, one program)
        uplink_loss_prob=0.01,
    )
    # the live carry: advance past the connect handshake so injected
    # publishes actually land (the injector rejects unconnected users)
    carry, _ = run(spec, state, net, bounds, n_ticks=300)
    jax.block_until_ready(carry.t)

    # --- ingest_rate: feed -> drain -> compiled injection -------------
    queue = IngestQueue(capacity=max(batch * 8, 64))
    inject = make_inject(spec, net, queue)
    rng = np.random.default_rng(0)
    st = carry
    for u in rng.integers(0, n_users, size=batch):
        queue.feed(int(u), 500.0)
    st = inject(st, 0)  # warm the injector compile outside the timing
    jax.block_until_ready(st.t)
    fed = 0
    t0 = time.perf_counter()
    for r in range(rounds):
        for u in rng.integers(0, n_users, size=batch):
            queue.feed(int(u), 500.0)
            fed += 1
        st = inject(st, r + 1)
    jax.block_until_ready(st.t)
    ingest_wall = time.perf_counter() - t0
    ingest_rate = fed / ingest_wall if ingest_wall > 0 else 0.0
    ingest_stats = queue.stats()

    # --- whatif_latency_s: cold fork compile, then warm asks ----------
    knobs = {
        "uplink_loss_prob": [
            round(0.01 + 0.01 * i, 4) for i in range(cells)
        ]
    }
    t0 = time.perf_counter()
    run_whatif(spec, carry, net, bounds, knobs, ticks)
    whatif_cold = time.perf_counter() - t0
    walls = []
    compiles_delta = 0.0
    for _ in range(3):
        snap = compile_cache.snapshot()
        t0 = time.perf_counter()
        run_whatif(spec, carry, net, bounds, knobs, ticks)
        walls.append(time.perf_counter() - t0)
        compiles_delta += compile_cache.delta_since(snap)["compiles"]
    whatif_latency = sorted(walls)[len(walls) // 2]

    return {
        "metric": "twin_ingest_arrivals_per_sec",
        "value": round(ingest_rate, 1),
        "unit": "arrivals/s (feed -> chunk-boundary injection)",
        "backend": backend,
        "policy": "min_busy",
        "n_users": n_users,
        "n_fogs": n_fogs,
        "horizon_s": horizon,
        "dt": 1e-3,
        "ingest_rate": round(ingest_rate, 1),
        "ingest_batch": batch,
        "ingest_rounds": rounds,
        "ingest_wall_s": round(ingest_wall, 4),
        "ingest_injected": ingest_stats["injected"],
        "ingest_rejected": ingest_stats["rejected"],
        "whatif_latency_s": round(whatif_latency, 4),
        "whatif_walls_s": [round(w, 4) for w in walls],
        "whatif_cold_s": round(whatif_cold, 3),
        "whatif_cells": cells,
        "whatif_ticks": ticks,
        "whatif_compile_events": compiles_delta,
        "compile_cache": {
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in compile_stats().items()
            if not isinstance(v, dict)
        },
        "determinism": "injection is draw-free; a session replayed "
        "from its arrival log is bit-exact (tests/test_twin.py)",
    }


def twin_main() -> None:
    """``python bench.py --twin`` (or ``BENCH_TWIN=1``): the live-twin
    headline — ingest-door throughput + warm what-if grid latency."""
    print(json.dumps(twin_measurement()))


def reconfig_main() -> None:
    """``python bench.py --reconfig`` (or ``BENCH_RECONFIG=1``): the
    ISSUE 13 headline — cold compile vs zero-compile warm knob tweak."""
    print(json.dumps(reconfig_measurement()))


def sharded_reconfig_main(mode: str) -> None:
    """``python bench.py --reconfig --tp`` / ``--reconfig --fleet``
    (ISSUE 20): cold sharded compile vs zero-compile warm knob tweak on
    the promoted TP tick / fleet scan.  Provisions BENCH_DEVICES
    virtual CPU devices when needed, like the throughput entries."""
    n = _env_int("BENCH_DEVICES", 8)
    ensure_mesh_devices(n)
    print(json.dumps(sharded_reconfig_measurement(mode, n)))


def chaos_main() -> None:
    """``python bench.py --chaos`` (or ``BENCH_CHAOS=1``): the
    hostile-world headline — the bench world under fog churn + link
    degradation, one row per scheduling policy."""
    print(json.dumps(chaos_measurement()))


def hier_main() -> None:
    """``python bench.py --hier`` (or ``BENCH_HIER=1``): the federation
    headline — the imbalanced multi-broker world plus the domain-down
    chaos world, one row per migration policy."""
    print(json.dumps(hier_measurement()))


def tp_main() -> None:
    """``python bench.py --tp`` (or ``BENCH_TP=1``): the TP capacity
    headline — one ≥1M-user world sharded over BENCH_DEVICES devices."""
    n = _env_int("BENCH_DEVICES", 8)
    ensure_mesh_devices(n)
    print(json.dumps(tp_measurement(n)))


def fleet_main() -> None:
    """``python bench.py --fleet`` (or ``BENCH_FLEET=1``): the multi-chip
    headline.  Provisions BENCH_DEVICES virtual CPU devices when needed
    (an unset platform is respected — a real multi-chip host measures
    its own hardware), then prints the :func:`fleet_measurement` JSON
    line."""
    n = _env_int("BENCH_DEVICES", 8)
    ensure_mesh_devices(n)
    print(json.dumps(fleet_measurement(n)))


if __name__ == "__main__":
    import sys

    _reconfig = "--reconfig" in sys.argv or os.environ.get("BENCH_RECONFIG")
    # --reconfig composes with --tp/--fleet (ISSUE 20): the sharded
    # warm-retune rows — checked FIRST so the modifier flags don't
    # swallow the reconfig entry
    if _reconfig and ("--tp" in sys.argv or os.environ.get("BENCH_TP")):
        sharded_reconfig_main("tp")
    elif _reconfig and (
        "--fleet" in sys.argv or os.environ.get("BENCH_FLEET")
    ):
        sharded_reconfig_main("fleet")
    elif "--fleet" in sys.argv or os.environ.get("BENCH_FLEET"):
        fleet_main()
    elif "--tp" in sys.argv or os.environ.get("BENCH_TP"):
        tp_main()
    elif "--chaos" in sys.argv or os.environ.get("BENCH_CHAOS"):
        chaos_main()
    elif "--hier" in sys.argv or os.environ.get("BENCH_HIER"):
        hier_main()
    elif _reconfig:
        reconfig_main()
    elif "--twin" in sys.argv or os.environ.get("BENCH_TWIN"):
        twin_main()
    else:
        main()
