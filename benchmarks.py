"""Benchmark suite: the BASELINE.json config grid on real hardware.

``bench.py`` is the driver-facing headline number (one JSON line); this
script reproduces the rest of BASELINE.json's config ladder and prints one
JSON line per config:

  2. 100-node grid, round-robin policy, single replica
  3. 1k-node world, greedy min-latency, 64 vmap replicas
  4. 10k-node mobile-handover world (APs + moving users + energy churn),
     energy-aware scheduler, replica fan-out sized to HBM
  5. policy x load parameter sweep (4 schedulers x 16 load levels)

Measured results are recorded in BENCHMARKS.md.  Each config times the
second invocation of the jitted program (compile excluded).

Run: ``python benchmarks.py [2 3 4 5 5b]``
"""
from __future__ import annotations

import json
import sys
import time


def _decisions(out):
    """Fetch (and thereby sync) the decision count from a config output."""
    import numpy as np

    metrics = out[0] if isinstance(out, tuple) else out
    return int(np.sum(np.asarray(metrics.n_scheduled)))


def _timed(go, arg, rekey, n_pipeline=3):
    """Time ``n_pipeline`` queued invocations with ONE trailing sync.

    ``jax.block_until_ready`` resolves before device completion on the
    tunneled runtime and a blocking fetch costs a flat ~95 ms (tunnel
    latency, not chip time — see bench.py), so each config enqueues a
    short pipeline of runs (fresh PRNG key each) and fetches at the end:
    sustained throughput, fixed cost amortized.  Returns
    (last_output, wall_seconds, total_decisions); callers multiply tick
    counts by ``n_pipeline``.
    """
    out = go(arg)
    _decisions(out)  # warm + compile + sync
    args = [rekey(arg, 1 + i) for i in range(n_pipeline)]
    t0 = time.perf_counter()
    outs = [go(a) for a in args]
    decisions = sum(_decisions(o) for o in outs)
    wall = time.perf_counter() - t0
    return outs[-1], wall, decisions, n_pipeline


def _emit(name, wall, decisions, ticks, extra=None):
    out = {
        "config": name,
        "wall_s": round(wall, 3),
        "decisions": int(decisions),
        "decisions_per_sec": round(decisions / wall, 1),
        "ticks_per_sec": round(ticks / wall, 1),
    }
    out.update(extra or {})
    print(json.dumps(out), flush=True)


def config2():
    """100-node grid, ROUND_ROBIN, single replica."""
    import jax
    import numpy as np

    from fognetsimpp_tpu.core.engine import run
    from fognetsimpp_tpu.scenarios import smoke
    from fognetsimpp_tpu.spec import Policy

    spec, state, net, bounds = smoke.build(
        n_users=96, n_fogs=4, policy=int(Policy.ROUND_ROBIN),
        send_interval=0.01, horizon=1.0, dt=1e-3,
        max_sends_per_user=104, arrival_window=1024,
    )
    go = jax.jit(lambda s: run(spec, s, net, bounds)[0].metrics)
    f, wall, dec, n_pipe = _timed(
        go, state, lambda s, i: s.replace(key=jax.random.PRNGKey(i))
    )
    _emit("2:100-node-grid-rr", wall, dec, spec.n_ticks * n_pipe)


def config3():
    """1k-node world, MIN_LATENCY, 64 vmap replicas."""
    import jax
    import numpy as np

    from fognetsimpp_tpu.core.engine import run
    from fognetsimpp_tpu.parallel import replicate_state
    from fognetsimpp_tpu.scenarios import smoke
    from fognetsimpp_tpu.spec import Policy

    R = 64
    spec, state, net, bounds = smoke.build(
        n_users=1000, n_fogs=24, policy=int(Policy.MIN_LATENCY),
        send_interval=0.01, horizon=0.25, dt=1e-3,
        max_sends_per_user=29, arrival_window=256,
        start_time_max=0.05,
    )
    batch = replicate_state(spec, state, R, seed=0)
    go = jax.jit(
        lambda b: jax.vmap(lambda s: run(spec, s, net, bounds)[0].metrics)(b)
    )
    f, wall, dec, n_pipe = _timed(
        go, batch,
        lambda b, i: b.replace(key=jax.random.split(jax.random.PRNGKey(i), R)),
    )
    _emit("3:1k-node-minlat-64rep", wall, dec, spec.n_ticks * R * n_pipe,
          {"replicas": R})


def config4(R: int = None, horizon: float = None):
    """10k-node mobile-handover world, ENERGY_AWARE, replica fan-out.

    The BASELINE.json-stated scale is "10k nodes, 1k replicas" — r5
    delivers it (4 x 250-replica chunks, one compile; BENCHMARKS.md
    row 4).  History: r4's run crashed the tunnel's TPU worker at
    R >= 256 — diagnosed in r5 as the classic arrival front-end's
    (F,T) fast-drop matmuls, whose vmap-expanded intermediates blew up
    under the replica axis; with the two-stage front-end R=512 runs
    monolithically and R=1000 fails as an ordinary RESOURCE_EXHAUSTED,
    which the chunking sidesteps.  CONFIG4_R / CONFIG4_HORIZON /
    CONFIG4_CHUNK override the defaults.  Pipeline depth 1: a run is
    ~30 s of device time, so the ~0.1 s tunnel overhead is amortized.
    """
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fognetsimpp_tpu.core.engine import run
    from fognetsimpp_tpu.parallel import replicate_state
    from fognetsimpp_tpu.scenarios import wireless
    from fognetsimpp_tpu.spec import Policy

    if R is None:
        R = int(os.environ.get("CONFIG4_R", 128))
    if horizon is None:
        horizon = float(os.environ.get("CONFIG4_HORIZON", 0.5))
    kw = dict(
        numb_users=10_000, horizon=horizon, dt=5e-3,
        policy=int(Policy.ENERGY_AWARE),
        send_interval=0.05, queue_capacity=64,
        # r5 (VERDICT r4 item 2): the linear-model escape hatch is
        # retired.  64 APs (5 reference + 59 grid) give ~156 stations
        # per cell at 20 fps each — ~3.1k offered frames/s/cell, just
        # above the single-frame 802.11g service rate, so the REAL
        # Bianchi model runs with a physical effective-contender count
        # (n_eff ~ 2, mild extra delay, near-zero retry loss) instead
        # of r4's choice between tab[2000] saturation and a rescaled
        # linear coefficient
        extra_aps=59,
        mac_model="bianchi",
    )
    spec0, *_ = wireless.wireless5(**kw)
    spec, state, net, bounds = wireless.wireless5(
        arrival_window=spec0.auto_arrival_window, **kw
    )
    def final(s):
        fs = run(spec, s, net, bounds)[0]
        return fs.metrics, jnp.sum(fs.nodes.alive.astype(jnp.int32))

    # the stated 1k-replica scale runs as sequential chunks under ONE
    # compile (identical shapes; CONFIG4_CHUNK overrides).  r5 bisect:
    # the r4 worker crash at R>=256 was the classic front-end's (F,T)
    # fast-drop matmuls (vmap-expanded intermediates); with the two-stage
    # front-end R=512 runs monolithically and R=1000 fails as an
    # ordinary RESOURCE_EXHAUSTED — hence chunks (BENCHMARKS.md row 4)
    import time as _time

    chunk = min(R, int(os.environ.get("CONFIG4_CHUNK", 250)))
    n_chunks = -(-R // chunk)
    R = chunk * n_chunks  # actual simulated count (exact when chunk | R)
    batch = replicate_state(spec, state, chunk, seed=0)
    go = jax.jit(lambda b: jax.vmap(final)(b))
    go(batch)[0].n_scheduled.block_until_ready()  # compile once
    t0 = _time.perf_counter()
    dec = 0
    ndm, alive_min = 0, 10**9
    for c in range(n_chunks):
        b = batch.replace(
            key=jax.random.split(jax.random.PRNGKey(1000 + c), chunk)
        )
        f = go(b)
        dec += int(np.asarray(f[0].n_scheduled).sum())
        ndm = max(ndm, int(np.asarray(f[0].n_deferred_max).max()))
        alive_min = min(alive_min, int(np.asarray(f[1]).min()))
    wall = _time.perf_counter() - t0
    _emit(
        f"4:10k-mobile-energy-{R}rep", wall, dec,
        spec.n_ticks * chunk * n_chunks,
        {"replicas": R,
         "chunk": chunk,
         "n_chunks": n_chunks,
         "arrival_window": spec.window,
         "n_deferred_max": ndm,
         "alive_min": alive_min})


def config5(dynamic: bool = False, n_users: int = 10_000,
            n_loads: int = 256, chunk: int = 32):
    """10k nodes x 4 schedulers x 256 load levels (EP x load sweep).

    The BASELINE.json-stated scale.  The grid is processed in load-axis
    chunks of ``chunk`` vmap replicas (a whole 256-load x 10k-node batch
    would need ~20 GB); every chunk builds the IDENTICAL spec (the global
    heaviest interval sizes the send budget), so the compiled program is
    reused across chunks — one compile per policy (or one total with
    ``dynamic=True``, config "5b").
    """
    import numpy as np

    from fognetsimpp_tpu.parallel import sweep_policies
    from fognetsimpp_tpu.scenarios import smoke
    from fognetsimpp_tpu.spec import Policy

    loads = list(np.geomspace(0.01, 0.16, n_loads))
    policies = [Policy.MIN_BUSY, Policy.ROUND_ROBIN, Policy.MIN_LATENCY,
                Policy.ENERGY_AWARE]
    n_rep = 1
    horizon, dt = 0.25, 5e-3
    build_kw = dict(
        n_users=n_users, n_fogs=32, horizon=horizon, dt=dt,
        send_interval=min(loads),  # same spec shape for every chunk
        max_sends_per_user=int(horizon / min(loads)) + 4,
        arrival_window=4096, queue_capacity=64, start_time_max=0.05,
    )
    t0 = time.perf_counter()
    decisions = 0
    for c0 in range(0, len(loads), chunk):
        grids = sweep_policies(
            smoke.build,
            policies=policies,
            load_intervals=loads[c0 : c0 + chunk],
            n_replicas_per_load=n_rep,
            dynamic=dynamic,
            **build_kw,
        )
        decisions += sum(int(g["n_scheduled"].sum()) for g in grids.values())
    wall = time.perf_counter() - t0  # includes the compile(s)
    n_ticks = int(round(horizon / dt)) * len(policies) * len(loads) * n_rep
    name = "5b:policy-sweep-dynamic" if dynamic else "5:policy-x-load-sweep"
    note = ("wall includes ONE whole-grid compile (Policy.DYNAMIC)"
            if dynamic else
            f"wall includes {len(policies)} policy compiles")
    _emit(name, wall, decisions, n_ticks,
          {"grid": f"{n_users} users x {len(policies)} policies x "
                   f"{len(loads)} loads x {n_rep} replicas",
           "chunk": chunk,
           "note": note})


if __name__ == "__main__":
    from fognetsimpp_tpu.compile_cache import enable_compile_cache

    enable_compile_cache()
    table = {"2": config2, "3": config3, "4": config4, "5": config5,
             "5b": lambda: config5(dynamic=True)}
    which = sys.argv[1:] or ["2", "3", "4", "5"]
    for n in which:
        table[n]()
