"""Benchmark suite: the BASELINE.json config grid on real hardware.

``bench.py`` is the driver-facing headline number (one JSON line); this
script reproduces the rest of BASELINE.json's config ladder and prints one
JSON line per config:

  2. 100-node grid, round-robin policy, single replica
  3. 1k-node world, greedy min-latency, 64 vmap replicas
  4. 10k-node mobile-handover world (APs + moving users + energy churn),
     energy-aware scheduler, replica fan-out sized to HBM
  5. policy x load parameter sweep (4 schedulers x 16 load levels)

Measured results are recorded in BENCHMARKS.md.  Each config times the
second invocation of the jitted program (compile excluded).

Run: ``python benchmarks.py [2 3 4 5 5b]``
"""
from __future__ import annotations

import json
import sys
import time


def _decisions(out):
    """Fetch (and thereby sync) the decision count from a config output."""
    import numpy as np

    metrics = out[0] if isinstance(out, tuple) else out
    return int(np.sum(np.asarray(metrics.n_scheduled)))


def _timed(go, arg, rekey, n_pipeline=3):
    """Time ``n_pipeline`` queued invocations with ONE trailing sync.

    ``jax.block_until_ready`` resolves before device completion on the
    tunneled runtime and a blocking fetch costs a flat ~95 ms (tunnel
    latency, not chip time — see bench.py), so each config enqueues a
    short pipeline of runs (fresh PRNG key each) and fetches at the end:
    sustained throughput, fixed cost amortized.  Returns
    (last_output, wall_seconds, total_decisions); callers multiply tick
    counts by ``n_pipeline``.
    """
    out = go(arg)
    _decisions(out)  # warm + compile + sync
    args = [rekey(arg, 1 + i) for i in range(n_pipeline)]
    t0 = time.perf_counter()
    outs = [go(a) for a in args]
    decisions = sum(_decisions(o) for o in outs)
    wall = time.perf_counter() - t0
    return outs[-1], wall, decisions, n_pipeline


def _emit(name, wall, decisions, ticks, extra=None):
    out = {
        "config": name,
        "wall_s": round(wall, 3),
        "decisions": int(decisions),
        "decisions_per_sec": round(decisions / wall, 1),
        "ticks_per_sec": round(ticks / wall, 1),
    }
    out.update(extra or {})
    print(json.dumps(out), flush=True)


def config2():
    """100-node grid, ROUND_ROBIN, single replica."""
    import jax
    import numpy as np

    from fognetsimpp_tpu.core.engine import run
    from fognetsimpp_tpu.scenarios import smoke
    from fognetsimpp_tpu.spec import Policy

    spec, state, net, bounds = smoke.build(
        n_users=96, n_fogs=4, policy=int(Policy.ROUND_ROBIN),
        send_interval=0.01, horizon=1.0, dt=1e-3,
        max_sends_per_user=104, arrival_window=1024,
    )
    go = jax.jit(lambda s: run(spec, s, net, bounds)[0].metrics)
    f, wall, dec, n_pipe = _timed(
        go, state, lambda s, i: s.replace(key=jax.random.PRNGKey(i))
    )
    _emit("2:100-node-grid-rr", wall, dec, spec.n_ticks * n_pipe)


def config3():
    """1k-node world, MIN_LATENCY, 64 vmap replicas."""
    import jax
    import numpy as np

    from fognetsimpp_tpu.core.engine import run
    from fognetsimpp_tpu.parallel import replicate_state
    from fognetsimpp_tpu.scenarios import smoke
    from fognetsimpp_tpu.spec import Policy

    R = 64
    spec, state, net, bounds = smoke.build(
        n_users=1000, n_fogs=24, policy=int(Policy.MIN_LATENCY),
        send_interval=0.01, horizon=0.25, dt=1e-3,
        max_sends_per_user=29, arrival_window=256,
        start_time_max=0.05,
    )
    batch = replicate_state(spec, state, R, seed=0)
    go = jax.jit(
        lambda b: jax.vmap(lambda s: run(spec, s, net, bounds)[0].metrics)(b)
    )
    f, wall, dec, n_pipe = _timed(
        go, batch,
        lambda b, i: b.replace(key=jax.random.split(jax.random.PRNGKey(i), R)),
    )
    _emit("3:1k-node-minlat-64rep", wall, dec, spec.n_ticks * R * n_pipe,
          {"replicas": R})


def config4():
    """10k-node mobile-handover world, ENERGY_AWARE, 8 replicas."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fognetsimpp_tpu.core.engine import run
    from fognetsimpp_tpu.parallel import replicate_state
    from fognetsimpp_tpu.scenarios import wireless
    from fognetsimpp_tpu.spec import Policy

    R = 8
    spec, state, net, bounds = wireless.wireless5(
        numb_users=10_000, horizon=2.0, dt=5e-3,
        policy=int(Policy.ENERGY_AWARE),
        send_interval=0.05, arrival_window=2048, queue_capacity=64,
        # 2000 stations/AP: per-station contention rescaled from the
        # 10-user calibration or the cell saturates (see wireless5)
        w_contention=1.5e-3 * 10 / 10_000,
    )
    batch = replicate_state(spec, state, R, seed=0)

    def final(s):
        fs = run(spec, s, net, bounds)[0]
        return fs.metrics, jnp.sum(fs.nodes.alive.astype(jnp.int32))

    go = jax.jit(lambda b: jax.vmap(final)(b))
    f, wall, dec, n_pipe = _timed(
        go, batch,
        lambda b, i: b.replace(key=jax.random.split(jax.random.PRNGKey(i), R)),
    )
    _emit("4:10k-mobile-energy-8rep", wall, dec, spec.n_ticks * R * n_pipe,
          {"replicas": R,
           "alive_min": int(np.asarray(f[1]).min())})


def config5(dynamic: bool = False):
    """4 schedulers x 16 load levels (EP x load sweep).

    ``dynamic=True`` (config "5b") runs the whole grid under one compile
    via Policy.DYNAMIC.
    """
    import numpy as np

    from fognetsimpp_tpu.parallel import sweep_policies
    from fognetsimpp_tpu.scenarios import smoke
    from fognetsimpp_tpu.spec import Policy

    loads = list(np.geomspace(0.005, 0.08, 16))
    policies = [Policy.MIN_BUSY, Policy.ROUND_ROBIN, Policy.MIN_LATENCY,
                Policy.ENERGY_AWARE]
    n_rep = 4
    horizon, dt = 0.25, 1e-3
    t0 = time.perf_counter()
    grids = sweep_policies(
        smoke.build,
        policies=policies,
        load_intervals=loads,
        n_replicas_per_load=n_rep,
        dynamic=dynamic,
        n_users=256, n_fogs=8, horizon=horizon, dt=dt,
        arrival_window=512, start_time_max=0.05,
    )
    wall = time.perf_counter() - t0  # includes the compile(s)
    decisions = sum(int(g["n_scheduled"].sum()) for g in grids.values())
    n_ticks = int(round(horizon / dt)) * len(policies) * len(loads) * n_rep
    name = "5b:policy-sweep-dynamic" if dynamic else "5:policy-x-load-sweep"
    note = ("wall includes ONE whole-grid compile (Policy.DYNAMIC)"
            if dynamic else
            f"wall includes {len(policies)} policy compiles")
    _emit(name, wall, decisions, n_ticks,
          {"grid": f"{len(policies)} policies x {len(loads)} loads x "
                   f"{n_rep} replicas",
           "note": note})


if __name__ == "__main__":
    from fognetsimpp_tpu.compile_cache import enable_compile_cache

    enable_compile_cache()
    table = {"2": config2, "3": config3, "4": config4, "5": config5,
             "5b": lambda: config5(dynamic=True)}
    which = sys.argv[1:] or ["2", "3", "4", "5"]
    for n in which:
        table[n]()
