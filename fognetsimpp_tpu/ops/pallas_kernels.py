"""Pallas TPU kernels for the engine's hot ops.

The arrival planner's within-fog rank is an O(K²) pairwise comparison
(``ops/queues.plan_arrivals``): for each of K same-tick arrivals, count the
arrivals to the same fog that precede it in (time, id) order.  XLA executes
that as several (K, K) elementwise kernels plus a row reduction; the Pallas
version streams row tiles through VMEM and fuses compare + reduce into one
kernel — one pass over the K-vectors, no materialised (K, K) intermediates
in HBM.

Measured head-to-head on the v5e at K=4096 (the bench window), the fused
Pallas kernel is ~14% *slower* end-to-end than XLA's own fusion of the
jnp formulation (1.11M vs 1.29M decisions/s) — the compiler already tiles
the compare+reduce well, and the hand-written grid adds overhead.  It is
therefore **opt-in** (`FNS_PALLAS_RANK=1`), kept as the template for
future hot ops where XLA's lowering is actually the bottleneck (cf. the
serialized `jnp.nonzero` the engine replaced).  ``interpret=True`` makes
the kernel testable on CPU (tests/test_pallas.py asserts equality with
the jnp path).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_ROW_TILE = 512
_MAX_K = 4096


def pallas_rank_applicable(K: int) -> bool:
    """Opt-in (FNS_PALLAS_RANK=1) + tile-aligned window on a TPU backend."""
    tk = min(_ROW_TILE, K)
    return (
        os.environ.get("FNS_PALLAS_RANK", "0") == "1"
        and K % 128 == 0
        and K % tk == 0  # grid rows must tile K exactly
        and K <= _MAX_K
        and jax.default_backend() == "tpu"
    )


def _rank_kernel(fog_all, t_all, mask_all, fog_row, t_row, mask_row, rank_ref,
                 *, tk: int, K: int):
    i = pl.program_id(0)
    fc = fog_all[0, :]  # (K,) column views
    tc = t_all[0, :]
    mc = mask_all[0, :]
    fr = fog_row[0, :]  # (tk,) this tile's rows
    tr = t_row[0, :]
    mr = mask_row[0, :]

    col_id = jax.lax.broadcasted_iota(jnp.int32, (tk, K), 1)
    row_id = i * tk + jax.lax.broadcasted_iota(jnp.int32, (tk, K), 0)

    same = fc[None, :] == fr[:, None]
    earlier = (tc[None, :] < tr[:, None]) | (
        (tc[None, :] == tr[:, None]) & (col_id < row_id)
    )
    before = same & earlier & mc[None, :]
    rank = jnp.sum(before.astype(jnp.int32), axis=1)
    rank_ref[0, :] = jnp.where(mr, rank, -1)


def pairwise_rank(
    mask: jax.Array,  # (K,) bool
    fog_key: jax.Array,  # (K,) i32 — destination fog (already sentinel-keyed)
    t_key: jax.Array,  # (K,) f32 — arrival time (inf where masked out)
    interpret: bool = False,
) -> jax.Array:
    """(K,) i32 within-fog arrival rank; -1 where masked out."""
    K = mask.shape[0]
    tk = min(_ROW_TILE, K)
    assert K % tk == 0, (K, tk)

    full = pl.BlockSpec((1, K), lambda i: (0, 0))
    row = pl.BlockSpec((1, tk), lambda i: (0, i))
    out = pl.pallas_call(
        functools.partial(_rank_kernel, tk=tk, K=K),
        out_shape=jax.ShapeDtypeStruct((1, K), jnp.int32),
        grid=(K // tk,),
        in_specs=[full, full, full, row, row, row],
        out_specs=row,
        interpret=interpret,
    )(
        fog_key.reshape(1, K), t_key.reshape(1, K), mask.reshape(1, K),
        fog_key.reshape(1, K), t_key.reshape(1, K), mask.reshape(1, K),
    )
    return out[0]
