"""Pallas TPU kernels for the engine's hot ops.

The arrival planner's within-fog rank is an O(K²) pairwise comparison
(``ops/queues.plan_arrivals``): for each of K same-tick arrivals, count the
arrivals to the same fog that precede it in (time, id) order.  XLA executes
that as several (K, K) elementwise kernels plus a row reduction; the Pallas
version streams row tiles through VMEM and fuses compare + reduce into one
kernel — one pass over the K-vectors, no materialised (K, K) intermediates
in HBM.

Measured head-to-head on the v5e at K=4096 (the bench window), the fused
Pallas kernel is ~14% *slower* end-to-end than XLA's own fusion of the
jnp formulation (1.11M vs 1.29M decisions/s) — the compiler already tiles
the compare+reduce well, and the hand-written grid adds overhead.  It is
therefore **opt-in** (`FNS_PALLAS_RANK=1`), kept as the template for
future hot ops where XLA's lowering is actually the bottleneck (cf. the
serialized `jnp.nonzero` the engine replaced).  ``interpret=True`` makes
the kernel testable on CPU (tests/test_pallas.py asserts equality with
the jnp path).
"""
from __future__ import annotations

import functools
import os
import sys

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_ROW_TILE = 512
_MAX_K = 4096

#: (env, reason) pairs already reported to stderr — the note is emitted
#: once per distinct disqualification, not once per trace.
_warned: set = set()


def _optin_note(env: str, reason: str) -> None:
    """One-line stderr note when an opt-in kernel's env flag is set but
    the shape/backend disqualifies it — so opt-in users aren't silently
    left on the XLA path wondering why nothing changed."""
    key = (env, reason)
    if key not in _warned:
        _warned.add(key)
        print(
            f"fognetsimpp_tpu: {env}=1 requested but {reason}; "
            "falling back to the XLA path",
            file=sys.stderr,
        )


def pallas_rank_applicable(K: int) -> bool:
    """Opt-in (FNS_PALLAS_RANK=1) + tile-aligned window on a TPU backend."""
    if os.environ.get("FNS_PALLAS_RANK", "0") != "1":
        return False
    tk = min(_ROW_TILE, K)
    if not (K % 128 == 0 and K % tk == 0 and K <= _MAX_K):
        _optin_note(
            "FNS_PALLAS_RANK",
            f"window K={K} is not 128-aligned within the {_MAX_K} tile "
            "budget",
        )
        return False
    backend = jax.default_backend()
    if backend != "tpu":
        _optin_note(
            "FNS_PALLAS_RANK", f"backend is {backend!r}, not tpu"
        )
        return False
    return True


def pallas_arrival_applicable(K: int, F: int) -> bool:
    """Opt-in (FNS_PALLAS_ARRIVAL=1) gate for the fused decide-and-reduce
    arrival kernel: tile-aligned window, a bounded fog axis, TPU backend.
    Same one-line stderr note discipline as the rank kernel when the
    opt-in is set but the shape disqualifies."""
    if os.environ.get("FNS_PALLAS_ARRIVAL", "0") != "1":
        return False
    tk = min(_ROW_TILE, K)
    if not (K % 128 == 0 and K % tk == 0 and K <= _MAX_K and F <= 1024):
        _optin_note(
            "FNS_PALLAS_ARRIVAL",
            f"window K={K} / F={F} is outside the tile-aligned "
            f"{_MAX_K}-window, F<=1024 envelope",
        )
        return False
    backend = jax.default_backend()
    if backend != "tpu":
        _optin_note(
            "FNS_PALLAS_ARRIVAL", f"backend is {backend!r}, not tpu"
        )
        return False
    return True


def _arrival_plan_kernel(
    fog_all, t_all, mask_all, fog_row, t_row, mask_row,
    rank_ref, cnt_ref, tmin_ref, first_ref, *, tk: int, K: int, F: int,
):
    """Fused decide-and-reduce over one row tile: the within-fog rank
    (the O(K^2) pairwise compare + row-sum) AND the per-fog arrival
    reductions (count, earliest (time, position) lex-min) in a single
    pass over the tile — no (K, K) or (F, K) HBM intermediates.  The
    per-fog outputs map every grid step to the same block and
    accumulate across the sequential grid (int adds and lex-min are
    associative and exact, so the result is bit-identical to the jnp
    reference reductions)."""
    i = pl.program_id(0)
    fc = fog_all[0, :]  # (K,) column views
    tc = t_all[0, :]
    mc = mask_all[0, :]
    fr = fog_row[0, :]  # (tk,) this tile's rows
    tr = t_row[0, :]
    mr = mask_row[0, :]

    col_id = jax.lax.broadcasted_iota(jnp.int32, (tk, K), 1)
    row_id = i * tk + jax.lax.broadcasted_iota(jnp.int32, (tk, K), 0)

    same = fc[None, :] == fr[:, None]
    earlier = (tc[None, :] < tr[:, None]) | (
        (tc[None, :] == tr[:, None]) & (col_id < row_id)
    )
    before = same & earlier & mc[None, :]
    rank = jnp.sum(before.astype(jnp.int32), axis=1)
    rank_ref[0, :] = jnp.where(mr, rank, -1)

    # per-fog reduce over this tile's rows
    pos = i * tk + jax.lax.broadcasted_iota(jnp.int32, (F, tk), 1)
    fid = jax.lax.broadcasted_iota(jnp.int32, (F, tk), 0)
    memb = (fr[None, :] == fid) & mr[None, :]  # (F, tk)
    cnt_tile = jnp.sum(memb.astype(jnp.int32), axis=1)
    tmat = jnp.where(memb, tr[None, :], jnp.inf)
    tmin_tile = jnp.min(tmat, axis=1)
    is_min = memb & (tmat == tmin_tile[:, None])
    pos_tile = jnp.min(jnp.where(is_min, pos, K), axis=1)

    @pl.when(i == 0)
    def _init():
        cnt_ref[0, :] = jnp.zeros((F,), jnp.int32)
        tmin_ref[0, :] = jnp.full((F,), jnp.inf, jnp.float32)
        first_ref[0, :] = jnp.full((F,), K, jnp.int32)

    prev_t = tmin_ref[0, :]
    prev_p = first_ref[0, :]
    take = (tmin_tile < prev_t) | (
        (tmin_tile == prev_t) & (pos_tile < prev_p)
    )
    cnt_ref[0, :] = cnt_ref[0, :] + cnt_tile
    tmin_ref[0, :] = jnp.where(take, tmin_tile, prev_t)
    first_ref[0, :] = jnp.where(take, pos_tile, prev_p)


def fused_arrival_plan(
    mask: jax.Array,  # (K,) bool
    fog_key: jax.Array,  # (K,) i32 (sentinel-keyed, like pairwise_rank)
    t_key: jax.Array,  # (K,) f32 (inf where masked out)
    n_fogs: int,
    interpret: bool = False,
):
    """(rank (K,), counts (F,), t_min (F,), first (F,)) in ONE Pallas
    kernel — the arrival tail's "decide" (within-fog rank + earliest
    arrival) and "reduce" (per-fog counts) fused.  ``interpret=True``
    runs the same kernel on CPU (tests/test_pallas.py asserts exact
    equality with the jnp reference path)."""
    K = mask.shape[0]
    F = n_fogs
    tk = min(_ROW_TILE, K)
    assert K % tk == 0, (K, tk)

    full = pl.BlockSpec((1, K), lambda i: (0, 0))
    row = pl.BlockSpec((1, tk), lambda i: (0, i))
    fogb = pl.BlockSpec((1, F), lambda i: (0, 0))
    rank, cnt, tmin, first = pl.pallas_call(
        functools.partial(_arrival_plan_kernel, tk=tk, K=K, F=F),
        out_shape=(
            jax.ShapeDtypeStruct((1, K), jnp.int32),
            jax.ShapeDtypeStruct((1, F), jnp.int32),
            jax.ShapeDtypeStruct((1, F), jnp.float32),
            jax.ShapeDtypeStruct((1, F), jnp.int32),
        ),
        grid=(K // tk,),
        in_specs=[full, full, full, row, row, row],
        out_specs=(row, fogb, fogb, fogb),
        interpret=interpret,
    )(
        fog_key.reshape(1, K), t_key.reshape(1, K), mask.reshape(1, K),
        fog_key.reshape(1, K), t_key.reshape(1, K), mask.reshape(1, K),
    )
    return rank[0], cnt[0], tmin[0], first[0]


def pallas_ring_applicable(
    ndim: int, n_shards: int, merged: bool = False
) -> bool:
    """Opt-in (FNS_PALLAS_RING=1) gate for the remote-DMA ring kernel
    used by the TP arrival exchange.  TPU backend only — the portable
    default is the ``lax.ppermute`` ring; ``interpret=True`` runs the
    identical kernel on CPU (tests/test_tp.py asserts exact equality
    with both the ppermute ring and a dense reference).  Takes the
    static rank (not the traced array) so the host-side gate never
    touches traced values (simlint R2).

    ``merged=True`` is the WINDOWED exchange
    (``parallel/taskshard.ring_topk_merge``): each hop merges the
    incoming K-slot window and truncates back to K, so the payload
    stays ``(K, W)`` — NOT the ``(n*K, W)`` all-gather shape this
    kernel produces.  The gate declines (with the opt-in note) rather
    than let ``FNS_PALLAS_RING=1`` silently hand the merge path a
    wrong-shaped gather; a merge-capable kernel (per-hop
    :func:`ops.queues.topk_merge_sorted` stage between the remote
    copies) is the follow-up that would flip this.
    """
    if os.environ.get("FNS_PALLAS_RING", "0") != "1":
        return False
    if merged:
        _optin_note(
            "FNS_PALLAS_RING",
            "the remote-DMA kernel all-gathers (n*K, W); the windowed "
            "exchange needs a per-hop top-K merge to a (K, W) payload "
            "— keeping the lax.ppermute merge ring",
        )
        return False
    if n_shards < 2 or ndim != 2:
        return False
    backend = jax.default_backend()
    if backend != "tpu":
        _optin_note("FNS_PALLAS_RING", f"backend is {backend!r}, not tpu")
        return False
    return True


def ring_all_gather_pallas(
    x: jax.Array,  # (K, C) — this shard's block
    axis_name: str,
    n_shards: int,
    interpret: bool = False,
) -> jax.Array:
    """(n*K, C) ring all-gather via Pallas remote DMA (SNIPPETS [2]).

    Each step remote-copies the block received last step (double-
    buffered comm scratch, per-slot DMA semaphores) to the RIGHT
    neighbor and files the incoming block at its home offset, so after
    ``n-1`` hops every shard holds the blocks in global shard order —
    the same contract as the ``lax.ppermute`` ring it replaces.  Must
    be called inside a ``shard_map`` body over ``axis_name``.  Opt-in
    (:func:`pallas_ring_applicable`): the XLA collective-permute path
    is the measured default until a chip session proves this kernel
    wins (the fused_arrival_plan discipline).

    This kernel serves the NO-WINDOW exchange only
    (``taskshard.ring_all_gather``): the output is the full ``(n*K,
    C)`` gather.  The windowed exchange (``taskshard.ring_topk_merge``)
    keeps a ``(K, C)`` payload by merging+truncating at every hop —
    :func:`pallas_ring_applicable` declines ``merged=True`` until this
    kernel grows that per-hop merge stage.
    """
    from jax.experimental.pallas import tpu as pltpu

    K, C = x.shape
    n = n_shards

    def kernel(x_ref, out_ref, comm_ref, send_sem, recv_sem):
        my_id = jax.lax.axis_index(axis_name)
        # local block straight to its home slot
        out_ref[pl.ds(my_id * K, K), :] = x_ref[...]
        comm_ref[0] = x_ref[...]
        for step in range(n - 1):
            send_slot = step % 2
            recv_slot = 1 - send_slot
            dst = jax.lax.rem(my_id + 1, n)
            rdma = pltpu.make_async_remote_copy(
                src_ref=comm_ref.at[send_slot],
                dst_ref=comm_ref.at[recv_slot],
                send_sem=send_sem.at[send_slot],
                recv_sem=recv_sem.at[recv_slot],
                device_id=dst,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdma.wait()
            src = jax.lax.rem(my_id - step - 1 + n, n)
            out_ref[pl.ds(src * K, K), :] = comm_ref[recv_slot]

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n * K, C), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, K, C), x.dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(x)


def _rank_kernel(fog_all, t_all, mask_all, fog_row, t_row, mask_row, rank_ref,
                 *, tk: int, K: int):
    i = pl.program_id(0)
    fc = fog_all[0, :]  # (K,) column views
    tc = t_all[0, :]
    mc = mask_all[0, :]
    fr = fog_row[0, :]  # (tk,) this tile's rows
    tr = t_row[0, :]
    mr = mask_row[0, :]

    col_id = jax.lax.broadcasted_iota(jnp.int32, (tk, K), 1)
    row_id = i * tk + jax.lax.broadcasted_iota(jnp.int32, (tk, K), 0)

    same = fc[None, :] == fr[:, None]
    earlier = (tc[None, :] < tr[:, None]) | (
        (tc[None, :] == tr[:, None]) & (col_id < row_id)
    )
    before = same & earlier & mc[None, :]
    rank = jnp.sum(before.astype(jnp.int32), axis=1)
    rank_ref[0, :] = jnp.where(mr, rank, -1)


def pairwise_rank(
    mask: jax.Array,  # (K,) bool
    fog_key: jax.Array,  # (K,) i32 — destination fog (already sentinel-keyed)
    t_key: jax.Array,  # (K,) f32 — arrival time (inf where masked out)
    interpret: bool = False,
) -> jax.Array:
    """(K,) i32 within-fog arrival rank; -1 where masked out."""
    K = mask.shape[0]
    tk = min(_ROW_TILE, K)
    assert K % tk == 0, (K, tk)

    full = pl.BlockSpec((1, K), lambda i: (0, 0))
    row = pl.BlockSpec((1, tk), lambda i: (0, i))
    out = pl.pallas_call(
        functools.partial(_rank_kernel, tk=tk, K=K),
        out_shape=jax.ShapeDtypeStruct((1, K), jnp.int32),
        grid=(K // tk,),
        in_specs=[full, full, full, row, row, row],
        out_specs=row,
        interpret=interpret,
    )(
        fog_key.reshape(1, K), t_key.reshape(1, K), mask.reshape(1, K),
        fog_key.reshape(1, K), t_key.reshape(1, K), mask.reshape(1, K),
    )
    return out[0]
