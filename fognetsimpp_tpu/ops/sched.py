"""Scheduler kernels: the base broker's placement decision, batched.

The reference makes one decision per publish arrival with an O(F) scan
(``src/mqttapp/BrokerBaseApp3.cc:267-281``).  Here a whole tick's worth of
arrivals is decided in one (T, F) score matrix + row argmin — the op the MXU
was built for.  Crucially this batching is *faithful*: the reference broker
does NOT update its ``brokers[]`` busy view after assigning (the view is only
refreshed by in-flight advertisements, ``BrokerBaseApp3.cc:123-136``), so
same-window arrivals all see the same snapshot there too.

Policies beyond MIN_BUSY realise the dead ``algo`` parameter
(``BrokerBaseApp3.ned:26``, SURVEY.md App. B item 4) as live kernels; they
share the same signature so the policy axis is sweepable.  With
``policy=Policy.DYNAMIC`` the argmin-family policy is selected by the
*traced* ``policy_id`` value (``lax.switch``), so a whole policy × load ×
replica grid runs under ONE compile — the EP axis as data
(SURVEY.md §2.3 EP row; vmap turns the switch into a masked select over
branches, trading a few extra scheduler kernels for zero recompiles).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..learn.bandits import (
    BanditArms,
    ducb_scores,
    exp3_probs,
    exp3_sample,
    ucb_scores,
)
from ..spec import Policy

_BIG = jnp.float32(3.4e38)


def task_uniform(base_key: jax.Array, task_ids: jax.Array) -> jax.Array:
    """Per-task unit draws: u[i] = U(fold_in(base_key, task_ids[i])).

    A pure function of the task id, independent of tick batching or
    execution order — the RANDOM policy's shared stream.  The native DES
    receives these exact f32 values (``bridge.replay_engine_world``).
    """
    return jax.vmap(
        lambda i: jax.random.uniform(jax.random.fold_in(base_key, i))
    )(task_ids)


def scalar_winner(
    policy: int,
    view_busy: jax.Array,  # (F,)
    view_mips: jax.Array,  # (F,)
    registered: jax.Array,  # (F,) bool
    fog_alive: jax.Array,  # (F,) bool (ENERGY_AWARE)
    fog_energy_frac: jax.Array,  # (F,)
    rtt_broker_fog: jax.Array,  # (F,) (MIN_LATENCY)
    v1_max_scan: bool,
) -> jax.Array:
    """The task-independent winner for the engine's dense broker path.

    With the faithful ``mips0_divisor`` quirk the per-task estimate term
    is constant across fog nodes, so MIN_BUSY / MIN_LATENCY /
    ENERGY_AWARE argmins (and the v1/v2 MAX_MIPS scan, batch-global by
    construction) collapse to one scalar — the same formulas as
    :func:`schedule_batch`'s per-task branches, kept HERE so the
    reference-bug-faithful scans have a single home (the dense/compacted
    equivalence is gate-tested via the DYNAMIC-vs-static sweep tests and
    the DES parity suite).  Returns () i32 fog index, -1 = no resource.
    """
    F = view_busy.shape[0]
    i32 = jnp.int32
    if F == 0:
        return jnp.full((), -1, i32)
    avail = registered
    # brokers[0] anchors = the FIRST REGISTERED fog (registration order)
    first_reg = jnp.argmax(avail).astype(i32)
    if policy == int(Policy.MAX_MIPS):
        # (LOCAL_FIRST deliberately NOT accepted: its local-pool branch is
        # sequential and has no dense-path equivalent — engine gate
        # _broker_dense_ok keeps it on the compacted path)
        idx = jnp.arange(F, dtype=i32)
        if v1_max_scan:
            cand = (
                avail
                & (idx > first_reg)
                & (view_mips > view_mips[first_reg])
            )
            last = jnp.max(jnp.where(cand, idx, -1))
            return jnp.where(last >= 0, last, first_reg).astype(i32)
        return jnp.argmax(jnp.where(avail, view_mips, -jnp.inf)).astype(i32)
    if policy == int(Policy.MIN_BUSY):
        base, avail_ = view_busy, avail
    elif policy == int(Policy.MIN_LATENCY):
        base, avail_ = rtt_broker_fog + view_busy, avail
    elif policy == int(Policy.ENERGY_AWARE):
        base = view_busy + 10.0 * (1.0 - fog_energy_frac)
        avail_ = avail & fog_alive
    else:
        raise ValueError(f"no scalar winner for policy {policy}")
    scores = jnp.nan_to_num(jnp.where(avail_, base, _BIG), posinf=_BIG)
    choice0 = jnp.argmin(scores).astype(i32)
    # est = mips_req / brokers[0].MIPS is +inf until the first advert
    # lands (MIPS=0 registration): every candidate scores +inf, the C++
    # strict-< scan never updates, and the winner stays its initial value
    # — brokers[0], i.e. the FIRST REGISTERED fog (ADVICE r3: anchoring
    # array slot 0 here diverged whenever fog slot 0 registered last)
    choice0 = jnp.where(view_mips[first_reg] > 0, choice0, first_reg)
    return jnp.where(jnp.any(avail_), choice0, -1).astype(i32)


def _safe_div(a: jax.Array, b: jax.Array) -> jax.Array:
    """a / b with b==0 -> +inf (matches C++ double division by zero).

    The broker registers fog nodes with MIPS=0 (``BrokerBaseApp3.cc:104``)
    until the first advertisement arrives, so early estimates are +inf in the
    reference as well.
    """
    return jnp.where(b > 0, a / jnp.where(b > 0, b, 1.0), jnp.inf)


def schedule_batch(
    policy: int,  # static; Policy.DYNAMIC dispatches on policy_id instead
    mask: jax.Array,  # (T,) bool — publishes being decided this tick
    mips_req: jax.Array,  # (T,) f32
    view_busy: jax.Array,  # (F,) f32 broker's stale busyTime view
    view_mips: jax.Array,  # (F,) f32 broker's stale MIPS view
    registered: jax.Array,  # (F,) bool
    fog_alive: jax.Array,  # (F,) bool — used by ENERGY_AWARE / RANDOM only
    fog_energy_frac: jax.Array,  # (F,) f32 in [0,1]
    rtt_broker_fog: jax.Array,  # (F,) f32 — 2*d(B,f), for MIN_LATENCY
    rr_cursor: jax.Array,  # () i32
    key: jax.Array,  # PRNG key for RANDOM
    mips0_divisor: bool,  # static bug-compat switch (SURVEY App. B item 1)
    v1_max_scan: bool = True,  # static bug-compat switch (MAX_MIPS scan)
    policy_id: Optional[jax.Array] = None,  # () i32, traced (DYNAMIC only)
    order_t: Optional[jax.Array] = None,  # (T,) f32 arrival times: orders
    #   same-window ROUND_ROBIN slots by event time (ties by index) the way
    #   a sequential broker would; None = compacted-index order
    rand_u: Optional[jax.Array] = None,  # (T,) f32 per-task unit draws for
    #   RANDOM — a pure function of the global task id (engine supplies
    #   task_uniform(spec.policy_seed, ids)) so the native DES can consume
    #   the identical stream; None derives a stream from `key` + index
    #   (unit-test convenience, no DES parity).  EXP3 samples its arm
    #   from the same stream.
    learn: Optional[BanditArms] = None,  # bandit arm statistics view
    #   (learn/bandits.py), required for the learned policies UCB/DUCB/
    #   EXP3; when supplied under DYNAMIC the traced switch also covers
    #   the bandit ids 8-10
    fog_owner: Optional[jax.Array] = None,  # (F,) i32 broker owning each
    #   fog (hier/): when given (with task_broker + n_brokers), every
    #   policy's candidate set is masked to the task's OWN broker domain
    #   — each logical broker decides over its local fog slice, with
    #   per-domain brokers[0] anchors and per-domain bandit-score
    #   totals.  None (the default) is the single-broker fast path,
    #   byte-identical to the pre-hier kernels.
    task_broker: Optional[jax.Array] = None,  # (T,) i32 owning broker
    #   per decided task (HierState.task_broker gathered at the window)
    n_brokers: int = 1,  # static broker count B
) -> Tuple[jax.Array, jax.Array]:
    """Pick a fog node for every masked task. Returns ((T,) i32 fog, rr').

    MIN_BUSY reproduces ``BrokerBaseApp3.cc:267-281`` exactly, including the
    first-wins tie-break of the ``<`` comparison and (optionally) the bug of
    dividing every candidate's estimate by ``brokers[0]``'s MIPS.
    """
    T = mask.shape[0]
    F = view_busy.shape[0]
    if F == 0:
        # no fog nodes exist: every decision is "no compute resource
        # available" (BrokerBaseApp3.cc:306-319); caller handles the ack
        return jnp.full((T,), -1, jnp.int32), rr_cursor
    avail = registered  # reference never evicts dead fogs (App. B item 7)
    # ``brokers[0]`` is the FIRST REGISTERED fog (registration order), not
    # array slot 0 — they differ only in the window where fog slot 0 has
    # not yet connected (ADVICE r2: the native DES anchored registration
    # order while this anchored slot 0)
    first_reg = jnp.argmax(avail).astype(jnp.int32)  # 0 if none

    # ---- federated hierarchy (hier/): per-domain candidate masking ----
    # Each logical broker owns a disjoint fog slice; its brokers[0]
    # anchor is the first registered fog OF ITS DOMAIN, and a task may
    # only score fogs its owning broker sees.  Static gate: fog_owner
    # is None on every single-broker world, so the pre-hier trace is
    # untouched.
    hier = fog_owner is not None
    if hier:
        B = n_brokers
        owned = (
            fog_owner[None, :]
            == jnp.arange(B, dtype=jnp.int32)[:, None]
        )  # (B, F)
        tb = jnp.clip(task_broker, 0, B - 1)  # (T,)
        avail_b = avail[None, :] & owned  # (B, F)
        first_reg_b = jnp.argmax(avail_b, axis=1).astype(jnp.int32)
        first_reg_t = first_reg_b[tb]  # (T,) per-domain brokers[0]
        allowed = owned[tb]  # (T, F) domain membership per task row

    if hier and mips0_divisor:
        # per-domain brokers[0] divisor (the mips0 quirk, tiled per
        # broker): every candidate of task i divides by the anchor of
        # i's own domain
        est = _safe_div(mips_req[:, None], view_mips[first_reg_t][:, None])
    else:
        divisor = view_mips[first_reg] if mips0_divisor else view_mips
        est = _safe_div(
            mips_req[:, None], jnp.broadcast_to(divisor, (F,))[None, :]
        )

    if policy in (int(Policy.MAX_MIPS), int(Policy.LOCAL_FIRST)):
        if hier:
            # per-domain batch-global winner (the v1/v2 scan, tiled):
            # winner_b over each domain's available slice, selected per
            # task by its owning broker
            idx = jnp.arange(F, dtype=jnp.int32)
            if v1_max_scan:
                anchor_mips = view_mips[first_reg_b]  # (B,)
                cand_b = (
                    avail_b
                    & (idx[None, :] > first_reg_b[:, None])
                    & (view_mips[None, :] > anchor_mips[:, None])
                )
                last_b = jnp.max(
                    jnp.where(cand_b, idx[None, :], -1), axis=1
                )
                winner_b = jnp.where(
                    last_b >= 0, last_b, first_reg_b
                ).astype(jnp.int32)
            else:
                winner_b = jnp.argmax(
                    jnp.where(avail_b, view_mips[None, :], -jnp.inf),
                    axis=1,
                ).astype(jnp.int32)
            any_b = jnp.any(avail_b, axis=1)
            winner_t = jnp.where(any_b[tb], winner_b[tb], -1)
            return (
                jnp.where(mask, winner_t, -1).astype(jnp.int32),
                rr_cursor,
            )
        # v1/v2 offload pick (BrokerBaseApp.cc:228-240): one winner for the
        # whole batch — the scan does not depend on the task.  With the
        # faithful bug (v1_max_scan) ``temp`` stays brokers[0]'s MIPS, so the
        # winner is the LAST fog whose MIPS beats fog 0's (or fog 0 itself).
        # LOCAL_FIRST's offload branch is exactly this scan (same function,
        # sendPubAck(status=false)); its local branch is decided by the
        # engine against the broker's own pool.  The engine also applies the
        # per-task guard ``MIPSRequired < winner MIPS`` (BrokerBaseApp.cc:
        # 244) — a failing task is never sent anywhere.
        idx = jnp.arange(F, dtype=jnp.int32)
        if v1_max_scan:
            cand = avail & (idx > first_reg) & (view_mips > view_mips[first_reg])
            last = jnp.max(jnp.where(cand, idx, -1))
            winner = jnp.where(last >= 0, last, first_reg).astype(jnp.int32)
        else:
            winner = jnp.argmax(jnp.where(avail, view_mips, -jnp.inf)).astype(
                jnp.int32
            )
        return jnp.where(mask, winner, -1).astype(jnp.int32), rr_cursor

    def from_scores(scores, avail_):
        if hier:
            # domain-masked rows: fogs outside the task's domain score
            # _BIG, the all-big fallback anchors on the task's OWN
            # domain's brokers[0], and "no available fog" is judged per
            # domain
            ok = avail_[None, :] & allowed
            scores = jnp.where(ok, scores, _BIG)
            scores = jnp.nan_to_num(scores, posinf=_BIG)
            choice = jnp.argmin(scores, axis=1).astype(jnp.int32)
            all_big = jnp.all(scores >= _BIG, axis=1)
            choice = jnp.where(all_big, first_reg_t, choice)
            any_t = jnp.any(avail_[None, :] & owned, axis=1)[tb]
            choice = jnp.where(any_t, choice, -1)
            return jnp.where(mask, choice, -1).astype(jnp.int32), rr_cursor
        scores = jnp.where(avail_[None, :], scores, _BIG)
        # all-inf rows (early publishes before any advertisement, with the
        # MIPS=0 registration): the C++ strict-< scan never updates, so the
        # winner stays its initial value — brokers[0], the FIRST REGISTERED
        # fog (ADVICE r3: a plain argmin over an all-_BIG row picked array
        # slot 0 instead, diverging when fog slot 0 registered last)
        scores = jnp.nan_to_num(scores, posinf=_BIG)
        choice = jnp.argmin(scores, axis=1).astype(jnp.int32)
        all_big = jnp.all(scores >= _BIG, axis=1)
        choice = jnp.where(all_big, first_reg, choice)
        # no available fog at all -> -1 (caller routes to Stage.NO_RESOURCE)
        choice = jnp.where(jnp.any(avail_), choice, -1)
        return jnp.where(mask, choice, -1).astype(jnp.int32), rr_cursor

    def b_min_busy():
        return from_scores(view_busy[None, :] + est, avail)

    def b_round_robin():
        if hier:
            # validate() gates this combination; the kernel refuses too
            # so a hand-built spec cannot silently share one cursor
            # across domains
            raise ValueError(
                "ROUND_ROBIN does not federate (single shared cursor); "
                "WorldSpec.validate() should have rejected this spec"
            )
        # k-th masked task of this tick gets fog (rr + k) % F among avail;
        # k follows the event order a sequential broker would see (arrival
        # time, ties by task index) when order_t is supplied
        if order_t is None:
            k = jnp.cumsum(mask.astype(jnp.int32)) - 1  # rank within batch
        else:
            ids = jnp.arange(T, dtype=jnp.int32)
            order = jnp.lexsort((ids, jnp.where(mask, order_t, jnp.inf)))
            rank_sorted = jnp.cumsum(mask[order].astype(jnp.int32)) - 1
            k = jnp.zeros((T,), jnp.int32).at[order].set(rank_sorted)
        n_avail = jnp.maximum(jnp.sum(avail.astype(jnp.int32)), 1)
        slot = (rr_cursor + k) % n_avail
        # map slot -> index of the slot-th available fog
        avail_rank = jnp.cumsum(avail.astype(jnp.int32)) - 1  # (F,)
        fog_of_slot = jnp.zeros((F,), jnp.int32).at[
            jnp.where(avail, avail_rank, F)
        ].set(jnp.arange(F, dtype=jnp.int32), mode="drop")
        choice = fog_of_slot[slot]
        choice = jnp.where(jnp.any(avail), choice, -1)
        rr_new = (rr_cursor + jnp.sum(mask.astype(jnp.int32))) % n_avail
        return jnp.where(mask, choice, -1).astype(jnp.int32), rr_new

    def b_min_latency():
        return from_scores(
            rtt_broker_fog[None, :] + view_busy[None, :] + est, avail
        )

    def b_energy_aware():
        # prefer energy-rich fogs; dead fogs are unusable (when every fog is
        # dead the all-masked argmin would silently pick fog 0 — the guard
        # in from_scores returns -1 so the caller routes to NO_RESOURCE)
        scores = (
            view_busy[None, :] + est
            + 10.0 * (1.0 - fog_energy_frac)[None, :]
        )
        return from_scores(scores, avail & fog_alive)

    def b_random():
        ok = avail & fog_alive
        if rand_u is None:
            u = task_uniform(key, jnp.arange(T, dtype=jnp.int32))
        else:
            u = rand_u
        if hier:
            # per-domain uniform pick: the task-id-keyed draw indexes
            # into its OWN domain's available slice (same stream, per-
            # domain slot tables)
            ok_b = ok[None, :] & owned  # (B, F)

            def per_domain(okb):
                n = jnp.sum(okb.astype(jnp.int32))
                rank = jnp.cumsum(okb.astype(jnp.int32)) - 1
                fos = jnp.zeros((F,), jnp.int32).at[
                    jnp.where(okb, rank, F)
                ].set(jnp.arange(F, dtype=jnp.int32), mode="drop")
                return n, fos

            n_ok_b, fos_b = jax.vmap(per_domain)(ok_b)
            n_ok_t = n_ok_b[tb]  # (T,)
            slot = jnp.clip(
                (u * n_ok_t.astype(jnp.float32)).astype(jnp.int32),
                0,
                jnp.maximum(n_ok_t - 1, 0),
            )
            choice = fos_b[tb, slot]
            choice = jnp.where(n_ok_t > 0, choice, -1)
            return jnp.where(mask, choice, -1).astype(jnp.int32), rr_cursor
        n_ok = jnp.sum(ok.astype(jnp.int32))
        # slot = floor(u * n_ok) in f32 — the DES computes the identical
        # float expression so boundary rounding agrees bit-for-bit
        slot = jnp.clip(
            (u * n_ok.astype(jnp.float32)).astype(jnp.int32),
            0,
            jnp.maximum(n_ok - 1, 0),
        )
        ok_rank = jnp.cumsum(ok.astype(jnp.int32)) - 1  # (F,)
        fog_of_slot = jnp.zeros((F,), jnp.int32).at[
            jnp.where(ok, ok_rank, F)
        ].set(jnp.arange(F, dtype=jnp.int32), mode="drop")
        choice = fog_of_slot[slot]
        choice = jnp.where(n_ok > 0, choice, -1)
        return jnp.where(mask, choice, -1).astype(jnp.int32), rr_cursor

    # ---- learned bandit policies (learn/bandits.py) -------------------
    # UCB/DUCB are task-independent masked argmaxes over the arm index
    # vector — one winner per window, exactly the shape of the argmin
    # family above; EXP3 samples per task from the softmax weights via
    # the task-id-keyed uniform stream.  Dead fogs are unusable (a pick
    # would never ack, starving the learner of its own reward signal).
    def _winner_from_index(scores, avail_):
        win = jnp.argmax(jnp.where(avail_, scores, -_BIG)).astype(jnp.int32)
        win = jnp.where(jnp.any(avail_), win, -1)
        return jnp.where(mask, win, -1).astype(jnp.int32), rr_cursor

    def _winner_per_domain(score_fn):
        # per-broker bandit slice: each domain's index argmax runs over
        # its OWN available arms with its OWN exploration total (the
        # score_fn sees only the domain's availability mask), so B
        # brokers learn B independent schedulers over one shared (F,)
        # statistics table — the slices are disjoint because domains
        # partition fogs
        ok_b = (avail & fog_alive)[None, :] & owned  # (B, F)
        scores_b = jax.vmap(lambda av: score_fn(learn, av))(ok_b)
        win_b = jnp.argmax(
            jnp.where(ok_b, scores_b, -_BIG), axis=1
        ).astype(jnp.int32)
        any_b = jnp.any(ok_b, axis=1)
        win_t = jnp.where(any_b[tb], win_b[tb], -1)
        return jnp.where(mask, win_t, -1).astype(jnp.int32), rr_cursor

    def b_ucb():
        if hier:
            return _winner_per_domain(ucb_scores)
        return _winner_from_index(
            ucb_scores(learn, avail & fog_alive), avail & fog_alive
        )

    def b_ducb():
        if hier:
            return _winner_per_domain(ducb_scores)
        return _winner_from_index(
            ducb_scores(learn, avail & fog_alive), avail & fog_alive
        )

    def b_exp3():
        ok = avail & fog_alive
        if rand_u is None:
            u = task_uniform(key, jnp.arange(T, dtype=jnp.int32))
        else:
            u = rand_u
        if hier:
            # per-domain softmax: broker b's distribution lives on its
            # own arms only; each task inverse-CDF samples from its
            # domain's row with the shared task-id-keyed stream
            ok_b = ok[None, :] & owned  # (B, F)
            p_b = jax.vmap(
                lambda av: exp3_probs(learn.logw, av, learn.explore)
            )(ok_b)  # (B, F)
            cdf_b = jnp.cumsum(p_b, axis=1)
            total_t = cdf_b[tb, F - 1]  # (T,)
            target = jnp.clip(u, 1e-7, 1.0) * total_t
            arm = jnp.argmax(
                cdf_b[tb] >= target[:, None], axis=1
            ).astype(jnp.int32)
            choice = jnp.where(total_t > 0, arm, -1)
            return jnp.where(mask, choice, -1).astype(jnp.int32), rr_cursor
        p = exp3_probs(learn.logw, ok, learn.explore)
        choice = exp3_sample(p, u)
        return jnp.where(mask, choice, -1).astype(jnp.int32), rr_cursor

    branches = {
        int(Policy.MIN_BUSY): b_min_busy,
        int(Policy.ROUND_ROBIN): b_round_robin,
        int(Policy.MIN_LATENCY): b_min_latency,
        int(Policy.ENERGY_AWARE): b_energy_aware,
        int(Policy.RANDOM): b_random,
        int(Policy.UCB): b_ucb,
        int(Policy.DUCB): b_ducb,
        int(Policy.EXP3): b_exp3,
    }
    if policy == int(Policy.DYNAMIC):
        if hier:
            raise ValueError(
                "Policy.DYNAMIC does not federate (n_brokers > 1); "
                "WorldSpec.validate() should have rejected this spec"
            )
        if policy_id is None:
            raise ValueError("Policy.DYNAMIC needs a traced policy_id")

        def b_invalid():
            # out-of-family id: fail loudly — nothing schedules (all
            # NO_RESOURCE) instead of silently running a remapped policy
            return jnp.full((T,), -1, jnp.int32), rr_cursor

        ordered = [branches[p] for p in range(5)] + [b_invalid]
        idx = jnp.where(
            (policy_id < 0) | (policy_id > 4), 5, policy_id
        ).astype(jnp.int32)
        if learn is not None:
            # the traced switch additionally covers the bandit ids: the
            # branch table appends [ucb, ducb, exp3] at 6..8 and the id
            # remap sends 8..10 there (5..7 stay invalid — LOCAL_FIRST/
            # MAX_MIPS/DYNAMIC have no traced dispatch)
            ordered = ordered + [b_ucb, b_ducb, b_exp3]
            bandit = (policy_id >= int(Policy.UCB)) & (
                policy_id <= int(Policy.EXP3)
            )
            idx = jnp.where(bandit, policy_id - 2, idx).astype(jnp.int32)
        return jax.lax.switch(idx, ordered)
    if policy in (int(Policy.UCB), int(Policy.DUCB), int(Policy.EXP3)):
        if learn is None:
            raise ValueError(
                f"policy {Policy(policy).name} needs the bandit arm view "
                "(learn=BanditArms)"
            )
        return branches[policy]()
    if policy not in branches:
        raise ValueError(f"unknown policy {policy}")
    return branches[policy]()
