"""Batched ring-buffer FIFO ops for fog-node queues.

The reference keeps one unbounded ``std::vector<Request*>`` per fog node and
mutates it one message at a time (``src/mqttapp/ComputeBrokerApp3.cc:304-314``
push, ``:236-252`` pop-front).  Here every fog node's FIFO is one row of a
fixed-capacity ``(F, Q)`` ring buffer and *all* fog nodes enqueue/dequeue in
one batched, jit-compiled operation per tick — including the case of many
tasks arriving at the same fog node in the same tick, which is resolved by an
in-tick stable sort (arrival time, then task id) so FIFO order matches the
event-driven execution.

In-tick write conflicts (two tasks -> one fog) are the batched analog of the
data races a threaded DES would have; they are resolved *by construction*
with rank-computation + scatter, never by locking (SURVEY.md §5 "race
detection" note).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

NO_TASK = -1


class ArrivalPlan(NamedTuple):
    """Result of planning same-tick arrivals at all fog nodes at once.

    Attributes:
      assign_task: (F,) i32 — task id to assign to each *idle* fog node now
        (NO_TASK where no arrival / fog busy).  This is the arrival that the
        sequential DES would have served first (min arrival time, ties by
        task id).
      rank: (T,) i32 — within-fog arrival rank of every masked-in task
        (0 = first); -1 for masked-out tasks.
      counts: (F,) i32 — number of masked-in arrivals per fog.
    """

    assign_task: jax.Array
    rank: jax.Array
    counts: jax.Array


# Above this batch width the O(K^2) pairwise rank falls back to sorting.
_PAIRWISE_MAX = 4096


def row_lexmin(keys: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-row ``(min, argmin)`` of a 2-D array in ONE variadic reduce.

    Bit-identical to ``(jnp.min(keys, 1), jnp.argmin(keys, 1))`` —
    first-occurrence tie-break included (the comparator prefers the
    lower index on equal values, and lexicographic min is associative,
    so the reduction tree cannot change the result) — but the two
    reductions collapse into a single HLO reduce: the fused tick's
    kernel-count discipline (tools/op_budget.py).  ``keys`` must be
    NaN-free (the engine's keys are times or +inf).
    """
    n_rows, n_cols = keys.shape
    ids = jax.lax.broadcasted_iota(jnp.int32, keys.shape, 1)

    def comb(a, b):
        av, ai = a
        bv, bi = b
        take_a = (av < bv) | ((av == bv) & (ai <= bi))
        return (jnp.where(take_a, av, bv), jnp.where(take_a, ai, bi))

    return jax.lax.reduce(
        (keys, ids),
        (jnp.float32(jnp.inf), jnp.int32(n_cols)),
        comb,
        (1,),
    )


def topk_merge_sorted(a: jax.Array, b: jax.Array) -> jax.Array:
    """Merge two K-row payload windows sorted ascending on the LAST column
    and truncate back to the best K rows.

    ``a`` and ``b`` are ``(K, W)`` i32 payload blocks whose last column is
    the sort key (the TP exchange ring's global scan-order position).  The
    merge is one binary-search rank per side (``searchsorted`` against the
    OTHER side's sorted keys — O(K log K), no sort network and no O(K^2)
    comparison matrix) + two drop-mode scatters.  Each output rank in
    ``[0, K)`` is written exactly once: ``rank_a[i] = i + |{j : b[j] <
    a[i]}|`` and ``rank_b[j] = j + |{i : a[i] <= b[j]}|`` partition the
    merged order with ``a`` winning ties, so for globally-unique keys
    (every valid candidate has a distinct scan position; padding rows are
    bit-identical sentinels) the result is set-determined — independent of
    which shard's window arrives as ``a`` vs ``b``, which is what makes the
    hop-merged ring replicate bit-coherently on every shard.
    """
    K = a.shape[0]
    av, bv = a[:, -1], b[:, -1]
    k = jnp.arange(K, dtype=jnp.int32)
    rank_a = k + jnp.searchsorted(bv, av, side="left").astype(jnp.int32)
    rank_b = k + jnp.searchsorted(av, bv, side="right").astype(jnp.int32)
    out = jnp.zeros_like(a)
    out = out.at[rank_a].set(a, mode="drop")
    out = out.at[rank_b].set(b, mode="drop")
    return out


def plan_arrivals(
    mask: jax.Array,  # (K,) bool — tasks arriving at a fog this tick
    fog: jax.Array,  # (K,) i32 — destination fog per task
    t_arrive: jax.Array,  # (K,) f32 — exact arrival time
    n_fogs: int,
    fog_idle: jax.Array,  # (F,) bool — fog can take a task immediately
    per_fog: jax.Array = None,  # (F, K) bool membership (fog[k]==f & mask),
    #   precomputed by the caller when it already needs the matrix
    fused: bool = False,  # fused tick (engine._fused_ok): merge the
    #   first-arrival min/argmin into one variadic reduce and SKIP the
    #   per-fog counts (the fused tail folds those into its single
    #   merged reduction) — returns counts=None
) -> ArrivalPlan:
    """Compute per-fog arrival order for a batch of same-tick arrivals.

    For bench-sized windows (K <= 4096) the within-fog rank is one fused
    O(K^2) pairwise comparison + row-sum — dramatically cheaper on TPU than
    a bitonic ``lexsort`` chain (tens of sequential sort stages per tick for
    a few thousand elements).  Larger windows fall back to the sort path.
    The per-fog counts and first arrival (min time, ties by id) are (F, K)
    masked reduces over the membership matrix — vectorised VPU rows instead
    of serialized ~6 ns/element scatter-min/add kernels (profiled r3).
    """
    K = mask.shape[0]
    ids = jnp.arange(K, dtype=jnp.int32)
    f_key = jnp.where(mask, fog, n_fogs).astype(jnp.int32)
    t_key = jnp.where(mask, t_arrive, jnp.inf)
    if per_fog is None:
        per_fog = (
            fog[None, :] == jnp.arange(n_fogs, dtype=jnp.int32)[:, None]
        ) & mask[None, :]

    from .pallas_kernels import (
        fused_arrival_plan,
        pairwise_rank,
        pallas_arrival_applicable,
        pallas_rank_applicable,
    )

    if pallas_arrival_applicable(K, n_fogs):
        # fused decide-and-reduce (opt-in, FNS_PALLAS_ARRIVAL=1): rank,
        # per-fog counts and the earliest (time, position) pair come out
        # of ONE Pallas pass — exact (int sums / lex-mins), so results
        # are bit-identical to the jnp path (tests/test_pallas.py)
        rank, counts, t_min, first = fused_arrival_plan(
            mask, f_key, t_key, n_fogs
        )
        assign_task = jnp.where(
            fog_idle & (counts > 0), first, NO_TASK
        ).astype(jnp.int32)
        return ArrivalPlan(assign_task=assign_task, rank=rank, counts=counts)

    if pallas_rank_applicable(K):
        # fused Pallas tile kernel: one pass, no (K, K) HBM intermediates
        rank = pairwise_rank(mask, f_key, t_key)
    elif K <= _PAIRWISE_MAX:
        same = f_key[None, :] == f_key[:, None]  # (K, K) j vs i
        earlier = (t_key[None, :] < t_key[:, None]) | (
            (t_key[None, :] == t_key[:, None]) & (ids[None, :] < ids[:, None])
        )
        before = same & earlier & mask[None, :]
        rank = jnp.where(mask, jnp.sum(before, axis=1, dtype=jnp.int32), -1)
    else:
        order = jnp.lexsort((ids, t_arrive, f_key))
        f_sorted = f_key[order]
        valid_sorted = mask[order]
        idx = jnp.arange(K, dtype=jnp.int32)
        is_start = jnp.concatenate(
            [jnp.ones((1,), bool), f_sorted[1:] != f_sorted[:-1]]
        )
        seg_start = jax.lax.associative_scan(
            jnp.maximum, jnp.where(is_start, idx, 0)
        )
        rank_sorted = jnp.where(valid_sorted, idx - seg_start, -1)
        rank = jnp.zeros((K,), jnp.int32).at[order].set(rank_sorted)

    if fused:
        # one variadic lex-min reduce gives (earliest time, its id) per
        # fog; an empty fog has t_min = inf, so finiteness replaces the
        # counts > 0 test bit-exactly (masked-in arrivals always carry
        # finite times).  counts move into the tail's merged reduction.
        t_min, first = row_lexmin(
            jnp.where(per_fog, t_key[None, :], jnp.inf)
        )
        counts = None
        has_arrival = jnp.isfinite(t_min)
    else:
        counts = jnp.sum(per_fog, axis=1, dtype=jnp.int32)

        # first arrival per fog: masked min on time, then min id among ties
        t_min = jnp.min(jnp.where(per_fog, t_key[None, :], jnp.inf), axis=1)
        is_tmin = per_fog & (t_key[None, :] == t_min[:, None])
        first = jnp.min(
            jnp.where(is_tmin, ids[None, :], jnp.iinfo(jnp.int32).max), axis=1
        )
        has_arrival = counts > 0
    assign_task = jnp.where(
        fog_idle & has_arrival, first, NO_TASK
    ).astype(jnp.int32)
    return ArrivalPlan(assign_task=assign_task, rank=rank, counts=counts)


def batched_enqueue(
    queue: jax.Array,  # (F, Q) i32
    q_head: jax.Array,  # (F,) i32
    q_len: jax.Array,  # (F,) i32
    mask: jax.Array,  # (K,) bool — tasks to enqueue
    fog: jax.Array,  # (K,) i32
    eff_rank: jax.Array,  # (K,) i32 — slot offset within this tick's batch
    task_ids: jax.Array = None,  # (K,) i32 — global task ids to store;
    #                               defaults to arange(K) (uncompacted call)
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Enqueue a batch of tasks into their fog rings at ``head+len+rank``.

    Returns (queue, q_len, enq_mask, n_dropped).  Tasks whose slot would
    exceed capacity are dropped (``enq_mask`` False) — the reference cannot
    drop (unbounded vector); size Q generously and watch the drop counter.
    """
    F, Q = queue.shape
    queue, fits = enqueue_scatter(
        queue, q_head, q_len, mask, fog, eff_rank, task_ids
    )

    fog_eq = fog[None, :] == jnp.arange(F, dtype=jnp.int32)[:, None]  # (F, K)
    added = jnp.sum(fog_eq & fits[None, :], axis=1, dtype=jnp.int32)
    dropped_per_fog = jnp.sum(
        fog_eq & (mask & ~fits)[None, :], axis=1, dtype=jnp.int32
    )
    q_len = q_len + added
    return queue, q_len, fits, dropped_per_fog


def enqueue_scatter(
    queue: jax.Array,  # (F, Q) i32
    q_head: jax.Array,  # (F,) i32
    q_len: jax.Array,  # (F,) i32
    mask: jax.Array,  # (K,) bool
    fog: jax.Array,  # (K,) i32
    eff_rank: jax.Array,  # (K,) i32
    task_ids: jax.Array = None,  # (K,) i32; defaults to arange(K)
    stacked: bool = False,  # fused tick: fetch (q_head, q_len) in ONE
    #   stacked gather (gathers are exact, so bit-identical; kept off
    #   for batched_enqueue so the unfused reference path is untouched)
) -> Tuple[jax.Array, jax.Array]:
    """The scatter half of :func:`batched_enqueue`: write the fitting
    tasks into their rings and return ``(queue, fits)``.

    The per-fog added/dropped counting stays in
    :func:`batched_enqueue`; the engine's fused tail calls this
    directly and folds those counts into its single merged per-fog
    reduction instead (same integers — `engine._fog_arrivals_tail`).
    """
    F, Q = queue.shape
    if stacked:
        hl = jnp.stack([q_head, q_len], axis=1)[jnp.clip(fog, 0, F - 1)]
        head_g, len_g = hl[:, 0], hl[:, 1]
    else:
        head_g = q_head[jnp.clip(fog, 0, F - 1)]
        len_g = q_len[jnp.clip(fog, 0, F - 1)]
    slot = head_g + len_g + eff_rank
    fits = mask & (len_g + eff_rank < Q) & (eff_rank >= 0)
    flat_idx = jnp.where(fits, jnp.clip(fog, 0, F - 1) * Q + slot % Q, F * Q)
    if task_ids is None:
        task_ids = jnp.arange(mask.shape[0], dtype=jnp.int32)
    flat = queue.reshape(F * Q)
    flat = flat.at[flat_idx].set(task_ids, mode="drop")
    return flat.reshape(F, Q), fits


def batched_pop(
    queue: jax.Array,  # (F, Q) i32
    q_head: jax.Array,  # (F,) i32
    q_len: jax.Array,  # (F,) i32
    pop_mask: jax.Array,  # (F,) bool — fogs that pop their FIFO head now
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Pop the head of each masked fog ring. Returns (task, q_head, q_len).

    ``task`` is NO_TASK where ``pop_mask`` is False or the ring is empty.
    Mirrors ``requests.erase(requests.begin())`` after the head is promoted
    to ``currentTask`` (``ComputeBrokerApp3.cc:240-246``).
    """
    F, Q = queue.shape
    can = pop_mask & (q_len > 0)
    head_task = jnp.where(can, jnp.take_along_axis(queue, (q_head % Q)[:, None], axis=1)[:, 0], NO_TASK)
    q_head = jnp.where(can, (q_head + 1) % Q, q_head)
    q_len = jnp.where(can, q_len - 1, q_len)
    return head_task, q_head, q_len
