"""One program, many worlds: dynamic-operand spec promotion (ISSUE 13).

Every jitted entry point takes the whole :class:`~fognetsimpp_tpu.spec.
WorldSpec` as a static argument, so historically changing ANY numeric
knob — a chaos MTBF, an RTT burst amplitude, an energy power budget —
threw away an 8-56 s XLA compile to re-run a sub-second tick program.
This module splits the spec into

* a **shape key** (:func:`shape_key`) — the spec with every promoted
  numeric knob replaced by a gate-preserving canonical value, so two
  worlds that differ only in knob *values* hash to the SAME static
  argument and share one compiled program; and
* a **DynSpec operand** (:func:`dyn_of`) — a tiny pytree of f32/i32
  scalars carrying the knob values onto the device as run-time data.

The correctness rail is bit-exactness: each DynSpec leaf is derived on
host with EXACTLY the arithmetic the static path used to fold into the
trace (``np.float32(spec.x)``, ``np.float32(2*pi/period)``, ...), so a
promoted run and a static run execute the same f32 ops on the same f32
values (tests/test_dynspec.py state-hash A/Bs the three policy-family
worlds across run/run_jit/run_chunked).  When ``dyn`` is ``None`` the
engine calls :func:`dyn_of` at trace time and the leaves are embedded
as the same host constants as before — the static path IS the promoted
path with constants, which is what makes the A/B trivial to reason
about.

Gate discipline: a handful of promoted fields also steer *Python-level*
trace structure (``if spec.uplink_loss_prob > 0:`` ...).  The canonical
values preserve each field's gate class (zero vs positive, finite vs
inf), so the shape key always selects the same trace as the real spec;
the values inside that trace come from the operand.  simlint rule R13
flags any NEW engine read of a promoted field that bypasses the operand
(closure re-capture is how this win would silently rot).

Knobs deliberately left static are listed in :data:`STATIC_REASONS`
with one-line reasons — the CLI ``--set`` classification
(:func:`classify_field`) and the README table both read it.
"""
from __future__ import annotations

import dataclasses
import os
import threading
from collections import OrderedDict
from typing import Dict, Mapping, Optional, Tuple

import jax
import numpy as np
from flax import struct

from .spec import WorldSpec

# ----------------------------------------------------------------------
# the promoted-field catalogue
# ----------------------------------------------------------------------

#: WorldSpec fields promoted to DynSpec operands: their VALUE (never
#: their shape) reaches the traced tick, so changing them re-uses the
#: compiled program.  Keep in sync with tools/simlint/rules.py R13
#: (tests/test_dynspec.py pins the two lists equal).
DYN_FIELDS: Tuple[str, ...] = (
    # wireless / link scalars
    "uplink_loss_prob",
    "send_stop_time",
    "link_up_s",
    "link_drain_s",
    "link_drain2_s",
    "link_rate_bps",
    # chaos fault-injection knobs (ISSUE 12)
    "chaos_mtbf_s",
    "chaos_mttr_s",
    "chaos_rtt_amp",
    "chaos_rtt_period_s",
    "chaos_rtt_burst_prob",
    "chaos_rtt_burst_mult",
    "chaos_max_retries",
    # learned-scheduler reward weights
    "learn_discount",
    "learn_reward_scale",
    # federated-hierarchy migration knobs (hier/)
    "hier_threshold",
    "hier_max_hops",
    "hier_rtt_s",
    "hier_rtt_matrix",
    # energy-model scalars
    "idle_power_w",
    "tx_energy_j",
    "rx_energy_j",
    "compute_power_w",
    "harvest_power_w",
    "harvest_period_s",
    "harvest_duty",
    "shutdown_frac",
    "start_frac",
)

#: Numeric knobs deliberately kept static, with the one-line reason the
#: tentpole demands (any knob that cannot stay bit-exact as an operand
#: stays static, documented).  Everything not listed here and not in
#: DYN_FIELDS is shape/gate/policy-defining by construction.
STATIC_REASONS: Dict[str, str] = {
    "dt": "sets n_ticks (the scan length) — shape-defining",
    "horizon": "sets n_ticks (the scan length) — shape-defining",
    "send_interval": "already dynamic: rides users.send_interval in the "
    "state (the sweep load axis)",
    "send_interval_jitter": "resample gate is trace structure "
    "(volatile-par draw per send)",
    "start_time_min": "folded into users.start_t at state init",
    "start_time_max": "folded into users.start_t at state init",
    "mips_required_min": "jax.random.randint bound — the draw pipeline "
    "is specialized on the static bound",
    "mips_required_max": "jax.random.randint bound — the draw pipeline "
    "is specialized on the static bound",
    "fixed_mips_required": "None-vs-value selects the draw-free spawn "
    "trace",
    "required_time": "v2 release pre-selection compares it to dt at "
    "trace time (validate() contract)",
    "adv_interval": "advert-boundary sub-phasing derives per-tick fire "
    "times whose trace the boundary count depends on",
    "broker_mips": "folded into broker pool state at init",
    "learn_explore": "already dynamic: rides LearnState.explore in the "
    "carry (sweep_explore's axis)",
    "policy_seed": "folded into the per-task threefry stream key",
    "chaos_seed": "folded into the chaos PRNG key at state init",
    "energy_capacity_j": "folded into nodes.energy/energy_capacity at "
    "state init",
    "task_bytes": "static int folded into DropTail byte constants with "
    "link_queue_frames",
    "link_queue_frames": "static int — frameCapacity folds into the "
    "DropTail cap constant",
    "link_burst_n": "static int gate selecting the one- vs two-phase "
    "drain trace",
    "link_buffer_frames": "static int gate selecting the mechanistic- "
    "buffer trace",
    "telemetry_hist_min_ms": "bucket edges are trace-time constants of "
    "the histogram compare ladder",
    "telemetry_hist_max_ms": "bucket edges are trace-time constants of "
    "the histogram compare ladder",
    "ingest_batch": "static int — sizes the fixed-width injection batch "
    "arrays the chunk-boundary injector is compiled for",
}

#: Gate classes: promoted fields whose VALUE also steers Python-level
#: trace structure.  The canonical value must preserve the gate bit so
#: the shape key selects the same trace as the real spec.
_GATED_POSITIVE = (
    "uplink_loss_prob",
    "link_up_s",
    "chaos_mtbf_s",
    "chaos_mttr_s",
    "chaos_rtt_amp",
    "chaos_rtt_burst_prob",
)

#: Canonical representatives (exact f32 values, deliberately DISTINCT
#: from common defaults): if an engine phase mistakenly reads the shape
#: key's value instead of the operand, the bit-exact A/B fails loudly
#: instead of passing by coincidence.
_CANONICAL: Dict[str, float] = {
    "uplink_loss_prob": 0.4375,
    "send_stop_time": 7.0,  # only when finite (gate: != inf)
    "link_up_s": 0.5,
    "link_drain_s": 0.03125,
    "link_drain2_s": 0.0625,
    "link_rate_bps": 64e6,
    "chaos_mtbf_s": 3.0,
    "chaos_mttr_s": 1.5,
    "chaos_rtt_amp": 0.75,
    "chaos_rtt_period_s": 2.0,
    "chaos_rtt_burst_prob": 0.4375,
    "chaos_rtt_burst_mult": 2.5,
    "chaos_max_retries": 3,
    "learn_discount": 0.875,
    "learn_reward_scale": 0.625,
    "hier_threshold": 0.8125,
    "hier_max_hops": 3,
    "hier_rtt_s": 0.015625,
    # hier_rtt_matrix is shape-dependent: handled in _canonical_value
    "hier_rtt_matrix": None,
    "idle_power_w": 0.25,
    "tx_energy_j": 0.25,
    "rx_energy_j": 0.25,
    "compute_power_w": 0.25,
    "harvest_power_w": 0.25,
    "harvest_period_s": 1.0,
    "harvest_duty": 0.5,
    "shutdown_frac": 0.125,
    "start_frac": 0.625,
}


@struct.dataclass
class DynSpec:
    """Device-operand view of the promoted numeric knobs.

    Every leaf is the EXACT f32 (or i32) scalar the static path would
    have folded into the trace as a constant — derived quantities
    (``chaos_rtt_omega`` = 2*pi/period, the energy per-tick products)
    are precomputed on HOST with the same f64->f32 rounding order, so
    operand and constant execute identical arithmetic.
    """

    # wireless / link
    uplink_loss_prob: jax.Array
    send_stop_time: jax.Array
    link_up_s: jax.Array
    link_drain_s: jax.Array
    link_drain2_s: jax.Array
    link_burst_base: jax.Array  # (link_burst_n-1) * f32(link_drain_s)
    link_inv_rate: jax.Array  # 8.0 / link_rate_bps  (s per byte)
    link_drain_bytes: jax.Array  # link_rate_bps / 8.0 * dt
    # chaos
    chaos_mtbf_s: jax.Array
    chaos_mttr_s: jax.Array  # host-clamped max(mttr, 0)
    chaos_rtt_amp: jax.Array
    chaos_rtt_omega: jax.Array  # 2*pi / chaos_rtt_period_s
    chaos_rtt_burst_prob: jax.Array
    chaos_rtt_burst_mult: jax.Array
    chaos_max_retries: jax.Array  # i32
    # learn
    learn_discount: jax.Array
    learn_reward_scale: jax.Array
    # federated hierarchy (hier/) — hier_rtt is the derived (B, B)
    # inter-broker RTT matrix (explicit hier_rtt_matrix, else uniform
    # hier_rtt_s off-diagonal with a zero diagonal); B is static so the
    # leaf's shape never depends on knob values
    hier_threshold: jax.Array
    hier_max_hops: jax.Array  # i32
    hier_rtt: jax.Array  # (B, B) f32; (1, 1) zero on single-broker worlds
    # energy (per-tick products precomputed against spec.dt)
    energy_idle_dt: jax.Array  # idle_power_w * dt
    energy_tx_j: jax.Array
    energy_rx_j: jax.Array
    energy_compute_dt: jax.Array  # compute_power_w * dt
    energy_harvest_dt: jax.Array  # harvest_power_w * dt
    harvest_period_s: jax.Array
    harvest_duty: jax.Array
    shutdown_frac: jax.Array
    start_frac: jax.Array


def dyn_of(spec: WorldSpec) -> DynSpec:
    """The DynSpec operand for ``spec``.

    Host np scalars, NOT device arrays: passed through jit they become
    device operands; used at trace time (the ``dyn=None`` static path)
    they are embedded as the same constants the pre-promotion engine
    folded in — which is the whole bit-exactness argument.
    """
    f32 = np.float32
    return DynSpec(
        uplink_loss_prob=f32(spec.uplink_loss_prob),
        send_stop_time=f32(spec.send_stop_time),
        link_up_s=f32(spec.link_up_s),
        link_drain_s=f32(spec.link_drain_s),
        link_drain2_s=f32(spec.link_drain2_s),
        # mirrors the engine's `nb * jnp.float32(drain_s)` host fold
        # (python-float nb times an f32, computed in f64, rounded once)
        link_burst_base=f32(
            float(max(spec.link_burst_n - 1, 0)) * f32(spec.link_drain_s)
        ),
        link_inv_rate=f32(8.0 / spec.link_rate_bps),
        link_drain_bytes=f32(spec.link_rate_bps / 8.0 * spec.dt),
        chaos_mtbf_s=f32(spec.chaos_mtbf_s),
        chaos_mttr_s=f32(max(spec.chaos_mttr_s, 0.0)),
        chaos_rtt_amp=f32(spec.chaos_rtt_amp),
        chaos_rtt_omega=f32(2.0 * np.pi / spec.chaos_rtt_period_s),
        chaos_rtt_burst_prob=f32(spec.chaos_rtt_burst_prob),
        chaos_rtt_burst_mult=f32(spec.chaos_rtt_burst_mult),
        chaos_max_retries=np.int32(spec.chaos_max_retries),
        learn_discount=f32(spec.learn_discount),
        learn_reward_scale=f32(spec.learn_reward_scale),
        hier_threshold=f32(spec.hier_threshold),
        hier_max_hops=np.int32(spec.hier_max_hops),
        hier_rtt=_hier_rtt_of(spec),
        energy_idle_dt=f32(spec.idle_power_w * spec.dt),
        energy_tx_j=f32(spec.tx_energy_j),
        energy_rx_j=f32(spec.rx_energy_j),
        energy_compute_dt=f32(spec.compute_power_w * spec.dt),
        energy_harvest_dt=f32(spec.harvest_power_w * spec.dt),
        harvest_period_s=f32(spec.harvest_period_s),
        harvest_duty=f32(spec.harvest_duty),
        shutdown_frac=f32(spec.shutdown_frac),
        start_frac=f32(spec.start_frac),
    )


def _hier_rtt_of(spec: WorldSpec) -> np.ndarray:
    """The derived (B, B) f32 inter-broker RTT matrix leaf.

    B is static (``spec.n_brokers``), so two worlds in one shape bucket
    always build same-shaped leaves; single-broker worlds carry an
    inert (1, 1) zero.
    """
    B = max(spec.n_brokers, 1)
    if spec.hier_rtt_matrix is not None:
        return np.asarray(spec.hier_rtt_matrix, np.float32)
    rtt = np.full((B, B), np.float32(spec.hier_rtt_s), np.float32)
    np.fill_diagonal(rtt, np.float32(0.0))
    return rtt


def _canonical_value(spec: WorldSpec, field: str):
    v = getattr(spec, field)
    if field == "send_stop_time":
        # gate: finite vs inf selects the stop-gated spawn trace
        return v if v == float("inf") else _CANONICAL[field]
    if field == "hier_rtt_matrix":
        # shape-dependent canonical: None (the uniform derivation) and
        # explicit matrices keep separate representatives, both
        # canonicalised within their class so knob VALUES never split
        # the bucket; n_brokers itself is static, so the leaf shape is
        # fixed either way
        if v is None:
            return None
        B = spec.n_brokers
        return ((0.0234375,) * B,) * B
    if field in _GATED_POSITIVE and not (v > 0):
        return 0.0
    return _CANONICAL[field]


def shape_key(spec: WorldSpec) -> WorldSpec:
    """The static-argument representative of ``spec``'s shape bucket.

    Promoted knobs are replaced by gate-preserving canonical values:
    every spec in the bucket maps to the SAME key, so jit caches (and
    the program registry) key one compiled program per bucket.  All
    shape, capacity, policy, gate and bug-compat fields pass through
    untouched.
    """
    return dataclasses.replace(
        spec, **{f: _canonical_value(spec, f) for f in DYN_FIELDS}
    )


def split_spec(spec: WorldSpec) -> Tuple[WorldSpec, DynSpec]:
    """``(shape_key(spec), dyn_of(spec))`` — the promotion primitive."""
    return shape_key(spec), dyn_of(spec)


def same_program(a: WorldSpec, b: WorldSpec) -> bool:
    """True when ``a`` and ``b`` share one compiled program (equal shape
    keys: they differ only in promoted knob values)."""
    return shape_key(a) == shape_key(b)


def apply_knobs(spec: WorldSpec, knobs: Mapping[str, float]) -> WorldSpec:
    """Re-configure promoted knobs on a live spec, compile-free.

    Raises ``ValueError`` (one actionable line) when a key is unknown,
    not a promoted knob, or when the new values change the shape key
    (i.e. flip a trace gate, like turning chaos RTT bursts on for a
    world compiled without them) — the caller must then take the
    recompile path explicitly instead of silently paying it here.
    """
    for k in knobs:
        if k not in DYN_FIELDS:
            why = STATIC_REASONS.get(k)
            if why is not None:
                raise ValueError(
                    f"spec.{k} is shape-defining ({why}): changing it "
                    "needs a recompile — rebuild the world instead of "
                    "re-configuring the live one"
                )
            raise ValueError(
                f"unknown dynamic knob {k!r} (promoted knobs: "
                + ", ".join(DYN_FIELDS) + ")"
            )
    spec2 = dataclasses.replace(spec, **dict(knobs)).validate()
    if shape_key(spec2) != shape_key(spec):
        changed = [
            k for k in knobs
            if _canonical_value(spec2, k) != _canonical_value(spec, k)
        ]
        raise ValueError(
            "knob change flips a trace gate (zero vs positive / finite "
            f"vs inf) on {', '.join(sorted(changed)) or 'a spec field'}: "
            "this needs a recompile — rebuild the world to cross gate "
            "classes"
        )
    return spec2


def promote_default() -> bool:
    """Whether the run/serve entry points promote by default.

    ``FNS_SPEC_PROMOTE=0`` forces the legacy static-spec path (the A/B
    reference); anything else (including unset) promotes.
    """
    env = os.environ.get("FNS_SPEC_PROMOTE", "1")
    return env.strip().lower() not in ("0", "off", "false", "no", "")


# ----------------------------------------------------------------------
# CLI classification (--set spec.X=V -> recompile: yes|no)
# ----------------------------------------------------------------------

def classify_field(field: str) -> Tuple[bool, str]:
    """``(recompiles, reason)`` for a WorldSpec field name.

    Raises ``ValueError`` (one line) for unknown fields — the same
    message the config tier produces, so the CLI surfaces it before
    building a world.

    Gated promoted knobs carry a caveat: the classifier cannot see the
    scenario's CURRENT value, so a ``--set`` that crosses the knob's
    trace gate (0 <-> positive, inf <-> finite) still compiles a fresh
    program despite the "no".
    """
    if field in _GATED_POSITIVE or field == "send_stop_time":
        gate = (
            "inf vs finite" if field == "send_stop_time"
            else "zero vs positive"
        )
        return False, (
            "dynamic operand — compiled programs are reused within its "
            f"gate class; crossing {gate} still recompiles"
        )
    if field in DYN_FIELDS:
        return False, "dynamic operand — compiled programs are reused"
    names = {f.name for f in dataclasses.fields(WorldSpec)}
    if field not in names:
        raise ValueError(f"unknown WorldSpec field {field!r}")
    why = STATIC_REASONS.get(field)
    if why is not None:
        return True, why
    return True, "shape/gate/policy-defining — selects a different trace"


# ----------------------------------------------------------------------
# shape-bucketed population padding (generalizes PR 8's TP padding)
# ----------------------------------------------------------------------

#: Populations at or below this are left alone: tiny worlds are parity/
#: test scale, where ghost rows would distort committed anchors.
BUCKET_FLOOR = 1024

#: Per-octave bucket boundaries: powers of two plus the 1.5x midpoint —
#: the classic "power-of-two-ish" ladder (1024, 1536, 2048, 3072, ...).
#: Worst-case ghost overhead is 33%, average ~15%.
_BUCKET_STEPS = (1.0, 1.5)


def bucket_users(n: int, floor: int = BUCKET_FLOOR) -> int:
    """Smallest bucket >= ``n`` on the power-of-two-ish ladder.

    ``n <= floor`` returns ``n`` unchanged (no bucketing below the
    floor); above it, the ladder is {2^k, 1.5 * 2^k}.
    """
    if n <= floor:
        return n
    p = 1 << (int(n - 1).bit_length() - 1)  # largest power of two <= n-1
    while True:
        for s in _BUCKET_STEPS:
            b = int(p * s)
            if b >= n:
                return b
        p *= 2


def bucket_spec(spec: WorldSpec, state, net, floor: int = BUCKET_FLOOR):
    """Pad ``n_users`` (and with it ``task_capacity``) up to its bucket.

    Ghost users are the inert rows of PR 8's
    :func:`~fognetsimpp_tpu.parallel.taskshard.pad_users_to_multiple`
    (never started, unconnected, all task rows UNUSED) — the real
    users' dynamics are exactly those of the same spec at the padded
    population, so two nearby population sizes share one compiled
    program per shape bucket.  Returns ``(spec, state, net)`` unchanged
    when the population is already on a bucket boundary (or below the
    floor).

    Note the per-user PRNG stream caveat pad_users_to_multiple
    documents: padding changes the (n_users,)-shaped draws vs the
    unpadded world, so bucketing trades bit-identity ACROSS population
    sizes for program reuse — worlds pinned to committed traces should
    run un-bucketed.
    """
    from .parallel.taskshard import pad_users_to_multiple

    b = bucket_users(spec.n_users, floor=floor)
    if b == spec.n_users:
        return spec, state, net
    return pad_users_to_multiple(spec, state, net, b)


# ----------------------------------------------------------------------
# bounded process-level program registry
# ----------------------------------------------------------------------

_REG_LOCK = threading.Lock()
_REGISTRY: "OrderedDict[Tuple, Dict]" = OrderedDict()
_REGISTRY_CAP = 128
_REG_COUNTS = {"programs": 0, "reuses": 0, "evictions": 0}


def registry_note(
    key_spec: WorldSpec, backend: str, donated: bool
) -> bool:
    """Record one promoted-entry-point invocation.

    Keyed on (shape key, backend, donation layout) — the axes on which
    XLA would compile distinct executables.  Returns True when the key
    is NEW to the registry (a compile is expected), False on reuse.
    The registry is bounded (LRU beyond :data:`_REGISTRY_CAP`) so a
    pathological spec-churn loop cannot grow host memory; eviction only
    loses accounting, never executables (jit owns those).
    """
    k = (key_spec, backend, bool(donated))
    with _REG_LOCK:
        ent = _REGISTRY.pop(k, None)
        if ent is None:
            ent = {"calls": 0}
            _REG_COUNTS["programs"] += 1
        else:
            _REG_COUNTS["reuses"] += 1
        ent["calls"] += 1
        _REGISTRY[k] = ent  # most-recently-used at the end
        while len(_REGISTRY) > _REGISTRY_CAP:
            _REGISTRY.popitem(last=False)
            _REG_COUNTS["evictions"] += 1
        return ent["calls"] == 1


def registry_stats() -> Dict:
    """Snapshot for the compile-latency observability plane: bucket
    count, total reuse hits, per-axis breakdown sizes."""
    with _REG_LOCK:
        return {
            "buckets": len(_REGISTRY),
            "programs": _REG_COUNTS["programs"],
            "reuses": _REG_COUNTS["reuses"],
            "evictions": _REG_COUNTS["evictions"],
        }


def registry_reset() -> None:
    """Test hook: forget all buckets and counters."""
    with _REG_LOCK:
        _REGISTRY.clear()
        for k in _REG_COUNTS:
            _REG_COUNTS[k] = 0


def _register_provider() -> None:
    from . import compile_cache

    compile_cache.register_stats_provider(
        "program_registry", registry_stats
    )


_register_provider()
