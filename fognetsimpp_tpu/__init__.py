"""fognetsimpp_tpu — a TPU-native fog-computing simulation framework.

A from-scratch reimplementation of the capabilities of FogNetSim++
(CharafeddineMechalikh/fognetsimpp: MQTT-style IoT task offloading over an
OMNeT++/INET discrete-event simulation) re-designed for TPU execution:

  * all world state lives in fixed-shape device arrays (one pytree);
  * one ``lax.scan`` tick advances every node, queue and in-flight message;
  * schedulers are jit'd batched argmin kernels;
  * ``vmap`` fans out Monte-Carlo replicas, ``pjit``/``shard_map`` shards
    replicas and nodes over a TPU mesh;
  * a C++ event-driven core (``fognetsimpp_tpu.native``) provides the
    sequential-DES parity baseline the batched engine is validated against.

See SURVEY.md at the repository root for the reference structural analysis
this build follows, and README.md for usage.
"""
from .spec import (  # noqa: F401
    ARGMIN_FAMILY,
    LEARNED_POLICIES,
    BugCompat,
    FogModel,
    HierPolicy,
    Mobility,
    NodeKind,
    Policy,
    Stage,
    WorldSpec,
    hier_policy_from_name,
    policy_from_name,
)
from .state import WorldState, init_state  # noqa: F401
from .core.engine import (  # noqa: F401
    make_step,
    prime_initial_advertisements,
    run,
    run_chunked,
    run_jit,
)
from .dynspec import (  # noqa: F401
    DYN_FIELDS,
    DynSpec,
    apply_knobs,
    bucket_spec,
    bucket_users,
    dyn_of,
    shape_key,
    split_spec,
)

__version__ = "0.1.0"
