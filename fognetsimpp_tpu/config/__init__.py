"""Config tier: ini-style wildcard overrides (the omnetpp.ini analog)."""
from .ini import Config, build_from_config, parse_value  # noqa: F401
