"""Hierarchical wildcard config: the ``omnetpp.ini`` tier re-created.

The reference's config system (SURVEY.md §5 "config/flag system") selects
implementations and sweeps values with wildcard keys like
``**.ComputeBroker1.udpApp[*].MIPS = 1000``.  Here the same mechanics bind
to the batched world: dotted parameter paths, ``*`` matching within one
path segment and ``**`` across segments, **first matching line wins**
(OMNeT++ precedence: put specific keys above general ones).

Recognised paths:
  * ``scenario``               — builder name (``smoke``, ``wireless5``,
    ``example``, ...)
  * ``scenario.<kwarg>``       — builder keyword (e.g. ``scenario.horizon``)
  * ``spec.<field>``           — any :class:`WorldSpec` field override
  * ``fog.<i|*>.mips``         — per-fog MIPS (``**.ComputeBroker2...MIPS``)
  * ``user.<i|*>.send_interval`` — per-user publish interval
  * ``seed``                   — PRNG seed
  * ``output.dir`` / ``output.run_id`` — recorder destination

Values parse as OMNeT++ quantities: ``50ms`` → 0.05, ``2s`` → 2.0,
``true``/``false``, ints, floats, bare strings.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

_UNITS = {
    "us": 1e-6, "ms": 1e-3, "s": 1.0, "m": 1.0, "mps": 1.0,
    "bps": 1.0, "kbps": 1e3, "Mbps": 1e6, "B": 1.0, "J": 1.0,
    "mW": 1e-3, "W": 1.0, "deg": 1.0,
}


def parse_value(raw: str):
    """'50ms' -> 0.05, 'true' -> True, '3' -> 3, '1.5' -> 1.5, else str."""
    v = raw.strip().strip('"')
    low = v.lower()
    if low in ("true", "false"):
        return low == "true"
    m = re.fullmatch(r"([-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?)\s*(\w+)?", v)
    if m:
        num, unit = m.group(1), m.group(2)
        if unit is None:
            f = float(num)
            return int(f) if f.is_integer() and "." not in num and "e" not in num.lower() else f
        if unit in _UNITS:
            return float(num) * _UNITS[unit]
    return v


def _pattern_to_regex(pat: str) -> re.Pattern:
    out = []
    i = 0
    while i < len(pat):
        if pat.startswith("**", i):
            out.append(".*")
            i += 2
        elif pat[i] == "*":
            out.append(r"[^.]*")
            i += 1
        else:
            out.append(re.escape(pat[i]))
            i += 1
    return re.compile("".join(out) + r"\Z")


class Config:
    """Ordered wildcard-pattern config; first matching line wins."""

    def __init__(self, entries: List[Tuple[str, object]]):
        self.entries = [(p, _pattern_to_regex(p), v) for p, v in entries]

    @classmethod
    def from_str(cls, text: str) -> "Config":
        entries: List[Tuple[str, object]] = []
        for line in text.splitlines():
            line = line.split("#", 1)[0].strip()
            if not line or line.startswith("["):  # [General]-style headers
                continue
            if "=" not in line:
                continue
            key, _, raw = line.partition("=")
            entries.append((key.strip(), parse_value(raw)))
        return cls(entries)

    @classmethod
    def from_file(cls, path: str) -> "Config":
        with open(path) as f:
            return cls.from_str(f.read())

    def lookup(self, path: str, default=None):
        for _, rx, v in self.entries:
            if rx.match(path):
                return v
        return default

    def matching(self, prefix: str) -> Dict[str, object]:
        """All literal keys under ``prefix.`` (for builder kwargs)."""
        out: Dict[str, object] = {}
        for pat, _, v in self.entries:
            if pat.startswith(prefix + ".") and "*" not in pat:
                out.setdefault(pat[len(prefix) + 1 :], v)
        return out


def scenario_builders():
    """Name → builder registry (the network-NED catalogue analog)."""
    from .. import scenarios

    return {
        "smoke": scenarios.smoke.build,
        "wired_v1": scenarios.wired_v1.build,
        "example": scenarios.example.build,
        "wireless": scenarios.wireless.wireless,
        "wireless2": scenarios.wireless.wireless2,
        "wireless3": scenarios.wireless.wireless3,
        "wireless4": scenarios.wireless.wireless4,
        "wireless5": scenarios.wireless.wireless5,
        "paper": scenarios.wireless.paper,
    }


def build_from_config(cfg: Config, seed: Optional[int] = None):
    """Construct ``(spec, state, net, bounds)`` from a :class:`Config`.

    The scenario builder supplies the topology; ``spec.*`` keys override
    WorldSpec fields; ``fog.<i>.mips`` / ``user.<i>.send_interval`` rewrite
    the per-node arrays afterwards (the per-module wildcard tier).
    """
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    from .. import scenarios
    from ..spec import WorldSpec

    name = cfg.lookup("scenario", "smoke")
    builders = scenario_builders()
    if name not in builders:
        raise ValueError(f"unknown scenario {name!r} (have {sorted(builders)})")
    kwargs = cfg.matching("scenario")
    if seed is None:
        seed = int(cfg.lookup("seed", 0))
    kwargs.setdefault("seed", seed)

    # spec.* overrides whose fields the builder accepts directly
    spec_fields = {f.name for f in dataclasses.fields(WorldSpec)}
    for pat, _, v in cfg.entries:
        if pat.startswith("spec.") and "*" not in pat:
            field = pat[5:]
            if field not in spec_fields:
                raise ValueError(f"unknown WorldSpec field {field!r}")
            if field == "chaos_script":
                # ini values are scalars: the scripted-outage schedule
                # travels as one 'fog:t_down:t_up;...' string and is
                # normalised to the spec's hashable tuple form here
                from ..chaos.profiles import parse_script

                v = parse_script(v)
            kwargs.setdefault(field, v)

    try:
        spec, state, net, bounds = builders[name](**kwargs)
    except TypeError as e:
        msg = str(e)
        if "multiple values" in msg:
            # a spec.* override collided with a field the builder owns
            import inspect

            m = re.search(r"argument '(\w+)'", msg)
            field = m.group(1) if m else "?"
            sig = set(inspect.signature(builders[name]).parameters)
            hint = (
                f"set it via a scenario.{field} key instead"
                if field in sig
                else "this field is derived by the builder and is not "
                "overridable for this scenario"
            )
            raise ValueError(
                f"scenario {name!r} owns WorldSpec field {field!r}: {hint}"
            ) from e
        raise

    # per-node wildcard tier (first match wins per index)
    mips = np.asarray(state.fogs.mips).copy()
    changed = False
    for i in range(spec.n_fogs):
        v = cfg.lookup(f"fog.{i}.mips")
        if v is not None:
            mips[i] = float(v)
            changed = True
    if changed:
        from ..core.engine import prime_initial_advertisements

        state = state.replace(
            fogs=state.fogs.replace(
                mips=jnp.asarray(mips), pool_avail=jnp.asarray(mips)
            )
        )
        # the primed first-advertisement payloads carried the old MIPS
        state = prime_initial_advertisements(spec, state, net)
    si = np.asarray(state.users.send_interval).copy()
    changed = False
    for i in range(spec.n_users):
        v = cfg.lookup(f"user.{i}.send_interval")
        if v is not None:
            si[i] = float(v)
            changed = True
    if changed:
        if si.min() <= 0:
            raise ValueError(
                f"user send_interval override must be > 0, got {si.min():g}"
            )
        # the send budget (max_sends_per_user) was sized from the builder's
        # interval; a faster per-user rate would silently truncate there
        if spec.horizon / si.min() + 1 > spec.max_sends_per_user:
            raise ValueError(
                f"user send_interval override {si.min():g}s exceeds the "
                f"world's send budget (max_sends_per_user="
                f"{spec.max_sends_per_user}); also set "
                f"spec.send_interval = {si.min():g} (or a smaller "
                "scenario horizon) so capacity is sized for the fastest "
                "publisher"
            )
        state = state.replace(
            users=state.users.replace(send_interval=jnp.asarray(si))
        )
    return spec, state, net, bounds
