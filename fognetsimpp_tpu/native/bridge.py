"""ctypes bridge to the native event-driven parity core (desim.cpp).

Compiles ``desim.cpp`` with g++ on first use (cached in ``_build/`` keyed on
source hash) and exposes :func:`run_gen` (all three app generations) plus
:func:`replay_engine_world`, which replays the exact publish workload a
batched-engine run decided client-side (task creation times + MIPSRequired)
through the sequential DES — the two simulators then disagree only where
their *execution models* differ, which is what the parity gate
(tests/test_parity.py) measures.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import Dict, Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "desim.cpp")
_BUILD = os.path.join(_DIR, "_build")

_lib: Optional[ctypes.CDLL] = None

_OUT_COLS = (
    "t_at_broker", "t_at_fog", "t_service_start", "t_complete", "t_ack3",
    "t_ack4_fwd", "t_ack5", "t_ack4_queued", "t_ack6", "queue_time",
)


def build(force: bool = False) -> str:
    """Compile desim.cpp -> _build/libdesim-<hash>.so; returns the path."""
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    so = os.path.join(_BUILD, f"libdesim-{tag}.so")
    if force or not os.path.exists(so):
        os.makedirs(_BUILD, exist_ok=True)
        proc = subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", so, _SRC],
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"desim.cpp compile failed (g++ exit {proc.returncode}):\n"
                f"{proc.stderr}"
            )
    return so


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(build())
        dp = ctypes.POINTER(ctypes.c_double)
        ip = ctypes.POINTER(ctypes.c_int)
        lib.desim_run_gen.restype = ctypes.c_long
        lib.desim_run_gen.argtypes = (
            [ctypes.c_int] * 3
            + [ip, dp, dp]  # task_user, t_create, mips_req
            + [dp] * 5  # d_ub, d_bf, fog_mips, register_t, adv0_t
            + [ctypes.c_double]  # horizon
            + [ctypes.c_int] * 10  # policy..queue_capacity
            + [ctypes.c_double] * 3  # broker_mips, required_time, adv_interval
            + [dp, dp]  # fog_energy0, fog_energy_cap (nullable)
            + [ctypes.c_double] * 4  # tx_j, rx_j, idle_w, compute_w
            + [dp]  # rand_u (nullable)
            + [ctypes.c_int]  # v2_local
            + [dp]  # d2b_tab (nullable)
            + [ctypes.c_int] * 2 + [ctypes.c_double]  # tab shape + dt
            + [ctypes.POINTER(ctypes.c_ubyte)]  # task_lost (nullable)
            # user energy + lifecycle mode (r5, nullable bundle)
            + [dp] * 4  # user_energy0/cap, user_start, user_interval
            + [ctypes.c_int] * 2  # connect_gating, max_sends_per_user
            + [ctypes.c_double] * 6  # e_dt, harvest w/period/duty, thresholds
            + [dp, ip] + [dp] * 9 + [ip]
            + [dp]  # o_fog_energy (nullable)
            + [dp, dp]  # o_t_create, o_user_energy (nullable)
            + [ctypes.POINTER(ctypes.c_ubyte)]  # o_user_alive (nullable)
        )
        _lib = lib
    return _lib


def run_gen(
    task_user: np.ndarray,
    task_t_create: np.ndarray,
    task_mips_req: np.ndarray,
    d_ub: np.ndarray,
    d_bf: np.ndarray,
    fog_mips: np.ndarray,
    register_t: np.ndarray,
    adv0_t: np.ndarray,
    horizon: float,
    policy: int = 0,
    fog_model: int = 0,
    app_gen: int = 3,
    mips0_divisor: bool = True,
    zero_initial_view: bool = True,
    adv_on_completion: bool = True,
    adv_periodic: bool = False,
    v1_max_scan: bool = True,
    local_pool_leak: bool = False,
    queue_capacity: int = 64,
    broker_mips: float = 0.0,
    required_time: float = 0.01,
    adv_interval: float = 0.01,
    fog_energy0: Optional[np.ndarray] = None,  # enables the energy model
    fog_energy_cap: Optional[np.ndarray] = None,
    tx_energy_j: float = 0.0,
    rx_energy_j: float = 0.0,
    idle_power_w: float = 0.0,
    compute_power_w: float = 0.0,
    rand_u: Optional[np.ndarray] = None,  # RANDOM's shared per-task draws
    v2_local: bool = False,  # spec.v2_local_broker hybrid semantics
    d2b_table: Optional[np.ndarray] = None,  # (n_ticks, n_nodes) per-tick
    #   node<->broker delays (wireless/mobility); None = static d_ub/d_bf
    table_dt: float = 0.0,
    task_lost: Optional[np.ndarray] = None,  # (n_tasks) uint8 loss replay
    user_energy: Optional[Dict] = None,  # r5 user-battery mode: dict with
    #   energy0, cap, start, interval (per-user arrays), connect_gating,
    #   max_sends_per_user, dt, harvest_w, harvest_period, harvest_duty,
    #   shutdown_frac, start_frac.  The DES then runs the send chain
    #   itself, alive-gated on its own tick-quantised battery state, and
    #   the result gains t_create / user_energy / user_alive arrays.
) -> Dict[str, np.ndarray]:
    """Run the native DES over an explicit publish schedule."""
    lib = _load()
    n_tasks = len(task_user)

    def d(a):
        return np.ascontiguousarray(np.asarray(a, np.float64))

    def i(a):
        return np.ascontiguousarray(np.asarray(a, np.int32))

    task_user = i(task_user)
    ins = [d(task_t_create), d(task_mips_req), d(d_ub), d(d_bf), d(fog_mips),
           d(register_t), d(adv0_t)]
    outs_d = {k: np.empty((n_tasks,), np.float64) for k in _OUT_COLS}
    fog = np.empty((n_tasks,), np.int32)
    stage = np.empty((n_tasks,), np.int32)

    dp = ctypes.POINTER(ctypes.c_double)
    ip = ctypes.POINTER(ctypes.c_int)

    def pd(a):
        return a.ctypes.data_as(dp)

    def pi(a):
        return a.ctypes.data_as(ip)

    null_d = ctypes.cast(None, dp)
    e0 = d(fog_energy0) if fog_energy0 is not None else None
    ecap = (
        d(fog_energy_cap)
        if fog_energy_cap is not None
        else (np.ones_like(e0) if e0 is not None else None)
    )
    ru = d(rand_u) if rand_u is not None else None
    fog_energy_out = (
        np.empty((len(d_bf),), np.float64) if e0 is not None else None
    )
    tab = (
        np.ascontiguousarray(np.asarray(d2b_table, np.float64))
        if d2b_table is not None
        else None
    )
    lost_arr = (
        np.ascontiguousarray(np.asarray(task_lost, np.uint8))
        if task_lost is not None
        else None
    )
    ue = user_energy
    if ue is not None:
        ue_arrs = [d(ue["energy0"]), d(ue["cap"]), d(ue["start"]),
                   d(ue["interval"])]
        o_t_create = np.empty((n_tasks,), np.float64)
        o_user_energy = np.empty((len(d_ub),), np.float64)
        o_user_alive = np.empty((len(d_ub),), np.uint8)
    else:
        ue_arrs = None
        o_t_create = o_user_energy = o_user_alive = None
    ubp = ctypes.POINTER(ctypes.c_ubyte)

    n_events = lib.desim_run_gen(
        len(d_ub), len(d_bf), n_tasks,
        pi(task_user), pd(ins[0]), pd(ins[1]),
        pd(ins[2]), pd(ins[3]), pd(ins[4]), pd(ins[5]), pd(ins[6]),
        ctypes.c_double(horizon),
        int(policy), int(fog_model), int(app_gen),
        int(mips0_divisor), int(zero_initial_view), int(adv_on_completion),
        int(adv_periodic), int(v1_max_scan), int(local_pool_leak),
        int(queue_capacity),
        ctypes.c_double(broker_mips), ctypes.c_double(required_time),
        ctypes.c_double(adv_interval),
        pd(e0) if e0 is not None else null_d,
        pd(ecap) if ecap is not None else null_d,
        ctypes.c_double(tx_energy_j), ctypes.c_double(rx_energy_j),
        ctypes.c_double(idle_power_w), ctypes.c_double(compute_power_w),
        pd(ru) if ru is not None else null_d,
        int(v2_local),
        pd(tab) if tab is not None else null_d,
        int(tab.shape[0]) if tab is not None else 0,
        int(tab.shape[1]) if tab is not None else 0,
        ctypes.c_double(table_dt),
        (lost_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte))
         if lost_arr is not None
         else ctypes.cast(None, ctypes.POINTER(ctypes.c_ubyte))),
        pd(ue_arrs[0]) if ue else null_d,
        pd(ue_arrs[1]) if ue else null_d,
        pd(ue_arrs[2]) if ue else null_d,
        pd(ue_arrs[3]) if ue else null_d,
        int(ue["connect_gating"]) if ue else 0,
        int(ue["max_sends_per_user"]) if ue else 0,
        ctypes.c_double(ue["dt"] if ue else 0.0),
        ctypes.c_double(ue["harvest_w"] if ue else 0.0),
        ctypes.c_double(ue["harvest_period"] if ue else 1.0),
        ctypes.c_double(ue["harvest_duty"] if ue else 0.0),
        ctypes.c_double(ue["shutdown_frac"] if ue else 0.0),
        ctypes.c_double(ue["start_frac"] if ue else 0.0),
        pd(outs_d["t_at_broker"]), pi(fog), pd(outs_d["t_at_fog"]),
        pd(outs_d["t_service_start"]), pd(outs_d["t_complete"]),
        pd(outs_d["t_ack3"]), pd(outs_d["t_ack4_fwd"]), pd(outs_d["t_ack5"]),
        pd(outs_d["t_ack4_queued"]), pd(outs_d["t_ack6"]),
        pd(outs_d["queue_time"]), pi(stage),
        pd(fog_energy_out) if fog_energy_out is not None else null_d,
        pd(o_t_create) if ue else null_d,
        pd(o_user_energy) if ue else null_d,
        (o_user_alive.ctypes.data_as(ubp) if ue else ctypes.cast(None, ubp)),
    )
    out = dict(outs_d)
    out["fog"] = fog
    out["stage"] = stage
    out["n_events"] = np.asarray(n_events)
    if ue is not None:
        out["t_create"] = o_t_create
        out["user_energy"] = o_user_energy
        out["user_alive"] = o_user_alive
    if fog_energy_out is not None:
        out["fog_energy"] = fog_energy_out
    return out


def delay_table(spec, state0, net, bounds=None, n_ticks=None) -> np.ndarray:
    """Per-tick node→broker delay table for the DES (wireless/mobility).

    Runs the SAME mobility + association chain the engine's tick runs
    (``step_mobility`` to end-of-tick positions, then ``associate`` — so
    row ``s`` is exactly the ``cache.d2b`` the engine's tick ``s`` decides
    with), without any protocol phases: the network model is deterministic
    data, so the sequential baseline can consume it while still executing
    every EVENT independently.  Returns float64 ``(n_ticks, n_nodes)``.

    Bianchi worlds (r5): MAC contention is keyed on each cell's offered
    load (``associate(..., offered_rate=)``), so the table scan threads
    the self-timed send chain — connect handshake then ``next_send +=
    interval`` — exactly as the engine's connect/spawn phases advance
    it.  The chain depends only on the table rows already computed
    (connack = 2x that tick's own d2b), never on scheduling decisions,
    so the network stays pure data.  Requires jitter == 0 for such
    worlds (the engine's jitter stream is PRNG-keyed per tick).
    """
    import jax
    import jax.numpy as jnp

    from ..net.mobility import default_bounds, step_mobility
    from ..net.topology import associate

    if bounds is None:
        bounds = default_bounds()
    n = spec.n_ticks if n_ticks is None else n_ticks
    U, S = spec.n_users, spec.max_sends_per_user
    keyed = int(np.asarray(net.mac_loss_tab).shape[0]) > 0
    if keyed and spec.send_interval_jitter > 0:
        raise NotImplementedError(
            "activity-keyed MAC + send_interval_jitter has no "
            "independent delay table (the jitter stream is engine-PRNG)"
        )
    if keyed and spec.energy_enabled:
        # ADVICE r5: the table's send chain assumes an always-alive user
        # set — with batteries the engine's offered-rate rows depend on
        # its own lifecycle trajectory, which this scan never steps, so
        # the rows would silently diverge.  Mirror the
        # replay_engine_world guard instead of producing wrong data.
        raise NotImplementedError(
            "activity-keyed MAC + energy lifecycle has no independent "
            "delay table (offered load depends on the engine's own "
            "alive trajectory): build the world with mac_model='linear' "
            "and w_contention=0, as replay_engine_world requires"
        )
    rest = spec.n_nodes - U

    def body(carry, tick):
        nodes, users = carry
        t0 = tick.astype(jnp.float32) * spec.dt
        t1 = (tick + 1).astype(jnp.float32) * spec.dt
        pos, vel = step_mobility(nodes, bounds, t1, spec.dt)
        nodes = nodes.replace(pos=pos, vel=vel)
        offered = None
        if keyed:
            # the engine's own helper: bit-identical by construction
            from ..core.engine import offered_rate_vector

            offered = offered_rate_vector(spec, nodes.alive[:U], users, t0)
        cache = associate(
            net, nodes.pos, nodes.alive, broker=spec.broker_index,
            offered_rate=offered,
        )
        # mirror _phase_connect's stamps (engine.py) on the users carry
        alive_u = nodes.alive[:U]
        if spec.connect_gating:
            pending = (
                alive_u
                & ~users.connected
                & jnp.isinf(users.connack_at)
                & (users.start_t < t1)
            )
            connack_at = jnp.where(
                pending,
                jnp.maximum(users.start_t, t0) + 2.0 * cache.d2b[:U],
                users.connack_at,
            )
            acked = ~users.connected & (connack_at <= t1)
            users = users.replace(
                connected=users.connected | acked,
                connack_at=connack_at,
                next_send=jnp.where(acked, connack_at, users.next_send),
            )
        # mirror the spawn phase's self-timed send chain (fire times only)
        base = jnp.maximum(users.next_send, t0)
        can = alive_u & users.connected & users.publisher
        n_fire = jnp.clip(
            jnp.ceil((t1 - base) / users.send_interval).astype(jnp.int32),
            0,
            spec.max_sends_per_tick,
        )
        if spec.send_stop_time != float("inf"):
            # fires at/past stopTime never happen (mqttApp2.cc:191-210)
            room = jnp.ceil(
                # simlint: disable=R13 -- the native-DES delay-table
                # chain compiles once per parity world and deliberately
                # mirrors the spawn phase against the ORIGINAL spec; it
                # is never a reused serving program
                (spec.send_stop_time - base) / users.send_interval
            ).astype(jnp.int32)
            n_fire = jnp.minimum(n_fire, jnp.maximum(room, 0))
        n_fire = jnp.where(
            can & (base < t1),
            jnp.minimum(n_fire, S - users.send_count),
            0,
        )
        users = users.replace(
            next_send=jnp.where(
                n_fire > 0,
                base + n_fire.astype(jnp.float32) * users.send_interval,
                users.next_send,
            ),
            send_count=users.send_count + n_fire,
        )
        return (nodes, users), cache.d2b

    _, d2b = jax.jit(
        lambda s, u: jax.lax.scan(
            body, (s, u), jnp.arange(n, dtype=jnp.int32)
        )
    )(state0.nodes, state0.users)
    return np.asarray(d2b, np.float64)


def replay_engine_world(
    spec, final_state, net, horizon: Optional[float] = None,
    state0=None, bounds=None,
) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
    """Replay a finished engine run's publish workload through the DES.

    Extracts the client-side inputs the engine decided (per-task user,
    creation time, MIPSRequired, uplink-loss draws — all independent of
    scheduling), the delay model, the fog boot schedule from the primed
    initial state, and the generation parameters from the spec, then runs
    the native core over the same horizon.

    Static wired worlds use one delay vector; wireless/mobility worlds
    (r4) pass ``state0`` (the scenario's initial state — positions and
    mobility programs are not recoverable from the final state) and the
    DES consumes a per-tick :func:`delay_table` from the same
    association/mobility model, so handover, contention and range loss
    reach the sequential baseline as time-varying data while every event
    is still executed independently.  Energy-driven lifecycle plus
    wireless is the one remaining exclusion (``alive`` would feed back
    into the table through the engine's own protocol traffic).
    """
    import jax.numpy as jnp  # deferred; host-side use only

    from ..net.topology import associate
    from ..state import init_state
    from ..core.engine import prime_initial_advertisements
    from ..spec import Stage

    wireless_world = bool(np.asarray(net.is_wireless).any()) or bool(
        (np.asarray(final_state.nodes.mobility) != 0).any()
    )
    if wireless_world and state0 is None:
        raise NotImplementedError(
            "wireless/mobility replay needs the scenario's initial state: "
            "replay_engine_world(spec, final, net, state0=state, "
            "bounds=bounds)"
        )
    user_mode = False
    if wireless_world and spec.energy_enabled:
        has_e = np.asarray(
            (state0 if state0 is not None else final_state).nodes.has_energy
        )
        if has_e[: spec.n_users].all() and not has_e[spec.n_users :].any():
            # r5 (VERDICT r4 item 5): USER batteries only — the flagship
            # wireless5 combination.  The DES derives its own alive
            # trajectory from its own tick-quantised tx/rx bookings and
            # runs the send chain itself; the delay table stays pure
            # data because APs/fogs never die and the table's only
            # alive-dependence (dead-user unreachability) is overlaid by
            # the DES from its own lifecycle state.  Contention must not
            # depend on user liveness, so Bianchi/linear-contention
            # worlds are excluded below.
            user_mode = True
            if np.asarray(net.mac_loss_tab).shape[0] > 0 or float(
                np.asarray(net.w_contention)
            ) > 0.0:
                raise NotImplementedError(
                    "user-battery wireless parity needs alive-independent "
                    "delays: build the world with mac_model='linear' and "
                    "w_contention=0 (contention-under-churn stays an "
                    "engine-only behaviour, PARITY.md deviation ledger)"
                )
            if (
                spec.send_interval_jitter > 0
                or spec.max_sends_per_tick > 1
                or spec.send_stop_time != float("inf")
            ):
                raise NotImplementedError(
                    "user-battery replay runs the send chain itself: it "
                    "needs send_interval_jitter == 0, max_sends_per_tick "
                    "== 1 and no send_stop_time (the C chain fires one "
                    "publish per user per tick)"
                )
            s0u = (state0 if state0 is not None else final_state).users
            if (
                not np.asarray(s0u.publisher).all()
                or np.asarray(s0u.sub_mask).any()
            ):
                raise NotImplementedError(
                    "user-battery replay books Connect/Connack energy "
                    "only: publisher-role splits and subscriptions are "
                    "not mirrored in the C send chain"
                )
        else:
            raise NotImplementedError(
                "wireless battery lifecycle needs batteries on ALL users "
                "and NONE on fogs/APs for an independent baseline: "
                "partial user batteries would drain battery-less users "
                "in the DES, and infrastructure deaths feed back into "
                "the delay table through the engine's own traffic "
                "(all-user-battery worlds ARE supported, r5; fog energy "
                "parity is gated separately on wired worlds, "
                "tests/test_parity.py::test_parity_energy_aware)"
            )
    # all 7 policies have a sequential baseline (r3): ENERGY_AWARE runs on
    # the DES's per-fog energy model (fed the spec's joule parameters) and
    # RANDOM consumes the same task-id-keyed stream as the engine
    if spec.policy not in (0, 1, 2, 3, 4, 5, 6):
        raise NotImplementedError(
            f"native DES has no parity path for policy {spec.policy}"
        )

    tasks = final_state.tasks
    t_create = np.asarray(tasks.t_create, np.float64)
    used = np.isfinite(t_create)
    table_kw = {}
    if wireless_world:
        tab = delay_table(spec, state0, net, bounds)
        d2b = tab[0]  # static fallback columns (unused when tab is given)
        table_kw = dict(d2b_table=tab, table_dt=spec.dt)
        # the engine's uplink-loss Bernoulli outcomes, replayed as data
        lost = (
            np.asarray(tasks.stage) == int(Stage.LOST)
        ).astype(np.uint8)
        table_kw["task_lost"] = lost if user_mode else lost[used]
    else:
        cache = associate(
            net, final_state.nodes.pos,
            jnp.ones_like(final_state.nodes.alive),
            broker=spec.broker_index,
        )
        d2b = np.asarray(cache.d2b, np.float64)
    fog_nodes = np.arange(spec.n_fogs) + spec.n_users

    # fog boot schedule exactly as prime_initial_advertisements stamped it
    # (a provided state0 is the builder's already-primed initial state)
    state0p = (
        state0
        if state0 is not None
        else prime_initial_advertisements(spec, init_state(spec), net)
    )
    register_t = np.asarray(state0p.broker.register_t, np.float64)
    adv0_t = np.asarray(state0p.broker.adv_arrive_t, np.float64)

    energy_kw = {}
    if spec.policy == 3 or spec.energy_enabled:
        # feed the DES the same joule model (net/energy.py parameters) and
        # the scenario's initial fog energies; harvesting and lifecycle
        # thresholds are not modelled in the DES (parity scenarios run
        # them off)
        fog_sl = slice(spec.n_users, spec.n_users + spec.n_fogs)
        caps = np.asarray(final_state.nodes.energy_capacity, np.float64)[
            fog_sl
        ]
        energy_kw = dict(
            # nodes boot with a full battery (init_state; scenario
            # builders that drain fogs pre-run have no replay path)
            fog_energy0=caps.copy(),
            fog_energy_cap=caps,
            tx_energy_j=spec.tx_energy_j if spec.energy_enabled else 0.0,
            rx_energy_j=spec.rx_energy_j if spec.energy_enabled else 0.0,
            idle_power_w=spec.idle_power_w if spec.energy_enabled else 0.0,
            compute_power_w=(
                spec.compute_power_w if spec.energy_enabled else 0.0
            ),
        )
    rand_kw = {}
    if spec.policy == 4:
        from ..ops.sched import task_uniform
        import jax

        ids = (
            np.arange(spec.task_capacity, dtype=np.int32)
            if user_mode
            else np.nonzero(used)[0].astype(np.int32)
        )
        rand_kw = dict(
            rand_u=np.asarray(
                task_uniform(
                    jax.random.PRNGKey(spec.policy_seed), jnp.asarray(ids)
                ),
                np.float64,
            )
        )

    if user_mode:
        U = spec.n_users
        used = np.ones((spec.task_capacity,), bool)
        energy_kw = dict(
            tx_energy_j=spec.tx_energy_j,
            rx_energy_j=spec.rx_energy_j,
            idle_power_w=spec.idle_power_w,
            compute_power_w=spec.compute_power_w,
            user_energy=dict(
                energy0=np.asarray(state0p.nodes.energy, np.float64)[:U],
                cap=np.asarray(
                    state0p.nodes.energy_capacity, np.float64
                )[:U],
                start=np.asarray(state0p.users.start_t, np.float64),
                interval=np.asarray(
                    state0p.users.send_interval, np.float64
                ),
                connect_gating=spec.connect_gating,
                max_sends_per_user=spec.max_sends_per_user,
                dt=spec.dt,
                harvest_w=spec.harvest_power_w,
                harvest_period=spec.harvest_period_s,
                harvest_duty=spec.harvest_duty,
                shutdown_frac=spec.shutdown_frac,
                start_frac=spec.start_frac,
            ),
        )

    return run_gen(
        task_user=np.asarray(tasks.user)[used],
        task_t_create=t_create[used],
        task_mips_req=np.asarray(tasks.mips_req, np.float64)[used],
        d_ub=d2b[: spec.n_users],
        d_bf=d2b[fog_nodes],
        fog_mips=np.asarray(final_state.fogs.mips, np.float64),
        register_t=register_t,
        adv0_t=adv0_t,
        horizon=spec.horizon if horizon is None else horizon,
        policy=spec.policy,
        fog_model=spec.fog_model,
        app_gen=spec.app_gen,
        mips0_divisor=spec.bug_compat.mips0_divisor,
        zero_initial_view=spec.bug_compat.zero_initial_view_mips,
        adv_on_completion=spec.adv_on_completion,
        adv_periodic=spec.adv_periodic,
        v1_max_scan=spec.bug_compat.v1_max_scan,
        local_pool_leak=spec.bug_compat.local_pool_leak,
        queue_capacity=spec.queue_capacity,
        broker_mips=spec.broker_mips,
        required_time=spec.required_time,
        adv_interval=spec.adv_interval,
        v2_local=spec.v2_local_broker,
        **energy_kw,
        **rand_kw,
        **table_kw,
    ), used
