"""Native C++ event-driven parity core.

``desim.cpp`` is a sequential DES (binary event heap, virtual clock, the
three v3 application state machines of the reference) standing in for
OMNeT++'s execution model; :mod:`bridge` compiles it with g++ and exposes it
over ctypes.  The batched JAX engine is validated against it by
``tests/test_parity.py`` (the <=1% criterion of BASELINE.json).
"""
from . import bridge  # noqa: F401
