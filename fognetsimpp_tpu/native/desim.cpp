// Native event-driven parity core: the sequential-DES baseline the batched
// JAX engine is validated against (the "within 1% of C++ DES" gate of
// BASELINE.json, replacing OMNeT++'s role natively — SURVEY.md §7 step 2).
//
// Implements the v3 hot path exactly as the reference's three application
// state machines execute it, one event at a time on a binary heap:
//
//   publish arrival -> broker argmin schedule   (BrokerBaseApp3.cc:231-319)
//   task arrival    -> fog assign / FIFO queue  (ComputeBrokerApp3.cc:269-320)
//   release         -> complete + promote head  (ComputeBrokerApp3.cc:224-256)
//   advert arrival  -> broker view refresh      (BrokerBaseApp3.cc:123-136)
//
// Faithful-parity switches mirror fognetsimpp_tpu.spec.BugCompat:
//   * mips0_divisor: every candidate's service estimate divides by
//     brokers[0]'s MIPS (BrokerBaseApp3.cc:268,273,275);
//   * zero_initial_view: fogs register with MIPS=0 until their first
//     advertisement lands (BrokerBaseApp3.cc:104), making early estimates
//     +inf exactly like the C++ double division.
//
// The publish schedule (user, creation time, MIPSRequired) is an *input*:
// the client-side behaviour (connect gating, send timers, task-size RNG) is
// driven by the caller so both simulators decide over identical workloads.
//
// Build: g++ -O2 -shared -fPIC desim.cpp -o libdesim.so   (see bridge.py)

#include <cmath>
#include <cstdint>
#include <cstring>
#include <limits>
#include <queue>
#include <vector>

namespace {

constexpr double kInf = std::numeric_limits<double>::infinity();

// Stage codes matching fognetsimpp_tpu.spec.Stage.
enum Stage : int {
  kUnused = 0,
  kPubInflight = 1,
  kTaskInflight = 2,
  kQueued = 3,
  kRunning = 4,
  kDone = 5,
  kNoResource = 6,
  kDropped = 7,
};

enum EventKind : int {
  kEvPubArrive = 0,   // publish reaches the base broker
  kEvTaskArrive = 1,  // FognetMsgTask reaches its fog node
  kEvRelease = 2,     // fog's in-service task completes
  kEvAdvArrive = 3,   // FognetMsgAdvertiseMIPS reaches the broker
  kEvRegister = 4,    // fog's Connect reaches the broker (registration)
};

struct Event {
  double t;
  int64_t seq;  // FIFO tie-break: heap pops equal-time events in push order,
                // matching OMNeT++'s insertion-ordered event list
  int kind;
  int a;      // task id / fog id
  double x;   // advert payload: MIPS
  double y;   // advert payload: busyTime
};

struct EventLater {
  bool operator()(const Event& l, const Event& r) const {
    if (l.t != r.t) return l.t > r.t;
    return l.seq > r.seq;
  }
};

struct Fog {
  double mips = 0.0;
  double busy_time = 0.0;  // sum of service times of queued+running tasks
  int current = -1;        // in-service task id
  double busy_until = kInf;
  std::vector<int> fifo;   // requests[] vector (head = front)
  size_t head = 0;
};

struct Task {
  int user = 0;
  double t_create = 0.0;
  double mips_req = 0.0;
  int stage = kUnused;
  int fog = -1;
  double t_at_broker = kInf;
  double t_at_fog = kInf;
  double t_service_start = kInf;
  double t_complete = kInf;
  double t_q_enter = kInf;
  double t_ack4_fwd = kInf;
  double t_ack4_queued = kInf;
  double t_ack5 = kInf;
  double t_ack6 = kInf;
  double queue_time = kInf;
  double svc = 0.0;  // service time at its fog (tskTime)
};

}  // namespace

extern "C" {

// Runs the v3 world to `horizon` (events past it are not processed, like a
// sim-time-limit) and writes per-task records. Returns processed event count.
long desim_run_v3(
    int n_users, int n_fogs, int n_tasks,
    const int* task_user, const double* task_t_create,
    const double* task_mips_req,
    const double* d_ub,       // (n_users) user<->broker one-way delay
    const double* d_bf,       // (n_fogs) broker<->fog one-way delay
    const double* fog_mips,   // (n_fogs)
    const double* register_t, // (n_fogs) Connect arrival at the broker
    const double* adv0_t,     // (n_fogs) first advertisement arrival time
    double horizon, int mips0_divisor, int zero_initial_view,
    int adv_on_completion, int queue_capacity,
    // outputs (n_tasks):
    double* o_t_at_broker, int* o_fog, double* o_t_at_fog,
    double* o_t_service_start, double* o_t_complete, double* o_t_ack4_fwd,
    double* o_t_ack5, double* o_t_ack4_queued, double* o_t_ack6,
    double* o_queue_time, int* o_stage) {
  std::vector<Fog> fogs(n_fogs);
  std::vector<Task> tasks(n_tasks);
  // broker's stale view (brokers[] vector, BrokerBaseApp3.h:26-63)
  std::vector<double> view_mips(n_fogs, 0.0), view_busy(n_fogs, 0.0);
  std::vector<char> registered(n_fogs, 0);

  std::priority_queue<Event, std::vector<Event>, EventLater> heap;
  int64_t seq = 0;
  auto push = [&](double t, int kind, int a, double x = 0.0, double y = 0.0) {
    heap.push(Event{t, seq++, kind, a, x, y});
  };

  for (int f = 0; f < n_fogs; ++f) {
    fogs[f].mips = fog_mips[f];
    if (!zero_initial_view) view_mips[f] = fog_mips[f];
    if (std::isfinite(register_t[f])) push(register_t[f], kEvRegister, f);
    if (std::isfinite(adv0_t[f]))
      push(adv0_t[f], kEvAdvArrive, f, fog_mips[f], 0.0);
  }
  for (int i = 0; i < n_tasks; ++i) {
    tasks[i].user = task_user[i];
    tasks[i].t_create = task_t_create[i];
    tasks[i].mips_req = task_mips_req[i];
    if (std::isfinite(task_t_create[i])) {
      tasks[i].stage = kPubInflight;
      tasks[i].t_at_broker = task_t_create[i] + d_ub[task_user[i]];
      push(tasks[i].t_at_broker, kEvPubArrive, i);
    }
  }

  long n_events = 0;
  while (!heap.empty()) {
    Event ev = heap.top();
    heap.pop();
    if (ev.t > horizon) break;
    ++n_events;
    switch (ev.kind) {
      case kEvRegister:
        registered[ev.a] = 1;  // brokers.push_back (BrokerBaseApp3.cc:102-107)
        break;
      case kEvAdvArrive:  // latest-wins view refresh (:123-136)
        view_mips[ev.a] = ev.x;
        view_busy[ev.a] = ev.y;
        break;
      case kEvPubArrive: {
        Task& tk = tasks[ev.a];
        // status-4 "forwarded" ack straight back to the client (:146-150)
        tk.t_ack4_fwd = ev.t + d_ub[tk.user];
        // the `<` scan over brokers[] (BrokerBaseApp3.cc:267-281):
        // first-wins tie-break, +inf estimates while view MIPS is 0
        int best = -1;
        double best_score = kInf;
        bool any = false;
        for (int f = 0; f < n_fogs; ++f) {
          if (!registered[f]) continue;
          double div = mips0_divisor ? view_mips[0] : view_mips[f];
          double est = div > 0.0 ? tk.mips_req / div : kInf;
          double score = view_busy[f] + est;
          if (!any || score < best_score) {
            best = f;
            best_score = score;
            any = true;
          }
        }
        if (!any) {  // "no compute resource available" (:306-319)
          tk.stage = kNoResource;
          break;
        }
        tk.stage = kTaskInflight;
        tk.fog = best;
        tk.t_at_fog = ev.t + d_bf[best];
        push(tk.t_at_fog, kEvTaskArrive, ev.a);
        break;
      }
      case kEvTaskArrive: {  // ComputeBrokerApp3.cc:269-320
        Task& tk = tasks[ev.a];
        Fog& fg = fogs[tk.fog];
        tk.svc = tk.mips_req / fg.mips;       // tskTime (:276)
        fg.busy_time += tk.svc;               // busyTime += tskTime (:279)
        if (fg.current < 0) {                 // idle: assign (:282-303)
          fg.current = ev.a;
          tk.stage = kRunning;
          tk.t_service_start = ev.t;
          fg.busy_until = ev.t + tk.svc;
          tk.t_ack5 = ev.t + d_bf[tk.fog] + d_ub[tk.user];  // "assigned"
          push(fg.busy_until, kEvRelease, tk.fog);
        } else {                              // busy: FIFO (:304-314)
          int backlog = static_cast<int>(fg.fifo.size() - fg.head);
          if (backlog >= queue_capacity) {    // engine-side cap analog; the
            tk.stage = kDropped;              // reference vector is unbounded
            break;
          }
          fg.fifo.push_back(ev.a);
          tk.stage = kQueued;
          tk.t_q_enter = ev.t;
          tk.t_ack4_queued = ev.t + d_bf[tk.fog] + d_ub[tk.user];  // "queued"
        }
        break;
      }
      case kEvRelease: {  // releaseResource (ComputeBrokerApp3.cc:224-256)
        Fog& fg = fogs[ev.a];
        if (fg.current < 0) break;
        Task& done = tasks[fg.current];
        double t_done = fg.busy_until;
        done.stage = kDone;
        done.t_complete = t_done;
        done.t_ack6 = t_done + d_bf[ev.a] + d_ub[done.user];  // "performed"
        fg.busy_time -= done.svc;  // busyTime -= requiredTime (:232)
        fg.current = -1;
        fg.busy_until = kInf;
        if (fg.head < fg.fifo.size()) {  // promote FIFO head (:236-252)
          int nxt = fg.fifo[fg.head++];
          Task& tn = tasks[nxt];
          fg.current = nxt;
          tn.stage = kRunning;
          tn.t_service_start = t_done;
          tn.queue_time = t_done - tn.t_q_enter;  // queueTime signal (:238)
          fg.busy_until = t_done + tn.svc;
          push(fg.busy_until, kEvRelease, ev.a);
        }
        if (adv_on_completion)  // advertiseMIPS() at :254
          push(t_done + d_bf[ev.a], kEvAdvArrive, ev.a, fg.mips, fg.busy_time);
        break;
      }
    }
  }

  for (int i = 0; i < n_tasks; ++i) {
    const Task& tk = tasks[i];
    o_t_at_broker[i] = tk.t_at_broker;
    o_fog[i] = tk.fog;
    o_t_at_fog[i] = tk.t_at_fog;
    o_t_service_start[i] = tk.t_service_start;
    o_t_complete[i] = tk.t_complete;
    o_t_ack4_fwd[i] = tk.t_ack4_fwd;
    o_t_ack5[i] = tk.t_ack5;
    o_t_ack4_queued[i] = tk.t_ack4_queued;
    o_t_ack6[i] = tk.t_ack6;
    o_queue_time[i] = tk.queue_time;
    o_stage[i] = tk.stage;
  }
  return n_events;
}

}  // extern "C"
