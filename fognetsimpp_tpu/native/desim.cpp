// Native event-driven parity core: the sequential-DES baseline the batched
// JAX engine is validated against (the "within 1% of C++ DES" gate of
// BASELINE.json, replacing OMNeT++'s role natively — SURVEY.md §7 step 2).
//
// Implements all three app generations exactly as the reference's state
// machines execute them, one event at a time on a binary heap:
//
//   v3 (FIFO fogs, min-busy broker):
//     publish arrival -> broker argmin schedule   (BrokerBaseApp3.cc:231-319)
//     task arrival    -> fog assign / FIFO queue  (ComputeBrokerApp3.cc:269-320)
//     release         -> complete + promote head  (ComputeBrokerApp3.cc:224-256)
//     advert arrival  -> broker view refresh      (BrokerBaseApp3.cc:123-136)
//   v1/v2 (MIPS-pool fogs, LOCAL_FIRST / buggy MAX_MIPS broker):
//     local accept    -> pool debit + status-3    (BrokerBaseApp.cc:171-212)
//     offload scan    -> compare-to-first winner  (BrokerBaseApp.cc:228-252)
//     pool arrival    -> strict-< accept/reject   (ComputeBrokerApp2.cc:258-310)
//     pool release    -> refund + status-6 relay  (ComputeBrokerApp2.cc:222-245)
//     periodic advert -> every 0.01 s, MIPS=pool  (ComputeBrokerApp2.cc:219)
//
// Faithful-parity switches mirror fognetsimpp_tpu.spec.BugCompat:
//   * mips0_divisor: every candidate's service estimate divides by
//     brokers[0]'s MIPS (BrokerBaseApp3.cc:268,273,275);
//   * zero_initial_view: fogs register with MIPS=0 until their first
//     advertisement lands (BrokerBaseApp3.cc:104);
//   * v1_max_scan: the offload scan never updates its running max, so the
//     winner is the LAST fog whose MIPS beats fog 0's (BrokerBaseApp.cc:
//     232-236);
//   * local_pool_leak: the v1 local path never records its request, so the
//     broker pool is never refunded (BrokerBaseApp.cc:208 commented out).
//
// The publish schedule (user, creation time, MIPSRequired) is an *input*:
// the client-side behaviour (connect gating, send timers, task-size RNG) is
// driven by the caller so both simulators decide over identical workloads.
//
// Build: g++ -O2 -shared -fPIC desim.cpp -o libdesim.so   (see bridge.py)

#include <cmath>
#include <cstddef>
#include <cstdint>
#include <limits>
#include <queue>
#include <vector>

namespace {

constexpr double kInf = std::numeric_limits<double>::infinity();

// Stage codes matching fognetsimpp_tpu.spec.Stage.
enum Stage : int {
  kUnused = 0,
  kPubInflight = 1,
  kTaskInflight = 2,
  kQueued = 3,
  kRunning = 4,
  kDone = 5,
  kNoResource = 6,
  kDropped = 7,
  kLocalRun = 8,
  kRejected = 9,
  kLost = 10,
};

// Policy codes matching fognetsimpp_tpu.spec.Policy.  r3: ENERGY_AWARE
// runs on the native per-fog energy model below (same joule accounting as
// net/energy.py) and RANDOM consumes the caller-provided per-task unit
// draws (ops/sched.py::task_uniform) — all 7 policies have a sequential
// baseline.
enum Policy : int {
  kMinBusy = 0,
  kRoundRobin = 1,
  kMinLatency = 2,
  kEnergyAware = 3,
  kRandom = 4,
  kLocalFirst = 5,
  kMaxMips = 6,
};

enum FogModel : int { kFifo = 0, kPool = 1 };

enum EventKind : int {
  kEvPubArrive = 0,   // publish reaches the base broker
  kEvTaskArrive = 1,  // FognetMsgTask reaches its fog node
  kEvRelease = 2,     // FIFO fog's in-service task completes
  kEvAdvArrive = 3,   // FognetMsgAdvertiseMIPS reaches the broker
  kEvRegister = 4,    // fog's Connect reaches the broker (registration)
  kEvPoolDone = 5,    // pool task's requiredTime expires (a = task id)
  kEvLocalDone = 6,   // broker-local task expires (a = task id)
  kEvAdvTimer = 7,    // v1/v2 periodic re-advertisement (a = fog id)
  kEvBrokerRelease = 8,  // v2 broker's shared RELEASERESOURCE self-message
  //                        (a = generation: stale == cancelled)
};

struct Event {
  double t;
  int64_t seq;  // FIFO tie-break: equal-time events pop in push order,
                // matching OMNeT++'s insertion-ordered event list
  int kind;
  int a;      // task id / fog id
  double x;   // advert payload: MIPS
  double y;   // advert payload: busyTime
};

struct EventLater {
  bool operator()(const Event& l, const Event& r) const {
    if (l.t != r.t) return l.t > r.t;
    return l.seq > r.seq;
  }
};

struct Fog {
  double mips = 0.0;
  double busy_time = 0.0;  // FIFO: sum of service times of queued+running
  double pool = 0.0;       // POOL: remaining MIPS
  int current = -1;        // FIFO in-service task id
  double busy_until = kInf;
  std::vector<int> fifo;   // requests[] vector (head = front)
  size_t head = 0;
  // per-fog energy (net/energy.py joule model, continuous-time form):
  // linear idle/compute drain integrated lazily at each touching event,
  // per-message costs deducted at the event, clipped to [0, cap].
  // (No harvesting or lifecycle thresholds here: the parity scenarios run
  // them off; the engine's tick model books message costs in the deciding
  // tick, so the skew between the two accountings is <= one tick.)
  bool has_energy = false;
  double energy = 0.0;
  double cap = 1.0;
  double t_energy = 0.0;  // last integration time
};

// Per-user battery + self-timed publish chain (r5, VERDICT r4 item 5):
// the flagship wireless5 combination — 802.11 users whose batteries
// drain, die and restart (wireless5.ini:150-166, mqttApp2.cc:471-492) —
// gets an INDEPENDENT sequential baseline by letting the DES derive its
// own alive trajectory from its own tx/rx events.  Energy/lifecycle is
// tick-quantised exactly like the engine's step_energy (net/energy.py):
// per-tick message counts, float32 arithmetic in the same op order,
// square-wave harvest, hysteresis thresholds.  Active only in
// user-energy mode (user_energy0 != nullptr); requires battery-less
// fogs/APs so the delay table stays pure data (the engine-side table
// assumes always-alive rows; the DES overlays dead-user unreachability
// itself via d_user).
struct UserNode {
  bool alive = true;
  float energy = 0.f, cap = 1.f;
  int tx_tick = 0, rx_tick = 0;  // current-tick message accumulators
  // mqttApp2 send chain (mirrors engine _phase_connect/_phase_spawn).
  // FLOAT on purpose: the engine's chain is float32 (next_send/connack
  // accumulate in f32), and tick-boundary comparisons must land on the
  // same side in both simulators.
  float start_t = std::numeric_limits<float>::infinity();
  float connack_at = std::numeric_limits<float>::infinity();
  float next_send = std::numeric_limits<float>::infinity();
  bool connected = false;
  int send_count = 0;
};

struct Task {
  int user = 0;
  double t_create = 0.0;
  double mips_req = 0.0;
  int stage = kUnused;
  int fog = -1;
  double t_at_broker = kInf;
  double t_at_fog = kInf;
  double t_service_start = kInf;
  double t_complete = kInf;
  double t_q_enter = kInf;
  double t_ack3 = kInf;
  double t_ack4_fwd = kInf;
  double t_ack4_queued = kInf;
  double t_ack5 = kInf;
  double t_ack6 = kInf;
  double queue_time = kInf;
  double svc = 0.0;  // FIFO service time at its fog (tskTime)
};

struct Params {
  int n_users, n_fogs, n_tasks;
  const double* d_ub;
  const double* d_bf;  // also yields MIN_LATENCY's rtt = 2 * d_bf
  double horizon;
  int policy, fog_model, app_gen;
  int mips0_divisor, zero_initial_view, adv_on_completion, adv_periodic;
  int v1_max_scan, local_pool_leak;
  int queue_capacity;
  double broker_mips, required_time, adv_interval;
  // energy model (spec.tx_energy_j etc.) + RANDOM's shared stream
  double tx_j, rx_j, idle_w, compute_w;
  const double* rand_u;  // (n_tasks) or nullptr
  // v2 hybrid broker (spec.v2_local_broker): single shared release timer
  int v2_local;
  // time-varying node<->broker delays (wireless/mobility worlds):
  // row s covers simulated time (s*tab_dt, (s+1)*tab_dt] — the batched
  // engine evaluates every event-decision phase against the link cache
  // of the tick CONTAINING the event under its `<= t1` masks, so the
  // lookup is ceil(t/tab_dt)-1.  nullptr = static d_ub/d_bf vectors.
  // +inf entries mean "unreachable now" (out of AP range): the message
  // is never delivered, like a packet that never associates in INET.
  const double* d2b_tab;  // (tab_steps, tab_stride) node-major rows
  int tab_steps;
  int tab_stride;  // n_users + n_fogs + ... (node-axis length)
  double tab_dt;
  // per-task wireless uplink loss (engine Stage.LOST replayed as data:
  // the Bernoulli draw is the engine's, so both simulators lose the
  // SAME publishes); nullptr = no loss
  const unsigned char* task_lost;
  // --- user energy + lifecycle mode (r5; nullptr = off) --------------
  const double* user_energy0;    // (n_users) initial joules
  const double* user_energy_cap; // (n_users)
  const double* user_start;      // (n_users) app start times
  const double* user_interval;   // (n_users) publish intervals
  int connect_gating;
  int max_sends_per_user;        // S: task slot = u * S + k
  double e_dt;                   // engine tick (energy quantum)
  double harvest_w, harvest_period, harvest_duty;
  double shutdown_frac, start_frac;
};

struct World {
  Params p;
  std::vector<Fog> fogs;
  std::vector<UserNode> users;  // populated only in user-energy mode
  std::vector<Task> tasks;
  std::vector<double> view_mips, view_busy;  // brokers[] stale view
  std::vector<char> registered;
  double local_pool = 0.0;
  int64_t rr_cursor = 0;  // ROUND_ROBIN position among registered fogs
  // v2 broker requests[] (insertion order) + the shared timer generation
  // (cancelEvent == bump the generation; stale events are skipped)
  std::vector<int> broker_reqs;
  std::vector<char> req_open;  // parallel to tasks
  int64_t release_gen = 0;
  std::priority_queue<Event, std::vector<Event>, EventLater> heap;
  int64_t seq = 0;

  void push(double t, int kind, int a, double x = 0.0, double y = 0.0) {
    if (!std::isfinite(t)) return;  // unreachable endpoint: never delivered
    heap.push(Event{t, seq++, kind, a, x, y});
  }

  // Lazy linear drain (idle + compute-while-serving), then optional
  // per-message joule cost; clip to [0, cap] like net/energy.py.
  void touch_energy(int f, double now, double msg_j = 0.0) {
    Fog& fg = fogs[f];
    if (!fg.has_energy) return;
    double drain =
        (p.idle_w + (fg.current >= 0 ? p.compute_w : 0.0)) *
        (now - fg.t_energy);
    fg.t_energy = now;
    fg.energy -= drain + msg_j;
    if (fg.energy < 0.0) fg.energy = 0.0;
    if (fg.energy > fg.cap) fg.energy = fg.cap;
  }

  // --- delay model -----------------------------------------------------
  // Static vectors (wired worlds) or the caller-precomputed per-tick
  // table (wireless/mobility): the same association/mobility model the
  // batched engine runs, evaluated at the tick containing the event.
  double tab(int node, double t) const {
    int s = static_cast<int>(std::ceil(t / p.tab_dt)) - 1;
    if (s < 0) s = 0;
    if (s >= p.tab_steps) s = p.tab_steps - 1;
    return p.d2b_tab[static_cast<size_t>(s) * p.tab_stride + node];
  }
  // row k of the delay table (the engine's tick-k cache) by INDEX —
  // time-keyed lookups at f32 tick boundaries can land one row off
  // when float(dt) > dt (code-review r5)
  double tab_row(int node, int k) const {
    int s = k < 0 ? 0 : (k >= p.tab_steps ? p.tab_steps - 1 : k);
    return p.d2b_tab[static_cast<size_t>(s) * p.tab_stride + node];
  }

  double d_user(int u, double t) const {
    // user-energy mode: a dead user is unassociated — exactly the
    // engine's cache (assoc requires alive), which the table rows
    // cannot carry because they are built alive-agnostic
    if (!users.empty() && !users[u].alive) return kInf;
    return p.d2b_tab ? tab(u, t) : p.d_ub[u];
  }
  // TickBuf bookings (user side): the engine charges message energy in
  // the tick where the send/receive is DECIDED; every DES handler runs
  // inside that same tick's drain window, so plain accumulators match.
  void user_tx(int u) { if (!users.empty()) users[u].tx_tick += 1; }
  void user_rx(int u) { if (!users.empty()) users[u].rx_tick += 1; }
  double d_fog(int f, double t) const {
    return p.d2b_tab ? tab(p.n_users + f, t) : p.d_bf[f];
  }

  // v3 `<` scan over brokers[] (BrokerBaseApp3.cc:267-281): first-wins
  // tie-break, +inf estimates while the view MIPS is 0.  MIN_LATENCY is
  // the same scan with the broker->fog round trip added per candidate;
  // ENERGY_AWARE adds 10*(1 - energy fraction) evaluated at decision time
  // (the dead `algo` parameter realised — same formula as ops/sched.py).
  int pick_min_score(double req, bool add_rtt, bool add_energy,
                     double now) {
    int best = -1;
    double best_score = kInf;
    bool any = false;
    int first_reg = -1;  // brokers[0] = first REGISTERED fog (ADVICE r2)
    for (int f = 0; f < p.n_fogs; ++f) {
      if (!registered[f]) continue;
      if (first_reg < 0) first_reg = f;
      double div = p.mips0_divisor ? view_mips[first_reg] : view_mips[f];
      double est = div > 0.0 ? req / div : kInf;
      double score = view_busy[f] + est;
      if (add_rtt) score += 2.0 * d_fog(f, now);
      if (add_energy) {
        touch_energy(f, now);
        double cap = fogs[f].cap > 1e-12 ? fogs[f].cap : 1e-12;
        score += 10.0 * (1.0 - fogs[f].energy / cap);
      }
      if (!any || score < best_score) {
        best = f;
        best_score = score;
        any = true;
      }
    }
    return any ? best : -1;
  }

  // RANDOM: slot = floor(u * n_registered) computed in f32 exactly like
  // the engine (ops/sched.py b_random) over the shared per-task stream.
  int pick_random(int task) {
    std::vector<int> avail;
    for (int f = 0; f < p.n_fogs; ++f)
      if (registered[f]) avail.push_back(f);
    if (avail.empty() || p.rand_u == nullptr) return avail.empty() ? -1 : avail[0];
    float u = static_cast<float>(p.rand_u[task]);
    int slot = static_cast<int>(u * static_cast<float>(avail.size()));
    if (slot < 0) slot = 0;
    if (slot >= static_cast<int>(avail.size()))
      slot = static_cast<int>(avail.size()) - 1;
    return avail[slot];
  }

  // ROUND_ROBIN over the registered set; the cursor advances per decision
  // (the batched engine advances it by the masked count per window and
  // ranks same-window arrivals by arrival time — the same sequence).
  int pick_round_robin() {
    std::vector<int> avail;
    for (int f = 0; f < p.n_fogs; ++f)
      if (registered[f]) avail.push_back(f);
    if (avail.empty()) return -1;
    int choice = avail[rr_cursor % avail.size()];
    rr_cursor = (rr_cursor + 1) % avail.size();
    return choice;
  }

  // v1/v2 offload scan (BrokerBaseApp.cc:228-240): with the faithful bug,
  // `temp` stays brokers[0]'s MIPS, so the winner is the LAST registered
  // fog whose advertised MIPS beats fog 0's (or fog 0 itself).
  int pick_max_mips() const {
    int first = -1, winner = -1;
    for (int f = 0; f < p.n_fogs; ++f) {
      if (!registered[f]) continue;
      if (first < 0) {
        first = winner = f;
        continue;
      }
      if (p.v1_max_scan) {
        if (view_mips[f] > view_mips[first]) winner = f;  // temp not updated
      } else {
        if (view_mips[f] > view_mips[winner]) winner = f;
      }
    }
    return winner;
  }

  void broker_decide(int i, double now) {
    Task& tk = tasks[i];
    user_rx(tk.user);  // engine: rx_u += 1 per decided publish (ack relay)
    // v1/v2 LOCAL_FIRST: run locally when the broker pool covers it
    // (strict <, BrokerBaseApp.cc:171-180 / BrokerBaseApp2.cc:181);
    // status-3 "processing" ack
    if (p.policy == kLocalFirst && tk.mips_req < local_pool) {
      local_pool -= tk.mips_req;
      tk.stage = kLocalRun;
      tk.t_service_start = now;
      tk.t_ack3 = now + d_user(tk.user, now);
      if (p.v2_local) {
        // v2: store the request; completion comes only from the shared
        // timer — cancelEvent + scheduleAt (BrokerBaseApp2.cc:221-224)
        broker_reqs.push_back(i);
        req_open[i] = 1;
        ++release_gen;
        push(now + p.required_time, kEvBrokerRelease, (int)release_gen);
      } else {
        tk.t_complete = now + p.required_time;
        push(tk.t_complete, kEvLocalDone, i);
      }
      return;
    }
    // every non-local publish gets the "forwarded" status-4 (:146-150)
    tk.t_ack4_fwd = now + d_user(tk.user, now);
    int choice;
    switch (p.policy) {
      case kMinBusy:
        choice = pick_min_score(tk.mips_req, false, false, now);
        break;
      case kRoundRobin:
        choice = pick_round_robin();
        break;
      case kMinLatency:
        choice = pick_min_score(tk.mips_req, true, false, now);
        break;
      case kEnergyAware:
        choice = pick_min_score(tk.mips_req, false, true, now);
        break;
      case kRandom:
        choice = pick_random(i);
        break;
      default:
        choice = pick_max_mips();
    }
    if (choice < 0) {  // "no compute resource available" (:306-319)
      tk.stage = kNoResource;
      return;
    }
    if (p.v2_local && p.policy == kLocalFirst) {
      // v2 stores a Request for every offload-branch decision with fogs
      // present — even when the guard below refuses to send
      // (BrokerBaseApp2.cc:244-252); its later release refunds MIPS that
      // was never debited (pool inflation)
      broker_reqs.push_back(i);
      req_open[i] = 1;
    }
    if ((p.policy == kLocalFirst || p.policy == kMaxMips) &&
        !(tk.mips_req < view_mips[choice])) {
      // v1 guard: an oversized task is never sent (BrokerBaseApp.cc:244)
      tk.stage = kRejected;
      return;
    }
    tk.stage = kTaskInflight;
    tk.fog = choice;
    tk.t_at_fog = now + d_fog(choice, now);
    push(tk.t_at_fog, kEvTaskArrive, i);
  }

  void fifo_arrive(int i, double now) {  // ComputeBrokerApp3.cc:269-320
    Task& tk = tasks[i];
    Fog& fg = fogs[tk.fog];
    // fog rx (the task) + tx (the assigned/queued ack) — the engine books
    // both per arrival (engine.py _phase_fog_arrivals tx_f/rx_f)
    touch_energy(tk.fog, now, p.rx_j + p.tx_j);
    tk.svc = tk.mips_req / fg.mips;       // tskTime (:276)
    fg.busy_time += tk.svc;               // busyTime += tskTime (:279)
    if (fg.current < 0) {                 // idle: assign (:282-303)
      fg.current = i;
      tk.stage = kRunning;
      tk.t_service_start = now;
      fg.busy_until = now + tk.svc;
      tk.t_ack5 = now + d_fog(tk.fog, now) + d_user(tk.user, now);  // "assigned"
      user_rx(tk.user);  // engine: acked arrivals book a user rx
      push(fg.busy_until, kEvRelease, tk.fog);
    } else {                              // busy: FIFO (:304-314)
      int backlog = static_cast<int>(fg.fifo.size() - fg.head);
      if (backlog >= p.queue_capacity) {  // engine-side cap analog; the
        tk.stage = kDropped;              // reference vector is unbounded
        return;
      }
      fg.fifo.push_back(i);
      tk.stage = kQueued;
      tk.t_q_enter = now;
      tk.t_ack4_queued = now + d_fog(tk.fog, now) + d_user(tk.user, now);  // "queued"
      user_rx(tk.user);
    }
  }

  void fifo_release(int f, double) {  // releaseResource (:224-256)
    Fog& fg = fogs[f];
    if (fg.current < 0) return;
    Task& done = tasks[fg.current];
    double t_done = fg.busy_until;
    // ack6 tx (+ advert tx when adv_on_completion) — engine books
    // comp * (2 | 1) in _phase_completions.  Touch BEFORE clearing
    // `current` so the compute drain integrates over the service time.
    touch_energy(f, t_done, p.tx_j * (p.adv_on_completion ? 2.0 : 1.0));
    done.stage = kDone;
    done.t_complete = t_done;
    done.t_ack6 = t_done + d_fog(f, t_done) + d_user(done.user, t_done);  // "performed"
    user_rx(done.user);
    fg.busy_time -= done.svc;  // busyTime -= requiredTime (:232)
    fg.current = -1;
    fg.busy_until = kInf;
    if (fg.head < fg.fifo.size()) {  // promote FIFO head (:236-252)
      int nxt = fg.fifo[fg.head++];
      Task& tn = tasks[nxt];
      fg.current = nxt;
      tn.stage = kRunning;
      tn.t_service_start = t_done;
      tn.queue_time = t_done - tn.t_q_enter;  // queueTime signal (:238)
      fg.busy_until = t_done + tn.svc;
      push(fg.busy_until, kEvRelease, f);
    }
    if (p.adv_on_completion)  // advertiseMIPS() at :254
      push(t_done + d_fog(f, t_done), kEvAdvArrive, f, fg.mips,
           fg.busy_time);
  }

  void pool_arrive(int i, double now) {  // ComputeBrokerApp2.cc:258-310
    Task& tk = tasks[i];
    Fog& fg = fogs[tk.fog];
    touch_energy(tk.fog, now, p.rx_j + p.tx_j);  // task rx + TaskAck tx
    if (tk.mips_req < fg.pool) {  // strict <, :269
      fg.pool -= tk.mips_req;     // :272
      tk.stage = kRunning;
      tk.t_service_start = now;
      tk.t_complete = now + p.required_time;
      push(tk.t_complete, kEvPoolDone, i);
    } else {  // TaskAck(status=false): every broker generation ignores it
      tk.stage = kRejected;  // (:300-310, BrokerBaseApp2.cc:139-141)
    }
  }

  void pool_done(int i, double now) {  // releaseResource (:222-245)
    Task& tk = tasks[i];
    touch_energy(tk.fog, now, p.tx_j);  // status-6 Puback tx
    fogs[tk.fog].pool += tk.mips_req;
    tk.stage = kDone;
    if (p.app_gen >= 2) {  // v1 acks via FognetMsgTaskAck, which the broker
      //                      logs and drops: the client never learns
      tk.t_ack6 = now + d_fog(tk.fog, now) + d_user(tk.user, now);
      user_rx(tk.user);
    }
  }

  void local_done(int i, double now) {  // BrokerBaseApp.cc:369-394
    Task& tk = tasks[i];
    if (!p.local_pool_leak) local_pool += tk.mips_req;
    tk.stage = kDone;
    tk.t_ack6 = now + d_user(tk.user, now);  // status-6 straight to the client
    user_rx(tk.user);
  }

  void v2_broker_release(int gen, double now) {
    // BrokerBaseApp2.cc:284-312: the shared timer fires — unless a later
    // accept cancelled it (stale generation) — and releases exactly ONE
    // stored request, the first in insertion order whose requiredTime
    // passed: pool += its MIPS, status-6 straight to the client, erase.
    if (gen != (int)release_gen) return;  // cancelEvent()
    for (size_t j = 0; j < broker_reqs.size(); ++j) {
      int i = broker_reqs[j];
      if (!req_open[i]) continue;
      Task& tk = tasks[i];
      if (tk.t_at_broker + p.required_time < now) {
        local_pool += tk.mips_req;
        req_open[i] = 0;
        broker_reqs.erase(broker_reqs.begin() + j);
        double ack = now + d_user(tk.user, now);
        user_rx(tk.user);
        if (ack < tk.t_ack6) tk.t_ack6 = ack;  // duplicate-ack min
        if (tk.stage == kLocalRun) {
          tk.stage = kDone;
          tk.t_complete = now;
        }
        break;
      }
    }
    // the self-message is spent; only the next accept reschedules it
  }

  // ---- user-energy mode (r5): tick-quantised lifecycle ---------------
  // The engine gates connect/spawn on `alive` per tick and runs
  // step_energy at each tick end; this loop replicates that ordering:
  // per tick — connect stamps, spawn fires, then every heap event with
  // t <= t1 (the engine's `<= t1` masks), then the energy step.

  void connect_phase(float t0, float t1, int k) {  // _phase_connect mirror
    if (!p.connect_gating) return;
    for (int u = 0; u < p.n_users; ++u) {
      UserNode& un = users[u];
      if (un.alive && !un.connected && !std::isfinite(un.connack_at) &&
          un.start_t < t1) {
        un.tx_tick += 1;  // Connect
        float t_send = std::max(un.start_t, t0);
        // cache row of THIS tick, fetched by index
        float d = static_cast<float>(
            p.d2b_tab ? tab_row(u, k) : p.d_ub[u]);
        un.connack_at = t_send + 2.0f * d;  // f32 like the engine
      }
      if (!un.connected && un.connack_at <= t1) {
        un.connected = true;
        un.rx_tick += 1;  // Connack (no subscriptions in these worlds)
        un.next_send = un.connack_at;
      }
    }
  }

  void spawn_phase(float t0, float t1, int k) {  // _phase_spawn mirror
    for (int u = 0; u < p.n_users; ++u) {
      UserNode& un = users[u];
      if (!(un.alive && un.connected && un.next_send < t1 &&
            un.send_count < p.max_sends_per_user))
        continue;
      float t_create = std::max(un.next_send, t0);
      int slot = u * p.max_sends_per_user + un.send_count;
      un.tx_tick += 1;  // the publish is sent either way
      Task& tk = tasks[slot];
      tk.user = u;
      tk.t_create = t_create;
      // mips_req replayed per slot (the engine's PRNG draw for this
      // fire tick — valid as data iff the alive trajectories agree,
      // which the gate asserts via the t_create columns)
      if (p.task_lost != nullptr && p.task_lost[slot]) {
        tk.stage = kLost;
      } else {
        tk.stage = kPubInflight;
        float d = static_cast<float>(
            p.d2b_tab ? tab_row(u, k) : p.d_ub[u]);
        tk.t_at_broker = t_create + d;  // f32 stamp like the engine
        push(tk.t_at_broker, kEvPubArrive, slot);
      }
      un.next_send = t_create + static_cast<float>(p.user_interval[u]);
      un.send_count += 1;
    }
  }

  void energy_tick(float, int k) {  // step_energy mirror (f32)
    float dt = static_cast<float>(p.e_dt);
    float t1f = static_cast<float>(k + 1) * dt;  // engine's f32 t1
    float phase = std::fmod(t1f, static_cast<float>(p.harvest_period)) /
                  static_cast<float>(p.harvest_period);
    // idle*dt and harvest*dt are PYTHON (f64) products in the engine,
    // rounded to f32 once as constants — round the f64 product, never
    // the factors (one-ulp drift here shifted revival ticks, r5)
    float gain = phase < static_cast<float>(p.harvest_duty)
                     ? static_cast<float>(p.harvest_w * p.e_dt)
                     : 0.f;
    float idle_dt = static_cast<float>(p.idle_w * p.e_dt);
    for (int u = 0; u < p.n_users; ++u) {
      UserNode& un = users[u];
      float drain = idle_dt +
                    static_cast<float>(p.tx_j) * un.tx_tick +
                    static_cast<float>(p.rx_j) * un.rx_tick;
      float e = un.energy - (un.alive ? drain : 0.f) + gain;
      if (e < 0.f) e = 0.f;
      if (e > un.cap) e = un.cap;
      un.energy = e;
      float frac = e / std::max(un.cap, 1e-12f);
      if (un.alive && frac <= static_cast<float>(p.shutdown_frac))
        un.alive = false;
      else if (!un.alive && frac >= static_cast<float>(p.start_frac))
        un.alive = true;
      un.tx_tick = un.rx_tick = 0;
    }
  }

  long run_user_energy() {
    long n_events = 0;
    // the engine runs spec.n_ticks = round(horizon / dt) ticks;
    // Python round() is half-to-even = nearbyint under the default
    // rounding mode (lround would round half away from zero)
    int n_ticks = static_cast<int>(std::nearbyint(p.horizon / p.e_dt));
    float dtf = static_cast<float>(p.e_dt);
    for (int k = 0; k < n_ticks; ++k) {
      // f32 tick boundaries, exactly the engine's
      //   t0 = tick.astype(f32) * dt;  t1 = (tick+1).astype(f32) * dt
      float t0 = static_cast<float>(k) * dtf;
      float t1 = static_cast<float>(k + 1) * dtf;
      connect_phase(t0, t1, k);
      spawn_phase(t0, t1, k);
      while (!heap.empty() &&
             heap.top().t <= static_cast<double>(t1)) {
        Event ev = heap.top();
        heap.pop();
        ++n_events;
        dispatch(ev);
      }
      energy_tick(t1, k);
    }
    return n_events;
  }

  void dispatch(const Event& ev) {
    switch (ev.kind) {
      case kEvRegister:
        registered[ev.a] = 1;
        break;
      case kEvAdvArrive:
        view_mips[ev.a] = ev.x;
        view_busy[ev.a] = ev.y;
        break;
      case kEvAdvTimer: {
        Fog& fg = fogs[ev.a];
        double payload = p.fog_model == kPool ? fg.pool : fg.mips;
        push(ev.t + d_fog(ev.a, ev.t), kEvAdvArrive, ev.a, payload,
             fg.busy_time);
        push(ev.t + p.adv_interval, kEvAdvTimer, ev.a);
        break;
      }
      case kEvPubArrive:
        broker_decide(ev.a, ev.t);
        break;
      case kEvTaskArrive:
        if (p.fog_model == kPool)
          pool_arrive(ev.a, ev.t);
        else
          fifo_arrive(ev.a, ev.t);
        break;
      case kEvRelease:
        fifo_release(ev.a, ev.t);
        break;
      case kEvPoolDone:
        pool_done(ev.a, ev.t);
        break;
      case kEvLocalDone:
        local_done(ev.a, ev.t);
        break;
      case kEvBrokerRelease:
        v2_broker_release(ev.a, ev.t);
        break;
    }
  }

  long run() {
    long n_events = 0;
    while (!heap.empty()) {
      Event ev = heap.top();
      heap.pop();
      if (ev.t > p.horizon) break;
      ++n_events;
      dispatch(ev);
    }
    return n_events;
  }
};

}  // namespace

extern "C" {

// Runs any app generation to `horizon` (events past it are not processed,
// like a sim-time-limit) and writes per-task records. Returns processed
// event count.
long desim_run_gen(
    int n_users, int n_fogs, int n_tasks,
    const int* task_user, const double* task_t_create,
    const double* task_mips_req,
    const double* d_ub,       // (n_users) user<->broker one-way delay
    const double* d_bf,       // (n_fogs) broker<->fog one-way delay
    const double* fog_mips,   // (n_fogs)
    const double* register_t, // (n_fogs) Connect arrival at the broker
    const double* adv0_t,     // (n_fogs) first advertisement arrival time
    double horizon, int policy, int fog_model, int app_gen,
    int mips0_divisor, int zero_initial_view, int adv_on_completion,
    int adv_periodic, int v1_max_scan, int local_pool_leak,
    int queue_capacity, double broker_mips, double required_time,
    double adv_interval,
    // energy model (r3; nullptr fog_energy0 disables) + RANDOM stream
    const double* fog_energy0,  // (n_fogs) initial joules or nullptr
    const double* fog_energy_cap,  // (n_fogs)
    double tx_j, double rx_j, double idle_w, double compute_w,
    const double* rand_u,  // (n_tasks) RANDOM unit draws or nullptr
    int v2_local,  // spec.v2_local_broker: v2 hybrid broker semantics
    // wireless/mobility (r4): per-tick delay table + engine loss replay
    const double* d2b_tab,  // (tab_steps, tab_stride) or nullptr (static)
    int tab_steps, int tab_stride, double tab_dt,
    const unsigned char* task_lost,  // (n_tasks) or nullptr
    // user energy + lifecycle mode (r5; nullptr user_energy0 = off).
    // In this mode the publish schedule is NOT replayed: the DES runs
    // the mqttApp2 send chain itself, gated on its OWN tick-quantised
    // battery/lifecycle state, and n_tasks must be n_users * S slots.
    const double* user_energy0, const double* user_energy_cap,
    const double* user_start, const double* user_interval,
    int connect_gating, int max_sends_per_user, double e_dt,
    double harvest_w, double harvest_period, double harvest_duty,
    double shutdown_frac, double start_frac,
    // outputs (n_tasks):
    double* o_t_at_broker, int* o_fog, double* o_t_at_fog,
    double* o_t_service_start, double* o_t_complete, double* o_t_ack3,
    double* o_t_ack4_fwd, double* o_t_ack5, double* o_t_ack4_queued,
    double* o_t_ack6, double* o_queue_time, int* o_stage,
    double* o_fog_energy,  // (n_fogs) final joules (energy model on)
    // user-energy-mode outputs (nullptr unless the mode is on):
    double* o_t_create,        // (n_tasks) DES-derived creation times
    double* o_user_energy,     // (n_users) final joules
    unsigned char* o_user_alive  // (n_users) final lifecycle state
    ) {
  World w;
  w.p = Params{n_users, n_fogs, n_tasks, d_ub, d_bf, horizon, policy,
               fog_model, app_gen, mips0_divisor, zero_initial_view,
               adv_on_completion, adv_periodic, v1_max_scan,
               local_pool_leak, queue_capacity, broker_mips, required_time,
               adv_interval, tx_j, rx_j, idle_w, compute_w, rand_u,
               v2_local, d2b_tab, tab_steps, tab_stride, tab_dt, task_lost,
               user_energy0, user_energy_cap, user_start, user_interval,
               connect_gating, max_sends_per_user, e_dt, harvest_w,
               harvest_period, harvest_duty, shutdown_frac, start_frac};
  w.fogs.resize(n_fogs);
  w.tasks.resize(n_tasks);
  w.view_mips.assign(n_fogs, 0.0);
  w.view_busy.assign(n_fogs, 0.0);
  w.registered.assign(n_fogs, 0);
  w.req_open.assign(n_tasks, 0);
  w.local_pool = broker_mips;

  for (int f = 0; f < n_fogs; ++f) {
    w.fogs[f].mips = fog_mips[f];
    w.fogs[f].pool = fog_mips[f];
    if (fog_energy0 != nullptr) {
      w.fogs[f].has_energy = true;
      w.fogs[f].energy = fog_energy0[f];
      w.fogs[f].cap = fog_energy_cap[f];
    }
    if (!zero_initial_view) w.view_mips[f] = fog_mips[f];
    if (std::isfinite(register_t[f])) w.push(register_t[f], kEvRegister, f);
    if (std::isfinite(adv0_t[f]))
      w.push(adv0_t[f], kEvAdvArrive, f, fog_mips[f], 0.0);
    if (adv_periodic)  // first timer at one interval (ComputeBrokerApp2.cc:219)
      w.push(adv_interval, kEvAdvTimer, f);
  }
  bool user_mode = user_energy0 != nullptr;
  if (user_mode) {
    // self-timed workload: only the per-slot MIPS draws and loss draws
    // are replayed; creation times come from the DES's own alive-gated
    // send chain (compared against the engine's by the parity gate)
    w.users.resize(n_users);
    for (int u = 0; u < n_users; ++u) {
      UserNode& un = w.users[u];
      un.energy = static_cast<float>(user_energy0[u]);
      un.cap = static_cast<float>(user_energy_cap[u]);
      un.start_t = static_cast<float>(user_start[u]);
      un.connected = !connect_gating;
      if (!connect_gating)
        un.next_send = static_cast<float>(user_start[u]);
    }
    for (int i = 0; i < n_tasks; ++i) {
      w.tasks[i].user = i / max_sends_per_user;
      w.tasks[i].t_create = kInf;
      w.tasks[i].mips_req = task_mips_req[i];
    }
  } else {
    for (int i = 0; i < n_tasks; ++i) {
      w.tasks[i].user = task_user[i];
      w.tasks[i].t_create = task_t_create[i];
      w.tasks[i].mips_req = task_mips_req[i];
      if (std::isfinite(task_t_create[i])) {
        if (task_lost != nullptr && task_lost[i]) {
          // wireless uplink loss, replayed from the engine's draw: the
          // publish was sent (tx energy) but never reaches the broker
          w.tasks[i].stage = kLost;
        } else {
          w.tasks[i].stage = kPubInflight;
          w.tasks[i].t_at_broker =
              task_t_create[i] + w.d_user(task_user[i], task_t_create[i]);
          w.push(w.tasks[i].t_at_broker, kEvPubArrive, i);
        }
      }
    }
  }

  long n_events = user_mode ? w.run_user_energy() : w.run();

  if (user_mode) {
    for (int u = 0; u < n_users; ++u) {
      if (o_user_energy != nullptr) o_user_energy[u] = w.users[u].energy;
      if (o_user_alive != nullptr) o_user_alive[u] = w.users[u].alive;
    }
    if (o_t_create != nullptr)
      for (int i = 0; i < n_tasks; ++i)
        o_t_create[i] = w.tasks[i].t_create;
  }

  if (o_fog_energy != nullptr) {
    for (int f = 0; f < n_fogs; ++f) {
      w.touch_energy(f, horizon);  // settle drains to the horizon
      o_fog_energy[f] = w.fogs[f].energy;
    }
  }
  for (int i = 0; i < n_tasks; ++i) {
    const Task& tk = w.tasks[i];
    o_t_at_broker[i] = tk.t_at_broker;
    o_fog[i] = tk.fog;
    o_t_at_fog[i] = tk.t_at_fog;
    o_t_service_start[i] = tk.t_service_start;
    o_t_complete[i] = tk.t_complete;
    o_t_ack3[i] = tk.t_ack3;
    o_t_ack4_fwd[i] = tk.t_ack4_fwd;
    o_t_ack5[i] = tk.t_ack5;
    o_t_ack4_queued[i] = tk.t_ack4_queued;
    o_t_ack6[i] = tk.t_ack6;
    o_queue_time[i] = tk.queue_time;
    o_stage[i] = tk.stage;
  }
  return n_events;
}

}  // extern "C"
