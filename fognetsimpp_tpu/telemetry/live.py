"""Live health plane, host half: serving loop, watchdog, flight recorder.

The ROADMAP's "streaming digital-twin serving mode" needs a production
loop around ``run_chunked``: this module is that loop.  Per chunk it

* fetches the reservoir rows the chunk completed
  (:func:`telemetry.metrics.reservoir_progress`) and feeds them to an
  **EWMA z-score watchdog** (queue depth, busy fraction, drop rate,
  deferred backlog — the FogMQ always-on-broker health signals);
* re-renders the full OpenMetrics exposition — including the
  ``# TYPE ... histogram`` latency series and per-fog quantile gauges
  when ``spec.telemetry_hist`` is on — behind a stdlib ``http.server``
  **pull endpoint** (``GET /metrics``; ``GET /healthz`` returns the
  watchdog/SLO state as JSON);
* appends the rows + a per-chunk **state hash** to a bounded
  :class:`FlightRecorder` ring, and on NaN, SLO breach, watchdog
  anomaly or crash dumps a post-mortem bundle (manifest JSON + the
  Perfetto trace of the last window) that ``tools/postmortem.py``
  inspects and diffs.

Everything here is host-side Python over the device-resident
accumulators: the jitted tick loop is untouched (the chunk callback
path of ``run_chunked`` already exists), so the health plane adds zero
compiled ops and cannot perturb the simulation — the same read-only
discipline the PR-4 telemetry gates enforce.
"""
from __future__ import annotations

import collections
import http.server
import json
import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..spec import WorldSpec

#: Signals the watchdog tracks, derived per chunk from reservoir rows.
#: ``defer_rate`` (ISSUE 11) is the per-row DELTA of the cumulative
#: ``defer_total`` reservoir column — the per-tick ``n_deferred`` gauge
#: (the ``defer`` signal) sits constant under sustained exchange-window
#: overflow because the tick-keyed rotation spreads deferral evenly, so
#: only the rate signal can page before a shard starves.
#: ``fog_down`` / ``crash_loss_rate`` (ISSUE 12) ride the chaos
#: reservoir columns: ``fog_down`` is the mean fraction of fogs down
#: over the chunk (a flapping fog oscillates it — the z-score fires),
#: and ``crash_loss_rate`` is the per-tick delta of the cumulative
#: crash-loss column with an ABSOLUTE floor next to its z-score,
#: exactly the ``defer_rate`` discipline: steady crash losses from
#: tick 0 have zero variance and must still page.
#: ``ingest_depth`` (ISSUE 17) is the twin ingestion queue's occupancy
#: FRACTION at the chunk boundary (depth / capacity, host-side — it
#: rides serve_run's ``extra`` signal door, not the reservoir): a
#: backing-up arrival queue is the twin's earliest overload page,
#: firing before a single request is dropped.
WATCH_SIGNALS = ("q_depth", "busy_frac", "drop_rate", "defer",
                 "defer_rate", "fog_down", "crash_loss_rate",
                 "ingest_depth")


class Ewma:
    """One exponentially-weighted mean/variance tracker.

    ``update`` returns the z-score of the NEW sample against the
    statistics accumulated *before* it (so a step change scores against
    the pre-step regime), then folds the sample in.  The first
    ``warmup`` samples return 0.0 — an empty-history z-score is noise.

    The score's denominator is floored at ``rel_floor * |mean| +
    abs_floor``: a signal that sat EXACTLY constant through warmup
    (zero drops on a healthy run, busy_frac pinned at 1.0 on a
    saturated fleet) has zero variance, and without the floor its first
    infinitesimal wiggle would score z ~ 1e5 and page — only a change
    that is material relative to the signal's own level should fire.
    """

    def __init__(
        self,
        alpha: float = 0.3,
        warmup: int = 3,
        rel_floor: float = 0.05,
        abs_floor: float = 0.01,
    ):
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.rel_floor = float(rel_floor)
        self.abs_floor = float(abs_floor)
        self.n = 0
        self.mean = 0.0
        self.var = 0.0

    def update(self, x: float) -> float:
        x = float(x)
        if self.n < self.warmup:
            z = 0.0
        else:
            floor = self.rel_floor * abs(self.mean) + self.abs_floor
            z = (x - self.mean) / math.sqrt(self.var + floor * floor)
        if self.n == 0:
            self.mean = x
        else:
            a = self.alpha
            d = x - self.mean
            self.mean += a * d
            self.var = (1.0 - a) * (self.var + a * d * d)
        self.n += 1
        return z


class Watchdog:
    """EWMA z-score anomaly detection over the per-chunk health signals.

    Feed it the reservoir rows each chunk delivered
    (:meth:`update_from_rows`); it derives per-chunk means of queue
    depth / busy fraction / deferred backlog, the per-row drop RATE
    from consecutive cumulative ``n_dropped`` samples, and flags any
    signal whose z-score exceeds ``z_threshold``.  Anomalies are
    returned AND kept in ``self.anomalies`` (the /healthz payload and
    the flight-recorder manifest read it).
    """

    def __init__(
        self,
        n_fogs: int,
        z_threshold: float = 4.0,
        alpha: float = 0.3,
        warmup: int = 3,
        defer_rate_floor: float = 1.0,
        crash_loss_floor: float = 1.0,
        row_ticks: float = 1.0,
        anomaly_capacity: int = 256,
    ):
        self.n_fogs = max(int(n_fogs), 1)
        self.z_threshold = float(z_threshold)
        # the defer-rate signal gets an ABSOLUTE trip on top of the
        # z-score (ISSUE 11): sustained exchange-window overflow from
        # tick 0 is a CONSTANT rate — zero variance, z ~ 0 forever —
        # yet it is exactly the condition that starves a shard.  Any
        # chunk whose mean deferred-PER-TICK rate exceeds the floor
        # pages, warmup or not.  ``row_ticks`` (the reservoir stride:
        # ticks covered per row) normalizes the per-row cumulative
        # delta into that per-tick unit, so the floor means the same
        # thing at any horizon — serve_run passes the spec's stride.
        # The EWMA floors (Ewma rel/abs) still apply to its z-score
        # like every other signal.
        self.defer_rate_floor = float(defer_rate_floor)
        # crash-loss twin of the defer-rate floor (ISSUE 12): a fog
        # that flaps and eats tasks at a CONSTANT per-tick rate never
        # moves the z-score — any chunk whose mean crash-losses-per-
        # tick exceeds this floor pages regardless of variance.
        self.crash_loss_floor = float(crash_loss_floor)
        self.row_ticks = max(float(row_ticks), 1.0)
        self._trackers = {
            s: Ewma(alpha=alpha, warmup=warmup) for s in WATCH_SIGNALS
        }
        self._last_dropped: Optional[float] = None
        self._last_deferred: Optional[float] = None
        self._last_crash_lost: Optional[float] = None
        # bounded ring (the FlightRecorder discipline): the defer-rate
        # FLOOR fires on EVERY chunk of a sustained-overflow run by
        # design — unbounded growth would leak host memory and bloat
        # late post-mortem manifests.  anomaly_count keeps the true
        # total for /healthz.
        self.anomalies: collections.deque = collections.deque(
            maxlen=int(anomaly_capacity)
        )
        self.anomaly_count = 0
        self.last_signals: Dict[str, float] = {}
        self.last_z: Dict[str, float] = {}

    def signals_from_rows(self, rows: Dict[str, np.ndarray]) -> Dict:
        """Chunk-level signal values from this chunk's reservoir rows
        (empty dict when the chunk completed no reservoir row)."""
        t = np.asarray(rows.get("t", ()))
        if t.size == 0:
            return {}
        sig = {
            "q_depth": float(np.mean(rows["q_len_total"])),
            "busy_frac": float(np.mean(rows["n_busy"])) / self.n_fogs,
            "defer": float(np.mean(rows["n_deferred"])),
        }
        dropped = np.asarray(rows["n_dropped"], dtype=float)
        prev = (
            self._last_dropped if self._last_dropped is not None
            else float(dropped[0])
        )
        sig["drop_rate"] = float(dropped[-1] - prev) / max(dropped.size, 1)
        self._last_dropped = float(dropped[-1])
        # cumulative-deferred delta (the defer RATE, per TICK: the
        # chunk's delta over the ticks its rows cover, so the absolute
        # floor is stride-independent) — rows recorded by a
        # pre-ISSUE-11 build have no defer_total column; skip then
        if "defer_total" in rows:
            deferred = np.asarray(rows["defer_total"], dtype=float)
            prev_d = (
                self._last_deferred if self._last_deferred is not None
                else float(deferred[0])
            )
            sig["defer_rate"] = float(deferred[-1] - prev_d) / max(
                deferred.size * self.row_ticks, 1.0
            )
            self._last_deferred = float(deferred[-1])
        # chaos columns (ISSUE 12) — rows recorded by a pre-chaos build
        # have neither; skip then (the postmortem .get-safety contract)
        if "n_fogs_down" in rows:
            sig["fog_down"] = float(
                np.mean(rows["n_fogs_down"])
            ) / self.n_fogs
        if "lost_crash_total" in rows:
            lost = np.asarray(rows["lost_crash_total"], dtype=float)
            prev_l = (
                self._last_crash_lost
                if self._last_crash_lost is not None
                else float(lost[0])
            )
            sig["crash_loss_rate"] = float(lost[-1] - prev_l) / max(
                lost.size * self.row_ticks, 1.0
            )
            self._last_crash_lost = float(lost[-1])
        return sig

    def update(self, signals: Dict[str, float], ticks_done: int) -> List[Dict]:
        """Score one chunk's signals; returns (and records) anomalies."""
        fired = []
        for name, value in signals.items():
            tracker = self._trackers.get(name)
            if tracker is None:
                continue
            z = tracker.update(value)
            self.last_z[name] = z
            tripped = abs(z) > self.z_threshold
            kind = "z"
            if (
                name == "defer_rate"
                and value > self.defer_rate_floor
            ):
                # absolute floor trip: a sustained overflow is constant
                # (z ~ 0) but still pages — see __init__
                tripped, kind = True, "floor"
            if (
                name == "crash_loss_rate"
                and value > self.crash_loss_floor
            ):
                # chaos twin (ISSUE 12): steady crash losses are z ~ 0
                tripped, kind = True, "floor"
            if tripped:
                fired.append(
                    {
                        "signal": name,
                        "value": value,
                        "z": z,
                        "kind": kind,
                        "mean": tracker.mean,
                        "ticks_done": int(ticks_done),
                    }
                )
        self.last_signals = dict(signals)
        self.anomalies.extend(fired)
        self.anomaly_count += len(fired)
        return fired

    def update_from_rows(
        self, rows: Dict[str, np.ndarray], ticks_done: int,
        extra: Optional[Dict[str, float]] = None,
    ) -> List[Dict]:
        """``extra`` merges host-side signals (the twin's
        ``ingest_depth``) into the chunk's row-derived ones — they are
        scored even when the chunk completed no reservoir row."""
        sig = self.signals_from_rows(rows)
        if extra:
            sig.update(extra)
        if not sig:
            return []
        return self.update(sig, ticks_done)


class FlightRecorder:
    """Bounded ring of recent reservoir rows + per-chunk state hashes.

    ``capacity`` bounds host memory no matter the horizon; on
    :meth:`dump` the ring, the watchdog state, the compile-cache stats
    and (when a final state is at hand) the Perfetto trace of the last
    window land in ``outdir`` as a post-mortem bundle —
    ``postmortem-<reason>-<ticks>.json`` plus a ``.trace.json`` twin —
    that :mod:`tools.postmortem` inspects and diffs.
    """

    def __init__(self, capacity: int = 64):
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(
            maxlen=self.capacity
        )
        self.dumps: List[str] = []

    def note_chunk(
        self,
        ticks_done: int,
        rows: Optional[Dict[str, np.ndarray]] = None,
        state_hash: Optional[str] = None,
        extra: Optional[Dict] = None,
    ) -> None:
        entry = {
            "ticks_done": int(ticks_done),
            "state_hash": state_hash,
            "rows": {
                k: [float(x) for x in np.asarray(v)]
                for k, v in (rows or {}).items()
            },
        }
        if extra:
            entry.update(extra)
        self._ring.append(entry)

    @property
    def ring(self) -> List[Dict]:
        return list(self._ring)

    def dump(
        self,
        outdir: str,
        reason: str,
        spec: Optional[WorldSpec] = None,
        final=None,
        watchdog: Optional[Watchdog] = None,
        detail: Optional[Dict] = None,
        max_tasks: int = 5000,
    ) -> str:
        """Write the post-mortem bundle; returns the manifest path."""
        from ..compile_cache import compile_stats
        from ..runtime.recorder import _json_sanitize, spec_to_dict

        os.makedirs(outdir, exist_ok=True)
        ticks = self._ring[-1]["ticks_done"] if self._ring else 0
        stem = f"postmortem-{reason}-{ticks:09d}"
        manifest_path = os.path.join(outdir, f"{stem}.json")
        manifest = {
            "reason": reason,
            "recorded_at": time.strftime("%Y-%m-%d %H:%M:%S"),
            "ticks_done": ticks,
            "detail": detail or {},
            "ring": self.ring,
            "compile_cache": compile_stats(),
        }
        # the twin's ingest roll-up (ISSUE 17): the newest chunk entry
        # carrying queue stats becomes the bundle's ingest_summary —
        # pre-twin bundles simply lack the key (the .get-safe contract)
        ing = next(
            (
                e["ingest"] for e in reversed(self._ring)
                if isinstance(e, dict) and e.get("ingest")
            ),
            None,
        )
        if ing is not None:
            manifest["ingest_summary"] = dict(ing)
        if watchdog is not None:
            manifest["watchdog"] = {
                "anomalies": list(watchdog.anomalies),
                "anomaly_count": watchdog.anomaly_count,
                "last_signals": watchdog.last_signals,
                "last_z": watchdog.last_z,
                "z_threshold": watchdog.z_threshold,
            }
        if spec is not None:
            manifest["spec"] = spec_to_dict(spec)
        if spec is not None and final is not None and spec.chaos:
            from ..chaos.faults import chaos_summary

            manifest["chaos"] = chaos_summary(spec, final)
        if (
            spec is not None
            and final is not None
            and getattr(spec, "journey_active", False)
        ):
            # journey rings ride the bundle RAW (ISSUE 15): the decode
            # needs no spec, so tools/postmortem.py can print "what was
            # task 4711 doing when the watchdog paged" from the
            # manifest alone; pre-journey bundles simply lack the key
            # (the .get-safe contract)
            from .journeys import snapshot_rings

            rings = snapshot_rings(final, spec)
            if rings is not None:
                manifest["journeys"] = {
                    "sampled": len(rings["task"]),
                    "dropped_total": rings["dropped"],
                    "rings": rings,
                }
        if spec is not None and final is not None:
            from .health import hist_summary

            hist = hist_summary(spec, final)
            if hist is not None:
                manifest["hist"] = {
                    "count": hist["count"],
                    "quantiles_ms": hist["quantiles_ms"],
                }
            # the Perfetto trace of the last window: the task spans
            # + counter tracks a post-mortem zooms into first
            from .timeline import export_trace

            trace_path = os.path.join(outdir, f"{stem}.trace.json")
            manifest["trace"] = export_trace(
                spec, final, trace_path, max_tasks=max_tasks
            )
        with open(manifest_path, "w") as f:
            json.dump(
                _json_sanitize(manifest), f, indent=1, allow_nan=False
            )
        self.dumps.append(manifest_path)
        return manifest_path

    @staticmethod
    def load(path: str) -> Dict:
        with open(path) as f:
            return json.load(f)


class HealthServer:
    """Stdlib pull endpoint: ``GET /metrics`` (OpenMetrics text) and
    ``GET /healthz`` (watchdog/SLO JSON).

    A daemon-threaded ``http.server`` — no dependency beyond the
    stdlib, matching the container constraint.  ``port=0`` binds an
    ephemeral port (read it back from ``.port``); content is swapped
    atomically under a lock by the serving loop.

    ``set_handler`` installs an optional route hook (ISSUE 17, the
    twin's extension door): called FIRST for every request as
    ``hook(method, path, body)`` and may return ``(status, ctype,
    body)`` to serve the request — ``POST /ingest``, ``GET /whatif``
    and the front door's per-tenant ``/t/<label>/...`` routes live in
    :mod:`fognetsimpp_tpu.twin` behind this hook, so the base server
    stays twin-agnostic.  Returning ``None`` falls through to the
    built-in GET routes (404 for anything else).
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        self._lock = threading.Lock()
        self._metrics = "# EOF\n"
        self._health: Dict = {"status": "starting"}
        self._hook: Optional[Callable] = None
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _respond(self, status, ctype, body):
                if isinstance(body, str):
                    body = body.encode()
                self.send_response(int(status))
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _hooked(self, method: str, body: bytes) -> bool:
                with outer._lock:
                    hook = outer._hook
                if hook is None:
                    return False
                out = hook(method, self.path, body)
                if out is None:
                    return False
                self._respond(*out)
                return True

            def do_GET(self):  # noqa: N802 (stdlib API name)
                if self._hooked("GET", b""):
                    return
                if self.path.startswith("/metrics"):
                    with outer._lock:
                        body = outer._metrics.encode()
                    ctype = "application/openmetrics-text; version=1.0.0"
                elif self.path.startswith("/healthz"):
                    with outer._lock:
                        payload = dict(outer._health)
                    body = (json.dumps(payload) + "\n").encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self._respond(200, ctype, body)

            def do_POST(self):  # noqa: N802 (stdlib API name)
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b""
                if self._hooked("POST", body):
                    return
                self.send_error(404)

            def log_message(self, *a):  # silence per-request stderr
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            (host, port), Handler
        )
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()

    def set_metrics(self, text: str) -> None:
        with self._lock:
            self._metrics = text

    def set_health(self, payload: Dict) -> None:
        with self._lock:
            self._health = payload

    def set_handler(self, hook: Optional[Callable]) -> None:
        """Install (or clear) the route hook — see the class docstring."""
        with self._lock:
            self._hook = hook

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class ReconfigDoor:
    """The live session's retune endpoint: queued promoted-knob changes.

    ``POST /reconfigure`` with ``{"knobs": {"<field>": value, ...}}``
    or the CLI's own ``--set`` shape, ``{"set":
    ["spec.<field>=<value>", ...]}``.  Knobs are validated EAGERLY
    against the door's live-spec shadow
    (:func:`fognetsimpp_tpu.dynspec.apply_knobs`: unknown fields,
    shape-defining fields and trace-gate flips answer 400 with the
    one-line error — the serving loop never sees them) and queued; the
    chunk runner pops the queue at the next chunk boundary via
    :meth:`as_reconfigure`.  An accepted retune therefore costs ZERO
    compile events on the promoted runners, and every accepted field
    answers ``"recompile": "no"`` — the CLI ``--set`` classification,
    served over HTTP.

    One door serves both substrates unchanged (ISSUE 20):
    ``serve_run`` → ``run_chunked`` and ``serve_tp_run`` →
    ``run_tp_chunked`` take the same ``reconfigure`` hook.  The POST
    thread only ever touches the spec shadow and the queue under the
    door lock; the chunk loop applies knobs between chunks, so a
    mid-chunk POST races nothing and lands one boundary later.
    """

    def __init__(self, spec: WorldSpec):
        self._lock = threading.Lock()
        self._spec = spec
        self._pending: Dict = {}
        self.accepted = 0
        self.rejected = 0
        self.applied_batches = 0

    # ---- HTTP (the HealthServer route hook) --------------------------
    def handle_http(self, method: str, path: str, body: bytes):
        """``POST /reconfigure`` handler; None for any other route."""
        if not path.split("?", 1)[0].rstrip("/").endswith("/reconfigure"):
            return None
        if method != "POST":
            with self._lock:
                pending = sorted(self._pending)
            return (
                200, "application/json",
                json.dumps({
                    "usage": 'POST {"knobs": {"<promoted field>": '
                             'value, ...}} or {"set": '
                             '["spec.<field>=<value>", ...]}',
                    "pending": pending,
                }) + "\n",
            )
        status, payload = self._post(body)
        return (status, "application/json", json.dumps(payload) + "\n")

    def _post(self, body: bytes):
        from ..dynspec import apply_knobs, classify_field

        try:
            doc = json.loads(body.decode() or "null")
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            return 400, {"error": f"invalid JSON ({e})"}
        if not isinstance(doc, dict):
            return 400, {
                "error": "POST a JSON object with 'knobs' and/or 'set'"
            }
        knobs = dict(doc.get("knobs") or {})
        for item in doc.get("set") or []:
            if not isinstance(item, str) or "=" not in item:
                return 400, {
                    "error": "'set' entries are 'spec.<field>=<value>' "
                             f"strings, got {item!r}"
                }
            key, val = item.split("=", 1)
            key = key.strip()
            if key.startswith("spec."):
                key = key[5:]
            try:
                knobs[key] = json.loads(val.strip())
            except json.JSONDecodeError:
                return 400, {
                    "error": f"could not parse the value for {key!r}: "
                             f"{val.strip()!r}"
                }
        if not knobs:
            return 400, {
                "error": "no knobs given: pass 'knobs' (field->value "
                         "object) and/or 'set' (a list of "
                         "'spec.<field>=<value>' strings)"
            }
        bad = [
            k for k, v in knobs.items()
            if isinstance(v, bool) or not isinstance(v, (int, float))
        ]
        if bad:
            return 400, {"error": f"knob {bad[0]!r} needs a number"}
        with self._lock:
            try:
                # the shadow accumulates accepted retunes, so gate
                # checks always run against the values the loop will
                # actually be carrying at the next boundary
                self._spec = apply_knobs(self._spec, knobs)
            except ValueError as e:
                self.rejected += 1
                return 400, {"error": str(e)}
            self._pending.update(knobs)
            pending = sorted(self._pending)
        self.accepted += 1
        return 200, {
            "accepted": {k: knobs[k] for k in sorted(knobs)},
            "recompile": "no",
            "why": {k: classify_field(k)[1] for k in sorted(knobs)},
            "pending": pending,
        }

    def as_reconfigure(self) -> Callable[[int], Optional[Dict]]:
        """The chunk-boundary hook: pops the queued knobs (applied once)."""

        def reconfigure(ticks_done: int) -> Optional[Dict]:
            with self._lock:
                if not self._pending:
                    return None
                knobs, self._pending = self._pending, {}
            self.applied_batches += 1
            return knobs

        return reconfigure


def serve_run(
    spec: WorldSpec,
    state,
    net,
    bounds=None,
    chunk_ticks: int = 1000,
    port: Optional[int] = 0,
    slo_ms: Optional[float] = None,
    z_threshold: float = 4.0,
    dump_dir: Optional[str] = None,
    recorder: Optional[FlightRecorder] = None,
    watchdog: Optional[Watchdog] = None,
    server: Optional[HealthServer] = None,
    on_chunk: Optional[Callable[[Dict], None]] = None,
    hash_every_chunk: bool = True,
    run_fn: Optional[Callable] = None,
    shard_hash_fn: Optional[Callable] = None,
    reconfigure: Optional[Callable[[int], Optional[Dict]]] = None,
    inject: Optional[Callable] = None,
    ingest=None,
):
    """The production serving loop over ``run_chunked``.

    ``run_fn`` swaps the chunked runner: it must accept
    ``(spec, state, net, bounds, chunk_ticks=..., callback=...)`` and
    return the final state — :func:`serve_tp_run` passes the TP
    sharded chunk runner here, so the watchdog/exposition loop is ONE
    code path whatever the execution substrate.  ``shard_hash_fn``
    (TP): called with each chunk's host-fetched state, returns the
    per-shard hash list the flight recorder stores next to the global
    state hash (``tools/postmortem.py --diff`` bisects WHICH shard
    diverged first); needs ``hash_every_chunk``.

    Returns ``(final_state, status)`` where ``status`` carries the
    server (still live, so late scrapes see the final exposition —
    callers own ``status['server'].close()``), the watchdog, the flight
    recorder and the run roll-up.  ``port=None`` disables the endpoint
    (watchdog + recorder only).  ``slo_ms`` arms the SLO-breach trigger
    (needs ``spec.telemetry_hist``); breaches, watchdog anomalies, NaNs
    and crashes each dump at most one post-mortem bundle per reason
    into ``dump_dir``.

    ``hash_every_chunk=False`` skips the per-chunk full-state fetch —
    both the state hash AND the NaN scan ride one ``device_get`` — for
    latency-sensitive serving; the flight recorder ring then carries
    rows only and NaN dumps are disabled (the histogram/SLO/watchdog
    triggers still fire).

    ``reconfigure`` (ISSUE 13, the live what-if door): forwarded to
    the chunk runner — called at every chunk boundary with the tick
    count, may return a dict of PROMOTED WorldSpec knobs (chaos
    amplitudes, loss probabilities, energy budgets...) to apply to the
    remaining horizon with zero recompiles, so an operator can steer a
    live twin between scrapes without ever paying the compile wall.
    The default ``run_chunked`` runner and any ``run_fn`` with an
    explicit ``reconfigure`` parameter support it (``serve_tp_run``'s
    TP chunk runner does, since the ISSUE 20 operand promotion); a
    ``run_fn`` without the parameter still raises up front.

    ``inject`` / ``ingest`` (ISSUE 17, the digital-twin input door):
    ``inject`` is forwarded to ``run_chunked``'s chunk-boundary hook
    (external arrivals land between chunks); ``ingest`` is the
    IngestQueue-like stats provider — anything with a ``stats()``
    returning the twin/ingest dict — whose depth/accepted/dropped/
    latency counters ride the exposition (``fns_twin_ingest_*``), the
    /healthz payload and the watchdog's ``ingest_depth`` signal.
    :func:`fognetsimpp_tpu.twin.ingest.serve_ingest_run` wires both
    plus the HTTP POST endpoint.  Like ``reconfigure``, both need the
    default ``run_chunked`` runner.
    """
    if reconfigure is not None and run_fn is not None:
        # a runner opts in by NAMING the parameter (VAR_KEYWORD does not
        # count: swallowing the hook silently would serve stale knobs)
        import inspect

        try:
            _params = inspect.signature(run_fn).parameters
        except (TypeError, ValueError):
            _params = {}
        if "reconfigure" not in _params:
            raise ValueError(
                "reconfigure rides the chunk runner's DynSpec operand; "
                "this run_fn runner does not take it (declare an "
                "explicit reconfigure= parameter, like the TP chunk "
                "loop's)"
            )
    if inject is not None and run_fn is not None:
        raise ValueError(
            "inject rides run_chunked's chunk-boundary hook; custom "
            "run_fn runners (the TP chunk loop) do not take it"
        )
    import jax

    from ..core.engine import run_chunked
    from ..runtime.signals import summarize
    from .health import find_nonfinite, hist_summary, slo_breach_count
    from .health import state_hash as health_state_hash
    from .metrics import reservoir_progress
    from .openmetrics import render_openmetrics

    if not spec.telemetry:
        raise ValueError(
            "serve_run needs spec.telemetry=True (the health plane "
            "reads the device-resident reservoir)"
        )
    if slo_ms is not None and not spec.telemetry_hist:
        raise ValueError(
            "slo_ms needs spec.telemetry_hist=True (SLO breaches are "
            "derived from the streaming latency histogram)"
        )
    if watchdog is None:
        # the reservoir stride (ticks per row) normalizes the
        # defer-rate signal to per-tick units, whatever the horizon
        stride = max(1, -(-spec.n_ticks // max(spec.telemetry_slots, 1)))
        watchdog = Watchdog(
            spec.n_fogs, z_threshold=z_threshold, row_ticks=stride
        )
    recorder = recorder or FlightRecorder()
    if server is None and port is not None:
        server = HealthServer(port=port)
    dumped_reasons: set = set()
    progress = {"next_row": 0, "chunks": 0, "t0": time.perf_counter()}
    slo_state = {"breaches": 0}

    def _dump(reason: str, s, detail: Optional[Dict] = None) -> None:
        if dump_dir is None or reason in dumped_reasons:
            return
        dumped_reasons.add(reason)
        recorder.dump(
            dump_dir, reason, spec=spec, final=s,
            watchdog=watchdog, detail=detail,
        )

    def _chunk_cb(s, ticks_done: int) -> None:
        rows, progress["next_row"] = reservoir_progress(
            spec, s.telem, ticks_done, progress["next_row"]
        )
        progress["chunks"] += 1
        # one device->host fetch serves both the fingerprint and the
        # NaN scan; hash_every_chunk=False skips the whole full-state
        # transfer for latency-sensitive serving (rows + histogram only)
        if hash_every_chunk:
            host = jax.device_get(s)
            h = health_state_hash(host)
            bad = find_nonfinite(host)
            shard_hashes = (
                shard_hash_fn(host) if shard_hash_fn is not None else None
            )
        else:
            h, bad, shard_hashes = None, {}, None
        extra = {}
        if shard_hashes:
            extra["shard_hashes"] = shard_hashes
        if spec.chaos:
            # chaos counters ride every chunk entry (five scalars):
            # a post-mortem of a churn run sees WHEN the losses grew
            from ..chaos.faults import chaos_counters

            extra["chaos"] = chaos_counters(s)
        if spec.hier_active:
            # federation counters ride every chunk entry (two scalars):
            # a post-mortem sees WHEN migration spiked or hops exhausted
            from ..hier.federation import hier_counters

            extra["hier"] = hier_counters(s)
        ingest_stats = ingest.stats() if ingest is not None else None
        if ingest_stats is not None:
            # the ingest roll-up rides every chunk entry: a post-mortem
            # of a live session sees WHEN the queue backed up
            extra["ingest"] = dict(ingest_stats)
        recorder.note_chunk(
            ticks_done, rows=rows, state_hash=h, extra=extra or None,
        )
        ingest_sig = None
        if ingest_stats is not None:
            ingest_sig = {
                "ingest_depth": ingest_stats["depth"]
                / max(float(ingest_stats.get("capacity", 1)), 1.0)
            }
        fired = watchdog.update_from_rows(
            rows, ticks_done, extra=ingest_sig
        )
        if fired:
            _dump("anomaly", s, detail={"anomalies": fired})
        if bad:
            _dump("nan", s, detail={"nonfinite": bad})
        # ONE hist_summary per chunk feeds the SLO check, /healthz and
        # the exposition alike (the single-quantile-source discipline)
        hist = hist_summary(spec, s)
        breaches = None
        if slo_ms is not None:
            breaches = slo_breach_count(spec, s, slo_ms, summ=hist)
            if breaches and breaches > slo_state["breaches"]:
                _dump(
                    "slo",
                    s,
                    detail={"slo_ms": slo_ms, "breaches": breaches},
                )
            slo_state["breaches"] = breaches or 0
        health = {
            "status": (
                "degraded" if (fired or bad or (breaches or 0) > 0)
                else "ok"
            ),
            "ticks_done": int(ticks_done),
            "chunks": progress["chunks"],
            "wall_s": round(time.perf_counter() - progress["t0"], 3),
            "signals": watchdog.last_signals,
            "z": watchdog.last_z,
            "anomalies": watchdog.anomaly_count,
            "nonfinite": sorted(bad),
            **(
                {"slo_ms": slo_ms, "slo_breaches": breaches}
                if slo_ms is not None
                else {}
            ),
            **(
                {"ingest": ingest_stats}
                if ingest_stats is not None
                else {}
            ),
        }
        if server is not None:
            if hist is not None:
                # an empty histogram yields NaN quantiles; /healthz is
                # strict JSON, so those become null
                health["latency_ms"] = {
                    k: (v if math.isfinite(v) else None)
                    for k, v in hist["quantiles_ms"].items()
                }
            server.set_metrics(
                render_openmetrics(
                    spec, s,
                    hist=hist,
                    ingest=ingest_stats,
                    attrs={
                        "live_chunks": progress["chunks"],
                        "live_ticks": int(ticks_done),
                        **(
                            {"slo_breaches": breaches}
                            if breaches is not None
                            else {}
                        ),
                    },
                )
            )
            server.set_health(health)
        if on_chunk is not None:
            on_chunk(health)

    try:
        final = (run_fn or run_chunked)(
            spec, state, net, bounds,
            chunk_ticks=chunk_ticks, callback=_chunk_cb,
            **({} if reconfigure is None else {"reconfigure": reconfigure}),
            **({} if inject is None else {"inject": inject}),
        )
    except Exception as e:
        # crash flight-record: the ring up to the last good chunk plus
        # the failure, then re-raise — a serving loop must not swallow
        if dump_dir is not None:
            recorder.dump(
                dump_dir, "crash", spec=spec, watchdog=watchdog,
                detail={"error": f"{type(e).__name__}: {e}"},
            )
        if server is not None:
            server.set_health(
                {"status": "crashed", "error": f"{type(e).__name__}: {e}"}
            )
        raise
    status = {
        "server": server,
        "port": server.port if server is not None else None,
        "watchdog": watchdog,
        "recorder": recorder,
        "chunks": progress["chunks"],
        "anomalies": watchdog.anomaly_count,
        "slo_breaches": slo_state["breaches"],
        "dumps": list(recorder.dumps),
        "scalars": summarize(final),
    }
    return final, status


def serve_tp_run(
    spec: WorldSpec,
    state,
    net,
    bounds=None,
    mesh=None,
    exchange_window: Optional[int] = None,
    **kw,
):
    """The sharded health plane (ISSUE 11): :func:`serve_run` over the
    TP task-table-sharded tick.

    ONE serving loop, two substrates: the chunk runner becomes
    ``parallel/taskshard.run_tp_chunked`` (each chunk one cached
    shard_map program, carry row-sharded between chunks), the flight
    recorder additionally stores PER-SHARD state hashes
    (:func:`telemetry.health.shard_state_hashes`) so
    ``tools/postmortem.py --diff`` can bisect which shard diverged
    first, and the exposition gains the ``fns_tp_exchange_*{shard}``
    families because the stamped spec carries the shard axis.  The
    spec is padded/stamped UP FRONT (before the loop) so every render
    sees the world it is actually serving; returns
    ``(spec, final_state, status)``.

    Accepts every :func:`serve_run` keyword (``chunk_ticks``, ``port``,
    ``slo_ms``, ``dump_dir``, ``on_chunk``, ...).  The watchdog's
    defer-rate signal matters most here: the TP exchange window DEFERS
    overflow instead of dropping, so the drop-rate signal is blind to
    a starving shard — the defer-rate floor is the trip that pages.
    """
    import functools

    from ..parallel.taskshard import (
        pad_users_to_multiple,
        run_tp_chunked,
        stamp_tp_telemetry,
    )
    from .health import shard_state_hashes

    if mesh is None:
        raise ValueError("serve_tp_run needs a Mesh (parallel.make_mesh)")
    if not spec.telemetry:
        raise ValueError(
            "serve_tp_run needs spec.telemetry=True (the health plane "
            "reads the device-resident reservoir)"
        )
    # pad + stamp ONCE, before the loop, so the first render already
    # sees the world actually being served (the chunk runner's own
    # setup re-derives the identical spec/state — idempotent)
    n_shards = int(
        mesh.shape["node"] if "node" in mesh.shape else mesh.devices.size
    )
    if spec.n_users % n_shards:
        spec, state, net = pad_users_to_multiple(spec, state, net, n_shards)
    spec, state = stamp_tp_telemetry(spec, state, n_shards)

    # the chunk loop applies reconfigure knobs to the live spec between
    # chunks (ISSUE 20, zero compile events — the promoted TP program
    # re-runs with new operand values); capture the retuned spec so the
    # caller's returned spec describes the state it actually served
    live = {"spec": spec}

    def _runner(sp, st, nt, bd, chunk_ticks, callback, reconfigure=None):
        live["spec"], final = run_tp_chunked(
            sp, st, nt, bd, mesh, chunk_ticks=chunk_ticks,
            callback=callback, exchange_window=exchange_window,
            reconfigure=reconfigure,
        )
        return final

    final, status = serve_run(
        spec, state, net, bounds,
        run_fn=_runner,
        shard_hash_fn=functools.partial(
            shard_state_hashes, spec, n_shards=n_shards
        ),
        **kw,
    )
    status["tp_shards"] = n_shards
    return live["spec"], final, status
