"""Live health plane, device half: streaming latency histograms.

FogMQ-style always-on broker fleets (arXiv:1610.00620) live or die by
continuous health monitoring, and iFogSim (arXiv:1606.02007) reports
latency *distributions*, not just means — but until this module the
repo's latency story was post-run sample vectors only
(``runtime/signals.py``).  Here the ``task_time`` signal (publish →
status-6 "performed" ack) streams into a **device-resident, per-fog,
log-spaced-bucket histogram** carried in
:class:`~fognetsimpp_tpu.telemetry.metrics.TelemetryState`: fixed
shapes, zero rows when ``spec.telemetry_hist`` is off (the PR-4
bit-exactness gate discipline), accumulated once per tick by
``core/engine._phase_latency_hist``.

Exactly-once: a completion backlog can ack a task whose ``t_ack6``
already lies *behind* the current tick window (the same late-credit
hazard the PR-2 learn-credit phase handles), so the trigger is a
persistent per-task ``lat_seen`` flag, not a time-interval test — no
sample is ever lost or double-counted, on any engine path
(run/run_jit/run_chunked/fleet).

Host half: :func:`hist_summary` is the SINGLE source of the derived
p50/p95/p99 quantiles — the recorder's ``.sca.json`` fog rows and the
OpenMetrics exposition both call it, so the two outputs agree exactly
(the ISSUE 6 acceptance gate asserts 1e-6), the
``telemetry.metrics.busy_fractions`` discipline.  SLO-breach counters
derive from the same cumulative bucket counts
(:func:`slo_breach_count`); the bucket edge containing the threshold is
the snap point, documented there.
"""
from __future__ import annotations

import hashlib
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..spec import Stage, WorldSpec

#: Quantiles the health plane derives and exposes, everywhere (recorder
#: fog rows, OpenMetrics gauges, the live /healthz endpoint).
QUANTILES = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))

_ST_DONE = np.int8(int(Stage.DONE))


def hist_edges_s(spec: WorldSpec) -> np.ndarray:
    """The histogram's finite bucket upper bounds, in SECONDS.

    ``B - 1`` log-spaced edges between ``telemetry_hist_min_ms`` and
    ``telemetry_hist_max_ms``; bucket ``b`` counts latencies in
    ``(edge[b-1], edge[b]]`` and bucket ``B-1`` is the +Inf overflow.
    A pure function of the static spec (float64 on host, cast to f32
    once at trace time), so device and host readers can never disagree
    about the binning.
    """
    B = spec.telemetry_hist_bins
    return np.geomspace(
        spec.telemetry_hist_min_ms * 1e-3,
        spec.telemetry_hist_max_ms * 1e-3,
        B - 1,
    ).astype(np.float32)


def accumulate_latency(spec: WorldSpec, telem, tasks, t1: jax.Array):
    """Fold this tick's newly-acked task latencies into the histogram.

    Dense over the task table (no compaction: the scatter-add is one
    fused pass and rows beyond this tick add zero).  A row streams when
    it is DONE, it ran on a fog (``fog >= 0`` — broker-local
    completions keep the ``NO_TASK`` sentinel and have no fog row to
    land in, the ``_phase_learn_credit`` guard), its status-6 ack has
    reached the client (``t_ack6 <= t1``) and its ``lat_seen`` flag is
    still clear; the flag then sets, making the accumulation
    exactly-once under any completion backlog.
    Pure function of its arguments (simlint R3) and a
    :class:`TelemetryState` endomorphism, so it rides the scan carry
    and the fleet's replica ``vmap`` unchanged.  Only traced when
    ``spec.telemetry_hist`` is on.  Delegates the due/bucket
    arithmetic to :func:`latency_hist_delta` — ONE definition shared
    with the TP tick's fold, so the two paths cannot drift.
    """
    hist_d, sum_d, seen = latency_hist_delta(spec, telem, tasks, t1)
    return telem.replace(
        lat_hist=telem.lat_hist + hist_d,
        lat_sum=telem.lat_sum + sum_d,
        lat_seen=seen,
    )


def latency_hist_delta(spec: WorldSpec, telem, tasks, t1: jax.Array):
    """The streaming-histogram accumulation arithmetic, as DELTAS.

    The single definition of the due mask (DONE, fog-executed, status-6
    ack landed, not yet seen), the log-bucket ``searchsorted`` (first
    edge >= latency — the cumulative ``le`` semantics of the
    exposition, bucket B-1 = +Inf) and the per-fog scatter-adds.
    :func:`accumulate_latency` folds the deltas in place
    (single-device / fleet); the TP tick ``psum``s them into the
    replicated histogram (ISSUE 11) — integer scatter-adds, so the
    cross-shard fold is bit-identical to the single-device scatter,
    while the f32 ``sum_delta`` fold is order-sensitive and documented
    as 1e-6-agreeing, not bit-exact (tests/test_tp_telemetry.py pins
    both).

    Returns ``(hist_delta (F, B) i32, sum_delta (F,) f32,
    lat_seen' (T,) i8)``; under TP the seen flag stays shard-local
    (each task is owned by exactly one shard).
    """
    B, F = spec.telemetry_hist_bins, spec.n_fogs
    i32 = jnp.int32
    edges = jnp.asarray(hist_edges_s(spec))  # (B-1,) f32, trace constant
    due = (
        (tasks.stage == _ST_DONE)
        & (tasks.fog >= 0)
        & (tasks.t_ack6 <= t1)
        & (telem.lat_seen == 0)
    )
    lat = tasks.t_ack6 - tasks.t_create  # (T,) f32 seconds
    b = jnp.searchsorted(edges, lat).astype(i32)
    fog = jnp.clip(tasks.fog, 0, F - 1)
    add = due.astype(i32)
    hist_d = (
        jnp.zeros((F * B,), i32).at[fog * B + b].add(add).reshape(F, B)
    )
    sum_d = jnp.zeros((F,), jnp.float32).at[fog].add(
        jnp.where(due, lat, 0.0)
    )
    seen = jnp.maximum(telem.lat_seen, due.astype(jnp.int8))
    return hist_d, sum_d, seen


# ----------------------------------------------------------------------
# host-side readers (post-run or per chunk; one fetch each)
# ----------------------------------------------------------------------

def _quantile_from_cum(
    cum: np.ndarray, edges_ms: np.ndarray, q: float, total: int,
    overflow_ms: float,
) -> float:
    """Upper-edge quantile estimator over cumulative bucket counts.

    Returns the smallest bucket upper bound (ms) whose cumulative count
    reaches ``q * total``; the +Inf overflow bucket reports
    ``overflow_ms`` (the configured histogram ceiling) so downstream
    JSON/OpenMetrics stay finite.  NaN when the histogram is empty.
    """
    if total <= 0:
        return float("nan")
    b = int(np.searchsorted(cum, q * total, side="left"))
    if b >= len(edges_ms):
        return float(overflow_ms)
    return float(edges_ms[b])


def hist_summary(spec: WorldSpec, final) -> Optional[Dict]:
    """Host roll-up of the device-resident latency histogram.

    ``None`` when ``spec.telemetry_hist`` was off.  The returned
    quantiles (global and per-fog, in ms) are THE values every consumer
    publishes — ``runtime/recorder.py`` (``.sca.json``),
    ``telemetry/openmetrics.py`` (quantile gauges) and
    ``telemetry/live.py`` (/healthz) all read this one dict, so they
    agree exactly, not merely to tolerance.

    Accepts a fleet's replica-batched final state too: a leading
    replica axis on ``lat_hist`` is summed away (replica-merged
    histogram, ``parallel/fleet.py``).
    """
    if not (spec.telemetry and spec.telemetry_hist):
        return None
    counts = np.asarray(final.telem.lat_hist, np.int64)
    sums = np.asarray(final.telem.lat_sum, np.float64)
    if counts.ndim == 3:  # (R, F, B) fleet batch -> replica-merged
        counts = counts.sum(axis=0)
        sums = sums.sum(axis=0)
    edges_ms = hist_edges_s(spec).astype(np.float64) * 1e3
    over_ms = float(spec.telemetry_hist_max_ms)
    per_fog_cum = np.cumsum(counts, axis=1)
    g_counts = counts.sum(axis=0)
    g_cum = np.cumsum(g_counts)
    total = int(g_cum[-1]) if g_cum.size else 0
    out = {
        "edges_ms": edges_ms,
        "counts": counts,  # (F, B) non-cumulative, last = +Inf overflow
        "sum_ms": float(sums.sum() * 1e3),
        "count": total,
        "per_fog_count": counts.sum(axis=1).astype(np.int64),
        "per_fog_sum_ms": sums * 1e3,
        "quantiles_ms": {
            name: _quantile_from_cum(g_cum, edges_ms, q, total, over_ms)
            for name, q in QUANTILES
        },
        "per_fog_quantiles_ms": {
            name: np.asarray(
                [
                    _quantile_from_cum(
                        per_fog_cum[f], edges_ms, q,
                        int(per_fog_cum[f][-1]), over_ms,
                    )
                    for f in range(counts.shape[0])
                ]
            )
            for name, q in QUANTILES
        },
    }
    return out


def slo_breach_count(
    spec: WorldSpec, final, slo_ms: float, summ: Optional[Dict] = None
) -> Optional[int]:
    """Tasks whose latency exceeded ``slo_ms``, from the histogram.

    Bucket-resolution: the threshold snaps UP to the containing
    bucket's upper edge (a breach is only counted once the whole bucket
    lies above the SLO), so the count is a lower bound within one
    bucket's width — log-spaced buckets keep that error a constant
    ratio.  ``None`` when the histogram plane is off.  Callers that
    already hold a :func:`hist_summary` dict pass it as ``summ`` to
    skip the device re-fetch (the live loop computes one per chunk).
    """
    if summ is None:
        summ = hist_summary(spec, final)
    if summ is None:
        return None
    edges = summ["edges_ms"]
    g = summ["counts"].sum(axis=0)
    b = int(np.searchsorted(edges, float(slo_ms), side="left"))
    return int(g[b + 1:].sum())


def state_hash(state) -> str:
    """sha256 over every leaf of a world state (host fetch).

    The flight recorder's per-chunk fingerprint: two runs that diverge
    anywhere diverge here, and the postmortem diff tool can bisect WHICH
    chunk first diverged without storing full states.
    """
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(state):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def shard_state_hashes(spec: WorldSpec, state, n_shards: int) -> list:
    """Per-shard sha256 fingerprints of a TP world state (host fetch).

    Hashes exactly the rows shard ``s`` OWNS under the task-table
    sharding (``parallel/taskshard``): its user block, its task block,
    its user-node block and (when the latency histogram rides the
    shard) its ``lat_seen`` block.  The replicated fog/broker state is
    deliberately excluded — it is bit-coherent by construction, so a
    divergence there would show in every shard at once and tell the
    post-mortem nothing.  The flight recorder stores one list per
    chunk; ``tools/postmortem.py --diff`` walks two runs' lists and
    reports WHICH shard diverged first.
    """
    U, S = spec.n_users, spec.max_sends_per_user
    if n_shards <= 0 or U % n_shards:
        return []
    U_loc = U // n_shards
    T_loc = U_loc * S
    users = [np.asarray(x) for x in jax.tree.leaves(state.users)]
    tasks = [np.asarray(x) for x in jax.tree.leaves(state.tasks)]
    nodes = [np.asarray(x) for x in jax.tree.leaves(state.nodes)]
    seen = np.asarray(state.telem.lat_seen)
    out = []
    for s in range(n_shards):
        u0, t0 = s * U_loc, s * T_loc
        h = hashlib.sha256()
        for leaf in users:
            h.update(leaf[u0 : u0 + U_loc].tobytes())
        for leaf in tasks:
            h.update(leaf[t0 : t0 + T_loc].tobytes())
        for leaf in nodes:  # node layout: [users | fogs | broker | ...]
            h.update(leaf[u0 : u0 + U_loc].tobytes())
        if seen.size:
            h.update(seen[t0 : t0 + T_loc].tobytes())
        out.append(h.hexdigest())
    return out


def find_nonfinite(state) -> Dict[str, str]:
    """NaN detector for the flight recorder: ``{leaf path: kind}`` for
    every float leaf containing NaN.  (+Inf is a legitimate "never
    happened" sentinel throughout the task table, so only NaN trips.)
    """
    bad: Dict[str, str] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        a = np.asarray(leaf)
        if np.issubdtype(a.dtype, np.floating) and np.isnan(a).any():
            bad[jax.tree_util.keystr(path)] = "nan"
    return bad
