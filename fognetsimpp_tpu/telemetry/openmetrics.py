"""Plane 3 (exposition): run scalars as OpenMetrics text.

The reference's scalars live in a binary-ish ``.sca`` only its own
Scave tooling reads; production observability wants the scrape format
everything else speaks.  :func:`render_openmetrics` turns a finished
run into OpenMetrics text exposition (one ``# TYPE`` line + samples per
family, ``# EOF`` terminator): every ``Metrics`` counter and signal
roll-up from :func:`runtime.signals.summarize`, plus — when
``spec.telemetry`` is on — per-fog gauges (busy fraction, queue-depth
mean/max, pool occupancy, bandit picks) straight from the
device-resident :class:`~fognetsimpp_tpu.telemetry.metrics
.TelemetryState`.

The per-fog busy fraction is read from
:func:`telemetry.metrics.telemetry_summary`'s ``busy_frac`` entry (one
:func:`~fognetsimpp_tpu.telemetry.metrics.busy_fractions` computation)
— the SAME source the recorder's ``.sca.json`` fog rows use — so the
two outputs agree exactly (the acceptance gate asserts 1e-6).  Non-finite values are skipped, never
emitted: OpenMetrics has no NaN/Infinity sample syntax worth relying
on, the same RFC-pitfall discipline as ``recorder._json_sanitize``.

``tools/check_openmetrics.py`` is the matching ~20-line format linter
(CI runs it on the smoke scenario's output).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from ..spec import WorldSpec
from ..state import WorldState

_PREFIX = "fns"


def _sample(lines: List[str], name: str, value, labels: str = "") -> None:
    v = float(value)
    if not math.isfinite(v):
        return
    # integral values render without a trailing .0 (stable goldens)
    sv = str(int(v)) if v == int(v) and abs(v) < 2**53 else repr(v)
    lines.append(f"{_PREFIX}_{name}{labels} {sv}")


def _family(
    lines: List[str], name: str, kind: str = "gauge",
    help_text: Optional[str] = None,
) -> None:
    # every family carries both metadata comments: the extended
    # tools/check_openmetrics.py lint REQUIRES a # HELP next to each
    # # TYPE (scrape UIs surface it; a bare family reads as a bug)
    h = help_text or name.replace("_", " ")
    lines.append(f"# HELP {_PREFIX}_{name} {h}")
    lines.append(f"# TYPE {_PREFIX}_{name} {kind}")


def _fmt_le(v: float) -> str:
    """`le` label text for a finite bucket edge (round-trips float())."""
    return repr(float(v))


def _render_latency_hist(
    lines: List[str], hist: Dict, family: str = "task_latency"
) -> None:
    """Emit one per-fog latency histogram + its quantile gauges.

    ``hist`` is :func:`telemetry.health.hist_summary`'s dict — the
    single source both this exposition and the recorder's ``.sca.json``
    read, so their quantiles agree exactly.  Bucket series follow the
    OpenMetrics histogram contract the extended lint enforces:
    cumulative counts, ascending ``le`` labels, ``+Inf`` terminal,
    ``_count`` == the +Inf bucket, ``_sum`` present.  Latency samples
    are seconds (the Prometheus base unit); the quantile gauges are
    milliseconds and say so in their name.
    """
    import numpy as np

    edges_s = hist["edges_ms"] / 1e3
    counts = hist["counts"]
    F = counts.shape[0]
    _family(
        lines, family, "histogram",
        help_text="task_time latency publish to status-6 ack (seconds)",
    )
    for f in range(F):
        cum = np.cumsum(counts[f])
        for b in range(len(edges_s)):
            _sample(
                lines, f"{family}_bucket", cum[b],
                labels=f'{{fog="{f}",le="{_fmt_le(edges_s[b])}"}}',
            )
        _sample(
            lines, f"{family}_bucket", cum[-1],
            labels=f'{{fog="{f}",le="+Inf"}}',
        )
        _sample(
            lines, f"{family}_sum", hist["per_fog_sum_ms"][f] / 1e3,
            labels=f'{{fog="{f}"}}',
        )
        _sample(
            lines, f"{family}_count", cum[-1], labels=f'{{fog="{f}"}}'
        )
    _family(
        lines, f"{family}_quantile_ms",
        help_text="latency quantiles from the device histogram (ms)",
    )
    for qname, qv in hist["quantiles_ms"].items():
        _sample(
            lines, f"{family}_quantile_ms", qv, labels=f'{{q="{qname}"}}'
        )
    for qname, vec in hist["per_fog_quantiles_ms"].items():
        for f in range(F):
            _sample(
                lines, f"{family}_quantile_ms", vec[f],
                labels=f'{{fog="{f}",q="{qname}"}}',
            )


def _render_tp_exchange(lines: List[str], ex: Dict) -> None:
    """Emit the per-shard TP exchange-plane families (ISSUE 11).

    ``ex`` is :func:`telemetry.metrics.exchange_summary`'s dict — the
    single source the recorder's ``.sca.json`` ``tp_shard`` rows and
    the Perfetto shard lanes also read.  The occupancy family is a
    real OpenMetrics histogram (one label-group per shard) and obeys
    the bucket contract ``tools/check_openmetrics.py`` enforces:
    cumulative counts, ascending ``le``, ``+Inf`` terminal, ``_count``
    == the +Inf bucket, ``_sum`` present.
    """
    import numpy as np

    S = ex["n_shards"]
    _family(lines, "tp_shards", help_text="task-table shard count")
    _sample(lines, "tp_shards", S)
    fam = "tp_exchange_occupancy"
    _family(
        lines, fam, "histogram",
        help_text="per-tick exchange-window occupancy fraction per "
        "shard (candidates surviving the saturated-fog fast drop / "
        "window slots; > 1 defers)",
    )
    edges = ex["occ_edges"]
    for s in range(S):
        cum = np.cumsum(ex["occ_hist"][s])
        for b, e in enumerate(edges):
            _sample(
                lines, f"{fam}_bucket", cum[b],
                labels=f'{{shard="{s}",le="{_fmt_le(e)}"}}',
            )
        _sample(
            lines, f"{fam}_bucket", cum[-1],
            labels=f'{{shard="{s}",le="+Inf"}}',
        )
        _sample(
            lines, f"{fam}_sum", ex["occ_sum"][s],
            labels=f'{{shard="{s}"}}',
        )
        _sample(
            lines, f"{fam}_count", cum[-1], labels=f'{{shard="{s}"}}'
        )
    for name, vec, kind, h in (
        ("tp_exchange_candidates", ex["cand"], "counter",
         "arrival candidates produced per shard"),
        ("tp_exchange_deferred", ex["defer_sum"], "counter",
         "candidates deferred at the exchange window per shard"),
        ("tp_exchange_deferred_max", ex["defer_max"], "gauge",
         "max per-tick deferred candidates per shard"),
        ("tp_exchange_utilization", ex["util_mean"], "gauge",
         "mean ppermute payload utilization per shard"),
        ("tp_exchange_defer_age_ticks_max", ex["age_max_ticks"],
         "gauge", "max tick-age of a deferred candidate per shard"),
    ):
        _family(lines, name, kind, help_text=h)
        for s in range(S):
            _sample(lines, name, vec[s], labels=f'{{shard="{s}"}}')


def _render_journeys(lines: List[str], js: Dict) -> None:
    """Emit the ``fns_journey_*`` scalar families (ISSUE 15).

    ``js`` is :func:`telemetry.journeys.journey_summary`'s dict — the
    single source the recorder's ``.sca.json`` ``journeys`` section,
    the Perfetto journey lanes and the flight-recorder bundles also
    read.  The terminal census labels each sampled task by the LAST
    decoded stage of its ring (``in_flight`` = sampled, spawned, not
    yet terminal; ``unspawned`` = sampled slot never used).
    """
    for name, key, kind, h in (
        ("journey_sampled", "sampled", "gauge",
         "task slots sampled into journey event rings"),
        ("journey_ring_rows", "ring", "gauge",
         "event rows per sampled task's ring (drop-oldest overflow)"),
        ("journey_events_total", "events_total", "counter",
         "journey lifecycle events appended across all sampled tasks"),
        ("journey_dropped_total", "dropped_total", "counter",
         "journey events overwritten by ring overflow (drop-oldest)"),
    ):
        _family(lines, name, kind, help_text=h)
        _sample(lines, name, js[key])
    _family(
        lines, "journey_tasks",
        help_text="sampled-task census by the last decoded journey "
        "stage",
    )
    census = dict(js["terminal"])
    census["in_flight"] = js["in_flight"]
    census["unspawned"] = js["unspawned"]
    for stage, n in sorted(census.items()):
        _sample(
            lines, "journey_tasks", n, labels=f'{{stage="{stage}"}}'
        )


def _render_compile_stats(lines: List[str]) -> None:
    """Compile-latency observability (ISSUE 6): the persistent-cache
    hit/miss counters and backend compile seconds from
    :func:`fognetsimpp_tpu.compile_cache.compile_stats`, in every
    exposition — the streaming serving mode's blocker is compile
    latency, so the scrape must see it."""
    from ..compile_cache import compile_stats

    cs = compile_stats()
    for family, key, kind in (
        ("compile_cache_hits", "cache_hits", "counter"),
        ("compile_cache_misses", "cache_misses", "counter"),
        ("compile_backend_compiles", "compiles", "counter"),
        ("compile_seconds_total", "compile_s_total", "counter"),
        ("compile_seconds_max", "compile_s_max", "gauge"),
    ):
        _family(lines, family, kind)
        _sample(lines, family, cs.get(key, 0))
    # shape-bucket program registry (ISSUE 13): how many compiled
    # programs this process holds vs how often re-configuration re-used
    # one — the scrape-visible proof the compile wall stays down
    reg = cs.get("program_registry") or {}
    for family, key, kind in (
        ("compile_program_buckets", "buckets", "gauge"),
        ("compile_program_reuses", "reuses", "counter"),
    ):
        _family(lines, family, kind)
        _sample(lines, family, reg.get(key, 0))


def _render_twin_ingest(lines: List[str], st: Dict) -> None:
    """Emit the twin ingestion-queue families (ISSUE 17).

    ``st`` is :meth:`fognetsimpp_tpu.twin.ingest.IngestQueue.stats` —
    the single host-side source the /healthz ``ingest`` section and the
    watchdog's ``ingest_depth`` signal also read.  The lint
    (tools/check_openmetrics.py) requires the family set complete: a
    drop counter without its depth gauge reads as a bug.
    """
    for family, key, kind, help_text in (
        ("twin_ingest_depth", "depth", "gauge",
         "arrival-queue occupancy at the last chunk boundary"),
        ("twin_ingest_capacity", "capacity", "gauge",
         "arrival-queue bound (feeds past it are dropped)"),
        ("twin_ingest_accepted_total", "accepted", "counter",
         "arrivals accepted into the queue"),
        ("twin_ingest_dropped_total", "dropped", "counter",
         "arrivals dropped at the full queue"),
        ("twin_ingest_injected_total", "injected", "counter",
         "arrivals landed into simulation state at chunk boundaries"),
        ("twin_ingest_rejected_total", "rejected", "counter",
         "drained arrivals the injector refused (dead/disconnected "
         "user or send slots exhausted)"),
        ("twin_ingest_latency_seconds", "latency_s", "gauge",
         "feed-to-injection wall latency of the last drained batch"),
    ):
        _family(lines, family, kind, help_text=help_text)
        _sample(lines, family, st.get(key, 0))


def render_openmetrics(
    spec: WorldSpec,
    final: WorldState,
    attrs: Optional[Dict] = None,
    hist: Optional[Dict] = None,
    ingest: Optional[Dict] = None,
) -> str:
    """OpenMetrics text for one finished run (terminated by ``# EOF``).

    ``hist``: a :func:`telemetry.health.hist_summary` dict the caller
    already computed (the recorder and the live loop hold one); when
    omitted it is derived here — one extra device fetch per render.

    ``ingest`` (ISSUE 17): the twin ingestion queue's ``stats()`` dict;
    serve_run passes it on live-ingestion sessions so the
    ``fns_twin_ingest_*`` families ride the same exposition.
    """
    from ..runtime.signals import summarize
    from .metrics import telemetry_summary

    lines: List[str] = []
    for k, v in summarize(final).items():
        if isinstance(v, float) and not math.isfinite(v):
            continue
        _family(lines, k)
        _sample(lines, k, v)
    summ = telemetry_summary(spec, final)
    if summ is not None:
        per_fog = {
            "fog_busy_fraction": summ["busy_frac"],
            "fog_q_len_mean": summ["q_len_mean"],
            "fog_q_len_max": summ["q_len_max"],
            "fog_pool_occ_mean": summ["pool_occ_mean"],
            "fog_picks": summ["pick_hist"],
        }
        for name, vec in per_fog.items():
            _family(lines, name)
            for f in range(spec.n_fogs):
                _sample(lines, name, vec[f], labels=f'{{fog="{f}"}}')
        _family(lines, "phase_work")
        for phase, n in summ["phase_work"].items():
            _sample(
                lines, "phase_work", n, labels=f'{{phase="{phase}"}}'
            )
        _family(lines, "telemetry_ticks")
        _sample(lines, "telemetry_ticks", summ["ticks"])
        _family(lines, "deferred_sum")
        _sample(lines, "deferred_sum", summ["defer_sum"])
        # per-shard TP exchange-plane families (ISSUE 11): present only
        # on stamped TP runs (spec.tp_shards > 0)
        if summ.get("tp_exchange") is not None:
            _render_tp_exchange(lines, summ["tp_exchange"])
    # chaos per-fog lifecycle family (ISSUE 12): the scalar counters
    # already rendered as fns_chaos_* via summarize(); here the per-fog
    # down-tick gauge — same chaos_summary() dict the recorder's
    # .sca.json chaos section reads, so the two cannot drift
    if spec.chaos:
        from ..chaos.faults import chaos_summary

        cs = chaos_summary(spec, final)
        _family(
            lines, "chaos_fog_down_ticks",
            help_text="ticks each fog spent crashed over the run",
        )
        for f in range(spec.n_fogs):
            _sample(
                lines, "chaos_fog_down_ticks", cs["down_ticks"][f],
                labels=f'{{fog="{f}"}}',
            )
    # federated-hierarchy per-broker families (hier/): the scalar
    # counters already rendered as fns_hier_* via summarize(); here the
    # per-broker gauges — same hier_summary() dict the recorder's
    # .sca.json hier section reads, so the two cannot drift
    if spec.hier_active:
        from ..hier.federation import hier_summary

        hs = hier_summary(spec, final)
        # the published broker count: the linter's gap rule
        # (tools/check_openmetrics.py) cross-checks every per-broker
        # family against it, the fns_tp_shards discipline
        _family(
            lines, "hier_brokers",
            help_text="broker domain count of the federation",
        )
        _sample(lines, "hier_brokers", hs["n_brokers"])
        for family, key, help_text in (
            ("hier_migrations_out", "mig_out",
             "tasks migrated away from each broker domain"),
            ("hier_migrations_in", "mig_in",
             "tasks migrated into each broker domain"),
            ("hier_fogs", "fogs_per_broker",
             "fog nodes owned by each broker domain"),
            ("hier_users", "users_per_broker",
             "users publishing to each broker domain"),
        ):
            _family(lines, family, help_text=help_text)
            for b in range(hs["n_brokers"]):
                _sample(
                    lines, family, hs[key][b],
                    labels=f'{{broker="{b}"}}',
                )
        if "load_mean" in hs:
            _family(
                lines, "hier_load_mean",
                help_text="mean busy fraction of each broker domain",
            )
            for b in range(hs["n_brokers"]):
                _sample(
                    lines, "hier_load_mean", hs["load_mean"][b],
                    labels=f'{{broker="{b}"}}',
                )
    # causal task-journey families (spec.telemetry_journeys, ISSUE 15):
    # same journey_summary() dict the recorder's .sca.json journeys
    # section and the Perfetto journey lanes read, so the outputs
    # cannot drift
    if spec.journey_active:
        from .journeys import journey_summary

        js = journey_summary(spec, final)
        if js is not None:
            _render_journeys(lines, js)
    # streaming latency histogram (spec.telemetry_hist, ISSUE 6)
    if hist is None:
        from .health import hist_summary

        hist = hist_summary(spec, final)
    if hist is not None:
        _render_latency_hist(lines, hist)
    # twin ingestion-queue families (ISSUE 17): host-side queue stats,
    # present only on live-ingestion serve sessions
    if ingest is not None:
        _render_twin_ingest(lines, ingest)
    _render_compile_stats(lines)
    for k, v in (attrs or {}).items():
        if isinstance(v, (int, float)) and math.isfinite(float(v)):
            _family(lines, f"run_{k}")
            _sample(lines, f"run_{k}", v)
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def render_twin_openmetrics(tenants: List[Dict]) -> str:
    """The front door's AGGREGATE exposition (ISSUE 17): one document
    over every admitted tenant, terminated by ``# EOF``.

    ``tenants`` is ordered by tenant index; each entry is a flat dict
    of per-tenant scalars (:meth:`fognetsimpp_tpu.twin.front.FrontDoor.
    tenant_rows` builds it).  Families carry a ``tenant="i"`` label,
    and the published ``fns_twin_tenants`` count is the linter's
    cross-check anchor: every tenant-labeled family must cover exactly
    ``tenant=0..N-1`` gap-free (the ``fns_tp_shards`` /
    ``fns_hier_brokers`` discipline).  Per-tenant FULL expositions live
    at the front door's ``/t/<label>/metrics`` routes; this document is
    the fleet-wide scrape.
    """
    lines: List[str] = []
    _family(
        lines, "twin_tenants",
        help_text="tenant sessions admitted behind the front door",
    )
    _sample(lines, "twin_tenants", len(tenants))
    for family, key, kind, help_text in (
        ("twin_tenant_ticks", "ticks", "gauge",
         "simulated ticks each tenant session has completed"),
        ("twin_tenant_chunks", "chunks", "gauge",
         "serve chunks each tenant session has completed"),
        ("twin_tenant_users", "n_users", "gauge",
         "bucketed user population of each tenant world"),
        ("twin_tenant_published_total", "n_published", "counter",
         "tasks published in each tenant world"),
        ("twin_tenant_completed_total", "n_completed", "counter",
         "tasks completed in each tenant world"),
        ("twin_tenant_ingest_depth", "ingest_depth", "gauge",
         "arrival-queue occupancy of each tenant (0 when the tenant "
         "has no ingestion queue)"),
    ):
        _family(lines, family, kind, help_text=help_text)
        for i, t in enumerate(tenants):
            _sample(
                lines, family, t.get(key, 0), labels=f'{{tenant="{i}"}}'
            )
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def render_fleet_openmetrics(
    fleet_scalars: Dict,
    busy_frac: Optional[np.ndarray] = None,
    hist: Optional[Dict] = None,
    phase_work: Optional[np.ndarray] = None,
) -> str:
    """OpenMetrics text for a fleet run's scalars.

    ``fleet_scalars`` is the dict from ``recorder.fleet_scalars``;
    ``busy_frac`` is the per-fog busy-fraction matrix — per-REPLICA
    ``(R, F)`` from
    :func:`fognetsimpp_tpu.parallel.fleet.fleet_busy_fractions_per_replica`
    (each replica becomes its own ``fleet="r"`` label, the second PR-4
    follow-up: a sweep's replicas stay distinguishable in the scrape
    instead of being averaged away).  A 1-D vector is accepted for
    backward compatibility and rendered without the ``fleet`` label.

    ``hist``: the REPLICA-MERGED latency histogram — pass
    :func:`telemetry.health.hist_summary` of the batched final state
    (it sums a leading replica axis away), rendered as the
    ``fns_fleet_task_latency`` histogram family.  Unlike the busy-frac
    gauges the histogram is merged, not per-replica: R x F x B bucket
    series would swamp a scrape, and the fleet's latency SLO is a
    fleet-level question.
    """
    lines: List[str] = []
    _family(lines, "fleet_replicas")
    _sample(lines, "fleet_replicas", fleet_scalars["n_replicas"])
    for k, agg in fleet_scalars["aggregate"].items():
        _family(lines, f"fleet_{k}")
        for stat in ("sum", "mean", "min", "max"):
            _sample(
                lines, f"fleet_{k}", agg[stat],
                labels=f'{{stat="{stat}"}}',
            )
    if busy_frac is not None:
        bf = np.asarray(busy_frac)
        _family(lines, "fleet_fog_busy_fraction")
        if bf.ndim == 2:
            for r in range(bf.shape[0]):
                for f in range(bf.shape[1]):
                    _sample(
                        lines, "fleet_fog_busy_fraction", bf[r, f],
                        labels=f'{{fleet="{r}",fog="{f}"}}',
                    )
        else:
            for f in range(len(bf)):
                _sample(
                    lines, "fleet_fog_busy_fraction", bf[f],
                    labels=f'{{fog="{f}"}}',
                )
    if phase_work is not None:
        # per-replica phase attribution (ISSUE 11): one sample per
        # (fleet=replica, phase), the per-replica busy-frac discipline
        from .metrics import PHASES

        pw = np.asarray(phase_work)
        _family(
            lines, "fleet_phase_work",
            help_text="per-replica per-phase work counters",
        )
        for r in range(pw.shape[0]):
            for p, name in enumerate(PHASES):
                _sample(
                    lines, "fleet_phase_work", pw[r, p],
                    labels=f'{{fleet="{r}",phase="{name}"}}',
                )
    if hist is not None:
        _render_latency_hist(lines, hist, family="fleet_task_latency")
    _render_compile_stats(lines)
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def write_openmetrics(
    path: str,
    spec: WorldSpec,
    final: WorldState,
    attrs: Optional[Dict] = None,
    hist: Optional[Dict] = None,
) -> str:
    """Render and write; returns ``path``."""
    with open(path, "w") as f:
        f.write(render_openmetrics(spec, final, attrs=attrs, hist=hist))
    return path
