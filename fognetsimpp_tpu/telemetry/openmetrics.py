"""Plane 3 (exposition): run scalars as OpenMetrics text.

The reference's scalars live in a binary-ish ``.sca`` only its own
Scave tooling reads; production observability wants the scrape format
everything else speaks.  :func:`render_openmetrics` turns a finished
run into OpenMetrics text exposition (one ``# TYPE`` line + samples per
family, ``# EOF`` terminator): every ``Metrics`` counter and signal
roll-up from :func:`runtime.signals.summarize`, plus — when
``spec.telemetry`` is on — per-fog gauges (busy fraction, queue-depth
mean/max, pool occupancy, bandit picks) straight from the
device-resident :class:`~fognetsimpp_tpu.telemetry.metrics
.TelemetryState`.

The per-fog busy fraction is read from
:func:`telemetry.metrics.telemetry_summary`'s ``busy_frac`` entry (one
:func:`~fognetsimpp_tpu.telemetry.metrics.busy_fractions` computation)
— the SAME source the recorder's ``.sca.json`` fog rows use — so the
two outputs agree exactly (the acceptance gate asserts 1e-6).  Non-finite values are skipped, never
emitted: OpenMetrics has no NaN/Infinity sample syntax worth relying
on, the same RFC-pitfall discipline as ``recorder._json_sanitize``.

``tools/check_openmetrics.py`` is the matching ~20-line format linter
(CI runs it on the smoke scenario's output).
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from ..spec import WorldSpec
from ..state import WorldState

_PREFIX = "fns"


def _sample(lines: List[str], name: str, value, labels: str = "") -> None:
    v = float(value)
    if not math.isfinite(v):
        return
    # integral values render without a trailing .0 (stable goldens)
    sv = str(int(v)) if v == int(v) and abs(v) < 2**53 else repr(v)
    lines.append(f"{_PREFIX}_{name}{labels} {sv}")


def _family(lines: List[str], name: str, kind: str = "gauge") -> None:
    lines.append(f"# TYPE {_PREFIX}_{name} {kind}")


def render_openmetrics(
    spec: WorldSpec,
    final: WorldState,
    attrs: Optional[Dict] = None,
) -> str:
    """OpenMetrics text for one finished run (terminated by ``# EOF``)."""
    from ..runtime.signals import summarize
    from .metrics import telemetry_summary

    lines: List[str] = []
    for k, v in summarize(final).items():
        if isinstance(v, float) and not math.isfinite(v):
            continue
        _family(lines, k)
        _sample(lines, k, v)
    summ = telemetry_summary(spec, final)
    if summ is not None:
        per_fog = {
            "fog_busy_fraction": summ["busy_frac"],
            "fog_q_len_mean": summ["q_len_mean"],
            "fog_q_len_max": summ["q_len_max"],
            "fog_pool_occ_mean": summ["pool_occ_mean"],
            "fog_picks": summ["pick_hist"],
        }
        for name, vec in per_fog.items():
            _family(lines, name)
            for f in range(spec.n_fogs):
                _sample(lines, name, vec[f], labels=f'{{fog="{f}"}}')
        _family(lines, "phase_work")
        for phase, n in summ["phase_work"].items():
            _sample(
                lines, "phase_work", n, labels=f'{{phase="{phase}"}}'
            )
        _family(lines, "telemetry_ticks")
        _sample(lines, "telemetry_ticks", summ["ticks"])
        _family(lines, "deferred_sum")
        _sample(lines, "deferred_sum", summ["defer_sum"])
    for k, v in (attrs or {}).items():
        if isinstance(v, (int, float)) and math.isfinite(float(v)):
            _family(lines, f"run_{k}")
            _sample(lines, f"run_{k}", v)
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def render_fleet_openmetrics(
    fleet_scalars: Dict,
    busy_frac: Optional[np.ndarray] = None,
) -> str:
    """OpenMetrics text for a fleet run's scalars.

    ``fleet_scalars`` is the dict from ``recorder.fleet_scalars``;
    ``busy_frac`` is the per-fog busy-fraction matrix — per-REPLICA
    ``(R, F)`` from
    :func:`fognetsimpp_tpu.parallel.fleet.fleet_busy_fractions_per_replica`
    (each replica becomes its own ``fleet="r"`` label, the second PR-4
    follow-up: a sweep's replicas stay distinguishable in the scrape
    instead of being averaged away).  A 1-D vector is accepted for
    backward compatibility and rendered without the ``fleet`` label.
    """
    lines: List[str] = []
    _family(lines, "fleet_replicas")
    _sample(lines, "fleet_replicas", fleet_scalars["n_replicas"])
    for k, agg in fleet_scalars["aggregate"].items():
        _family(lines, f"fleet_{k}")
        for stat in ("sum", "mean", "min", "max"):
            _sample(
                lines, f"fleet_{k}", agg[stat],
                labels=f'{{stat="{stat}"}}',
            )
    if busy_frac is not None:
        bf = np.asarray(busy_frac)
        _family(lines, "fleet_fog_busy_fraction")
        if bf.ndim == 2:
            for r in range(bf.shape[0]):
                for f in range(bf.shape[1]):
                    _sample(
                        lines, "fleet_fog_busy_fraction", bf[r, f],
                        labels=f'{{fleet="{r}",fog="{f}"}}',
                    )
        else:
            for f in range(len(bf)):
                _sample(
                    lines, "fleet_fog_busy_fraction", bf[f],
                    labels=f'{{fog="{f}"}}',
                )
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def write_openmetrics(
    path: str,
    spec: WorldSpec,
    final: WorldState,
    attrs: Optional[Dict] = None,
) -> str:
    """Render and write; returns ``path``."""
    with open(path, "w") as f:
        f.write(render_openmetrics(spec, final, attrs=attrs))
    return path
