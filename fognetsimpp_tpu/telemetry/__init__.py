"""Three-plane observability layer (the reference's GUI/@statistic analog).

The reference ships its observability through OMNeT++'s Tkenv animation
and ``@statistic`` signals; this reproduction records end-of-run
artifacts (``runtime/recorder.py``) but — before this package — nothing
observed what happens *inside* the jitted tick loop.  Three planes:

* **Plane 1 — device-resident metrics** (:mod:`.metrics`): a
  fixed-shape :class:`~fognetsimpp_tpu.telemetry.metrics.TelemetryState`
  pytree riding the scan carry next to ``LearnState`` (zero-row when
  ``spec.telemetry`` is off), accumulating per-tick/per-fog queue
  depths, busy fractions, pool occupancy, bandit pick histograms and
  per-phase work counters entirely on device, plus a bounded strided
  reservoir of per-tick series rows — device memory stays bounded and
  dispatch stays flat (the ``bench.py`` one-scalar-fetch rule).
* **Plane 2 — task-lifecycle tracing** (:mod:`.timeline`): a post-run
  exporter reconstructing each task's lifecycle spans (publish → broker
  → fog queue → service → ack) from the task-table absolute-time
  columns into Chrome/Perfetto trace-event JSON — the headless analog
  of the reference's Tkenv animation, sibling to ``runtime/trails.py``.
* **Plane 3 — host/compiler profiling** (:mod:`.profile`,
  :mod:`.openmetrics`): ``jax.named_scope`` annotations on every engine
  phase (XLA profiles attribute cost to ``phase_broker`` vs
  ``phase_fog_arrivals``), ``bench.py --profile`` wrapping
  ``jax.profiler.trace`` with dispatch-latency histograms, and
  OpenMetrics text exposition of run scalars wired through
  ``runtime/recorder.py`` and the fleet runner's replica-aggregated
  recording.

* **Live health plane** (:mod:`.health`, :mod:`.live`, ISSUE 6): a
  device-resident streaming latency histogram (per-fog log buckets of
  the task_time signal, ``spec.telemetry_hist``, zero-row when off)
  from which p50/p95/p99 and SLO-breach counters derive on host; a
  serving loop over ``run_chunked`` exposing the OpenMetrics text —
  histogram series and quantile gauges included — behind a stdlib
  ``http.server`` pull endpoint with an EWMA z-score watchdog; a
  bounded flight recorder that dumps post-mortem bundles on NaN / SLO
  breach / anomaly / crash (``tools/postmortem.py`` inspects them);
  and compile-latency stats (``compile_cache.compile_stats``) in every
  exposition.

* **Distributed observability** (ISSUE 11): all three planes extend to
  the sharded execution paths — per-shard ``phase_work`` attribution
  under TP (bit-equal to the single-device profile), device-resident
  exchange-plane gauges (:func:`.metrics.exchange_summary`,
  ``fns_tp_exchange_*{shard}``), the latency histogram riding the
  shards, and the sharded health plane (:func:`.live.serve_tp_run`:
  ``--serve --tp N`` with a defer-rate watchdog and per-shard flight
  recorder hashes).

Only :mod:`.metrics` and :mod:`.health`'s device half are imported
here: the exporter modules import ``state``/``recorder`` and would
otherwise cycle with ``state.py``'s ``TelemetryState`` import.
"""
from .health import (  # noqa: F401
    QUANTILES,
    hist_edges_s,
    hist_summary,
    slo_breach_count,
)
from .metrics import (  # noqa: F401
    PHASES,
    RES_FIELDS,
    TelemetryState,
    busy_fractions,
    exchange_summary,
    init_telemetry_state,
    telemetry_summary,
)
