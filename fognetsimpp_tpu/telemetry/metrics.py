"""Plane 1: device-resident per-phase/per-fog metrics on the scan carry.

:class:`TelemetryState` is a small fixed-shape pytree carried inside
:class:`~fognetsimpp_tpu.state.WorldState` next to ``LearnState``: when
``spec.telemetry`` is off every array leaf has zero rows (and the two
scalar counters are never written), so inert worlds pay no memory and
stay bit-exact — the same gate discipline as the PR 2 inert-LearnState
contract (``tests/test_telemetry.py`` A/Bs it).

Everything accumulates ON DEVICE inside the jitted tick loop — the
engine's ``_phase_telemetry`` calls :func:`accumulate_tick` once per
tick — and is fetched once, after the run, by
:func:`telemetry_summary` / ``runtime/recorder.py``.  The per-tick
reservoir is a strided sample of the run (``spec.telemetry_slots``
rows for the whole horizon), so device memory stays bounded no matter
the horizon, the ``run_fleet_series`` discipline without the per-chunk
host offload.

Per-phase "work done" counters: the engine brackets every phase call
with :func:`metrics_activity` (the sum of all ``Metrics`` counters, a
monotone per-tick activity measure) and credits the delta to that
phase's :data:`PHASES` slot — so a regression in, say, credit
assignment shows up as a shifted ``phase_work`` profile instead of only
a moved benchmark number.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..spec import FogModel, WorldSpec

#: Engine phase slots for the ``phase_work`` counter vector, in tick
#: execution order.  Indices are static; phases a spec never traces
#: simply keep a zero slot.
PHASES = (
    "connect",
    "adverts",
    "spawn",
    "v2_release_pre",
    "broker",
    "v2_release_post",
    "pool_completions",
    "pool_arrivals",
    "completions",
    "fog_arrivals",
    "local_completions",
    "learn_credit",
    "latency_hist",
    # --- TP exchange-plane slots (ISSUE 11): booked only by the
    # sharded tick (parallel/taskshard._tp_tick), zero on every
    # single-device path.  The established slots above book the SAME
    # work deltas under TP as on one device (shard-partial deltas
    # folded in the end-of-tick psum), so summing those over shards
    # reproduces the single-device profile bit-for-bit; these two
    # carry the TP-only quantities a single device has no analog for.
    "tp_exchange",  # candidate slots seated in the exchange window
    "tp_defer",  # candidates deferred at the exchange window (overflow)
    # --- chaos fault injection (ISSUE 12): appended after the TP slots
    # so every established PHASE_INDEX stays stable; the chaos phase
    # actually executes FIRST in the tick (display order here is not
    # execution order for the post-TP entries).
    "chaos",  # fog lifecycle edges + in-flight sweep + re-offloads
    # --- federated hierarchy (hier/): appended after the chaos slot so
    # every established PHASE_INDEX stays stable; executes right after
    # chaos, before any decide phase.
    "broker_migrate",  # broker↔broker task migration + peer-view aging
)
PHASE_INDEX: Dict[str, int] = {name: i for i, name in enumerate(PHASES)}

#: Columns of one reservoir row (all f32).  ``n_dropped`` (cumulative
#: queue-overflow count) joined in r6: the live watchdog derives its
#: per-chunk drop RATE from consecutive rows' deltas
#: (telemetry/live.py), so the signal must ride the reservoir.
#: ``defer_total`` (cumulative deferred-arrival count, the running
#: ``defer_sum``) joined in ISSUE 11: the per-tick ``n_deferred`` gauge
#: sits CONSTANT under sustained exchange-window overflow (the
#: tick-keyed rotation spreads deferral evenly), so a z-score watchdog
#: on the gauge never fires — the defer RATE from consecutive
#: cumulative samples is the signal that pages.
#: ``n_fogs_down`` (gauge: fogs down at the sampled tick) and
#: ``lost_crash_total`` (cumulative tasks lost to crashes, LOSE +
#: retry-exhausted — monotone like ``n_dropped``) joined in ISSUE 12:
#: the serving watchdog derives a crash-loss RATE from consecutive
#: cumulative samples (a flapping fog must page even when each
#: individual outage looks small), and both columns stay zero on
#: chaos-off worlds.
RES_FIELDS = (
    "t", "q_len_total", "n_busy", "n_deferred", "n_completed", "n_dropped",
    "defer_total", "n_fogs_down", "lost_crash_total",
)

#: Finite bucket upper edges of the per-shard exchange-window OCCUPANCY
#: histogram (occupancy fraction = candidates CONTENDING for the window
#: — i.e. surviving the saturated-fog fast drop — / window slots;
#: > 1.0 means overflow -> deferral.  The separate ``exg_cand_sum``
#: counter is the PRE-drop production count).  Static, shared by the
#: device accumulation and every host reader; the last bucket is +Inf.
EXG_OCC_EDGES = (0.25, 0.5, 0.75, 0.9, 1.0)
EXG_OCC_BINS = len(EXG_OCC_EDGES) + 1


@struct.dataclass
class TelemetryState:
    """Carry-resident telemetry accumulators (one per world / replica).

    Array leaves are sized ``spec.telemetry_fogs`` /
    ``spec.telemetry_phases`` / ``spec.telemetry_slots`` — the real
    dimensions when ``spec.telemetry`` is on, zero rows otherwise.
    """

    ticks: jax.Array  # () i32 ticks accumulated (stays 0 when inert)
    defer_sum: jax.Array  # () i32 sum of the per-tick deferred gauge
    q_len_sum: jax.Array  # (Fm,) f32 per-fog queue-depth sum over ticks
    q_len_max: jax.Array  # (Fm,) i32 per-fog queue-depth running max
    q_len_min: jax.Array  # (Fm,) i32 per-fog queue-depth running min
    busy_ticks: jax.Array  # (Fm,) i32 ticks the fog server was busy
    pool_occ_sum: jax.Array  # (Fm,) f32 POOL-model occupancy-fraction sum
    pick_hist: jax.Array  # (Fm,) f32 bandit pick histogram (a live copy
    #   of LearnState.pick_count; zeros when the learn subsystem is off)
    phase_work: jax.Array  # (Pm,) i32 per-phase work-done counters
    res: jax.Array  # (Rm, len(RES_FIELDS)) f32 strided per-tick rows
    # --- streaming latency histogram (spec.telemetry_hist, ISSUE 6) ---
    # accumulated by core/engine._phase_latency_hist via
    # telemetry/health.accumulate_latency; all three leaves are
    # zero-row when the histogram gate is off
    lat_hist: jax.Array  # (Fh, Bh) i32 per-fog log-bucket counts of the
    #   task_time latency (publish -> status-6 ack); last bucket = +Inf
    lat_sum: jax.Array  # (Fh,) f32 per-fog latency sum (seconds) — the
    #   OpenMetrics histogram `_sum` series
    lat_seen: jax.Array  # (Th,) i8 per-task counted flag (exactly-once)
    # --- TP exchange-plane telemetry (spec.tp_shards, ISSUE 11) -------
    # Per-shard gauges of the ring arrival exchange, accumulated by the
    # sharded tick's end-of-tick telemetry fold (parallel/taskshard).
    # All leaves are zero-row unless the spec is a stamped TP world view
    # with telemetry on (spec.telemetry_tp_shards > 0).
    exg_occ_hist: jax.Array  # (Sm, EXG_OCC_BINS) i32 per-shard histogram
    #   of per-tick exchange-window occupancy fraction (last = overflow)
    exg_occ_sum: jax.Array  # (Sm,) f32 occupancy-fraction sum over ticks
    #   (the fns_tp_exchange_occupancy histogram `_sum`)
    exg_cand_sum: jax.Array  # (Sm,) i32 arrival candidates produced
    exg_defer_sum: jax.Array  # (Sm,) i32 candidates deferred at the
    #   exchange window (overflow; the engine's K-window defer contract)
    exg_defer_max: jax.Array  # (Sm,) i32 max per-tick deferred count
    exg_util_sum: jax.Array  # (Sm,) f32 ppermute payload utilization
    #   (seated slots / window slots) summed over ticks
    exg_age_max: jax.Array  # (Sm,) f32 max tick-age of a deferred
    #   candidate (how long the oldest waiting arrival sat unseated)
    exg_occ_res: jax.Array  # (Rm, Sm) f32 strided per-tick per-shard
    #   occupancy rows (same stride as `res`): the Perfetto per-shard
    #   counter lanes and live dashboards read these
    # --- federated hierarchy (spec.n_brokers > 1, hier/) --------------
    # Per-broker domain load, accumulated by the end-of-tick telemetry
    # fold.  Zero-row unless BOTH the telemetry plane and the hierarchy
    # are on (spec.telemetry_hier_brokers > 0).
    hier_load_sum: jax.Array  # (Bm,) f32 per-broker busy-fraction sum
    #   over ticks (mean = / ticks; the fns_hier_load gauge)
    hier_load_res: jax.Array  # (Rm, Bm) f32 strided per-tick per-broker
    #   load rows (same stride as `res`): the Perfetto broker lanes
    # --- causal task-journey rings (spec.telemetry_journeys, ISSUE 15)
    # Per-sampled-task bounded event rings, appended by the engine's
    # end-of-tick journey tap (telemetry/journeys.journey_tick).  All
    # leaves are zero-row unless spec.journey_active; j_dropped is a
    # scalar and stays exactly zero on journey-off worlds.
    j_task: jax.Array  # (Jm,) i32 sampled task ids (sorted; the
    #   deterministic hash-select from the world key)
    j_prev: jax.Array  # (Jm, len(journeys.J_COLS)) i32 previous
    #   end-of-tick snapshot rows the per-tick diff runs against
    j_ring: jax.Array  # (Jm, Rj, 4) i32 packed (t_bits, code, a, b)
    #   event rows; drop-oldest wrap via j_cursor
    j_cursor: jax.Array  # (Jm,) i32 total events appended per slot
    j_dropped: jax.Array  # () i32 ring rows overwritten (drop-oldest)


def init_telemetry_state(
    spec: WorldSpec, key: Optional[jax.Array] = None
) -> TelemetryState:
    """The t=0 telemetry state for ``spec`` (zero-row when off).

    ``key`` is the WORLD key: the journey plane hash-selects its task
    sample from it (threefry-folded, never split — see
    :func:`..telemetry.journeys.journey_sample_ids`); only consulted
    when ``spec.journey_active``.
    """
    from .journeys import init_journey_leaves

    Fm, Pm, Rm = (
        spec.telemetry_fogs, spec.telemetry_phases, spec.telemetry_slots
    )
    f32, i32 = jnp.float32, jnp.int32
    return TelemetryState(
        ticks=jnp.zeros((), i32),
        defer_sum=jnp.zeros((), i32),
        q_len_sum=jnp.zeros((Fm,), f32),
        q_len_max=jnp.zeros((Fm,), i32),
        q_len_min=jnp.full((Fm,), spec.queue_capacity, i32),
        busy_ticks=jnp.zeros((Fm,), i32),
        pool_occ_sum=jnp.zeros((Fm,), f32),
        pick_hist=jnp.zeros((Fm,), f32),
        phase_work=jnp.zeros((Pm,), i32),
        res=jnp.zeros((Rm, len(RES_FIELDS)), f32),
        lat_hist=jnp.zeros(
            (spec.telemetry_hist_fogs, spec.telemetry_hist_nbins), i32
        ),
        lat_sum=jnp.zeros((spec.telemetry_hist_fogs,), f32),
        lat_seen=jnp.zeros((spec.telemetry_hist_tasks,), jnp.int8),
        **init_exchange_leaves(spec),
        **init_hier_leaves(spec),
        **init_journey_leaves(spec, key),
    )


def init_hier_leaves(spec: WorldSpec) -> Dict[str, jax.Array]:
    """The t=0 hierarchy telemetry leaves for ``spec`` (zero-row unless
    the spec is a telemetry-on federated world)."""
    Bm = spec.telemetry_hier_brokers
    Rm = spec.telemetry_slots if Bm else 0
    f32 = jnp.float32
    return dict(
        hier_load_sum=jnp.zeros((Bm,), f32),
        hier_load_res=jnp.zeros((Rm, Bm), f32),
    )


def init_exchange_leaves(spec: WorldSpec) -> Dict[str, jax.Array]:
    """The t=0 TP exchange-plane leaves for ``spec`` (zero-row when the
    spec is not a telemetry-on TP world view).  Split out so
    ``run_tp_sharded`` can extend a single-device world's telemetry
    state in place when it stamps ``spec.tp_shards``."""
    Sm = spec.telemetry_tp_shards
    Rm = spec.telemetry_slots if Sm else 0
    f32, i32 = jnp.float32, jnp.int32
    return dict(
        exg_occ_hist=jnp.zeros((Sm, EXG_OCC_BINS), i32),
        exg_occ_sum=jnp.zeros((Sm,), f32),
        exg_cand_sum=jnp.zeros((Sm,), i32),
        exg_defer_sum=jnp.zeros((Sm,), i32),
        exg_defer_max=jnp.zeros((Sm,), i32),
        exg_util_sum=jnp.zeros((Sm,), f32),
        exg_age_max=jnp.zeros((Sm,), f32),
        exg_occ_res=jnp.zeros((Rm, Sm), f32),
    )


def metrics_activity(metrics) -> jax.Array:
    """Sum of every ``Metrics`` counter: the phase-work bracket scalar.

    Within one tick every counter is non-decreasing (the ``n_deferred``
    gauge resets before the phases run), so the delta across a phase
    call is that phase's booked activity.
    """
    vals = [
        getattr(metrics, f.name) for f in dataclasses.fields(metrics)
    ]
    return jnp.sum(jnp.stack(vals))


def tick_activity(metrics, buf) -> jax.Array:
    """Activity bracket over Metrics counters AND the tick's message
    buffers (``engine.TickBuf``): phases whose work is pure message
    movement (fog arrivals queueing tasks, ack relays) book no Metrics
    counter, but every one of them books tx/rx — so the combined sum is
    the monotone-within-a-tick measure the per-phase work counters
    bracket."""
    s = metrics_activity(metrics)
    for leaf in buf:
        s = s + jnp.sum(leaf)
    return s


def accumulate_tick(
    spec: WorldSpec,
    telem: TelemetryState,
    fogs,
    learn,
    metrics,
    tick: jax.Array,
    t1: jax.Array,
    phase_work: Optional[Dict[int, jax.Array]] = None,
    chaos=None,
    fogs_down: Optional[jax.Array] = None,
    hier_load: Optional[jax.Array] = None,
) -> TelemetryState:
    """Fold one finished tick into the telemetry accumulators.

    Pure function of its arguments (``fogs``/``learn``/``metrics`` ride
    in as args, never by closure — simlint R3) and an endomorphism over
    :class:`TelemetryState`, so it is scan-carry safe and ``vmap``s over
    the fleet's replica axis unchanged.  Only traced when
    ``spec.telemetry`` is on.
    """
    from ..ops.queues import NO_TASK

    f32, i32 = jnp.float32, jnp.int32
    q = fogs.q_len.astype(i32)
    if spec.fog_model == int(FogModel.POOL):
        occ = jnp.clip(
            (fogs.mips - fogs.pool_avail)
            / jnp.maximum(fogs.mips, 1e-9),
            0.0,
            1.0,
        )
        busy = fogs.pool_avail < fogs.mips
    else:
        busy = fogs.current_task != NO_TASK
        occ = busy.astype(f32)
    telem = telem.replace(
        ticks=telem.ticks + 1,
        defer_sum=telem.defer_sum + metrics.n_deferred,
        q_len_sum=telem.q_len_sum + q.astype(f32),
        q_len_max=jnp.maximum(telem.q_len_max, q),
        q_len_min=jnp.minimum(telem.q_len_min, q),
        busy_ticks=telem.busy_ticks + busy.astype(i32),
        pool_occ_sum=telem.pool_occ_sum + occ,
    )
    if spec.learn_active:
        telem = telem.replace(pick_hist=learn.pick_count)
    if hier_load is not None:
        # federated hierarchy: per-broker busy-fraction sum + strided
        # per-tick lanes (the broker analog of the exchange-plane rows)
        telem = telem.replace(
            hier_load_sum=telem.hier_load_sum + hier_load
        )
        Rh = telem.hier_load_res.shape[0]
        if Rh > 0:
            stride_h = max(1, -(-spec.n_ticks // Rh))
            slot_h = (tick // stride_h).astype(i32)
            write_h = (tick % stride_h) == 0
            telem = telem.replace(
                hier_load_res=telem.hier_load_res.at[
                    jnp.where(write_h, slot_h, Rh)
                ].set(hier_load, mode="drop")
            )
    if phase_work:
        idxs = np.asarray(sorted(phase_work), np.int32)
        vals = jnp.stack(
            [phase_work[int(i)] for i in idxs]
        ).astype(i32)
        telem = telem.replace(
            phase_work=telem.phase_work.at[idxs].add(vals)
        )
    R = telem.res.shape[0]
    if R > 0:
        stride = max(1, -(-spec.n_ticks // R))
        slot = (tick // stride).astype(i32)
        write = (tick % stride) == 0
        # chaos columns (ISSUE 12): fogs down now + cumulative crash
        # losses (LOSE + retry-exhausted) — zeros on chaos-off worlds
        zero = jnp.zeros((), f32)
        down_now = (
            fogs_down.astype(f32) if fogs_down is not None else zero
        )
        lost_tot = (
            (chaos.n_lost_crash + chaos.n_retry_exhausted).astype(f32)
            if chaos is not None
            else zero
        )
        row = jnp.stack(
            [
                t1.astype(f32),
                jnp.sum(q).astype(f32),
                jnp.sum(busy.astype(i32)).astype(f32),
                metrics.n_deferred.astype(f32),
                metrics.n_completed.astype(f32),
                metrics.n_dropped.astype(f32),
                # cumulative deferred count INCLUDING this tick (the
                # defer_sum update above ran first): the watchdog's
                # defer-rate signal needs a monotone column, like
                # n_dropped next to it
                telem.defer_sum.astype(f32),
                down_now,
                lost_tot,
            ]
        )
        telem = telem.replace(
            res=telem.res.at[jnp.where(write, slot, R)].set(
                row, mode="drop"
            )
        )
    return telem


def accumulate_exchange(
    spec: WorldSpec,
    telem: TelemetryState,
    occ: jax.Array,
    util: jax.Array,
    age: jax.Array,
    cand: jax.Array,
    defer: jax.Array,
    tick: jax.Array,
) -> TelemetryState:
    """Fold one tick's psum-gathered per-shard exchange vectors.

    All five inputs are replicated ``(S,)`` f32 vectors — the sharded
    tick builds them as one-hot columns (each shard fills only its own
    slot) and a single ``psum`` assembles the full per-shard view, so
    every shard folds identical values and the replicated telemetry
    state stays bit-coherent.  ``cand``/``defer`` are integer-valued
    f32 (bounded by the per-shard candidate capacity, far below 2^24 —
    ``taskshard._tp_setup`` asserts the bound at build time) and cast
    back exactly.  Pure function of its arguments and a
    :class:`TelemetryState` endomorphism; only traced when the spec is
    a telemetry-on TP world view.
    """
    f32, i32 = jnp.float32, jnp.int32
    edges = jnp.asarray(EXG_OCC_EDGES, f32)
    # searchsorted(side='left'): first bucket whose edge >= occ — the
    # same cumulative `le` convention as the latency histogram
    b = jnp.searchsorted(edges, occ).astype(i32)
    onehot = (
        b[:, None] == jnp.arange(EXG_OCC_BINS, dtype=i32)[None, :]
    ).astype(i32)
    telem = telem.replace(
        exg_occ_hist=telem.exg_occ_hist + onehot,
        exg_occ_sum=telem.exg_occ_sum + occ,
        exg_cand_sum=telem.exg_cand_sum + cand.astype(i32),
        exg_defer_sum=telem.exg_defer_sum + defer.astype(i32),
        exg_defer_max=jnp.maximum(
            telem.exg_defer_max, defer.astype(i32)
        ),
        exg_util_sum=telem.exg_util_sum + util,
        exg_age_max=jnp.maximum(telem.exg_age_max, age),
    )
    Rm = telem.exg_occ_res.shape[0]
    if Rm > 0:
        stride = max(1, -(-spec.n_ticks // Rm))
        slot = (tick // stride).astype(i32)
        write = (tick % stride) == 0
        telem = telem.replace(
            exg_occ_res=telem.exg_occ_res.at[
                jnp.where(write, slot, Rm)
            ].set(occ, mode="drop")
        )
    return telem


# ----------------------------------------------------------------------
# host-side readers (post-run; one fetch each)
# ----------------------------------------------------------------------

def reservoir_progress(
    spec: WorldSpec, telem: TelemetryState, ticks_done: int,
    start_row: int = 0,
) -> tuple:
    """Incremental read of the strided per-tick reservoir.

    Returns ``({field: host rows [start_row:filled]}, filled)`` where
    ``filled`` is the number of reservoir rows complete after
    ``ticks_done`` ticks (row k holds tick ``k * stride``).  This is the
    ``run_chunked`` live-streaming primitive (the PR-4 follow-up): each
    chunk boundary fetches only the rows the chunk filled, so dashboards
    see per-tick rows without waiting for run end — and without breaking
    the chunk donation discipline (the fetch completes before the next
    chunk consumes the state).
    """
    R = telem.res.shape[0]
    if R == 0 or ticks_done <= 0:
        return {f: np.zeros((0,)) for f in RES_FIELDS}, start_row
    stride = max(1, -(-spec.n_ticks // R))
    filled = min(R, -(-ticks_done // stride))
    rows = np.asarray(telem.res[start_row:filled])
    return (
        {f: rows[:, i] for i, f in enumerate(RES_FIELDS)},
        max(filled, start_row),
    )


def busy_fractions(spec: WorldSpec, final) -> Optional[np.ndarray]:
    """Per-fog busy fraction (ticks busy / ticks observed) as a host
    array, or ``None`` when ``spec.telemetry`` was off.

    The single source of truth for the value: ``recorder
    .per_module_scalars`` (the ``.sca.json`` fog rows) and the
    OpenMetrics exposition both call this, so the two outputs agree
    exactly, not merely to tolerance.
    """
    if not spec.telemetry:
        return None
    ticks = max(int(np.asarray(final.telem.ticks)), 1)
    return np.asarray(final.telem.busy_ticks, np.float64) / ticks


def exchange_summary(spec: WorldSpec, final) -> Optional[Dict]:
    """Host roll-up of the per-shard TP exchange-plane telemetry.

    ``None`` unless ``final`` carries stamped exchange leaves
    (``spec.telemetry_tp_shards > 0``).  The returned per-shard vectors
    are THE values every exposition publishes — ``runtime/recorder.py``
    (``.sca.json`` ``tp_shard`` rows), ``telemetry/openmetrics.py``
    (``fns_tp_exchange_*`` families) and ``telemetry/timeline.py``
    (per-shard Perfetto lanes) all read this one dict, the
    ``busy_fractions`` single-source discipline.
    """
    if not spec.telemetry or spec.telemetry_tp_shards == 0:
        return None
    t = final.telem
    S = t.exg_cand_sum.shape[0]
    if S == 0:
        return None
    ticks = max(int(np.asarray(t.ticks)), 1)
    res = np.asarray(t.res, np.float64)
    occ_res = np.asarray(t.exg_occ_res, np.float64)
    Rm = occ_res.shape[0]
    stride = max(1, -(-spec.n_ticks // Rm)) if Rm else 1
    n_rows = min(Rm, -(-ticks // stride)) if Rm else 0
    return {
        "n_shards": S,
        "ticks": ticks,
        "occ_edges": list(EXG_OCC_EDGES),
        "occ_hist": np.asarray(t.exg_occ_hist, np.int64),  # (S, B)
        "occ_sum": np.asarray(t.exg_occ_sum, np.float64),
        "occ_mean": np.asarray(t.exg_occ_sum, np.float64) / ticks,
        "cand": np.asarray(t.exg_cand_sum, np.int64),
        "defer_sum": np.asarray(t.exg_defer_sum, np.int64),
        "defer_max": np.asarray(t.exg_defer_max, np.int64),
        "util_mean": np.asarray(t.exg_util_sum, np.float64) / ticks,
        "age_max_ticks": np.asarray(t.exg_age_max, np.float64),
        # strided per-tick rows for the Perfetto lanes: (rows, S)
        # occupancy plus the matching reservoir timestamps
        "occ_rows": occ_res[:n_rows],
        "occ_rows_t": res[:n_rows, 0] if n_rows else np.zeros((0,)),
    }


def telemetry_summary(spec: WorldSpec, final) -> Optional[Dict]:
    """Host-side roll-up of a finished telemetry-on run.

    Returns ``None`` when ``spec.telemetry`` was off; otherwise a dict
    of per-fog vectors (busy fraction, queue-depth mean/min/max, pool
    occupancy, pick histogram), the named per-phase work counters, and
    the reservoir as ``{field: (Rm,) array}``.
    """
    if not spec.telemetry:
        return None
    t = final.telem
    ticks = max(int(np.asarray(t.ticks)), 1)
    res = np.asarray(t.res, np.float64)
    Rm = res.shape[0]
    stride = max(1, -(-spec.n_ticks // Rm)) if Rm else 1
    n_rows = min(Rm, -(-ticks // stride))
    return {
        "ticks": ticks,
        "defer_sum": int(np.asarray(t.defer_sum)),
        "busy_frac": busy_fractions(spec, final),
        "q_len_mean": np.asarray(t.q_len_sum, np.float64) / ticks,
        "q_len_max": np.asarray(t.q_len_max, np.int64),
        "q_len_min": np.asarray(t.q_len_min, np.int64),
        "pool_occ_mean": np.asarray(t.pool_occ_sum, np.float64) / ticks,
        "pick_hist": np.asarray(t.pick_hist, np.float64),
        "phase_work": {
            name: int(np.asarray(t.phase_work[i]))
            for i, name in enumerate(PHASES)
        },
        "reservoir": {
            f: res[:n_rows, i] for i, f in enumerate(RES_FIELDS)
        },
        # per-shard TP exchange-plane roll-up (None off the TP path)
        "tp_exchange": exchange_summary(spec, final),
    }
