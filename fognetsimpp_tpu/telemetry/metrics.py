"""Plane 1: device-resident per-phase/per-fog metrics on the scan carry.

:class:`TelemetryState` is a small fixed-shape pytree carried inside
:class:`~fognetsimpp_tpu.state.WorldState` next to ``LearnState``: when
``spec.telemetry`` is off every array leaf has zero rows (and the two
scalar counters are never written), so inert worlds pay no memory and
stay bit-exact — the same gate discipline as the PR 2 inert-LearnState
contract (``tests/test_telemetry.py`` A/Bs it).

Everything accumulates ON DEVICE inside the jitted tick loop — the
engine's ``_phase_telemetry`` calls :func:`accumulate_tick` once per
tick — and is fetched once, after the run, by
:func:`telemetry_summary` / ``runtime/recorder.py``.  The per-tick
reservoir is a strided sample of the run (``spec.telemetry_slots``
rows for the whole horizon), so device memory stays bounded no matter
the horizon, the ``run_fleet_series`` discipline without the per-chunk
host offload.

Per-phase "work done" counters: the engine brackets every phase call
with :func:`metrics_activity` (the sum of all ``Metrics`` counters, a
monotone per-tick activity measure) and credits the delta to that
phase's :data:`PHASES` slot — so a regression in, say, credit
assignment shows up as a shifted ``phase_work`` profile instead of only
a moved benchmark number.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..spec import FogModel, WorldSpec

#: Engine phase slots for the ``phase_work`` counter vector, in tick
#: execution order.  Indices are static; phases a spec never traces
#: simply keep a zero slot.
PHASES = (
    "connect",
    "adverts",
    "spawn",
    "v2_release_pre",
    "broker",
    "v2_release_post",
    "pool_completions",
    "pool_arrivals",
    "completions",
    "fog_arrivals",
    "local_completions",
    "learn_credit",
    "latency_hist",
)
PHASE_INDEX: Dict[str, int] = {name: i for i, name in enumerate(PHASES)}

#: Columns of one reservoir row (all f32).  ``n_dropped`` (cumulative
#: queue-overflow count) joined in r6: the live watchdog derives its
#: per-chunk drop RATE from consecutive rows' deltas
#: (telemetry/live.py), so the signal must ride the reservoir.
RES_FIELDS = (
    "t", "q_len_total", "n_busy", "n_deferred", "n_completed", "n_dropped",
)


@struct.dataclass
class TelemetryState:
    """Carry-resident telemetry accumulators (one per world / replica).

    Array leaves are sized ``spec.telemetry_fogs`` /
    ``spec.telemetry_phases`` / ``spec.telemetry_slots`` — the real
    dimensions when ``spec.telemetry`` is on, zero rows otherwise.
    """

    ticks: jax.Array  # () i32 ticks accumulated (stays 0 when inert)
    defer_sum: jax.Array  # () i32 sum of the per-tick deferred gauge
    q_len_sum: jax.Array  # (Fm,) f32 per-fog queue-depth sum over ticks
    q_len_max: jax.Array  # (Fm,) i32 per-fog queue-depth running max
    q_len_min: jax.Array  # (Fm,) i32 per-fog queue-depth running min
    busy_ticks: jax.Array  # (Fm,) i32 ticks the fog server was busy
    pool_occ_sum: jax.Array  # (Fm,) f32 POOL-model occupancy-fraction sum
    pick_hist: jax.Array  # (Fm,) f32 bandit pick histogram (a live copy
    #   of LearnState.pick_count; zeros when the learn subsystem is off)
    phase_work: jax.Array  # (Pm,) i32 per-phase work-done counters
    res: jax.Array  # (Rm, len(RES_FIELDS)) f32 strided per-tick rows
    # --- streaming latency histogram (spec.telemetry_hist, ISSUE 6) ---
    # accumulated by core/engine._phase_latency_hist via
    # telemetry/health.accumulate_latency; all three leaves are
    # zero-row when the histogram gate is off
    lat_hist: jax.Array  # (Fh, Bh) i32 per-fog log-bucket counts of the
    #   task_time latency (publish -> status-6 ack); last bucket = +Inf
    lat_sum: jax.Array  # (Fh,) f32 per-fog latency sum (seconds) — the
    #   OpenMetrics histogram `_sum` series
    lat_seen: jax.Array  # (Th,) i8 per-task counted flag (exactly-once)


def init_telemetry_state(spec: WorldSpec) -> TelemetryState:
    """The t=0 telemetry state for ``spec`` (zero-row when off)."""
    Fm, Pm, Rm = (
        spec.telemetry_fogs, spec.telemetry_phases, spec.telemetry_slots
    )
    f32, i32 = jnp.float32, jnp.int32
    return TelemetryState(
        ticks=jnp.zeros((), i32),
        defer_sum=jnp.zeros((), i32),
        q_len_sum=jnp.zeros((Fm,), f32),
        q_len_max=jnp.zeros((Fm,), i32),
        q_len_min=jnp.full((Fm,), spec.queue_capacity, i32),
        busy_ticks=jnp.zeros((Fm,), i32),
        pool_occ_sum=jnp.zeros((Fm,), f32),
        pick_hist=jnp.zeros((Fm,), f32),
        phase_work=jnp.zeros((Pm,), i32),
        res=jnp.zeros((Rm, len(RES_FIELDS)), f32),
        lat_hist=jnp.zeros(
            (spec.telemetry_hist_fogs, spec.telemetry_hist_nbins), i32
        ),
        lat_sum=jnp.zeros((spec.telemetry_hist_fogs,), f32),
        lat_seen=jnp.zeros((spec.telemetry_hist_tasks,), jnp.int8),
    )


def metrics_activity(metrics) -> jax.Array:
    """Sum of every ``Metrics`` counter: the phase-work bracket scalar.

    Within one tick every counter is non-decreasing (the ``n_deferred``
    gauge resets before the phases run), so the delta across a phase
    call is that phase's booked activity.
    """
    vals = [
        getattr(metrics, f.name) for f in dataclasses.fields(metrics)
    ]
    return jnp.sum(jnp.stack(vals))


def tick_activity(metrics, buf) -> jax.Array:
    """Activity bracket over Metrics counters AND the tick's message
    buffers (``engine.TickBuf``): phases whose work is pure message
    movement (fog arrivals queueing tasks, ack relays) book no Metrics
    counter, but every one of them books tx/rx — so the combined sum is
    the monotone-within-a-tick measure the per-phase work counters
    bracket."""
    s = metrics_activity(metrics)
    for leaf in buf:
        s = s + jnp.sum(leaf)
    return s


def accumulate_tick(
    spec: WorldSpec,
    telem: TelemetryState,
    fogs,
    learn,
    metrics,
    tick: jax.Array,
    t1: jax.Array,
    phase_work: Optional[Dict[int, jax.Array]] = None,
) -> TelemetryState:
    """Fold one finished tick into the telemetry accumulators.

    Pure function of its arguments (``fogs``/``learn``/``metrics`` ride
    in as args, never by closure — simlint R3) and an endomorphism over
    :class:`TelemetryState`, so it is scan-carry safe and ``vmap``s over
    the fleet's replica axis unchanged.  Only traced when
    ``spec.telemetry`` is on.
    """
    from ..ops.queues import NO_TASK

    f32, i32 = jnp.float32, jnp.int32
    q = fogs.q_len.astype(i32)
    if spec.fog_model == int(FogModel.POOL):
        occ = jnp.clip(
            (fogs.mips - fogs.pool_avail)
            / jnp.maximum(fogs.mips, 1e-9),
            0.0,
            1.0,
        )
        busy = fogs.pool_avail < fogs.mips
    else:
        busy = fogs.current_task != NO_TASK
        occ = busy.astype(f32)
    telem = telem.replace(
        ticks=telem.ticks + 1,
        defer_sum=telem.defer_sum + metrics.n_deferred,
        q_len_sum=telem.q_len_sum + q.astype(f32),
        q_len_max=jnp.maximum(telem.q_len_max, q),
        q_len_min=jnp.minimum(telem.q_len_min, q),
        busy_ticks=telem.busy_ticks + busy.astype(i32),
        pool_occ_sum=telem.pool_occ_sum + occ,
    )
    if spec.learn_active:
        telem = telem.replace(pick_hist=learn.pick_count)
    if phase_work:
        idxs = np.asarray(sorted(phase_work), np.int32)
        vals = jnp.stack(
            [phase_work[int(i)] for i in idxs]
        ).astype(i32)
        telem = telem.replace(
            phase_work=telem.phase_work.at[idxs].add(vals)
        )
    R = telem.res.shape[0]
    if R > 0:
        stride = max(1, -(-spec.n_ticks // R))
        slot = (tick // stride).astype(i32)
        write = (tick % stride) == 0
        row = jnp.stack(
            [
                t1.astype(f32),
                jnp.sum(q).astype(f32),
                jnp.sum(busy.astype(i32)).astype(f32),
                metrics.n_deferred.astype(f32),
                metrics.n_completed.astype(f32),
                metrics.n_dropped.astype(f32),
            ]
        )
        telem = telem.replace(
            res=telem.res.at[jnp.where(write, slot, R)].set(
                row, mode="drop"
            )
        )
    return telem


# ----------------------------------------------------------------------
# host-side readers (post-run; one fetch each)
# ----------------------------------------------------------------------

def reservoir_progress(
    spec: WorldSpec, telem: TelemetryState, ticks_done: int,
    start_row: int = 0,
) -> tuple:
    """Incremental read of the strided per-tick reservoir.

    Returns ``({field: host rows [start_row:filled]}, filled)`` where
    ``filled`` is the number of reservoir rows complete after
    ``ticks_done`` ticks (row k holds tick ``k * stride``).  This is the
    ``run_chunked`` live-streaming primitive (the PR-4 follow-up): each
    chunk boundary fetches only the rows the chunk filled, so dashboards
    see per-tick rows without waiting for run end — and without breaking
    the chunk donation discipline (the fetch completes before the next
    chunk consumes the state).
    """
    R = telem.res.shape[0]
    if R == 0 or ticks_done <= 0:
        return {f: np.zeros((0,)) for f in RES_FIELDS}, start_row
    stride = max(1, -(-spec.n_ticks // R))
    filled = min(R, -(-ticks_done // stride))
    rows = np.asarray(telem.res[start_row:filled])
    return (
        {f: rows[:, i] for i, f in enumerate(RES_FIELDS)},
        max(filled, start_row),
    )


def busy_fractions(spec: WorldSpec, final) -> Optional[np.ndarray]:
    """Per-fog busy fraction (ticks busy / ticks observed) as a host
    array, or ``None`` when ``spec.telemetry`` was off.

    The single source of truth for the value: ``recorder
    .per_module_scalars`` (the ``.sca.json`` fog rows) and the
    OpenMetrics exposition both call this, so the two outputs agree
    exactly, not merely to tolerance.
    """
    if not spec.telemetry:
        return None
    ticks = max(int(np.asarray(final.telem.ticks)), 1)
    return np.asarray(final.telem.busy_ticks, np.float64) / ticks


def telemetry_summary(spec: WorldSpec, final) -> Optional[Dict]:
    """Host-side roll-up of a finished telemetry-on run.

    Returns ``None`` when ``spec.telemetry`` was off; otherwise a dict
    of per-fog vectors (busy fraction, queue-depth mean/min/max, pool
    occupancy, pick histogram), the named per-phase work counters, and
    the reservoir as ``{field: (Rm,) array}``.
    """
    if not spec.telemetry:
        return None
    t = final.telem
    ticks = max(int(np.asarray(t.ticks)), 1)
    res = np.asarray(t.res, np.float64)
    Rm = res.shape[0]
    stride = max(1, -(-spec.n_ticks // Rm)) if Rm else 1
    n_rows = min(Rm, -(-ticks // stride))
    return {
        "ticks": ticks,
        "defer_sum": int(np.asarray(t.defer_sum)),
        "busy_frac": busy_fractions(spec, final),
        "q_len_mean": np.asarray(t.q_len_sum, np.float64) / ticks,
        "q_len_max": np.asarray(t.q_len_max, np.int64),
        "q_len_min": np.asarray(t.q_len_min, np.int64),
        "pool_occ_mean": np.asarray(t.pool_occ_sum, np.float64) / ticks,
        "pick_hist": np.asarray(t.pick_hist, np.float64),
        "phase_work": {
            name: int(np.asarray(t.phase_work[i]))
            for i, name in enumerate(PHASES)
        },
        "reservoir": {
            f: res[:n_rows, i] for i, f in enumerate(RES_FIELDS)
        },
    }
