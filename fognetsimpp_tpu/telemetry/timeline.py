"""Plane 2: task-lifecycle tracing to Chrome/Perfetto trace-event JSON.

The task table already holds every lifecycle timestamp as an absolute
event time (``state.TaskState``: ``t_create`` → ``t_at_broker`` →
``t_at_fog`` → ``t_q_enter`` → ``t_service_start`` → ``t_complete`` →
``t_ack6``), masked exactly like :mod:`fognetsimpp_tpu.runtime.signals`
does.  This exporter reconstructs those columns into the trace-event
JSON format (the ``chrome://tracing`` / Perfetto schema), so a whole
simulated run is inspectable as a zoomable timeline — the headless
analog of the reference's Tkenv animation, sibling to
``runtime/trails.py``'s SVG snapshot.

Mapping: **replica → pid, fog → tid**.  Each replica is one "process";
inside it every fog node is a "thread" carrying, per task it served, a
``task`` span (fog arrival → completion) with nested ``queued`` and
``service`` child spans; one extra ``broker`` thread (tid = n_fogs)
carries the ``publish`` uplink spans and instant markers for terminal
failures (lost / dropped / rejected / no-resource).  Timestamps are
simulated microseconds (the trace-event unit), durations clamped ≥ 0,
and only finite columns are emitted — the output round-trips through
strict ``json.loads`` with no ``NaN``/``Infinity`` tokens (the same
RFC 8259 pitfall ``recorder.spec_to_dict`` already handles).

Counter tracks (ISSUE 6): next to the spans, each fog contributes two
Perfetto counter series reconstructed from the same task columns —
``fogN queue_depth`` (a +1/−1 edge at every queue enter / service
start, cumulatively summed: the exact queue-occupancy staircase) and
``fogN busy_frac`` (service-interval overlap fraction over
:data:`BUSY_WINDOWS` equal windows of the run).  iFogSim-style
*distribution-over-time* observability in the same zoomable timeline
as the task lifecycle.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import numpy as np

from ..spec import Stage, WorldSpec
from ..state import WorldState

#: Windows the per-fog busy-fraction counter track averages over.
BUSY_WINDOWS = 24

#: Terminal stages that never reach a fog: shown as instant markers.
_FAIL_STAGES = {
    int(Stage.LOST): "lost",
    int(Stage.DROPPED): "dropped",
    int(Stage.REJECTED): "rejected",
    int(Stage.NO_RESOURCE): "no_resource",
    int(Stage.HOP_EXHAUSTED): "hop_exhausted",
}


def _us(t: np.ndarray) -> np.ndarray:
    """Seconds → microseconds, as float64 (trace-event ts unit)."""
    return np.asarray(t, np.float64) * 1e6


def _span(name, pid, tid, ts, dur, args=None) -> Dict:
    ev = {
        "name": name,
        "ph": "X",
        "pid": int(pid),
        "tid": int(tid),
        "ts": float(ts),
        "dur": float(max(dur, 0.0)),
        "cat": "task",
    }
    if args:
        ev["args"] = args
    return ev


def _counter(name: str, pid: int, ts: float, key: str, value) -> Dict:
    return {
        "name": name,
        "ph": "C",
        "pid": int(pid),
        "ts": float(ts),
        "cat": "health",
        "args": {key: float(value)},
    }


def _counter_events(
    spec: WorldSpec,
    tasks_np: Dict[str, np.ndarray],
    pid: int,
    ids: np.ndarray,
) -> List[Dict]:
    """Per-fog queue-depth and busy-fraction counter tracks.

    Reconstructed from the same (capped) task rows the span builder
    uses: queue depth is the cumulative sum of +1 edges at
    ``t_q_enter`` and −1 edges at ``t_service_start``; busy fraction is
    the service-interval overlap with :data:`BUSY_WINDOWS` equal
    windows of the observed span.  Pure post-run host work — no new
    device state.
    """
    events: List[Dict] = []
    if ids.size == 0:
        return events
    fog = tasks_np["fog"].astype(np.int64)[ids]
    qe = _us(tasks_np["t_q_enter"])[ids]
    ss = _us(tasks_np["t_service_start"])[ids]
    tc = _us(tasks_np["t_complete"])[ids]
    t_hi = spec.horizon * 1e6
    for f in range(spec.n_fogs):
        mine = fog == f
        # queue-depth staircase: +1 on queue enter, -1 on service start
        t_in = qe[mine & np.isfinite(qe)]
        t_out = ss[mine & np.isfinite(qe) & np.isfinite(ss)]
        if t_in.size:
            ts = np.concatenate([t_in, t_out])
            dv = np.concatenate(
                [np.ones_like(t_in), -np.ones_like(t_out)]
            )
            order = np.argsort(ts, kind="stable")
            depth = np.cumsum(dv[order])
            ts_s = ts[order]
            events.extend(
                _counter(
                    f"fog{f} queue_depth", pid, ts_s[i], "tasks",
                    max(depth[i], 0.0),
                )
                for i in range(len(ts_s))
            )
        # busy fraction: service-interval overlap per window
        svc = mine & np.isfinite(ss) & np.isfinite(tc)
        if not svc.any():
            continue
        s0, s1 = ss[svc], np.minimum(tc[svc], t_hi)
        edges = np.linspace(0.0, t_hi, BUSY_WINDOWS + 1)
        for w in range(BUSY_WINDOWS):
            w0, w1 = edges[w], edges[w + 1]
            if w1 <= w0:
                continue
            overlap = np.clip(
                np.minimum(s1, w1) - np.maximum(s0, w0), 0.0, None
            ).sum()
            events.append(
                _counter(
                    f"fog{f} busy_frac", pid, w0, "frac",
                    min(overlap / (w1 - w0), 1.0),
                )
            )
    return events


def _replica_events(
    spec: WorldSpec, tasks_np: Dict[str, np.ndarray], pid: int,
    max_tasks: Optional[int] = None,
) -> List[Dict]:
    F, S = spec.n_fogs, spec.max_sends_per_user
    stage = tasks_np["stage"].astype(np.int64)
    fog = tasks_np["fog"].astype(np.int64)
    used = stage != int(Stage.UNUSED)
    ids = np.nonzero(used)[0]
    if max_tasks is not None:
        ids = ids[:max_tasks]
    events: List[Dict] = []
    broker_tid = F
    t_create = _us(tasks_np["t_create"])
    t_at_broker = _us(tasks_np["t_at_broker"])
    t_at_fog = _us(tasks_np["t_at_fog"])
    t_q_enter = _us(tasks_np["t_q_enter"])
    t_service = _us(tasks_np["t_service_start"])
    t_complete = _us(tasks_np["t_complete"])
    t_ack6 = _us(tasks_np["t_ack6"])
    mips = np.asarray(tasks_np["mips_req"], np.float64)
    for i in ids:
        i = int(i)
        user = i // S
        args = {"task": i, "user": user, "mips_req": float(mips[i])}
        st = int(stage[i])
        if np.isfinite(t_create[i]) and np.isfinite(t_at_broker[i]):
            events.append(
                _span(
                    "publish", pid, broker_tid, t_create[i],
                    t_at_broker[i] - t_create[i], args,
                )
            )
        if st in _FAIL_STAGES and np.isfinite(t_create[i]):
            events.append(
                {
                    "name": _FAIL_STAGES[st],
                    "ph": "i",
                    "s": "t",
                    "pid": pid,
                    "tid": int(broker_tid),
                    "ts": float(t_create[i]),
                    "cat": "task",
                    "args": args,
                }
            )
        f = int(fog[i])
        if f < 0 or f >= F:
            continue
        if np.isfinite(t_at_fog[i]) and np.isfinite(t_complete[i]):
            events.append(
                _span(
                    f"task{i}", pid, f, t_at_fog[i],
                    t_complete[i] - t_at_fog[i], args,
                )
            )
        if np.isfinite(t_q_enter[i]) and np.isfinite(t_service[i]):
            events.append(
                _span(
                    "queued", pid, f, t_q_enter[i],
                    t_service[i] - t_q_enter[i],
                )
            )
        if np.isfinite(t_service[i]) and np.isfinite(t_complete[i]):
            events.append(
                _span(
                    "service", pid, f, t_service[i],
                    t_complete[i] - t_service[i],
                )
            )
        if np.isfinite(t_complete[i]) and np.isfinite(t_ack6[i]):
            events.append(
                _span(
                    "ack", pid, broker_tid, t_complete[i],
                    t_ack6[i] - t_complete[i], args,
                )
            )
    # per-fog queue-depth / busy-fraction counter tracks (ISSUE 6)
    events.extend(_counter_events(spec, tasks_np, pid, ids))
    # lane names: one metadata event per thread (Perfetto track labels)
    for f in range(F):
        events.append(
            {
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": f, "args": {"name": f"fog{f}"},
            }
        )
    events.append(
        {
            "name": "thread_name", "ph": "M", "pid": pid,
            "tid": int(broker_tid), "args": {"name": "broker"},
        }
    )
    events.append(
        {
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": f"replica{pid}"},
        }
    )
    return events


def _chaos_lifecycle_events(
    spec: WorldSpec, final, pid: int
) -> List[Dict]:
    """Per-fog lifecycle track (ISSUE 12): one ``fog_down`` span per
    outage on the owning fog's lane, replayed on host from the
    deterministic schedule (``chaos/faults.outage_timeline`` — the
    chaos key rides the final state, and random schedules are a pure
    function of it, so this is exact, not a reconstruction).  Empty on
    chaos-off runs: every existing trace stays byte-identical.
    """
    if not spec.chaos:
        return []
    from ..chaos.faults import outage_timeline

    events: List[Dict] = []
    for f, td, tu in outage_timeline(spec, final.chaos.key):
        events.append(
            {
                "name": "fog_down",
                "ph": "X",
                "pid": int(pid),
                "tid": int(f),
                "ts": float(td * 1e6),
                "dur": float(max(tu - td, 0.0) * 1e6),
                "cat": "chaos",
                "args": {"fog": int(f)},
            }
        )
    return events


def _tp_exchange_events(spec: WorldSpec, final, pid: int) -> List[Dict]:
    """Per-SHARD exchange-plane counter lanes (ISSUE 11).

    One dedicated "tp-exchange" process whose threads are counter
    tracks ``shard{s} exchange_occ`` — the strided per-tick
    exchange-window occupancy rows the sharded tick folded into
    ``TelemetryState.exg_occ_res``, timestamped from the matching
    reservoir rows.  Empty on non-TP (or telemetry-off) runs, so every
    existing trace is byte-identical.
    """
    from .metrics import exchange_summary

    ex = exchange_summary(spec, final)
    if ex is None or ex["occ_rows"].size == 0:
        return []
    events: List[Dict] = []
    ts = _us(ex["occ_rows_t"])
    for s in range(ex["n_shards"]):
        events.extend(
            _counter(
                f"shard{s} exchange_occ", pid, ts[i], "occ",
                ex["occ_rows"][i, s],
            )
            for i in range(len(ts))
        )
    events.append(
        {
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": "tp-exchange"},
        }
    )
    return events


def _hier_broker_events(spec: WorldSpec, final, pid: int) -> List[Dict]:
    """Per-BROKER federation lanes (hier/).

    One dedicated "hier-brokers" process whose threads are counter
    tracks ``broker{b} load`` — the strided per-tick per-broker domain
    load rows the telemetry fold keeps in
    ``TelemetryState.hier_load_res``, timestamped from the matching
    reservoir rows (the TP exchange-lane discipline).  Empty on
    single-broker (or telemetry-off) runs, so every existing trace is
    byte-identical.
    """
    from ..hier.federation import hier_summary

    hs = hier_summary(spec, final)
    if hs is None or "load_rows" not in hs or hs["load_rows"].size == 0:
        return []
    events: List[Dict] = []
    ts = _us(hs["load_rows_t"])
    for b in range(hs["n_brokers"]):
        events.extend(
            _counter(
                f"broker{b} load", pid, ts[i], "load",
                hs["load_rows"][i, b],
            )
            for i in range(len(ts))
        )
    events.append(
        {
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": "hier-brokers"},
        }
    )
    return events


def _journey_events(spec: WorldSpec, final, pid: int) -> List[Dict]:
    """Causal task-journey lanes + Perfetto FLOW chains (ISSUE 15).

    One dedicated "journeys" process: tids ``0..B-1`` are broker lanes,
    ``B..B+F-1`` fog lanes.  Every decoded ring event becomes one
    slice on the lane of the entity handling it (the broker owning the
    task for spawn/decide/migrate/re-offload, the fog for
    enqueue/service/terminals), sized to the gap until the task's next
    event — and the events of one task are joined by flow events
    (``ph`` ``s``/``t``/``f`` with ``id`` = task id + 1), so Perfetto
    draws ONE connected arrow chain following the task through crash,
    re-offload, broker→broker migration and completion across lanes.
    Unlike the post-run span reconstruction above, these events come
    from the device-resident rings, so restamped columns cannot erase
    the intermediate history.  Empty on journey-off runs: every
    existing trace stays byte-identical.

    On a TP-stamped world (``spec.tp_shards > 1``, ISSUE 19) each
    sampled task's chain renders in its OWNING shard's process
    (``journeys-shard{k}``, one pid per shard above ``pid``) — the
    per-shard lanes of the sharded journey plane; unsharded runs keep
    the single byte-identical "journeys" process.
    """
    from .journeys import (
        BROKER_SIDE_EVENTS,
        JourneyEvent,
        decode_rings,
        journey_owner_shards,
    )

    if not spec.journey_active:
        return []
    decoded = decode_rings(spec, final)
    if not decoded:
        return []
    owners = journey_owner_shards(
        spec, [d["task"] for d in decoded]
    )
    B = max(1, spec.n_brokers)
    F = spec.n_fogs
    ub = (
        np.asarray(final.hier.user_broker, np.int64)
        if spec.hier_active
        else None
    )
    mig = int(JourneyEvent.MIGRATE)
    dfr = int(JourneyEvent.DEFER)
    events: List[Dict] = []
    used_tids = set()
    for d_i, d in enumerate(decoded):
        evs = d["events"]
        if not evs:
            continue
        task = d["task"]
        pid_d = pid if owners is None else pid + owners[d_i]
        cur_b = (
            int(ub[d["user"]])
            if ub is not None and d["user"] < len(ub)
            else 0
        )
        flow_id = task + 1  # Perfetto treats id 0 as unset
        ts_all = [e["t"] * 1e6 for e in evs]  # seconds -> trace us
        for i, e in enumerate(evs):
            code = e["code"]
            if code == dfr and e["b"] == 0:
                # broker-side wait (matured publish not yet decided):
                # the slice sits on the broker the task waits at
                tid = min(max(e["a"], 0), B - 1)
            elif code == dfr:
                # fog-side wait (matured arrival not yet seated —
                # K-window / exchange overflow): the target fog's lane
                tid = B + min(max(e["a"], 0), max(F - 1, 0))
            elif code in BROKER_SIDE_EVENTS:
                if code == mig:
                    # the hop slice sits on the SRC lane; later events
                    # land on the destination broker's lane
                    tid = e["a"] if e["a"] >= 0 else cur_b
                    cur_b = min(max(e["b"], 0), B - 1)
                elif code == int(JourneyEvent.DECIDE):
                    cur_b = min(max(e["b"], 0), B - 1)
                    tid = cur_b
                else:
                    tid = cur_b
                tid = min(max(int(tid), 0), B - 1)
            else:
                tid = B + min(max(e["a"], 0), max(F - 1, 0))
            used_tids.add((int(pid_d), int(tid)))
            ts = ts_all[i]
            dur = (
                max(ts_all[i + 1] - ts, 0.0) if i + 1 < len(evs) else 0.0
            )
            args = {"task": task, "a": e["a"], "b": e["b"]}
            events.append(
                {
                    "name": e["name"],
                    "ph": "X",
                    "pid": int(pid_d),
                    "tid": int(tid),
                    "ts": float(ts),
                    "dur": float(dur),
                    "cat": "journey",
                    "args": args,
                }
            )
            # the flow chain: s (first) -> t ... -> f (last), bound to
            # the enclosing slice just emitted on the same lane/ts; a
            # single-event chain gets NO flow (an "s" with no "f" is a
            # dangling Perfetto binding)
            if len(evs) < 2:
                continue
            ph = "s" if i == 0 else ("f" if i + 1 == len(evs) else "t")
            flow = {
                "name": f"journey{task}",
                "ph": ph,
                "id": int(flow_id),
                "pid": int(pid_d),
                "tid": int(tid),
                "ts": float(ts),
                "cat": "journey",
            }
            if ph != "s":
                flow["bp"] = "e"
            events.append(flow)
    if not events:
        return []
    pids = sorted({p for p, _ in used_tids})
    for p in pids:
        for b in range(B):
            if (p, b) in used_tids:
                events.append(
                    {
                        "name": "thread_name", "ph": "M", "pid": int(p),
                        "tid": b, "args": {"name": f"broker{b}"},
                    }
                )
        for f in range(F):
            if (p, B + f) in used_tids:
                events.append(
                    {
                        "name": "thread_name", "ph": "M", "pid": int(p),
                        "tid": B + f, "args": {"name": f"fog{f}"},
                    }
                )
        events.append(
            {
                "name": "process_name", "ph": "M", "pid": int(p),
                "args": {
                    "name": (
                        "journeys" if owners is None
                        else f"journeys-shard{p - pid}"
                    )
                },
            }
        )
    return events


def build_trace(
    spec: WorldSpec, final: WorldState, max_tasks: Optional[int] = None
) -> Dict:
    """The trace-event dict for a finished run (single world or a
    replica batch: a leading replica axis on the task columns becomes
    one pid per replica)."""
    cols = {
        k: np.asarray(getattr(final.tasks, k))
        for k in (
            "stage", "fog", "mips_req", "t_create", "t_at_broker",
            "t_at_fog", "t_q_enter", "t_service_start", "t_complete",
            "t_ack6",
        )
    }
    batched = cols["stage"].ndim == 2
    n_rep = cols["stage"].shape[0] if batched else 1
    events: List[Dict] = []
    for r in range(n_rep):
        rep_cols = (
            {k: v[r] for k, v in cols.items()} if batched else cols
        )
        events.extend(
            _replica_events(spec, rep_cols, pid=r, max_tasks=max_tasks)
        )
    if not batched:
        # per-shard exchange lanes on TP runs (no-op everywhere else)
        events.extend(_tp_exchange_events(spec, final, pid=n_rep))
        # fog crash/recover lifecycle spans on chaos runs (ISSUE 12)
        events.extend(_chaos_lifecycle_events(spec, final, pid=0))
        # per-broker federation load lanes on hier runs
        events.extend(_hier_broker_events(spec, final, pid=n_rep + 1))
        # causal journey lanes + flow chains on journey runs (ISSUE 15)
        events.extend(_journey_events(spec, final, pid=n_rep + 2))
    # metadata first, then spans by (ts, -dur): a parent span sorts
    # before its children, and Perfetto/golden checks see monotone ts
    events.sort(
        key=lambda e: (
            0 if e["ph"] == "M" else 1,
            e.get("ts", 0.0),
            -e.get("dur", 0.0),
        )
    )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_trace(
    spec: WorldSpec,
    final: WorldState,
    path: str,
    max_tasks: Optional[int] = None,
) -> str:
    """Write the Perfetto trace JSON for ``final`` to ``path``."""
    trace = build_trace(spec, final, max_tasks=max_tasks)
    # compact separators: pretty-printing roughly doubles the very
    # traces the --trace-max-tasks cap exists to keep loadable
    with open(path, "w") as f:
        json.dump(trace, f, separators=(",", ":"), allow_nan=False)
    return path
