"""Causal task-journey tracing: device-resident sampled event rings.

The reference gets per-task causality for free — every OMNeT++
``cMessage`` is a live object whose hops are observable end-to-end
(iFogSim-class debuggability, arXiv:1606.02007) and FogMQ-style broker
federations make the cross-broker message journey the unit of analysis
(arXiv:1610.00620).  Our Perfetto plane reconstructs spans post-run
from the FINAL task table, so every restamping phase — the chaos
re-offload bounce, ``_phase_broker_migrate``'s ``t_at_broker`` advance,
a TP exchange defer — overwrites the intermediate history and the
rendered trace silently lies about what actually happened.

This module is the journey plane that fixes it:

* **Sampling**: ``spec.telemetry_journeys = J`` hash-selects J task
  slots from the WORLD key (:func:`journey_sample_ids` — threefry
  *folded*, never split, so enabling journeys perturbs no draw of the
  main simulation stream, the chaos-key discipline).
* **Rings**: each sampled task owns a bounded
  ``(spec.telemetry_journey_ring, 4)`` i32 event ring riding
  :class:`~fognetsimpp_tpu.telemetry.metrics.TelemetryState` in the
  scan carry (``j_ring``), with a per-slot append cursor and
  drop-OLDEST overflow (the cursor wraps; overwrites are counted in
  the ``j_dropped`` scalar) — the ring always holds the LAST R events,
  which is the flight-recorder question ("what was task 4711 doing
  when the watchdog paged").
* **Taps**: once per tick, after every phase has run (and the fused
  write set has flushed), the engine's ``_phase_journeys`` diffs each
  sampled task's packed row against the previous tick's snapshot
  (``j_prev``) and appends one packed ``(t_bits, code, a, b)`` row per
  lifecycle edge — spawn, chaos re-offload, broker→broker migration
  hop, broker decide, per-tick matured-but-unseated defer (the
  K-window / exchange-ring wait, ISSUE 19), fog enqueue, service start
  and every terminal.  Under TP the same diff runs shard-local inside
  the sharded tick (:func:`journey_tick_tp`): each shard owns the
  sampled slots falling in its row block, rings stitch back in global
  slot order, and only the scalar drop census rides the end-of-tick
  psum.
  Event times are the EXACT event-time columns of the task table
  (f32 bit patterns via ``bitcast_convert_type``), not tick-quantised;
  the per-tick diff only controls when an edge is *observed*, exactly
  the engine's own staleness contract.
* **Determinism**: :func:`journey_edges` is ONE array-module-generic
  rule set — the jitted tap calls it with ``jnp``, the host replay
  (:func:`replay_tick`) with ``numpy`` — so the device-decoded chain
  can be bit-compared against a host replay of the same schedule
  (tests/test_journeys.py drives the real step tick-by-tick and
  asserts event-for-event equality).

Everything is spec-gated with the inert-LearnState discipline: when
``spec.journey_active`` is off every journey leaf has zero rows and no
journey code is traced, so journey-off worlds are bit-exact vs the
journey-less engine on every entry point.
"""
from __future__ import annotations

import enum
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..spec import Stage, WorldSpec

#: Domain separator folded into the world key to derive the journey
#: sample (the chaos `_CHAOS_FOLD` discipline: folded, never split).
_JOURNEY_FOLD = 0x10A7

#: Columns of one packed per-task snapshot row (all i32; the time
#: columns are f32 bit patterns).  Shared by the device tap, the host
#: replay and every decoder — indices below are load-bearing.
J_COLS = (
    "stage",            # 0
    "fog",              # 1
    "broker",           # 2  hier task_broker (0 when hier off; -1 = init)
    "hops",             # 3  hier migration hop count
    "retry",            # 4  chaos re-offload count
    "t_create",         # 5
    "t_at_broker",      # 6
    "t_at_fog",         # 7
    "t_q_enter",        # 8
    "t_service_start",  # 9
    "t_complete",       # 10
)

#: f32 +inf bit pattern: the "not yet stamped" sentinel of every time
#: column (the task table never stores NaN — state.py's init note).
INF_BITS = int(np.float32(np.inf).view(np.int32))


class JourneyEvent(enum.IntEnum):
    """Lifecycle edge codes of one packed ring row.

    Operand conventions (``a``/``b`` of the ``(t_bits, code, a, b)``
    row) are documented per code; -1 means "not applicable".
    """

    SPAWN = 1          # a=user, b=send index k (slot = u*S + k)
    REOFFLOAD = 2      # chaos bounce: a=crashed fog, b=retry count
    MIGRATE = 3        # broker→broker hop: a=src broker, b=dst broker
    DECIDE = 4         # a=chosen fog, b=owning broker
    LOCAL_RUN = 5      # v1 broker-local accept: a=-1
    ENQUEUE = 6        # a=fog
    SVC_START = 7      # a=fog
    DONE = 8           # terminal: a=fog
    NO_RESOURCE = 9    # terminal: a=broker
    REJECTED = 10      # terminal: a=fog (pool reject / v1 unsendable)
    DROPPED = 11       # terminal: a=fog (queue overflow)
    LOST = 12          # terminal: uplink/link loss, a=-1
    CRASH_LOST = 13    # terminal: LOSE-mode crash, a=crashed fog
    RETRY_EXHAUST = 14  # terminal: a=crashed fog, b=retry count
    HOP_EXHAUSTED = 15  # terminal: a=broker, b=hop count
    DEFER = 16         # matured but unseated this tick (K-window / per-
    #                    user-cap / exchange-ring overflow): a=broker
    #                    (publish wait, b=0) or fog (arrival wait, b=1)


EVENT_NAMES: Dict[int, str] = {
    int(e): e.name.lower() for e in JourneyEvent
}

#: Codes that end a journey (the terminal census buckets).
TERMINAL_EVENTS = frozenset(
    int(e)
    for e in (
        JourneyEvent.DONE,
        JourneyEvent.NO_RESOURCE,
        JourneyEvent.REJECTED,
        JourneyEvent.DROPPED,
        JourneyEvent.LOST,
        JourneyEvent.CRASH_LOST,
        JourneyEvent.RETRY_EXHAUST,
        JourneyEvent.HOP_EXHAUSTED,
    )
)

#: Events handled at a broker (Perfetto broker-lane placement); the
#: rest land on the handling fog's lane.
BROKER_SIDE_EVENTS = frozenset(
    int(e)
    for e in (
        JourneyEvent.SPAWN,
        JourneyEvent.REOFFLOAD,
        JourneyEvent.MIGRATE,
        JourneyEvent.DECIDE,
        JourneyEvent.LOCAL_RUN,
        JourneyEvent.NO_RESOURCE,
        JourneyEvent.LOST,
        JourneyEvent.HOP_EXHAUSTED,
    )
)


# ----------------------------------------------------------------------
# sampling + init (zero-row when the plane is off)
# ----------------------------------------------------------------------

def journey_sample_ids(spec: WorldSpec, key: jax.Array) -> jax.Array:
    """The J sampled task ids for ``spec`` on world key ``key``.

    A deterministic hash-select: the journey stream is threefry-FOLDED
    from the world key (never split), so the selection is a pure
    function of (key, J) and the main simulation stream is untouched —
    host tooling can re-derive the sample exactly.  Sorted ascending
    for stable slot order.
    """
    jkey = jax.random.fold_in(key, _JOURNEY_FOLD)
    ids = jax.random.choice(
        jkey, spec.task_capacity, (spec.journey_slots,), replace=False
    )
    return jnp.sort(ids.astype(jnp.int32))


def _init_prev_row() -> np.ndarray:
    """The pre-first-tick snapshot row: an UNUSED task with no fog, an
    UNKNOWN owning broker (-1: `stamp_ownership` may restamp domains
    after state init, so the first tick learns the real owner from the
    live table) and every time column at +inf."""
    return np.asarray(
        [int(Stage.UNUSED), -1, -1, 0, 0] + [INF_BITS] * 6, np.int32
    )


def init_journey_leaves(
    spec: WorldSpec, key: Optional[jax.Array] = None
) -> Dict[str, jax.Array]:
    """The t=0 journey leaves for ``spec`` (zero-row when off)."""
    J, R, NC = spec.journey_slots, spec.journey_ring, len(J_COLS)
    i32 = jnp.int32
    if J:
        if key is None:
            key = jax.random.PRNGKey(0)
        j_task = journey_sample_ids(spec, key)
        j_prev = jnp.tile(jnp.asarray(_init_prev_row()), (J, 1))
    else:
        j_task = jnp.zeros((0,), i32)
        j_prev = jnp.zeros((0, NC), i32)
    return dict(
        j_task=j_task,
        j_prev=j_prev,
        j_ring=jnp.zeros((J, R, 4), i32),
        j_cursor=jnp.zeros((J,), i32),
        j_dropped=jnp.zeros((), i32),
    )


# ----------------------------------------------------------------------
# the per-tick tap (device; also reused eagerly by the host replay)
# ----------------------------------------------------------------------

def snapshot_rows(
    spec: WorldSpec, tasks, chaos, hier, ids: jax.Array
) -> jax.Array:
    """Gather the sampled tasks' packed ``(J, len(J_COLS))`` i32 rows.

    J-sized gathers only — the tap never materialises a task-capacity
    intermediate.  Time columns become exact f32 bit patterns.
    """
    i32 = jnp.int32
    J = ids.shape[0]

    def bits(col):
        return jax.lax.bitcast_convert_type(
            col[ids].astype(jnp.float32), i32
        )

    if spec.hier_active:
        brk = hier.task_broker[ids].astype(i32)
        hop = hier.hops[ids].astype(i32)
    else:
        brk = jnp.zeros((J,), i32)
        hop = jnp.zeros((J,), i32)
    if spec.chaos and chaos.retry.shape[0]:
        rty = chaos.retry[ids].astype(i32)
    else:
        rty = jnp.zeros((J,), i32)
    return jnp.stack(
        [
            tasks.stage[ids].astype(i32),
            tasks.fog[ids].astype(i32),
            brk,
            hop,
            rty,
            bits(tasks.t_create),
            bits(tasks.t_at_broker),
            bits(tasks.t_at_fog),
            bits(tasks.t_q_enter),
            bits(tasks.t_service_start),
            bits(tasks.t_complete),
        ],
        axis=1,
    )


def journey_edges(xp, prev, cur, users, sends, t1_bits):
    """Synthesise this tick's lifecycle edges from two snapshots.

    ONE rule set, generic over the array module: the jitted tap passes
    ``jnp``, the host replay passes ``numpy`` — so device and host can
    never drift (the bit-match test's backbone).  ``prev``/``cur`` are
    ``(J, len(J_COLS))`` i32; returns five ``(J, E)`` arrays
    ``(valid, code, t_bits, a, b)`` with the E=9 candidate slots in
    canonical causal order: spawn, re-offload, migrate, decide, defer,
    local, enqueue, service start, terminal.

    The DEFER edge is the exchange-plane mark (ISSUE 19): a task still
    waiting at end of tick — matured (``t_at_broker <= t1`` while
    ``PUB_INFLIGHT``, ``t_at_fog <= t1`` while ``TASK_INFLIGHT``) but
    unseated by the K-window / per-user cap / exchange ring — books one
    DEFER per waiting tick, stamped at the observing tick's end (the
    crash-edge convention).  A pure function of the end-of-tick
    snapshot, so the single-device windowed engine and the TP exchange
    ring book it identically by construction (their end-of-tick states
    bit-match).  The i32 bit compare is exact: every time column is a
    non-negative f32 whose bit pattern preserves order, and the +inf
    sentinel's bits exceed every finite ``t1``.
    """
    i32 = np.int32
    st_p, st_c = prev[:, 0], cur[:, 0]
    fog_p, fog_c = prev[:, 1], cur[:, 1]
    brk_p, brk_c = prev[:, 2], cur[:, 2]
    rty_c = cur[:, 4]
    tc, tb, tf = cur[:, 5], cur[:, 6], cur[:, 7]
    tq, ts, td = cur[:, 8], cur[:, 9], cur[:, 10]
    inf = i32(INF_BITS)
    neg1 = xp.full_like(st_c, i32(-1))
    zero = xp.zeros_like(st_c)

    def st(v):
        return i32(int(v))

    # --- edge predicates (each fires at most once per tick per task) --
    spawn = (st_p == st(Stage.UNUSED)) & (st_c != st(Stage.UNUSED))
    rty_delta = rty_c > prev[:, 4]
    reoff = rty_delta & (st_c != st(Stage.LOST))
    mig = cur[:, 3] > prev[:, 3]  # hop-count delta: exact migrate mark
    decide = (fog_c >= 0) & ((fog_c != fog_p) | (tf != prev[:, 7]))
    local = (st_c == st(Stage.LOCAL_RUN)) & (
        st_p != st(Stage.LOCAL_RUN)
    )
    enq = (tq != prev[:, 8]) & (tq != inf)
    svc = (ts != prev[:, 9]) & (ts != inf)
    # matured-but-unseated at end of tick: still waiting for a broker
    # seat (PUB_INFLIGHT past t_at_broker) or a fog arrival seat
    # (TASK_INFLIGHT past t_at_fog).  Bit-pattern <= is the engine's
    # own maturity predicate (non-negative f32s order by their bits)
    defer_b = (st_c == st(Stage.PUB_INFLIGHT)) & (tb <= t1_bits)
    defer_f = (st_c == st(Stage.TASK_INFLIGHT)) & (tf <= t1_bits)
    defer = defer_b | defer_f
    changed = st_c != st_p
    was_on_fog = (
        (st_p == st(Stage.TASK_INFLIGHT))
        | (st_p == st(Stage.QUEUED))
        | (st_p == st(Stage.RUNNING))
    )
    lost = changed & (st_c == st(Stage.LOST))
    is_done = changed & (st_c == st(Stage.DONE))
    is_nores = changed & (st_c == st(Stage.NO_RESOURCE))
    is_rej = changed & (st_c == st(Stage.REJECTED))
    is_drop = changed & (st_c == st(Stage.DROPPED))
    is_hopx = changed & (st_c == st(Stage.HOP_EXHAUSTED))
    is_retryx = lost & rty_delta
    is_crash = lost & ~rty_delta & was_on_fog
    # (plain uplink/link loss — lost & ~rty_delta & ~was_on_fog — is
    # term_code's sel default below, so it needs no mask of its own)
    term = (
        is_done | is_nores | is_rej | is_drop | is_hopx | lost
    )

    # --- terminal code / time / operand selection ---------------------
    def sel(pairs, default):
        out = default
        for mask, val in reversed(pairs):
            out = xp.where(mask, val, out)
        return out

    ev = JourneyEvent
    term_code = sel(
        [
            (is_done, i32(int(ev.DONE))),
            (is_nores, i32(int(ev.NO_RESOURCE))),
            (is_rej, i32(int(ev.REJECTED))),
            (is_drop, i32(int(ev.DROPPED))),
            (is_hopx, i32(int(ev.HOP_EXHAUSTED))),
            (is_retryx, i32(int(ev.RETRY_EXHAUST))),
            (is_crash, i32(int(ev.CRASH_LOST))),
        ],
        xp.full_like(st_c, i32(int(ev.LOST))),
    )
    tf_or_tb = xp.where(tf != inf, tf, tb)
    term_t = sel(
        [
            (is_done, td),
            (is_nores | is_hopx, tb),
            (is_rej | is_drop, tf_or_tb),
            # crash edges carry no exact time column (the sweep wiped
            # them): stamp the observing tick's end — the host replay
            # applies the identical rule
            (is_retryx | is_crash, xp.full_like(st_c, t1_bits)),
        ],
        tc,  # plain uplink/link loss: the publish creation time
    )
    term_a = sel(
        [
            (is_done | is_rej | is_drop | is_retryx | is_crash, fog_c),
            (is_nores | is_hopx, brk_c),
        ],
        neg1,
    )
    term_b = sel(
        [(is_hopx, cur[:, 3]), (is_retryx, rty_c)], zero
    )

    # defer operands: the lane the task is waiting at — (broker, b=0)
    # for the publish wait, (fog, b=1) for the arrival wait — stamped
    # at the observing tick's end like the crash edges
    t1_full = xp.full_like(st_c, t1_bits)
    defer_a = xp.where(defer_f, fog_c, brk_c)
    defer_bb = xp.where(defer_f, xp.full_like(st_c, i32(1)), zero)

    stack = lambda cols: xp.stack(cols, axis=1)  # noqa: E731
    valid = stack(
        [spawn, reoff, mig, decide, defer, local, enq, svc, term]
    )
    code = stack(
        [
            xp.full_like(st_c, i32(int(ev.SPAWN))),
            xp.full_like(st_c, i32(int(ev.REOFFLOAD))),
            xp.full_like(st_c, i32(int(ev.MIGRATE))),
            xp.full_like(st_c, i32(int(ev.DECIDE))),
            xp.full_like(st_c, i32(int(ev.DEFER))),
            xp.full_like(st_c, i32(int(ev.LOCAL_RUN))),
            xp.full_like(st_c, i32(int(ev.ENQUEUE))),
            xp.full_like(st_c, i32(int(ev.SVC_START))),
            term_code,
        ]
    )
    t_bits = stack([tc, tb, tb, tb, t1_full, tb, tq, ts, term_t])
    a = stack(
        [users, fog_p, brk_p, fog_c, defer_a, neg1, fog_c, fog_c, term_a]
    )
    b = stack(
        [sends, rty_c, brk_c, brk_c, defer_bb, zero, zero, zero, term_b]
    )
    return valid, code, t_bits, a, b


def journey_tick(
    spec: WorldSpec, telem, tasks, t1: jax.Array, chaos=None, hier=None
):
    """Fold one finished tick into the journey rings (device).

    Pure function of its arguments and a TelemetryState endomorphism —
    scan-carry safe, ``vmap``s over the fleet replica axis unchanged.
    Only traced when ``spec.journey_active``.  Appends every edge the
    snapshot diff surfaces via the established drop-scatter idiom
    (invalid candidates target row J and fall off under
    ``mode="drop"``); the cursor wraps for drop-oldest overflow, with
    overwrites counted in ``j_dropped``.
    """
    S = spec.max_sends_per_user
    ids = telem.j_task
    cur = snapshot_rows(spec, tasks, chaos, hier, ids)
    t1_bits = jax.lax.bitcast_convert_type(
        t1.astype(jnp.float32), jnp.int32
    )
    valid, code, t_bits, a, b = journey_edges(
        jnp, telem.j_prev, cur, ids // S, ids % S, t1_bits
    )
    telem, over = _append_edges(telem, cur, valid, code, t_bits, a, b)
    return telem.replace(j_dropped=telem.j_dropped + over)


def _append_edges(telem, cur, valid, code, t_bits, a, b):
    """Append one tick's edge candidates to the rings (shared by the
    single-device and TP taps).  Returns ``(telem', over)`` with
    ``j_prev``/``j_ring``/``j_cursor`` advanced and ``over`` the tick's
    drop-oldest overwrite count — the caller owns ``j_dropped`` (the
    TP tap psums ``over`` across shards before folding it in).  Sizes
    come from the leaves, not the spec: the TP tap runs under a LOCAL
    spec whose ``task_capacity`` may undercut the global slot count."""
    J, R = telem.j_task.shape[0], telem.j_ring.shape[1]
    i32 = jnp.int32
    vi = valid.astype(i32)
    # per-slot append positions: cursor + in-tick offset, ring-wrapped
    off = jnp.cumsum(vi, axis=1) - 1
    pos = (telem.j_cursor[:, None] + jnp.maximum(off, 0)) % R
    slot = jnp.where(valid, jnp.arange(J, dtype=i32)[:, None], J)
    rows = jnp.stack([t_bits, code, a, b], axis=-1).astype(i32)
    ring = telem.j_ring.at[slot, pos].set(rows, mode="drop")
    n_new = jnp.sum(vi, axis=1)
    cursor = telem.j_cursor + n_new
    # drop-oldest accounting: appends that landed on a live row
    over = jnp.sum(
        jnp.maximum(cursor - R, 0) - jnp.maximum(telem.j_cursor - R, 0)
    )
    return (
        telem.replace(j_prev=cur, j_ring=ring, j_cursor=cursor),
        over,
    )


def journey_tick_tp(
    spec_local: WorldSpec, telem, tasks, t1: jax.Array, t_off
):
    """The shard-local TP tap (ISSUE 19): one :func:`journey_tick` over
    the LOCAL task view inside the shard_map'd tick.

    Task rows are row-sharded and never change owners, so each sampled
    slot is OWNED by exactly one shard: ``telem.j_task`` carries the
    GLOBAL slot ids (the same replicated sample on every shard's local
    journey leaves), each shard diffs only the slots whose rows fall in
    its ``[t_off, t_off + task_capacity_local)`` block and holds every
    other slot's ``j_prev`` fixed, with the edge candidates explicitly
    masked to owned rows (level-triggered DEFER would otherwise re-fire
    on a frozen mid-flight snapshot).  Slot ids stay global end to end —
    the ``(user, send)`` operands and the decode gather are the
    single-device ones — and the diff itself is the SAME
    :func:`journey_edges` rule set, so the stitched per-owner rings
    bit-match the single-device tap (tests/test_tp_journeys.py).

    Returns ``(telem', over)``: ``over`` is this shard's drop-oldest
    count for the end-of-tick psum — the replicated ``j_dropped``
    scalar is NOT touched here (each shard adding its own count would
    break the replication invariant).
    """
    S = spec_local.max_sends_per_user
    T_loc = spec_local.task_capacity
    ids = telem.j_task  # GLOBAL slot ids
    loc = ids - t_off
    owned = (loc >= 0) & (loc < T_loc)
    safe = jnp.clip(loc, 0, T_loc - 1)
    cur = snapshot_rows(spec_local, tasks, None, None, safe)
    cur = jnp.where(owned[:, None], cur, telem.j_prev)
    t1_bits = jax.lax.bitcast_convert_type(
        t1.astype(jnp.float32), jnp.int32
    )
    valid, code, t_bits, a, b = journey_edges(
        jnp, telem.j_prev, cur, ids // S, ids % S, t1_bits
    )
    # ownership mask: DEFER is LEVEL-triggered (an in-flight matured
    # row re-fires every tick without a state change), so cur == prev
    # alone does not silence non-owned copies once a chunk boundary
    # re-tiles a mid-flight snapshot onto every shard — without the
    # mask each non-owner would book phantom defers into its (later
    # discarded) ring copy and leak their overflow into the psum'd
    # drop census
    valid = valid & owned[:, None]
    return _append_edges(telem, cur, valid, code, t_bits, a, b)


# ----------------------------------------------------------------------
# host replay (the determinism oracle; numpy, no tracing)
# ----------------------------------------------------------------------

def replay_tick(
    spec: WorldSpec,
    prev: np.ndarray,
    cur: np.ndarray,
    ids: np.ndarray,
    t1: float,
) -> List[List[Dict]]:
    """Host twin of one :func:`journey_tick` diff.

    ``prev``/``cur`` are host ``(J, len(J_COLS))`` i32 snapshots (e.g.
    ``np.asarray(snapshot_rows(...))`` of two consecutive tick states);
    returns, per slot, this tick's decoded events in append order —
    the SAME :func:`journey_edges` rule set the device tap traces, so
    a mismatch against the device-decoded ring is a tap bug, never a
    rule drift.
    """
    S = spec.max_sends_per_user
    ids = np.asarray(ids, np.int64)
    t1_bits = int(np.float32(t1).view(np.int32))
    valid, code, t_bits, a, b = journey_edges(
        np,
        np.asarray(prev, np.int32),
        np.asarray(cur, np.int32),
        (ids // S).astype(np.int32),
        (ids % S).astype(np.int32),
        np.int32(t1_bits),
    )
    out: List[List[Dict]] = []
    for j in range(valid.shape[0]):
        evs = []
        for e in range(valid.shape[1]):
            if valid[j, e]:
                evs.append(
                    _event_dict(
                        int(t_bits[j, e]), int(code[j, e]),
                        int(a[j, e]), int(b[j, e]),
                    )
                )
        out.append(evs)
    return out


# ----------------------------------------------------------------------
# host-side readers (post-run; one fetch each)
# ----------------------------------------------------------------------

def _bits_to_time(bits: int) -> float:
    return float(np.int32(bits).view(np.float32))


def _event_dict(t_bits: int, code: int, a: int, b: int) -> Dict:
    return {
        "t": _bits_to_time(t_bits),
        "code": int(code),
        "name": EVENT_NAMES.get(int(code), f"code{code}"),
        "a": int(a),
        "b": int(b),
    }


def decode_rings(spec: WorldSpec, final) -> List[Dict]:
    """Decode every sampled task's ring into its event list (in causal
    append order; drop-oldest wrap resolved).  One host fetch."""
    t = final.telem
    J = t.j_task.shape[0]
    if J == 0:
        return []
    ids = np.asarray(t.j_task, np.int64)
    cursor = np.asarray(t.j_cursor, np.int64)
    ring = np.asarray(t.j_ring, np.int64)
    R = ring.shape[1]
    S = spec.max_sends_per_user
    out = []
    for j in range(J):
        n = int(cursor[j])
        if n <= R:
            order = range(n)
        else:
            # cursor wrapped: the oldest retained row sits at n % R
            order = ((n + k) % R for k in range(R))
        events = [
            _event_dict(*(int(x) for x in ring[j, k])) for k in order
        ]
        out.append(
            {
                "task": int(ids[j]),
                "user": int(ids[j]) // S,
                "send": int(ids[j]) % S,
                "events_total": n,
                "dropped": max(0, n - R),
                "events": events,
            }
        )
    return out


def journey_summary(spec: WorldSpec, final) -> Optional[Dict]:
    """Host roll-up of a finished journey-on run (None when off).

    THE values every exposition publishes — the recorder's
    ``.sca.json`` ``journeys`` section, the ``fns_journey_*``
    OpenMetrics families, the Perfetto journey lanes and the
    flight-recorder bundles all read this one dict (the
    ``busy_fractions`` single-source discipline).
    """
    if not spec.journey_active:
        return None
    t = final.telem
    if t.j_task.shape[0] == 0:
        return None
    decoded = decode_rings(spec, final)
    terminal: Dict[str, int] = {}
    in_flight = 0
    untouched = 0
    for d in decoded:
        if not d["events"]:
            untouched += 1
            continue
        last = d["events"][-1]
        if last["code"] in TERMINAL_EVENTS:
            terminal[last["name"]] = terminal.get(last["name"], 0) + 1
        else:
            in_flight += 1
    return {
        "sampled": len(decoded),
        "ring": int(spec.journey_ring),
        "events_total": int(np.asarray(t.j_cursor).sum()),
        "dropped_total": int(np.asarray(t.j_dropped)),
        "terminal": dict(sorted(terminal.items())),
        "in_flight": in_flight,
        "unspawned": untouched,
        "tasks": decoded,
    }


def journey_owner_shards(spec: WorldSpec, ids) -> Optional[List[int]]:
    """Owning TP shard of each sampled GLOBAL slot id, or ``None`` on
    an unsharded world view.

    Tasks are row-sharded in contiguous blocks that never change
    owners, so ownership is arithmetic on the stamped spec:
    ``shard = slot_id // (task_capacity / tp_shards)``.  Used by the
    Perfetto per-shard journey lanes, the flight-recorder snapshot and
    (via the bundle's ``shard`` list) ``tools/postmortem.py --task``.
    """
    n = getattr(spec, "tp_shards", 0)
    if n <= 1:
        return None
    t_loc = spec.task_capacity // n
    return [int(i) // t_loc for i in np.asarray(ids, np.int64)]


def snapshot_rings(final, spec: Optional[WorldSpec] = None) -> Optional[Dict]:
    """JSON-safe raw ring snapshot for flight-recorder bundles.

    Raw ``(t_bits, code, a, b)`` rows (plain ints) plus cursors — the
    bundle stays loadable by :func:`rings_from_snapshot` without the
    spec, so ``tools/postmortem.py`` can decode a crash dump from the
    manifest alone (pre-journey bundles simply lack the key: the
    ``.get``-safe contract).  When ``spec`` is a stamped TP world view
    (``spec.tp_shards > 1``) the snapshot also records each sampled
    slot's owning shard so ``postmortem.py --task`` can name it
    stdlib-only; pre-TP bundles simply lack the ``shard`` list.
    """
    t = getattr(final, "telem", None)
    if t is None or t.j_task.shape[0] == 0:
        return None
    snap = {
        "task": [int(x) for x in np.asarray(t.j_task)],
        "cursor": [int(x) for x in np.asarray(t.j_cursor)],
        "dropped": int(np.asarray(t.j_dropped)),
        "ring": np.asarray(t.j_ring, np.int64).tolist(),
    }
    if spec is not None:
        owners = journey_owner_shards(spec, t.j_task)
        if owners is not None:
            snap["shard"] = owners
    return snap


def rings_from_snapshot(snap: Dict) -> List[Dict]:
    """Decode a :func:`snapshot_rings` bundle (postmortem's reader)."""
    out = []
    tasks = snap.get("task") or []
    cursor = snap.get("cursor") or []
    ring = snap.get("ring") or []
    for j, task in enumerate(tasks):
        n = int(cursor[j]) if j < len(cursor) else 0
        rows = ring[j] if j < len(ring) else []
        R = len(rows)
        if n <= R:
            order = range(n)
        else:
            order = ((n + k) % R for k in range(R))
        out.append(
            {
                "task": int(task),
                "events_total": n,
                "dropped": max(0, n - R) if R else n,
                "events": [
                    _event_dict(*(int(x) for x in rows[k]))
                    for k in order
                ],
            }
        )
    return out
