"""Plane 3 (host/compiler): profiler wiring + dispatch-latency stats.

Three small tools, all host-side:

* :func:`profile_trace` — a context manager around
  ``jax.profiler.trace`` (XLA/TensorBoard trace capture).  With the
  ``jax.named_scope`` annotations the engine puts on every phase, the
  captured trace attributes device time to ``phase_broker`` vs
  ``phase_fog_arrivals`` etc. instead of one opaque scan body.  Profiler
  start failures (unsupported backend, already-active session) degrade
  to a no-op with a note — profiling must never take down a run.
* :func:`measure_dispatch` — times repeated calls of an already-warm
  jitted callable (including the value fetch, i.e. the real round trip
  the tunnel charges) and returns a latency histogram: the per-chunk
  dispatch cost ``BENCHMARKS.md``'s methodology section talks about,
  measured instead of asserted.
* :func:`measure_compile` — wall-clock of ``jax.jit(fn).lower(...)
  .compile()``: the cold-compile number a driver capture reports.

``bench.py --profile`` composes all three into the benchmark JSON.
"""
from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, List, Optional, Sequence


@contextlib.contextmanager
def profile_trace(outdir: Optional[str]):
    """Wrap a block in ``jax.profiler.trace(outdir)`` when possible.

    Yields a dict with ``{"active": bool, "dir": str|None, "error":
    str|None}`` so callers can report what actually happened.
    """
    info = {"active": False, "dir": outdir, "error": None}
    if not outdir:
        yield info
        return
    try:
        import jax

        jax.profiler.start_trace(outdir)
        info["active"] = True
    except Exception as e:  # unsupported backend / nested session
        info["error"] = f"{type(e).__name__}: {e}"
        yield info
        return
    try:
        yield info
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception as e:
            info["error"] = f"{type(e).__name__}: {e}"


def latency_histogram(
    samples_s: Sequence[float],
    edges_ms: Sequence[float] = (1.0, 5.0, 20.0, 50.0, 100.0, 250.0),
) -> Dict:
    """Summary + bucket counts (ms) for a list of wall-time samples."""
    ms = sorted(s * 1e3 for s in samples_s)
    n = len(ms)
    if n == 0:
        return {"n": 0}
    q = lambda p: ms[min(n - 1, int(p * n))]
    buckets: Dict[str, int] = {}
    lo = 0.0
    for e in edges_ms:
        buckets[f"le_{e:g}ms"] = sum(1 for m in ms if lo < m <= e)
        lo = e
    buckets["gt"] = sum(1 for m in ms if m > edges_ms[-1])
    return {
        "n": n,
        "p50_ms": round(q(0.50), 3),
        "p90_ms": round(q(0.90), 3),
        "max_ms": round(ms[-1], 3),
        "buckets": buckets,
    }


def measure_dispatch(
    call: Callable[[], object], n: int = 10, warmup: int = 1
) -> Dict:
    """Latency histogram over ``n`` calls of a warm jitted callable.

    ``call`` must synchronize (fetch a value) so each sample covers the
    full dispatch + fetch round trip — the flat per-call cost the
    bench methodology pipelines around.
    """
    for _ in range(warmup):
        call()
    samples: List[float] = []
    for _ in range(n):
        t0 = time.perf_counter()
        call()
        samples.append(time.perf_counter() - t0)
    return latency_histogram(samples)


def measure_compile(fn: Callable, *args, **kwargs) -> float:
    """Seconds to lower + compile ``fn`` for the given arguments."""
    import jax

    t0 = time.perf_counter()
    jax.jit(fn).lower(*args, **kwargs).compile()
    return time.perf_counter() - t0
