"""Chaos state + fault-schedule kernels (device half) and host readers.

The reference gets node death and link churn for free from INET's
lifecycle and radio models; this module is the batched engine's analog
— a fully deterministic, jit-compatible fault source that runs *inside*
the tick loop:

* **Fog lifecycle**: per-fog crash/recover schedules.  Random outages
  are exponential MTBF/MTTR draws keyed
  ``fold_in(fold_in(chaos_key, fog), outage_index)`` — a pure function
  of (chaos key, fog, epoch), so the device carry machine
  (:func:`step_lifecycle`) and the host replay
  (:func:`outage_timeline`) consume the identical stream and can never
  disagree.  Scripted ``(fog, t_down, t_up)`` intervals
  (``spec.chaos_script``) compose on top: a fog is down while ANY
  source holds it down.
* **Link degradation**: a periodic + PRNG-burst multiplier over the
  broker->fog rows of the tick's delay cache (:func:`rtt_factor`),
  keyed on the tick index — deterministic across
  run/run_jit/run_chunked by construction.

Everything rides :class:`ChaosState` in the scan carry with the
inert-LearnState gate discipline: every array leaf is zero-row when
``spec.chaos`` is off, and the chaos key is *folded* from the world key
(never split), so the main PRNG stream is bit-identical with the
subsystem on or off.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..spec import ChaosMode, WorldSpec


def _dv(spec: WorldSpec, dyn):
    """The DynSpec view of the chaos knobs (ISSUE 13): the promoted
    operand when the engine passes one, else the host-constant fold of
    the spec's own values (bit-identical to the pre-promotion trace)."""
    if dyn is not None:
        return dyn
    from ..dynspec import dyn_of

    return dyn_of(spec)

#: Domain separator folded into the world key to derive the chaos
#: stream (so chaos_seed=0 still decorrelates from the world draws).
_CHAOS_FOLD = 0x0C4A05
#: Separator for the static per-fog RTT phase draw.
_RTT_PHASE_FOLD = 0x0B17
#: Separator for the per-tick RTT burst draws.
_RTT_BURST_FOLD = 0x0B57


@struct.dataclass
class ChaosState:
    """Carry-resident fault-injection state (one per world).

    Per-fog leaves are sized ``spec.chaos_fogs`` and the per-task retry
    column ``spec.chaos_tasks`` — the real dimensions when
    ``spec.chaos`` is on, zero rows otherwise.  The scalar counters are
    always present and stay exactly zero on inert worlds.
    """

    key: jax.Array  # chaos PRNG key (constant through the run: every
    #   draw is a fold_in of it, nothing ever consumes it)
    next_down: jax.Array  # (Fc,) f32 next scheduled random crash time
    #   (+inf = no random crash pending)
    next_up: jax.Array  # (Fc,) f32 scheduled random recover time
    #   (+inf = the fog is not in a random outage)
    epoch: jax.Array  # (Fc,) i32 outage index — keys the per-outage
    #   (gap, duration) draws, incremented at each random recovery
    down_ticks: jax.Array  # (Fc,) i32 cumulative ticks spent down
    rtt_phase: jax.Array  # (Fc,) f32 per-fog phase offset of the
    #   periodic RTT degradation term (static draw at init)
    retry: jax.Array  # (Tc,) i8 per-task re-offload count (REOFFLOAD)
    n_crashes: jax.Array  # () i32 crash edges observed
    n_recovers: jax.Array  # () i32 recover edges observed
    n_lost_crash: jax.Array  # () i32 tasks lost to a crash (LOSE mode)
    n_reoffloaded: jax.Array  # () i32 tasks bounced back to the broker
    n_retry_exhausted: jax.Array  # () i32 tasks lost after the retry
    #   budget ran out (REOFFLOAD mode)


def _chaos_key(spec: WorldSpec, key: jax.Array) -> jax.Array:
    """The chaos PRNG stream for ``spec`` on world key ``key``.

    Folded (not split) from the world key: enabling chaos consumes
    nothing from the main stream, which is what keeps the chaos-off
    bit-exactness gate trivially true.
    """
    return jax.random.fold_in(
        jax.random.fold_in(key, _CHAOS_FOLD), spec.chaos_seed
    )


def _outage_draws(
    spec: WorldSpec, key: jax.Array, epoch: jax.Array, dyn=None
) -> Tuple[jax.Array, jax.Array]:
    """(gap, duration) exponential draws for each fog's ``epoch``-th
    outage, both clamped to >= dt so every outage spans at least one
    tick (which statically rules out same-tick crash->recover blips —
    see :func:`step_lifecycle`'s ordering argument).

    MTBF/MTTR come from the DynSpec operand when the caller promotes
    them (the draws' UNIFORMS are keyed on (key, fog, epoch) only, so a
    re-configured MTBF rescales the same stream — exactly the host
    replay's contract)."""
    F = epoch.shape[0]
    dv = _dv(spec, dyn)

    def one(f, e):
        k = jax.random.fold_in(jax.random.fold_in(key, f), e)
        return jax.random.uniform(
            k, (2,), jnp.float32, minval=1e-7, maxval=1.0
        )

    u = jax.vmap(one)(jnp.arange(F, dtype=jnp.int32), epoch)  # (F, 2)
    dt = np.float32(spec.dt)
    gap = jnp.maximum(-dv.chaos_mtbf_s * jnp.log(u[:, 0]), dt)
    dur = jnp.maximum(-dv.chaos_mttr_s * jnp.log(u[:, 1]), dt)
    return gap, dur


def init_chaos_state(
    spec: WorldSpec, key: Optional[jax.Array] = None
) -> ChaosState:
    """The t=0 chaos state for ``spec`` (inert zero-row when off)."""
    F, Tc = spec.chaos_fogs, spec.chaos_tasks
    f32, i32 = jnp.float32, jnp.int32
    if spec.chaos:
        if key is None:
            key = jax.random.PRNGKey(0)
        ck = _chaos_key(spec, key)
        epoch0 = jnp.zeros((F,), i32)
        if spec.chaos_mtbf_s > 0:
            gap0, _ = _outage_draws(spec, ck, epoch0)
            next_down = gap0
        else:
            next_down = jnp.full((F,), jnp.inf, f32)
        rtt_phase = jax.random.uniform(
            jax.random.fold_in(ck, _RTT_PHASE_FOLD), (F,), f32,
            minval=0.0, maxval=2.0 * np.pi,
        )
    else:
        ck = jax.random.PRNGKey(0)
        next_down = jnp.zeros((F,), f32)
        rtt_phase = jnp.zeros((F,), f32)
        epoch0 = jnp.zeros((F,), i32)
    return ChaosState(
        key=ck,
        next_down=next_down,
        next_up=jnp.full((F,), jnp.inf, f32) if spec.chaos
        else jnp.zeros((F,), f32),
        epoch=epoch0,
        down_ticks=jnp.zeros((F,), i32),
        rtt_phase=rtt_phase,
        retry=jnp.zeros((Tc,), jnp.int8),
        n_crashes=jnp.zeros((), i32),
        n_recovers=jnp.zeros((), i32),
        n_lost_crash=jnp.zeros((), i32),
        n_reoffloaded=jnp.zeros((), i32),
        n_retry_exhausted=jnp.zeros((), i32),
    )


def refold_chaos_state(
    spec: WorldSpec, ch: ChaosState, new_key: jax.Array
) -> ChaosState:
    """Re-key a t=0 chaos state onto a new chaos stream key.

    Re-derives every key-dependent init draw (the first crash gaps and
    the per-fog RTT phases) from ``new_key`` so the whole schedule —
    including epoch 0 — is a pure function of the new stream, exactly
    what :func:`outage_timeline` replays.  The per-replica fan-out
    (``parallel/replicas.replicate_state``) vmaps this over
    ``fold_in(chaos_key, replica)`` keys; a state whose counters have
    already advanced must not be refolded (asserting that would need a
    device fetch, so the contract is documented, not checked).
    """
    if not spec.chaos:
        return ch
    epoch0 = jnp.zeros_like(ch.epoch)
    if spec.chaos_mtbf_s > 0:
        gap0, _ = _outage_draws(spec, new_key, epoch0)
        next_down = gap0
    else:
        next_down = jnp.full_like(ch.next_down, jnp.inf)
    F = ch.rtt_phase.shape[0]
    rtt_phase = jax.random.uniform(
        jax.random.fold_in(new_key, _RTT_PHASE_FOLD), (F,), jnp.float32,
        minval=0.0, maxval=2.0 * np.pi,
    )
    return ch.replace(
        key=new_key,
        next_down=next_down,
        next_up=jnp.full_like(ch.next_up, jnp.inf),
        rtt_phase=rtt_phase,
    )


def step_lifecycle(
    spec: WorldSpec,
    ch: ChaosState,
    up_prev: jax.Array,  # (F,) bool — fog liveness entering this tick
    t0: jax.Array,
    t1: jax.Array,
    dyn=None,  # Optional[DynSpec]: promoted MTBF/MTTR operands
):
    """Advance the outage schedules one tick.

    Returns ``(ch', up_new, crashed, recovered, crash_t, recover_t)``
    where ``crashed``/``recovered`` are this tick's edges vs
    ``up_prev`` and ``crash_t``/``recover_t`` are per-fog event times
    clamped into ``[t0, t1]``.

    Random-machine ordering per tick: recoveries fire first, then crash
    triggers.  Because every draw is clamped >= dt, a fog that recovers
    this tick has its next crash at ``next_up + gap >= t0 + dt = t1``
    (not < t1), and a fog that crashes has ``next_up = next_down + dur
    >= t1`` — so neither a crash nor a recovery can re-fire within the
    same tick, and every outage is visible to at least one tick's
    dispatch masking.
    """
    F = spec.n_fogs
    f32, i32 = jnp.float32, jnp.int32
    next_down, next_up, epoch = ch.next_down, ch.next_up, ch.epoch
    inf = jnp.inf

    if spec.chaos_mtbf_s > 0:
        _, dur_e = _outage_draws(spec, ch.key, epoch, dyn)
        gap_next, _ = _outage_draws(spec, ch.key, epoch + 1, dyn)
        rand_down = jnp.isfinite(next_up)
        # 1. recoveries
        rec = rand_down & (next_up < t1)
        rand_rec_t = jnp.where(rec, next_up, inf)
        epoch = jnp.where(rec, epoch + 1, epoch)
        next_down = jnp.where(rec, next_up + gap_next, next_down)
        next_up = jnp.where(rec, inf, next_up)
        rand_down = rand_down & ~rec
        # 2. crash triggers
        crash = ~rand_down & (next_down < t1)
        rand_crash_t = jnp.where(crash, next_down, inf)
        next_up = jnp.where(crash, next_down + dur_e, next_up)
        next_down = jnp.where(crash, inf, next_down)
        rand_down = rand_down | crash
    else:
        rand_down = jnp.zeros((F,), bool)
        rand_crash_t = jnp.full((F,), inf, f32)
        rand_rec_t = jnp.full((F,), inf, f32)

    # scripted intervals: down for the tick ending at t1 iff
    # t_down < t1 <= t_up (static entries, traced clock)
    scripted_down = jnp.zeros((F,), bool)
    s_crash_t = jnp.full((F,), inf, f32)
    s_rec_t = jnp.full((F,), -inf, f32)
    idx = jnp.arange(F, dtype=i32)
    for f, td, tu in spec.chaos_script:
        onehot = idx == int(f)
        td = np.float32(td)
        tu = np.float32(tu)
        active = (td < t1) & (tu >= t1)
        scripted_down = scripted_down | (onehot & active)
        started = (td >= t0) & (td < t1)
        s_crash_t = jnp.where(
            onehot & started, jnp.minimum(s_crash_t, td), s_crash_t
        )
        ended = (tu >= t0) & (tu < t1)
        s_rec_t = jnp.where(
            onehot & ended, jnp.maximum(s_rec_t, tu), s_rec_t
        )

    up_new = ~(rand_down | scripted_down)
    crashed = up_prev & ~up_new
    recovered = ~up_prev & up_new
    crash_t = jnp.clip(jnp.minimum(rand_crash_t, s_crash_t), t0, t1)
    # a fog recovers when its LAST holding source releases it
    recover_t = jnp.clip(
        jnp.maximum(jnp.where(jnp.isfinite(rand_rec_t), rand_rec_t,
                              -inf), s_rec_t),
        t0, t1,
    )
    ch = ch.replace(
        next_down=next_down,
        next_up=next_up,
        epoch=epoch,
        down_ticks=ch.down_ticks + (~up_new).astype(i32),
        n_crashes=ch.n_crashes + jnp.sum(crashed.astype(i32)),
        n_recovers=ch.n_recovers + jnp.sum(recovered.astype(i32)),
    )
    return ch, up_new, crashed, recovered, crash_t, recover_t


def rtt_factor(
    spec: WorldSpec, ch: ChaosState, tick: jax.Array, t0: jax.Array,
    dyn=None,
) -> jax.Array:
    """(F,) multiplier for the broker->fog rows of the delay cache.

    Periodic term: ``1 + amp * (1 + sin(2*pi*t/period + phase_f)) / 2``
    — each fog's phase offset is a static draw from the chaos stream,
    so congestion waves do not hit every fog in lockstep.  Burst term:
    per-fog Bernoulli(``chaos_rtt_burst_prob``) draws keyed on the TICK
    INDEX (``fold_in(chaos_key, tick)``), multiplying by
    ``chaos_rtt_burst_mult`` — a pure function of (key, tick), so
    run/run_jit/run_chunked see the identical burst sequence.
    """
    F = spec.n_fogs
    dv = _dv(spec, dyn)
    fac = jnp.ones((F,), jnp.float32)
    if spec.chaos_rtt_amp > 0:
        fac = fac * (
            1.0
            + dv.chaos_rtt_amp
            * 0.5
            * (1.0 + jnp.sin(dv.chaos_rtt_omega * t0 + ch.rtt_phase))
        )
    if spec.chaos_rtt_burst_prob > 0:
        kb = jax.random.fold_in(
            jax.random.fold_in(ch.key, _RTT_BURST_FOLD),
            tick.astype(jnp.int32),
        )
        burst = jax.random.uniform(kb, (F,)) < dv.chaos_rtt_burst_prob
        fac = jnp.where(burst, fac * dv.chaos_rtt_burst_mult, fac)
    return fac


# ----------------------------------------------------------------------
# host-side readers (post-run / per chunk; one fetch each)
# ----------------------------------------------------------------------

def outage_timeline(
    spec: WorldSpec,
    chaos_key,
    horizon: Optional[float] = None,
    max_outages_per_fog: int = 10_000,
) -> List[Tuple[int, float, float]]:
    """Replay the full ``(fog, t_down, t_up)`` outage list on host.

    Random schedules are a pure function of (chaos key, fog, epoch) —
    the same ``fold_in`` draws the device carry machine consumes, so
    this replay is exact, not a reconstruction.  Scripted intervals are
    appended verbatim (clipped to the horizon).  Feeds the Perfetto
    fog-lifecycle track (``telemetry/timeline.py``) and schedule-replay
    tests.  ``chaos_key`` is ``final.chaos.key`` (constant through the
    run) or anything array-like holding it.
    """
    hz = float(spec.horizon if horizon is None else horizon)
    out: List[Tuple[int, float, float]] = []
    for f, td, tu in spec.chaos_script:
        if float(td) < hz:
            out.append((int(f), float(td), min(float(tu), hz)))
    if spec.chaos and spec.chaos_mtbf_s > 0:
        key = jnp.asarray(np.asarray(chaos_key))
        dt32 = np.float32(spec.dt)
        mtbf32 = np.float32(spec.chaos_mtbf_s)
        mttr32 = np.float32(spec.chaos_mttr_s)
        # draws fetched in epoch CHUNKS (one vmapped dispatch per 64
        # epochs per fog instead of one per outage — a churny wide
        # world produces thousands) — same fold order as the device
        chunk = 64
        draw_chunk = jax.jit(
            jax.vmap(
                lambda k, e: jax.random.uniform(
                    jax.random.fold_in(k, e), (2,), jnp.float32,
                    minval=1e-7, maxval=1.0,
                ),
                in_axes=(None, 0),
            )
        )
        for f in range(spec.n_fogs):
            kf = jax.random.fold_in(key, f)
            # f32 accumulation MIRRORS the device carry machine
            # (next_down = next_up + gap etc. are f32 adds): a float64
            # host sum could place an edge in a different tick
            t = np.float32(0.0)
            done = False
            for e0 in range(0, max_outages_per_fog, chunk):
                u = np.asarray(draw_chunk(
                    kf, jnp.arange(e0, e0 + chunk, dtype=jnp.int32)
                ))
                gaps = np.maximum(-mtbf32 * np.log(u[:, 0]), dt32)
                durs = np.maximum(-mttr32 * np.log(u[:, 1]), dt32)
                for i in range(chunk):
                    down = np.float32(t + gaps[i])
                    if float(down) >= hz:
                        done = True
                        break
                    up = np.float32(down + durs[i])
                    out.append((f, float(down), min(float(up), hz)))
                    t = up
                if done:
                    break
    out.sort(key=lambda x: (x[0], x[1]))
    return out


def chaos_summary(spec: WorldSpec, final) -> Optional[dict]:
    """Host roll-up of a finished chaos run (None when the subsystem is
    off).  THE values every exposition publishes — the recorder's
    ``.sca.json`` chaos section, the ``fns_chaos_*`` OpenMetrics
    families and the flight-recorder manifests all read this one dict
    (the ``busy_fractions`` single-source discipline)."""
    if not spec.chaos:
        return None
    ch = final.chaos
    return {
        "mode": ChaosMode(spec.chaos_mode).name.lower(),
        "crashes": int(np.asarray(ch.n_crashes)),
        "recovers": int(np.asarray(ch.n_recovers)),
        "lost_crash": int(np.asarray(ch.n_lost_crash)),
        "reoffloaded": int(np.asarray(ch.n_reoffloaded)),
        "retry_exhausted": int(np.asarray(ch.n_retry_exhausted)),
        # plain ints: every consumer JSON-serializes this dict verbatim
        "down_ticks": [int(x) for x in np.asarray(ch.down_ticks)],
    }


def chaos_counters(final) -> dict:
    """Tiny per-chunk counter fetch for the live health plane (the
    flight-recorder ``note_chunk`` extra): five scalars, no per-fog or
    per-task leaves — safe at any serving cadence."""
    ch = final.chaos
    return {
        "crashes": int(np.asarray(ch.n_crashes)),
        "recovers": int(np.asarray(ch.n_recovers)),
        "lost_crash": int(np.asarray(ch.n_lost_crash)),
        "reoffloaded": int(np.asarray(ch.n_reoffloaded)),
        "retry_exhausted": int(np.asarray(ch.n_retry_exhausted)),
    }
