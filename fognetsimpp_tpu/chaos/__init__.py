"""Deterministic fault injection: fog crash/recover lifecycle, in-flight
task loss / re-offload, and broker->fog link degradation (ISSUE 12).

``faults.py`` owns the carry-resident :class:`ChaosState`, the PRNG-keyed
outage schedule stepping, the RTT degradation factors and the host-side
readers (schedule replay, summary roll-up); ``profiles.py`` owns the CLI
profile catalogue and the scripted-schedule parser.  The engine phase
that applies all of it lives in ``core/engine._phase_chaos`` — the same
split as ``learn/`` (state + kernels here, tick wiring in the engine).
"""
from .faults import (  # noqa: F401
    ChaosState,
    chaos_counters,
    chaos_summary,
    init_chaos_state,
    outage_timeline,
    rtt_factor,
    step_lifecycle,
)
from .profiles import PROFILES, chaos_config_lines, parse_script  # noqa: F401
