"""Named chaos profiles (the ``--chaos <profile>`` CLI surface) and the
scripted-schedule parser shared by the CLI and the config tier.

A profile is a dict of ``WorldSpec`` chaos-field overrides; the CLI
turns it into ``spec.*`` config lines (:func:`chaos_config_lines`) so
profiles compose with every other config tier (``--set`` overrides win,
first-match semantics of ``config/ini.py``).  An unknown profile name is
ONE actionable ValueError listing the catalogue — the ``--policy``
unknown-name convention.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Optional, Sequence, Tuple

from ..spec import ChaosMode

#: The profile catalogue.  MTBF/MTTR are simulated seconds; committed
#: horizons are a few seconds, so these produce several outages per fog
#: per run without flat-lining the world.
PROFILES: Dict[str, Dict] = {
    # crash/recover churn, conservative: tasks bounce back and retry
    "light": dict(
        chaos=True, chaos_mode=int(ChaosMode.REOFFLOAD),
        chaos_mtbf_s=2.0, chaos_mttr_s=0.2, chaos_max_retries=4,
    ),
    # heavy churn, still lossless while any fog stays up
    "heavy": dict(
        chaos=True, chaos_mode=int(ChaosMode.REOFFLOAD),
        chaos_mtbf_s=0.5, chaos_mttr_s=0.25, chaos_max_retries=4,
    ),
    # hard failures: in-flight work on a crashed fog is lost
    "flaky": dict(
        chaos=True, chaos_mode=int(ChaosMode.LOSE),
        chaos_mtbf_s=0.5, chaos_mttr_s=0.15,
    ),
    # links only: periodic + bursty broker->fog RTT degradation, no
    # crashes — staleness without loss
    "degraded": dict(
        chaos=True, chaos_rtt_amp=1.0, chaos_rtt_period_s=0.5,
        chaos_rtt_burst_prob=0.05, chaos_rtt_burst_mult=5.0,
    ),
    # everything at once: churn + degradation (the hostile-world bench)
    "hostile": dict(
        chaos=True, chaos_mode=int(ChaosMode.REOFFLOAD),
        chaos_mtbf_s=0.5, chaos_mttr_s=0.2, chaos_max_retries=4,
        chaos_rtt_amp=0.5, chaos_rtt_period_s=0.5,
        chaos_rtt_burst_prob=0.02, chaos_rtt_burst_mult=4.0,
    ),
    # the master gate alone: scripted schedules / --set knobs drive it
    "scripted": dict(chaos=True),
}


def resolve_profile(name: str) -> Dict:
    """Profile dict for ``name`` — unknown names are one actionable
    line listing the catalogue, never a traceback."""
    key = str(name).strip().lower()
    if key not in PROFILES:
        raise ValueError(
            f"unknown chaos profile {name!r} "
            f"(have {', '.join(sorted(PROFILES))})"
        )
    return dict(PROFILES[key])


def parse_script(value) -> Tuple[Tuple[int, float, float], ...]:
    """Normalise a scripted-outage schedule to the spec's tuple form.

    Accepts the spec tuple itself, any sequence of (fog, t_down, t_up)
    triples (e.g. parsed JSON lists), or the compact string form
    ``"fog:t_down:t_up;fog:t_down:t_up"`` the config tier carries
    (ini values are scalars, so the schedule travels as one string).
    Malformed input raises one actionable ValueError.
    """
    if isinstance(value, str):
        entries = []
        for part in value.split(";"):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            if len(bits) != 3:
                raise ValueError(
                    f"chaos script entry {part!r} is not "
                    "'fog:t_down:t_up'"
                )
            entries.append(bits)
        value = entries
    out = []
    for ent in value:
        if not isinstance(ent, Sequence) or len(ent) != 3:
            raise ValueError(
                f"chaos script entries are (fog, t_down, t_up) triples, "
                f"got {ent!r}"
            )
        f, td, tu = ent
        try:
            out.append((int(f), float(td), float(tu)))
        except (TypeError, ValueError):
            raise ValueError(
                f"chaos script entry {ent!r} needs an int fog index and "
                "float down/up times"
            ) from None
    return tuple(out)


def load_script_file(path: str) -> Tuple[Tuple[int, float, float], ...]:
    """Load a scripted schedule from a JSON file (a list of
    ``[fog, t_down, t_up]`` triples) or the compact ``fog:td:tu;...``
    text form.  One actionable ValueError on anything else."""
    if not os.path.exists(path):
        raise ValueError(f"chaos script file not found: {path}")
    with open(path) as f:
        text = f.read()
    try:
        data = json.loads(text)
    except json.JSONDecodeError:
        data = text.strip()
    return parse_script(data)


def script_to_str(script) -> str:
    """The compact one-string encoding config lines carry."""
    return ";".join(f"{int(f)}:{td:g}:{tu:g}" for f, td, tu in script)


def chaos_config_lines(
    profile: str,
    seed: Optional[int] = None,
    mode: Optional[str] = None,
    script: Optional[Sequence] = None,
) -> list:
    """``spec.* = value`` config lines for a profile (+ overrides).

    The CLI prepends these BELOW explicit ``--set`` lines, so the
    first-match-wins config semantics let users refine any profile knob.
    """
    over = resolve_profile(profile)
    if seed is not None:
        over["chaos_seed"] = int(seed)
    if mode is not None:
        m = str(mode).strip().lower()
        try:
            over["chaos_mode"] = int(ChaosMode[m.upper()])
        except KeyError:
            raise ValueError(
                f"unknown chaos mode {mode!r} (have "
                + ", ".join(x.name.lower() for x in ChaosMode)
                + ")"
            ) from None
    lines = [f"spec.{k} = {str(v).lower() if isinstance(v, bool) else v}"
             for k, v in over.items()]
    if script:
        lines.append(
            f"spec.chaos_script = {script_to_str(parse_script(script))}"
        )
    return lines
