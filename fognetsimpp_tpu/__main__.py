"""Launcher CLI: the ``simulations/run`` analog.

The reference launches ``../src/fognetsim -n .:../src <ini>``
(``simulations/run:1-4``); here::

    python -m fognetsimpp_tpu --config run.ini
    python -m fognetsimpp_tpu --scenario wireless5 --set spec.horizon=30 \
        --out results/

builds the world from the config tier (:mod:`fognetsimpp_tpu.config.ini`),
runs the jitted scan, persists ``.sca.json``/``.vec.npz`` results
(:mod:`fognetsimpp_tpu.runtime.recorder`), and prints a one-line JSON
summary.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time


def main(argv=None) -> int:
    # spec-only import (no jax): the help text enumerates the live policy
    # catalogue from the enum instead of hardcoding a count that rots
    from .spec import (
        ARGMIN_FAMILY,
        LEARNED_POLICIES,
        Policy,
        policy_from_name,
    )

    _policy_catalogue = ", ".join(
        f"{p.name.lower()}={int(p)}" for p in Policy
    )
    ap = argparse.ArgumentParser(
        prog="python -m fognetsimpp_tpu",
        description="TPU-native fog-computing simulator (FogNetSim++ capability set)",
    )
    ap.add_argument("--config", "-c", help="ini-style config file")
    ap.add_argument("--scenario", "-s", help="scenario builder name")
    ap.add_argument(
        "--policy", "-p", default=None, metavar="NAME|ID",
        help="scheduling policy by name or id (shorthand for "
        f"scenario.policy): {_policy_catalogue}",
    )
    ap.add_argument(
        "--set", action="append", default=[], metavar="KEY=VALUE",
        help="config override (e.g. spec.horizon=2.0, fog.0.mips=4000); "
        "repeatable; takes precedence over --config",
    )
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--out", "-o", default=None, help="results directory")
    ap.add_argument("--run-id", default=None,
                    help="defaults to config output.run_id, else General-0")
    ap.add_argument("--ticks", action="store_true",
                    help="record per-tick series vectors")
    ap.add_argument("--telemetry", action="store_true",
                    help="carry device-resident telemetry (per-fog busy "
                    "fractions, queue depths, per-phase work counters, a "
                    "bounded per-tick reservoir) through the scan; "
                    "shorthand for spec.telemetry=true — adds the "
                    "per-fog gauges to .sca.json and the OpenMetrics "
                    "output")
    ap.add_argument("--hist", action="store_true",
                    help="carry the device-resident streaming latency "
                    "histogram (per-fog log buckets of the task_time "
                    "signal) through the scan; shorthand for "
                    "spec.telemetry_hist=true (implies --telemetry) — "
                    "adds '# TYPE histogram' series and p50/p95/p99 "
                    "quantile gauges to the OpenMetrics output and "
                    "lat_* rows to .sca.json")
    ap.add_argument("--journeys", type=int, metavar="N", default=None,
                    help="sample N task slots into device-resident "
                    "journey event rings (telemetry/journeys.py): every "
                    "lifecycle edge of a sampled task — spawn, decide, "
                    "chaos re-offload, broker migration hop, enqueue, "
                    "service, terminal — is appended on device and "
                    "decoded into .sca.json, fns_journey_* families and "
                    "Perfetto flow chains in --trace-out; shorthand for "
                    "spec.telemetry_journeys=N (needs --telemetry)")
    ap.add_argument("--serve", type=int, metavar="PORT", default=None,
                    help="live health plane (telemetry/live.py): run "
                    "the horizon in chunks behind an OpenMetrics pull "
                    "endpoint (GET /metrics, GET /healthz) with an "
                    "EWMA z-score watchdog on queue depth / drop rate "
                    "/ busy fraction; 0 binds an ephemeral port; "
                    "implies --telemetry")
    ap.add_argument("--serve-chunk", type=int, metavar="N", default=1000,
                    help="ticks per serving chunk (default 1000): the "
                    "scrape/watchdog refresh granularity")
    ap.add_argument("--ingest", type=int, nargs="?", const=1024,
                    default=None, metavar="CAP",
                    help="open the live ingestion door under --serve "
                    "(twin/): POST /ingest + in-process arrivals land "
                    "at chunk boundaries through the compiled "
                    "injector; CAP bounds the drop-counted queue "
                    "(default 1024); implies spec.ingest")
    ap.add_argument("--ingest-batch", type=int, metavar="B", default=None,
                    help="max arrivals injected per chunk boundary "
                    "(spec.ingest_batch, default 64)")
    ap.add_argument("--arrival-log", metavar="JSON", default=None,
                    help="write the session's recorded arrival log on "
                    "exit (the replayable input record)")
    ap.add_argument("--replay-arrivals", metavar="JSON", default=None,
                    help="re-inject a recorded arrival log instead of "
                    "serving the live queue: the session reproduces "
                    "the original chunk state hashes bit-exactly")
    ap.add_argument("--whatif", metavar="GRID", default=None,
                    help="answer a promoted-knob grid from the final "
                    "carry, e.g. 'uplink_loss_prob=0.05,0.1 "
                    "ticks=400': K retunings forked from current "
                    "state, H ticks ahead, one vmapped program; one "
                    "JSON line per run")
    ap.add_argument("--tenants", type=int, metavar="N", default=None,
                    help="multiplex N tenant sessions of the scenario "
                    "(seeds seed..seed+N-1) behind one endpoint: "
                    "round-robin chunks over the shared bucketed "
                    "program, per-tenant /t/<label>/metrics|healthz|"
                    "ingest|whatif routing; needs --serve")
    ap.add_argument("--tenant-cap", type=int, metavar="M", default=None,
                    help="front-door admission bound (default: N); "
                    "admitting past it is the one-line [TWIN-CAP] "
                    "rejection")
    ap.add_argument("--slo", type=float, metavar="MS", default=None,
                    help="task-latency SLO in milliseconds: breaches "
                    "derive from the streaming histogram (implies "
                    "--hist) and trip the flight recorder under "
                    "--serve")
    ap.add_argument("--postmortem", metavar="DIR", default=None,
                    help="flight-recorder dump directory: on NaN, SLO "
                    "breach, watchdog anomaly or crash the serving "
                    "loop writes a post-mortem bundle here (inspect "
                    "with tools/postmortem.py)")
    ap.add_argument("--trace-out", metavar="JSON", default=None,
                    help="export the run's task-lifecycle spans as "
                    "Chrome/Perfetto trace-event JSON to this path "
                    "(replica→pid, fog→tid; open in ui.perfetto.dev)")
    ap.add_argument("--trace-max-tasks", type=int, metavar="N",
                    default=100_000,
                    help="cap on tasks per replica in the --trace-out "
                    "export (default 100000: Perfetto chokes on "
                    "multi-hundred-MB traces; 0 = unbounded)")
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="capture a jax.profiler trace of the run into "
                    "DIR (phases show up as named scopes; open with "
                    "TensorBoard or Perfetto)")
    ap.add_argument("--trails", metavar="SVG", default=None,
                    help="render movement/communication trails to this "
                    "SVG (the Tkenv-animation analog; implies --ticks)")
    ap.add_argument("--progress", type=int, metavar="N", default=None,
                    help="run in N-tick chunks, printing a progress line "
                    "per chunk (the Cmdenv status-line analog; excludes "
                    "--ticks)")
    ap.add_argument("--tp", type=int, default=None, metavar="N",
                    help="task-table tensor parallelism: shard ONE "
                    "world's user/task axis over an N-device mesh "
                    "(parallel/taskshard.run_tp_sharded: shard_map "
                    "megaphases, explicit broker<->fog collectives, "
                    "ring arrival exchange); dense-broker FIFO worlds "
                    "only — composes with --policy/--telemetry/--hist "
                    "and --serve (the sharded health plane: per-shard "
                    "fns_tp_exchange_* gauges + defer-rate watchdog); "
                    "non-divisible populations are padded with inert "
                    "users")
    ap.add_argument("--tp-window", type=int, metavar="K", default=None,
                    help="per-shard TP arrival-exchange window (slots "
                    "per shard per tick; default: the full candidate "
                    "list, which never defers).  Bounded windows defer "
                    "overflow arrivals a tick (Metrics.n_deferred, the "
                    "fns_tp_exchange_* gauges, and — under --serve — "
                    "the defer-rate watchdog make it observable).  "
                    "Applies to NO-WINDOW specs only: a spec with its "
                    "own scenario.arrival_window already runs the "
                    "distributed K-window selection (the hop-pruned "
                    "top-K exchange ring, bit-exact vs single-device) "
                    "and rejects --tp-window")
    ap.add_argument("--replicas", type=int, default=None, metavar="R",
                    help="Monte-Carlo fleet: advance R replica worlds "
                    "(per-replica PRNG streams) sharded over the device "
                    "mesh in one jitted scan (parallel/fleet.py); R must "
                    "divide evenly over the mesh")
    ap.add_argument("--mesh", type=int, default=None, metavar="D",
                    help="devices in the replica mesh (default: all "
                    "visible devices); implies --replicas D when "
                    "--replicas is omitted")
    ap.add_argument("--brokers", type=int, metavar="B", default=None,
                    help="federated multi-broker hierarchy (hier/): "
                    "partition users and fogs into B broker domains "
                    "(block-contiguous ownership) with broker↔broker "
                    "task migration; shorthand for spec.n_brokers=B — "
                    "composes with --policy/--telemetry/--chaos/"
                    "--trace-out; B must be in [1, n_fogs]")
    ap.add_argument("--hier-policy", metavar="NAME", default=None,
                    help="broker↔broker migration policy: never, "
                    "threshold (local busy fraction > "
                    "spec.hier_threshold), or least_loaded (aged peer "
                    "load summaries); needs --brokers B with B > 1; "
                    "refine knobs with --set spec.hier_*=...")
    ap.add_argument("--chaos", metavar="PROFILE", default=None,
                    help="deterministic fault injection (chaos/): run "
                    "the scenario under a named chaos profile — fog "
                    "crash/recover schedules, in-flight task loss or "
                    "re-offload, broker→fog link degradation "
                    "(profiles: light, heavy, flaky, degraded, "
                    "hostile, scripted); composes with --policy/"
                    "--telemetry/--hist/--serve/--trace-out; refine "
                    "any knob with --set spec.chaos_*=...")
    ap.add_argument("--chaos-seed", type=int, metavar="N", default=None,
                    help="seed of the chaos PRNG stream (fault "
                    "schedules + RTT bursts); needs --chaos")
    ap.add_argument("--chaos-mode", metavar="MODE", default=None,
                    help="in-flight task handling on a crash: 'lose' "
                    "or 'reoffload' (overrides the profile); needs "
                    "--chaos")
    ap.add_argument("--chaos-script", metavar="FILE", default=None,
                    help="scripted outage schedule: JSON list of "
                    "[fog, t_down, t_up] triples (or the compact "
                    "'fog:td:tu;...' text form); composes with the "
                    "profile's random schedule; needs --chaos")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (cpu/tpu)")
    ap.add_argument("--checkify", nargs="?", const="div", default=None,
                    metavar="SETS",
                    help="debug SLOW PATH: run under "
                    "jax.experimental.checkify with the named error "
                    "sets (comma-joined from nan,div,oob, or 'all'; "
                    "default div — engine.CHECKIFY_SETS documents why "
                    "nan/oob page on two deliberate idioms); also "
                    "enabled by FNS_CHECKIFY=1 or FNS_CHECKIFY=<SETS>")
    ap.add_argument("--analyze", metavar="DIR", default=None,
                    help="analyse recorded runs in DIR and exit (.anf analog)")
    _dyn_names = ", ".join(
        p.name.lower() for p in tuple(ARGMIN_FAMILY) + tuple(LEARNED_POLICIES)
    )
    ap.add_argument("--sweep", metavar="GRID", default=None,
                    help="policy x load sweep over the scenario, e.g. "
                    "'policies=min_busy,ucb loads=0.01,0.02,0.05 reps=4 "
                    "dynamic=1' — policies by name or id; one JSON line "
                    "per (policy, load); dynamic=1 compiles the whole "
                    f"grid ONCE (Policy.DYNAMIC: {_dyn_names}); "
                    "'policy=ucb explores=0.1,0.5 loads=...' instead "
                    "sweeps a learned policy's exploration-rate x load "
                    "grid under one compile")
    args = ap.parse_args(argv)
    if args.policy is not None:
        try:
            args.policy = int(policy_from_name(args.policy))
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    if args.analyze:
        from .runtime.analysis import analyze, render_report

        print(render_report(analyze(args.analyze)))
        return 0

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from .compile_cache import enable_compile_cache

    enable_compile_cache()

    from .config.ini import Config, build_from_config
    from .core.engine import run
    from .runtime.recorder import record_run
    from .runtime.signals import summarize

    # opt-in runtime sanitizer (ISSUE 7 satellite): --checkify wins,
    # else the FNS_CHECKIFY env knob ("1"/set names; "0"/"" = off)
    checkify_sets = args.checkify
    if checkify_sets is None:
        env = os.environ.get("FNS_CHECKIFY", "")
        if env.lower() not in ("", "0", "off", "false", "no"):
            checkify_sets = env
    if checkify_sets is not None and (
        args.serve is not None
        or args.replicas is not None
        or args.mesh is not None
        or args.tp is not None
        or args.sweep
        or args.progress
    ):
        ap.error("[CLI-CHECKIFY-SOLO] --checkify/FNS_CHECKIFY is the "
                 "single-world debug slow path; it does not combine with "
                 "--serve/--replicas/--mesh/--tp/--sweep/--progress")

    if args.tp is not None:
        # ---- TP guard rails: one parallel axis per run ----------------
        if args.replicas is not None or args.mesh is not None:
            ap.error("[CLI-TP-FLEET] --tp shards ONE world's task table "
                     "over the mesh; --replicas/--mesh fan out "
                     "independent worlds — pick one parallel axis per run")
        if args.sweep:
            ap.error("[CLI-SWEEP-TP] --sweep owns its own replica "
                     "fan-out; it does not combine with --tp")
        if args.progress or args.ticks or args.trails:
            # same cell as the engine gate's [TP-SERIES] clause: the CLI
            # one-liner keys on the gate's ID, never re-words the cell
            ap.error("[TP-SERIES] --tp runs one jitted sharded scan; "
                     "--progress/--ticks/--trails do not apply")
    elif args.tp_window is not None:
        ap.error("[CLI-TPWINDOW] --tp-window sizes the TP arrival "
                 "exchange; it needs --tp N")

    # ---- digital-twin guard rails (twin/): the CLI cites the gate
    # module's [TWIN-*] clauses verbatim, never re-words them ----------
    if args.ingest is not None or args.replay_arrivals is not None:
        from .twin.gates import (
            ingest_needs_serve_error,
            ingest_reject_reason,
        )

        if args.tp is not None:
            ap.error(ingest_reject_reason("tp"))
        if args.replicas is not None or args.mesh is not None:
            ap.error(ingest_reject_reason("fleet"))
        if args.serve is None:
            ap.error(ingest_needs_serve_error())
        if args.ingest is not None and args.ingest < 1:
            ap.error(f"--ingest queue capacity must be >= 1, got "
                     f"{args.ingest}")
    if args.whatif is not None:
        from .dynspec import promote_default
        from .twin.gates import whatif_reject_reason

        reason = whatif_reject_reason(
            fleet=args.replicas is not None or args.mesh is not None,
            promote=promote_default(),
        )
        if reason:
            ap.error(reason)
        if args.sweep:
            ap.error("[CLI-SWEEP-TWIN] --sweep builds every cell's "
                     "world at t=0; --whatif forks a LIVE carry — they "
                     "do not combine")
    if args.tenants is not None:
        from .twin.gates import front_reject_reason

        if args.tenants < 1:
            ap.error(f"--tenants must be >= 1, got {args.tenants}")
        if args.tp is not None:
            ap.error(front_reject_reason("tp"))
        if args.replicas is not None or args.mesh is not None:
            ap.error(front_reject_reason("fleet"))
        if args.serve is None:
            ap.error(front_reject_reason("solo"))
        if args.whatif is not None:
            ap.error("[CLI-TENANTS-WHATIF] per-tenant what-ifs ride "
                     "the front door's /t/<label>/whatif routes; the "
                     "--whatif one-shot applies to single-session runs")
        if args.replay_arrivals is not None or args.arrival_log:
            ap.error("[CLI-TENANTS-REPLAY] arrival logs are per "
                     "session; record/replay a tenant through the "
                     "single-session --serve --ingest path")
    elif args.tenant_cap is not None:
        ap.error("[CLI-TENANTCAP] --tenant-cap bounds front-door "
                 "admission; it needs --tenants N")

    # ---- hierarchy guard rails (hier/) --------------------------------
    if args.brokers is not None:
        if args.brokers < 1:
            print(
                f"error: --brokers must be >= 1, got {args.brokers} "
                "(1 = the single base broker, B > 1 federates)",
                file=sys.stderr,
            )
            return 2
        if args.tp is not None:
            # same cells as the hier_reject_reason gate: the CLI keys on
            # the gate's [TP-HIER]/[FLEET-HIER] IDs, never re-words them
            ap.error("[TP-HIER] --brokers federates ONE world's decide "
                     "phase; the TP sharded tick does not carry the "
                     "hierarchy yet — pick one of --brokers/--tp per run")
        if args.replicas is not None or args.mesh is not None:
            ap.error("[FLEET-HIER] --brokers federates ONE world; the "
                     "fleet runner does not carry the hierarchy yet — "
                     "run federated worlds without --replicas/--mesh")
        if args.sweep:
            ap.error("[CLI-SWEEP-HIER] --sweep grids own their replica "
                     "fan-out and do not carry the hierarchy; run "
                     "federated worlds without --sweep")
    if args.hier_policy is not None:
        if args.brokers is None or args.brokers < 2:
            print(
                "error: [CLI-HIERPOLICY] --hier-policy selects the "
                "broker↔broker migration policy; it needs --brokers B "
                "with B > 1",
                file=sys.stderr,
            )
            return 2
        from .spec import hier_policy_from_name

        try:
            args.hier_policy = int(hier_policy_from_name(args.hier_policy))
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    # ---- chaos guard rails (ISSUE 12) ---------------------------------
    if args.chaos is None:
        for flag, val in (("--chaos-seed", args.chaos_seed),
                          ("--chaos-mode", args.chaos_mode),
                          ("--chaos-script", args.chaos_script)):
            if val is not None:
                ap.error(f"[CLI-CHAOS-KNOBS] {flag} refines a chaos "
                         "profile; it needs --chaos <profile>")
    elif args.sweep:
        ap.error("[CLI-SWEEP-CHAOS] --chaos perturbs one world's fault "
                 "schedule; --sweep grids own their replica fan-out — "
                 "run chaos worlds without --sweep")

    # ---- journey guard rails (ISSUE 15) -------------------------------
    if args.journeys is not None:
        if args.journeys < 1:
            print(
                f"error: --journeys samples N >= 1 task slots, got "
                f"{args.journeys} (omit the flag to disable the "
                "journey plane)",
                file=sys.stderr,
            )
            return 2
        if not (
            args.telemetry
            or args.hist
            or args.serve is not None
            or args.slo is not None
        ):
            print(
                "error: [SPEC-JOURNEYS-TELEM] --journeys rides the "
                "device-resident telemetry plane (the event rings live "
                "in TelemetryState); add --telemetry (or --serve/--hist)",
                file=sys.stderr,
            )
            return 2
        # --journeys --tp composes since ISSUE 19: the sharded tick
        # carries shard-local rings (parallel/taskshard.py) and the
        # run path below stitches/decodes them like any journey run

    text = ""
    if args.config:
        with open(args.config) as f:
            text = f.read()
    pre = []
    if args.scenario:
        pre.append(f"scenario = {args.scenario}")
    if args.policy is not None:
        pre.append(f"scenario.policy = {args.policy}")
    for o in args.set:
        if "=" not in o:
            ap.error(f"--set needs KEY=VALUE, got {o!r}")
        pre.append(o.replace("=", " = ", 1))
        key = o.split("=", 1)[0].strip()
        if key.startswith("spec.") and "*" not in key:
            # one-line recompile classification (ISSUE 13): dynamic-
            # operand knobs re-use the compiled program, shape-defining
            # fields pay a fresh compile — surfaced BEFORE the run so a
            # what-if operator knows which wall they are about to hit.
            # Unknown fields fail here with the config tier's own
            # message (one line, before any world is built).
            from .dynspec import classify_field

            try:
                recompiles, why = classify_field(key[5:])
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            print(
                f"recompile: {'yes' if recompiles else 'no'} "
                f"({key}: {why})",
                file=sys.stderr,
            )
    if args.chaos is not None:
        # profile lines land BELOW the --set overrides (first match
        # wins), so --set spec.chaos_*=... refines any profile knob
        from .chaos.profiles import chaos_config_lines, load_script_file

        try:
            script = (
                load_script_file(args.chaos_script)
                if args.chaos_script is not None
                else None
            )
            pre += chaos_config_lines(
                args.chaos, seed=args.chaos_seed,
                mode=args.chaos_mode, script=script,
            )
        except ValueError as e:
            # unknown profile/mode or a malformed script file: one
            # actionable line, never a traceback
            print(f"error: {e}", file=sys.stderr)
            return 2
    # hierarchy lines land BELOW the --set overrides (first match
    # wins), so --set spec.n_brokers/hier_* refines the flags
    if args.brokers is not None:
        pre.append(f"spec.n_brokers = {args.brokers}")
    if args.hier_policy is not None:
        pre.append(f"spec.hier_policy = {args.hier_policy}")
    if args.ticks or args.trails:
        pre.append("spec.record_tick_series = true")
    if args.trails:
        pre.append("spec.record_trails = true")
    if args.ingest is not None or args.replay_arrivals is not None:
        pre.append("spec.ingest = true")
    if args.ingest_batch is not None:
        pre.append(f"spec.ingest_batch = {args.ingest_batch}")
    if args.telemetry or args.serve is not None:
        pre.append("spec.telemetry = true")
    if args.hist or args.slo is not None:
        pre.append("spec.telemetry = true")
        pre.append("spec.telemetry_hist = true")
    if args.journeys is not None:
        pre.append(f"spec.telemetry_journeys = {args.journeys}")
    cfg = Config.from_str("\n".join(pre) + "\n" + text)

    if args.sweep:
        import numpy as np

        from .config.ini import scenario_builders
        from .parallel import sweep_explore, sweep_policies

        if args.ticks or args.trails:
            ap.error("[CLI-SWEEP-SERIES] --sweep is incompatible with "
                     "--ticks/--trails (sweeps return counter grids, "
                     "not series)")
        if args.telemetry or args.trace_out or args.profile:
            ap.error("[CLI-SWEEP-TELEM] --sweep returns counter grids, "
                     "not a final world; --telemetry/--trace-out/"
                     "--profile apply to single-scenario runs")
        if args.serve is not None or args.slo is not None or args.hist:
            ap.error("[CLI-SWEEP-SERVE] --sweep returns counter grids, "
                     "not a live world; --serve/--slo/--hist apply to "
                     "single-scenario runs")
        if args.replicas is not None or args.mesh is not None:
            ap.error("[CLI-SWEEP-FLEET] --sweep owns its own replica "
                     "fan-out (reps=); --replicas/--mesh apply to "
                     "single-scenario runs")
        if args.policy is not None:
            print(
                "error: [CLI-SWEEP-POLICY] --policy conflicts with "
                "--sweep (the sweep owns the policy axis: use "
                "'policies=...' or 'policy=...' inside the grid spec)",
                file=sys.stderr,
            )
            return 2
        opts = dict(kv.split("=", 1) for kv in args.sweep.split())
        try:
            # policy tokens are names OR ids (PR 1's unknown-name
            # convention: a typo is a one-line error, never a traceback)
            policies = [
                int(policy_from_name(p))
                for p in opts.get("policies", "0").split(",")
            ]
            explores = (
                [float(x) for x in opts["explores"].split(",")]
                if "explores" in opts
                else None
            )
            exp_policy = (
                int(policy_from_name(opts["policy"]))
                if "policy" in opts
                else None
            )
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if explores is not None and exp_policy is None:
            print(
                "error: explores= sweeps need policy=<learned policy> "
                f"(one of {', '.join(p.name.lower() for p in LEARNED_POLICIES)})",
                file=sys.stderr,
            )
            return 2
        if exp_policy is not None and explores is None:
            print(
                "error: policy= selects the exploration-rate sweep and "
                "needs explores=<rates>; for a plain policy grid use "
                "policies=... instead",
                file=sys.stderr,
            )
            return 2
        loads = [float(x) for x in opts.get("loads", "0.05").split(",")]
        reps = int(opts.get("reps", "1"))
        dynamic = opts.get("dynamic", "0") not in ("0", "false", "")
        name = cfg.lookup("scenario", "smoke")
        builders = scenario_builders()
        if name not in builders:
            ap.error(
                f"unknown scenario {name!r} (have {sorted(builders)})"
            )
        # the sweep path passes only scenario.* kwargs to the builder —
        # fail loudly on override tiers it cannot honour (wildcard
        # patterns included) rather than silently running a different
        # world than the user configured
        unsupported = sorted(
            {
                pat.split(".", 1)[0]
                for pat, _, _ in cfg.entries
                if pat.split(".", 1)[0] in ("spec", "fog", "user")
            }
        )
        if unsupported:
            ap.error(
                "--sweep supports scenario.* overrides only; "
                f"{', '.join(u + '.*' for u in unsupported)} overrides "
                "are not applied in sweep mode — move them into the "
                "scenario builder's kwargs or run without --sweep"
            )
        build_kwargs = cfg.matching("scenario")
        build_kwargs.pop("seed", None)
        # the sweep owns the policy axis; a scenario.policy override would
        # collide with the per-cell policy= kwarg inside the driver
        build_kwargs.pop("policy", None)
        t0 = time.perf_counter()
        if explores is not None:
            try:
                grids = sweep_explore(
                    builders[name],
                    policy=exp_policy,
                    explore_rates=explores,
                    load_intervals=loads,
                    n_replicas_per_load=reps,
                    seed=args.seed or 0,
                    **build_kwargs,
                )
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            for rate, g in grids.items():
                for li, load in enumerate(loads):
                    # mean over the replicas that credited anything (a
                    # single empty replica must not NaN-poison the
                    # cell); null — not a bare NaN token, invalid JSON —
                    # when none did
                    cell = g["lat_mean_s"][li]
                    lm = (
                        float(np.nanmean(cell))
                        if np.isfinite(cell).any()
                        else None
                    )
                    print(json.dumps({
                        "policy": exp_policy, "explore": rate,
                        "send_interval": load,
                        "n_scheduled_mean": float(g["n_scheduled"][li].mean()),
                        "n_completed_mean": float(g["n_completed"][li].mean()),
                        "lat_mean_s": lm,
                        "reps": reps,
                    }))
            print(json.dumps(
                {"sweep_wall_s": round(time.perf_counter() - t0, 2),
                 "explores": explores, "scenario": name}))
            return 0
        try:
            grids = sweep_policies(
                builders[name],
                policies=policies,
                load_intervals=loads,
                n_replicas_per_load=reps,
                dynamic=dynamic,
                seed=args.seed or 0,
                **build_kwargs,
            )
        except ValueError as e:
            # e.g. a policy outside the traced-dispatch families under
            # dynamic=1 — actionable one-liner, not a traceback
            print(f"error: {e}", file=sys.stderr)
            return 2
        for pol, g in grids.items():
            for li, load in enumerate(loads):
                print(json.dumps({
                    "policy": pol, "send_interval": load,
                    "n_scheduled_mean": float(g["n_scheduled"][li].mean()),
                    "n_completed_mean": float(g["n_completed"][li].mean()),
                    "n_dropped_mean": float(g["n_dropped"][li].mean()),
                    "reps": reps,
                }))
        print(json.dumps({"sweep_wall_s": round(time.perf_counter() - t0, 2),
                          "dynamic": dynamic, "scenario": name}))
        return 0

    try:
        spec, state, net, bounds = build_from_config(cfg, seed=args.seed)
    except ValueError as e:
        # e.g. an .ini referencing an unknown scenario/network name: a
        # one-line actionable error (listing the known names), not a
        # traceback
        print(f"error: {e}", file=sys.stderr)
        return 2

    def _announce(health):
        # one status line per chunk, the Cmdenv-progress analog
        print(json.dumps(health), flush=True)

    def _whatif_extra(spec_f, carry):
        """The --whatif one-shot: answer the knob grid from the run's
        final carry (the offline twin question; the live endpoint
        answers the same grids mid-session).  Raises ValueError with
        the one-line grid/knob errors."""
        if args.whatif is None:
            return {}
        from .twin.whatif import _json_safe, parse_grid, run_whatif

        knobs, wi_ticks = parse_grid(args.whatif)
        return {
            "whatif": _json_safe(
                run_whatif(spec_f, carry, net, bounds, knobs, wi_ticks)
            )
        }

    def _finish_serve(spec_f, final, status, t0, prof, extra=None):
        """Shared --serve epilogue (single-device and --tp branches):
        summary dict, recording, trace/profile export, server shutdown,
        one JSON line — edited in ONE place for both paths."""
        wall = time.perf_counter() - t0
        out = {
            "scenario": cfg.lookup("scenario", "smoke"),
            "wall_s": round(wall, 3),
            **(extra or {}),
            "port": status["port"],
            "chunks": status["chunks"],
            "anomalies": status["anomalies"],
            "slo_breaches": status["slo_breaches"],
            "dumps": status["dumps"],
        }
        outdir = args.out or cfg.lookup("output.dir")
        if outdir:
            run_id = args.run_id or cfg.lookup(
                "output.run_id", "General-0"
            )
            out.update(record_run(
                outdir, spec_f, final, run_id=run_id,
                attrs={
                    "argv": sys.argv[1:] if argv is None else list(argv),
                    "scenario": cfg.lookup("scenario", "smoke"),
                    "served_port": status["port"],
                    **{
                        k: v for k, v in (extra or {}).items()
                        if k == "tp_shards"
                    },
                },
            ))
        if args.trace_out:
            # TP runs: the per-shard exchange lanes ride this export
            from .telemetry.timeline import export_trace

            out["trace"] = export_trace(
                spec_f, final, args.trace_out,
                max_tasks=args.trace_max_tasks or None,
            )
        if args.profile:
            out["profile_dir"] = prof["dir"] if prof["active"] else None
            if prof["error"]:
                out["profile_error"] = prof["error"]
        s = summarize(final)
        out.update(
            n_published=s["n_published"], n_completed=s["n_completed"],
        )
        if status["server"] is not None:
            status["server"].close()
        print(json.dumps(out))
        return 0

    if args.tp is not None and args.serve is not None:
        # ---- sharded health plane: --serve --tp N (ISSUE 11) ----------
        from .dynspec import promote_default
        from .parallel import make_mesh
        from .telemetry.live import HealthServer, ReconfigDoor, serve_tp_run
        from .telemetry.profile import profile_trace

        t0 = time.perf_counter()
        if args.whatif is not None and spec.n_users % args.tp:
            # pre-pad at the CLI so the --whatif fork's net matches the
            # padded population the session runs (the runner's own
            # padding is idempotent on an already-padded world)
            from .parallel.taskshard import pad_users_to_multiple

            spec, state, net = pad_users_to_multiple(
                spec, state, net, args.tp
            )
        # live retuning (ISSUE 20): POST /reconfigure queues promoted
        # knobs that the TP chunk loop applies at the next boundary
        # with ZERO compile events; needs the promoted runners
        door = server = None
        if promote_default():
            door = ReconfigDoor(spec)
            server = HealthServer(port=args.serve)
            server.set_handler(door.handle_http)
        try:
            with profile_trace(args.profile) as prof:
                mesh = make_mesh(args.tp, axis_name="node")
                spec, final, status = serve_tp_run(
                    spec, state, net, bounds, mesh,
                    exchange_window=args.tp_window,
                    chunk_ticks=args.serve_chunk,
                    port=args.serve,
                    slo_ms=args.slo,
                    dump_dir=args.postmortem,
                    on_chunk=_announce,
                    server=server,
                    **(
                        {"reconfigure": door.as_reconfigure()}
                        if door is not None else {}
                    ),
                )
        except ValueError as e:
            # e.g. a policy outside the dense-broker TP family, or more
            # shards than devices: one actionable line
            if server is not None:
                server.close()
            print(f"error: {e}", file=sys.stderr)
            return 2
        try:
            # the --whatif one-shot forks the final sharded carry onto
            # the knob grid: unstamp gathers it off the mesh (ISSUE 20)
            if args.whatif is not None:
                from .parallel.taskshard import unstamp_tp_carry

                sp_w, carry = unstamp_tp_carry(spec, final)
                wi = _whatif_extra(sp_w, carry)
            else:
                wi = {}
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        return _finish_serve(
            spec, final, status, t0, prof,
            extra={
                "tp_shards": args.tp,
                "n_users": spec.n_users,  # post-padding population
                **(
                    {"reconfigured": door.applied_batches}
                    if door is not None else {}
                ),
                **wi,
            },
        )

    if args.tp is not None:
        # ---- TP: one world's task table sharded over the mesh ---------
        import jax

        from .parallel import make_mesh
        from .parallel.taskshard import run_tp_sharded
        from .telemetry.profile import profile_trace

        t0 = time.perf_counter()
        if args.whatif is not None and spec.n_users % args.tp:
            # pre-pad at the CLI so the --whatif fork's net matches the
            # padded population the session runs (the runner's own
            # padding is idempotent on an already-padded world)
            from .parallel.taskshard import pad_users_to_multiple

            spec, state, net = pad_users_to_multiple(
                spec, state, net, args.tp
            )
        try:
            with profile_trace(args.profile) as prof:
                mesh = make_mesh(args.tp, axis_name="node")
                spec, final = run_tp_sharded(
                    spec, state, net, bounds, mesh,
                    exchange_window=args.tp_window, pad=True,
                )
                jax.block_until_ready(final)
        except ValueError as e:
            # e.g. a policy outside the dense-broker TP family, --hist,
            # or more shards than devices: one actionable line
            print(f"error: {e}", file=sys.stderr)
            return 2
        wall = time.perf_counter() - t0
        try:
            # the --whatif one-shot forks the final sharded carry onto
            # the knob grid: unstamp gathers it off the mesh (ISSUE 20)
            if args.whatif is not None:
                from .parallel.taskshard import unstamp_tp_carry

                sp_w, carry = unstamp_tp_carry(spec, final)
                wi = _whatif_extra(sp_w, carry)
            else:
                wi = {}
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        out = {
            "scenario": cfg.lookup("scenario", "smoke"),
            "wall_s": round(wall, 3),
            "tp_shards": args.tp,
            "n_users": spec.n_users,  # post-padding population
            **wi,
        }
        outdir = args.out or cfg.lookup("output.dir")
        if outdir:
            run_id = args.run_id or cfg.lookup("output.run_id", "General-0")
            out.update(record_run(
                outdir, spec, final, run_id=run_id,
                attrs={
                    "argv": sys.argv[1:] if argv is None else list(argv),
                    "scenario": cfg.lookup("scenario", "smoke"),
                    "tp_shards": args.tp,
                },
            ))
        if args.trace_out:
            from .telemetry.timeline import export_trace

            out["trace"] = export_trace(
                spec, final, args.trace_out,
                max_tasks=args.trace_max_tasks or None,
            )
        if args.profile:
            out["profile_dir"] = prof["dir"] if prof["active"] else None
            if prof["error"]:
                out["profile_error"] = prof["error"]
        s = summarize(final)
        out.update(
            n_published=s["n_published"], n_completed=s["n_completed"],
        )
        print(json.dumps(out))
        return 0

    if args.serve is not None:
        # ---- live health plane (telemetry/live.py, ISSUE 6) -----------
        if args.progress or args.ticks or args.trails:
            ap.error("[CLI-SERVE-SERIES] --serve owns the chunking "
                     "(--serve-chunk); --progress/--ticks/--trails do "
                     "not apply")
        if args.replicas is not None or args.mesh is not None:
            ap.error("[CLI-SERVE-FLEET] --serve is a single-world loop; "
                     "fleet serving is a follow-up (run --replicas "
                     "without --serve)")
        from .telemetry.profile import profile_trace

        if args.tenants is not None:
            # ---- multi-tenant front door (twin/front.py, ISSUE 17) ----
            from .twin.front import FrontDoor

            t0 = time.perf_counter()
            cap = (
                args.tenant_cap if args.tenant_cap is not None
                else args.tenants
            )
            door = FrontDoor(
                capacity=cap, chunk_ticks=args.serve_chunk,
                port=args.serve,
            )
            try:
                for i in range(args.tenants):
                    sp_i, st_i, net_i, b_i = build_from_config(
                        cfg, seed=(args.seed or 0) + i
                    )
                    door.admit(
                        f"t{i}", sp_i, st_i, net_i, b_i,
                        ingest_capacity=args.ingest or 1024,
                    )
            except ValueError as e:
                # duplicate label / telemetry-less spec / [TWIN-CAP]
                # past the admission bound: one actionable line
                door.close()
                print(f"error: {e}", file=sys.stderr)
                return 2
            rounds = -(-spec.n_ticks // args.serve_chunk)
            ticks = door.serve(rounds)
            out = {
                "scenario": cfg.lookup("scenario", "smoke"),
                "tenants": args.tenants,
                "tenant_cap": cap,
                "port": door.server.port if door.server else None,
                "rounds": rounds,
                "ticks": ticks,
                "published": {
                    r["label"]: r["n_published"]
                    for r in door.tenant_rows()
                },
                "wall_s": round(time.perf_counter() - t0, 3),
            }
            door.close()
            print(json.dumps(out))
            return 0

        t0 = time.perf_counter()
        if args.ingest is not None or args.replay_arrivals is not None:
            # ---- live-ingestion twin session (twin/ingest.py) ---------
            from .twin.ingest import load_log, serve_ingest_run

            replay = (
                load_log(args.replay_arrivals)
                if args.replay_arrivals else None
            )
            with profile_trace(args.profile) as prof:
                final, status = serve_ingest_run(
                    spec, state, net, bounds,
                    capacity=args.ingest or 1024,
                    chunk_ticks=args.serve_chunk,
                    port=args.serve,
                    replay_log=replay,
                    slo_ms=args.slo,
                    dump_dir=args.postmortem,
                    on_chunk=_announce,
                )
            if args.arrival_log:
                with open(args.arrival_log, "w") as f:
                    json.dump(
                        {
                            "capacity": status["ingest"]["capacity"],
                            "entries": status["arrival_log"],
                        },
                        f, indent=1,
                    )
            try:
                wi = _whatif_extra(spec, final)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            return _finish_serve(
                spec, final, status, t0, prof,
                extra={"ingest": status["ingest"], **wi},
            )

        from .dynspec import promote_default
        from .telemetry.live import HealthServer, ReconfigDoor, serve_run

        # live retuning (ISSUE 20): POST /reconfigure queues promoted
        # knobs that run_chunked applies at the next chunk boundary
        # with ZERO compile events; needs the promoted runners
        door = server = None
        if promote_default():
            door = ReconfigDoor(spec)
            server = HealthServer(port=args.serve)
            server.set_handler(door.handle_http)
        with profile_trace(args.profile) as prof:
            final, status = serve_run(
                spec, state, net, bounds,
                chunk_ticks=args.serve_chunk,
                port=args.serve,
                slo_ms=args.slo,
                dump_dir=args.postmortem,
                on_chunk=_announce,
                server=server,
                **(
                    {"reconfigure": door.as_reconfigure()}
                    if door is not None else {}
                ),
            )
        try:
            wi = _whatif_extra(spec, final)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if door is not None:
            wi = {"reconfigured": door.applied_batches, **wi}
        return _finish_serve(spec, final, status, t0, prof, extra=wi)

    if args.replicas is not None or args.mesh is not None:
        # ---- replica-sharded fleet run (parallel/fleet.py) ------------
        if args.progress:
            ap.error("[CLI-FLEET-PROGRESS] --replicas/--mesh and "
                     "--progress are mutually exclusive (the fleet scan "
                     "is one jitted call)")
        if args.trails:
            ap.error("[CLI-FLEET-TRAILS] --trails renders one world's "
                     "movement; slice a replica out of a fleet run "
                     "instead")
        import jax

        from .parallel import make_mesh, replicate_state
        from .parallel.fleet import run_fleet, run_fleet_series
        from .runtime.recorder import fleet_scalars, record_fleet_run

        try:
            mesh = make_mesh(args.mesh)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        n_devices = int(mesh.devices.size)
        n_replicas = (
            args.replicas if args.replicas is not None else n_devices
        )
        batch = replicate_state(
            spec, state, n_replicas, seed=args.seed or 0
        )
        from .telemetry.profile import profile_trace

        t0 = time.perf_counter()
        try:
            with profile_trace(args.profile) as prof:
                if args.ticks:
                    final, series = run_fleet_series(
                        spec, batch, net, bounds, mesh
                    )
                else:
                    final = run_fleet(spec, batch, net, bounds, mesh)
                    series = None
                jax.block_until_ready(final)
        except ValueError as e:
            # e.g. a replica count that does not divide over the mesh
            print(f"error: {e}", file=sys.stderr)
            return 2
        wall = time.perf_counter() - t0
        fs = fleet_scalars(spec, final)
        out = {
            "scenario": cfg.lookup("scenario", "smoke"),
            "wall_s": round(wall, 3),
            "n_replicas": n_replicas,
            "n_devices": n_devices,
            "n_published_sum": int(fs["aggregate"]["n_published"]["sum"]),
            "n_completed_sum": int(fs["aggregate"]["n_completed"]["sum"]),
            "n_completed_minmax": [
                int(fs["aggregate"]["n_completed"]["min"]),
                int(fs["aggregate"]["n_completed"]["max"]),
            ],
        }
        outdir = args.out or cfg.lookup("output.dir")
        if outdir:
            run_id = args.run_id or cfg.lookup("output.run_id", "Fleet-0")
            out.update(record_fleet_run(
                outdir, spec, final, series=series, run_id=run_id,
                attrs={
                    "argv": sys.argv[1:] if argv is None else list(argv),
                    "scenario": cfg.lookup("scenario", "smoke"),
                    "n_devices": n_devices,
                },
                scalars=fs,  # already gathered for the summary above
            ))
        if args.trace_out:
            from .telemetry.timeline import export_trace

            out["trace"] = export_trace(
                spec, final, args.trace_out,
                max_tasks=args.trace_max_tasks or None,
            )
        if args.profile:
            out["profile_dir"] = prof["dir"] if prof["active"] else None
            if prof["error"]:
                out["profile_error"] = prof["error"]
        print(json.dumps(out))
        return 0

    from .telemetry.profile import profile_trace

    t0 = time.perf_counter()
    with profile_trace(args.profile) as prof:
        if args.progress:
            if args.ticks or args.trails:
                ap.error("[CLI-PROGRESS-SERIES] --progress and "
                         "--ticks/--trails are mutually exclusive "
                         "(chunked runs record via snapshots, not "
                         "series)")
            from .core.engine import run_chunked
            from .runtime.signals import summarize as _sumz

            def _cb(s, tick):
                m = _sumz(s)
                print(json.dumps({
                    "tick": tick, "t": round(tick * spec.dt, 6),
                    "n_published": m["n_published"],
                    "n_completed": m["n_completed"],
                    "wall_s": round(time.perf_counter() - t0, 2),
                }), flush=True)

            final = run_chunked(spec, state, net, bounds,
                                chunk_ticks=args.progress, callback=_cb)
            series = None
        elif checkify_sets is not None:
            from jax.experimental.checkify import JaxRuntimeError

            from .core.engine import _checkify_errors, run_checkified

            try:
                _checkify_errors(checkify_sets)  # unknown set names
            except ValueError as e:
                print(f"error: checkify: {e}", file=sys.stderr)
                return 1
            try:
                final, series = run_checkified(
                    spec, state, net, bounds, errors=checkify_sets
                )
            except JaxRuntimeError as e:
                # a tripped runtime check: one actionable line (the
                # offending primitive is in the message); any other
                # error is NOT the sanitizer's and keeps its traceback
                print(f"error: checkify: {e}", file=sys.stderr)
                return 1
        else:
            final, series = run(spec, state, net, bounds)
        import jax

        jax.block_until_ready(final)
    wall = time.perf_counter() - t0

    out = {"scenario": cfg.lookup("scenario", "smoke"), "wall_s": round(wall, 3)}
    outdir = args.out or cfg.lookup("output.dir")
    if outdir:
        run_id = args.run_id or cfg.lookup("output.run_id", "General-0")
        paths = record_run(
            outdir, spec, final, series=series, run_id=run_id,
            attrs={
                "argv": sys.argv[1:] if argv is None else list(argv),
                "scenario": cfg.lookup("scenario", "smoke"),
            },
        )
        out.update(paths)
    if args.trails:
        from .runtime.trails import render_trails_svg

        out["trails"] = render_trails_svg(
            spec, final, series, args.trails, net=net
        )
    if args.trace_out:
        from .telemetry.timeline import export_trace

        out["trace"] = export_trace(
            spec, final, args.trace_out,
            max_tasks=args.trace_max_tasks or None,
        )
    if args.profile:
        out["profile_dir"] = prof["dir"] if prof["active"] else None
        if prof["error"]:
            out["profile_error"] = prof["error"]
    s = summarize(final)
    out.update(
        n_published=s["n_published"], n_completed=s["n_completed"],
        task_time_mean_ms=round(s["task_time_mean_ms"], 3)
        if s["task_time_mean_ms"] == s["task_time_mean_ms"] else None,
    )
    if spec.telemetry_hist:
        # streaming-histogram roll-up on the one-line summary (the same
        # hist_summary() the recorder and OpenMetrics read)
        from .telemetry.health import hist_summary, slo_breach_count

        hist = hist_summary(spec, final)
        out["lat_quantiles_ms"] = {
            k: (round(v, 3) if v == v else None)
            for k, v in hist["quantiles_ms"].items()
        }
        if args.slo is not None:
            out["slo_ms"] = args.slo
            out["slo_breaches"] = slo_breach_count(
                spec, final, args.slo, summ=hist
            )
    try:
        out.update(_whatif_extra(spec, final))
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
