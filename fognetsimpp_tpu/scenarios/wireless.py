"""The wireless scenario ladder: the reference's integration suite rebuilt.

Each builder reproduces one rung of ``simulations/testing/*.ini`` →
``*.ned`` (SURVEY.md §4 table) as a batched world: the NED topology
becomes an infrastructure delay graph (routers/APs + 100 Mbps / 0.1 µs
``channel C`` links, ``testing/wireless5.ned:37-42``), 802.11 access
becomes the calibrated per-AP contention model
(:mod:`fognetsimpp_tpu.net.topology`), and the ini's mobility / MIPS /
energy blocks become per-node state arrays.

Ladder (reference config → builder):
  * ``wireless.ini`` → :func:`wireless` — 1 linear user, 2 APs, 2 fogs.
  * ``wireless2.ini`` → :func:`wireless2` — 10+1 users, 4 APs, 3 fogs,
    CircleMobility on selected users.
  * ``wireless3.ini`` → :func:`wireless3` — parametric AP chain
    (``wireless3.ned:81-85``'s NED for-loop).
  * ``wireless4.ini`` → :func:`wireless4` — 10-AP row, linear users
    traverse it (handover).
  * ``wireless5.ini`` → :func:`wireless5` — the full-feature world:
    heterogeneous fog MIPS 1000-4000, broker MIPS 0, energy
    storage/harvesting + node shutdown/start churn.
  * ``paper.ned`` → :func:`paper` — the publication topology (4 fogs,
    7 APs, 18 users — 17 wireless hosts incl. the mobiles/laptop plus one
    wired static sensor); no committed ini, so v3 defaults.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.engine import prime_initial_advertisements
from ..net.mobility import MobilityBounds
from ..net.topology import NetParams, build_core_delay, make_net_params
from ..spec import Mobility, WorldSpec
from ..state import init_state

# `channel C extends DatarateChannel { datarate = 100Mbps; delay = 0.1us; }`
C_RATE = 100e6
C_DELAY = 1e-7


class InfraGraph:
    """Named infrastructure points + C-channel links -> core delay matrix."""

    def __init__(self) -> None:
        self.names: Dict[str, int] = {}
        self.links: List[Tuple[int, int, float, float]] = []

    def node(self, name: str) -> int:
        return self.names.setdefault(name, len(self.names))

    def link(self, a: str, b: str, rate: float = C_RATE,
             delay: float = C_DELAY) -> None:
        self.links.append((self.node(a), self.node(b), rate, delay))

    def core(self, packet_bytes: int) -> np.ndarray:
        return build_core_delay(len(self.names), self.links, packet_bytes)


def access_cost(packet_bytes: int) -> float:
    """One C-channel hop: propagation + serialization."""
    return C_DELAY + packet_bytes * 8.0 / C_RATE


def _deg(a: float) -> float:
    return a * math.pi / 180.0


def assemble(
    spec: WorldSpec,
    graph: InfraGraph,
    *,
    seed: int = 0,
    fog_mips: Sequence[float],
    fog_attach: Sequence[str],
    broker_attach: str,
    fog_pos: Optional[Sequence[Tuple[float, float]]] = None,
    broker_pos: Tuple[float, float] = (0.0, 0.0),
    ap_names: Sequence[str] = (),
    ap_pos: Sequence[Tuple[float, float]] = (),
    ap_range: float = 250.0,
    user_pos: Sequence[Tuple[float, float]] = (),
    linear: Optional[Dict[int, Tuple[float, float]]] = None,  # u -> (speed, angle_rad)
    circle: Optional[Dict[int, Tuple[float, float, float, float, float]]] = None,
    # u -> (cx, cy, r, speed, start_angle_rad)
    wired_users: Optional[Dict[int, str]] = None,  # u -> infra attach name
    area: Tuple[float, float] = (600.0, 400.0),
    w_base: float = 2e-3,
    w_prop: float = 3.336e-9,
    w_contention: float = 1.5e-3,
    mac_model: str = "bianchi",
    energy_users: bool = False,
    initial_energy_frac: Optional[Tuple[float, float]] = None,
):
    """Shared scenario assembler: builds ``(spec, state, net, bounds)``.

    Node layout [users | fogs | broker | aps]; routers exist only as infra
    points.  Wired hosts (fogs, broker, APs themselves, wired users) attach
    to their router with one C-hop access cost; wireless users associate
    per tick.
    """
    U, F, A = spec.n_users, spec.n_fogs, spec.n_aps
    assert A == len(ap_names) == len(ap_pos)
    assert F == len(fog_mips) == len(fog_attach)
    # declare the activity-keyed MAC on the spec so illegal combinations
    # (assume_static, see WorldSpec.validate) fail at construction, not
    # mid-run; mirrors make_net_params' own table-attachment condition
    keyed = A > 0 and mac_model == "bianchi"
    if keyed != spec.mac_keyed:
        spec = dataclasses.replace(spec, mac_keyed=keyed).validate()
    N = spec.n_nodes
    cost = access_cost(spec.task_bytes)

    node_attach = np.full((N,), -1, np.int32)
    node_acc = np.zeros((N,), np.float32)
    is_wireless = np.zeros((N,), bool)
    is_wireless[:U] = True
    for u, name in (wired_users or {}).items():
        is_wireless[u] = False
        node_attach[u] = graph.node(name)
        node_acc[u] = cost
    for f in range(F):
        node_attach[U + f] = graph.node(fog_attach[f])
        node_acc[U + f] = cost
    node_attach[spec.broker_index] = graph.node(broker_attach)
    node_acc[spec.broker_index] = cost
    ap_infra = [graph.node(nm) for nm in ap_names]
    for i in range(A):
        node_attach[spec.ap_slice[0] + i] = ap_infra[i]

    net = make_net_params(
        n_nodes=N,
        core_delay=graph.core(spec.task_bytes),
        node_attach=node_attach,
        is_wireless=is_wireless,
        ap_nodes=list(range(spec.ap_slice[0], spec.ap_slice[0] + A)),
        ap_attach=ap_infra,
        ap_range=ap_range,
        w_base=w_base,
        w_prop=w_prop,
        w_contention=w_contention,
        node_acc=node_acc,
        mac_model=mac_model,
    )

    state = init_state(spec, jax.random.PRNGKey(seed))
    mips = jnp.asarray(fog_mips, jnp.float32)
    state = state.replace(fogs=state.fogs.replace(mips=mips, pool_avail=mips))

    pos = np.zeros((N, 2), np.float32)
    pos[:U] = np.asarray(user_pos, np.float32) if len(user_pos) else 0.0
    if fog_pos is not None:
        pos[U : U + F] = np.asarray(fog_pos, np.float32)
    pos[spec.broker_index] = broker_pos
    if A:
        pos[spec.ap_slice[0] : spec.ap_slice[0] + A] = np.asarray(
            ap_pos, np.float32
        )

    mob = np.zeros((N,), np.int8)
    vel = np.zeros((N, 2), np.float32)
    ccen = np.zeros((N, 2), np.float32)
    crad = np.zeros((N,), np.float32)
    comg = np.zeros((N,), np.float32)
    cpha = np.zeros((N,), np.float32)
    for u, (speed, ang) in (linear or {}).items():
        mob[u] = int(Mobility.LINEAR)
        vel[u] = (speed * math.cos(ang), speed * math.sin(ang))
    for u, (cx, cy, r, speed, start) in (circle or {}).items():
        mob[u] = int(Mobility.CIRCLE)
        ccen[u] = (cx, cy)
        crad[u] = r
        comg[u] = speed / r
        cpha[u] = start
        pos[u] = (cx + r * math.cos(start), cy + r * math.sin(start))

    nodes = state.nodes.replace(
        pos=jnp.asarray(pos),
        mobility=jnp.asarray(mob),
        vel=jnp.asarray(vel),
        circle_center=jnp.asarray(ccen),
        circle_radius=jnp.asarray(crad),
        circle_omega=jnp.asarray(comg),
        circle_phase=jnp.asarray(cpha),
    )
    if energy_users:
        has = np.zeros((N,), bool)
        has[:U] = True
        nodes = nodes.replace(has_energy=jnp.asarray(has))
        if initial_energy_frac is not None:
            lo, hi = initial_energy_frac
            key = jax.random.PRNGKey(seed + 1)
            frac = jax.random.uniform(key, (N,), minval=lo, maxval=hi)
            nodes = nodes.replace(
                energy=jnp.where(
                    jnp.asarray(has), frac * nodes.energy_capacity,
                    nodes.energy,
                )
            )
    state = state.replace(nodes=nodes)
    state = prime_initial_advertisements(spec, state, net)
    bounds = MobilityBounds(
        lo=jnp.zeros((2,), jnp.float32),
        hi=jnp.asarray(area, jnp.float32),
    )
    return spec, state, net, bounds


# ----------------------------------------------------------------------
# the ladder
# ----------------------------------------------------------------------

def _sized(overrides: dict, horizon: float, default_interval: float) -> dict:
    """Apply the ladder's send-capacity sizing idiom in one place.

    Routes ``send_interval`` through ``overrides`` (so config-tier
    overrides don't collide with a builder-owned kwarg) and sizes
    ``max_sends_per_user`` from the *effective* interval, so faster
    overridden rates never truncate at the default-rate send budget.
    """
    overrides.setdefault("send_interval", default_interval)
    overrides.setdefault(
        "max_sends_per_user",
        int(horizon / overrides["send_interval"]) + 4,
    )
    return overrides


def wireless(horizon: float = 10.0, dt: float = 1e-3, seed: int = 0,
             **overrides):
    """``testing/wireless.ini`` → WirelessNetwork: 1 linear user, 2 APs.

    2 fogs MIPS 1000 behind router1; APs via router to the broker
    (``Wireless.ned:73-80``); user LinearMobility 20 mps in a 600x400 area,
    publish every 50 ms.
    """
    spec = WorldSpec(
        n_users=1, n_fogs=2, n_aps=2, horizon=horizon, dt=dt,
        **_sized(overrides, horizon, 0.05),
    ).validate()
    g = InfraGraph()
    for a, b in [("ap2", "ap1"), ("router", "ap1"), ("router", "ap2"),
                 ("router", "bb"), ("router1", "bb"), ("router1", "cb1"),
                 ("router1", "cb2")]:
        g.link(a, b)
    return assemble(
        spec, g, seed=seed,
        fog_mips=(1000.0, 1000.0), fog_attach=("router1", "router1"),
        broker_attach="router",
        ap_names=("ap1", "ap2"), ap_pos=((123.0, 175.0), (467.0, 175.0)),
        ap_range=250.0,
        user_pos=((397.0, 78.0),),
        linear={0: (20.0, 0.0)},
        area=(600.0, 400.0),
    )


def wireless2(horizon: float = 10.0, dt: float = 1e-3, seed: int = 0,
              **overrides):
    """``testing/wireless2.ini`` → WirelessNetwork2: 10+1 users, 4 APs.

    user1-analog (index 10) and user2 (index 2) ride CircleMobility around
    (300, 300) r=250 at 40 mps (``wireless2.ini:15-27``); the rest are
    LinearMobility 20 mps.  3 fogs MIPS 1000, publish every 1 s.
    """
    U = 11
    spec = WorldSpec(
        n_users=U, n_fogs=3, n_aps=4, horizon=horizon, dt=dt,
        **_sized(overrides, horizon, 1.0),
    ).validate()
    g = InfraGraph()
    for a, b in [("ap1", "ap2"), ("router3", "ap1"), ("router2", "ap2"),
                 ("router2", "ap3"), ("router3", "ap4"), ("ap3", "ap4"),
                 ("router3", "router"), ("router2", "router"),
                 ("router", "bb"), ("router1", "bb")] + [
            ("router1", f"cb{i}") for i in range(3)]:
        g.link(a, b)
    rng = np.random.default_rng(seed)
    user_pos = rng.uniform((50, 50), (550, 350), (U, 2))
    linear = {u: (20.0, 0.0) for u in range(U)}
    circle = {}
    for u, start in ((10, _deg(360)), (2, _deg(180))):
        del linear[u]
        circle[u] = (300.0, 300.0, 250.0, 40.0, start)
    return assemble(
        spec, g, seed=seed,
        fog_mips=(1000.0,) * 3, fog_attach=("router1",) * 3,
        broker_attach="router",
        ap_names=("ap1", "ap2", "ap3", "ap4"),
        ap_pos=((77.0, 151.0), (475.0, 151.0), (475.0, 408.0), (77.0, 398.0)),
        ap_range=300.0,
        user_pos=user_pos, linear=linear, circle=circle,
        area=(600.0, 400.0),
    )


def wireless3(numb: int = 4, numb_users: int = 2, horizon: float = 10.0,
              dt: float = 1e-3, seed: int = 0, **overrides):
    """``testing/wireless3.ini`` → WirelessNetwork3: the parametric AP chain.

    ``numb`` APs chained ap[i] <-> ap[i+1], each backhauled through
    routerL3[i] to the broker — the NED for-loop topology
    (``wireless3.ned:81-85``).  ``numb_users`` wireless users (user index 1
    circles like the ini's user1 when present), 3 fogs MIPS 1000.
    """
    assert numb >= 2, "the AP chain needs >= 2 APs (the NED loop is 0..numb-2)"
    spec = WorldSpec(
        n_users=numb_users, n_fogs=3, n_aps=numb, horizon=horizon, dt=dt,
        **_sized(overrides, horizon, 1.0),
    ).validate()
    g = InfraGraph()
    for a, b in [("router1", "bb")] + [("router1", f"cb{i}") for i in range(3)]:
        g.link(a, b)
    for i in range(numb - 1):  # the NED for i=0..(numb-2) loop
        g.link(f"ap{i}", f"ap{i + 1}")
        g.link(f"routerL3{i}", f"ap{i}")
        g.link(f"routerL3{i}", "bb")
    ap_pos = [(100.0 + 250.0 * i, 123.0) for i in range(numb)]
    rng = np.random.default_rng(seed)
    user_pos = rng.uniform((50, 50), (100 + 250 * (numb - 1), 350),
                           (numb_users, 2))
    linear = {u: (20.0, 0.0) for u in range(numb_users)}
    circle = {}
    if numb_users > 1:
        del linear[1]
        circle[1] = (300.0, 300.0, 250.0, 40.0, _deg(360))
    return assemble(
        spec, g, seed=seed,
        fog_mips=(1000.0,) * 3, fog_attach=("router1",) * 3,
        broker_attach="router1",
        ap_names=[f"ap{i}" for i in range(numb)], ap_pos=ap_pos,
        ap_range=300.0,
        user_pos=user_pos, linear=linear, circle=circle,
        area=(100.0 + 250.0 * numb, 400.0),
    )


def wireless4(numb_users: int = 2, horizon: float = 30.0, dt: float = 1e-3,
              seed: int = 0, **overrides):
    """``testing/wireless4.ini`` → WirelessNetwork4: the 10-AP handover row.

    10 APs at y=259 spanning x=60..1074, each backhauled through its own
    router to the broker (``wireless4.ned``); users are LinearMobility
    20 mps along +x, so they traverse AP cells and hand over.  Publish
    every 2 s; 3 fogs MIPS 1000.
    """
    ap_x = [60.0, 177.0, 298.0, 422.0, 529.0, 634.0, 742.0, 834.0, 954.0,
            1074.0]
    spec = WorldSpec(
        n_users=numb_users, n_fogs=3, n_aps=10, horizon=horizon, dt=dt,
        **_sized(overrides, horizon, 2.0),
    ).validate()
    g = InfraGraph()
    g.link("router1", "bb")
    for i in range(3):
        g.link("router1", f"cb{i}")
    for i in range(10):
        g.link(f"r{i}", f"ap{i}")
        g.link(f"r{i}", "bb")
    rng = np.random.default_rng(seed)
    ys = rng.uniform(150.0, 260.0, numb_users)
    user_pos = np.stack([np.full(numb_users, 70.0), ys], axis=-1)
    return assemble(
        spec, g, seed=seed,
        fog_mips=(1000.0,) * 3, fog_attach=("router1",) * 3,
        broker_attach="router1",
        ap_names=[f"ap{i}" for i in range(10)],
        ap_pos=[(x, 259.0) for x in ap_x],
        ap_range=100.0,  # 2.5 mW cells: only the nearest row AP is in range
        user_pos=user_pos,
        linear={u: (20.0, 0.0) for u in range(numb_users)},
        area=(1150.0, 400.0),
    )


def wireless5(numb_users: int = 10, horizon: float = 60.0, dt: float = 0.01,
              seed: int = 0, ap_range: float = 400.0,
              w_contention: float = 1.5e-3, mac_model: str = "bianchi",
              extra_aps: int = 0, **overrides):
    """``testing/wireless5.ini`` → WirelessNetwork5: the full-feature world.

    Heterogeneous fogs MIPS 1000/2000/3000/4000 (``wireless5.ini:116-119``),
    broker MIPS 0 (pure scheduler, ``:110``), 5 APs with ap4 as the hub
    (``wireless5.ned:103-126``), users 0..5 on CircleMobility (start angles
    30..180°, ``:23-33``), the rest LinearMobility; publish every 1.5 s;
    and the energy framework (``:150-166``): 0.05 J storage, initial charge
    uniform(10%, 100%), 4 mW alternating harvester, shutdown at 10% /
    restart at 50% — the reference's fault-injection mechanism.

    ``extra_aps`` (r5): a square grid of additional APs over the 1 km²
    area, alternately backhauled through router2/router11.  The
    reference's 5-AP layout serves its 10 users; benchmark worlds that
    scale ``numb_users`` to 10k keep a physical cell density this way
    (VERDICT r4 item 2: config 4 now runs the real Bianchi model over a
    realistic AP count instead of the ``mac_model="linear"`` escape
    hatch).
    """
    overrides.setdefault("energy_enabled", True)
    overrides.setdefault("energy_capacity_j", 0.05)
    overrides.setdefault("harvest_power_w", 4e-3)
    # AlternatingEpEnergyGenerator: generation/sleep ~ exponential(25 s)
    # (wireless5.ini:165-166) -> square wave, 50 s period, 50% duty
    overrides.setdefault("harvest_period_s", 50.0)
    overrides.setdefault("harvest_duty", 0.5)
    overrides.setdefault("shutdown_frac", 0.10)
    overrides.setdefault("start_frac", 0.50)
    spec = WorldSpec(
        n_users=numb_users, n_fogs=4, n_aps=5 + extra_aps,
        horizon=horizon, dt=dt,
        **_sized(overrides, horizon, 1.5),
    ).validate()
    g = InfraGraph()
    for a, b in ([("router1", "bb")] +
                 [("router1", f"cb{i}") for i in range(4)] +
                 [("router2", "bb"), ("router11", "bb"),
                  ("router2", "ap"), ("router11", "ap2"),
                  ("router11", "ap1"), ("router2", "ap3"),
                  ("ap4", "bb"), ("ap4", "ap"), ("ap4", "ap1"),
                  ("ap4", "ap2"), ("ap4", "ap3")]):
        g.link(a, b)
    ap_names = ["ap", "ap1", "ap2", "ap3", "ap4"]
    ap_pos = [(133.0, 172.0), (997.0, 566.0), (997.0, 172.0),
              (139.0, 566.0), (582.0, 330.0)]
    if extra_aps:
        side = int(np.ceil(np.sqrt(extra_aps)))
        step = 1000.0 / side
        for i in range(extra_aps):
            nm = f"apx{i}"
            g.link(nm, "router2" if i % 2 == 0 else "router11")
            ap_names.append(nm)
            ap_pos.append(
                (step * (i % side + 0.5), step * (i // side + 0.5))
            )
    rng = np.random.default_rng(seed)
    user_pos = rng.uniform((50, 50), (950, 950), (numb_users, 2))
    linear = {u: (20.0, 0.0) for u in range(numb_users)}
    circle = {}
    for u in range(min(6, numb_users)):
        del linear[u]
        circle[u] = (300.0, 300.0, 250.0, 40.0, _deg(30.0 * (u + 1)))
    return assemble(
        spec, g, seed=seed,
        fog_mips=(1000.0, 2000.0, 3000.0, 4000.0),
        fog_attach=("router1",) * 4, broker_attach="router1",
        ap_names=tuple(ap_names),
        ap_pos=tuple(ap_pos),
        # default 400 m ~ 3.5 mW transmit power (wireless5.ini:52); the
        # per-station contention coefficient is calibrated for the ini's
        # 10 users — scale it down when scaling numb_users up, or the
        # access delay saturates (physically: one 802.11 cell cannot carry
        # thousands of stations)
        ap_range=ap_range,
        w_contention=w_contention,
        mac_model=mac_model,
        user_pos=user_pos, linear=linear, circle=circle,
        area=(1000.0, 1000.0),
        energy_users=True, initial_energy_frac=(0.10, 1.0),
    )


def paper(horizon: float = 10.0, dt: float = 1e-3, seed: int = 0,
          **overrides):
    """``testing/paper.ned`` → WirelessNetwork6: the publication topology.

    4 fog nodes on separate routers, 7 APs, 17 wireless users + 1 wired
    static sensor (``paper.ned:31-188``).  No committed ini selects it
    (SURVEY.md §6), so v3 app defaults apply (publish every 1 s).
    """
    user_pos = [
        (710.0, 268.0), (320.0, 59.0), (725.0, 74.0), (109.0, 128.0),
        (471.0, 180.0), (109.0, 251.0), (497.0, 95.0), (816.0, 497.0),
        (725.0, 419.0), (421.0, 419.0), (131.0, 437.0), (922.0, 290.0),
        (870.0, 74.0), (274.0, 144.0), (344.0, 503.0), (679.0, 164.0),
        (589.0, 31.0), (301.0, 451.0),  # last = staticSensor (wired)
    ]
    U = len(user_pos)
    spec = WorldSpec(
        n_users=U, n_fogs=4, n_aps=7, horizon=horizon, dt=dt,
        **_sized(overrides, horizon, 1.0),
    ).validate()
    g = InfraGraph()
    for a, b in [("router1", "bb"), ("router2", "fn1a"), ("router1", "fn2a"),
                 ("router3", "fn3a"), ("router11", "fn4a"),
                 ("router2", "bb"), ("router11", "bb"), ("router3", "bb"),
                 ("router2", "ap"), ("router3", "ap4"), ("router11", "ap2"),
                 ("router11", "ap1"), ("router2", "ap3"), ("router2", "ap5"),
                 ("router11", "ap6")]:
        g.link(a, b)
    # the four "mobile*" hosts move; everyone else is stationary
    linear = {7: (20.0, 0.0), 13: (20.0, 0.0), 14: (20.0, 0.0),
              15: (20.0, 0.0)}
    return assemble(
        spec, g, seed=seed,
        fog_mips=(1000.0,) * 4,
        fog_attach=("router2", "router1", "router3", "router11"),
        broker_attach="router1",
        ap_names=("ap", "ap1", "ap2", "ap3", "ap4", "ap5", "ap6"),
        ap_pos=((363.0, 163.0), (783.0, 172.0), (909.0, 172.0),
                (197.0, 163.0), (566.0, 163.0), (197.0, 528.0),
                (909.0, 528.0)),
        ap_range=300.0,
        user_pos=user_pos, linear=linear,
        wired_users={U - 1: "router2"},  # staticSensor: StandardHost
        area=(1000.0, 600.0),
    )
