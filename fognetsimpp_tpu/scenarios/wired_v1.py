"""``testing/omnetpp.ini`` → Network: the wired v1 pub/sub smoke world.

The reference's first ladder rung (SURVEY.md §4): two standard users, two
fog nodes and the base broker all hanging off one router over identical
100 Mbps links (``network.ned:27-69``), running the generation-1 apps:

  * ``standardUser`` publishes fixed-size tasks (``MIPSRequired = 100``,
    ``mqttApp.cc:330``) on "test topic 1"; ``standardUser1`` publishes
    nothing and subscribes to topics 1 and 2 (``omnetpp.ini:18-21``).
  * ``BrokerBaseApp`` (v1) runs a task locally when its MIPS pool covers
    it (strict <, ``BrokerBaseApp.cc:171-180``) and otherwise offloads via
    the buggy compare-to-first MAX_MIPS scan (``:228-240``) —
    ``Policy.LOCAL_FIRST`` with ``broker_mips = 1000``.
  * ``ComputeBrokerApp`` (v1) fogs are MIPS pools (subtract on accept,
    reject when exhausted, ``ComputeBrokerApp.cc:285-320``).

v1 quirk ledger honoured here: ``app_gen=1`` records no status-6 ack for
offloaded tasks (the v1 broker logs and drops the fog's TaskAck,
``BrokerBaseApp.cc:142-147``), while broker-local completions do ack the
client directly (``:369-394``).  The reference even reads its *publish*
topics from the ``subscribeToTopics`` parameter (``mqttApp.cc:54`` — a
faithful-parity quirk we do not replicate; topics here are explicit).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core.engine import prime_initial_advertisements
from ..net.mobility import default_bounds
from ..net.topology import wired_star
from ..spec import BugCompat, FogModel, Policy, WorldSpec
from ..state import init_state


def build(
    horizon: float = 5.0,
    dt: float = 1e-3,
    send_interval: float = 0.05,
    broker_mips: float = 1000.0,
    fog_mips: float = 1000.0,
    seed: int = 0,
    max_sends_per_user: Optional[int] = None,
    **overrides,
):
    """Returns (spec, state, net, bounds) for the wired v1 world."""
    overrides.setdefault("app_gen", 1)
    overrides.setdefault("policy", int(Policy.LOCAL_FIRST))
    overrides.setdefault("fog_model", int(FogModel.POOL))
    overrides.setdefault("fixed_mips_required", 100)  # mqttApp.cc:330
    overrides.setdefault("adv_periodic", True)  # v1 re-advertises on a timer
    overrides.setdefault("adv_on_completion", False)
    overrides.setdefault("n_topics", 2)
    # Faithful v1: the broker's local pool is never refunded (the request
    # record push is commented out, BrokerBaseApp.cc:208), so the pool
    # drains over the first ~broker_mips/100 tasks and everything after
    # goes down the offload path — both branches get exercised.
    overrides.setdefault("bug_compat", BugCompat(local_pool_leak=True))
    if max_sends_per_user is None:
        max_sends_per_user = int(horizon / send_interval) + 4
    spec = WorldSpec(
        n_users=2,
        n_fogs=2,
        send_interval=send_interval,
        horizon=horizon,
        dt=dt,
        broker_mips=broker_mips,
        max_sends_per_user=max_sends_per_user,
        **overrides,
    ).validate()

    state = init_state(spec, jax.random.PRNGKey(seed))
    mips = jnp.full((2,), fog_mips, jnp.float32)
    state = state.replace(fogs=state.fogs.replace(mips=mips, pool_avail=mips))
    # pub/sub split (omnetpp.ini:18-21): user 0 publishes topic 0; user 1
    # is subscribe-only on topics 0 and 1
    users = state.users.replace(
        publisher=jnp.asarray([True, False]),
        pub_topic=jnp.asarray([0, 0], jnp.int32),
        sub_mask=jnp.asarray([[False, False], [True, True]]),
    )
    state = state.replace(users=users)

    net = wired_star(spec.n_nodes, link_delay=1e-7, rate=100e6,
                     packet_bytes=spec.task_bytes)
    state = prime_initial_advertisements(spec, state, net)
    return spec, state, net, default_bounds(1000.0)
