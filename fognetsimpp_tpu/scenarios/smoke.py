"""Wired smoke scenario: 2 users, 2 fog nodes, 1 base broker.

The batched-engine rendition of the reference's wired integration smoke test
``simulations/testing/omnetpp.ini:2`` -> network ``Network``
(``simulations/testing/network.ned:27-69``): users and fog nodes hang off one
router over identical 100 Mbps Ethernet links, clients publish compute tasks
to the base broker which offloads to the least-busy fog node.

Also the "minimum end-to-end slice" of SURVEY.md §7 and the shape used by the
C++-DES parity gate (tests/test_parity.py).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..core.engine import prime_initial_advertisements
from ..net.mobility import MobilityBounds, default_bounds
from ..net.topology import NetParams, wired_star
from ..spec import Policy, WorldSpec
from ..state import WorldState, init_state


def build(
    n_users: int = 2,
    n_fogs: int = 2,
    fog_mips: Sequence[float] = (1000.0, 2000.0),
    send_interval: float = 0.05,
    horizon: float = 3.35,
    dt: float = 1e-3,
    link_delay: float = 1e-4,
    policy: int = int(Policy.MIN_BUSY),
    seed: int = 0,
    max_sends_per_user: Optional[int] = None,
    **spec_overrides,
):
    """Returns (spec, state, net, bounds) for the wired smoke world."""
    if max_sends_per_user is None:
        max_sends_per_user = int(horizon / send_interval) + 4
    # all nodes are stationary on a wired star: the association/delay
    # cache is constant, so the engine may hoist it out of the scan
    # (spec.assume_static) unless a liveness-mutating subsystem is on
    # (the energy lifecycle, or chaos crash/recover schedules)
    spec_overrides.setdefault(
        "assume_static",
        not (
            spec_overrides.get("energy_enabled", False)
            or spec_overrides.get("chaos", False)
        ),
    )
    spec = WorldSpec(
        n_users=n_users,
        n_fogs=n_fogs,
        send_interval=send_interval,
        horizon=horizon,
        dt=dt,
        policy=policy,
        max_sends_per_user=max_sends_per_user,
        **spec_overrides,
    ).validate()

    state = init_state(spec, jax.random.PRNGKey(seed))
    # heterogeneous fog MIPS like wireless5.ini:116-119
    mips = jnp.asarray(
        [fog_mips[i % len(fog_mips)] for i in range(n_fogs)], jnp.float32
    )
    state = state.replace(
        fogs=state.fogs.replace(mips=mips, pool_avail=mips)
    )
    # spread nodes on a line (positions irrelevant for wired delay)
    n = spec.n_nodes
    pos = jnp.stack(
        [jnp.linspace(0.0, 100.0, n), jnp.zeros((n,))], axis=-1
    ).astype(jnp.float32)
    state = state.replace(nodes=state.nodes.replace(pos=pos))

    net = wired_star(spec.n_nodes, link_delay=link_delay, packet_bytes=spec.task_bytes)
    state = prime_initial_advertisements(spec, state, net)
    return spec, state, net, default_bounds(1000.0)
