"""``example/wirelessNet.ini`` → WirelessNet: the shipped v2 demo.

One 802.11 user circling (300, 300) at r=250 m / 40 mps
(``wirelessNet.ini:13-18``), publishing a task every 50 ms; five fog nodes
(MIPS 1000, the v2 MIPS-pool model) behind routerD; three APs each
backhauled through an own router to the broker (``wirelessNet.ned:94-114``).
Apps are generation 2: ``BrokerBaseApp2`` / ``ComputeBrokerApp2`` /
``mqttApp2`` (``wirelessNet.ini:56,62``).

The v2 base broker is a *hybrid*: ``MIPSRequired < MIPS`` runs locally on
the broker's own 1000-MIPS pool (``wirelessNet.ini:58``,
``BrokerBaseApp2.cc:181``); only pool-exhausted publishes offload via the
buggy MAX_MIPS scan — with all fogs advertising 1000 MIPS the winner is
always the first registered fog, which is why the committed run's
ComputeBroker1 received every forwarded task (1 Connack + 4 tasks = 5
"packets received") while ComputeBroker2–5 received only their Connack
(``example/results/General-0.sca``).  The pool only exhausts because
releaseResource runs off ONE shared self-message (each accept cancels the
pending release, spec.v2_local_broker) — during the sub-requiredTime
warm-up burst the pool leaks and a handful of offloads escape.

Calibration (r5, mechanistic): the reference's only committed ground
truth is this run's ``delay`` vector — publish→broker transit, mean
0.502 s (n=52, min 0.401, max 0.981; BASELINE.md).  Mapping each
committed sample back to its creation index (``creation = arrival −
delay``, ``example/results/General-0.vec`` vector 1093) shows the run is
DETERMINISTIC: creations k=0..13 all drain (a 7-packet burst 1.0414 →
1.0755 s, then a ~48 ms trickle to 1.4116 s), creations k=14..19 — the
last six before the 1.0414 s link-up — are ALL absent (the bounded
pending queue overflowed while the link established), and every
post-link-up creation k=20..57 arrives at a constant 0.4015 s transit
with ZERO loss (k ≥ 58 would arrive past the 3.35 s horizon).  r1–r4
modelled the 14 missing samples as a fitted 26% uniform steady-state
loss — which reproduces the counts only by seed luck and places losses
where the trace has none.  ``link_buffer_frames = 14`` replaces it: the
warm-up buffer keeps the first 14 creations, overflow is deterministic,
steady loss is exactly 0.  All four anchored statistics (n/mean/min/max)
are now seed-independent, and the steady-state segment is *predicted*
from warm-up-only fits (tests/test_calibration_holdout.py).
"""
from __future__ import annotations

from ..spec import FogModel, Policy, WorldSpec
from .wireless import InfraGraph, assemble, _deg

# Fitted against simulations/example/results/General-0.vec vector 1093
# (and the .sca sent-vs-recorded counts: 67 sent, 52 delay samples):
CALIB_START = 0.06  # first publish creation time in the committed run
CALIB_LINK_UP = 1.0414  # link-up instant (max delay = 1.0414 - 0.06)
CALIB_BURST_N = 7  # packets in the fast drain burst (vec: 1.0414..1.0755)
CALIB_DRAIN = 0.0056833  # burst gap ((1.0755-1.0414)/6)
CALIB_DRAIN2 = 0.0480143  # backlog trickle gap ((1.4116-1.0755)/7)
CALIB_BUFFER = 14  # pending-queue capacity: creations 14..19 overflowed
CALIB_W_BASE = 0.4013  # steady transit 0.4015 minus the wired core hops
CALIB_AP_RANGE = 600.0
CALIB_BROKER_MIPS = 1000.0  # wirelessNet.ini:58


def build(horizon: float = 3.35, dt: float = 1e-3, seed: int = 0,
          send_interval: float = 0.05, w_base: float = CALIB_W_BASE,
          **overrides):
    """Returns (spec, state, net, bounds) for the WirelessNet demo world.

    ``w_base`` (steady wireless transit) is exposed so the hold-out
    validation can rebuild the world from its own warm-up-only fit.
    """
    overrides.setdefault("app_gen", 2)
    overrides.setdefault("fog_model", int(FogModel.POOL))
    # the v2 hybrid broker: local pool first, MAX_MIPS offload overflow
    overrides.setdefault("policy", int(Policy.LOCAL_FIRST))
    overrides.setdefault("broker_mips", CALIB_BROKER_MIPS)
    overrides.setdefault("v2_local_broker", True)
    overrides.setdefault("adv_on_completion", False)
    overrides.setdefault("adv_periodic", True)
    overrides.setdefault("required_time", 0.01)
    # app-level connect completes before the first publish in the trace;
    # the observable startup transient is link-level (warm-up block above)
    overrides.setdefault("connect_gating", False)
    overrides.setdefault("start_time_min", CALIB_START)
    overrides.setdefault("start_time_max", CALIB_START + 1e-6)
    overrides.setdefault("link_up_s", CALIB_LINK_UP)
    overrides.setdefault("link_drain_s", CALIB_DRAIN)
    overrides.setdefault("link_burst_n", CALIB_BURST_N)
    overrides.setdefault("link_drain2_s", CALIB_DRAIN2)
    overrides.setdefault("link_buffer_frames", CALIB_BUFFER)
    overrides.setdefault("task_bytes", 1024)  # messageLength = 1024B
    spec = WorldSpec(
        n_users=1, n_fogs=5, n_aps=3,
        send_interval=send_interval, horizon=horizon, dt=dt,
        max_sends_per_user=int(horizon / send_interval) + 4,
        **overrides,
    ).validate()
    g = InfraGraph()
    for a, b in ([("ap5", "ap"), ("ap3", "ap"),
                  ("ap", "router1"), ("ap3", "router3"), ("ap5", "router5"),
                  ("router1", "bb"), ("router3", "bb"), ("router5", "bb"),
                  ("routerD", "bb")] +
                 [("routerD", f"cb{i}") for i in range(5)]):
        g.link(a, b)
    return assemble(
        spec, g, seed=seed,
        fog_mips=(1000.0,) * 5, fog_attach=("routerD",) * 5,
        broker_attach="routerD",
        ap_names=("ap", "ap3", "ap5"),
        ap_pos=((109.0, 508.0), (374.0, 185.0), (654.0, 508.0)),
        ap_range=CALIB_AP_RANGE,
        user_pos=((550.0, 300.0),),
        circle={0: (300.0, 300.0, 250.0, 40.0, _deg(360.0))},
        area=(784.0, 1014.0),
        w_base=w_base,
        w_contention=0.0,  # single station: steady transit is constant
    )
