"""``example/wirelessNet.ini`` → WirelessNet: the shipped v2 demo.

One 802.11 user circling (300, 300) at r=250 m / 40 mps
(``wirelessNet.ini:13-18``), publishing a task every 50 ms; five fog nodes
(MIPS 1000, the v2 MIPS-pool model) behind routerD; three APs each
backhauled through an own router to the broker (``wirelessNet.ned:94-114``).
Apps are generation 2: ``BrokerBaseApp2`` / ``ComputeBrokerApp2`` /
``mqttApp2`` (``wirelessNet.ini:56,62``) — POOL fogs with periodic
advertisement, the v1/v2 offload scan, requiredTime expiry.

Calibration: the reference's only committed ground truth is this run's
``delay`` vector — publish→broker transit, mean 0.502 s (n=52, min 0.401,
max 0.981; BASELINE.md).  Reading the committed samples
(``example/results/General-0.vec`` vector 1093) shows two regimes: a
~1.04 s link warm-up during which the first 12 publishes buffer below the
app and then drain as a burst (first sample's delay is exactly
``link_up - app_start`` = 0.9814), settling to a *constant* 0.4015 s
steady-state transit.  The parameters below reproduce both: ``link_up_s``/
``link_drain_s`` model the warm-up (``WorldSpec`` link warm-up block) and
``w_base`` carries the steady transit.
``tests/test_scenarios.py::test_example_matches_committed_trace`` pins the
resulting mean/min/max/n to the committed trace.
"""
from __future__ import annotations

from ..spec import FogModel, Policy, WorldSpec
from .wireless import InfraGraph, assemble, _deg

# Fitted against simulations/example/results/General-0.vec vector 1093
# (and the .sca sent-vs-recorded counts: 67 sent, 52 delay samples):
CALIB_START = 0.06  # first publish creation time in the committed run
CALIB_LINK_UP = 1.0414  # link-up instant (max delay = 1.0414 - 0.06)
CALIB_DRAIN = 0.0237  # backlog drain spacing -> trace mean 0.502
CALIB_W_BASE = 0.4013  # steady transit 0.4015 minus the wired core hops
CALIB_LOSS = 0.26  # steady-state uplink loss (~14 of 54 post-warm-up)
CALIB_AP_RANGE = 600.0


def build(horizon: float = 3.35, dt: float = 1e-3, seed: int = 0,
          send_interval: float = 0.05, **overrides):
    """Returns (spec, state, net, bounds) for the WirelessNet demo world."""
    overrides.setdefault("app_gen", 2)
    overrides.setdefault("fog_model", int(FogModel.POOL))
    overrides.setdefault("policy", int(Policy.MAX_MIPS))
    overrides.setdefault("adv_on_completion", False)
    overrides.setdefault("adv_periodic", True)
    overrides.setdefault("required_time", 0.01)
    # app-level connect completes before the first publish in the trace;
    # the observable startup transient is link-level (warm-up block above)
    overrides.setdefault("connect_gating", False)
    overrides.setdefault("start_time_min", CALIB_START)
    overrides.setdefault("start_time_max", CALIB_START + 1e-6)
    overrides.setdefault("link_up_s", CALIB_LINK_UP)
    overrides.setdefault("link_drain_s", CALIB_DRAIN)
    overrides.setdefault("uplink_loss_prob", CALIB_LOSS)
    overrides.setdefault("task_bytes", 1024)  # messageLength = 1024B
    spec = WorldSpec(
        n_users=1, n_fogs=5, n_aps=3,
        send_interval=send_interval, horizon=horizon, dt=dt,
        max_sends_per_user=int(horizon / send_interval) + 4,
        **overrides,
    ).validate()
    g = InfraGraph()
    for a, b in ([("ap5", "ap"), ("ap3", "ap"),
                  ("ap", "router1"), ("ap3", "router3"), ("ap5", "router5"),
                  ("router1", "bb"), ("router3", "bb"), ("router5", "bb"),
                  ("routerD", "bb")] +
                 [("routerD", f"cb{i}") for i in range(5)]):
        g.link(a, b)
    return assemble(
        spec, g, seed=seed,
        fog_mips=(1000.0,) * 5, fog_attach=("routerD",) * 5,
        broker_attach="routerD",
        ap_names=("ap", "ap3", "ap5"),
        ap_pos=((109.0, 508.0), (374.0, 185.0), (654.0, 508.0)),
        ap_range=CALIB_AP_RANGE,
        user_pos=((550.0, 300.0),),
        circle={0: (300.0, 300.0, 250.0, 40.0, _deg(360.0))},
        area=(784.0, 1014.0),
        w_base=CALIB_W_BASE,
        w_contention=0.0,  # single station: steady transit is constant
    )
