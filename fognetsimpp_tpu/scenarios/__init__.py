"""Scenario builders: the reference's simulation ladder re-expressed.

Each module builds (spec, state, net, bounds) for one of the reference's
scenarios (SURVEY.md §4 table); `smoke` is the wired integration shape.
"""
from . import example, smoke, wired_v1, wireless  # noqa: F401
