"""Queue-fed live ingestion: the twin's input door (ISSUE 17a).

External arrival requests — HTTP ``POST /ingest`` next to the serving
endpoint's ``GET /metrics``, or the in-process :meth:`IngestQueue.feed`
API — land in a BOUNDED, drop-counted host-side queue.  At every chunk
boundary the serve loop drains up to ``spec.ingest_batch`` rows and
hands them to the engine's compiled injector
(:func:`~fognetsimpp_tpu.core.engine.inject_arrivals`): injected
publishes enter the simulation through the established K-window
contract, stamped at the boundary's sim time.  The compiled tick never
hosts a transfer — injection happens strictly BETWEEN chunks
(``tools/hloaudit``'s ``tick_ingest`` variant pins the tick clean).

**Flight-recorder discipline, extended to inputs**: every drained batch
is appended to the session's arrival log (``ticks_done`` + rows), and
:func:`make_replay_inject` turns a saved log back into the inject hook
— because the injector is draw-free (a pure function of state and
batch), a live session replayed from its log reproduces every chunk
state hash bit-exactly.  That is the twin's bisection story:
``tools/postmortem.py --diff`` works across a replay.

Queue depth / accepted / dropped / injected / latency ride the
``fns_twin_ingest_*`` OpenMetrics families, the /healthz ``ingest``
section and the watchdog's ``ingest_depth`` signal (all fed from ONE
:meth:`IngestQueue.stats` dict, the single-source discipline).
"""
from __future__ import annotations

import collections
import json
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .gates import ingest_off_error, payload_error


class IngestQueue:
    """Bounded, drop-counted, thread-safe arrival queue + arrival log.

    ``feed`` is the in-process producer API (tests, bench, embedding
    services); :meth:`handle_http` is the same producer behind ``POST
    /ingest`` (installed on the HealthServer's route hook by
    :func:`serve_ingest_run`).  A feed past ``capacity`` is DROPPED and
    counted — never blocks, never grows host memory — the bounded-ring
    FlightRecorder discipline.
    """

    def __init__(self, capacity: int = 1024):
        if capacity < 1:
            raise ValueError(
                f"ingest queue capacity must be >= 1, got {capacity}"
            )
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._q: collections.deque = collections.deque()
        self.accepted = 0
        self.dropped = 0
        self.injected = 0  # landed into simulation state
        self.rejected = 0  # drained but refused by the injector
        self.latency_s = 0.0  # feed->injection wall latency, last batch
        #: the arrival log: one entry per NON-EMPTY drained batch,
        #: ``{"ticks_done": t, "user": [...], "mips": [...]}`` — the
        #: session's replayable input record
        self.log: List[Dict] = []

    def feed(self, user: int, mips: float) -> bool:
        """Queue one arrival; False (and a drop count) when full."""
        row = (int(user), float(mips), time.monotonic())
        with self._lock:
            if len(self._q) >= self.capacity:
                self.dropped += 1
                return False
            self._q.append(row)
            self.accepted += 1
            return True

    def feed_rows(self, rows: Sequence[Sequence]) -> Tuple[int, int]:
        """Queue many ``(user, mips)`` rows; returns (accepted, dropped)."""
        acc = drop = 0
        for r in rows:
            if self.feed(r[0], r[1]):
                acc += 1
            else:
                drop += 1
        return acc, drop

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._q)

    def drain(self, max_n: int) -> Tuple[List[int], List[float], float]:
        """Pop up to ``max_n`` rows in feed order.

        Returns ``(users, mips, oldest_feed_monotonic)`` — the third
        element feeds the injected-latency gauge (0.0 when empty).
        Rows beyond ``max_n`` stay queued for the next boundary.
        """
        users: List[int] = []
        mips: List[float] = []
        oldest = 0.0
        with self._lock:
            while self._q and len(users) < max_n:
                u, m, t = self._q.popleft()
                if not users:
                    oldest = t
                users.append(u)
                mips.append(m)
        return users, mips, oldest

    def note_injected(
        self, n_injected: int, n_rejected: int, latency_s: float
    ) -> None:
        with self._lock:
            self.injected += int(n_injected)
            self.rejected += int(n_rejected)
            self.latency_s = float(latency_s)

    def stats(self) -> Dict:
        """The single source every exposition reads (openmetrics
        ``fns_twin_ingest_*``, /healthz ``ingest``, the watchdog's
        ``ingest_depth`` signal, post-mortem chunk extras)."""
        with self._lock:
            return {
                "depth": len(self._q),
                "capacity": self.capacity,
                "accepted": self.accepted,
                "dropped": self.dropped,
                "injected": self.injected,
                "rejected": self.rejected,
                "latency_s": round(self.latency_s, 6),
            }

    # ---- HTTP producer (the HealthServer route hook) -----------------
    def handle_http(
        self, method: str, path: str, body: bytes
    ) -> Optional[Tuple[int, str, str]]:
        """``POST /ingest`` handler; None for any other route."""
        if not path.split("?", 1)[0].rstrip("/").endswith("/ingest"):
            return None
        if method != "POST":
            return (405, "text/plain", "error: POST /ingest only\n")
        status, payload = self.ingest_payload(body)
        return (status, "application/json", json.dumps(payload) + "\n")

    def ingest_payload(self, body: bytes) -> Tuple[int, Dict]:
        """Parse + queue one ingest payload; (HTTP status, response).

        Accepted shapes: ``{"user": u, "mips": m}`` or ``{"rows":
        [[u, m], ...]}``.  Anything else is a 400 with the one-line
        ``[TWIN-PAYLOAD]`` error — malformed traffic must never kill
        the serving loop.
        """
        try:
            doc = json.loads(body.decode() or "null")
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            return 400, {"error": payload_error(f"invalid JSON ({e})")}
        if isinstance(doc, dict) and "rows" in doc:
            rows = doc["rows"]
            if not isinstance(rows, list):
                return 400, {"error": payload_error("rows is not a list")}
        elif isinstance(doc, dict) and "user" in doc:
            rows = [[doc["user"], doc.get("mips", 0)]]
        else:
            return 400, {
                "error": payload_error("neither 'user' nor 'rows' given")
            }
        clean: List[Tuple[int, float]] = []
        for r in rows:
            if (
                not isinstance(r, (list, tuple)) or len(r) != 2
                or isinstance(r[0], bool)
                or not isinstance(r[0], int)
                or isinstance(r[1], bool)
                or not isinstance(r[1], (int, float))
                or r[0] < 0 or not (float(r[1]) >= 0.0)
            ):
                return 400, {
                    "error": payload_error(
                        f"row {r!r} is not [user >= 0, mips >= 0]"
                    )
                }
            clean.append((r[0], float(r[1])))
        acc, drop = self.feed_rows(clean)
        return 200, {"accepted": acc, "dropped": drop, "depth": self.depth}

    # ---- arrival-log persistence (replay-from-inputs) ----------------
    def save_log(self, path: str) -> None:
        """Write the arrival log as JSON (the input flight record)."""
        with self._lock:
            doc = {"capacity": self.capacity, "entries": list(self.log)}
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=1)


def load_log(path: str) -> List[Dict]:
    """Read an arrival log written by :meth:`IngestQueue.save_log`."""
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    return list(doc["entries"])


def make_inject(spec, net, queue: IngestQueue) -> Callable:
    """The chunk-boundary drain hook for ``run_chunked(inject=...)``.

    Drains up to ``spec.ingest_batch`` queued rows, lands them through
    the compiled injector, appends the batch to the session's arrival
    log and updates the queue's injected/rejected/latency counters.
    An empty queue is a no-op (no log entry — the log records inputs,
    not boundaries).
    """
    from ..core.engine import inject_arrivals

    if not spec.ingest:
        raise ValueError(ingest_off_error())

    def inject(state, ticks_done: int):
        users, mips, oldest = queue.drain(spec.ingest_batch)
        if not users:
            return state
        state, n_inj, n_rej = inject_arrivals(spec, state, net, users, mips)
        queue.note_injected(
            n_inj, n_rej,
            (time.monotonic() - oldest) if oldest else 0.0,
        )
        queue.log.append({
            "ticks_done": int(ticks_done),
            "user": list(users),
            "mips": list(mips),
        })
        return state

    return inject


def make_replay_inject(
    spec, net, log: Sequence[Dict],
    queue: Optional[IngestQueue] = None,
) -> Callable:
    """Re-run a recorded arrival log as the inject hook.

    Because injection is draw-free and the log records exactly what
    was INJECTED (post-drain) at which ``ticks_done``, replaying under
    the same spec/chunking reproduces every chunk state hash of the
    original session bit-exactly — the determinism rail
    tests/test_twin.py asserts and ``tools/postmortem.py --diff``
    leans on.  When ``queue`` is given, replayed injections count into
    its stats and re-record its arrival log, so the replay session's
    exposition/bundle matches the original's (and replay-then-save
    round-trips the log).
    """
    from ..core.engine import inject_arrivals

    if not spec.ingest:
        raise ValueError(ingest_off_error())
    by_tick: Dict[int, List[Dict]] = {}
    for e in log:
        by_tick.setdefault(int(e["ticks_done"]), []).append(e)

    def inject(state, ticks_done: int):
        for e in by_tick.get(int(ticks_done), ()):
            state, n_inj, n_rej = inject_arrivals(
                spec, state, net, e["user"], e["mips"]
            )
            if queue is not None:
                queue.note_injected(n_inj, n_rej, 0.0)
                queue.log.append({
                    "ticks_done": int(ticks_done),
                    "user": list(e["user"]),
                    "mips": list(e["mips"]),
                })
        return state

    return inject


def chain_hooks(*hooks) -> Callable:
    """Compose HealthServer route hooks: first non-None answer wins."""
    live = [h for h in hooks if h is not None]

    def hook(method: str, path: str, body: bytes):
        for h in live:
            out = h(method, path, body)
            if out is not None:
                return out
        return None

    return hook


def serve_ingest_run(
    spec,
    state,
    net,
    bounds=None,
    queue: Optional[IngestQueue] = None,
    capacity: int = 1024,
    port: Optional[int] = 0,
    replay_log: Optional[Sequence[Dict]] = None,
    whatif: bool = True,
    whatif_ticks: int = 256,
    **serve_kwargs,
):
    """`serve_run` with the twin's doors wired (the live-twin entry).

    Creates (or reuses) the :class:`IngestQueue`, installs ``POST
    /ingest`` and ``POST /whatif`` on the health server's route hook,
    threads the chunk-boundary drain into ``run_chunked`` and the
    queue stats into the exposition/watchdog.  ``replay_log`` swaps the
    queue drain for a recorded arrival log — the bit-exact replay mode.

    Returns ``(final_state, status)`` with ``status["ingest"]`` holding
    the queue's final stats and ``status["arrival_log"]`` the session's
    recorded inputs.
    """
    from ..telemetry.live import HealthServer, serve_run
    from .whatif import WhatIfDoor

    if not spec.ingest:
        raise ValueError(ingest_off_error())
    queue = queue or IngestQueue(capacity=capacity)
    if replay_log is not None:
        inject = make_replay_inject(spec, net, replay_log, queue=queue)
    else:
        inject = make_inject(spec, net, queue)
    door = None
    if whatif:
        door = WhatIfDoor(spec, net, bounds, default_ticks=whatif_ticks)
        door.update(state, 0)  # pre-first-chunk carry: askable immediately
        inject = door.wrap_inject(inject)
    server = serve_kwargs.pop("server", None)
    if server is None and port is not None:
        server = HealthServer(port=port)
    if server is not None:
        server.set_handler(chain_hooks(
            queue.handle_http, door.handle_http if door else None
        ))
    final, status = serve_run(
        spec, state, net, bounds,
        port=None, server=server,
        inject=inject, ingest=queue,
        **serve_kwargs,
    )
    status["ingest"] = queue.stats()
    status["arrival_log"] = list(queue.log)
    return final, status
