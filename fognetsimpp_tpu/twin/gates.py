"""Composition gates of the twin subsystem — the ``[TWIN-*]`` clauses.

This module OWNS the ``TWIN-*`` clause-ID family (``tools/featmat``'s
``OWNER_OF``): every rejection the twin layer can raise leads with a
stable bracketed ID defined exactly once here, and the CLI cites these
IDs instead of re-wording them — the anti-drift discipline
``core/engine.tp_reject_reason`` established.  Each rejected cell of
the feature-composition matrix has a test asserting its ID
(``tests/test_cli_errors.py``), and deleting a clause without flipping
its matrix cell fails ``python -m tools.featmat --check`` in CI.
"""
from __future__ import annotations

from typing import Optional


def ingest_reject_reason(runner: str) -> Optional[str]:
    """Why live ingestion cannot ride the given production runner
    (``None`` = it can).

    The injection phase lands single-device chunk boundaries: the
    sharded runners would need a cross-shard scatter of the arrival
    batch plus per-replica queue demultiplexing — neither exists yet
    (the rejection matrix names the work, ROADMAP open item 1).
    """
    if runner == "tp":
        return (
            "[TWIN-INGEST-TP] live ingestion lands arrivals at "
            "single-device chunk boundaries; the TP runner's sharded "
            "task table would need a cross-shard injection scatter — "
            "serve the twin unsharded (drop --tp) or run --tp without "
            "--ingest"
        )
    if runner == "fleet":
        return (
            "[TWIN-INGEST-FLEET] live ingestion feeds ONE live "
            "session; the fleet batches R independent replicas and "
            "has no per-replica arrival demultiplex — drop --replicas "
            "or --ingest"
        )
    return None


def ingest_needs_serve_error() -> str:
    """One-line error for ``--ingest``/``--replay-arrivals`` without the
    serving loop that owns the chunk boundaries."""
    return (
        "[TWIN-INGEST-SERVE] live ingestion drains at the serving "
        "loop's chunk boundaries; --ingest/--replay-arrivals need "
        "--serve PORT"
    )


def whatif_reject_reason(
    *, fleet: bool = False, promote: bool = True
) -> Optional[str]:
    """Why a what-if fork cannot be served (``None`` = it can).

    The TP clause ([TWIN-WHATIF-TP]) was deleted by ISSUE 20: a TP
    chunk-boundary carry now leaves the mesh through
    ``parallel.taskshard.unstamp_tp_carry`` and forks onto the knob
    grid like any single-device carry.
    """
    if fleet:
        return (
            "[TWIN-WHATIF-FLEET] what-if forks already vmap the live "
            "carry over the knob grid; layering that onto the fleet's "
            "replica batch would nest vmaps the runner does not "
            "compile — fork from a single live session (drop "
            "--replicas)"
        )
    if not promote:
        return (
            "[TWIN-WHATIF-STATIC] what-if grids ride the promoted "
            "DynSpec operand (one compiled program, K knob rows); the "
            "static-spec path (FNS_SPEC_PROMOTE=0) would compile per "
            "cell — re-enable promotion"
        )
    return None


def ingest_off_error() -> str:
    """One-line error for feeding a world whose ingest gate is off."""
    return (
        "[TWIN-INGEST-OFF] this world was built without the ingestion "
        "gate: injection is compiled out (the bit-exactness contract); "
        "rebuild with spec.ingest=True (--ingest)"
    )


def payload_error(detail: str) -> str:
    """One-line error for a malformed ingest payload (HTTP 400)."""
    return (
        f"[TWIN-PAYLOAD] malformed ingest payload: {detail}; expected "
        'JSON {"user": <int>, "mips": <number>} or '
        '{"rows": [[user, mips], ...]}'
    )


def front_reject_reason(runner: str) -> Optional[str]:
    """Why the multi-tenant front door cannot ride the given runner
    (``None`` = it can; ``"solo"`` = no serving endpoint at all)."""
    if runner == "tp":
        return (
            "[TWIN-FRONT-TP] the front door round-robins single-device "
            "tenant sessions through one shared program; the TP "
            "sharded chunk loop is a different executable per mesh — "
            "serve tenants unsharded (drop --tp)"
        )
    if runner == "fleet":
        return (
            "[TWIN-FRONT-FLEET] the front door multiplexes N "
            "INDEPENDENT live sessions (own carry, recorder, watchdog "
            "each); the fleet batches replicas of one spec inside one "
            "jitted call — drop --replicas/--mesh or --tenants"
        )
    if runner == "solo":
        return (
            "[TWIN-FRONT-SERVE] --tenants multiplexes live sessions "
            "behind one HTTP endpoint; it needs --serve PORT"
        )
    return None


def whatif_payload_error(detail: str) -> str:
    """One-line error for a malformed ``/whatif`` request (HTTP 400)."""
    return (
        f"[TWIN-WHATIF-PAYLOAD] malformed what-if payload: {detail}; "
        "expected "
        'JSON {"knobs": {"<promoted field>": [values...]}, '
        '"ticks": <int>}'
    )


def admission_error(label: str, capacity: int) -> str:
    """One-line error for tenant admission past the capacity bound."""
    return (
        f"[TWIN-CAP] front door at capacity ({capacity} tenant"
        f"{'s' if capacity != 1 else ''}): cannot admit {label!r}; "
        "evict a tenant or raise the admission bound (--tenant-cap)"
    )
