"""Digital-twin serving subsystem (ISSUE 17): the front door that turns
``--serve`` from a replayed batch job into a service.

Three coupled capabilities, layered over ``run_chunked``/``serve_run``:

* :mod:`~fognetsimpp_tpu.twin.ingest` — queue-fed arrivals: a bounded,
  drop-counted host-side queue (HTTP ``POST /ingest`` + in-process
  ``feed()``) drained at each chunk boundary into next-chunk arrival
  state through the engine's contract-registered injection phase, with
  every accepted batch appended to a recorded arrival log so any live
  session replays bit-exactly from its inputs.
* :mod:`~fognetsimpp_tpu.twin.whatif` — state-forked what-if grids:
  fork the chunk-boundary carry onto a promoted-knob grid
  (``sweep_dyn_from``) and answer "p95/energy/defer under these K
  retunings, starting from current state, H ticks ahead" in one
  vmapped compile — zero compile events warm.
* :mod:`~fognetsimpp_tpu.twin.front` — multi-tenant front door: N
  independent serve sessions multiplexed over the shared bucketed
  program registry, with capacity-bounded admission, round-robin chunk
  scheduling, per-tenant flight recorders and per-tenant
  ``/metrics``-``/healthz``-``/whatif`` routing (the FogMQ shape,
  arXiv:1610.00620: broker federation as a SERVICE, not a batch job).

Composition limits carry stable ``[TWIN-*]`` clause IDs
(:mod:`~fognetsimpp_tpu.twin.gates`, machine-checked by
``tools/featmat``).
"""
from .front import FrontDoor  # noqa: F401
from .ingest import IngestQueue, make_inject, serve_ingest_run  # noqa: F401
from .whatif import run_whatif  # noqa: F401
