"""Multi-tenant serve front door (ISSUE 17c): N live sessions, one
compiled program.

The FogMQ shape (arXiv:1610.00620) as a SERVICE: instead of one
``--serve`` loop owning the process, a :class:`FrontDoor` multiplexes
up to ``capacity`` independent serve sessions over the SHARED bucketed
program registry.  Each admitted tenant's population is padded to its
shape bucket (:func:`~fognetsimpp_tpu.dynspec.bucket_spec`), its spec
split into ``(shape key, DynSpec)``
(:func:`~fognetsimpp_tpu.dynspec.split_spec`) — so tenants with nearby
populations and different promoted knob values all execute the SAME
jitted chunk program (:func:`_tenant_chunk`'s cache size stays 1, the
front-door rail's assertion), round-robin one chunk per
:meth:`FrontDoor.step`.

Per tenant, the whole single-session health plane is replicated in
miniature: its own bounded :class:`~fognetsimpp_tpu.telemetry.live.
FlightRecorder` (chunk rows + state hashes, post-mortem-diffable per
tenant), its own :class:`~fognetsimpp_tpu.telemetry.live.Watchdog`,
its own optional ingestion queue and what-if door.  The shared HTTP
endpoint routes by tenant label — ``/t/<label>/metrics``,
``/t/<label>/healthz``, ``/t/<label>/ingest``, ``/t/<label>/whatif``
— while the root ``/metrics`` serves the tenant-labeled aggregate
(``fns_twin_tenant_*{tenant="i"}``,
:func:`~fognetsimpp_tpu.telemetry.openmetrics.render_twin_openmetrics`).

Admission past ``capacity`` raises the one-line ``[TWIN-CAP]`` clause
(:mod:`~fognetsimpp_tpu.twin.gates`); :meth:`FrontDoor.evict` frees a
slot.
"""
from __future__ import annotations

import collections
import functools
import json
import math
import threading
from typing import Dict, List, Optional, Tuple

import jax

from ..core.engine import run
from .gates import admission_error
from .ingest import IngestQueue
from .whatif import WhatIfDoor


# simlint: disable=R6 -- the front door round-robins N tenant carries
# through this ONE shared program; donating a tenant's carry would
# invalidate the state the door must still hold (and re-serve on
# /metrics) between that tenant's turns
@functools.partial(jax.jit, static_argnums=(0, 1))
def _tenant_chunk(run_spec, n_ticks, state, net, bounds, dyn):
    """One tenant chunk under the shared bucketed program.

    jit-cached on ``(shape key, chunk ticks)`` — every tenant in the
    same bucket reuses one executable whatever its promoted knob
    values (``dyn``) are.  Non-donating: tenant carries interleave.
    """
    final, _ = run(run_spec, state, net, bounds, n_ticks=n_ticks, dyn=dyn)
    return final


class Tenant:
    """One admitted serve session: carry + per-tenant health plane."""

    def __init__(self, label, spec, run_spec, dyn, state, net, bounds,
                 queue, door, watchdog, recorder):
        self.label = label
        self.spec = spec
        self.run_spec = run_spec
        self.dyn = dyn
        self.state = state
        self.net = net
        self.bounds = bounds
        self.queue: Optional[IngestQueue] = queue
        self.door: Optional[WhatIfDoor] = door
        self.watchdog = watchdog
        self.recorder = recorder
        self.ticks_done = 0
        self.chunks = 0
        self.next_row = 0
        self.metrics_text = "# EOF\n"
        self.health: Dict = {"status": "admitted", "ticks_done": 0}


class FrontDoor:
    """Capacity-bounded multiplexer of live serve sessions.

    ``capacity`` bounds admission (``[TWIN-CAP]`` past it);
    ``chunk_ticks`` is the round-robin quantum; ``bucket_floor`` is
    forwarded to :func:`~fognetsimpp_tpu.dynspec.bucket_spec` (lower it
    in tests so small nearby populations still share a bucket);
    ``port`` opens the shared HTTP endpoint (None = API-only).
    """

    def __init__(
        self,
        capacity: int = 4,
        chunk_ticks: int = 256,
        bucket_floor: Optional[int] = None,
        port: Optional[int] = None,
        hash_every_chunk: bool = True,
        whatif_ticks: int = 256,
    ):
        if capacity < 1:
            raise ValueError(
                f"front door capacity must be >= 1 tenant, got {capacity}"
            )
        from ..dynspec import BUCKET_FLOOR

        self.capacity = int(capacity)
        self.chunk_ticks = int(chunk_ticks)
        self.bucket_floor = (
            BUCKET_FLOOR if bucket_floor is None else int(bucket_floor)
        )
        self.hash_every_chunk = bool(hash_every_chunk)
        self.whatif_ticks = int(whatif_ticks)
        self._lock = threading.Lock()
        self._tenants: "collections.OrderedDict[str, Tenant]" = (
            collections.OrderedDict()
        )
        self.server = None
        if port is not None:
            from ..telemetry.live import HealthServer

            self.server = HealthServer(port=port)
            self.server.set_handler(self._route)

    # ---- admission ---------------------------------------------------
    def admit(
        self, label: str, spec, state, net, bounds,
        ingest_capacity: int = 1024,
    ) -> Tenant:
        """Admit one serve session under the shared program registry.

        Buckets the population, splits the spec into (shape key, dyn
        rows), notes the program registry, and builds the tenant's own
        recorder/watchdog/queue/what-if door.  Raises the one-line
        ``[TWIN-CAP]`` error at capacity and a plain ``ValueError`` for
        a duplicate label or a telemetry-less spec (the per-tenant
        health plane reads the device-resident reservoir, the
        ``serve_run`` precondition).
        """
        from ..dynspec import bucket_spec, registry_note, split_spec
        from ..telemetry.live import FlightRecorder, Watchdog

        if not spec.telemetry:
            raise ValueError(
                "front-door tenants need spec.telemetry=True (each "
                "tenant's watchdog reads its device-resident reservoir)"
            )
        with self._lock:
            if label in self._tenants:
                raise ValueError(
                    f"tenant label {label!r} is already admitted: "
                    "labels route /t/<label>/* and must be unique"
                )
            if len(self._tenants) >= self.capacity:
                raise ValueError(admission_error(label, self.capacity))
        spec, state, net = bucket_spec(
            spec, state, net, floor=self.bucket_floor
        )
        run_spec, dyn = split_spec(spec)
        registry_note(run_spec, jax.default_backend(), donated=False)
        queue = (
            IngestQueue(capacity=ingest_capacity) if spec.ingest else None
        )
        door = WhatIfDoor(
            spec, net, bounds, default_ticks=self.whatif_ticks
        )
        door.update(state, 0)
        stride = max(1, -(-spec.n_ticks // max(spec.telemetry_slots, 1)))
        tenant = Tenant(
            label, spec, run_spec, dyn, state, net, bounds,
            queue, door,
            Watchdog(spec.n_fogs, row_ticks=stride),
            FlightRecorder(),
        )
        with self._lock:
            if len(self._tenants) >= self.capacity:
                raise ValueError(admission_error(label, self.capacity))
            self._tenants[label] = tenant
        return tenant

    def evict(self, label: str) -> Tenant:
        """Release a slot; the tenant object (carry included) returns
        to the caller for archival or re-admission elsewhere."""
        with self._lock:
            if label not in self._tenants:
                raise ValueError(f"no tenant {label!r} admitted")
            return self._tenants.pop(label)

    @property
    def tenants(self) -> List[str]:
        with self._lock:
            return list(self._tenants)

    # ---- the round-robin chunk scheduler -----------------------------
    def step(self) -> Dict[str, int]:
        """One round-robin sweep: every tenant advances one chunk (in
        admission order).  Returns ``{label: ticks_done}``."""
        with self._lock:
            order = list(self._tenants.values())
        out = {}
        for t in order:
            self._advance(t)
            out[t.label] = t.ticks_done
        if self.server is not None:
            self.server.set_metrics(self.render_aggregate())
            self.server.set_health({
                "status": "ok",
                "tenants": {
                    t.label: t.health.get("status", "ok") for t in order
                },
            })
        return out

    def serve(self, n_rounds: int) -> Dict[str, int]:
        """``n_rounds`` round-robin sweeps; returns final tick counts."""
        out: Dict[str, int] = {}
        for _ in range(int(n_rounds)):
            out = self.step()
        return out

    def _advance(self, t: Tenant) -> None:
        from ..telemetry.health import hist_summary, state_hash
        from ..telemetry.metrics import reservoir_progress
        from ..telemetry.openmetrics import render_openmetrics

        t.state = _tenant_chunk(
            t.run_spec, self.chunk_ticks, t.state, t.net, t.bounds, t.dyn
        )
        t.ticks_done += self.chunk_ticks
        t.chunks += 1
        # drain AFTER the chunk — injections land at the interior
        # boundary exactly as run_chunked's inject hook does (never
        # before tick 0, where users are still mid-handshake)
        if t.queue is not None:
            from ..core.engine import inject_arrivals

            users, mips, oldest = t.queue.drain(t.spec.ingest_batch)
            if users:
                import time as _time

                t.state, n_inj, n_rej = inject_arrivals(
                    t.spec, t.state, t.net, users, mips
                )
                t.queue.note_injected(
                    n_inj, n_rej,
                    (_time.monotonic() - oldest) if oldest else 0.0,
                )
                t.queue.log.append({
                    "ticks_done": t.ticks_done,
                    "user": list(users),
                    "mips": list(mips),
                })
        rows, t.next_row = reservoir_progress(
            t.spec, t.state.telem, t.ticks_done, t.next_row
        )
        h = (
            state_hash(jax.device_get(t.state))
            if self.hash_every_chunk else None
        )
        stats = t.queue.stats() if t.queue is not None else None
        t.recorder.note_chunk(
            t.ticks_done, rows=rows, state_hash=h,
            extra={"ingest": dict(stats)} if stats is not None else None,
        )
        ingest_sig = None
        if stats is not None:
            ingest_sig = {
                "ingest_depth": stats["depth"]
                / max(float(stats["capacity"]), 1.0)
            }
        fired = t.watchdog.update_from_rows(
            rows, t.ticks_done, extra=ingest_sig
        )
        if t.door is not None:
            t.door.update(t.state, t.ticks_done)
        hist = hist_summary(t.spec, t.state)
        t.metrics_text = render_openmetrics(
            t.spec, t.state, hist=hist,
            ingest=stats,
            attrs={"live_chunks": t.chunks, "live_ticks": t.ticks_done},
        )
        t.health = {
            "status": "degraded" if fired else "ok",
            "tenant": t.label,
            "ticks_done": t.ticks_done,
            "chunks": t.chunks,
            "signals": t.watchdog.last_signals,
            "anomalies": t.watchdog.anomaly_count,
            **({"ingest": stats} if stats is not None else {}),
        }
        if hist is not None:
            t.health["latency_ms"] = {
                k: (v if math.isfinite(v) else None)
                for k, v in hist["quantiles_ms"].items()
            }

    # ---- exposition --------------------------------------------------
    def tenant_rows(self) -> List[Dict]:
        """One dict per tenant (admission order) for
        :func:`~fognetsimpp_tpu.telemetry.openmetrics.
        render_twin_openmetrics` — the ``tenant="0..N-1"`` label axis
        ``tools/check_openmetrics.py`` cross-checks against
        ``fns_twin_tenants``."""
        with self._lock:
            order = list(self._tenants.values())
        rows = []
        for t in order:
            m = t.state.metrics
            rows.append({
                "label": t.label,
                "ticks": t.ticks_done,
                "chunks": t.chunks,
                "n_users": t.spec.n_users,
                "n_published": int(m.n_published),
                "n_completed": int(m.n_completed),
                "ingest_depth": (
                    t.queue.depth if t.queue is not None else 0
                ),
            })
        return rows

    def render_aggregate(self) -> str:
        from ..telemetry.openmetrics import render_twin_openmetrics

        return render_twin_openmetrics(self.tenant_rows())

    # ---- HTTP routing (the shared endpoint's route hook) -------------
    def _route(
        self, method: str, path: str, body: bytes
    ) -> Optional[Tuple[int, str, str]]:
        """``/t/<label>/(metrics|healthz|ingest|whatif)``; None lets
        the HealthServer's own ``/metrics``+``/healthz`` (the
        aggregate) answer."""
        parts = path.split("?", 1)[0].strip("/").split("/")
        if len(parts) != 3 or parts[0] != "t":
            return None
        label, leaf = parts[1], parts[2]
        with self._lock:
            t = self._tenants.get(label)
        if t is None:
            return (
                404, "text/plain",
                f"error: no tenant {label!r} admitted "
                f"(tenants: {', '.join(self.tenants) or 'none'})\n",
            )
        if leaf == "metrics":
            return (
                200,
                "application/openmetrics-text; version=1.0.0; "
                "charset=utf-8",
                t.metrics_text,
            )
        if leaf == "healthz":
            return (
                200, "application/json", json.dumps(t.health) + "\n"
            )
        if leaf == "ingest":
            if t.queue is None:
                from .gates import ingest_off_error

                return (
                    409, "application/json",
                    json.dumps({"error": ingest_off_error()}) + "\n",
                )
            return t.queue.handle_http(method, path, body)
        if leaf == "whatif" and t.door is not None:
            return t.door.handle_http(method, path, body)
        return (404, "text/plain", f"error: unknown route {path!r}\n")

    def close(self) -> None:
        if self.server is not None:
            self.server.close()
