"""State-forked what-if grids: the twin's question door (ISSUE 17b).

A live session holds a chunk-boundary carry; :func:`run_whatif` forks
that carry onto a grid of promoted-knob retunings
(:func:`~fognetsimpp_tpu.parallel.sweep.sweep_dyn_from`) and advances
every cell ``n_ticks`` into the future under ONE vmapped program —
answering "p95 / energy / defer under these K retunings, starting from
current state, H ticks ahead" with ZERO compile events once the
session's shape bucket is warm (``run_replicated``'s jit cache serves
every fork; tests assert the ``compile_stats`` delta).

Everything reported is a DELTA against the fork point: Metrics
counters subtract the carry's values, and latency quantiles come from
the per-cell histogram minus the carry's histogram (``lat_hist`` is
cumulative), so each cell describes only its hypothetical future, not
the shared past.  Because :func:`~fognetsimpp_tpu.parallel.sweep.
fork_state` re-keys NOTHING, cell *i*'s final state is bit-identical
to a direct ``run`` of that retuned spec from the same carry — the
what-if rail.

:class:`WhatIfDoor` is the serving wrapper: it shadows the latest
chunk-boundary carry (via :meth:`WhatIfDoor.wrap_inject` on the
``run_chunked`` inject hook) and answers ``POST /whatif`` on the
health server's route hook.
"""
from __future__ import annotations

import dataclasses
import json
import math
import threading
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from .gates import whatif_payload_error


def _fork_counters(state) -> Dict[str, int]:
    """The carry's Metrics counters (the delta baseline), by field
    enumeration — a counter added to the state never silently vanishes
    from what-if reports (the ``summarize`` discipline)."""
    return {
        f.name: int(getattr(state.metrics, f.name))
        for f in dataclasses.fields(state.metrics)
    }


def _cell_quantiles(
    spec, carry, final, i: int
) -> Tuple[Optional[Dict[str, float]], int]:
    """Latency quantiles (ms) of cell ``i``'s forked window.

    ``lat_hist`` is CUMULATIVE over the session, so the cell's own
    window is its final histogram minus the carry's — the same
    upper-edge estimator ``hist_summary`` publishes, over the delta
    counts.  ``(None, 0)`` when the histogram plane is off.
    """
    if not (spec.telemetry and spec.telemetry_hist):
        return None, 0
    from ..telemetry.health import QUANTILES, _quantile_from_cum
    from ..telemetry.health import hist_edges_s

    base = np.asarray(carry.telem.lat_hist, np.int64)  # (F, B)
    counts = np.asarray(final.telem.lat_hist, np.int64)[i] - base
    edges_ms = hist_edges_s(spec).astype(np.float64) * 1e3
    g_cum = np.cumsum(counts.sum(axis=0))
    total = int(g_cum[-1]) if g_cum.size else 0
    q = {
        name: _quantile_from_cum(
            g_cum, edges_ms, frac, total, float(spec.telemetry_hist_max_ms)
        )
        for name, frac in QUANTILES
    }
    return q, total


def run_whatif(
    spec,
    state,
    net,
    bounds,
    knobs: Mapping[str, Sequence],
    n_ticks: int,
    return_state: bool = False,
):
    """Answer a knob grid from a live carry: per-cell future deltas.

    ``knobs`` maps promoted fields (``dynspec.DYN_FIELDS``) to value
    lists; the cartesian grid forks ``state`` and runs ``n_ticks``
    ticks per cell under one compiled program.  Returns a
    JSON-serializable report::

        {"horizon_ticks": H, "fork_t": <sim seconds>,
         "n_cells": K, "knobs": [names...],
         "cells": [{<knob values...>,
                    "delta": {counter: int, ...},   # future-only
                    "counters": {counter: int, ...}, # absolute
                    "quantiles_ms": {p50/p95/p99} | None,
                    "completed_in_window": int}, ...]}

    ``return_state=True`` additionally returns the replica-batched
    final state (row *i* = cell *i*) for bit-exactness assertions.
    Raises ``ValueError`` (one actionable line) for unpromoted knobs,
    bucket-crossing cells or a non-positive horizon.
    """
    from ..parallel.replicas import replica_counters
    from ..parallel.sweep import sweep_dyn_from

    if n_ticks < 1:
        raise ValueError(
            f"what-if horizon must be >= 1 tick, got {n_ticks}"
        )
    base = _fork_counters(state)
    grid, final = sweep_dyn_from(spec, state, net, bounds, knobs, n_ticks)
    cells: List[Dict] = []
    if grid:
        counters = replica_counters(final)
        for i, cell in enumerate(grid):
            absolute = {k: int(v[i]) for k, v in counters.items()}
            q, n_win = _cell_quantiles(spec, state, final, i)
            cells.append({
                **cell,
                "counters": absolute,
                "delta": {k: absolute[k] - base[k] for k in absolute},
                "quantiles_ms": q,
                "completed_in_window": n_win,
            })
    report = {
        "horizon_ticks": int(n_ticks),
        "fork_t": float(state.t),
        "n_cells": len(grid),
        "knobs": sorted(knobs),
        "cells": cells,
    }
    if return_state:
        return report, final
    return report


def parse_grid(text: str) -> Tuple[Dict[str, List[float]], int]:
    """Parse the CLI ``--whatif`` grid syntax: ``'knob=v1,v2 ...
    [ticks=H]'`` → ``(knobs, n_ticks)``.  Raises ``ValueError`` (one
    actionable line) on malformed tokens — knob-name validity is
    checked downstream by :func:`run_whatif` against ``DYN_FIELDS``.
    """
    knobs: Dict[str, List[float]] = {}
    ticks = 400
    for tok in text.split():
        if "=" not in tok:
            raise ValueError(
                f"--whatif grid token {tok!r} is not KEY=VALUES; "
                "expected e.g. 'uplink_loss_prob=0.05,0.1 ticks=400'"
            )
        k, v = tok.split("=", 1)
        try:
            if k == "ticks":
                ticks = int(v)
            else:
                knobs[k] = [float(x) for x in v.split(",") if x]
        except ValueError:
            raise ValueError(
                f"--whatif grid token {tok!r} has non-numeric values"
            ) from None
    if not any(knobs.values()):
        raise ValueError(
            "--whatif needs at least one promoted knob with values, "
            "e.g. 'uplink_loss_prob=0.05,0.1 ticks=400'"
        )
    return knobs, ticks


def _json_safe(obj):
    """NaN/Inf → None, numpy scalars → python — strict-JSON payloads."""
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (float, np.floating)):
        f = float(obj)
        return f if math.isfinite(f) else None
    return obj


class WhatIfDoor:
    """The live session's what-if endpoint: latest-carry shadow + HTTP.

    The door never owns the chunk loop — it SHADOWS it:
    :meth:`wrap_inject` decorates the ``run_chunked`` inject hook so
    every chunk boundary (post-injection, i.e. "current state" as the
    next chunk will see it) updates the held carry, and
    :meth:`handle_http` answers ``POST /whatif`` from whatever carry is
    newest.  Forks read immutable device arrays, so answering mid-run
    from the server thread races nothing.
    """

    def __init__(
        self,
        spec,
        net,
        bounds,
        default_ticks: int = 256,
        max_cells: int = 64,
    ):
        self.spec = spec
        self.net = net
        self.bounds = bounds
        self.default_ticks = int(default_ticks)
        self.max_cells = int(max_cells)
        self._lock = threading.Lock()
        self._carry = None
        self._ticks_done = 0

    def update(self, state, ticks_done: int) -> None:
        """Install a new chunk-boundary carry (newest wins)."""
        with self._lock:
            self._carry = state
            self._ticks_done = int(ticks_done)

    def wrap_inject(self, inject=None):
        """Decorate (or stand in for) the ``run_chunked`` inject hook so
        each boundary's post-injection state becomes the door's carry."""

        def hook(state, ticks_done: int):
            if inject is not None:
                state = inject(state, ticks_done)
            self.update(state, ticks_done)
            return state

        return hook

    def ask(
        self, knobs: Mapping[str, Sequence], n_ticks: Optional[int] = None
    ) -> Dict:
        """Run the grid from the latest carry; adds ``fork_ticks_done``."""
        with self._lock:
            carry, done = self._carry, self._ticks_done
        if carry is None:
            raise ValueError(
                "what-if door holds no carry yet: the first chunk "
                "boundary has not landed (ask again after one chunk)"
            )
        n = self.default_ticks if n_ticks is None else int(n_ticks)
        n_cells = 1
        for vals in knobs.values():
            n_cells *= max(len(vals), 1)
        if n_cells > self.max_cells:
            raise ValueError(
                f"what-if grid has {n_cells} cells, over the door's "
                f"bound of {self.max_cells}: coarsen the grid or raise "
                "max_cells"
            )
        report = run_whatif(
            self.spec, carry, self.net, self.bounds, knobs, n
        )
        report["fork_ticks_done"] = done
        return report

    # ---- HTTP (the HealthServer route hook) --------------------------
    def handle_http(
        self, method: str, path: str, body: bytes
    ) -> Optional[Tuple[int, str, str]]:
        """``POST /whatif`` handler; None for any other route."""
        if not path.split("?", 1)[0].rstrip("/").endswith("/whatif"):
            return None
        if method != "POST":
            return (
                200, "application/json",
                json.dumps({
                    "usage": 'POST {"knobs": {"<promoted field>": '
                             '[values...]}, "ticks": <int>}',
                    "default_ticks": self.default_ticks,
                    "max_cells": self.max_cells,
                }) + "\n",
            )
        status, payload = self._post(body)
        return (status, "application/json", json.dumps(payload) + "\n")

    def _post(self, body: bytes) -> Tuple[int, Dict]:
        try:
            doc = json.loads(body.decode() or "null")
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            return 400, {"error": whatif_payload_error(f"invalid JSON ({e})")}
        if not isinstance(doc, dict) or not isinstance(
            doc.get("knobs"), dict
        ):
            return 400, {
                "error": whatif_payload_error("no 'knobs' object given")
            }
        knobs = doc["knobs"]
        for k, vals in knobs.items():
            if not isinstance(vals, list) or not vals or not all(
                isinstance(v, (int, float)) and not isinstance(v, bool)
                for v in vals
            ):
                return 400, {
                    "error": whatif_payload_error(
                        f"knob {k!r} needs a non-empty list of numbers"
                    )
                }
        ticks = doc.get("ticks")
        if ticks is not None and (
            isinstance(ticks, bool) or not isinstance(ticks, int)
        ):
            return 400, {
                "error": whatif_payload_error("'ticks' is not an int")
            }
        try:
            return 200, _json_safe(self.ask(knobs, ticks))
        except ValueError as e:
            return 400, {"error": str(e)}
