"""Signal extraction: the reference's statistic vectors, with safe sentinels.

The task table stores absolute event times with ``+inf`` meaning "never
happened" (and ``nan`` for unset queue times).  This module is the one place
that turns those columns into the reference's per-task signal vectors —
masked, finite, in milliseconds — so no downstream consumer ever does
``inf - inf`` arithmetic:

  * ``latency``   — publish → status-5 "assigned" ack (``mqttApp2.cc:256-267``)
  * ``latency_h1``— publish → status-4 ack, both the broker's own "forwarded"
    and the relayed fog "queued" (``mqttApp2.cc:269-277``)
  * ``task_time`` — publish → status-6 "performed" ack (``mqttApp2.cc:279-291``)
  * ``queue_time``— fog FIFO wait (``ComputeBrokerApp3.cc:238``)
  * ``delay``     — broker-side publish transit (``BrokerBaseApp3.cc:143``)
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from ..spec import Stage
from ..state import WorldState


def _finite_ms(
    t_end: np.ndarray, t_start: np.ndarray, t_now: float = float("inf")
) -> np.ndarray:
    """(t_end - t_start) * 1e3 where both ends are finite and the end
    event has actually HAPPENED by ``t_now`` (the run's end time) — a
    packet whose pre-computed arrival lies past the horizon is still in
    flight, and the reference would not have recorded its sample (r5: the
    deterministic demo calibration exposed this — creations k >= 58 have
    stamped arrivals past the 3.35 s horizon)."""
    m = np.isfinite(t_end) & np.isfinite(t_start) & (t_end <= t_now)
    return ((t_end[m] - t_start[m]) * 1e3).astype(np.float64)


def extract_signals(final: WorldState) -> Dict[str, np.ndarray]:
    """Per-task signal vectors (milliseconds) from a finished run.

    Keys mirror the reference's ``@statistic`` names; each value is the
    1-D vector of samples that the reference would have recorded into its
    ``.vec`` file for that signal.
    """
    t = final.tasks
    t_create = np.asarray(t.t_create)
    t_now = float(final.t)
    return {
        "latency": _finite_ms(np.asarray(t.t_ack5), t_create, t_now),
        "latency_h1": np.concatenate(
            [
                _finite_ms(np.asarray(t.t_ack4_fwd), t_create, t_now),
                _finite_ms(np.asarray(t.t_ack4_queued), t_create, t_now),
            ]
        ),
        "task_time": _finite_ms(np.asarray(t.t_ack6), t_create, t_now),
        "ack3": _finite_ms(np.asarray(t.t_ack3), t_create, t_now),
        "queue_time": np.asarray(t.queue_time_ms)[
            np.isfinite(np.asarray(t.queue_time_ms))
            & ~np.isnan(np.asarray(t.queue_time_ms))
        ].astype(np.float64),
        "delay": _finite_ms(np.asarray(t.t_at_broker), t_create, t_now),
    }


def summarize(final: WorldState) -> Dict[str, float]:
    """Scalar roll-up: counts plus mean/max of each signal (ms)."""
    sig = extract_signals(final)
    stage = np.asarray(final.tasks.stage)
    # the per-stage census is namespaced stage_<name> so the Metrics
    # counters below can never shadow it (ADVICE r2: n_lost/n_dropped used
    # to overwrite the census keys — equal today because LOST/DROPPED are
    # terminal stages, but a future divergence would have been masked)
    out: Dict[str, float] = {
        f"stage_{s.name.lower()}": int((stage == int(s)).sum()) for s in Stage
    }
    m = final.metrics
    # every Metrics counter, by field enumeration (a counter added to the
    # state can never silently vanish from the .sca roll-up)
    import dataclasses

    out.update(
        {f.name: int(getattr(m, f.name)) for f in dataclasses.fields(m)}
    )
    for name, v in sig.items():
        out[f"{name}_n"] = int(v.size)
        out[f"{name}_mean_ms"] = float(v.mean()) if v.size else float("nan")
        out[f"{name}_max_ms"] = float(v.max()) if v.size else float("nan")
    # bandit-scheduler roll-up (learn/): credited-reward census + the
    # credited mean latency the regret harness compares against oracles.
    # pick_p has learn_capacity rows, so its size doubles as the
    # subsystem's is-active flag without needing the spec here.
    # chaos fault-injection roll-up (chaos/): the per-fog schedule
    # leaves double as the is-active flag (zero-row when chaos is off),
    # the pick_p discipline below.  The chaos_* keys become the
    # fns_chaos_* scalar OpenMetrics families via render_openmetrics'
    # summarize() pass.
    if np.asarray(final.chaos.next_down).size:
        ch = final.chaos
        out["chaos_crashes"] = int(ch.n_crashes)
        out["chaos_recovers"] = int(ch.n_recovers)
        out["chaos_lost_crash"] = int(ch.n_lost_crash)
        out["chaos_reoffloaded"] = int(ch.n_reoffloaded)
        out["chaos_retry_exhausted"] = int(ch.n_retry_exhausted)
    # federated-hierarchy roll-up (hier/): the ownership leaves double
    # as the is-active flag (zero-row when n_brokers == 1); the hier_*
    # keys become fns_hier_* scalar OpenMetrics families via
    # render_openmetrics' summarize() pass
    if np.asarray(final.hier.fog_broker).size:
        h = final.hier
        out["hier_migrated"] = int(h.n_migrated)
        out["hier_hop_exhausted"] = int(h.n_hop_exhausted)
    if np.asarray(final.learn.pick_p).size:
        lat_cnt = float(final.learn.lat_cnt)
        out["learn_credited"] = int(lat_cnt)
        out["learn_lat_mean_ms"] = (
            float(final.learn.lat_sum) / lat_cnt * 1e3
            if lat_cnt > 0
            else float("nan")
        )
    return out
