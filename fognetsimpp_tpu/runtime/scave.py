"""OMNeT++/Scave-compatible text result files (`.sca` / `.vec`): both
directions — an exporter rendering a finished run in the reference's
grammar, and a reader (:func:`read_sca` / :func:`read_vec`) that parses
the reference's own committed artifacts
(``/root/reference/simulations/example/results/General-0.sca`` — 1,497
scalar rows — and the 153.9 s testing run under
``simulations/results/``), proving format compatibility against the real
files rather than only against this exporter's idea of them (VERDICT r4
item 7).

The reference's L5 output is the OMNeT++ 4.x "version 2" text format
(``/root/reference/simulations/example/results/General-0.sca`` — header
``version 2`` + ``run`` + ``attr`` lines, then ``scalar <module> <name>
<value>`` rows and ``statistic`` blocks with ``field`` lines; the ``.vec``
twin declares ``vector <id> <module> <name> ETV`` and streams
``<id>\\t<event>\\t<time>\\t<value>`` rows), consumed by ``.anf``
descriptors (``/root/reference/simulations/General.anf:1-9``).

This exporter renders a finished run in exactly that grammar so the
reference's analysis tooling (Scave IDE / ``opp_scavetool``) reads the
repo's results unmodified — making the "drop-in result collectors" claim
literally true.  The richer ``.sca.json`` / ``.vec.npz`` pair stays the
primary machine-readable output (``runtime/recorder.py``).

Module naming follows the reference networks: ``<net>.user[<u>].udpApp[0]``
(the demo's single user is plain ``<net>.user.udpApp[0]``),
``<net>.ComputeBroker<f+1>.udpApp[0]``, ``<net>.BaseBroker.udpApp[0]``.
"""
from __future__ import annotations

import os
import time
from typing import Dict, Optional, TextIO, Tuple

import numpy as np

from ..spec import WorldSpec
from ..state import WorldState
from .recorder import per_module_scalars

# per-user statistic blocks / vectors above this population are aggregated
# into one synthetic `<net>.users` module (the committed reference worlds
# have <= 13 users; a 10k-user bench world would emit 40k text blocks)
_PER_USER_LIMIT = 64

# scenario builder -> reference NED network name (SURVEY.md §2 topologies)
NETWORK_NAMES = {
    "smoke": "Network",
    "wired_v1": "Network",
    "wireless": "WirelessNetwork",
    "wireless2": "WirelessNetwork2",
    "wireless3": "WirelessNetwork3",
    "wireless4": "WirelessNetwork4",
    "wireless5": "WirelessNetwork5",
    "paper": "WirelessNetwork6",
    "example": "WirelessNet",
}


def _q(name: str) -> str:
    """Quote a scalar/statistic name the way OMNeT++ does (spaces)."""
    return f'"{name}"' if (" " in name or "\t" in name) else name


def _write_header(f: TextIO, run_id: str, attrs: Dict[str, str]) -> None:
    f.write("version 2\n")
    f.write(f"run {run_id}\n")
    for k, v in attrs.items():
        sv = str(v)
        if sv == "" or " " in sv:
            sv = f'"{sv}"'
        f.write(f"attr {k} {sv}\n")
    f.write("\n")


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, (int, np.integer)):
        return str(int(v))
    x = float(v)
    if np.isnan(x):
        return "nan"
    if np.isinf(x):
        return "inf" if x > 0 else "-inf"
    return repr(x)


def _scalar(f: TextIO, module: str, name: str, value) -> None:
    f.write(f"scalar {module} \t{_q(name)} \t{_fmt(value)}\n")


def _statistic(f: TextIO, module: str, name: str, v: np.ndarray) -> None:
    """A `statistic` block with the reference's seven `field` rows
    (General-0.sca:52-59)."""
    f.write(f"statistic {module} \t{_q(name)}\n")
    n = int(v.size)
    f.write(f"field count {n}\n")
    f.write(f"field mean {_fmt(v.mean() if n else float('nan'))}\n")
    std = v.std(ddof=1) if n > 1 else float("nan")
    f.write(f"field stddev {_fmt(std)}\n")
    f.write(f"field sum {_fmt(v.sum() if n else 0.0)}\n")
    f.write(f"field sqrsum {_fmt(float(np.square(v, dtype=np.float64).sum()) if n else 0.0)}\n")
    f.write(f"field min {_fmt(v.min() if n else float('nan'))}\n")
    f.write(f"field max {_fmt(v.max() if n else float('nan'))}\n")


def _signal_samples(
    spec: WorldSpec, final: WorldState
) -> Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Per-signal (user, emit_time_s, value_ms) triples from the task table.

    The emission times are the exact ack-arrival event times the reference
    would have recorded each sample at (``mqttApp2.cc:256-291``); the
    values mirror :func:`~fognetsimpp_tpu.runtime.signals.extract_signals`.
    """
    t = final.tasks
    user = np.asarray(t.user)
    t_create = np.asarray(t.t_create, np.float64)

    def tri(t_end_arr, owner=None):
        t_end = np.asarray(t_end_arr, np.float64)
        m = np.isfinite(t_end) & np.isfinite(t_create)
        o = user if owner is None else owner
        return o[m], t_end[m], (t_end[m] - t_create[m]) * 1e3

    out = {
        "latency": tri(t.t_ack5),
        "taskTime": tri(t.t_ack6),
        "delay": tri(t.t_at_broker),
    }
    # latencyH1: both the broker's own "forwarded" and the relayed fog
    # "queued" status-4 acks produce samples (mqttApp2.cc:269-277)
    u4a, tt4a, v4a = tri(t.t_ack4_fwd)
    u4b, tt4b, v4b = tri(t.t_ack4_queued)
    out["latencyH1"] = (
        np.concatenate([u4a, u4b]),
        np.concatenate([tt4a, tt4b]),
        np.concatenate([v4a, v4b]),
    )
    # queueTime belongs to the fog module that served the task
    qt = np.asarray(t.queue_time_ms, np.float64)
    mq = np.isfinite(qt)
    fog = np.asarray(t.fog)
    ts = np.asarray(t.t_service_start, np.float64)
    out["queueTime"] = (
        fog[mq],
        np.where(np.isfinite(ts[mq]), ts[mq], 0.0),
        qt[mq],
    )
    return out


def _user_module(net: str, u: int, n_users: int) -> str:
    if n_users == 1:
        return f"{net}.user.udpApp[0]"  # the demo's single circling user
    return f"{net}.user[{u}].udpApp[0]"


def export_scave(
    outdir: str,
    spec: WorldSpec,
    final: WorldState,
    series: Optional[Dict] = None,
    run_id: str = "General-0",
    attrs: Optional[Dict] = None,
    network: str = "Network",
) -> Dict[str, str]:
    """Write `<run_id>.sca` + `<run_id>.vec` in OMNeT++ text format.

    Returns ``{"sca": path, "vec": path, "anf": path}``; the ``.anf``
    descriptor points Scave at both files, like
    ``simulations/General.anf``.
    """
    os.makedirs(outdir, exist_ok=True)
    sca_path = os.path.join(outdir, f"{run_id}.sca")
    vec_path = os.path.join(outdir, f"{run_id}.vec")
    anf_path = os.path.join(outdir, "General.anf")

    stamp = time.strftime("%Y%m%d-%H:%M:%S")
    header = {
        "configname": "General",
        "datetime": stamp,
        "experiment": "General",
        "inifile": (attrs or {}).get("scenario", "scenario"),
        "iterationvars": "",
        "iterationvars2": "$repetition=0",
        "measurement": "",
        "network": network,
        "processid": os.getpid(),
        "repetition": 0,
        "replication": "#0",
        "resultdir": "results",
        "runnumber": 0,
        "seedset": 0,
    }
    if attrs:
        header.update({k: v for k, v in attrs.items()})

    mods = per_module_scalars(spec, final)
    U, F = spec.n_users, spec.n_fogs
    per_user = U <= _PER_USER_LIMIT
    sig = _signal_samples(spec, final)

    # ------------------------------------------------------------- .sca
    with open(sca_path, "w") as f:
        _write_header(f, run_id, header)
        for u, row in enumerate(mods["user"]):
            mod = _user_module(network, u, U)
            # the reference's exact row names where a direct analog exists
            _scalar(f, mod, "packets sent", row["tx_msgs"])
            _scalar(f, mod, "packets received", row["rx_msgs"])
            _scalar(f, mod, "sentPk:count", row["sent"])
            _scalar(f, mod, "completedTasks:count", row["completed"])
            _scalar(f, mod, "acked6:count", row["acked6"])
            _scalar(f, mod, "delivered:count", row["delivered"])
            _scalar(f, mod, "residualEnergy", row["energy_j"])
            _scalar(f, mod, "alive", row["alive"])
        for fi, row in enumerate(mods["fog"]):
            mod = f"{network}.ComputeBroker{fi + 1}.udpApp[0]"
            _scalar(f, mod, "packets sent", row["tx_msgs"])
            _scalar(f, mod, "packets received", row["rx_msgs"])
            _scalar(f, mod, "assignedTasks:count", row["assigned"])
            _scalar(f, mod, "completedTasks:count", row["completed"])
            _scalar(f, mod, "busyTime", row["busy_time"])
            _scalar(f, mod, "queueLength", row["q_len"])
            _scalar(f, mod, "queueDrops:count", row["q_drops"])
        bmod = f"{network}.BaseBroker.udpApp[0]"
        _scalar(f, bmod, "packets sent", mods["broker"]["tx_msgs"])
        # everything the broker app processed — the `echoedPk:count` analog
        _scalar(f, bmod, "echoedPk:count", mods["broker"]["rx_msgs"])
        for a, row in enumerate(mods["ap"]):
            _scalar(f, f"{network}.ap{a + 1}", "assocStations:mean",
                    row["assoc_mean"])

        # per-signal statistic blocks (the @statistic record=stats half,
        # mqttApp2.ned:50-55); values in ms like the signal layer
        for name, owner_mod in (
            ("latency", "user"),
            ("latencyH1", "user"),
            ("taskTime", "user"),
        ):
            owner, _, val = sig[name]
            if per_user:
                for u in range(U):
                    _statistic(
                        f, _user_module(network, u, U), f"{name}:stats",
                        val[owner == u],
                    )
            else:
                _statistic(f, f"{network}.users", f"{name}:stats", val)
        for fi in range(F):
            owner, _, val = sig["queueTime"]
            _statistic(
                f, f"{network}.ComputeBroker{fi + 1}.udpApp[0]",
                "queueTime:stats", val[owner == fi],
            )
        _statistic(f, bmod, "delay:stats", sig["delay"][2])

    # ------------------------------------------------------------- .vec
    with open(vec_path, "w") as f:
        _write_header(f, run_id, header)
        decls = []  # (vec_id, module, name, times, values)
        vid = 0
        for name in ("latency", "latencyH1", "taskTime"):
            owner, tt, val = sig[name]
            if per_user:
                for u in range(U):
                    m = owner == u
                    decls.append(
                        (vid, _user_module(network, u, U), f"{name}:vector",
                         tt[m], val[m])
                    )
                    vid += 1
            else:
                decls.append(
                    (vid, f"{network}.users", f"{name}:vector", tt, val)
                )
                vid += 1
        for fi in range(F):
            owner, tt, val = sig["queueTime"]
            m = owner == fi
            decls.append(
                (vid, f"{network}.ComputeBroker{fi + 1}.udpApp[0]",
                 "queueTime:vector", tt[m], val[m])
            )
            vid += 1
        owner, tt, val = sig["delay"]
        decls.append((vid, bmod, "delay:vector", tt, val))
        vid += 1
        if series is not None:
            ts = np.asarray(series.get("t", []), np.float64).ravel()
            for k, v in series.items():
                arr = np.asarray(v, np.float64)
                if k == "t" or arr.ndim != 1 or arr.shape[0] != ts.shape[0]:
                    continue  # per-fog matrices live in the .npz
                decls.append((vid, f"{network}.tick", f"{k}:vector", ts, arr))
                vid += 1

        for i, mod, name, _, _ in decls:
            f.write(f"vector {i}  {mod}  {name}  ETV\n")
        f.write("\n")
        # one global event counter over all samples in time order, so the
        # E column is monotone the way the kernel's would be
        all_t = np.concatenate([d[3] for d in decls]) if decls else np.zeros(0)
        all_vid = np.concatenate(
            [np.full(d[3].shape[0], d[0], np.int64) for d in decls]
        ) if decls else np.zeros(0, np.int64)
        all_v = np.concatenate([d[4] for d in decls]) if decls else np.zeros(0)
        order = np.argsort(all_t, kind="stable")
        for ev, j in enumerate(order):
            f.write(
                f"{int(all_vid[j])}\t{ev}\t{float(all_t[j])!r}\t"
                f"{float(all_v[j])!r}\n"
            )

    with open(anf_path, "w") as f:
        f.write('<?xml version="1.0" encoding="UTF-8"?>\n')
        f.write(
            '<scave:Analysis xmi:version="2.0" '
            'xmlns:xmi="http://www.omg.org/XMI" '
            'xmlns:scave="http://www.omnetpp.org/omnetpp/scave">\n'
        )
        f.write("  <inputs>\n")
        f.write(f'    <inputs name="{os.path.abspath(sca_path)}"/>\n')
        f.write(f'    <inputs name="{os.path.abspath(vec_path)}"/>\n')
        f.write("  </inputs>\n  <datasets/>\n  <chartSheets/>\n")
        f.write("</scave:Analysis>\n")

    return {"sca": sca_path, "vec": vec_path, "anf": anf_path}


# ----------------------------------------------------------------------
# readers (the opp_scavetool/Scave-side half of the format contract)
# ----------------------------------------------------------------------

def _split_name(rest: str):
    """Split `<name-or-quoted> <value...>` returning (name, remainder)."""
    rest = rest.strip()
    if rest.startswith('"'):
        end = rest.index('"', 1)
        return rest[1:end], rest[end + 1 :].strip()
    parts = rest.split(None, 1)
    return parts[0], (parts[1] if len(parts) > 1 else "")


def read_sca(path: str) -> Dict:
    """Parse an OMNeT++ version-2 text `.sca` file.

    Handles the grammar of the reference's committed artifacts
    (``simulations/example/results/General-0.sca``): `run`/`attr`
    header, `scalar <module> <name> <value>` rows (names may be quoted:
    ``"simulated time"``), `statistic` blocks with `field` rows, nested
    `attr` rows and histogram `bin` rows.

    Returns ``{"run": str, "attrs": {..}, "scalars": {(module, name):
    float}, "statistics": {(module, name): {"fields": {..}, "bins":
    [(edge, count), ...]}}}``.
    """
    out = {"run": "", "attrs": {}, "scalars": {}, "statistics": {}}
    cur = None  # open statistic block
    with open(path) as f:
        first = f.readline().strip()
        if first != "version 2":
            raise ValueError(f"unsupported result-file version: {first!r}")
        for ln in f:
            ln = ln.rstrip("\n")
            if not ln.strip():
                cur = None
                continue
            kind, _, rest = ln.partition(" ")
            if kind == "run":
                out["run"] = rest.strip()
            elif kind == "attr":
                name, val = _split_name(rest)
                if cur is not None:
                    cur.setdefault("attrs", {})[name] = val.strip('"')
                else:
                    out["attrs"][name] = val.strip('"')
            elif kind == "scalar":
                module, rest2 = _split_name(rest)
                name, val = _split_name(rest2)
                out["scalars"][(module, name)] = float(val)
                cur = None
            elif kind == "statistic":
                module, rest2 = _split_name(rest)
                name, _ = _split_name(rest2 + " _")
                cur = {"fields": {}, "bins": []}
                out["statistics"][(module, name)] = cur
            elif kind == "field" and cur is not None:
                name, val = _split_name(rest)
                cur["fields"][name] = float(val)
            elif kind == "bin" and cur is not None:
                edge_s, count_s = rest.split()
                edge = float("-inf") if edge_s == "-INF" else float(edge_s)
                cur["bins"].append((edge, float(count_s)))
    return out


def read_vec(path: str, vector_ids: Optional[set] = None) -> Dict:
    """Parse an OMNeT++ version-2 text `.vec` file.

    Returns ``{"run": str, "attrs": {..}, "vectors": {id: {"module":
    str, "name": str, "columns": str}}, "data": {id: (events, times,
    values)}}`` — data as numpy arrays.  ``vector_ids`` restricts data
    collection (declarations are always read); the reference's committed
    `.vec` is 63k lines, so callers anchoring one vector skip the rest.
    """
    decls: Dict[int, Dict] = {}
    data: Dict[int, list] = {}
    out = {"run": "", "attrs": {}, "vectors": decls}
    with open(path) as f:
        first = f.readline().strip()
        if first != "version 2":
            raise ValueError(f"unsupported result-file version: {first!r}")
        for ln in f:
            c = ln[0] if ln else "\n"
            if c.isdigit():
                vid_s, _, rest = ln.partition("\t")
                vid = int(vid_s)
                if vector_ids is not None and vid not in vector_ids:
                    continue
                decl = decls.get(vid)
                if decl is not None and decl["columns"] != "ETV":
                    raise ValueError(
                        f"vector {vid} declares columns "
                        f"{decl['columns']!r}; only ETV is supported"
                    )
                cols = rest.split()
                # ETV: event, time, value
                data.setdefault(vid, []).append(
                    (int(cols[0]), float(cols[1]), float(cols[2]))
                )
            elif ln.startswith("vector "):
                rest = ln[len("vector ") :]
                vid_s, rest = rest.split(None, 1)
                module, rest = _split_name(rest)
                name, cols = _split_name(rest)
                decls[int(vid_s)] = {
                    "module": module,
                    "name": name,
                    "columns": cols.strip() or "ETV",
                }
            elif ln.startswith("run "):
                out["run"] = ln[4:].strip()
            elif ln.startswith("attr "):
                name, val = _split_name(ln[5:])
                out["attrs"][name] = val.strip('"')
    out["data"] = {
        vid: (
            np.asarray([r[0] for r in rows], np.int64),
            np.asarray([r[1] for r in rows], np.float64),
            np.asarray([r[2] for r in rows], np.float64),
        )
        for vid, rows in data.items()
    }
    return out
