"""Results recorder: the ``.sca`` / ``.vec`` output layer.

The reference's L5 (SURVEY.md §1): OMNeT++ records ``@statistic`` signals
into ``results/General-0.sca`` (scalars at finish) and ``.vec`` (sample
vectors), which ``.anf`` descriptors then analyse.  Here a finished run is
persisted as

  * ``<run>.sca.json`` — run attributes (scenario, seed, spec) + every
    scalar :func:`~fognetsimpp_tpu.runtime.signals.summarize` produces
    (counts, per-signal mean/max) — human- and tool-readable;
  * ``<run>.vec.npz`` — the per-task signal vectors
    (:func:`extract_signals`: latency, latencyH1, taskTime, queueTime,
    delay) plus any per-tick series from ``spec.record_tick_series``.

Unlike the reference's signal-handle scalars (``recordScalar(name,
signal)`` records an int handle — SURVEY.md App. B item 6), the scalars
here are real statistics.
"""
from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Dict, Optional

import numpy as np

from ..spec import WorldSpec
from ..state import WorldState
from .signals import extract_signals, summarize


def spec_to_dict(spec: WorldSpec) -> Dict:
    """JSON-safe spec dict: non-finite floats become the string "inf".

    ``json.dump`` would otherwise emit the non-standard ``Infinity`` token
    (invalid per RFC 8259) for fields like ``send_stop_time``;
    :func:`dict_to_spec` reverses the encoding.
    """
    d = dataclasses.asdict(spec)  # recurses into BugCompat
    for k, v in d.items():
        if isinstance(v, float) and not np.isfinite(v):
            d[k] = "inf" if v > 0 else "-inf"
    return d


def dict_to_spec(d: Dict) -> WorldSpec:
    """Inverse of :func:`spec_to_dict`."""
    from ..spec import BugCompat

    d = dict(d)
    for k, v in d.items():
        if v == "inf":
            d[k] = float("inf")
        elif v == "-inf":
            d[k] = float("-inf")
    d["bug_compat"] = BugCompat(**d["bug_compat"])
    if "chaos_script" in d:
        # JSON round-trips the scripted-outage tuples as lists, which
        # would make the reconstructed spec unhashable under jit
        d["chaos_script"] = tuple(
            (int(f), float(td), float(tu))
            for f, td, tu in d["chaos_script"]
        )
    if d.get("hier_rtt_matrix") is not None:
        # same listification hazard for the inter-broker RTT matrix
        d["hier_rtt_matrix"] = tuple(
            tuple(float(x) for x in row) for row in d["hier_rtt_matrix"]
        )
    return WorldSpec(**d).validate()


def per_module_scalars(
    spec: WorldSpec, final: WorldState, hist: Optional[Dict] = None
) -> Dict:
    """Per-module scalar rows: the reference's per-host ``.sca`` section.

    OMNeT++ records scalars per module path (the example run has ~1.5k
    rows, e.g. ``WirelessNet.ComputeBroker1.udpApp[0] echoedPk:count``);
    here every user and fog node gets its own scalar dict reconstructed
    from the task table and node state.
    """
    from ..spec import Stage

    t = final.tasks
    user = np.asarray(t.user)
    stage = np.asarray(t.stage)
    fog = np.asarray(t.fog)
    used = stage != int(Stage.UNUSED)
    ack6 = np.isfinite(np.asarray(t.t_ack6))
    done = stage == int(Stage.DONE)
    U, F = spec.n_users, spec.n_fogs

    # one bincount pass per statistic (O(U + F + T), not per-module scans)
    u_sent = np.bincount(user[used], minlength=U)
    u_done = np.bincount(user[used & done], minlength=U)
    u_ack6 = np.bincount(user[used & ack6], minlength=U)
    fmask = fog >= 0
    f_assigned = np.bincount(fog[fmask], minlength=F)
    f_done = np.bincount(fog[fmask & done], minlength=F)
    n_delivered = np.asarray(final.users.n_delivered)
    energy = np.asarray(final.nodes.energy)
    alive = np.asarray(final.nodes.alive)
    busy = np.asarray(final.fogs.busy_time)
    pool = np.asarray(final.fogs.pool_avail)
    q_len = np.asarray(final.fogs.q_len)
    q_drops = np.asarray(final.fogs.q_drops)
    learn_picks = (
        np.asarray(final.learn.pick_count) if spec.learn_active else None
    )
    # plane-1 telemetry rows (telemetry/metrics.py): the per-fog busy
    # fraction comes from busy_fractions() — the SAME call the
    # OpenMetrics exposition uses, so .sca.json and the scrape output
    # can never drift (the ISSUE 4 acceptance gate)
    from ..telemetry.metrics import telemetry_summary

    telem = telemetry_summary(spec, final)
    busy_frac = telem["busy_frac"] if telem is not None else None
    # streaming latency histogram (ISSUE 6): the per-fog quantile rows
    # come from hist_summary() — the SAME call the OpenMetrics quantile
    # gauges read, so .sca.json and the scrape agree exactly (record_run
    # computes the dict once and passes it in; standalone callers derive
    # it here)
    if hist is None:
        from ..telemetry.health import hist_summary

        hist = hist_summary(spec, final)
    # stack-level rows (r2 missing #4): per-node message counters — the
    # "packets sent"/"packets received" and per-NIC traffic rows of the
    # reference's ~1.5k-scalar .sca — plus per-AP association occupancy.
    # (Unlike the reference's numSent, which skips advertisement sends —
    # ComputeBrokerApp2.cc:202-219 has no numSent++ — these counters see
    # every message the simulation moves.)
    tx = np.asarray(final.nodes.tx_count)
    rx = np.asarray(final.nodes.rx_count)
    # int64 before the multiply: int32 * python int stays int32 under
    # NumPy 2 promotion and wraps negative at benchmark scale (ADVICE r3)
    link_bytes = (tx.astype(np.int64) + rx) * int(spec.task_bytes)
    n_ticks = max(int(np.asarray(final.tick)), 1)
    assoc_sum = np.asarray(final.nodes.assoc_sum)
    broker_i = spec.broker_index

    users = [
        {
            "sent": int(u_sent[u]),
            "completed": int(u_done[u]),
            "acked6": int(u_ack6[u]),
            "delivered": int(n_delivered[u]),
            "energy_j": float(energy[u]),
            "alive": bool(alive[u]),
            "tx_msgs": int(tx[u]),
            "rx_msgs": int(rx[u]),
            "link_bytes": int(link_bytes[u]),
        }
        for u in range(U)
    ]
    fogs = [
        {
            "assigned": int(f_assigned[f]),
            "completed": int(f_done[f]),
            "busy_time": float(busy[f]),
            "pool_avail": float(pool[f]),
            "q_len": int(q_len[f]),
            "q_drops": int(q_drops[f]),
            "tx_msgs": int(tx[U + f]),
            "rx_msgs": int(rx[U + f]),
            "link_bytes": int(link_bytes[U + f]),
            # bandit-scheduler arm row (the learnPicks[f] scalar): only
            # present when the learn subsystem is live for this spec
            **(
                {"learn_picks": float(learn_picks[f])}
                if learn_picks is not None
                else {}
            ),
            # device-resident telemetry rows (spec.telemetry)
            **(
                {
                    "busy_frac": float(busy_frac[f]),
                    "q_len_mean": float(telem["q_len_mean"][f]),
                    "q_len_peak": int(telem["q_len_max"][f]),
                }
                if telem is not None
                else {}
            ),
            # streaming latency-histogram rows (spec.telemetry_hist)
            **(
                {
                    "lat_count": int(hist["per_fog_count"][f]),
                    "lat_sum_ms": float(hist["per_fog_sum_ms"][f]),
                    **{
                        f"lat_{q}_ms": float(vec[f])
                        for q, vec in hist[
                            "per_fog_quantiles_ms"
                        ].items()
                    },
                }
                if hist is not None
                else {}
            ),
        }
        for f in range(F)
    ]
    broker = {
        "tx_msgs": int(tx[broker_i]),
        # the reference's BaseBroker `echoedPk:count` analog: everything
        # the broker app processed
        "rx_msgs": int(rx[broker_i]),
        "link_bytes": int(link_bytes[broker_i]),
        "local_pool": float(np.asarray(final.broker.local_pool)),
    }
    a0, a1 = spec.ap_slice
    aps = [
        {
            "assoc_mean": float(assoc_sum[a] / n_ticks),
            "assoc_sum": int(assoc_sum[a]),
        }
        for a in range(a0, a1)
    ]
    out = {"user": users, "fog": fogs, "broker": broker, "ap": aps}
    # per-shard TP exchange-plane rows (ISSUE 11): present only on
    # stamped TP runs — same exchange_summary() dict the OpenMetrics
    # fns_tp_exchange_* families render, so the two cannot drift
    ex = telem.get("tp_exchange") if telem is not None else None
    if ex is not None:
        out["tp_shard"] = [
            {
                "occ_mean": float(ex["occ_mean"][s]),
                "occ_hist": [int(c) for c in ex["occ_hist"][s]],
                "candidates": int(ex["cand"][s]),
                "deferred": int(ex["defer_sum"][s]),
                "deferred_max": int(ex["defer_max"][s]),
                "util_mean": float(ex["util_mean"][s]),
                "defer_age_ticks_max": float(ex["age_max_ticks"][s]),
            }
            for s in range(ex["n_shards"])
        ]
    return out


def _json_sanitize(obj):
    """Recursively map non-finite floats to None (JSON null)."""
    if isinstance(obj, dict):
        return {k: _json_sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_sanitize(v) for v in obj]
    if isinstance(obj, float) and not np.isfinite(obj):
        return None
    return obj


def record_run(
    outdir: str,
    spec: WorldSpec,
    final: WorldState,
    series: Optional[Dict] = None,
    run_id: str = "General-0",
    attrs: Optional[Dict] = None,
    scave: bool = True,
    extra_vectors: Optional[Dict[str, np.ndarray]] = None,
) -> Dict[str, str]:
    """Persist one finished run. Returns {'sca': path, 'vec': path}.

    ``scave=True`` additionally emits OMNeT++ text-format twins
    (``<run_id>.sca`` / ``.vec`` + a ``General.anf`` descriptor) readable
    by the reference's Scave tooling (:mod:`fognetsimpp_tpu.runtime.scave`).

    ``extra_vectors`` adds caller-computed signal vectors to the
    ``.vec.npz`` under their given names (unlike ``series``, whose keys
    get the ``tick.`` prefix) — the regret harness emits its
    ``learnRegret``/``learnPicks`` curves this way (learn/eval.py).
    """
    os.makedirs(outdir, exist_ok=True)
    sca_path = os.path.join(outdir, f"{run_id}.sca.json")
    vec_path = os.path.join(outdir, f"{run_id}.vec.npz")

    from ..compile_cache import compile_stats
    from ..telemetry.health import hist_summary

    hist = hist_summary(spec, final)
    if spec.chaos:
        from ..chaos.faults import chaos_summary

        chaos_sca = chaos_summary(spec, final)
    else:
        chaos_sca = None
    if spec.hier_active:
        from ..hier.federation import hier_summary

        hier_sca = hier_summary(spec, final)
        # the strided load lanes are Perfetto/live material, not .sca
        # scalars — drop the arrays, keep the per-broker means
        hier_sca = {
            k: v for k, v in hier_sca.items()
            if k not in ("load_rows", "load_rows_t")
        }
    else:
        hier_sca = None
    if spec.journey_active:
        from ..telemetry.journeys import journey_summary

        journey_sca = journey_summary(spec, final)
    else:
        journey_sca = None
    sca = {
        "run": run_id,
        "recorded_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        "attrs": attrs or {},
        "spec": spec_to_dict(spec),
        "scalars": summarize(final),
        "modules": per_module_scalars(spec, final, hist=hist),
        # compile-latency observability (ISSUE 6): hit/miss/compile
        # seconds next to the run scalars, same keys as the OpenMetrics
        # fns_compile_* families
        "compile_cache": compile_stats(),
        # chaos fault-injection section (spec.chaos, ISSUE 12): the
        # same chaos_summary() dict the fns_chaos_* exposition and the
        # flight-recorder manifests read, so the outputs cannot drift
        **({"chaos": chaos_sca} if chaos_sca is not None else {}),
        # federated-hierarchy section (spec.n_brokers > 1, hier/): the
        # same hier_summary() dict the fns_hier_* exposition and the
        # Perfetto broker lanes read, so the outputs cannot drift
        **({"hier": hier_sca} if hier_sca is not None else {}),
        # causal task-journey section (spec.telemetry_journeys, ISSUE
        # 15): the same journey_summary() dict the fns_journey_*
        # exposition and the Perfetto journey lanes read — per-task
        # decoded event chains included (J and the ring bound it)
        **(
            {"journeys": journey_sca}
            if journey_sca is not None
            else {}
        ),
        # global latency-histogram roll-up (spec.telemetry_hist): the
        # quantiles are hist_summary()'s — identical to the OpenMetrics
        # quantile gauges by construction
        **(
            {
                "hist": {
                    "count": hist["count"],
                    "sum_ms": hist["sum_ms"],
                    "edges_ms": [float(e) for e in hist["edges_ms"]],
                    "counts": hist["counts"].tolist(),
                    "quantiles_ms": {
                        k: float(v)
                        for k, v in hist["quantiles_ms"].items()
                    },
                }
            }
            if hist is not None
            else {}
        ),
    }
    # RFC-8259-valid output (ADVICE r2): summarize() yields nan means for
    # empty signal vectors and json.dump would emit literal NaN tokens —
    # encode non-finite scalars as null instead
    sca = _json_sanitize(sca)
    with open(sca_path, "w") as f:
        json.dump(sca, f, indent=1, default=str, allow_nan=False)

    vectors = dict(extract_signals(final))
    if series is not None:
        for k, v in series.items():
            vectors[f"tick.{k}"] = np.asarray(v)
    if extra_vectors is not None:
        for k, v in extra_vectors.items():
            vectors[k] = np.asarray(v)
    np.savez_compressed(vec_path, **vectors)
    paths = {"sca": sca_path, "vec": vec_path}
    # OpenMetrics text exposition (telemetry plane 3): always written —
    # run counters are available on every run; the per-fog telemetry
    # gauges join in when spec.telemetry was on
    from ..telemetry.openmetrics import write_openmetrics

    paths["om"] = write_openmetrics(
        os.path.join(outdir, f"{run_id}.om.txt"), spec, final, hist=hist
    )
    if scave:
        from .scave import NETWORK_NAMES, export_scave

        network = (attrs or {}).get(
            "network",
            NETWORK_NAMES.get((attrs or {}).get("scenario", ""), "Network"),
        )
        sc = export_scave(
            outdir, spec, final, series=series, run_id=run_id,
            attrs=attrs, network=network,
        )
        paths.update(
            {"sca_txt": sc["sca"], "vec_txt": sc["vec"], "anf": sc["anf"]}
        )
    return paths


def fleet_scalars(spec: WorldSpec, final_batch: WorldState) -> Dict:
    """Aggregate a fleet run's metric counters across the replica axis.

    ``final_batch`` is the replica-batched final state from
    :func:`fognetsimpp_tpu.parallel.fleet.run_fleet` (its leaves may
    still be mesh-sharded — ``np.asarray`` inside
    :func:`~fognetsimpp_tpu.parallel.replicas.replica_counters` is the
    single host gather).  Returns ``{"n_replicas", "per_replica",
    "aggregate"}`` where ``aggregate`` carries sum/mean/min/max per
    counter — the Monte-Carlo summary the reference would need N
    process launches plus a results-merging script to produce.
    """
    from ..parallel.replicas import replica_counters

    counters = replica_counters(final_batch)
    n_replicas = int(next(iter(counters.values())).shape[0])
    per_replica = {k: np.asarray(v).tolist() for k, v in counters.items()}
    aggregate = {
        k: {
            "sum": float(np.sum(v)),
            "mean": float(np.mean(v)),
            "min": float(np.min(v)),
            "max": float(np.max(v)),
        }
        for k, v in counters.items()
    }
    return {
        "n_replicas": n_replicas,
        "per_replica": per_replica,
        "aggregate": aggregate,
    }


def record_fleet_run(
    outdir: str,
    spec: WorldSpec,
    final_batch: WorldState,
    series: Optional[Dict] = None,
    run_id: str = "Fleet-0",
    attrs: Optional[Dict] = None,
    scalars: Optional[Dict] = None,
) -> Dict[str, str]:
    """Persist one finished fleet run: ``<run_id>.fleet.sca.json`` (spec +
    replica-aggregated scalars) and, when per-tick ``series`` were
    recorded (:func:`fognetsimpp_tpu.parallel.fleet.run_fleet_series`:
    host arrays of shape ``(R, n_ticks, ...)``), a ``.fleet.vec.npz``
    with one ``tick.<name>`` entry per series vector.

    A fleet is R worlds, so the per-task signal extraction of
    :func:`record_run` (single-world ``.sca``/``.vec`` twins) does not
    apply; slice one replica out of the batch and use :func:`record_run`
    for a full single-world record.

    ``scalars``: a precomputed :func:`fleet_scalars` dict — pass it when
    the caller already gathered the counters (the CLI does, for its JSON
    summary) so the host gather is not repeated.
    """
    from ..parallel.fleet import fleet_latency_hist

    os.makedirs(outdir, exist_ok=True)
    sca_path = os.path.join(outdir, f"{run_id}.fleet.sca.json")
    # replica-merged latency histogram (ISSUE 6): the documented fleet
    # API (sums the leading replica axis of the batched TelemetryState)
    hist = fleet_latency_hist(spec, final_batch)
    sca = {
        "run": run_id,
        "recorded_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        "attrs": attrs or {},
        "spec": spec_to_dict(spec),
        "fleet": (
            scalars if scalars is not None
            else fleet_scalars(spec, final_batch)
        ),
        **(
            {
                "hist": {
                    "count": hist["count"],
                    "sum_ms": hist["sum_ms"],
                    "quantiles_ms": {
                        k: float(v)
                        for k, v in hist["quantiles_ms"].items()
                    },
                }
            }
            if hist is not None
            else {}
        ),
    }
    with open(sca_path, "w") as f:
        json.dump(_json_sanitize(sca), f, indent=1, default=str,
                  allow_nan=False)
    paths = {"sca": sca_path}
    # OpenMetrics exposition (telemetry plane 3): aggregated counters
    # plus PER-REPLICA fog gauges (fleet="r" label — the second PR-4
    # follow-up; replicas are not averaged away in the scrape)
    from ..parallel.fleet import (
        fleet_busy_fractions_per_replica,
        fleet_phase_work,
    )
    from ..telemetry.openmetrics import render_fleet_openmetrics

    # .fleet.-namespaced like the other fleet artifacts, so a
    # single-world record_run into the same outdir/run_id never
    # overwrites it
    om_path = os.path.join(outdir, f"{run_id}.fleet.om.txt")
    with open(om_path, "w") as f:
        f.write(
            render_fleet_openmetrics(
                sca["fleet"],
                fleet_busy_fractions_per_replica(spec, final_batch),
                hist=hist,
                phase_work=fleet_phase_work(spec, final_batch),
            )
        )
    paths["om"] = om_path
    if series is not None:
        vec_path = os.path.join(outdir, f"{run_id}.fleet.vec.npz")
        np.savez_compressed(
            vec_path, **{f"tick.{k}": np.asarray(v) for k, v in series.items()}
        )
        paths["vec"] = vec_path
    return paths


def load_scalars(path: str) -> Dict:
    with open(path) as f:
        return json.load(f)


def load_vectors(path: str) -> Dict[str, np.ndarray]:
    with np.load(path) as z:
        return {k: z[k] for k in z.files}
