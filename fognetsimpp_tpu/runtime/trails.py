"""Movement/communication trails: the Tkenv-animation analog as SVG.

The reference ships interactive observability through OMNeT++'s GUI:
mobility trails and communication lines
(``simulations/example/wirelessNet.ini:79-88`` turns on
``moveTrail``/``communicationTrail`` visualizers), display-string counters
(``rcvd: %d pks / sent: %d pks`` bubbles, ``mqttApp2.cc:103-107``), and
range circles around radios.  The batched framework renders the same
picture headlessly: one self-contained SVG per run showing

  * per-user movement trails (polyline over the recorded tick positions,
    ``spec.record_trails``);
  * APs as squares with their range circles, fog nodes as triangles, the
    base broker as a diamond;
  * a communication line from every wireless user's final position to its
    associated AP;
  * the display-string counters (sent/rcvd per node) from the cumulative
    per-node tx/rx counters.

No third-party rendering dependency: the SVG is assembled textually.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ..net.topology import NetParams, associate
from ..spec import WorldSpec
from ..state import WorldState

_COLORS = ["#1f77b4", "#d62728", "#2ca02c", "#9467bd", "#ff7f0e",
           "#17becf", "#8c564b", "#e377c2"]


def render_trails_svg(
    spec: WorldSpec,
    final: WorldState,
    series: Dict,
    out_path: str,
    net: Optional[NetParams] = None,
    size: int = 640,
) -> str:
    """Write the trail picture for a finished run; returns the path.

    ``series`` must come from a run with ``spec.record_trails`` (it needs
    the per-tick ``pos`` array).
    """
    if "pos" not in series:
        raise ValueError(
            "series has no 'pos' — run with spec.record_trails=True "
            "(and record_tick_series=True)"
        )
    pos = np.asarray(series["pos"])  # (ticks, N, 2)
    U, F = spec.n_users, spec.n_fogs
    last = pos[-1]
    lo = pos.reshape(-1, 2).min(axis=0) - 20.0
    hi = pos.reshape(-1, 2).max(axis=0) + 20.0
    span = np.maximum(hi - lo, 1e-6)
    scale = (size - 40) / span.max()

    def xy(p):
        q = (p - lo) * scale + 20.0
        return float(q[0]), float(size - q[1])  # y up

    out = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{size}" '
        f'height="{size}" style="background:#fff;font:10px sans-serif">'
    ]
    tx = np.asarray(final.nodes.tx_count)
    rx = np.asarray(final.nodes.rx_count)

    # AP range circles + squares
    a0, a1 = spec.ap_slice
    ap_range = (
        np.asarray(net.ap_range) if net is not None and spec.n_aps else None
    )
    for i, a in enumerate(range(a0, a1)):
        x, y = xy(last[a])
        if ap_range is not None:
            r = float(ap_range[i]) * scale
            out.append(
                f'<circle cx="{x:.1f}" cy="{y:.1f}" r="{r:.1f}" '
                'fill="#1f77b410" stroke="#1f77b440"/>'
            )
        out.append(
            f'<rect x="{x - 5:.1f}" y="{y - 5:.1f}" width="10" height="10" '
            'fill="#444"/>'
            f'<text x="{x + 7:.1f}" y="{y:.1f}">ap{i}</text>'
        )

    # communication lines: wireless users to their associated AP
    if net is not None and spec.n_aps:
        cache = associate(
            net, final.nodes.pos, final.nodes.alive, broker=spec.broker_index
        )
        assoc = np.asarray(cache.assoc)
        ap_nodes = np.asarray(net.ap_nodes)
        for u in range(U):
            if assoc[u] >= 0:
                x1, y1 = xy(last[u])
                x2, y2 = xy(last[ap_nodes[assoc[u]]])
                out.append(
                    f'<line x1="{x1:.1f}" y1="{y1:.1f}" x2="{x2:.1f}" '
                    f'y2="{y2:.1f}" stroke="#2ca02c80" stroke-dasharray="4 3"/>'
                )

    # movement trails + user markers with display-string counters
    for u in range(U):
        c = _COLORS[u % len(_COLORS)]
        pts = " ".join(
            "{:.1f},{:.1f}".format(*xy(p)) for p in pos[:, u, :]
        )
        out.append(
            f'<polyline points="{pts}" fill="none" stroke="{c}" '
            'stroke-opacity="0.5"/>'
        )
        x, y = xy(last[u])
        out.append(
            f'<circle cx="{x:.1f}" cy="{y:.1f}" r="4" fill="{c}"/>'
            f'<text x="{x + 6:.1f}" y="{y + 4:.1f}">u{u} '
            f"sent:{int(tx[u])} rcvd:{int(rx[u])}</text>"
        )

    # fog nodes (triangles) + broker (diamond), with counters
    for f in range(F):
        n = spec.n_users + f
        x, y = xy(last[n])
        out.append(
            f'<path d="M {x:.1f} {y - 6:.1f} L {x - 6:.1f} {y + 5:.1f} '
            f'L {x + 6:.1f} {y + 5:.1f} Z" fill="#9467bd"/>'
            f'<text x="{x + 7:.1f}" y="{y + 4:.1f}">fog{f} '
            f"sent:{int(tx[n])} rcvd:{int(rx[n])}</text>"
        )
    b = spec.broker_index
    x, y = xy(last[b])
    out.append(
        f'<path d="M {x:.1f} {y - 7:.1f} L {x - 7:.1f} {y:.1f} '
        f'L {x:.1f} {y + 7:.1f} L {x + 7:.1f} {y:.1f} Z" fill="#d62728"/>'
        f'<text x="{x + 8:.1f}" y="{y + 4:.1f}">broker '
        f"sent:{int(tx[b])} rcvd:{int(rx[b])}</text>"
    )
    out.append("</svg>")
    svg = "\n".join(out)
    with open(out_path, "w") as fh:
        fh.write(svg)
    return out_path
