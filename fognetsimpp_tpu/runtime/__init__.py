"""Runtime services: signal extraction, result recording, checkpointing."""
from .signals import extract_signals, summarize  # noqa: F401
