"""Runtime services: signal extraction, result recording, checkpointing."""
from .signals import extract_signals, summarize  # noqa: F401
from .recorder import load_scalars, load_vectors, record_run  # noqa: F401
from . import checkpoint  # noqa: F401
from .analysis import analyze, render_report  # noqa: F401
from .scave import export_scave, read_sca, read_vec  # noqa: F401
