"""Result analysis: the ``.anf``/Scave analog over recorded runs.

The reference analyses its ``.sca``/``.vec`` outputs with OMNeT++'s Scave
tool driven by ``.anf`` descriptors (``simulations/General.anf:1-9``).
Here :func:`analyze` computes the same statistic set (count, mean, min,
max, percentiles) over every signal vector of one or more recorded runs,
and :func:`render_report` formats the cross-run comparison table —
available from the CLI as ``python -m fognetsimpp_tpu --analyze DIR``.
"""
from __future__ import annotations

import glob
import os
from typing import Dict, List

import numpy as np

from .recorder import load_scalars, load_vectors


def _stats(v: np.ndarray) -> Dict[str, float]:
    if v.size == 0:
        return {"n": 0}
    return {
        "n": int(v.size),
        "mean": float(v.mean()),
        "min": float(v.min()),
        "p50": float(np.percentile(v, 50)),
        "p95": float(np.percentile(v, 95)),
        "max": float(v.max()),
    }


def analyze(results_dir: str) -> Dict[str, Dict]:
    """Per-run signal statistics for every recorded run in a directory.

    Returns ``{run_id: {"scalars": {...}, "modules": {"user": [...],
    "fog": [...]}, "signals": {name: stats}}}`` (``modules`` is empty for
    runs recorded before per-module scalars existed).
    """
    out: Dict[str, Dict] = {}
    for sca_path in sorted(glob.glob(os.path.join(results_dir, "*.sca.json"))):
        run_id = os.path.basename(sca_path)[: -len(".sca.json")]
        sca = load_scalars(sca_path)
        entry: Dict = {
            "scalars": sca.get("scalars", {}),
            "modules": sca.get("modules", {}),
            "signals": {},
        }
        vec_path = os.path.join(results_dir, f"{run_id}.vec.npz")
        if os.path.exists(vec_path):
            for name, v in load_vectors(vec_path).items():
                # per-tick series (possibly (ticks, F)-shaped) flatten into
                # the same scalar-stat treatment as the signal vectors
                entry["signals"][name] = _stats(
                    np.asarray(v, np.float64).ravel()
                )
        out[run_id] = entry
    if not out:
        raise FileNotFoundError(f"no *.sca.json runs under {results_dir!r}")
    return out


def render_report(results: Dict[str, Dict]) -> str:
    """Human-readable cross-run table (the .anf chart-sheet analog)."""
    lines: List[str] = []
    for run_id, entry in results.items():
        sc = entry["scalars"]
        lines.append(f"== run {run_id}")
        lines.append(
            "   published={n_published} scheduled={n_scheduled} "
            "completed={n_completed} no_resource={n_no_resource} "
            "dropped={n_dropped} rejected={n_rejected}".format(
                **{k: sc.get(k, 0) for k in (
                    "n_published", "n_scheduled", "n_completed",
                    "n_no_resource", "n_dropped", "n_rejected",
                )}
            )
        )
        hdr = (f"   {'signal':<16}{'n':>7}{'mean':>10}{'min':>10}{'p50':>10}"
               f"{'p95':>10}{'max':>10}")
        lines.append(hdr)
        for name, s in sorted(entry["signals"].items()):
            if s["n"] == 0:
                lines.append(f"   {name:<16}{0:>7}")
                continue
            lines.append(
                f"   {name:<16}{s['n']:>7}{s['mean']:>10.2f}{s['min']:>10.2f}"
                f"{s['p50']:>10.2f}{s['p95']:>10.2f}{s['max']:>10.2f}"
            )
        fogs = entry.get("modules", {}).get("fog", [])
        if fogs:
            lines.append(
                f"   {'fog':<6}{'assigned':>9}{'completed':>10}"
                f"{'busy':>9}{'q_len':>7}{'drops':>7}"
            )
            for f, row in enumerate(fogs):
                lines.append(
                    f"   {f:<6}{row['assigned']:>9}{row['completed']:>10}"
                    f"{row['busy_time']:>9.2f}{row['q_len']:>7}"
                    f"{row['q_drops']:>7}"
                )
    return "\n".join(lines)
