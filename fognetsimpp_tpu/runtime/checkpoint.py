"""Checkpoint/resume: snapshot the whole world, restore it, keep running.

Absent from the reference (no snapshot keys in any ini — SURVEY.md §5);
nearly free here because the entire world is one pytree of fixed-shape
arrays whose *structure* is a pure function of the spec: save = spec JSON
+ flattened leaves; load = rebuild the skeleton from the spec and pour the
leaves back in.  A resumed run continues bit-identically (the PRNG key is
part of the state).
"""
from __future__ import annotations

import dataclasses
import json
from typing import Tuple

import jax
import numpy as np

from ..spec import WorldSpec
from ..state import WorldState, init_state
from .recorder import spec_to_dict


def save(path: str, spec: WorldSpec, state: WorldState) -> None:
    """Write ``<path>`` (npz): spec JSON + the state pytree's leaves."""
    leaves = jax.tree.leaves(state)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    arrays["spec_json"] = np.frombuffer(
        json.dumps(spec_to_dict(spec)).encode(), dtype=np.uint8
    )
    np.savez_compressed(path, **arrays)


def load(path: str) -> Tuple[WorldSpec, WorldState]:
    """Rebuild (spec, state) from a :func:`save` file."""
    from .recorder import dict_to_spec

    with np.load(path) as z:
        spec_d = json.loads(bytes(z["spec_json"]).decode())
        leaves = [z[f"leaf_{i}"] for i in range(len(z.files) - 1)]
    spec = dict_to_spec(spec_d)
    skeleton = init_state(spec)
    treedef = jax.tree.structure(skeleton)
    if len(leaves) != treedef.num_leaves:
        raise ValueError(
            f"checkpoint {path!r} has {len(leaves)} state leaves, spec "
            f"expects {treedef.num_leaves} — saved by an incompatible "
            "WorldState layout"
        )
    state = jax.tree.unflatten(
        treedef, [jax.numpy.asarray(x) for x in leaves]
    )
    # trace-time contract (simlint R8 layer): a leaf whose shape/dtype
    # drifted from the spec's skeleton would otherwise surface as a
    # recompile or an opaque scan carry-mismatch deep inside the engine
    from ..core.contracts import assert_same_struct

    assert_same_struct(skeleton, state, what=f"checkpoint {path!r}")
    return spec, state
