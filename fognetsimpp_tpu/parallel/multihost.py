"""Multi-host execution: replicas sharded across processes over DCN.

The reference has no distributed backend at all (single-threaded DES,
parsim unused — SURVEY.md §2.3); the TPU-native scale-out across hosts is
``jax.distributed`` + a process-spanning mesh: every host runs the same
program, the replica axis spans all devices of all processes, and XLA
routes any cross-replica combine over ICI within a slice and DCN across
slices.  Because replicas are embarrassingly parallel in the steady state
(zero collectives per tick — :mod:`fognetsimpp_tpu.parallel.mesh`), the
multi-host scaling of the sweep grids is linear by construction.

Single-process calls are a no-op passthrough, so the same entry point
works on one chip, one host, or a pod.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

from .mesh import REPLICA_AXIS


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto: bool = False,
) -> int:
    """Join the jax.distributed cluster; returns the process count.

    Three modes, explicit by design:
      * coordinator args given — initialize with them;
      * ``auto=True`` — delegate to ``jax.distributed.initialize()``'s
        cluster autodetection (SLURM / multislice TPU env); raises if no
        cluster is detectable, so a mis-launched pod job fails loudly
        instead of running N duplicate single-process programs;
      * neither — single-process passthrough (local dev / one host).
    """
    if coordinator_address is not None or num_processes is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    elif auto:
        jax.distributed.initialize()
    return jax.process_count()


def global_mesh(axis_name: str = REPLICA_AXIS) -> Mesh:
    """1-D mesh over every device of every process.

    With ``shard_replicas`` on top, each host owns
    ``R / (n_processes * devices_per_host)`` replicas; per-host
    ``jax.local_devices()`` hold only the local shard (the standard
    multi-host data layout).
    """
    return Mesh(np.asarray(jax.devices()), (axis_name,))
