"""Monte-Carlo replica fan-out: ``vmap`` over whole worlds.

The reference runs one world per OS process and sweeps sequentially
(``simulations/run:3`` launches a single binary; no ``repeat`` keys in any
ini — SURVEY.md §2.3 DP row).  Here a replica is one more leading axis on
the world pytree: ``vmap(step)`` advances every replica's every node in the
same fused kernels, and the replica axis is what the mesh shards
(:mod:`fognetsimpp_tpu.parallel.mesh`).

Replicas share the (static) topology/``NetParams`` and differ in PRNG key —
hence task sizes (``mqttApp2.cc:370``), app start times, and optionally the
per-user publish interval (the ``volatile sendInterval`` NED parameter,
``mqttApp2.ned:22-40``, re-sampled per replica here).
"""
from __future__ import annotations

import dataclasses
import functools

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.engine import run
from ..net.mobility import MobilityBounds
from ..net.topology import NetParams
from ..spec import WorldSpec
from ..state import WorldState


def fold_replica_chaos_keys(chaos_key: jax.Array, n_replicas: int) -> jax.Array:
    """(R, 2) per-replica chaos keys: ``fold_in(chaos_key, r)``.

    The chaos analog of :func:`fleet.fold_replica_keys` — and literally
    that function applied to the chaos key, so the one replica-fold
    discipline can never drift between world keys and chaos keys: each
    replica's fault schedule is keyed on its own stable id,
    decorrelated from the template's single schedule, and reproducible
    on host via ``chaos/faults.outage_timeline`` with the folded key.
    """
    from .fleet import fold_replica_keys

    return fold_replica_keys(chaos_key, n_replicas)


def replicate_state(
    spec: WorldSpec,
    state: WorldState,
    n_replicas: int,
    seed: int = 0,
    resample_starts: bool = True,
) -> WorldState:
    """Broadcast one world to ``n_replicas`` with per-replica PRNG keys.

    Every leaf gains a leading replica axis.  When ``resample_starts`` and
    the spec declares a start-time window, each replica redraws its user app
    start times (the per-run RNG seeding the reference gets from OMNeT++'s
    seedset — SURVEY.md §4 item 4).
    """
    R = n_replicas
    keys = jax.random.split(jax.random.PRNGKey(seed), R)
    batch = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (R,) + jnp.shape(x)), state
    )
    batch = batch.replace(key=keys)
    if spec.chaos:
        # per-replica fault schedules (the ROADMAP fleet-chaos
        # follow-up): replica r's chaos stream is fold_in(chaos_key, r)
        # — folded from the TEMPLATE's chaos key on the replica's own
        # stable id, the fold_replica_keys discipline, so replica r
        # draws the same outage trajectory whether the fleet runs 8 or
        # 800 replicas around it.  refold_chaos_state re-derives the
        # key-dependent init draws (first crash gaps, RTT phases) so
        # the whole schedule is a pure function of the folded key —
        # host replay via outage_timeline(spec, fold_in(ck, r)) stays
        # exact.  Both the vmap (run_replicated) and the sharded fleet
        # path read these rows, which is what makes the fleet-vs-vmap
        # state-hash A/B hold under chaos (tests/test_fleet.py).
        from ..chaos.faults import refold_chaos_state

        ck_r = fold_replica_chaos_keys(state.chaos.key, R)
        batch = batch.replace(
            chaos=jax.vmap(
                lambda k: refold_chaos_state(spec, state.chaos, k)
            )(ck_r)
        )
    if resample_starts and spec.start_time_max > spec.start_time_min:
        sub = jax.vmap(lambda k: jax.random.fold_in(k, 1))(keys)
        starts = jax.vmap(
            lambda k: jax.random.uniform(
                k,
                (spec.n_users,),
                jnp.float32,
                minval=spec.start_time_min,
                maxval=spec.start_time_max,
            )
        )(sub)
        users = batch.users.replace(start_t=starts)
        if not spec.connect_gating:
            # without the connect handshake the first publish fires at the
            # app start time directly (round-1 shortcut worlds)
            users = users.replace(next_send=starts)
        batch = batch.replace(users=users)
    return batch


def run_replicated(
    spec: WorldSpec,
    batch: WorldState,
    net: NetParams,
    bounds: MobilityBounds,
    n_ticks: Optional[int] = None,
    dyn_rows=None,
) -> WorldState:
    """Advance every replica over the horizon: ``jit(vmap(scan(step)))``.

    ``net``/``bounds`` are shared (broadcast via ``in_axes=None``) across
    replicas — passed as jit arguments, not closure-captured (simlint R3:
    captured arrays are baked into the trace as constants and retrace per
    call; as arguments the jitted program is cached on ``(spec,
    n_ticks)`` across calls).  Returns the batched final state; pull
    per-replica scalars with :func:`replica_counters`.

    ``dyn_rows`` (ISSUE 13): a :class:`~fognetsimpp_tpu.dynspec.DynSpec`
    whose every leaf carries a leading replica axis — each replica then
    runs its OWN promoted knob values (chaos amplitudes, reward weights,
    loss probabilities...) under the one compiled program; ``spec``
    should be the grid's shared shape key.  ``None`` keeps the classic
    all-replicas-one-spec fan-out.
    """
    return _run_replicated(spec, n_ticks, batch, net, bounds, dyn_rows)


# simlint: disable=R6 -- callers A/B the same batch across run_replicated
# and run_sharded (tests/test_parallel.py); donating it would invalidate
# the shared input
@functools.partial(jax.jit, static_argnums=(0, 1))
def _run_replicated(
    spec: WorldSpec, n_ticks: Optional[int], batch: WorldState,
    net: NetParams, bounds: MobilityBounds, dyn_rows=None,
) -> WorldState:
    def run_one(s, net_, bounds_, dyn_):
        final, _ = run(spec, s, net_, bounds_, n_ticks=n_ticks, dyn=dyn_)
        return final

    return jax.vmap(
        run_one,
        in_axes=(0, None, None, 0 if dyn_rows is not None else None),
    )(batch, net, bounds, dyn_rows)


def replica_counters(final_batch: WorldState) -> Dict[str, np.ndarray]:
    """Per-replica metric counters as host numpy arrays, keyed by name.

    Enumerates every Metrics field, so counters added to the state never
    silently vanish from sweep grids.
    """
    m = final_batch.metrics
    return {
        f.name: np.asarray(getattr(m, f.name))
        for f in dataclasses.fields(m)
    }
