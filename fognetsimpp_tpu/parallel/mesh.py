"""Device-mesh sharding of the replica axis (DP over ICI/DCN).

A batched world (leading replica axis from
:func:`fognetsimpp_tpu.parallel.replicas.replicate_state`) is laid out with
``NamedSharding(mesh, P('replica', ...))`` on every leaf; the jitted
``vmap(scan(step))`` then partitions cleanly — replicas never communicate,
so XLA inserts zero collectives in the steady state and each chip advances
its local slice at full speed.  Cross-replica reductions (sweep summaries)
become single ``psum``-style combines at the end, riding ICI within a slice
and DCN across slices (SURVEY.md §2.3 "distributed comm backend" row).

This is the TPU-native replacement for launching N OMNeT++ processes: one
program, one compile, N_devices × replicas-per-device worlds.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.engine import run
from ..net.mobility import MobilityBounds
from ..net.topology import NetParams
from ..spec import WorldSpec
from ..state import WorldState

REPLICA_AXIS = "replica"


def make_mesh(
    n_devices: Optional[int] = None, axis_name: str = REPLICA_AXIS
) -> Mesh:
    """1-D mesh over the first ``n_devices`` devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} available"
            )
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis_name,))


def replica_sharding(mesh: Mesh, axis_name: str = REPLICA_AXIS):
    """Pytree-of-shardings: leading axis split over the mesh, rest replicated."""

    def leaf(x):
        x = jax.numpy.asarray(x) if not hasattr(x, "ndim") else x
        return NamedSharding(mesh, P(axis_name, *([None] * (x.ndim - 1))))

    return leaf


def shard_replicas(
    batch: WorldState, mesh: Mesh, axis_name: str = REPLICA_AXIS
) -> WorldState:
    """Place a replicated world on the mesh, replica axis sharded.

    The replica count must divide the mesh size evenly (fixed shapes).
    """
    leaf = replica_sharding(mesh, axis_name)
    return jax.tree.map(lambda x: jax.device_put(x, leaf(x)), batch)


def shard_world(
    batch: WorldState,
    net: NetParams,
    bounds: MobilityBounds,
    mesh: Mesh,
    axis_name: str = REPLICA_AXIS,
):
    """Lay a replicated world out on the mesh: the production DP sharding.

    The replica axis of every world-state leaf is split over the mesh;
    ``net``/``bounds`` (shared topology) are replicated to every device.
    Returns ``(batch, net, bounds, out_shardings)`` — the single source of
    truth used by both :func:`run_sharded` and the driver's
    ``dryrun_multichip``.
    """
    batch = shard_replicas(batch, mesh, axis_name)
    repl = NamedSharding(mesh, P())
    net = jax.tree.map(lambda x: jax.device_put(x, repl), net)
    bounds = jax.tree.map(lambda x: jax.device_put(x, repl), bounds)
    leaf = replica_sharding(mesh, axis_name)
    return batch, net, bounds, jax.tree.map(leaf, batch)


def run_sharded(
    spec: WorldSpec,
    batch: WorldState,
    net: NetParams,
    bounds: MobilityBounds,
    mesh: Mesh,
    n_ticks: Optional[int] = None,
    axis_name: str = REPLICA_AXIS,
) -> WorldState:
    """Shard the replica axis over ``mesh`` and advance all replicas.

    Identical semantics to :func:`replicas.run_replicated` — a test asserts
    bit-equality — but each device owns ``R / n_devices`` replicas.  ``net``
    and ``bounds`` are replicated to every device.
    """
    batch, net, bounds, out_shardings = shard_world(
        batch, net, bounds, mesh, axis_name
    )

    def run_one(s: WorldState, net_, bounds_) -> WorldState:
        final, _ = run(spec, s, net_, bounds_, n_ticks=n_ticks)
        return final

    # net/bounds ride in as broadcast arguments, not closure constants
    # (simlint R3); out_shardings pins the result to the replica layout.
    # simlint: disable=R6 -- bit-equality tests feed the same batch here
    # and to run_replicated; donation would consume the shared input
    fn = jax.jit(
        jax.vmap(run_one, in_axes=(0, None, None)),
        out_shardings=out_shardings,
    )
    return fn(batch, net, bounds)
